"""Common functional ops: linear, dropout, embedding-adjacent utilities
(reference: python/paddle/nn/functional/common.py)."""
import jax
import jax.numpy as jnp

from ...framework import random as prandom
from ...framework.core import Tensor, apply, to_tensor


def _t(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


def linear(x, weight, bias=None, name=None):
    """y = x @ W (+ b). Weight layout [in, out] as in the reference
    (python/paddle/nn/functional/common.py linear; phi matmul kernel)."""
    from ...amp.auto_cast import amp_cast_inputs

    if bias is None:

        def fn(a, w):
            a, w = amp_cast_inputs("linear", [a, w])
            return a @ w

        return apply(fn, _t(x), _t(weight), name="linear")

    def fnb(a, w, b):
        a, w, b = amp_cast_inputs("linear", [a, w, b])
        return a @ w + b

    return apply(fnb, _t(x), _t(weight), _t(bias), name="linear")


def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train", name=None):
    x = _t(x)
    if not training or p == 0.0:
        if mode == "downscale_in_infer" and not training:
            return apply(lambda a: a * (1.0 - p), x, name="dropout_infer")
        return x.clone() if not x.stop_gradient else Tensor(x._data)
    if p == 1.0:
        return apply(lambda a: jnp.zeros_like(a), x, name="dropout")
    shape = tuple(x.shape)
    if axis is not None:
        axes = axis if isinstance(axis, (list, tuple)) else [axis]
        mask_shape = tuple(s if i in [a % len(shape) for a in axes] else 1 for i, s in enumerate(shape))
    else:
        mask_shape = shape
    keep = jax.random.bernoulli(prandom.next_key(), 1.0 - p, mask_shape)

    def fn(a):
        if mode == "upscale_in_train":
            return jnp.where(keep, a / (1.0 - p), 0.0).astype(a.dtype)
        return jnp.where(keep, a, 0.0).astype(a.dtype)

    return apply(fn, x, name="dropout")


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    axis = [0, 1] if data_format == "NCHW" else [0, 3]
    return dropout(x, p, axis=axis, training=training)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    axis = [0, 1] if data_format == "NCDHW" else [0, 4]
    return dropout(x, p, axis=axis, training=training)


def _alpha_dropout_impl(x, p, mask_shape, name):
    """Shared alpha-dropout core: dropped positions take the SELU negative
    saturation value, then an affine (a, b) restores zero mean/unit var.
    mask_shape broadcasts against x (full shape = per-element dropout,
    [N, C, 1, ...] = whole-channel/feature dropout)."""
    alpha = 1.6732632423543772
    scale = 1.0507009873554805
    alpha_p = -alpha * scale
    keep = jax.random.bernoulli(prandom.next_key(), 1.0 - p, mask_shape)
    a = 1.0 / ((1.0 - p) * (1.0 + p * alpha_p**2)) ** 0.5
    b = -a * alpha_p * p

    def fn(v):
        return (jnp.where(keep, v, alpha_p) * a + b).astype(v.dtype)

    return apply(fn, x, name=name)


def alpha_dropout(x, p=0.5, training=True, name=None):
    if not training or p == 0.0:
        return _t(x)
    x = _t(x)
    return _alpha_dropout_impl(x, p, tuple(x.shape), "alpha_dropout")


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):
    from ...tensor import manipulation

    return manipulation.pad(x, pad, mode, value, data_format)


def interpolate(
    x,
    size=None,
    scale_factor=None,
    mode="nearest",
    align_corners=False,
    align_mode=0,
    data_format="NCHW",
    name=None,
):
    x = _t(x)
    spatial = x.shape[2:] if data_format.startswith("NC") else x.shape[1:-1]
    if size is None:
        if isinstance(scale_factor, (int, float)):
            scale_factor = [scale_factor] * len(spatial)
        size = [int(s * f) for s, f in zip(spatial, scale_factor)]
    if isinstance(size, Tensor):
        size = [int(v) for v in size.numpy()]
    size = [int(v.item()) if isinstance(v, Tensor) else int(v) for v in size]
    method = {"nearest": "nearest", "bilinear": "linear", "trilinear": "linear", "bicubic": "cubic", "linear": "linear", "area": "linear"}[mode]

    if data_format.startswith("NC"):
        out_shape = tuple(x.shape[:2]) + tuple(size)
        spatial_axes = tuple(range(2, x.ndim))
    else:
        out_shape = (x.shape[0],) + tuple(size) + (x.shape[-1],)
        spatial_axes = tuple(range(1, x.ndim - 1))

    def fn(a):
        return jax.image.resize(a, out_shape, method=method)

    return apply(fn, x, name="interpolate")


def upsample(x, size=None, scale_factor=None, mode="nearest", align_corners=False, align_mode=0, data_format="NCHW", name=None):
    return interpolate(x, size, scale_factor, mode, align_corners, align_mode, data_format)


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    x = _t(x)
    k = kernel_sizes if isinstance(kernel_sizes, (list, tuple)) else [kernel_sizes] * 2
    s = strides if isinstance(strides, (list, tuple)) else [strides] * 2
    p = paddings if isinstance(paddings, (list, tuple)) else [paddings] * 2
    if len(p) == 2:
        p = [p[0], p[1], p[0], p[1]]
    d = dilations if isinstance(dilations, (list, tuple)) else [dilations] * 2

    def fn(a):
        n, c, h, w = a.shape
        a = jnp.pad(a, ((0, 0), (0, 0), (p[0], p[2]), (p[1], p[3])))
        oh = (a.shape[2] - (d[0] * (k[0] - 1) + 1)) // s[0] + 1
        ow = (a.shape[3] - (d[1] * (k[1] - 1) + 1)) // s[1] + 1
        patches = []
        for i in range(k[0]):
            for j in range(k[1]):
                patches.append(
                    a[:, :, i * d[0] : i * d[0] + oh * s[0] : s[0], j * d[1] : j * d[1] + ow * s[1] : s[1]]
                )
        out = jnp.stack(patches, axis=2)  # n, c, k*k, oh, ow
        return out.reshape(n, c * k[0] * k[1], oh * ow)

    return apply(fn, x, name="unfold")


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    x = _t(x)
    k = kernel_sizes if isinstance(kernel_sizes, (list, tuple)) else [kernel_sizes] * 2
    s = strides if isinstance(strides, (list, tuple)) else [strides] * 2
    p = paddings if isinstance(paddings, (list, tuple)) else [paddings] * 2
    if len(p) == 2:
        p = [p[0], p[1], p[0], p[1]]
    d = dilations if isinstance(dilations, (list, tuple)) else [dilations] * 2
    oh_out, ow_out = output_sizes

    def fn(a):
        n, ckk, L = a.shape
        c = ckk // (k[0] * k[1])
        ph, pw = oh_out + p[0] + p[2], ow_out + p[1] + p[3]
        oh = (ph - (d[0] * (k[0] - 1) + 1)) // s[0] + 1
        ow = (pw - (d[1] * (k[1] - 1) + 1)) // s[1] + 1
        a = a.reshape(n, c, k[0], k[1], oh, ow)
        out = jnp.zeros((n, c, ph, pw), a.dtype)
        for i in range(k[0]):
            for j in range(k[1]):
                out = out.at[:, :, i * d[0] : i * d[0] + oh * s[0] : s[0], j * d[1] : j * d[1] + ow * s[1] : s[1]].add(
                    a[:, :, i, j]
                )
        return out[:, :, p[0] : ph - p[2], p[1] : pw - p[3]]

    return apply(fn, x, name="fold")


def bilinear(x1, x2, weight, bias=None, name=None):
    def fn(a, b, w, *rest):
        out = jnp.einsum("bi,oij,bj->bo", a, w, b)
        if rest:
            out = out + rest[0]
        return out

    args = [_t(x1), _t(x2), _t(weight)] + ([_t(bias)] if bias is not None else [])
    return apply(fn, *args, name="bilinear")


def cosine_similarity(x1, x2, axis=1, eps=1e-8, name=None):
    def fn(a, b):
        num = jnp.sum(a * b, axis=axis)
        den = jnp.linalg.norm(a, axis=axis) * jnp.linalg.norm(b, axis=axis)
        return num / jnp.maximum(den, eps)

    return apply(fn, _t(x1), _t(x2), name="cosine_similarity")


def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    def fn(a):
        n = jnp.sum(jnp.abs(a) ** p, axis=axis, keepdims=True) ** (1.0 / p)
        return a / jnp.maximum(n, epsilon)

    return apply(fn, _t(x), name="normalize")


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    def fn(l):
        k = l.shape[-1]
        if prior_dist is not None:
            pd = prior_dist._data if isinstance(prior_dist, Tensor) else jnp.asarray(prior_dist)
            return (1 - epsilon) * l + epsilon * pd
        return (1 - epsilon) * l + epsilon / k

    return apply(fn, _t(label), name="label_smooth")


def one_hot(x, num_classes, name=None):
    return Tensor(jax.nn.one_hot(_t(x)._data, num_classes))


def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    idx = _t(x)._data

    def fn(w):
        out = jnp.take(w, idx, axis=0)
        if padding_idx is not None:
            mask = (idx == padding_idx)[..., None]
            out = jnp.where(mask, 0.0, out)
        return out

    return apply(fn, _t(weight), name="embedding")


def class_center_sample(label, num_classes, num_samples, group=None):
    raise NotImplementedError("class_center_sample is PS-scale; out of TPU scope (SURVEY.md §2.3)")


def pixel_shuffle(x, upscale_factor, data_format="NCHW", name=None):
    r = upscale_factor

    def fn(a):
        if data_format == "NCHW":
            n, c, h, w = a.shape
            a = a.reshape(n, c // (r * r), r, r, h, w)
            a = a.transpose(0, 1, 4, 2, 5, 3)
            return a.reshape(n, c // (r * r), h * r, w * r)
        n, h, w, c = a.shape
        a = a.reshape(n, h, w, r, r, c // (r * r))
        a = a.transpose(0, 1, 3, 2, 4, 5)
        return a.reshape(n, h * r, w * r, c // (r * r))

    return apply(fn, _t(x), name="pixel_shuffle")


def pixel_unshuffle(x, downscale_factor, data_format="NCHW", name=None):
    r = downscale_factor

    def fn(a):
        if data_format == "NCHW":
            n, c, h, w = a.shape
            a = a.reshape(n, c, h // r, r, w // r, r)
            a = a.transpose(0, 1, 3, 5, 2, 4)
            return a.reshape(n, c * r * r, h // r, w // r)
        n, h, w, c = a.shape
        a = a.reshape(n, h // r, r, w // r, r, c)
        a = a.transpose(0, 1, 3, 2, 4, 5)
        return a.reshape(n, h // r, w // r, c * r * r)

    return apply(fn, _t(x), name="pixel_unshuffle")


def channel_shuffle(x, groups, data_format="NCHW", name=None):
    def fn(a):
        n, c, h, w = a.shape
        a = a.reshape(n, groups, c // groups, h, w)
        return a.transpose(0, 2, 1, 3, 4).reshape(n, c, h, w)

    return apply(fn, _t(x), name="channel_shuffle")


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros", align_corners=True, name=None):
    x, grid = _t(x), _t(grid)

    def fn(a, g):
        n, c, h, w = a.shape
        gx, gy = g[..., 0], g[..., 1]
        if align_corners:
            ix = (gx + 1) * (w - 1) / 2
            iy = (gy + 1) * (h - 1) / 2
        else:
            ix = ((gx + 1) * w - 1) / 2
            iy = ((gy + 1) * h - 1) / 2

        x0 = jnp.floor(ix)
        y0 = jnp.floor(iy)
        x1, y1 = x0 + 1, y0 + 1

        def sample(xi, yi):
            xi_c = jnp.clip(xi, 0, w - 1).astype(jnp.int32)
            yi_c = jnp.clip(yi, 0, h - 1).astype(jnp.int32)
            v = a[jnp.arange(n)[:, None, None], :, yi_c, xi_c]  # n,hg,wg,c
            if padding_mode == "zeros":
                valid = ((xi >= 0) & (xi <= w - 1) & (yi >= 0) & (yi <= h - 1))[..., None]
                v = jnp.where(valid, v, 0.0)
            return v

        if mode == "nearest":
            out = sample(jnp.round(ix), jnp.round(iy))
        else:
            wa = ((x1 - ix) * (y1 - iy))[..., None]
            wb = ((x1 - ix) * (iy - y0))[..., None]
            wc = ((ix - x0) * (y1 - iy))[..., None]
            wd = ((ix - x0) * (iy - y0))[..., None]
            out = wa * sample(x0, y0) + wb * sample(x0, y1) + wc * sample(x1, y0) + wd * sample(x1, y1)
        return out.transpose(0, 3, 1, 2)

    return apply(fn, x, grid, name="grid_sample")


def affine_grid(theta, out_shape, align_corners=True, name=None):
    theta = _t(theta)
    if isinstance(out_shape, Tensor):
        out_shape = [int(v) for v in out_shape.numpy()]
    n, c, h, w = out_shape

    def fn(th):
        if align_corners:
            ys = jnp.linspace(-1, 1, h)
            xs = jnp.linspace(-1, 1, w)
        else:
            ys = (jnp.arange(h) * 2 + 1) / h - 1
            xs = (jnp.arange(w) * 2 + 1) / w - 1
        gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
        base = jnp.stack([gx, gy, jnp.ones_like(gx)], axis=-1)  # h,w,3
        return jnp.einsum("nij,hwj->nhwi", th, base)

    return apply(fn, theta, name="affine_grid")


def temporal_shift(x, seg_num, shift_ratio=0.25, data_format="NCHW", name=None):
    def fn(a):
        nt, c, h, w = a.shape
        n = nt // seg_num
        a = a.reshape(n, seg_num, c, h, w)
        fold_ = int(c * shift_ratio)
        out = jnp.zeros_like(a)
        out = out.at[:, :-1, :fold_].set(a[:, 1:, :fold_])
        out = out.at[:, 1:, fold_ : 2 * fold_].set(a[:, :-1, fold_ : 2 * fold_])
        out = out.at[:, :, 2 * fold_ :].set(a[:, :, 2 * fold_ :])
        return out.reshape(nt, c, h, w)

    return apply(fn, _t(x), name="temporal_shift")


def zeropad2d(x, padding, data_format="NCHW", name=None):
    """reference: F.zeropad2d — constant-zero spatial padding
    [left, right, top, bottom]."""
    return pad(x, padding, mode="constant", value=0.0, data_format=data_format)


def feature_alpha_dropout(x, p=0.5, training=True, name=None):
    """reference: F.feature_alpha_dropout — alpha dropout over whole
    channel maps (one keep/drop decision per [N, C], broadcast over the
    spatial dims)."""
    if not training or p == 0.0:
        return _t(x)
    t = _t(x)
    mask_shape = tuple(t.shape[:2]) + (1,) * (len(t.shape) - 2)
    return _alpha_dropout_impl(t, p, mask_shape, "feature_alpha_dropout")


def gather_tree(ids, parents, name=None):
    """reference: F.gather_tree — walk beam-search parent pointers backward
    so time step t holds the t-th token of each FULL surviving sequence.
    ids/parents: [T, B, K] int; out[t, b, k] = token at time t of the
    sequence ending in beam k at time T-1."""
    t_ids, t_par = _t(ids), _t(parents)

    def fn(idv, par):
        T, _, K = idv.shape
        last_beam = jnp.broadcast_to(jnp.arange(K), idv.shape[1:])

        def body(beam, t):
            # t runs T-2 .. 0; beam is the surviving beam index at t+1
            prev_beam = jnp.take_along_axis(par[t + 1], beam, axis=-1)
            tok = jnp.take_along_axis(idv[t], prev_beam, axis=-1)
            return prev_beam, tok

        _, toks = jax.lax.scan(body, last_beam, jnp.arange(T - 2, -1, -1))
        return jnp.concatenate([toks[::-1], idv[T - 1][None]], axis=0)

    return apply(fn, t_ids, t_par, name="gather_tree")
