"""Flash/SDP attention (reference: python/paddle/nn/functional/flash_attention.py
— FlashAttnKernel glue at paddle/phi/kernels/gpu/flash_attn_kernel.cu).

TPU-native path: a Pallas flash-attention kernel (paddle_tpu/ops/flash_attention.py)
tiled for the MXU, with a pure-XLA fallback that jnp-composes softmax(QK^T)V —
XLA itself fuses this well on TPU for moderate sequence lengths.

Layout contract matches the reference: q/k/v are [batch, seqlen, num_heads,
head_dim]; causal masking supported; dropout applied inside attention.
"""
import contextlib
import functools

import jax
import jax.numpy as jnp

from ...framework import random as prandom
from ...framework.core import Tensor, apply, to_tensor

_sdp_config = {"enable_flash": True, "enable_math": True, "enable_mem_efficient": True}


@contextlib.contextmanager
def sdp_kernel(enable_flash=True, enable_math=True, enable_mem_efficient=True):
    prev = dict(_sdp_config)
    _sdp_config.update(
        enable_flash=enable_flash, enable_math=enable_math, enable_mem_efficient=enable_mem_efficient
    )
    try:
        yield
    finally:
        _sdp_config.update(prev)


def _t(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


def _flash_enabled():
    # sdp_kernel(enable_flash=False) is the user escape hatch: it must
    # force the math path even on TPU (head-dim/alignment gating lives in
    # ops.flash_attention.flash_attention_fwd, the single dispatch point)
    return _sdp_config["enable_flash"]


def _math_attention(q, k, v, mask, causal, dropout, dropout_key, scale):
    # [B, S, H, D] -> [B, H, S, D]
    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    # grouped-query attention: broadcast kv heads
    hq, hk = qt.shape[1], kt.shape[1]
    if hq != hk:
        kt = jnp.repeat(kt, hq // hk, axis=1)
        vt = jnp.repeat(vt, hq // hk, axis=1)
    logits = jnp.einsum("bhqd,bhkd->bhqk", qt, kt) * scale
    logits = logits.astype(jnp.float32)
    if causal:
        sq, sk = logits.shape[-2], logits.shape[-1]
        cm = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        logits = jnp.where(cm, logits, -jnp.inf)
    if mask is not None:
        if mask.dtype == jnp.bool_:
            logits = jnp.where(mask, logits, -jnp.inf)
        else:
            logits = logits + mask.astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    if dropout > 0.0 and dropout_key is not None:
        keep = jax.random.bernoulli(dropout_key, 1.0 - dropout, probs.shape)
        probs = jnp.where(keep, probs / (1.0 - dropout), 0.0).astype(q.dtype)
    out = jnp.einsum("bhqk,bhkd->bhqd", probs, vt)
    return jnp.swapaxes(out, 1, 2)


def flash_attention(
    query,
    key,
    value,
    dropout=0.0,
    causal=False,
    return_softmax=False,
    fixed_seed_offset=None,
    rng_name="",
    training=True,
    name=None,
):
    """paddle.nn.functional.flash_attention.flash_attention parity."""
    q, k, v = _t(query), _t(key), _t(value)
    head_dim = q.shape[-1]
    scale = 1.0 / (head_dim**0.5)
    drop = dropout if training else 0.0
    dropout_key = prandom.next_key() if drop > 0.0 else None

    if drop == 0.0 and _flash_enabled():
        # single dispatch point: flash_attention_fwd picks splash/pallas on
        # an aligned TPU trace and the fused-XLA math path otherwise, and
        # records the choice in ops.flash_attention.LAST_IMPL
        from ...ops.flash_attention import flash_attention_fwd

        out = apply(
            functools.partial(flash_attention_fwd, causal=causal, scale=scale),
            q,
            k,
            v,
            name="pallas_flash_attn",
        )
    else:
        out = apply(
            lambda a, b, c: _math_attention(a, b, c, None, causal, drop, dropout_key, scale),
            q,
            k,
            v,
            name="flash_attn",
        )
    return out, None


def flash_attn_unpadded(
    query, key, value, cu_seqlens_q, cu_seqlens_k, max_seqlen_q, max_seqlen_k,
    scale=None, dropout=0.0, causal=False, return_softmax=False, training=True, name=None,
):
    """Varlen flash attention: total-token packed layout [total, H, D] with
    cumulative sequence offsets (reference: flash_attn_unpadded). On TPU,
    the Pallas splash kernel with dynamic SegmentIds — O(total·block)
    memory, no dense [total, total] score matrix; dense segment-masked
    math fallback elsewhere (ops.flash_attention.flash_attention_varlen_fwd)."""
    from ...ops.flash_attention import _same_offsets, flash_attention_varlen_fwd

    q, k, v = _t(query), _t(key), _t(value)
    cu_q = _t(cu_seqlens_q)._data
    cu_k = _t(cu_seqlens_k)._data
    scale = scale or 1.0 / (q.shape[-1] ** 0.5)
    # decide self- vs cross-attention HERE, where the offsets may still be
    # concrete — inside the traced region the values are unreadable and the
    # kernel path would be lost. Under an outer jit, pass the SAME tensor
    # object as both cu_seqlens to keep the kernel path for self-attention.
    same = cu_seqlens_q is cu_seqlens_k or _same_offsets(cu_q, cu_k)
    out = apply(
        functools.partial(
            flash_attention_varlen_fwd, cu_q=cu_q, cu_k=cu_k, causal=causal,
            scale=scale, same_offsets=same, force_math=not _flash_enabled(),
        ),
        q, k, v,
        name="flash_attn_varlen",
    )
    return out, None


def scaled_dot_product_attention(
    query, key, value, attn_mask=None, dropout_p=0.0, is_causal=False, training=True, name=None
):
    """paddle.nn.functional.scaled_dot_product_attention parity.
    Layout [batch, seqlen, heads, head_dim], like the reference."""
    q, k, v = _t(query), _t(key), _t(value)
    head_dim = q.shape[-1]
    scale = 1.0 / (head_dim**0.5)
    drop = dropout_p if training else 0.0
    dropout_key = prandom.next_key() if drop > 0.0 else None

    if attn_mask is None and drop == 0.0 and _flash_enabled():
        from ...ops.flash_attention import flash_attention_fwd

        return apply(
            functools.partial(flash_attention_fwd, causal=is_causal, scale=scale),
            q,
            k,
            v,
            name="pallas_sdpa",
        )

    mask_data = _t(attn_mask)._data if attn_mask is not None else None
    return apply(
        lambda a, b, c: _math_attention(a, b, c, mask_data, is_causal, drop, dropout_key, scale),
        q,
        k,
        v,
        name="sdpa",
    )
