"""Loss functionals (reference: python/paddle/nn/functional/loss.py)."""
import jax
import jax.numpy as jnp

from ...framework.core import Tensor, apply, to_tensor


def _t(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


def _reduce(out, reduction):
    if reduction == "mean":
        return jnp.mean(out)
    if reduction == "sum":
        return jnp.sum(out)
    return out


def cross_entropy(
    input,
    label,
    weight=None,
    ignore_index=-100,
    reduction="mean",
    soft_label=False,
    axis=-1,
    use_softmax=True,
    label_smoothing=0.0,
    name=None,
):
    input = _t(input)
    label = _t(label)

    ldata = label._data

    def fn(logits, *rest):
        it = iter(rest)
        lab = next(it) if soft_label else ldata
        w = next(it) if weight is not None else None
        logp = jax.nn.log_softmax(logits, axis=axis) if use_softmax else jnp.log(jnp.maximum(logits, 1e-30))
        n_classes = logits.shape[axis]
        if soft_label:
            sl = lab
            if label_smoothing > 0:
                sl = sl * (1 - label_smoothing) + label_smoothing / n_classes
            per = -jnp.sum(sl * logp, axis=axis)
            valid = jnp.ones_like(per, dtype=bool)
        else:
            li = lab
            if li.ndim == logp.ndim and li.shape[axis] == 1:
                li = jnp.squeeze(li, axis)
            li = li.astype(jnp.int32)
            valid = li != ignore_index
            safe = jnp.where(valid, li, 0)
            if label_smoothing > 0:
                onehot = jax.nn.one_hot(safe, n_classes, dtype=logp.dtype)
                sl = onehot * (1 - label_smoothing) + label_smoothing / n_classes
                per = -jnp.sum(sl * logp, axis=axis)
            else:
                per = -jnp.take_along_axis(logp, safe[..., None], axis=axis).squeeze(axis)
            per = jnp.where(valid, per, 0.0)
            if w is not None:
                wt = jnp.take(w, safe, axis=0)
                wt = jnp.where(valid, wt, 0.0)
                per = per * wt
                if reduction == "mean":
                    return jnp.sum(per) / jnp.maximum(jnp.sum(wt), 1e-12)
        if reduction == "mean":
            denom = jnp.maximum(jnp.sum(valid.astype(per.dtype)), 1.0)
            return jnp.sum(per) / denom
        if reduction == "sum":
            return jnp.sum(per)
        return per

    args = [input]
    if soft_label:
        args.append(label)
    if weight is not None:
        args.append(_t(weight))
    return apply(fn, *args, name="cross_entropy")


def softmax_with_cross_entropy(logits, label, soft_label=False, ignore_index=-100, numeric_stable_mode=True, return_softmax=False, axis=-1):
    loss = cross_entropy(logits, label, soft_label=soft_label, ignore_index=ignore_index, reduction="none", axis=axis)
    loss = loss.unsqueeze(axis)
    if return_softmax:
        from .activation import softmax as _softmax

        return loss, _softmax(logits, axis=axis)
    return loss


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean", name=None):
    label_d = _t(label)._data

    def fn(logp, *rest):
        li = label_d.astype(jnp.int32)
        valid = li != ignore_index
        safe = jnp.where(valid, li, 0)
        per = -jnp.take_along_axis(logp, safe[..., None], axis=1).squeeze(1)
        if rest:
            wt = jnp.take(rest[0], safe, axis=0)
            wt = jnp.where(valid, wt, 0.0)
            per = per * wt
            if reduction == "mean":
                return jnp.sum(jnp.where(valid, per, 0.0)) / jnp.maximum(jnp.sum(wt), 1e-12)
        per = jnp.where(valid, per, 0.0)
        if reduction == "mean":
            return jnp.sum(per) / jnp.maximum(jnp.sum(valid.astype(per.dtype)), 1.0)
        return _reduce(per, reduction)

    args = [_t(input)] + ([_t(weight)] if weight is not None else [])
    return apply(fn, *args, name="nll_loss")


def mse_loss(input, label, reduction="mean", name=None):
    return apply(lambda a, b: _reduce(jnp.square(a - b), reduction), _t(input), _t(label), name="mse_loss")


def l1_loss(input, label, reduction="mean", name=None):
    return apply(lambda a, b: _reduce(jnp.abs(a - b), reduction), _t(input), _t(label), name="l1_loss")


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):
    def fn(a, b):
        # standard huber: 0.5 d^2 inside delta, linear outside
        d = jnp.abs(a - b)
        out = jnp.where(d < delta, 0.5 * d * d, delta * (d - 0.5 * delta))
        return _reduce(out, reduction)

    return apply(fn, _t(input), _t(label), name="smooth_l1")


def binary_cross_entropy(input, label, weight=None, reduction="mean", name=None):
    def fn(p, l, *rest):
        per = -(l * jnp.log(jnp.maximum(p, 1e-12)) + (1 - l) * jnp.log(jnp.maximum(1 - p, 1e-12)))
        if rest:
            per = per * rest[0]
        return _reduce(per, reduction)

    args = [_t(input), _t(label)] + ([_t(weight)] if weight is not None else [])
    return apply(fn, *args, name="bce")


def binary_cross_entropy_with_logits(logit, label, weight=None, reduction="mean", pos_weight=None, name=None):
    def fn(z, l, *rest):
        it = iter(rest)
        w = next(it) if weight is not None else None
        pw = next(it) if pos_weight is not None else None
        log_sig = jax.nn.log_sigmoid(z)
        log_one_minus = jax.nn.log_sigmoid(-z)
        if pw is not None:
            per = -(pw * l * log_sig + (1 - l) * log_one_minus)
        else:
            per = -(l * log_sig + (1 - l) * log_one_minus)
        if w is not None:
            per = per * w
        return _reduce(per, reduction)

    args = [_t(logit), _t(label)]
    if weight is not None:
        args.append(_t(weight))
    if pos_weight is not None:
        args.append(_t(pos_weight))
    return apply(fn, *args, name="bce_logits")


def kl_div(input, label, reduction="mean", log_target=False, name=None):
    def fn(lp, t):
        tt = jnp.exp(t) if log_target else t
        per = tt * ((t if log_target else jnp.log(jnp.maximum(t, 1e-12))) - lp)
        if reduction == "batchmean":
            return jnp.sum(per) / lp.shape[0]
        return _reduce(per, reduction)

    return apply(fn, _t(input), _t(label), name="kl_div")


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean", name=None):
    def fn(a, b, l):
        return _reduce(jnp.maximum(0.0, -l * (a - b) + margin), reduction)

    return apply(fn, _t(input), _t(other), _t(label), name="margin_ranking")


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean", name=None):
    def fn(a, l):
        out = jnp.where(l == 1, a, jnp.maximum(0.0, margin - a))
        return _reduce(out, reduction)

    return apply(fn, _t(input), _t(label), name="hinge_embedding")


def cosine_embedding_loss(input1, input2, label, margin=0.0, reduction="mean", name=None):
    def fn(a, b, l):
        cos = jnp.sum(a * b, axis=-1) / (
            jnp.maximum(jnp.linalg.norm(a, axis=-1) * jnp.linalg.norm(b, axis=-1), 1e-12)
        )
        out = jnp.where(l == 1, 1 - cos, jnp.maximum(0.0, cos - margin))
        return _reduce(out, reduction)

    return apply(fn, _t(input1), _t(input2), _t(label), name="cosine_embedding")


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0, epsilon=1e-6, swap=False, reduction="mean", name=None):
    def fn(a, pos, neg):
        dp = jnp.linalg.norm(a - pos + epsilon, ord=p, axis=-1)
        dn = jnp.linalg.norm(a - neg + epsilon, ord=p, axis=-1)
        if swap:
            dn2 = jnp.linalg.norm(pos - neg + epsilon, ord=p, axis=-1)
            dn = jnp.minimum(dn, dn2)
        return _reduce(jnp.maximum(dp - dn + margin, 0.0), reduction)

    return apply(fn, _t(input), _t(positive), _t(negative), name="triplet")


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0, reduction="sum", name=None):
    def fn(z, l, *rest):
        p = jax.nn.sigmoid(z)
        ce = -(l * jax.nn.log_sigmoid(z) + (1 - l) * jax.nn.log_sigmoid(-z))
        pt = p * l + (1 - p) * (1 - l)
        a_t = alpha * l + (1 - alpha) * (1 - l)
        out = a_t * ((1 - pt) ** gamma) * ce
        if rest:
            out = out / rest[0]
        return _reduce(out, reduction)

    args = [_t(logit), _t(label)] + ([_t(normalizer)] if normalizer is not None else [])
    return apply(fn, *args, name="focal")


def log_loss(input, label, epsilon=1e-4, name=None):
    return apply(
        lambda p, l: -l * jnp.log(p + epsilon) - (1 - l) * jnp.log(1 - p + epsilon),
        _t(input),
        _t(label),
        name="log_loss",
    )


def square_error_cost(input, label):
    return apply(lambda a, b: jnp.square(a - b), _t(input), _t(label), name="square_error")


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0, reduction="mean", norm_by_times=False):
    """reference: nn/functional/loss.py ctc_loss (warpctc kernel). TPU:
    optax.ctc_loss — a pure-XLA forward-backward over the label lattice.

    log_probs: [T, B, C] time-major logits/log-probs (reference layout);
    labels: [B, L] padded with any value past label_lengths; blank=`blank`.
    reduction 'mean' divides each sample's loss by its label length, then
    averages (reference semantics). norm_by_times is a warpctc legacy knob
    (scales grads, not the loss) — accepted, no-op here."""
    import optax

    def fn(lp, lab, in_len, lab_len):
        logits = jnp.transpose(lp, (1, 0, 2)).astype(jnp.float32)  # [B, T, C]
        B, T, _ = logits.shape
        L = lab.shape[1]
        logit_pad = (jnp.arange(T)[None, :] >= in_len[:, None]).astype(jnp.float32)
        label_pad = (jnp.arange(L)[None, :] >= lab_len[:, None]).astype(jnp.float32)
        # optax reserves blank_id; labels must be valid class ids everywhere
        safe_labels = jnp.where(label_pad > 0, 0, lab).astype(jnp.int32)
        per = optax.ctc_loss(logits, logit_pad, safe_labels, label_pad, blank_id=blank)
        if reduction == "mean":
            return jnp.mean(per / jnp.maximum(lab_len.astype(jnp.float32), 1.0))
        if reduction == "sum":
            return jnp.sum(per)
        return per

    return apply(fn, _t(log_probs), _t(labels), _t(input_lengths), _t(label_lengths),
                 name="ctc_loss")


def dice_loss(input, label, epsilon=1e-5, name=None):
    def fn(p, l):
        l_onehot = jax.nn.one_hot(l.squeeze(-1), p.shape[-1], dtype=p.dtype)
        inter = jnp.sum(p * l_onehot, axis=tuple(range(1, p.ndim)))
        union = jnp.sum(p, axis=tuple(range(1, p.ndim))) + jnp.sum(l_onehot, axis=tuple(range(1, p.ndim)))
        return jnp.mean(1 - (2 * inter + epsilon) / (union + epsilon))

    return apply(fn, _t(input), _t(label), name="dice")


def npair_loss(anchor, positive, labels, l2_reg=0.002):
    def fn(a, p):
        sim = a @ p.T
        l = _t(labels)._data.reshape(-1)
        target = (l[:, None] == l[None, :]).astype(sim.dtype)
        target = target / jnp.sum(target, axis=1, keepdims=True)
        ce = -jnp.mean(jnp.sum(jax.nn.log_softmax(sim, axis=1) * target, axis=1))
        reg = l2_reg * (jnp.mean(jnp.sum(a * a, axis=1)) + jnp.mean(jnp.sum(p * p, axis=1))) * 0.25
        return ce + reg

    return apply(fn, _t(anchor), _t(positive), name="npair")


def huber_loss(input, label, delta=1.0, reduction="mean", name=None):
    """reference: F.huber_loss — quadratic within |r|<=delta, linear beyond
    (SmoothL1 scaled by delta)."""
    def fn(a, b):
        r = jnp.abs(a - b)
        out = jnp.where(r <= delta, 0.5 * r * r, delta * (r - 0.5 * delta))
        return _reduce(out, reduction)

    return apply(fn, _t(input), _t(label), name="huber_loss")


def poisson_nll_loss(input, label, log_input=True, full=False, epsilon=1e-8,
                     reduction="mean", name=None):
    """reference: F.poisson_nll_loss (Stirling term when full=True)."""
    def fn(a, b):
        if log_input:
            out = jnp.exp(a) - b * a
        else:
            out = a - b * jnp.log(a + epsilon)
        if full:
            stir = b * jnp.log(b) - b + 0.5 * jnp.log(2.0 * jnp.pi * b)
            out = out + jnp.where(b > 1, stir, 0.0)
        return _reduce(out, reduction)

    return apply(fn, _t(input), _t(label), name="poisson_nll")


def gaussian_nll_loss(input, label, variance, full=False, epsilon=1e-6,
                      reduction="mean", name=None):
    """reference: F.gaussian_nll_loss — heteroscedastic Gaussian NLL."""
    def fn(mu, y, var):
        var = jnp.maximum(var, epsilon)
        out = 0.5 * (jnp.log(var) + jnp.square(y - mu) / var)
        if full:
            out = out + 0.5 * jnp.log(2.0 * jnp.pi)
        return _reduce(out, reduction)

    return apply(fn, _t(input), _t(label), _t(variance), name="gaussian_nll")


def soft_margin_loss(input, label, reduction="mean", name=None):
    """reference: F.soft_margin_loss — log(1 + exp(-y*x))."""
    return apply(
        lambda a, b: _reduce(jnp.log1p(jnp.exp(-b * a)), reduction),
        _t(input), _t(label), name="soft_margin",
    )


def multi_label_soft_margin_loss(input, label, weight=None, reduction="mean",
                                 name=None):
    """reference: F.multi_label_soft_margin_loss — mean over classes of
    -[y*log sigma(x) + (1-y)*log sigma(-x)], optional class weights."""
    def fn(a, b, *w):
        out = -(b * jax.nn.log_sigmoid(a) + (1.0 - b) * jax.nn.log_sigmoid(-a))
        if w:
            out = out * w[0]
        return _reduce(out.mean(axis=-1), reduction)

    args = [_t(input), _t(label)] + ([_t(weight)] if weight is not None else [])
    return apply(fn, *args, name="multi_label_soft_margin")
