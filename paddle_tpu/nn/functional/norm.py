"""Normalization functionals (reference: python/paddle/nn/functional/norm.py).

These stay as jnp compositions — XLA fuses mean/var/scale chains into the
surrounding kernels, which is exactly what the reference's fused
bias-dropout-residual-LN CUDA kernels hand-achieve.
"""
import jax
import jax.numpy as jnp

from ...framework.core import Tensor, apply, to_tensor


def _t(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-5, name=None):
    x = _t(x)
    if isinstance(normalized_shape, int):
        normalized_shape = [normalized_shape]
    nd = len(normalized_shape)
    axes = tuple(range(x.ndim - nd, x.ndim))

    def fn(a, *rest):
        mean = jnp.mean(a.astype(jnp.float32), axis=axes, keepdims=True)
        var = jnp.var(a.astype(jnp.float32), axis=axes, keepdims=True)
        out = ((a.astype(jnp.float32) - mean) * jax.lax.rsqrt(var + epsilon)).astype(a.dtype)
        it = iter(rest)
        if weight is not None:
            out = out * next(it)
        if bias is not None:
            out = out + next(it)
        return out

    args = [x] + ([_t(weight)] if weight is not None else []) + ([_t(bias)] if bias is not None else [])
    return apply(fn, *args, name="layer_norm")


def rms_norm(x, weight=None, epsilon=1e-6, name=None):
    """RMSNorm (reference: incubate fused_rms_norm / PaddleNLP): the LLaMA norm."""
    x = _t(x)

    def fn(a, *rest):
        var = jnp.mean(jnp.square(a.astype(jnp.float32)), axis=-1, keepdims=True)
        out = (a.astype(jnp.float32) * jax.lax.rsqrt(var + epsilon)).astype(a.dtype)
        if rest:
            out = out * rest[0]
        return out

    args = [x] + ([_t(weight)] if weight is not None else [])
    return apply(fn, *args, name="rms_norm")


def batch_norm(
    x,
    running_mean,
    running_var,
    weight=None,
    bias=None,
    training=False,
    momentum=0.9,
    epsilon=1e-5,
    data_format="NCHW",
    use_global_stats=None,
    name=None,
):
    x = _t(x)
    ch_axis = 1 if (data_format.startswith("NC") or x.ndim <= 2) else x.ndim - 1
    reduce_axes = tuple(i for i in range(x.ndim) if i != ch_axis)
    bshape = [1] * x.ndim
    bshape[ch_axis] = x.shape[ch_axis]
    use_batch_stats = training and not use_global_stats

    if use_batch_stats:
        mean_t = apply(lambda a: jnp.mean(a, axis=reduce_axes), x, name="bn_mean")
        var_t = apply(
            lambda a, m: jnp.mean(jnp.square(a - m.reshape(bshape)), axis=reduce_axes), x, mean_t, name="bn_var"
        )
        # update running stats in place (reference: phi batch_norm kernel)
        if running_mean is not None:
            running_mean.set_value(
                Tensor(momentum * running_mean._data + (1 - momentum) * mean_t._data)
            )
            running_var.set_value(Tensor(momentum * running_var._data + (1 - momentum) * var_t._data))
        mean_used, var_used = mean_t, var_t
    else:
        mean_used, var_used = _t(running_mean), _t(running_var)

    def fn(a, m, v, *rest):
        out = (a - m.reshape(bshape)) * jax.lax.rsqrt(v.reshape(bshape) + epsilon)
        it = iter(rest)
        if weight is not None:
            out = out * next(it).reshape(bshape)
        if bias is not None:
            out = out + next(it).reshape(bshape)
        return out.astype(a.dtype)

    args = [x, mean_used, var_used]
    if weight is not None:
        args.append(_t(weight))
    if bias is not None:
        args.append(_t(bias))
    return apply(fn, *args, name="batch_norm")


def instance_norm(x, running_mean=None, running_var=None, weight=None, bias=None, use_input_stats=True, momentum=0.9, eps=1e-5, data_format="NCHW", name=None):
    x = _t(x)
    axes = tuple(range(2, x.ndim))

    def fn(a, *rest):
        mean = jnp.mean(a, axis=axes, keepdims=True)
        var = jnp.var(a, axis=axes, keepdims=True)
        out = (a - mean) * jax.lax.rsqrt(var + eps)
        it = iter(rest)
        shape = [1, a.shape[1]] + [1] * (a.ndim - 2)
        if weight is not None:
            out = out * next(it).reshape(shape)
        if bias is not None:
            out = out + next(it).reshape(shape)
        return out

    args = [x] + ([_t(weight)] if weight is not None else []) + ([_t(bias)] if bias is not None else [])
    return apply(fn, *args, name="instance_norm")


def group_norm(x, num_groups, epsilon=1e-5, weight=None, bias=None, data_format="NCHW", name=None):
    x = _t(x)

    def fn(a, *rest):
        n, c = a.shape[0], a.shape[1]
        rest_shape = a.shape[2:]
        g = a.reshape(n, num_groups, c // num_groups, *rest_shape)
        axes = tuple(range(2, g.ndim))
        mean = jnp.mean(g, axis=axes, keepdims=True)
        var = jnp.var(g, axis=axes, keepdims=True)
        out = ((g - mean) * jax.lax.rsqrt(var + epsilon)).reshape(a.shape)
        it = iter(rest)
        shape = [1, c] + [1] * (a.ndim - 2)
        if weight is not None:
            out = out * next(it).reshape(shape)
        if bias is not None:
            out = out + next(it).reshape(shape)
        return out

    args = [x] + ([_t(weight)] if weight is not None else []) + ([_t(bias)] if bias is not None else [])
    return apply(fn, *args, name="group_norm")


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0, data_format="NCHW", name=None):
    def fn(a):
        sq = jnp.square(a)
        half = size // 2
        pads = [(0, 0)] * a.ndim
        pads[1] = (half, size - half - 1)
        padded = jnp.pad(sq, pads)
        window = [1] * a.ndim
        window[1] = size
        summed = jax.lax.reduce_window(padded, 0.0, jax.lax.add, tuple(window), (1,) * a.ndim, "VALID")
        return a / (k + alpha * summed) ** beta

    return apply(fn, _t(x), name="lrn")


def spectral_norm(weight, weight_u, weight_v, dim=0, power_iters=1, eps=1e-12, name=None):
    w = _t(weight)

    def fn(wd, u, v):
        wm = jnp.moveaxis(wd, dim, 0).reshape(wd.shape[dim], -1)
        for _ in range(power_iters):
            v = wm.T @ u
            v = v / (jnp.linalg.norm(v) + eps)
            u = wm @ v
            u = u / (jnp.linalg.norm(u) + eps)
        sigma = u @ wm @ v
        return wd / sigma

    return apply(fn, w, _t(weight_u), _t(weight_v), name="spectral_norm")
