"""Activations (reference: python/paddle/nn/functional/activation.py)."""
import jax
import jax.numpy as jnp

from ...framework.core import Tensor, apply, to_tensor


def _t(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


def _u(fn, name):
    def op(x, name_=None, **kw):
        return apply(lambda a: fn(a, **kw) if kw else fn(a), _t(x), name=name)

    op.__name__ = name
    return op


relu = _u(jax.nn.relu, "relu")
relu_ = relu
relu6 = _u(jax.nn.relu6, "relu6")
sigmoid = _u(jax.nn.sigmoid, "sigmoid")
tanh = _u(jnp.tanh, "tanh")
silu = _u(jax.nn.silu, "silu")
swish = silu
mish = _u(lambda a: a * jnp.tanh(jax.nn.softplus(a)), "mish")
softsign = _u(jax.nn.soft_sign, "softsign")
tanhshrink = _u(lambda a: a - jnp.tanh(a), "tanhshrink")
log_sigmoid = _u(jax.nn.log_sigmoid, "log_sigmoid")


def gelu(x, approximate=False, name=None):
    return apply(lambda a: jax.nn.gelu(a, approximate=approximate), _t(x), name="gelu")


def leaky_relu(x, negative_slope=0.01, name=None):
    return apply(lambda a: jax.nn.leaky_relu(a, negative_slope), _t(x), name="leaky_relu")


def elu(x, alpha=1.0, name=None):
    return apply(lambda a: jax.nn.elu(a, alpha), _t(x), name="elu")


def celu(x, alpha=1.0, name=None):
    return apply(lambda a: jax.nn.celu(a, alpha), _t(x), name="celu")


def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772, name=None):
    return apply(lambda a: scale * jnp.where(a > 0, a, alpha * jnp.expm1(a)), _t(x), name="selu")


def hardshrink(x, threshold=0.5, name=None):
    return apply(lambda a: jnp.where(jnp.abs(a) > threshold, a, 0.0), _t(x), name="hardshrink")


def softshrink(x, threshold=0.5, name=None):
    return apply(
        lambda a: jnp.where(a > threshold, a - threshold, jnp.where(a < -threshold, a + threshold, 0.0)),
        _t(x),
        name="softshrink",
    )


def hardsigmoid(x, slope=0.1666667, offset=0.5, name=None):
    return apply(lambda a: jnp.clip(slope * a + offset, 0.0, 1.0), _t(x), name="hardsigmoid")


def hardswish(x, name=None):
    return apply(lambda a: a * jnp.clip(a + 3.0, 0.0, 6.0) / 6.0, _t(x), name="hardswish")


def hardtanh(x, min=-1.0, max=1.0, name=None):
    return apply(lambda a: jnp.clip(a, min, max), _t(x), name="hardtanh")


def softplus(x, beta=1.0, threshold=20.0, name=None):
    return apply(
        lambda a: jnp.where(a * beta > threshold, a, jax.nn.softplus(a * beta) / beta), _t(x), name="softplus"
    )


def thresholded_relu(x, threshold=1.0, value=0.0, name=None):
    return apply(lambda a: jnp.where(a > threshold, a, value), _t(x), name="thresholded_relu")


def prelu(x, weight, data_format="NCHW", name=None):
    def fn(a, w):
        if w.size == 1:
            return jnp.where(a > 0, a, w.reshape(()) * a)
        shape = [1] * a.ndim
        ch_axis = 1 if data_format[1] == "C" else a.ndim - 1
        shape[ch_axis] = w.size
        return jnp.where(a > 0, a, w.reshape(shape) * a)

    return apply(fn, _t(x), _t(weight), name="prelu")


def rrelu(x, lower=0.125, upper=0.3333333, training=False, name=None):
    mid = (lower + upper) / 2.0
    return apply(lambda a: jnp.where(a >= 0, a, mid * a), _t(x), name="rrelu")


def maxout(x, groups, axis=1, name=None):
    def fn(a):
        ax = axis % a.ndim
        c = a.shape[ax]
        new_shape = a.shape[:ax] + (groups, c // groups) + a.shape[ax + 1 :]
        return jnp.max(a.reshape(new_shape), axis=ax)

    return apply(fn, _t(x), name="maxout")


def softmax(x, axis=-1, dtype=None, name=None):
    x = _t(x)
    if dtype is not None:
        x = x.astype(dtype)
    return apply(lambda a: jax.nn.softmax(a, axis=axis), x, name="softmax")


softmax_ = softmax


def log_softmax(x, axis=-1, dtype=None, name=None):
    x = _t(x)
    if dtype is not None:
        x = x.astype(dtype)
    return apply(lambda a: jax.nn.log_softmax(a, axis=axis), x, name="log_softmax")


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    from ...framework import random as prandom

    g = jax.random.gumbel(prandom.next_key(), tuple(_t(x).shape), _t(x).dtype)

    def fn(a):
        y = jax.nn.softmax((a + g) / temperature, axis=axis)
        if hard:
            idx = jnp.argmax(y, axis=axis, keepdims=True)
            y_hard = jnp.zeros_like(y)
            y_hard = jnp.put_along_axis(y_hard, idx, 1.0, axis=axis, inplace=False)
            y = y_hard + y - jax.lax.stop_gradient(y)
        return y

    return apply(fn, _t(x), name="gumbel_softmax")


def glu(x, axis=-1, name=None):
    return apply(lambda a: jax.nn.glu(a, axis=axis), _t(x), name="glu")
