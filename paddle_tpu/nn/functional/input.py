"""Input ops (reference: python/paddle/nn/functional/input.py)."""
from .common import embedding, one_hot  # noqa: F401


def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    """reference: nn.functional.sequence_mask — mask[i, j] = j < x[i]."""
    import jax.numpy as jnp

    from ...framework import dtype as dtypes
    from ...framework.core import apply, to_tensor

    xt = to_tensor(x)
    m = int(maxlen) if maxlen is not None else int(jnp.max(xt._data))
    dt = dtypes.convert_dtype(dtype)
    return apply(lambda a: (jnp.arange(m) < a[..., None]).astype(dt), xt,
                 name="sequence_mask")
