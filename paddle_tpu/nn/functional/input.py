"""Input ops (reference: python/paddle/nn/functional/input.py)."""
from .common import embedding, one_hot  # noqa: F401
