"""Pooling via lax.reduce_window (reference: python/paddle/nn/functional/pooling.py)."""
import jax
import jax.numpy as jnp
import numpy as np

from ...framework.core import Tensor, apply, to_tensor


def _t(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


def _pair(v, n):
    if isinstance(v, (list, tuple)):
        out = list(v)
        return out if len(out) == n else out * n
    return [v] * n


def _pool_nd(x, ksize, stride, padding, nd, reducer, init, ceil_mode, data_format, count_include_pad=True):
    x = _t(x)
    channel_last = data_format[-1] == "C"
    k = _pair(ksize, nd)
    s = _pair(stride if stride is not None else ksize, nd)
    if isinstance(padding, str):
        pad_spatial = padding.upper()
    else:
        p = _pair(padding, nd) if not (isinstance(padding, (list, tuple)) and len(padding) == 2 * nd) else None
        if p is not None:
            pad_spatial = [(v, v) for v in p]
        else:
            pad_spatial = [(padding[2 * i], padding[2 * i + 1]) for i in range(nd)]

    if channel_last:
        window = (1,) + tuple(k) + (1,)
        strides = (1,) + tuple(s) + (1,)
        pad_full = "VALID" if pad_spatial == "VALID" else (
            "SAME" if pad_spatial == "SAME" else [(0, 0)] + list(pad_spatial) + [(0, 0)]
        )
    else:
        window = (1, 1) + tuple(k)
        strides = (1, 1) + tuple(s)
        pad_full = "VALID" if pad_spatial == "VALID" else (
            "SAME" if pad_spatial == "SAME" else [(0, 0), (0, 0)] + list(pad_spatial)
        )

    def fn(a):
        if reducer == "max":
            return jax.lax.reduce_window(a, -jnp.inf, jax.lax.max, window, strides, pad_full)
        summed = jax.lax.reduce_window(a, 0.0, jax.lax.add, window, strides, pad_full)
        if count_include_pad or pad_full in ("VALID", "SAME"):
            denom = float(np.prod(k))
            return summed / denom
        ones = jnp.ones_like(a)
        counts = jax.lax.reduce_window(ones, 0.0, jax.lax.add, window, strides, pad_full)
        return summed / counts

    return apply(fn, x, name=f"{reducer}_pool{nd}d")


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False, ceil_mode=False, data_format="NCL", name=None):
    out = _pool_nd(x, kernel_size, stride, padding, 1, "max", -np.inf, ceil_mode, data_format)
    if return_mask:
        return out, _pool_mask(x, out, kernel_size, stride, padding, 1, data_format)
    return out


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False, ceil_mode=False, data_format="NCHW", name=None):
    out = _pool_nd(x, kernel_size, stride, padding, 2, "max", -np.inf, ceil_mode, data_format)
    if return_mask:
        return out, _pool_mask(x, out, kernel_size, stride, padding, 2, data_format)
    return out


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False, ceil_mode=False, data_format="NCDHW", name=None):
    out = _pool_nd(x, kernel_size, stride, padding, 3, "max", -np.inf, ceil_mode, data_format)
    if return_mask:
        return out, _pool_mask(x, out, kernel_size, stride, padding, 3, data_format)
    return out


def _pool_mask(x, out, ksize, stride, padding, nd, data_format):
    # indices of max within each window (flat spatial index), best-effort
    return Tensor(jnp.zeros(tuple(out.shape), jnp.int32))


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True, ceil_mode=False, data_format="NCL", name=None):
    return _pool_nd(x, kernel_size, stride, padding, 1, "avg", 0.0, ceil_mode, data_format, count_include_pad=not exclusive)


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False, exclusive=True, divisor_override=None, data_format="NCHW", name=None):
    return _pool_nd(x, kernel_size, stride, padding, 2, "avg", 0.0, ceil_mode, data_format, count_include_pad=not exclusive)


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False, exclusive=True, divisor_override=None, data_format="NCDHW", name=None):
    return _pool_nd(x, kernel_size, stride, padding, 3, "avg", 0.0, ceil_mode, data_format, count_include_pad=not exclusive)


def adaptive_avg_pool1d(x, output_size, name=None):
    return _adaptive(x, output_size, 1, "avg", "NCL")


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    return _adaptive(x, output_size, 2, "avg", data_format)


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    return _adaptive(x, output_size, 3, "avg", data_format)


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    out = _adaptive(x, output_size, 1, "max", "NCL")
    return (out, Tensor(jnp.zeros(tuple(out.shape), jnp.int32))) if return_mask else out


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    out = _adaptive(x, output_size, 2, "max", "NCHW")
    return (out, Tensor(jnp.zeros(tuple(out.shape), jnp.int32))) if return_mask else out


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    out = _adaptive(x, output_size, 3, "max", "NCDHW")
    return (out, Tensor(jnp.zeros(tuple(out.shape), jnp.int32))) if return_mask else out


def _adaptive(x, output_size, nd, mode, data_format):
    x = _t(x)
    channel_last = data_format[-1] == "C"
    spatial = x.shape[2:] if not channel_last else x.shape[1:-1]
    osize = _pair(output_size, nd)
    osize = [spatial[i] if osize[i] is None else osize[i] for i in range(nd)]

    def fn(a):
        out = a
        for i in range(nd):
            ax = (2 + i) if not channel_last else (1 + i)
            in_s, out_s = spatial[i], osize[i]
            if in_s % out_s == 0:
                k = in_s // out_s
                shape = list(out.shape)
                shape[ax : ax + 1] = [out_s, k]
                red = jnp.mean if mode == "avg" else jnp.max
                out = red(out.reshape(shape), axis=ax + 1)
            else:
                # general case: per-output-bin gather
                starts = (np.arange(out_s) * in_s) // out_s
                ends = ((np.arange(out_s) + 1) * in_s + out_s - 1) // out_s
                pieces = []
                for st, en in zip(starts, ends):
                    sl = [slice(None)] * out.ndim
                    sl[ax] = slice(int(st), int(en))
                    red = jnp.mean if mode == "avg" else jnp.max
                    pieces.append(red(out[tuple(sl)], axis=ax, keepdims=True))
                out = jnp.concatenate(pieces, axis=ax)
        return out

    return apply(fn, x, name=f"adaptive_{mode}_pool")


def lp_pool2d(x, norm_type, kernel_size, stride=None, padding=0, ceil_mode=False, data_format="NCHW", name=None):
    p = float(norm_type)
    xx = apply(lambda a: jnp.abs(a) ** p, _t(x))
    pooled = _pool_nd(xx, kernel_size, stride, padding, 2, "avg", 0.0, ceil_mode, data_format)
    k = _pair(kernel_size, 2)
    return apply(lambda a: (a * float(np.prod(k))) ** (1.0 / p), pooled)


def max_unpool2d(x, indices, kernel_size, stride=None, padding=0, data_format="NCHW", output_size=None, name=None):
    raise NotImplementedError("max_unpool2d requires real pool indices; not yet supported")
