"""Pooling via lax.reduce_window (reference: python/paddle/nn/functional/pooling.py)."""
import jax
import jax.numpy as jnp
import numpy as np

from ...framework.core import Tensor, apply, to_tensor


def _t(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


def _pair(v, n):
    if isinstance(v, (list, tuple)):
        out = list(v)
        return out if len(out) == n else out * n
    return [v] * n


def _pool_nd(x, ksize, stride, padding, nd, reducer, init, ceil_mode, data_format, count_include_pad=True):
    x = _t(x)
    channel_last = data_format[-1] == "C"
    k = _pair(ksize, nd)
    s = _pair(stride if stride is not None else ksize, nd)
    if isinstance(padding, str):
        pad_spatial = padding.upper()
    else:
        p = _pair(padding, nd) if not (isinstance(padding, (list, tuple)) and len(padding) == 2 * nd) else None
        if p is not None:
            pad_spatial = [(v, v) for v in p]
        else:
            pad_spatial = [(padding[2 * i], padding[2 * i + 1]) for i in range(nd)]

    if channel_last:
        window = (1,) + tuple(k) + (1,)
        strides = (1,) + tuple(s) + (1,)
        pad_full = "VALID" if pad_spatial == "VALID" else (
            "SAME" if pad_spatial == "SAME" else [(0, 0)] + list(pad_spatial) + [(0, 0)]
        )
    else:
        window = (1, 1) + tuple(k)
        strides = (1, 1) + tuple(s)
        pad_full = "VALID" if pad_spatial == "VALID" else (
            "SAME" if pad_spatial == "SAME" else [(0, 0), (0, 0)] + list(pad_spatial)
        )

    def fn(a):
        if reducer == "max":
            return jax.lax.reduce_window(a, -jnp.inf, jax.lax.max, window, strides, pad_full)
        summed = jax.lax.reduce_window(a, 0.0, jax.lax.add, window, strides, pad_full)
        if count_include_pad or pad_full in ("VALID", "SAME"):
            denom = float(np.prod(k))
            return summed / denom
        ones = jnp.ones_like(a)
        counts = jax.lax.reduce_window(ones, 0.0, jax.lax.add, window, strides, pad_full)
        return summed / counts

    return apply(fn, x, name=f"{reducer}_pool{nd}d")


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False, ceil_mode=False, data_format="NCL", name=None):
    out = _pool_nd(x, kernel_size, stride, padding, 1, "max", -np.inf, ceil_mode, data_format)
    if return_mask:
        return out, _pool_mask(x, out, kernel_size, stride, padding, 1, data_format)
    return out


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False, ceil_mode=False, data_format="NCHW", name=None):
    out = _pool_nd(x, kernel_size, stride, padding, 2, "max", -np.inf, ceil_mode, data_format)
    if return_mask:
        return out, _pool_mask(x, out, kernel_size, stride, padding, 2, data_format)
    return out


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False, ceil_mode=False, data_format="NCDHW", name=None):
    out = _pool_nd(x, kernel_size, stride, padding, 3, "max", -np.inf, ceil_mode, data_format)
    if return_mask:
        return out, _pool_mask(x, out, kernel_size, stride, padding, 3, data_format)
    return out


def _pool_mask(x, out, ksize, stride, padding, nd, data_format):
    """REAL argmax indices per pooling window, flattened over the input's
    spatial dims per channel map (paddle/torch max_pool return_mask
    semantics — the contract max_unpool inverts)."""
    if nd == 1:
        k = _pair(ksize, 1)[0]
        s = _pair(stride, 1)[0] if stride is not None else k
        p = _pair(padding, 1)[0]
        return _max_indices_2d(_t(x), (k, 1), (s, 1), (p, 0), expand_1d=True)
    if nd == 2:
        k = _pair(ksize, 2)
        s = _pair(stride, 2) if stride is not None else k
        p = _pair(padding, 2)
        return _max_indices_2d(_t(x), k, s, p)
    return Tensor(jnp.zeros(tuple(out.shape), jnp.int32))  # 3d: not required by unpool API


def _max_indices_2d(x, k, s, p, expand_1d=False):
    """x: [N, C, H, W] (or [N, C, L] with expand_1d) -> int32 [N, C, Ho, Wo]
    flat spatial argmax indices (h*W + w)."""
    kh, kw = int(k[0]), int(k[1])
    sh, sw = int(s[0]), int(s[1])
    ph, pw = int(p[0]), int(p[1])

    def fn(a):
        if expand_1d:
            a = a[..., None]
        N, C, H, W = a.shape
        Ho = (H + 2 * ph - kh) // sh + 1
        Wo = (W + 2 * pw - kw) // sw + 1
        hi = jnp.arange(Ho)[:, None] * sh - ph + jnp.arange(kh)[None, :]  # [Ho, kh]
        wi = jnp.arange(Wo)[:, None] * sw - pw + jnp.arange(kw)[None, :]  # [Wo, kw]
        vh = (hi >= 0) & (hi < H)
        vw = (wi >= 0) & (wi < W)
        hc = jnp.clip(hi, 0, H - 1)
        wc = jnp.clip(wi, 0, W - 1)
        # windows: [N, C, Ho, kh, Wo, kw]
        win = a[:, :, hc[:, :, None, None], wc[None, None, :, :]]
        valid = vh[:, :, None, None] & vw[None, None, :, :]
        win = jnp.where(valid, win, -jnp.inf)
        win = jnp.moveaxis(win, 3, 4).reshape(N, C, Ho, Wo, kh * kw)
        kidx = jnp.argmax(win, axis=-1)  # [N, C, Ho, Wo]
        # map window-slot -> absolute h/w: slot = r*kw + c
        r, c = kidx // kw, kidx % kw
        h_abs = hc[jnp.arange(Ho)[None, None, :, None], r]
        w_abs = wc[jnp.arange(Wo)[None, None, None, :], c]
        flat = (h_abs * W + w_abs).astype(jnp.int32)
        if expand_1d:
            flat = flat[..., 0]
        return flat

    return apply(fn, x, name="max_pool_indices")


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True, ceil_mode=False, data_format="NCL", name=None):
    return _pool_nd(x, kernel_size, stride, padding, 1, "avg", 0.0, ceil_mode, data_format, count_include_pad=not exclusive)


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False, exclusive=True, divisor_override=None, data_format="NCHW", name=None):
    return _pool_nd(x, kernel_size, stride, padding, 2, "avg", 0.0, ceil_mode, data_format, count_include_pad=not exclusive)


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False, exclusive=True, divisor_override=None, data_format="NCDHW", name=None):
    return _pool_nd(x, kernel_size, stride, padding, 3, "avg", 0.0, ceil_mode, data_format, count_include_pad=not exclusive)


def adaptive_avg_pool1d(x, output_size, name=None):
    return _adaptive(x, output_size, 1, "avg", "NCL")


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    return _adaptive(x, output_size, 2, "avg", data_format)


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    return _adaptive(x, output_size, 3, "avg", data_format)


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    out = _adaptive(x, output_size, 1, "max", "NCL")
    if not return_mask:
        return out
    return out, _adaptive_max_indices(_t(x), _pair(output_size, 1), nd=1)


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    out = _adaptive(x, output_size, 2, "max", "NCHW")
    if not return_mask:
        return out
    return out, _adaptive_max_indices(_t(x), _pair(output_size, 2), nd=2)


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    out = _adaptive(x, output_size, 3, "max", "NCDHW")
    if return_mask:
        raise NotImplementedError(
            "adaptive_max_pool3d(return_mask=True): 3d argmax indices not "
            "implemented — 1d/2d are; file a need if this path matters"
        )
    return out


def _adaptive_max_indices(x, osize, nd):
    """Flat spatial argmax indices for adaptive max pooling (torch/paddle
    return_mask contract), variable per-bin windows handled by gathering
    max-width windows with validity masking."""
    spatial = x.shape[2:]
    osize = [spatial[i] if osize[i] is None else int(osize[i]) for i in range(nd)]

    def bins(in_s, out_s):
        st = (np.arange(out_s) * in_s) // out_s
        en = ((np.arange(out_s) + 1) * in_s + out_s - 1) // out_s
        return st, en, int((en - st).max())

    if nd == 1:
        (L,) = spatial
        st, en, K = bins(L, osize[0])

        def fn(a):
            pos = jnp.asarray(st)[:, None] + jnp.arange(K)[None, :]  # [Lo, K]
            valid = pos < jnp.asarray(en)[:, None]
            pc = jnp.clip(pos, 0, L - 1)
            win = a[:, :, pc]  # [N, C, Lo, K]
            win = jnp.where(valid[None, None], win, -jnp.inf)
            kidx = jnp.argmax(win, axis=-1)
            return jnp.take_along_axis(
                jnp.broadcast_to(pc, win.shape[:2] + pc.shape), kidx[..., None], -1
            )[..., 0].astype(jnp.int32)

        return apply(fn, x, name="adaptive_max_indices1d")

    H, W = spatial
    sh, eh, Kh = bins(H, osize[0])
    sw, ew, Kw = bins(W, osize[1])

    def fn(a):
        N, C = a.shape[:2]
        hp = jnp.asarray(sh)[:, None] + jnp.arange(Kh)[None, :]  # [Ho, Kh]
        wp = jnp.asarray(sw)[:, None] + jnp.arange(Kw)[None, :]  # [Wo, Kw]
        vh = hp < jnp.asarray(eh)[:, None]
        vw = wp < jnp.asarray(ew)[:, None]
        hc = jnp.clip(hp, 0, H - 1)
        wc = jnp.clip(wp, 0, W - 1)
        win = a[:, :, hc[:, :, None, None], wc[None, None, :, :]]  # [N,C,Ho,Kh,Wo,Kw]
        valid = vh[:, :, None, None] & vw[None, None, :, :]
        win = jnp.where(valid, win, -jnp.inf)
        win = jnp.moveaxis(win, 3, 4).reshape(N, C, len(sh), len(sw), Kh * Kw)
        kidx = jnp.argmax(win, axis=-1)
        r, c = kidx // Kw, kidx % Kw
        h_abs = hc[jnp.arange(len(sh))[None, None, :, None], r]
        w_abs = wc[jnp.arange(len(sw))[None, None, None, :], c]
        return (h_abs * W + w_abs).astype(jnp.int32)

    return apply(fn, x, name="adaptive_max_indices2d")


def _adaptive(x, output_size, nd, mode, data_format):
    x = _t(x)
    channel_last = data_format[-1] == "C"
    spatial = x.shape[2:] if not channel_last else x.shape[1:-1]
    osize = _pair(output_size, nd)
    osize = [spatial[i] if osize[i] is None else osize[i] for i in range(nd)]

    def fn(a):
        out = a
        for i in range(nd):
            ax = (2 + i) if not channel_last else (1 + i)
            in_s, out_s = spatial[i], osize[i]
            if in_s % out_s == 0:
                k = in_s // out_s
                shape = list(out.shape)
                shape[ax : ax + 1] = [out_s, k]
                red = jnp.mean if mode == "avg" else jnp.max
                out = red(out.reshape(shape), axis=ax + 1)
            else:
                # general case: per-output-bin gather
                starts = (np.arange(out_s) * in_s) // out_s
                ends = ((np.arange(out_s) + 1) * in_s + out_s - 1) // out_s
                pieces = []
                for st, en in zip(starts, ends):
                    sl = [slice(None)] * out.ndim
                    sl[ax] = slice(int(st), int(en))
                    red = jnp.mean if mode == "avg" else jnp.max
                    pieces.append(red(out[tuple(sl)], axis=ax, keepdims=True))
                out = jnp.concatenate(pieces, axis=ax)
        return out

    return apply(fn, x, name=f"adaptive_{mode}_pool")


def lp_pool2d(x, norm_type, kernel_size, stride=None, padding=0, ceil_mode=False, data_format="NCHW", name=None):
    p = float(norm_type)
    xx = apply(lambda a: jnp.abs(a) ** p, _t(x))
    pooled = _pool_nd(xx, kernel_size, stride, padding, 2, "avg", 0.0, ceil_mode, data_format)
    k = _pair(kernel_size, 2)
    return apply(lambda a: (a * float(np.prod(k))) ** (1.0 / p), pooled)


def max_unpool2d(x, indices, kernel_size, stride=None, padding=0, data_format="NCHW", output_size=None, name=None):
    """Inverse of max_pool2d(return_mask=True): scatter pooled values back
    to their argmax positions, zeros elsewhere (reference:
    nn/functional/pooling.py max_unpool2d / phi unpool kernel)."""
    k = _pair(kernel_size, 2)
    s = _pair(stride, 2) if stride is not None else k
    p = _pair(padding, 2)
    xt, it = _t(x), _t(indices)
    N, C, Ho, Wo = xt.shape
    if output_size is not None:
        Hout, Wout = [int(v) for v in output_size[-2:]]
    else:
        Hout = (Ho - 1) * s[0] - 2 * p[0] + k[0]
        Wout = (Wo - 1) * s[1] - 2 * p[1] + k[1]

    def fn(v, idx):
        flat = jnp.zeros((N, C, Hout * Wout), v.dtype)
        n = jnp.arange(N)[:, None, None]
        c = jnp.arange(C)[None, :, None]
        flat = flat.at[n, c, idx.reshape(N, C, -1)].set(v.reshape(N, C, -1))
        return flat.reshape(N, C, Hout, Wout)

    return apply(fn, xt, it, name="max_unpool2d")
