"""paddle.hub parity (reference: python/paddle/hapi/hub.py — list/help/load
from github/local hubconf.py). No-egress: local source only."""
import importlib.util
import os
import sys


def _load_hubconf(repo_dir):
    path = os.path.join(repo_dir, "hubconf.py")
    if not os.path.exists(path):
        raise FileNotFoundError(f"no hubconf.py in {repo_dir}")
    spec = importlib.util.spec_from_file_location("hubconf", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def list(repo_dir, source="local", force_reload=False):
    if source != "local":
        raise RuntimeError("no network egress; only source='local' is supported")
    mod = _load_hubconf(repo_dir)
    return [k for k, v in vars(mod).items() if callable(v) and not k.startswith("_")]


def help(repo_dir, model, source="local", force_reload=False):
    if source != "local":
        raise RuntimeError("no network egress; only source='local' is supported")
    return getattr(_load_hubconf(repo_dir), model).__doc__


def load(repo_dir, model, *args, source="local", force_reload=False, **kwargs):
    if source != "local":
        raise RuntimeError("no network egress; only source='local' is supported")
    return getattr(_load_hubconf(repo_dir), model)(*args, **kwargs)
