"""Search/sort ops (reference: python/paddle/tensor/search.py).

Integer-output ops (argmax/argsort/topk indices) are non-differentiable; ops
with mixed outputs compute indices outside the tape and values via gather so
gradients flow only through values.
"""
import jax
import jax.numpy as jnp
import numpy as np

from ..framework import dtype as dtypes
from ..framework.core import Tensor, apply, to_tensor
from . import manipulation


def _t(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


def argmax(x, axis=None, keepdim=False, dtype="int64", name=None):
    a = _t(x)._data
    out = jnp.argmax(a.reshape(-1) if axis is None else a, axis=None if axis is None else int(axis))
    if keepdim and axis is not None:
        out = jnp.expand_dims(out, int(axis))
    return Tensor(out.astype(dtypes.convert_dtype(dtype)))


def argmin(x, axis=None, keepdim=False, dtype="int64", name=None):
    a = _t(x)._data
    out = jnp.argmin(a.reshape(-1) if axis is None else a, axis=None if axis is None else int(axis))
    if keepdim and axis is not None:
        out = jnp.expand_dims(out, int(axis))
    return Tensor(out.astype(dtypes.convert_dtype(dtype)))


def argsort(x, axis=-1, descending=False, stable=False, name=None):
    a = _t(x)._data
    out = jnp.argsort(-a if descending else a, axis=axis, stable=stable or descending)
    return Tensor(out.astype(jnp.int64))


def sort(x, axis=-1, descending=False, stable=False, name=None):
    x = _t(x)
    idx = argsort(x, axis=axis, descending=descending, stable=stable)._data
    return manipulation.take_along_axis(x, Tensor(idx), axis=axis, broadcast=False)


def topk(x, k, axis=None, largest=True, sorted=True, name=None):
    x = _t(x)
    if isinstance(k, Tensor):
        k = int(k.item())
    ax = -1 if axis is None else int(axis)

    a = x._data
    moved = jnp.moveaxis(a, ax, -1)
    vals_idx = jax.lax.top_k(moved if largest else -moved, k)[1]
    idx = jnp.moveaxis(vals_idx, -1, ax)
    values = manipulation.take_along_axis(x, Tensor(idx), axis=ax, broadcast=False)
    return values, Tensor(idx.astype(jnp.int64))


def kthvalue(x, k, axis=-1, keepdim=False, name=None):
    x = _t(x)
    idx_sorted = jnp.argsort(x._data, axis=axis)
    idx = jnp.take(idx_sorted, k - 1, axis=axis)
    idx_e = jnp.expand_dims(idx, axis)
    vals = manipulation.take_along_axis(x, Tensor(idx_e), axis=axis, broadcast=False)
    if not keepdim:
        vals = manipulation.squeeze(vals, axis)
    return vals, Tensor(idx.astype(jnp.int64))


def mode(x, axis=-1, keepdim=False, name=None):
    a = np.asarray(_t(x)._data)
    from scipy import stats as _stats  # scipy ships with jax deps

    m = _stats.mode(a, axis=axis, keepdims=keepdim)
    return Tensor(jnp.asarray(m.mode)), Tensor(jnp.asarray(m.count))


def searchsorted(sorted_sequence, values, out_int32=False, right=False, name=None):
    out = jnp.searchsorted(_t(sorted_sequence)._data, _t(values)._data, side="right" if right else "left")
    return Tensor(out.astype(jnp.int32 if out_int32 else jnp.int64))


def bucketize(x, sorted_sequence, out_int32=False, right=False, name=None):
    return searchsorted(sorted_sequence, x, out_int32, right)


def index_fill(x, index, axis, value, name=None):
    idx = _t(index)._data

    def fn(a):
        sl = [slice(None)] * a.ndim
        sl[axis] = idx
        return a.at[tuple(sl)].set(value)

    return apply(fn, _t(x))


def masked_argmax(x, mask, axis=None, keepdim=False):
    a = jnp.where(_t(mask)._data, _t(x)._data, -jnp.inf)
    return argmax(Tensor(a), axis=axis, keepdim=keepdim)
