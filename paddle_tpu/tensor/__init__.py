"""paddle_tpu.tensor — the op surface (reference: python/paddle/tensor/).

Functions live in submodules; this package re-exports them and installs them
as Tensor methods + Python operators (reference installs methods via
monkey-patching in python/paddle/tensor/__init__.py too).
"""
from ..framework.core import Tensor
from . import creation, einsum as _einsum_mod, extras, linalg, logic, manipulation, math, search
from .creation import *  # noqa: F401,F403
from .einsum import einsum  # noqa: F401
from .linalg import *  # noqa: F401,F403
from .logic import *  # noqa: F401,F403
from .manipulation import *  # noqa: F401,F403
from .math import *  # noqa: F401,F403
from .search import *  # noqa: F401,F403
from .extras import *  # noqa: F401,F403

_METHOD_SOURCES = [math, manipulation, linalg, logic, search, creation, extras]

# name → (module, function) explicit method table where names differ
_EXPLICIT = {
    "einsum": _einsum_mod.einsum,
}


def _install_methods():
    method_names = set()
    for mod in _METHOD_SOURCES:
        for name in dir(mod):
            if name.startswith("_"):
                continue
            fn = getattr(mod, name)
            if not callable(fn) or isinstance(fn, type):
                continue
            if name in ("to_tensor", "slice_obj", "builtins_slice", "apply") or getattr(
                fn, "__module__", ""
            ).startswith(("jax", "scipy")):
                continue
            if not hasattr(Tensor, name):
                setattr(Tensor, name, fn)
                method_names.add(name)

    # operators
    Tensor.__add__ = lambda s, o: math.add(s, o)
    Tensor.__radd__ = lambda s, o: math.add(o, s)
    Tensor.__sub__ = lambda s, o: math.subtract(s, o)
    Tensor.__rsub__ = lambda s, o: math.subtract(o, s)
    Tensor.__mul__ = lambda s, o: math.multiply(s, o)
    Tensor.__rmul__ = lambda s, o: math.multiply(o, s)
    Tensor.__truediv__ = lambda s, o: math.divide(s, o)
    Tensor.__rtruediv__ = lambda s, o: math.divide(o, s)
    Tensor.__floordiv__ = lambda s, o: math.floor_divide(s, o)
    Tensor.__rfloordiv__ = lambda s, o: math.floor_divide(o, s)
    Tensor.__mod__ = lambda s, o: math.remainder(s, o)
    Tensor.__pow__ = lambda s, o: math.pow(s, o)
    Tensor.__rpow__ = lambda s, o: math.pow(o, s)
    Tensor.__neg__ = lambda s: math.neg(s)
    Tensor.__abs__ = lambda s: math.abs(s)
    Tensor.__matmul__ = lambda s, o: linalg.matmul(s, o)
    Tensor.__rmatmul__ = lambda s, o: linalg.matmul(o, s)
    Tensor.__eq__ = lambda s, o: logic.equal(s, o)
    Tensor.__ne__ = lambda s, o: logic.not_equal(s, o)
    Tensor.__lt__ = lambda s, o: logic.less_than(s, o)
    Tensor.__le__ = lambda s, o: logic.less_equal(s, o)
    Tensor.__gt__ = lambda s, o: logic.greater_than(s, o)
    Tensor.__ge__ = lambda s, o: logic.greater_equal(s, o)
    Tensor.__invert__ = lambda s: logic.logical_not(s)
    Tensor.__and__ = lambda s, o: logic.bitwise_and(s, o)
    Tensor.__or__ = lambda s, o: logic.bitwise_or(s, o)
    Tensor.__xor__ = lambda s, o: logic.bitwise_xor(s, o)
    Tensor.__hash__ = object.__hash__


_install_methods()
