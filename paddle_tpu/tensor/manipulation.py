"""Shape/layout ops (reference: python/paddle/tensor/manipulation.py)."""
import jax
import jax.numpy as jnp
import numpy as np

from ..framework import dtype as dtypes
from ..framework.core import Tensor, apply, to_tensor


def _t(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


def _shape_arg(shape):
    def coerce(s):
        if isinstance(s, Tensor):
            return int(s._data)
        try:
            return int(s)
        except Exception:
            # symbolic dims (jax.export shape polymorphism) pass through —
            # they participate in shape arithmetic but are not constants
            return s

    return tuple(coerce(s) for s in shape)


def reshape(x, shape, name=None):
    if isinstance(shape, Tensor):
        shape = tuple(int(v) for v in shape.numpy())
    else:
        shape = _shape_arg(shape)
    # Paddle semantics: 0 means "copy this dim from input".
    x = _t(x)
    shape = tuple(x.shape[i] if s == 0 else s for i, s in enumerate(shape))
    return apply(lambda a: jnp.reshape(a, shape), x, name="reshape")


def reshape_(x, shape, name=None):
    out = reshape(x, shape)
    x._data, x._node, x._out_idx = out._data, out._node, out._out_idx
    x.stop_gradient = out.stop_gradient
    return x


def flatten(x, start_axis=0, stop_axis=-1, name=None):
    x = _t(x)
    nd = x.ndim
    s, e = start_axis % nd, stop_axis % nd
    new_shape = x.shape[:s] + [int(np.prod(x.shape[s : e + 1]))] + x.shape[e + 1 :]
    return reshape(x, new_shape)


def squeeze(x, axis=None, name=None):
    x = _t(x)
    if axis is None:
        ax = None
    else:
        axes = axis if isinstance(axis, (list, tuple)) else [axis]
        ax = tuple(a % x.ndim for a in axes if x.shape[a % x.ndim] == 1)
    return apply(lambda a: jnp.squeeze(a, axis=ax), x, name="squeeze")


def unsqueeze(x, axis, name=None):
    axes = axis if isinstance(axis, (list, tuple)) else [axis]
    axes = [int(a._data) if isinstance(a, Tensor) else int(a) for a in axes]
    return apply(lambda a: jnp.expand_dims(a, axis=tuple(axes)), _t(x), name="unsqueeze")


unsqueeze_ = unsqueeze


def transpose(x, perm, name=None):
    return apply(lambda a: jnp.transpose(a, axes=tuple(perm)), _t(x), name="transpose")


def moveaxis(x, source, destination, name=None):
    return apply(lambda a: jnp.moveaxis(a, source, destination), _t(x))


def swapaxes(x, axis1, axis2, name=None):
    return apply(lambda a: jnp.swapaxes(a, axis1, axis2), _t(x))


swapdims = swapaxes


def concat(x, axis=0, name=None):
    ts = [_t(v) for v in x]
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    return apply(lambda *arrs: jnp.concatenate(arrs, axis=axis), *ts, name="concat")


def stack(x, axis=0, name=None):
    ts = [_t(v) for v in x]
    return apply(lambda *arrs: jnp.stack(arrs, axis=axis), *ts, name="stack")


def split(x, num_or_sections, axis=0, name=None):
    x = _t(x)
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    ax = axis % x.ndim
    if isinstance(num_or_sections, int):
        n = num_or_sections
        if x.shape[ax] % n != 0:
            raise ValueError(
                f"split: dimension {ax} (size {x.shape[ax]}) is not divisible by {n}; "
                "pass explicit section sizes instead"
            )
        sizes = [x.shape[ax] // n] * n
    else:
        sizes = [
            int(s._data) if isinstance(s, Tensor) else int(s) for s in num_or_sections
        ]
        total = x.shape[ax]
        if -1 in sizes:
            known = sum(s for s in sizes if s != -1)
            sizes[sizes.index(-1)] = total - known
    offsets = np.cumsum([0] + sizes)

    def fn(a):
        return tuple(jax.lax.slice_in_dim(a, int(offsets[i]), int(offsets[i + 1]), axis=ax) for i in range(len(sizes)))

    return list(apply(fn, x, name="split"))


def chunk(x, chunks, axis=0, name=None):
    return split(x, chunks, axis)


def unbind(x, axis=0, name=None):
    x = _t(x)
    n = x.shape[axis % x.ndim]
    outs = split(x, n, axis)
    return [squeeze(o, axis) for o in outs]


def tile(x, repeat_times, name=None):
    reps = tuple(int(r._data) if isinstance(r, Tensor) else int(r) for r in repeat_times)
    return apply(lambda a: jnp.tile(a, reps), _t(x), name="tile")


def expand(x, shape, name=None):
    x = _t(x)
    shape = [int(s._data) if isinstance(s, Tensor) else int(s) for s in shape]
    shape = [x.shape[i - (len(shape) - x.ndim)] if s == -1 and i >= len(shape) - x.ndim else s for i, s in enumerate(shape)]
    return apply(lambda a: jnp.broadcast_to(a, tuple(shape)), x, name="expand")


def expand_as(x, y, name=None):
    return apply(lambda a: jnp.broadcast_to(a, tuple(_t(y).shape)), _t(x))


def broadcast_to(x, shape, name=None):
    return apply(lambda a: jnp.broadcast_to(a, tuple(shape)), _t(x), name="broadcast_to")


def broadcast_tensors(inputs, name=None):
    ts = [_t(v) for v in inputs]
    shape = jnp.broadcast_shapes(*[tuple(t.shape) for t in ts])
    return [broadcast_to(t, shape) for t in ts]


def flip(x, axis, name=None):
    axes = tuple(axis) if isinstance(axis, (list, tuple)) else (axis,)
    return apply(lambda a: jnp.flip(a, axis=axes), _t(x), name="flip")


def rot90(x, k=1, axes=(0, 1), name=None):
    return apply(lambda a: jnp.rot90(a, k=k, axes=tuple(axes)), _t(x))


def roll(x, shifts, axis=None, name=None):
    return apply(lambda a: jnp.roll(a, shifts, axis=axis), _t(x), name="roll")


def gather(x, index, axis=0, name=None):
    idx = _t(index)._data
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    if idx.ndim > 1:
        idx = idx.reshape(-1)
    return apply(lambda a: jnp.take(a, idx, axis=axis), _t(x), name="gather")


def gather_nd(x, index, name=None):
    idx = _t(index)._data

    def fn(a):
        return a[tuple(jnp.moveaxis(idx, -1, 0))]

    return apply(fn, _t(x), name="gather_nd")


def take_along_axis(arr, indices, axis, broadcast=True, name=None):
    idx = _t(indices)._data
    arr = _t(arr)
    if broadcast:
        tgt = list(arr.shape)
        tgt[axis] = idx.shape[axis]
        idx = jnp.broadcast_to(idx, tuple(tgt))
    return apply(lambda a: jnp.take_along_axis(a, idx, axis=axis), arr, name="take_along_axis")


def put_along_axis(arr, indices, values, axis, reduce="assign", name=None, **kw):
    idx = _t(indices)._data
    arr_t = _t(arr)
    idx_full = jnp.broadcast_to(idx, tuple(arr_t.shape[:axis]) + (idx.shape[axis],) + tuple(arr_t.shape[axis + 1 :]))
    grids = jnp.meshgrid(*[jnp.arange(s) for s in idx_full.shape], indexing="ij")
    grids[axis] = idx_full
    locs = tuple(grids)

    def fn(a, v):
        v = jnp.broadcast_to(v, idx_full.shape)
        ref = a.at[locs]
        if reduce == "assign":
            return ref.set(v)
        if reduce in ("add", "sum"):
            return ref.add(v)
        if reduce in ("mul", "multiply"):
            return ref.multiply(v)
        if reduce == "amax":
            return ref.max(v)
        if reduce == "amin":
            return ref.min(v)
        raise ValueError(reduce)

    return apply(fn, arr_t, _t(values), name="put_along_axis")


def scatter(x, index, updates, overwrite=True, name=None):
    idx = _t(index)._data.reshape(-1)

    def fn(a, u):
        if overwrite:
            return a.at[idx].set(u)
        return a.at[idx].set(0).at[idx].add(u)

    return apply(fn, _t(x), _t(updates), name="scatter")


def scatter_nd_add(x, index, updates, name=None):
    idx = _t(index)._data

    def fn(a, u):
        return a.at[tuple(jnp.moveaxis(idx, -1, 0))].add(u)

    return apply(fn, _t(x), _t(updates), name="scatter_nd_add")


def scatter_nd(index, updates, shape, name=None):
    zeros = Tensor(jnp.zeros(tuple(shape), _t(updates).dtype))
    return scatter_nd_add(zeros, index, updates)


def index_select(x, index, axis=0, name=None):
    return gather(x, index, axis)


def index_sample(x, index):
    idx = _t(index)._data

    def fn(a):
        return jnp.take_along_axis(a, idx, axis=1)

    return apply(fn, _t(x), name="index_sample")


def index_add(x, index, axis, value, name=None):
    idx = _t(index)._data

    def fn(a, v):
        sl = [slice(None)] * a.ndim
        sl[axis] = idx
        return a.at[tuple(sl)].add(v)

    return apply(fn, _t(x), _t(value), name="index_add")


def index_put(x, indices, value, accumulate=False, name=None):
    locs = tuple(_t(i)._data for i in indices)

    def fn(a, v):
        return a.at[locs].add(v) if accumulate else a.at[locs].set(v)

    return apply(fn, _t(x), _t(value), name="index_put")


def masked_select(x, mask, name=None):
    x, mask = _t(x), _t(mask)
    return Tensor(x._data[mask._data])


def masked_fill(x, mask, value, name=None):
    m = _t(mask)._data
    v = value.item() if isinstance(value, Tensor) and value.size == 1 else value
    if isinstance(v, Tensor):
        return apply(lambda a, b: jnp.where(m, b, a), _t(x), v, name="masked_fill")
    return apply(lambda a: jnp.where(m, v, a), _t(x), name="masked_fill")


def masked_scatter(x, mask, value, name=None):
    x, mask, value = _t(x), _t(mask), _t(value)
    m = mask._data
    flat_idx = jnp.cumsum(m.reshape(-1)) - 1

    def fn(a, v):
        picked = v.reshape(-1)[jnp.clip(flat_idx, 0, v.size - 1)].reshape(a.shape)
        return jnp.where(m, picked, a)

    return apply(fn, x, value, name="masked_scatter")


def where(condition, x=None, y=None, name=None):
    cond = _t(condition)._data
    if x is None and y is None:
        return nonzero(condition, as_tuple=True)
    return apply(lambda a, b: jnp.where(cond, a, b), _t(x), _t(y), name="where")


def nonzero(x, as_tuple=False):
    arr = np.asarray(_t(x)._data)
    nz = np.nonzero(arr)
    if as_tuple:
        return tuple(Tensor(jnp.asarray(v)) for v in nz)
    return Tensor(jnp.asarray(np.stack(nz, axis=1).astype(np.int64)))


def unique(x, return_index=False, return_inverse=False, return_counts=False, axis=None, dtype="int64", name=None):
    arr = np.asarray(_t(x)._data)
    res = np.unique(arr, return_index=return_index, return_inverse=return_inverse, return_counts=return_counts, axis=axis)
    if not isinstance(res, tuple):
        return Tensor(jnp.asarray(res))
    out = [Tensor(jnp.asarray(r)) for r in res]
    if return_index:
        # paddle's unique does not return first-occurrence index unless asked;
        # numpy ordering differs (sorted) — acceptable here.
        pass
    return tuple(out)


def unique_consecutive(x, return_inverse=False, return_counts=False, axis=None, dtype="int64", name=None):
    arr = np.asarray(_t(x)._data)
    if axis is None:
        arr = arr.reshape(-1)
        change = np.concatenate([[True], arr[1:] != arr[:-1]])
        vals = arr[change]
        outs = [Tensor(jnp.asarray(vals))]
        if return_inverse:
            outs.append(Tensor(jnp.asarray(np.cumsum(change) - 1)))
        if return_counts:
            idx = np.nonzero(change)[0]
            outs.append(Tensor(jnp.asarray(np.diff(np.append(idx, arr.size)))))
        return outs[0] if len(outs) == 1 else tuple(outs)
    # axis path: dedupe consecutive slices along `axis`
    moved = np.moveaxis(arr, axis, 0)
    flat = moved.reshape(moved.shape[0], -1)
    change = np.concatenate([[True], np.any(flat[1:] != flat[:-1], axis=1)])
    vals = np.moveaxis(moved[change], 0, axis)
    outs = [Tensor(jnp.asarray(vals))]
    if return_inverse:
        outs.append(Tensor(jnp.asarray(np.cumsum(change) - 1)))
    if return_counts:
        idx = np.nonzero(change)[0]
        outs.append(Tensor(jnp.asarray(np.diff(np.append(idx, moved.shape[0])))))
    return outs[0] if len(outs) == 1 else tuple(outs)


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):
    x = _t(x)
    if isinstance(pad, Tensor):
        pad = [int(v) for v in pad.numpy()]
    pad = list(pad)
    nd = x.ndim
    if len(pad) == 2 * nd:
        # full-rank spec: per-dim (low, high) pairs in dim order
        widths = [(pad[2 * i], pad[2 * i + 1]) for i in range(nd)]
    else:
        # partial spec applies to trailing spatial dims, last dim first
        n = len(pad) // 2
        rev = [(pad[2 * i], pad[2 * i + 1]) for i in range(n)]
        widths = [(0, 0)] * (nd - n) + rev[::-1]
    jmode = {"constant": "constant", "reflect": "reflect", "replicate": "edge", "circular": "wrap"}[mode]
    kw = {"constant_values": value} if jmode == "constant" else {}
    return apply(lambda a: jnp.pad(a, widths, mode=jmode, **kw), x, name="pad")


def repeat_interleave(x, repeats, axis=None, name=None):
    x = _t(x)
    if isinstance(repeats, Tensor):
        reps = repeats._data
        return Tensor(jnp.repeat(x._data if axis is not None else x._data.reshape(-1), reps, axis=axis if axis is not None else 0))
    return apply(
        lambda a: jnp.repeat(a if axis is not None else a.reshape(-1), repeats, axis=axis if axis is not None else 0),
        x,
    )


def as_strided(x, shape, stride, offset=0, name=None):
    arr = np.lib.stride_tricks.as_strided(
        np.asarray(_t(x)._data).reshape(-1)[offset:],
        shape=tuple(shape),
        strides=tuple(s * np.dtype(_t(x).dtype).itemsize for s in stride),
    )
    return Tensor(jnp.asarray(arr.copy()))


def view(x, shape_or_dtype, name=None):
    if isinstance(shape_or_dtype, (list, tuple)):
        return reshape(x, shape_or_dtype)
    return _t(x).astype(shape_or_dtype)


def view_as(x, other, name=None):
    return reshape(x, _t(other).shape)


def slice(x, axes, starts, ends):
    x = _t(x)
    sl = [builtins_slice()] * x.ndim if False else [None] * 0
    idx = [slice_obj(None) for _ in range(x.ndim)]
    for ax, s, e in zip(axes, starts, ends):
        s = int(s._data) if isinstance(s, Tensor) else int(s)
        e = int(e._data) if isinstance(e, Tensor) else int(e)
        idx[ax] = slice_obj(s, e)
    idx = tuple(idx)
    return apply(lambda a: a[idx], x, name="slice")


def slice_obj(*args):
    import builtins

    return builtins.slice(*args)


def builtins_slice():
    import builtins

    return builtins.slice(None)


def strided_slice(x, axes, starts, ends, strides, name=None):
    x = _t(x)
    idx = [slice_obj(None) for _ in range(x.ndim)]
    for ax, s, e, st in zip(axes, starts, ends, strides):
        idx[ax] = slice_obj(int(s), int(e), int(st))
    idx = tuple(idx)
    return apply(lambda a: a[idx], x, name="strided_slice")


def crop(x, shape=None, offsets=None, name=None):
    x = _t(x)
    offsets = offsets or [0] * x.ndim
    idx = tuple(slice_obj(int(o), int(o) + int(s) if int(s) != -1 else None) for o, s in zip(offsets, shape))
    return apply(lambda a: a[idx], x, name="crop")


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):
    size = (index_num + nshards - 1) // nshards

    def fn(a):
        shard = a // size
        return jnp.where(shard == shard_id, a % size, ignore_value)

    return Tensor(fn(_t(input)._data))


def tensordot(x, y, axes=2, name=None):
    ax = axes
    if isinstance(ax, Tensor):
        ax = ax.numpy().tolist()
    if isinstance(ax, (list, tuple)):
        ax = tuple(tuple(a) if isinstance(a, (list, tuple)) else a for a in ax)
    return apply(lambda a, b: jnp.tensordot(a, b, axes=ax), _t(x), _t(y), name="tensordot")


def atleast_1d(*inputs):
    outs = [apply(jnp.atleast_1d, _t(x)) for x in inputs]
    return outs[0] if len(outs) == 1 else outs


def atleast_2d(*inputs):
    outs = [apply(jnp.atleast_2d, _t(x)) for x in inputs]
    return outs[0] if len(outs) == 1 else outs


def atleast_3d(*inputs):
    outs = [apply(jnp.atleast_3d, _t(x)) for x in inputs]
    return outs[0] if len(outs) == 1 else outs


def hstack(x, name=None):
    return apply(lambda *arrs: jnp.hstack(arrs), *[_t(v) for v in x])


def vstack(x, name=None):
    return apply(lambda *arrs: jnp.vstack(arrs), *[_t(v) for v in x])


def dstack(x, name=None):
    return apply(lambda *arrs: jnp.dstack(arrs), *[_t(v) for v in x])


def dsplit(x, num_or_indices, name=None):
    return split(x, num_or_indices, axis=2)


def hsplit(x, num_or_indices, name=None):
    return split(x, num_or_indices, axis=1 if _t(x).ndim > 1 else 0)


def vsplit(x, num_or_indices, name=None):
    return split(x, num_or_indices, axis=0)
