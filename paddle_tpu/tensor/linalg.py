"""Linear algebra (reference: python/paddle/tensor/linalg.py).

matmul is THE op on TPU: it lowers straight to MXU dot_general. No blas
wrapper layer exists (reference needed cuBLAS glue; XLA is our BLAS).
"""
import jax
import jax.numpy as jnp
import numpy as np

from ..framework.core import Tensor, apply, to_tensor


def _t(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    def fn(a, b):
        from ..amp.auto_cast import amp_cast_inputs

        a, b = amp_cast_inputs("matmul", [a, b])
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2) if a.ndim > 1 else a
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2) if b.ndim > 1 else b
        return a @ b

    return apply(fn, _t(x), _t(y), name="matmul")


def mm(x, y, name=None):
    return matmul(x, y)


def bmm(x, y, name=None):
    return apply(jnp.matmul, _t(x), _t(y), name="bmm")


def mv(x, vec, name=None):
    return apply(jnp.matmul, _t(x), _t(vec), name="mv")


def t(x, name=None):
    x = _t(x)
    if x.ndim < 2:
        return x.clone()
    return apply(lambda a: jnp.swapaxes(a, -1, -2), x, name="t")


def dist(x, y, p=2.0, name=None):
    return apply(lambda a, b: _p_norm(a - b, p), _t(x), _t(y), name="dist")


def _p_norm(a, p, axis=None, keepdims=False):
    if p == float("inf"):
        return jnp.max(jnp.abs(a), axis=axis, keepdims=keepdims)
    if p == float("-inf"):
        return jnp.min(jnp.abs(a), axis=axis, keepdims=keepdims)
    if p == 0:
        return jnp.sum((a != 0).astype(a.dtype), axis=axis, keepdims=keepdims)
    return jnp.sum(jnp.abs(a) ** p, axis=axis, keepdims=keepdims) ** (1.0 / p)


def norm(x, p=None, axis=None, keepdim=False, name=None):
    x = _t(x)
    if p is None:
        p = "fro" if axis is None or isinstance(axis, (list, tuple)) else 2.0
    if p == "fro":
        ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
        return apply(lambda a: jnp.sqrt(jnp.sum(a * a, axis=ax, keepdims=keepdim)), x, name="fro_norm")
    if p == "nuc":
        return apply(lambda a: jnp.sum(jnp.linalg.svd(a, compute_uv=False), axis=-1), x)
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
    return apply(lambda a: _p_norm(a, p, axis=ax, keepdims=keepdim), x, name="p_norm")


def p_norm(x, p=2.0, axis=None, keepdim=False):
    return norm(x, p, axis, keepdim)


def cond(x, p=None, name=None):
    return apply(lambda a: jnp.linalg.cond(a, p=p), _t(x))


def dot(x, y, name=None):
    return apply(lambda a, b: jnp.sum(a * b, axis=-1), _t(x), _t(y), name="dot")


def cholesky(x, upper=False, name=None):
    def fn(a):
        L = jnp.linalg.cholesky(a)
        return jnp.swapaxes(L, -1, -2) if upper else L

    return apply(fn, _t(x), name="cholesky")


def cholesky_solve(x, y, upper=False, name=None):
    def fn(b, L):
        Lm = jnp.swapaxes(L, -1, -2) if upper else L
        z = jax.scipy.linalg.solve_triangular(Lm, b, lower=True)
        return jax.scipy.linalg.solve_triangular(jnp.swapaxes(Lm, -1, -2), z, lower=False)

    return apply(fn, _t(x), _t(y))


def inverse(x, name=None):
    return apply(jnp.linalg.inv, _t(x), name="inverse")


inv = inverse


def pinv(x, rcond=1e-15, hermitian=False, name=None):
    return apply(lambda a: jnp.linalg.pinv(a, rtol=rcond, hermitian=hermitian), _t(x))


def det(x, name=None):
    return apply(jnp.linalg.det, _t(x), name="det")


def slogdet(x, name=None):
    def fn(a):
        sign, logabs = jnp.linalg.slogdet(a)
        return jnp.stack([sign, logabs])

    return apply(fn, _t(x), name="slogdet")


def solve(x, y, name=None):
    return apply(jnp.linalg.solve, _t(x), _t(y), name="solve")


def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False, name=None):
    def fn(a, b):
        return jax.scipy.linalg.solve_triangular(
            a, b, lower=not upper, trans=1 if transpose else 0, unit_diagonal=unitriangular
        )

    return apply(fn, _t(x), _t(y))


def lstsq(x, y, rcond=None, driver=None, name=None):
    a, b = _t(x)._data, _t(y)._data
    sol, res, rank, sv = jnp.linalg.lstsq(a, b, rcond=rcond)
    return Tensor(sol), Tensor(res), Tensor(rank), Tensor(sv)


def qr(x, mode="reduced", name=None):
    x = _t(x)
    if mode == "r":
        return apply(lambda a: jnp.linalg.qr(a, mode="r"), x)
    q, r = jnp.linalg.qr(x._data, mode=mode)

    def fn(a):
        return jnp.linalg.qr(a, mode=mode)

    return apply(fn, x, name="qr")


def svd(x, full_matrices=False, name=None):
    def fn(a):
        u, s, vh = jnp.linalg.svd(a, full_matrices=full_matrices)
        return u, s, jnp.swapaxes(vh, -1, -2)  # paddle returns V^H as vh? paddle returns vh

    # paddle.linalg.svd returns (U, S, VH)
    def fn2(a):
        return jnp.linalg.svd(a, full_matrices=full_matrices)

    return apply(fn2, _t(x), name="svd")


def svdvals(x, name=None):
    return apply(lambda a: jnp.linalg.svd(a, compute_uv=False), _t(x))


def eig(x, name=None):
    vals, vecs = np.linalg.eig(np.asarray(_t(x)._data))
    return Tensor(jnp.asarray(vals)), Tensor(jnp.asarray(vecs))


def eigh(x, UPLO="L", name=None):
    return apply(lambda a: jnp.linalg.eigh(a, UPLO=UPLO), _t(x), name="eigh")


def eigvals(x, name=None):
    return Tensor(jnp.asarray(np.linalg.eigvals(np.asarray(_t(x)._data))))


def eigvalsh(x, UPLO="L", name=None):
    return apply(lambda a: jnp.linalg.eigvalsh(a, UPLO=UPLO), _t(x))


def matrix_power(x, n, name=None):
    return apply(lambda a: jnp.linalg.matrix_power(a, n), _t(x))


def matrix_rank(x, tol=None, hermitian=False, name=None):
    return Tensor(jnp.linalg.matrix_rank(_t(x)._data, rtol=tol))


def multi_dot(x, name=None):
    return apply(lambda *arrs: jnp.linalg.multi_dot(arrs), *[_t(v) for v in x])


def lu(x, pivot=True, get_infos=False, name=None):
    a = _t(x)
    lu_, piv = jax.scipy.linalg.lu_factor(a._data)
    outs = (Tensor(lu_), Tensor(piv.astype(jnp.int32) + 1))
    if get_infos:
        return outs + (Tensor(jnp.zeros((), jnp.int32)),)
    return outs


def householder_product(x, tau, name=None):
    a, t_ = np.asarray(_t(x)._data), np.asarray(_t(tau)._data)
    m, n = a.shape[-2], a.shape[-1]
    q = np.eye(m, dtype=a.dtype)
    for i in range(len(t_) - 1, -1, -1):
        v = np.zeros(m, dtype=a.dtype)
        v[i] = 1.0
        v[i + 1 :] = a[i + 1 :, i]
        q = (np.eye(m) - t_[i] * np.outer(v, v)) @ q
    return Tensor(jnp.asarray(q[:, :n]))


def corrcoef(x, rowvar=True, name=None):
    return apply(lambda a: jnp.corrcoef(a, rowvar=rowvar), _t(x))


def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None, name=None):
    return apply(lambda a: jnp.cov(a, rowvar=rowvar, ddof=1 if ddof else 0), _t(x))


def histogram(input, bins=100, min=0, max=0, name=None):
    a = np.asarray(_t(input)._data)
    rng = None if (min == 0 and max == 0) else (min, max)
    hist, _ = np.histogram(a, bins=bins, range=rng)
    return Tensor(jnp.asarray(hist.astype(np.int64)))


def bincount(x, weights=None, minlength=0, name=None):
    a = _t(x)._data
    w = _t(weights)._data if weights is not None else None
    length = int(np.maximum(np.asarray(a).max(initial=-1) + 1, minlength))
    return Tensor(jnp.bincount(a, weights=w, length=length))


def matrix_exp(x, name=None):
    return apply(jax.scipy.linalg.expm, _t(x))


def lu_unpack(lu_data, lu_pivots, unpack_ludata=True, unpack_pivots=True, name=None):
    """reference: linalg.lu_unpack — (P, L, U) from lu()'s packed output
    (pivots are the 1-based lu_factor convention lu() emits). Batched
    inputs unpack per matrix; the 3-tuple arity is stable — a flag turned
    off yields None in that slot."""
    a = _t(lu_data)._data
    piv = _t(lu_pivots)._data.astype(jnp.int32) - 1
    m, n = a.shape[-2], a.shape[-1]
    k = min(m, n)

    def unpack_one(mat, pv):
        L = jnp.tril(mat, -1)[..., :, :k] + jnp.eye(m, k, dtype=mat.dtype)
        U = jnp.triu(mat)[..., :k, :]
        perm = jnp.arange(m)

        def body(pr, i):
            j = pv[i]
            pi, pj = pr[i], pr[j]
            return pr.at[i].set(pj).at[j].set(pi), None

        perm, _ = jax.lax.scan(body, perm, jnp.arange(pv.shape[-1]))
        P = jnp.eye(m, dtype=mat.dtype)[perm].T
        return P, L, U

    batch = a.shape[:-2]
    if batch:
        flat_a = a.reshape((-1,) + a.shape[-2:])
        flat_p = piv.reshape((-1,) + piv.shape[-1:])
        P, L, U = jax.vmap(unpack_one)(flat_a, flat_p)
        P = P.reshape(batch + P.shape[-2:])
        L = L.reshape(batch + L.shape[-2:])
        U = U.reshape(batch + U.shape[-2:])
    else:
        P, L, U = unpack_one(a, piv)
    return (
        Tensor(P) if unpack_pivots else None,
        Tensor(L) if unpack_ludata else None,
        Tensor(U) if unpack_ludata else None,
    )


def vector_norm(x, p=2.0, axis=None, keepdim=False, name=None):
    """reference: linalg.vector_norm — always the vector norm, any shape.
    axis=None reduces ALL dims (keepdim yields a rank-preserving all-ones
    shape, like the reference)."""

    def fn(a):
        if axis is not None:
            return jnp.linalg.norm(a, ord=p, axis=axis, keepdims=keepdim)
        out = jnp.linalg.norm(a.reshape(-1), ord=p)
        return out.reshape((1,) * a.ndim) if keepdim else out

    return apply(fn, _t(x), name="vector_norm")


def matrix_norm(x, p="fro", axis=(-2, -1), keepdim=False, name=None):
    """reference: linalg.matrix_norm — norm over the trailing matrix dims."""
    return apply(
        lambda a: jnp.linalg.norm(a, ord=p, axis=tuple(axis), keepdims=keepdim),
        _t(x), name="matrix_norm",
    )
