"""einsum (reference: python/paddle/tensor/einsum.py) — direct jnp lowering."""
import jax.numpy as jnp

from ..framework.core import Tensor, apply, to_tensor


def einsum(equation, *operands):
    ts = [o if isinstance(o, Tensor) else to_tensor(o) for o in operands]
    return apply(lambda *arrs: jnp.einsum(equation, *arrs), *ts, name="einsum")
