"""Comparison / logical ops (reference: python/paddle/tensor/logic.py)."""
import jax.numpy as jnp

from ..framework.core import Tensor, to_tensor


def _t(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


def _cmp(fn):
    def op(x, y, name=None):
        a = _t(x)._data
        b = y if isinstance(y, (int, float, bool)) else _t(y)._data
        return Tensor(fn(a, b))

    return op


equal = _cmp(jnp.equal)
not_equal = _cmp(jnp.not_equal)
greater_than = _cmp(jnp.greater)
greater_equal = _cmp(jnp.greater_equal)
less_than = _cmp(jnp.less)
less_equal = _cmp(jnp.less_equal)
logical_and = _cmp(jnp.logical_and)
logical_or = _cmp(jnp.logical_or)
logical_xor = _cmp(jnp.logical_xor)
bitwise_and = _cmp(jnp.bitwise_and)
bitwise_or = _cmp(jnp.bitwise_or)
bitwise_xor = _cmp(jnp.bitwise_xor)


def logical_not(x, name=None):
    return Tensor(jnp.logical_not(_t(x)._data))


def bitwise_not(x, name=None):
    return Tensor(jnp.bitwise_not(_t(x)._data))


def equal_all(x, y, name=None):
    return Tensor(jnp.array_equal(_t(x)._data, _t(y)._data))


def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return Tensor(jnp.allclose(_t(x)._data, _t(y)._data, rtol=rtol, atol=atol, equal_nan=equal_nan))


def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return Tensor(jnp.isclose(_t(x)._data, _t(y)._data, rtol=rtol, atol=atol, equal_nan=equal_nan))


def is_tensor(x):
    return isinstance(x, Tensor)


def is_empty(x, name=None):
    return Tensor(jnp.asarray(_t(x).size == 0))


def isin(x, test_x, assume_unique=False, invert=False, name=None):
    return Tensor(jnp.isin(_t(x)._data, _t(test_x)._data, invert=invert))
