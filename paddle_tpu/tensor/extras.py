"""Op-surface sprint (reference: python/paddle/tensor/{math,manipulation,
creation,linalg}.py long tail). Same contract as math.py: every op is a
jnp lambda under `apply`, so XLA fuses chains of these under jit.
"""
import jax
import jax.numpy as jnp
import numpy as np

from ..framework import random as prandom
from ..framework.core import Tensor, apply, to_tensor


def _t(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


# ---- elementwise / special-function math -----------------------------------

def sgn(x, name=None):
    """Complex-aware sign: x/|x| for complex, jnp.sign for real."""
    def fn(a):
        if jnp.iscomplexobj(a):
            mag = jnp.abs(a)
            return jnp.where(mag == 0, 0, a / jnp.where(mag == 0, 1, mag))
        return jnp.sign(a)

    return apply(fn, _t(x), name="sgn")


def sinc(x, name=None):
    return apply(jnp.sinc, _t(x), name="sinc")


def signbit(x, name=None):
    return apply(jnp.signbit, _t(x), name="signbit")


def ldexp(x, y, name=None):
    return apply(lambda a, b: jnp.ldexp(a, b.astype(jnp.int32)), _t(x), _t(y), name="ldexp")


def frexp(x, name=None):
    return apply(lambda a: jnp.frexp(a), _t(x), name="frexp")


def logcumsumexp(x, axis=None, dtype=None, name=None):
    def fn(a):
        if axis is None:
            a = a.reshape(-1)
            ax = 0
        else:
            ax = axis
        out = jax.lax.associative_scan(jnp.logaddexp, a.astype(jnp.float32), axis=ax)
        return out.astype(dtype or a.dtype) if dtype or not jnp.issubdtype(a.dtype, jnp.floating) else out.astype(a.dtype)

    return apply(fn, _t(x), name="logcumsumexp")


def cumulative_trapezoid(y, x=None, dx=1.0, axis=-1, name=None):
    def core(ya, xa=None):
        n = ya.shape[axis]
        y0 = jax.lax.slice_in_dim(ya, 0, n - 1, axis=axis)
        y1 = jax.lax.slice_in_dim(ya, 1, n, axis=axis)
        if xa is not None:
            x0 = jax.lax.slice_in_dim(xa, 0, n - 1, axis=axis)
            x1 = jax.lax.slice_in_dim(xa, 1, n, axis=axis)
            steps = x1 - x0
        else:
            steps = dx
        return jnp.cumsum((y0 + y1) * 0.5 * steps, axis=axis)

    if x is None:
        return apply(core, _t(y), name="cumulative_trapezoid")
    return apply(core, _t(y), _t(x), name="cumulative_trapezoid")


def gammaln(x, name=None):
    return apply(jax.scipy.special.gammaln, _t(x), name="gammaln")


def gammainc(x, y, name=None):
    return apply(jax.scipy.special.gammainc, _t(x), _t(y), name="gammainc")


def gammaincc(x, y, name=None):
    return apply(jax.scipy.special.gammaincc, _t(x), _t(y), name="gammaincc")


def multigammaln(x, p, name=None):
    return apply(lambda a: jax.scipy.special.multigammaln(a, p), _t(x), name="multigammaln")


def polygamma(x, n, name=None):
    return apply(lambda a: jax.scipy.special.polygamma(n, a), _t(x), name="polygamma")


def i0e(x, name=None):
    return apply(jax.scipy.special.i0e, _t(x), name="i0e")


def i1e(x, name=None):
    return apply(jax.scipy.special.i1e, _t(x), name="i1e")


def nanmedian(x, axis=None, keepdim=False, name=None):
    return apply(lambda a: jnp.nanmedian(a, axis=axis, keepdims=keepdim), _t(x),
                 name="nanmedian")


def isneginf(x, name=None):
    return apply(jnp.isneginf, _t(x), name="isneginf")


def isposinf(x, name=None):
    return apply(jnp.isposinf, _t(x), name="isposinf")


def isreal(x, name=None):
    return apply(jnp.isreal, _t(x), name="isreal")


def is_complex(x):
    return jnp.issubdtype(_t(x)._data.dtype, jnp.complexfloating)


def is_floating_point(x):
    return jnp.issubdtype(_t(x)._data.dtype, jnp.floating)


def is_integer(x):
    return jnp.issubdtype(_t(x)._data.dtype, jnp.integer)


# ---- complex construction ---------------------------------------------------

def polar(abs, angle, name=None):  # noqa: A002 — paddle signature
    return apply(lambda r, t: (r * jnp.exp(1j * t.astype(jnp.complex64))).astype(jnp.complex64),
                 _t(abs), _t(angle), name="polar")


def as_complex(x, name=None):
    return apply(lambda a: jax.lax.complex(a[..., 0], a[..., 1]), _t(x), name="as_complex")


def as_real(x, name=None):
    return apply(lambda a: jnp.stack([jnp.real(a), jnp.imag(a)], axis=-1), _t(x),
                 name="as_real")


# ---- creation ---------------------------------------------------------------

def logspace(start, stop, num, base=10.0, dtype=None, name=None):
    return Tensor(jnp.logspace(float(start), float(stop), int(num), base=float(base),
                               dtype=dtype or jnp.float32))


def vander(x, n=None, increasing=False, name=None):
    return apply(lambda a: jnp.vander(a, N=n, increasing=increasing), _t(x), name="vander")


def poisson(x, name=None):
    """Sample Poisson(lam=x) elementwise (reference: paddle.poisson)."""
    key = prandom.next_key()
    return apply(lambda lam: jax.random.poisson(key, lam, lam.shape).astype(lam.dtype),
                 _t(x), name="poisson")


# ---- manipulation -----------------------------------------------------------

def cat(x, axis=0, name=None):
    from .manipulation import concat

    return concat(x, axis=axis)


def cast(x, dtype):
    return _t(x).astype(dtype)


def permute(x, *perm):
    from .manipulation import transpose

    if len(perm) == 1 and isinstance(perm[0], (list, tuple)):
        perm = tuple(perm[0])
    return transpose(_t(x), perm)


def column_stack(x, name=None):
    return apply(lambda *arrs: jnp.column_stack(arrs), *[_t(a) for a in x],
                 name="column_stack")


def fliplr(x, name=None):
    return apply(jnp.fliplr, _t(x), name="fliplr")


def flipud(x, name=None):
    return apply(jnp.flipud, _t(x), name="flipud")


def tensor_split(x, num_or_indices, axis=0, name=None):
    def fn(a):
        if isinstance(num_or_indices, int):
            return tuple(jnp.array_split(a, num_or_indices, axis=axis))
        return tuple(jnp.split(a, list(num_or_indices), axis=axis))

    return list(apply(fn, _t(x), name="tensor_split"))


def unflatten(x, axis, shape, name=None):
    def fn(a):
        ax = axis % a.ndim
        new = list(a.shape[:ax]) + list(shape) + list(a.shape[ax + 1:])
        # a single -1 in shape is inferred
        if -1 in shape:
            known = int(np.prod([s for s in shape if s != -1]))
            new[new.index(-1)] = a.shape[ax] // known
        return a.reshape(new)

    return apply(fn, _t(x), name="unflatten")


def unfold(x, axis, size, step, name=None):
    """Sliding windows along `axis`: appends a trailing window dim of
    `size` (reference: paddle.unfold / Tensor.unfold)."""
    def fn(a):
        ax = axis % a.ndim
        n = (a.shape[ax] - size) // step + 1
        idx = jnp.arange(n)[:, None] * step + jnp.arange(size)[None]
        out = jnp.take(a, idx, axis=ax)  # [..., n, size, ...] at ax
        return jnp.moveaxis(out, ax + 1, -1)

    return apply(fn, _t(x), name="unfold")


def unstack(x, axis=0, num=None, name=None):
    from .manipulation import unbind

    return unbind(_t(x), axis=axis)


def diagflat(x, offset=0, name=None):
    return apply(lambda a: jnp.diagflat(a, k=offset), _t(x), name="diagflat")


def block_diag(inputs, name=None):
    """reference: paddle.block_diag — block-diagonal matrix from a list of
    2-D (or promotable) tensors."""
    import jax.scipy.linalg as jsl

    ts = [_t(x) for x in inputs]
    return apply(lambda *arrs: jsl.block_diag(*[jnp.atleast_2d(a) for a in arrs]),
                 *ts, name="block_diag")


def diagonal(x, offset=0, axis1=0, axis2=1, name=None):
    return apply(lambda a: jnp.diagonal(a, offset=offset, axis1=axis1, axis2=axis2),
                 _t(x), name="diagonal")


def select_scatter(x, values, axis, index, name=None):
    def fn(a, v):
        idx = [slice(None)] * a.ndim
        idx[axis % a.ndim] = index
        return a.at[tuple(idx)].set(v.astype(a.dtype))

    return apply(fn, _t(x), _t(values), name="select_scatter")


def numel(x, name=None):
    return Tensor(jnp.asarray(int(np.prod(_t(x).shape)), jnp.int32))


def rank(x):
    return Tensor(jnp.asarray(len(_t(x).shape), jnp.int32))


def tolist(x):
    return np.asarray(_t(x)._data).tolist()


# ---- linalg-ish -------------------------------------------------------------

def baddbmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    return apply(
        lambda i, a, b: beta * i + alpha * jnp.matmul(a, b),
        _t(input), _t(x), _t(y), name="baddbmm",
    )


def cdist(x, y, p=2.0, compute_mode="use_mm_for_euclid_dist_if_necessary", name=None):
    def fn(a, b):
        diff = a[..., :, None, :] - b[..., None, :, :]
        if p == 2.0:
            return jnp.sqrt(jnp.maximum(jnp.sum(diff * diff, axis=-1), 0.0))
        if p == 0:
            return jnp.sum((diff != 0).astype(a.dtype), axis=-1)
        if jnp.isinf(p):
            return jnp.max(jnp.abs(diff), axis=-1)
        return jnp.sum(jnp.abs(diff) ** p, axis=-1) ** (1.0 / p)

    return apply(fn, _t(x), _t(y), name="cdist")


def pdist(x, p=2.0, name=None):
    def fn(a):
        n = a.shape[0]
        iu, ju = np.triu_indices(n, k=1)
        diff = a[iu] - a[ju]
        if p == 2.0:
            return jnp.sqrt(jnp.maximum(jnp.sum(diff * diff, axis=-1), 0.0))
        if jnp.isinf(p):
            return jnp.max(jnp.abs(diff), axis=-1)
        return jnp.sum(jnp.abs(diff) ** p, axis=-1) ** (1.0 / p)

    return apply(fn, _t(x), name="pdist")


def histogramdd(x, bins=10, ranges=None, density=False, weights=None, name=None):
    xa = _t(x)._data
    wa = _t(weights)._data if weights is not None else None
    hist, edges = jnp.histogramdd(xa, bins=bins, range=ranges, density=density,
                                  weights=wa)
    return Tensor(hist), [Tensor(e) for e in edges]


def combinations(x, r=2, with_replacement=False, name=None):
    """All r-combinations of a 1-D tensor's elements, shape [C, r]."""
    import itertools

    n = _t(x).shape[0]
    pick = (itertools.combinations_with_replacement if with_replacement
            else itertools.combinations)
    idx = np.asarray(list(pick(range(n), r)), np.int32).reshape(-1, r)
    return apply(lambda a: jnp.take(a, jnp.asarray(idx), axis=0), _t(x),
                 name="combinations")


# ---- bitwise ----------------------------------------------------------------

def bitwise_invert(x, out=None, name=None):
    from .logic import bitwise_not

    return bitwise_not(_t(x))


def bitwise_left_shift(x, y, is_arithmetic=True, out=None, name=None):
    return apply(jnp.left_shift, _t(x), _t(y), name="bitwise_left_shift")


def bitwise_right_shift(x, y, is_arithmetic=True, out=None, name=None):
    def fn(a, b):
        if is_arithmetic:
            return jnp.right_shift(a, b)
        # logical shift: operate on the unsigned view, cast back
        ui = jnp.dtype(a.dtype).name.replace("int", "uint")
        return jax.lax.shift_right_logical(a.view(ui), b.astype(ui).view(ui)).view(a.dtype)

    return apply(fn, _t(x), _t(y), name="bitwise_right_shift")
