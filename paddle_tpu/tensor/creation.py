"""Creation ops (reference: python/paddle/tensor/creation.py)."""
import jax.numpy as jnp
import numpy as np

from ..framework import dtype as dtypes
from ..framework import random as prandom
from ..framework.core import Tensor, apply, to_tensor  # noqa: F401


def _dt(dtype, default=None):
    d = dtypes.convert_dtype(dtype)
    return d if d is not None else (default or dtypes.get_default_dtype())


def _shape(shape):
    if isinstance(shape, Tensor):
        return tuple(int(s) for s in shape.numpy())
    if isinstance(shape, (int, np.integer)):
        return (int(shape),)
    return tuple(int(s._data) if isinstance(s, Tensor) else int(s) for s in shape)


def zeros(shape, dtype=None, name=None):
    return Tensor(jnp.zeros(_shape(shape), _dt(dtype)))


def ones(shape, dtype=None, name=None):
    return Tensor(jnp.ones(_shape(shape), _dt(dtype)))


def full(shape, fill_value, dtype=None, name=None):
    if isinstance(fill_value, Tensor):
        fill_value = fill_value.item()
    if dtype is None:
        dtype = dtypes.get_default_dtype() if isinstance(fill_value, float) else None
    return Tensor(jnp.full(_shape(shape), fill_value, _dt(dtype) if dtype is not None else None))


def empty(shape, dtype=None, name=None):
    return zeros(shape, dtype)


def zeros_like(x, dtype=None, name=None):
    x = to_tensor(x)
    return Tensor(jnp.zeros(x._data.shape, _dt(dtype, np.dtype(x.dtype))))


def ones_like(x, dtype=None, name=None):
    x = to_tensor(x)
    return Tensor(jnp.ones(x._data.shape, _dt(dtype, np.dtype(x.dtype))))


def full_like(x, fill_value, dtype=None, name=None):
    x = to_tensor(x)
    return Tensor(jnp.full(x._data.shape, fill_value, _dt(dtype, np.dtype(x.dtype))))


def empty_like(x, dtype=None, name=None):
    return zeros_like(x, dtype)


def arange(start=0, end=None, step=1, dtype=None, name=None):
    def _v(x):
        return x.item() if isinstance(x, Tensor) else x

    start, end, step = _v(start), _v(end), _v(step)
    if end is None:
        start, end = 0, start
    if dtype is None:
        dtype = (
            dtypes.get_default_dtype()
            if any(isinstance(v, float) for v in (start, end, step))
            else np.int64
        )
    return Tensor(jnp.arange(start, end, step, _dt(dtype)))


def linspace(start, stop, num, dtype=None, name=None):
    def _v(x):
        return x.item() if isinstance(x, Tensor) else x

    return Tensor(jnp.linspace(_v(start), _v(stop), int(_v(num)), dtype=_dt(dtype)))


def eye(num_rows, num_columns=None, dtype=None, name=None):
    return Tensor(jnp.eye(num_rows, num_columns, dtype=_dt(dtype)))


def diag(x, offset=0, padding_value=0, name=None):
    x = to_tensor(x)
    if x.ndim == 1 and padding_value != 0:

        def fn(a):
            n = a.shape[0] + abs(offset)
            out = jnp.full((n, n), padding_value, a.dtype)
            idx = jnp.arange(a.shape[0])
            r, c = (idx, idx + offset) if offset >= 0 else (idx - offset, idx)
            return out.at[r, c].set(a)

        return apply(fn, x, name="diag")
    return apply(lambda a: jnp.diag(a, k=offset), x, name="diag")


def diag_embed(x, offset=0, dim1=-2, dim2=-1):
    x = to_tensor(x)
    return apply(lambda a: _diag_embed(a, offset, dim1, dim2), x, name="diag_embed")


def _diag_embed(a, offset, dim1, dim2):
    n = a.shape[-1] + abs(offset)
    out = jnp.zeros(a.shape[:-1] + (n, n), a.dtype)
    idx = jnp.arange(a.shape[-1])
    r, c = (idx, idx + offset) if offset >= 0 else (idx - offset, idx)
    out = out.at[..., r, c].set(a)
    if (dim1, dim2) not in ((-2, -1), (a.ndim - 1, a.ndim)):
        out = jnp.moveaxis(out, (-2, -1), (dim1, dim2))
    return out


def tril(x, diagonal=0, name=None):
    return apply(lambda a: jnp.tril(a, k=diagonal), to_tensor(x), name="tril")


def triu(x, diagonal=0, name=None):
    return apply(lambda a: jnp.triu(a, k=diagonal), to_tensor(x), name="triu")


def meshgrid(*args, **kwargs):
    ts = [to_tensor(a) for a in (args[0] if len(args) == 1 and isinstance(args[0], (list, tuple)) else args)]
    outs = jnp.meshgrid(*[t._data for t in ts], indexing="ij")
    return [Tensor(o) for o in outs]


def assign(x, output=None):
    t = to_tensor(x)
    if output is not None:
        output.set_value(t)
        return output
    return t.clone() if not t.stop_gradient else Tensor(t._data)


def clone(x):
    return to_tensor(x).clone()


def complex(real, imag, name=None):
    return apply(lambda r, i: r + 1j * i.astype(jnp.result_type(i.dtype, jnp.complex64)), real, imag)


def tril_indices(row, col, offset=0, dtype="int64"):
    r, c = np.tril_indices(row, offset, col)
    return Tensor(jnp.asarray(np.stack([r, c]), _dt(dtype)))


def triu_indices(row, col=None, offset=0, dtype="int64"):
    r, c = np.triu_indices(row, offset, col if col is not None else row)
    return Tensor(jnp.asarray(np.stack([r, c]), _dt(dtype)))


# -- random creation (python/paddle/tensor/random.py) -----------------------
import jax


def rand(shape, dtype=None, name=None):
    return Tensor(jax.random.uniform(prandom.next_key(), _shape(shape), _dt(dtype)))


uniform_random = rand


def randn(shape, dtype=None, name=None):
    return Tensor(jax.random.normal(prandom.next_key(), _shape(shape), _dt(dtype)))


def normal(mean=0.0, std=1.0, shape=None, name=None):
    if isinstance(mean, Tensor) or isinstance(std, Tensor):
        m = to_tensor(mean)._data
        s = to_tensor(std)._data
        shp = jnp.broadcast_shapes(np.shape(m), np.shape(s))
        return Tensor(jax.random.normal(prandom.next_key(), shp, dtypes.get_default_dtype()) * s + m)
    shp = _shape(shape) if shape is not None else ()
    return Tensor(jax.random.normal(prandom.next_key(), shp, dtypes.get_default_dtype()) * std + mean)


def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0, name=None):
    key = jax.random.PRNGKey(seed) if seed else prandom.next_key()
    return Tensor(jax.random.uniform(key, _shape(shape), _dt(dtype), minval=min, maxval=max))


def randint(low=0, high=None, shape=(1,), dtype="int64", name=None):
    if high is None:
        low, high = 0, low
    return Tensor(jax.random.randint(prandom.next_key(), _shape(shape), low, high, _dt(dtype)))


def randint_like(x, low=0, high=None, dtype=None):
    x = to_tensor(x)
    if high is None:
        low, high = 0, low
    return Tensor(
        jax.random.randint(prandom.next_key(), tuple(x._data.shape), low, high, _dt(dtype, np.dtype(x.dtype)))
    )


def randperm(n, dtype="int64", name=None):
    return Tensor(jax.random.permutation(prandom.next_key(), n).astype(_dt(dtype)))


def multinomial(x, num_samples=1, replacement=False, name=None):
    x = to_tensor(x)
    logits = jnp.log(jnp.maximum(x._data, 1e-30))
    if replacement:
        out = jax.random.categorical(prandom.next_key(), logits, axis=-1, shape=(num_samples,) + x._data.shape[:-1])
        out = jnp.moveaxis(out, 0, -1)
    else:
        k = prandom.next_key()
        g = jax.random.gumbel(k, x._data.shape)
        out = jax.lax.top_k(logits + g, num_samples)[1]
    return Tensor(out.astype(jnp.int64))


def bernoulli(x, name=None):
    x = to_tensor(x)
    return Tensor(
        (jax.random.uniform(prandom.next_key(), tuple(x._data.shape)) < x._data).astype(x.dtype)
    )
