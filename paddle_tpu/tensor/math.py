"""Elementwise & reduction math (reference: python/paddle/tensor/math.py).

Every op is a jnp lambda under `apply`, so XLA fuses chains of these into
single kernels when the surrounding step is jit-compiled.
"""
import jax
import jax.numpy as jnp
import numpy as np

from ..framework import dtype as dtypes
from ..framework.core import Tensor, apply, to_tensor


def _t(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


def _binary(fn, name):
    def op(x, y, name_=None, **kw):
        if isinstance(y, (int, float, bool)) and not isinstance(y, Tensor):
            return apply(lambda a: fn(a, y), _t(x), name=name)
        if isinstance(x, (int, float, bool)) and not isinstance(x, Tensor):
            return apply(lambda b: fn(x, b), _t(y), name=name)
        return apply(fn, _t(x), _t(y), name=name)

    op.__name__ = name
    return op


def _unary(fn, name):
    def op(x, name_=None, **kw):
        return apply(lambda a: fn(a, **kw) if kw else fn(a), _t(x), name=name)

    op.__name__ = name
    return op


add = _binary(jnp.add, "add")
subtract = _binary(jnp.subtract, "subtract")
multiply = _binary(jnp.multiply, "multiply")
divide = _binary(jnp.divide, "divide")
floor_divide = _binary(jnp.floor_divide, "floor_divide")
remainder = _binary(jnp.remainder, "remainder")
mod = remainder
floor_mod = remainder
pow = _binary(jnp.power, "pow")
maximum = _binary(jnp.maximum, "maximum")
minimum = _binary(jnp.minimum, "minimum")
fmax = _binary(jnp.fmax, "fmax")
fmin = _binary(jnp.fmin, "fmin")
atan2 = _binary(jnp.arctan2, "atan2")
hypot = _binary(jnp.hypot, "hypot")
logaddexp = _binary(jnp.logaddexp, "logaddexp")
nextafter = _binary(jnp.nextafter, "nextafter")
copysign = _binary(jnp.copysign, "copysign")
heaviside = _binary(jnp.heaviside, "heaviside")
gcd = _binary(jnp.gcd, "gcd")
lcm = _binary(jnp.lcm, "lcm")

exp = _unary(jnp.exp, "exp")
expm1 = _unary(jnp.expm1, "expm1")
log = _unary(jnp.log, "log")
log2 = _unary(jnp.log2, "log2")
log10 = _unary(jnp.log10, "log10")
log1p = _unary(jnp.log1p, "log1p")
sqrt = _unary(jnp.sqrt, "sqrt")
rsqrt = _unary(jax.lax.rsqrt, "rsqrt")
square = _unary(jnp.square, "square")
abs = _unary(jnp.abs, "abs")
neg = _unary(jnp.negative, "neg")
sign = _unary(jnp.sign, "sign")
floor = _unary(jnp.floor, "floor")
ceil = _unary(jnp.ceil, "ceil")
round = _unary(jnp.round, "round")
trunc = _unary(jnp.trunc, "trunc")
frac = _unary(lambda x: x - jnp.trunc(x), "frac")
reciprocal = _unary(jnp.reciprocal, "reciprocal")
sin = _unary(jnp.sin, "sin")
cos = _unary(jnp.cos, "cos")
tan = _unary(jnp.tan, "tan")
asin = _unary(jnp.arcsin, "asin")
acos = _unary(jnp.arccos, "acos")
atan = _unary(jnp.arctan, "atan")
sinh = _unary(jnp.sinh, "sinh")
cosh = _unary(jnp.cosh, "cosh")
tanh = _unary(jnp.tanh, "tanh")
asinh = _unary(jnp.arcsinh, "asinh")
acosh = _unary(jnp.arccosh, "acosh")
atanh = _unary(jnp.arctanh, "atanh")
erf = _unary(jax.scipy.special.erf, "erf")
erfinv = _unary(jax.scipy.special.erfinv, "erfinv")
sigmoid = _unary(jax.nn.sigmoid, "sigmoid")
logit = _unary(jax.scipy.special.logit, "logit")
digamma = _unary(jax.scipy.special.digamma, "digamma")
lgamma = _unary(jax.scipy.special.gammaln, "lgamma")
gamma = _unary(lambda x: jnp.exp(jax.scipy.special.gammaln(x)) * jnp.sign(x), "gamma")
i0 = _unary(jax.scipy.special.i0, "i0")
i1 = _unary(jax.scipy.special.i1, "i1")
angle = _unary(jnp.angle, "angle")
conj = _unary(jnp.conj, "conj")
real = _unary(jnp.real, "real")
imag = _unary(jnp.imag, "imag")
deg2rad = _unary(jnp.deg2rad, "deg2rad")
rad2deg = _unary(jnp.rad2deg, "rad2deg")
exponential_ = None  # in-place random not supported; use creation.uniform


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    def fn(a):
        out = a * scale + bias if bias_after_scale else (a + bias) * scale
        return out

    return apply(fn, _t(x), name="scale")


def clip(x, min=None, max=None, name=None):
    mn = min.item() if isinstance(min, Tensor) else min
    mx = max.item() if isinstance(max, Tensor) else max
    return apply(lambda a: jnp.clip(a, mn, mx), _t(x), name="clip")


def lerp(x, y, weight, name=None):
    if isinstance(weight, Tensor):
        return apply(lambda a, b, w: a + w * (b - a), _t(x), _t(y), weight, name="lerp")
    return apply(lambda a, b: a + weight * (b - a), _t(x), _t(y), name="lerp")


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    return apply(lambda i, a, b: beta * i + alpha * (a @ b), _t(input), _t(x), _t(y), name="addmm")


def multiplex(inputs, index, name=None):
    stacked = jnp.stack([_t(i)._data for i in inputs], 1)
    idx = _t(index)._data.reshape(-1)
    # (slice/None tuple instead of star-unpacking in the subscript: that
    # syntax needs py3.11, and the package must import on 3.10)
    expand = (slice(None), None) + (None,) * (stacked.ndim - 2)
    return Tensor(jnp.take_along_axis(stacked, idx[expand], axis=1).squeeze(1))


# -- reductions --------------------------------------------------------------
def _axis(axis):
    if axis is None:
        return None
    if isinstance(axis, Tensor):
        a = axis.numpy()
        return tuple(int(v) for v in np.atleast_1d(a))
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


def sum(x, axis=None, dtype=None, keepdim=False, name=None):
    x = _t(x)
    dt = dtypes.convert_dtype(dtype)
    if dt is None and np.issubdtype(np.dtype(x.dtype), np.bool_):
        dt = np.dtype(np.int64)
    return apply(lambda a: jnp.sum(a, axis=_axis(axis), dtype=dt, keepdims=keepdim), x, name="sum")


def mean(x, axis=None, keepdim=False, name=None):
    return apply(lambda a: jnp.mean(a, axis=_axis(axis), keepdims=keepdim), _t(x), name="mean")


def prod(x, axis=None, keepdim=False, dtype=None, name=None):
    return apply(
        lambda a: jnp.prod(a, axis=_axis(axis), dtype=dtypes.convert_dtype(dtype), keepdims=keepdim),
        _t(x),
        name="prod",
    )


def max(x, axis=None, keepdim=False, name=None):
    return apply(lambda a: jnp.max(a, axis=_axis(axis), keepdims=keepdim), _t(x), name="max")


def min(x, axis=None, keepdim=False, name=None):
    return apply(lambda a: jnp.min(a, axis=_axis(axis), keepdims=keepdim), _t(x), name="min")


def amax(x, axis=None, keepdim=False, name=None):
    return max(x, axis, keepdim)


def amin(x, axis=None, keepdim=False, name=None):
    return min(x, axis, keepdim)


def logsumexp(x, axis=None, keepdim=False, name=None):
    return apply(
        lambda a: jax.scipy.special.logsumexp(a, axis=_axis(axis), keepdims=keepdim), _t(x), name="logsumexp"
    )


def cumsum(x, axis=None, dtype=None, name=None):
    x = _t(x)
    if axis is None:
        return apply(lambda a: jnp.cumsum(a.reshape(-1), dtype=dtypes.convert_dtype(dtype)), x)
    return apply(lambda a: jnp.cumsum(a, axis=int(axis), dtype=dtypes.convert_dtype(dtype)), x)


def cumprod(x, dim=None, dtype=None, name=None):
    return apply(lambda a: jnp.cumprod(a, axis=dim, dtype=dtypes.convert_dtype(dtype)), _t(x))


def cummax(x, axis=None, dtype="int64", name=None):
    x = _t(x)
    ax = 0 if axis is None else int(axis)
    a = x._data.reshape(-1) if axis is None else x._data
    vals = jax.lax.associative_scan(jnp.maximum, a, axis=ax)
    idx_src = jnp.arange(a.shape[ax]).reshape([-1 if i == ax % a.ndim else 1 for i in range(a.ndim)])
    idx = jnp.where(a == vals, jnp.broadcast_to(idx_src, a.shape), 0)
    idx = jax.lax.associative_scan(jnp.maximum, idx, axis=ax)
    values = apply(lambda t: jax.lax.associative_scan(jnp.maximum, t.reshape(-1) if axis is None else t, axis=ax), x)
    return values, Tensor(idx.astype(dtypes.convert_dtype(dtype)))


def cummin(x, axis=None, dtype="int64", name=None):
    neg, idx = cummax(-_t(x), axis, dtype)
    return -neg, idx


def nanmean(x, axis=None, keepdim=False, name=None):
    return apply(lambda a: jnp.nanmean(a, axis=_axis(axis), keepdims=keepdim), _t(x))


def nansum(x, axis=None, dtype=None, keepdim=False, name=None):
    return apply(
        lambda a: jnp.nansum(a, axis=_axis(axis), dtype=dtypes.convert_dtype(dtype), keepdims=keepdim), _t(x)
    )


def count_nonzero(x, axis=None, keepdim=False, name=None):
    return Tensor(jnp.count_nonzero(_t(x)._data, axis=_axis(axis), keepdims=keepdim).astype(jnp.int64))


def all(x, axis=None, keepdim=False, name=None):
    return Tensor(jnp.all(_t(x)._data, axis=_axis(axis), keepdims=keepdim))


def any(x, axis=None, keepdim=False, name=None):
    return Tensor(jnp.any(_t(x)._data, axis=_axis(axis), keepdims=keepdim))


def broadcast_shape(a, b):
    return list(jnp.broadcast_shapes(tuple(a), tuple(b)))


def increment(x, value=1.0, name=None):
    x.set_value(Tensor(x._data + value))
    return x


def isfinite(x, name=None):
    return Tensor(jnp.isfinite(_t(x)._data))


def isinf(x, name=None):
    return Tensor(jnp.isinf(_t(x)._data))


def isnan(x, name=None):
    return Tensor(jnp.isnan(_t(x)._data))


def nan_to_num(x, nan=0.0, posinf=None, neginf=None, name=None):
    return apply(lambda a: jnp.nan_to_num(a, nan=nan, posinf=posinf, neginf=neginf), _t(x))


def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    return apply(lambda a: scale_b * jnp.tanh(scale_a * a), _t(x))


def inner(x, y, name=None):
    return apply(jnp.inner, _t(x), _t(y), name="inner")


def outer(x, y, name=None):
    return apply(lambda a, b: jnp.outer(a, b), _t(x), _t(y), name="outer")


def kron(x, y, name=None):
    return apply(jnp.kron, _t(x), _t(y), name="kron")


def diff(x, n=1, axis=-1, prepend=None, append=None, name=None):
    args = [_t(x)]
    kw = {}
    fn = lambda a, *extra: jnp.diff(
        a,
        n=n,
        axis=axis,
        prepend=extra[0] if prepend is not None else None,
        append=extra[-1] if append is not None else None,
    )
    if prepend is not None:
        args.append(_t(prepend))
    if append is not None:
        args.append(_t(append))
    return apply(fn, *args, name="diff")


def trace(x, offset=0, axis1=0, axis2=1, name=None):
    return apply(lambda a: jnp.trace(a, offset=offset, axis1=axis1, axis2=axis2), _t(x), name="trace")


def cross(x, y, axis=9, name=None):
    ax = axis if axis != 9 else (9 if 9 < _t(x).ndim else -1)
    if ax == 9:
        ax = next(i for i, s in enumerate(_t(x).shape) if s == 3)
    return apply(lambda a, b: jnp.cross(a, b, axis=ax), _t(x), _t(y), name="cross")


def dot(x, y, name=None):
    def fn(a, b):
        return jnp.sum(a * b, axis=-1)

    return apply(fn, _t(x), _t(y), name="dot")


def log_normalize(x, axis=-1):
    return apply(lambda a: a - jax.scipy.special.logsumexp(a, axis=axis, keepdims=True), _t(x))


def renorm(x, p, axis, max_norm):
    def fn(a):
        dims = tuple(i for i in range(a.ndim) if i != axis % a.ndim)
        norms = jnp.sum(jnp.abs(a) ** p, axis=dims, keepdims=True) ** (1.0 / p)
        factor = jnp.where(norms > max_norm, max_norm / jnp.maximum(norms, 1e-12), 1.0)
        return a * factor

    return apply(fn, _t(x), name="renorm")


def take(x, index, mode="raise", name=None):
    x, index = _t(x), _t(index)
    idx = index._data
    if mode == "wrap":
        idx = idx % x.size
    elif mode == "clip":
        idx = jnp.clip(idx, -x.size, x.size - 1)
    return apply(lambda a: a.reshape(-1)[idx], x, name="take")


def trapezoid(y, x=None, dx=None, axis=-1, name=None):
    y = _t(y)
    if x is not None:
        return apply(lambda a, b: jax.scipy.integrate.trapezoid(a, b, axis=axis), y, _t(x))
    return apply(lambda a: jax.scipy.integrate.trapezoid(a, dx=dx or 1.0, axis=axis), y)


def median(x, axis=None, keepdim=False, mode="avg", name=None):
    x = _t(x)
    if mode == "avg":
        return apply(lambda a: jnp.median(a, axis=_axis(axis), keepdims=keepdim), x)
    ax = _axis(axis)
    out = jnp.quantile(x._data, 0.5, axis=ax, keepdims=keepdim, method="lower")
    idx = jnp.argmax((jnp.sort(x._data, axis=ax if ax is not None else None) == out), axis=ax)
    return apply(lambda a: jnp.quantile(a, 0.5, axis=ax, keepdims=keepdim, method="lower"), x), Tensor(idx)


def quantile(x, q, axis=None, keepdim=False, interpolation="linear", name=None):
    qv = q.numpy() if isinstance(q, Tensor) else q
    return apply(
        lambda a: jnp.quantile(a, jnp.asarray(qv), axis=_axis(axis), keepdims=keepdim, method=interpolation),
        _t(x),
    )


def nanquantile(x, q, axis=None, keepdim=False, name=None):
    qv = q.numpy() if isinstance(q, Tensor) else q
    return apply(lambda a: jnp.nanquantile(a, jnp.asarray(qv), axis=_axis(axis), keepdims=keepdim), _t(x))


def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    return apply(
        lambda a: jnp.std(a, axis=_axis(axis), ddof=1 if unbiased else 0, keepdims=keepdim), _t(x), name="std"
    )


def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    return apply(
        lambda a: jnp.var(a, axis=_axis(axis), ddof=1 if unbiased else 0, keepdims=keepdim), _t(x), name="var"
    )
