"""hapi.Model (reference: python/paddle/hapi/model.py — Model.fit/evaluate/
predict over callbacks).

The train step runs through jit_api.TrainStep: one compiled XLA program per
(shapes) signature, the dygraph loop only feeds batches — this is where the
reference's per-op dispatch cost disappears (SURVEY.md §3.1).
"""
import numpy as np

from ..framework.core import Tensor, to_tensor
from ..io import DataLoader
from ..jit_api import TrainStep
from ..observability import goodput as _goodput
from ..observability import tracing as _tracing
from .callbacks import CallbackList, ProgBarLogger


class Model:
    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._inputs = inputs
        self._labels = labels
        self._loss = None
        self._optimizer = None
        self._metrics = []
        self._train_step = None
        self.stop_training = False

    def prepare(self, optimizer=None, loss=None, metrics=None, amp_configs=None):
        self._optimizer = optimizer
        self._loss = loss
        self._metrics = metrics if isinstance(metrics, (list, tuple)) else ([metrics] if metrics else [])
        self._train_step = None
        return self

    # -- single step APIs ---------------------------------------------------
    def train_batch(self, inputs, labels=None, update=True):
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        labels = labels if isinstance(labels, (list, tuple)) else ([labels] if labels is not None else [])
        if self._train_step is None:
            self._train_step = TrainStep(
                self.network, self._wrapped_loss, self._optimizer, n_labels=max(len(labels), 1),
                accumulate_steps=getattr(self, "_accumulate_grad_batches", 1),
            )
        loss = self._train_step(*inputs, *labels)
        metrics = self._eval_metrics_on_batch(inputs, labels)
        return ([float(loss.numpy())], metrics) if metrics else [float(loss.numpy())]

    @property
    def _wrapped_loss(self):
        loss_fn = self._loss

        def fn(*args):
            out = loss_fn(*args)
            if isinstance(out, (list, tuple)):
                total = out[0]
                for o in out[1:]:
                    total = total + o
                return total.mean() if total.ndim > 0 else total
            return out.mean() if out.ndim > 0 else out

        return fn

    def _eval_metrics_on_batch(self, inputs, labels):
        if not self._metrics:
            return None
        import paddle_tpu as ptpu

        with ptpu.no_grad():
            self.network.eval()
            out = self.network(*inputs)
            self.network.train()
        res = []
        for m in self._metrics:
            c = m.compute(out, *labels)
            res.append(m.update(c))
        return res

    def eval_batch(self, inputs, labels=None):
        import paddle_tpu as ptpu

        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        labels = labels if isinstance(labels, (list, tuple)) else ([labels] if labels is not None else [])
        with ptpu.no_grad():
            out = self.network(*inputs)
            outs = out if isinstance(out, (list, tuple)) else [out]
            loss = self._wrapped_loss(*outs, *[to_tensor(l) for l in labels])
        metrics = []
        for m in self._metrics:
            c = m.compute(out, *labels)
            metrics.append(m.update(c))
        return ([float(loss.numpy())], metrics) if metrics else [float(loss.numpy())]

    def predict_batch(self, inputs):
        import paddle_tpu as ptpu

        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        with ptpu.no_grad():
            self.network.eval()
            out = self.network(*inputs)
            self.network.train()
        return [o.numpy() for o in (out if isinstance(out, (list, tuple)) else [out])]

    # -- loops --------------------------------------------------------------
    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1, eval_freq=1,
            log_freq=10, save_dir=None, save_freq=1, verbose=2, drop_last=False, shuffle=True,
            num_workers=0, callbacks=None, accumulate_grad_batches=1, num_iters=None):
        train_loader = train_data if isinstance(train_data, DataLoader) else DataLoader(
            train_data, batch_size=batch_size, shuffle=shuffle, drop_last=drop_last, num_workers=num_workers
        )
        eval_loader = None
        if eval_data is not None:
            eval_loader = eval_data if isinstance(eval_data, DataLoader) else DataLoader(
                eval_data, batch_size=batch_size, num_workers=num_workers
            )
        acc = int(accumulate_grad_batches)
        if acc != getattr(self, "_accumulate_grad_batches", 1):
            self._accumulate_grad_batches = acc
            self._train_step = None  # rebuild the compiled step with the scan
        cbks = CallbackList(callbacks, model=self, verbose=verbose)
        try:
            steps = len(train_loader)
        except TypeError:
            steps = None
        cbks.on_begin("train", {"epochs": epochs, "steps": steps, "verbose": verbose,
                                "metrics": ["loss"] + self._metric_names()})
        for epoch in range(epochs):
            if self.stop_training:
                break
            for m in self._metrics:
                m.reset()
            cbks.on_epoch_begin(epoch)
            logs = {}
            # manual iteration so loader stalls are measured as data_wait
            # badput (the train_batch step itself is spanned inside
            # TrainStep) — telemetry disabled, both hooks are no-ops
            data_iter = iter(train_loader)
            step = 0
            while num_iters is None or step < num_iters:
                with _tracing.span("data.wait"), \
                        _goodput.account("data_wait"):
                    try:
                        batch = next(data_iter)
                    except StopIteration:
                        break
                cbks.on_batch_begin("train", step, logs)
                ins, labs = self._split_batch(batch)
                res = self.train_batch(ins, labs)
                logs = self._to_logs(res)
                cbks.on_batch_end("train", step, logs)
                step += 1
            if eval_loader is not None and (epoch + 1) % eval_freq == 0:
                eval_res = self.evaluate(eval_loader, verbose=0)
                logs.update({f"eval_{k}": v for k, v in eval_res.items()})
            cbks.on_epoch_end(epoch, logs)
            if save_dir and (epoch + 1) % save_freq == 0:
                self.save(f"{save_dir}/{epoch}")
        cbks.on_end("train", logs)
        if save_dir:
            self.save(f"{save_dir}/final")
        return self

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2, num_workers=0,
                 callbacks=None, num_iters=None):
        loader = eval_data if isinstance(eval_data, DataLoader) else DataLoader(
            eval_data, batch_size=batch_size, num_workers=num_workers
        )
        for m in self._metrics:
            m.reset()
        losses = []
        for step, batch in enumerate(loader):
            if num_iters is not None and step >= num_iters:
                break
            ins, labs = self._split_batch(batch)
            res = self.eval_batch(ins, labs)
            losses.append(res[0][0] if isinstance(res, tuple) else res[0])
        out = {"loss": [float(np.mean(losses))] if losses else [0.0]}
        for m in self._metrics:
            name = m.name()
            acc = m.accumulate()
            if isinstance(name, list):
                for n, a in zip(name, acc if isinstance(acc, list) else [acc]):
                    out[n] = a
            else:
                out[name] = acc
        return out

    def predict(self, test_data, batch_size=1, num_workers=0, stack_outputs=False,
                verbose=1, callbacks=None):
        loader = test_data if isinstance(test_data, DataLoader) else DataLoader(
            test_data, batch_size=batch_size, num_workers=num_workers
        )
        outputs = []
        for batch in loader:
            ins, _ = self._split_batch(batch, has_labels=False)
            outputs.append(self.predict_batch(ins))
        if stack_outputs and outputs:
            n_out = len(outputs[0])
            return [np.concatenate([o[i] for o in outputs]) for i in range(n_out)]
        return outputs

    def _split_batch(self, batch, has_labels=True):
        if isinstance(batch, (list, tuple)):
            batch = list(batch)
            if has_labels and len(batch) >= 2:
                return batch[:-1], batch[-1:]
            return batch, []
        return [batch], []

    def _metric_names(self):
        names = []
        for m in self._metrics:
            n = m.name()
            names.extend(n if isinstance(n, list) else [n])
        return names

    def _to_logs(self, res):
        logs = {}
        if isinstance(res, tuple):
            losses, metrics = res
            logs["loss"] = losses
            for m, v in zip(self._metrics, metrics):
                n = m.name()
                if isinstance(n, list):
                    for nn, vv in zip(n, v if isinstance(v, list) else [v]):
                        logs[nn] = vv
                else:
                    logs[n] = v
        else:
            logs["loss"] = res
        return logs

    # -- persistence --------------------------------------------------------
    def save(self, path, training=True):
        from .. import serialization

        payload = {"model": self.network.state_dict()}
        if training and self._optimizer is not None:
            payload["optimizer"] = self._optimizer.state_dict()
        serialization.save(payload["model"], path + ".pdparams")
        if training and self._optimizer is not None:
            serialization.save(payload["optimizer"], path + ".pdopt")

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        import os

        from .. import serialization

        sd = serialization.load(path + ".pdparams")
        self.network.set_state_dict(sd)
        if not reset_optimizer and self._optimizer is not None and os.path.exists(path + ".pdopt"):
            self._optimizer.set_state_dict(serialization.load(path + ".pdopt"))

    def parameters(self, *args, **kwargs):
        return self.network.parameters(*args, **kwargs)

    def summary(self, input_size=None, dtype=None):
        return summary(self.network, input_size)


def summary(net, input_size=None, dtypes=None, input=None):
    """paddle.summary parity: parameter count table."""
    total, trainable = 0, 0
    lines = [f"{'Layer':<40}{'Params':>12}"]
    for name, p in net.named_parameters():
        n = int(np.prod(p.shape)) if p.shape else 1
        total += n
        if not p.stop_gradient:
            trainable += n
        lines.append(f"{name:<40}{n:>12}")
    lines.append(f"Total params: {total}")
    lines.append(f"Trainable params: {trainable}")
    report = "\n".join(lines)
    print(report)
    return {"total_params": total, "trainable_params": trainable}
