"""hapi callbacks (reference: python/paddle/hapi/callbacks.py)."""
import numbers
import time

import numpy as np


class Callback:
    def __init__(self):
        self.model = None
        self.params = {}

    def set_model(self, model):
        self.model = model

    def set_params(self, params):
        self.params = params or {}

    def on_begin(self, mode, logs=None):
        getattr(self, f"on_{mode}_begin", lambda logs=None: None)(logs)

    def on_end(self, mode, logs=None):
        getattr(self, f"on_{mode}_end", lambda logs=None: None)(logs)

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_eval_begin(self, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass

    def on_eval_batch_begin(self, step, logs=None):
        pass

    def on_eval_batch_end(self, step, logs=None):
        pass


class CallbackList:
    def __init__(self, callbacks=None, model=None, verbose=2):
        self.callbacks = list(callbacks or [])
        if verbose and not any(isinstance(c, ProgBarLogger) for c in self.callbacks):
            self.callbacks.insert(0, ProgBarLogger(verbose=verbose))
        for c in self.callbacks:
            c.set_model(model)

    def _call(self, name, *args):
        for c in self.callbacks:
            getattr(c, name)(*args)

    def on_begin(self, mode, params=None):
        for c in self.callbacks:
            c.set_params(params)
        self._call("on_begin", mode, params)

    def on_end(self, mode, logs=None):
        self._call("on_end", mode, logs)

    def on_epoch_begin(self, epoch, logs=None):
        self._call("on_epoch_begin", epoch, logs)

    def on_epoch_end(self, epoch, logs=None):
        self._call("on_epoch_end", epoch, logs)

    def on_batch_begin(self, mode, step, logs=None):
        self._call(f"on_{mode}_batch_begin", step, logs)

    def on_batch_end(self, mode, step, logs=None):
        self._call(f"on_{mode}_batch_end", step, logs)


class ProgBarLogger(Callback):
    def __init__(self, log_freq=10, verbose=2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose
        self._t0 = None
        self._step = 0
        self._epoch = 0

    def on_train_begin(self, logs=None):
        self._t0 = time.time()

    def on_epoch_begin(self, epoch, logs=None):
        self._epoch = epoch
        self._step = 0
        self._epoch_t0 = time.time()

    def on_train_batch_end(self, step, logs=None):
        self._step = step
        if self.verbose >= 2 and step % self.log_freq == 0:
            msg = self._fmt(logs)
            total = self.params.get("steps")
            print(f"Epoch {self._epoch}: step {step}{f'/{total}' if total else ''} - {msg}")

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            dt = time.time() - self._epoch_t0
            print(f"Epoch {epoch} done in {dt:.1f}s - {self._fmt(logs)}")

    @staticmethod
    def _fmt(logs):
        parts = []
        for k, v in (logs or {}).items():
            if isinstance(v, (list, tuple)):
                v = v[0] if v else 0.0
            if isinstance(v, numbers.Number):
                parts.append(f"{k}: {v:.4f}")
        return ", ".join(parts)


class ModelCheckpoint(Callback):
    def __init__(self, save_freq=1, save_dir=None):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.model is not None and self.save_dir and epoch % self.save_freq == 0:
            self.model.save(f"{self.save_dir}/{epoch}")

    def on_train_end(self, logs=None):
        if self.model is not None and self.save_dir:
            self.model.save(f"{self.save_dir}/final")


class LRScheduler(Callback):
    def __init__(self, by_step=True, by_epoch=False):
        super().__init__()
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        opt = getattr(self.model, "_optimizer", None)
        return opt._learning_rate_scheduler if opt is not None else None

    def on_train_batch_end(self, step, logs=None):
        # TrainStep already steps the scheduler per step; only epoch mode acts
        pass

    def on_epoch_end(self, epoch, logs=None):
        if self.by_epoch:
            s = self._sched()
            if s is not None:
                s.step()


class EarlyStopping(Callback):
    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1, min_delta=0,
                 baseline=None, save_best_model=True):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        self.wait = 0
        self.best = None
        if mode == "max" or (mode == "auto" and "acc" in monitor):
            self.cmp = lambda cur, best: cur > best + self.min_delta
        else:
            self.cmp = lambda cur, best: cur < best - self.min_delta

    def on_epoch_end(self, epoch, logs=None):
        logs = logs or {}
        cur = logs.get(self.monitor)
        if cur is None:
            return
        if isinstance(cur, (list, tuple)):
            cur = cur[0]
        if self.best is None or self.cmp(cur, self.best):
            self.best = cur
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.model.stop_training = True


class VisualDL(Callback):
    """Metrics file writer (reference: VisualDL callback). Writes JSONL —
    TensorBoard-free observability for this environment."""

    def __init__(self, log_dir="./vdl_log"):
        super().__init__()
        self.log_dir = log_dir
        self._f = None

    def on_train_begin(self, logs=None):
        import json
        import os

        os.makedirs(self.log_dir, exist_ok=True)
        self._f = open(f"{self.log_dir}/metrics.jsonl", "a")

    def on_train_batch_end(self, step, logs=None):
        import json

        if self._f:
            rec = {"step": step}
            for k, v in (logs or {}).items():
                if isinstance(v, (list, tuple)):
                    v = v[0] if v else None
                if isinstance(v, numbers.Number):
                    rec[k] = float(v)
            self._f.write(json.dumps(rec) + "\n")

    def on_train_end(self, logs=None):
        if self._f:
            self._f.close()


class TensorBoard(Callback):
    """TensorBoard scalar logging for Model.fit via the self-contained
    tfevents writer (utils/tensorboard.py); per-batch loss + per-epoch
    metrics land under `train/` and `epoch/` tags."""

    def __init__(self, log_dir="./runs", log_freq=10):
        super().__init__()
        self.log_dir = log_dir
        self.log_freq = log_freq
        self._writer = None
        self._global_step = 0

    def _w(self):
        if self._writer is None:
            from ..utils.tensorboard import SummaryWriter

            self._writer = SummaryWriter(self.log_dir)
        return self._writer

    def on_train_batch_end(self, step, logs=None):
        self._global_step += 1
        if self._global_step % self.log_freq:
            return
        for k, v in (logs or {}).items():
            if isinstance(v, (list, tuple)):
                v = v[0] if v else None
            if isinstance(v, numbers.Number):
                self._w().add_scalar(f"train/{k}", v, self._global_step)
        self._w().flush()

    def on_epoch_end(self, epoch, logs=None):
        for k, v in (logs or {}).items():
            if isinstance(v, (list, tuple)):
                v = v[0] if v else None
            if isinstance(v, numbers.Number):
                self._w().add_scalar(f"epoch/{k}", v, epoch)
        self._w().flush()

    def on_train_end(self, logs=None):
        if self._writer is not None:
            self._writer.close()


class MetricsBusCallback(Callback):
    """Routes Model.fit batches through the step-metrics bus (SURVEY.md §5:
    loss/throughput/memory observability). tokens_per_sample converts
    sample throughput to token throughput for LM training."""

    def __init__(self, bus=None, log_every=10, tensorboard_dir=None, jsonl_path=None,
                 tokens_per_sample=None):
        super().__init__()
        from ..utils.metrics_bus import JsonlWriter, StepMetricsBus, stdout_logger

        self.tokens_per_sample = tokens_per_sample
        if bus is not None:
            # caller-provided bus: its sinks are the caller's business
            self.bus = bus
            return
        self.bus = StepMetricsBus(log_every=log_every, skip_first=1)
        self.bus.subscribe(stdout_logger())
        if jsonl_path:
            self.bus.subscribe(JsonlWriter(jsonl_path))
        if tensorboard_dir:
            from ..utils.tensorboard import SummaryWriter

            self.bus.subscribe(SummaryWriter(tensorboard_dir))

    def on_train_batch_end(self, step, logs=None):
        logs = logs or {}
        loss = logs.get("loss")
        if isinstance(loss, (list, tuple)):
            loss = loss[0] if loss else None
        bs = logs.get("batch_size", 1)
        tokens = bs * (self.tokens_per_sample or 1)
        self.bus.on_step(loss=loss, tokens=tokens)
