from .callbacks import (
    Callback,
    CallbackList,
    EarlyStopping,
    LRScheduler,
    ModelCheckpoint,
    ProgBarLogger,
)
from .model import Model, summary
