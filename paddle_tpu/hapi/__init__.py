from .callbacks import (
    Callback,
    CallbackList,
    EarlyStopping,
    LRScheduler,
    MetricsBusCallback,
    ModelCheckpoint,
    ProgBarLogger,
    TensorBoard,
    VisualDL,
)
from .model import Model, summary
