"""Profiler (reference: python/paddle/profiler/profiler.py + C++ profiler v2
at paddle/fluid/platform/profiler/).

TPU-native: device timelines come from jax.profiler (xprof/libtpu), replacing
the CUPTI tracer; host-side RecordEvent annotations are kept and exported as
chrome-trace JSON, same as the reference's ChromeTracingLogger.
"""
import contextlib
import json
import os
import threading
import time
from enum import Enum

import jax


class ProfilerState(Enum):
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


class ProfilerTarget(Enum):
    CPU = 0
    GPU = 1
    TPU = 2
    CUSTOM_DEVICE = 3


def make_scheduler(*, closed, ready, record, repeat=0, skip_first=0):
    """CLOSED→READY→RECORD(→RETURN) state machine (reference:
    profiler.make_scheduler)."""

    def scheduler(step):
        if step < skip_first:
            return ProfilerState.CLOSED
        s = step - skip_first
        period = closed + ready + record
        if repeat and s >= repeat * period:
            return ProfilerState.CLOSED
        pos = s % period
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == period - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD

    return scheduler


def export_chrome_tracing(dir_name, worker_name=None):
    def handler(prof):
        os.makedirs(dir_name, exist_ok=True)
        name = worker_name or f"worker_{os.getpid()}"
        path = os.path.join(dir_name, f"{name}_{int(time.time())}.pt.trace.json")
        prof._export_host_events(path)

    return handler


_host_events = []
_events_lock = threading.Lock()
_recording = False


def _record_host_event(name, ts_us, dur_us):
    """Append one complete-event to the chrome-trace host buffer (shared
    sink: RecordEvent AND observability.tracing spans land in the same
    timeline). No-op unless a Profiler is recording."""
    if not _recording:
        return
    from ..observability.tracing import _small_tid

    with _events_lock:
        _host_events.append(
            {
                "name": name,
                "ph": "X",
                "ts": ts_us,
                "dur": dur_us,
                "pid": os.getpid(),
                # stable sequential per-thread id — the old
                # `get_ident() % 100000` could collide two threads into one
                # trace row, interleaving their events
                "tid": _small_tid(),
            }
        )


class RecordEvent:
    """Host-side RAII annotation (reference: platform/profiler/event_tracing.h
    RecordEvent). Also forwards to jax.profiler.TraceAnnotation so host spans
    appear in xprof device traces."""

    def __init__(self, name, event_type=None):
        self.name = name
        self._t0 = None
        self._jax_ctx = None

    def begin(self):
        self._t0 = time.perf_counter_ns()
        try:
            self._jax_ctx = jax.profiler.TraceAnnotation(self.name)
            self._jax_ctx.__enter__()
        except Exception:
            self._jax_ctx = None

    def end(self):
        if self._jax_ctx is not None:
            self._jax_ctx.__exit__(None, None, None)
        if self._t0 is not None:
            _record_host_event(
                self.name,
                self._t0 / 1000.0,
                (time.perf_counter_ns() - self._t0) / 1000.0,
            )

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()


class Profiler:
    def __init__(self, *, targets=None, scheduler=None, on_trace_ready=None, record_shapes=False,
                 profile_memory=False, timer_only=False, with_flops=False):
        if isinstance(scheduler, tuple):
            start, end = scheduler
            scheduler = make_scheduler(closed=start, ready=0, record=end - start, repeat=1)
        self.scheduler = scheduler
        self.on_trace_ready = on_trace_ready or export_chrome_tracing("./profiler_log")
        self.timer_only = timer_only
        self.step_num = 0
        self.current_state = ProfilerState.CLOSED
        self._jax_tracing = False
        self._step_times = []
        self._last_step_t = None

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()

    def start(self):
        global _recording
        self.current_state = self.scheduler(self.step_num) if self.scheduler else ProfilerState.RECORD
        if self.current_state in (ProfilerState.RECORD, ProfilerState.RECORD_AND_RETURN):
            _recording = True
        self._last_step_t = time.perf_counter()

    def stop(self):
        global _recording
        if _recording:
            _recording = False
            if self.on_trace_ready:
                self.on_trace_ready(self)

    def step(self, num_samples=None):
        global _recording
        now = time.perf_counter()
        if self._last_step_t is not None:
            self._step_times.append(now - self._last_step_t)
        self._last_step_t = now
        self.step_num += 1
        if self.scheduler is None:
            return
        prev = self.current_state
        self.current_state = self.scheduler(self.step_num)
        if prev == ProfilerState.RECORD_AND_RETURN and self.on_trace_ready:
            _recording = False
            self.on_trace_ready(self)
        if self.current_state in (ProfilerState.RECORD, ProfilerState.RECORD_AND_RETURN):
            _recording = True
        elif self.current_state == ProfilerState.CLOSED:
            _recording = False

    def step_info(self, unit=None):
        if not self._step_times:
            return ""
        avg = sum(self._step_times) / len(self._step_times)
        return f"avg_step_time: {avg*1000:.2f} ms, ips: {1.0/avg:.2f} steps/s"

    def summary(self, sorted_by=None, op_detail=True, thread_sep=False, time_unit="ms"):
        with _events_lock:
            by_name = {}
            for e in _host_events:
                agg = by_name.setdefault(e["name"], {"calls": 0, "total_us": 0.0})
                agg["calls"] += 1
                agg["total_us"] += e["dur"]
        lines = [f"{'Name':<40}{'Calls':>8}{'Total(ms)':>12}"]
        for name, agg in sorted(by_name.items(), key=lambda kv: -kv[1]["total_us"]):
            lines.append(f"{name:<40}{agg['calls']:>8}{agg['total_us']/1000:>12.3f}")
        return "\n".join(lines)

    def export(self, path, format="json"):
        self._export_host_events(path)

    def _export_host_events(self, path):
        with _events_lock:
            events = list(_host_events)
            _host_events.clear()
        with open(path, "w") as f:
            json.dump({"traceEvents": events}, f)


def start_xprof_trace(log_dir="/tmp/xprof"):
    """Start a device trace via jax.profiler (xprof) — the CUPTI
    equivalent. Routed through the flight recorder's capture registry
    (ISSUE 13): every profile artifact is ledgered, bounded to one live
    capture, and visible at /profilez — raw ``jax.profiler.start_trace``
    anywhere else fails the ``profiler-capture`` analysis rule."""
    from ..observability import flightrec

    flightrec.start_capture(log_dir, trigger="profiler_api")


def stop_xprof_trace():
    from ..observability import flightrec

    flightrec.stop_capture()


@contextlib.contextmanager
def xprof_trace(log_dir="/tmp/xprof"):
    start_xprof_trace(log_dir)
    try:
        yield
    finally:
        stop_xprof_trace()


def load_profiler_result(filename):
    with open(filename) as f:
        return json.load(f)
