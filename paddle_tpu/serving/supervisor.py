"""Self-healing replica lifecycle (ISSUE 12 tentpole, part 1).

Everything below the supervisor already exists: the router marks replicas
DEAD off damped heartbeats (PR 4 + this PR's flap damping), the breaker
trips sick replicas into PROBATION, drain() empties a replica without
losing a request, the fleet rollup (PR 11) distills burn rate + occupancy
into one ``pressure``/``scale_hint`` signal, and the PR-9 fencing contract
defines how a superseded incarnation is kept from writing. What was
missing is the actor: a dead replica stayed dead until a human restarted
it, and ``scale_hint`` was a dashboard number. The ReplicaSupervisor
closes both loops:

**Replacement.** A DEAD replica's failure domain gets a replacement spawn
(``engine_factory()`` -> :meth:`ServingFrontend.add_replica`) under a
per-domain restart budget with bounded exponential backoff. The budget
counts restart *intensity*, not a lifetime total: only budget-many
attempts within ``budget_window_s`` exhaust a domain — deaths separated
by a healthy window are independent incidents. A domain that keeps dying
inside the window (bad host, corrupted pool) stops consuming spawns
(``supervisor.budget_exhausted``) instead of crash-looping. Each
incarnation carries a :class:`ReplicaFence`: the supervisor revokes the
dead incarnation's fence BEFORE the replacement exists (per-incarnation
— healthy siblings sharing the failure domain keep writing), so its late
heartbeat-file and fleet-snapshot writes raise ``StaleGenerationError``
and are dropped (``supervisor.fenced_writes``) — a zombie dispatcher
cannot masquerade as its own replacement.

**Scaling.** The fleet signal's ``scale_hint`` drives grow/shrink with
hysteresis: grow only after the hint has held for ``grow_hold_s``
(sustained pressure, or the multi-window burn alert — both windows
alight — that the rollup folds into the hint), shrink only after
``shrink_cooldown_s`` of sustained quiet, and always via ``drain()`` so
no request is lost; a drain that cannot finish within its timeout aborts
the shrink and revives the replica. Scale/replace actions are themselves
generation-fenced at the process level: a supervisor whose elastic
incarnation was superseded (PR-9 ``process_fence``) stops acting
permanently instead of fighting its successor.

The control loop is event-driven (``Event.wait`` on the supervisor
cadence, woken early by ``poke()``) — no polling ``time.sleep`` in any
decision path (the serving-sleep lint covers this file). **Default-off**:
:meth:`ReplicaSupervisor.from_env` returns None unless
``PADDLE_SUPERVISOR`` is truthy, so an unconfigured frontend gains zero
threads and zero overhead. Chaos seams ``supervisor.decision`` (every
tick) and ``serving.spawn_fail`` (every spawn) make the recovery paths
deterministically drivable from tests (docs/CHAOS.md).
"""
import threading
import time
from collections import deque

from ..distributed.fleet.elastic.fencing import (
    StaleGenerationError,
    process_fence,
)
from ..observability.metrics import registry as _registry
from ..testing import chaos
from ..utils.envs import env_bool, env_float, env_int
from .router import DEAD, LIVE

__all__ = ["ReplicaFence", "ReplicaSupervisor"]

_M_TICKS = _registry.counter(
    "supervisor.ticks", help="supervisor control-loop decision passes")
_M_RESPAWNS = _registry.counter(
    "supervisor.respawns",
    help="dead replicas replaced with a freshly spawned incarnation")
_M_SPAWN_FAILURES = _registry.counter(
    "supervisor.spawn_failures",
    help="replacement/scale-up spawns that failed (retried under backoff)")
_M_BUDGET_EXHAUSTED = _registry.counter(
    "supervisor.budget_exhausted",
    help="failure domains whose restart budget ran out (left dead)")
_M_SCALE_UPS = _registry.counter(
    "supervisor.scale_ups", help="replicas added on a sustained grow hint")
_M_SCALE_DOWNS = _registry.counter(
    "supervisor.scale_downs",
    help="replicas drained and removed on a sustained shrink hint")
_M_GENERATION = _registry.gauge(
    "supervisor.generation",
    help="newest replica incarnation generation across failure domains")


class ReplicaFence:
    """The PR-9 ``check()`` contract applied to replica incarnations: one
    (domain, generation) identity captured at spawn, revoked by the
    supervisor the moment THIS incarnation is superseded (replacement) or
    retired (scale-down). Revocation is per-incarnation — a failure
    domain may hold several healthy replicas, and replacing one must not
    fence its siblings' telemetry — and it happens BEFORE the replacement
    exists, so a superseded incarnation's ``check()`` raises
    :class:`StaleGenerationError` from that moment on.
    ReplicaHandle.fence_writable() turns that into dropped heartbeat/
    snapshot writes (``supervisor.fenced_writes``)."""

    __slots__ = ("_supervisor", "domain", "generation", "revoked")

    def __init__(self, supervisor, domain, generation):
        self._supervisor = supervisor
        self.domain = str(domain)
        self.generation = int(generation)
        self.revoked = False

    def revoke(self):
        # single writer (the supervisor loop), monotonic False->True; a
        # racing reader at worst sees one last pre-revocation write
        self.revoked = True  # lint: shared-mutation-without-lock-ok (monotonic flag, single supervisor writer)

    def check(self, op="write"):
        if self.revoked:
            newest = self._supervisor.domain_generation(self.domain)
            raise StaleGenerationError(
                f"{op}: replica incarnation generation {self.generation} of "
                f"failure domain {self.domain!r} was superseded (domain is "
                f"at generation {newest}) — a replaced replica must not "
                f"publish telemetry its replacement's aggregator would "
                f"trust")
        return True

    def __repr__(self):
        return (f"ReplicaFence({self.domain!r}, gen={self.generation}"
                f"{', REVOKED' if self.revoked else ''})")


class _Domain:
    """Per-failure-domain restart bookkeeping: spawn attempts against the
    budget, the bounded-backoff schedule, and the incarnation generation
    the fences compare against."""

    __slots__ = ("name", "generation", "attempts", "next_attempt_t",
                 "window_start_t", "exhausted")

    def __init__(self, name):
        self.name = str(name)
        self.generation = 0
        self.attempts = 0
        self.next_attempt_t = 0.0
        self.window_start_t = 0.0   # first attempt of the current window
        self.exhausted = False


class ReplicaSupervisor:
    """Closed-loop replica lifecycle over one :class:`ServingFrontend`.

    ``engine_factory`` is the spawn recipe: a zero-arg callable returning
    a fresh engine replica (model + pools loaded — build it warm; the
    dispatcher's warmup hook covers AOT compiles). Construct directly in
    tests (``start=False`` + ``tick()`` for deterministic single steps) or
    via :meth:`from_env` in production wiring — the default-off env gate.

    Every knob falls back to a ``PADDLE_SUPERVISOR_*`` env (docs/ENVS.md);
    the injectable ``clock`` makes backoff/hysteresis unit-testable
    without wall-clock waits.
    """

    def __init__(self, frontend, engine_factory, min_replicas=None,
                 max_replicas=None, restart_budget=None,
                 budget_window_s=None, backoff_base_s=None,
                 backoff_max_s=None, grow_hold_s=None,
                 shrink_cooldown_s=None, interval_s=None,
                 drain_timeout_s=30.0, clock=time.monotonic, start=False,
                 min_replicas_by_role=None):
        if not callable(engine_factory):
            raise ValueError("engine_factory must be a zero-arg callable "
                             "returning a fresh engine replica")
        self.frontend = frontend
        self.engine_factory = engine_factory
        n0 = len(frontend.replicas)
        self.min_replicas = (env_int("PADDLE_SUPERVISOR_MIN_REPLICAS", 1)
                             if min_replicas is None else int(min_replicas))
        self.max_replicas = (env_int("PADDLE_SUPERVISOR_MAX_REPLICAS",
                                     max(2 * n0, 2))
                             if max_replicas is None else int(max_replicas))
        if not 1 <= self.min_replicas <= self.max_replicas:
            raise ValueError(
                f"need 1 <= min_replicas <= max_replicas, got "
                f"{self.min_replicas}..{self.max_replicas}")
        self.restart_budget = (env_int("PADDLE_SUPERVISOR_RESTART_BUDGET", 3)
                               if restart_budget is None
                               else int(restart_budget))
        self.budget_window_s = (
            env_float("PADDLE_SUPERVISOR_BUDGET_WINDOW_S", 300.0)
            if budget_window_s is None else float(budget_window_s))
        self.backoff_base_s = (env_float("PADDLE_SUPERVISOR_BACKOFF_S", 0.5)
                               if backoff_base_s is None
                               else float(backoff_base_s))
        self.backoff_max_s = (env_float("PADDLE_SUPERVISOR_BACKOFF_MAX_S",
                                        15.0)
                              if backoff_max_s is None
                              else float(backoff_max_s))
        self.grow_hold_s = (env_float("PADDLE_SUPERVISOR_GROW_HOLD_S", 3.0)
                            if grow_hold_s is None else float(grow_hold_s))
        self.shrink_cooldown_s = (
            env_float("PADDLE_SUPERVISOR_SHRINK_COOLDOWN_S", 10.0)
            if shrink_cooldown_s is None else float(shrink_cooldown_s))
        self.interval_s = (env_float("PADDLE_SUPERVISOR_INTERVAL_S", 0.25)
                           if interval_s is None else float(interval_s))
        self.drain_timeout_s = float(drain_timeout_s)
        # per-role floors (ISSUE 16): a disaggregated fleet's shrink path
        # must respect each POOL's floor, not just the fleet total — a
        # sustained lull on decode must never drain the last prefill
        # replica (or vice versa). Unlisted roles fall back to the global
        # min_replicas. env: PADDLE_SUPERVISOR_MIN_REPLICAS_<ROLE>
        self.min_replicas_by_role = dict(min_replicas_by_role or {})
        env_floors = {
            "prefill": env_int("PADDLE_SUPERVISOR_MIN_REPLICAS_PREFILL", 0),
            "decode": env_int("PADDLE_SUPERVISOR_MIN_REPLICAS_DECODE", 0),
            "blended": env_int("PADDLE_SUPERVISOR_MIN_REPLICAS_BLENDED", 0),
        }
        for role, v in env_floors.items():
            if v and role not in self.min_replicas_by_role:
                self.min_replicas_by_role[role] = v
        self._clock = clock
        self._lock = threading.Lock()
        self._domains = {}
        # hold/cooldown state PER (role, hint) — a prefill pool's grow
        # pressure must not be masked (or reset) by the decode pool's
        self._hint_since = {}
        self._scale_seq = 0
        self._events = deque(maxlen=64)
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._thread = None
        self.superseded = False
        # adopt the existing fleet: every replica joins a failure domain
        # and gets fenced at the current (zero) generation, so the very
        # first replacement already rejects its predecessor's late writes
        for rep in frontend.replicas:
            dom = self._domain(rep.domain or rep.name)
            rep.domain = dom.name
            if rep.fence is None:
                rep.fence = ReplicaFence(self, dom.name, dom.generation)
        frontend.supervisor = self
        if start:
            self.start()

    @classmethod
    def from_env(cls, frontend, engine_factory, **kw):
        """The default-off gate (acceptance criterion: a disabled
        supervisor adds ZERO threads): returns a started supervisor only
        when ``PADDLE_SUPERVISOR`` is truthy, else None — no object, no
        fences, no thread, nothing to pay for."""
        if not env_bool("PADDLE_SUPERVISOR"):
            return None
        return cls(frontend, engine_factory, start=True, **kw)

    # ---- lifecycle ---------------------------------------------------------
    def start(self):
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="paddle-serving-supervisor")
        self._thread.start()
        return self

    def stop(self, timeout=None):
        """Stop the control loop. Joins with ``timeout`` (default: long
        enough for one in-flight drain to conclude)."""
        self._stop.set()
        self._wake.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=self.drain_timeout_s + 5.0
                   if timeout is None else timeout)

    def poke(self):
        """Wake the control loop now (a death just observed, a test
        stepping the clock) instead of waiting out the cadence."""
        self._wake.set()

    def _run(self):
        while not self._stop.is_set():
            try:
                self.tick()
            except StaleGenerationError:
                # this whole incarnation was superseded (elastic re-form):
                # the successor owns the fleet now — acting would be a
                # split-brain spawn storm. Permanent, deliberate stop.
                self.superseded = True  # lint: shared-mutation-without-lock-ok (sole writer is this loop's terminal path; readers are report()/tests)
                self._log("superseded", "")
                return
            except Exception as e:
                # a failed decision pass (chaos fault, transient rollup
                # error) must not kill the loop that exists to survive
                # failures — count it and keep going
                _registry.counter(
                    "supervisor.decision_errors",
                    help="decision passes aborted by an exception "
                         "(loop survives)").inc()
                self._log("decision_error", f"{type(e).__name__}: {e}")
            self._wake.wait(self.interval_s)
            self._wake.clear()

    # ---- the decision pass -------------------------------------------------
    def tick(self, now=None):
        """One decision pass: fence check, replace the dead, autoscale.
        Callable directly (tests, ops) — the thread is just this on a
        cadence."""
        now = self._clock() if now is None else now
        _M_TICKS.inc()
        chaos.site("supervisor.decision")
        f = process_fence()
        if f is not False:
            f.check("supervisor.tick")  # raises when superseded (PR 9)
        self._replace_dead(now)
        self._autoscale(now)

    def _domain(self, name):
        with self._lock:
            d = self._domains.get(name)
            if d is None:
                d = self._domains[name] = _Domain(name)
            return d

    def domain_generation(self, domain):
        """Newest incarnation generation for ``domain`` (the fences'
        comparison point)."""
        d = self._domains.get(domain)
        return d.generation if d is not None else 0

    def _bump_generation(self, domain):
        with self._lock:
            domain.generation += 1
            _M_GENERATION.set(max(d.generation
                                  for d in self._domains.values()))

    def _log(self, kind, detail):
        self._events.append((round(self._clock(), 3), kind, detail))

    def _replace_dead(self, now):
        for rep in list(self.frontend.replicas):
            if rep.state != DEAD:
                continue
            if rep.retired:
                # a scale-down victim that died mid-drain: its work was
                # already relocated and we wanted it gone — just clean up
                self.frontend.remove_replica(rep)
                self._log("retired_dead_removed", rep.name)
                continue
            domain = self._domain(rep.domain or rep.name)
            if domain.exhausted:
                continue
            if now < domain.next_attempt_t:
                continue  # backing off a recent spawn failure
            if domain.attempts and self.budget_window_s > 0 \
                    and now - domain.window_start_t >= self.budget_window_s:
                # restart INTENSITY, not a lifetime count: deaths separated
                # by a healthy window are independent incidents, not a
                # crash loop — only budget-many attempts WITHIN the window
                # exhaust the domain
                domain.attempts = 0
            if domain.attempts >= self.restart_budget:
                domain.exhausted = True
                _M_BUDGET_EXHAUSTED.inc()
                self._log("budget_exhausted", domain.name)
                continue
            if domain.attempts == 0:
                domain.window_start_t = now
            domain.attempts += 1
            # fence FIRST: from here the dead incarnation (and any zombie
            # dispatcher still wedged in a device call under its name)
            # cannot publish telemetry the replacement's view would trust.
            # Revocation is per-incarnation — healthy siblings sharing the
            # failure domain keep writing
            if rep.fence is not None:
                rep.fence.revoke()
            self._bump_generation(domain)
            # the replacement inherits the dead incarnation's pool role —
            # a prefill replica's successor serves prefill
            new = self._spawn(domain, role=rep.role)
            if new is None:
                backoff = min(self.backoff_max_s,
                              self.backoff_base_s
                              * (2 ** (domain.attempts - 1)))
                domain.next_attempt_t = now + backoff
                continue
            _M_RESPAWNS.inc()
            self._log("respawn", f"{rep.name} -> {new.name}")
            self.frontend.remove_replica(rep)

    def _spawn(self, domain, role="blended"):
        """One engine spawn + pool join for ``domain``'s current
        generation. Returns the new ReplicaHandle, or None on failure
        (counted; the caller schedules the backoff). ``role`` is offered
        to the factory (disaggregated pools may build prefill and decode
        replicas differently) and falls back to a zero-arg call for
        factories that predate roles."""
        try:
            # the chaos seam: a FaultPlan arming serving.spawn_fail makes
            # this spawn fail deterministically (budget/backoff drills)
            chaos.site("serving.spawn_fail")
            try:
                engine = self.engine_factory(role=role)
            except TypeError:
                engine = self.engine_factory()
            return self.frontend.add_replica(
                engine, name=f"{domain.name}-g{domain.generation}",
                domain=domain.name, role=role,
                fence=ReplicaFence(self, domain.name, domain.generation))
        except Exception as e:
            _M_SPAWN_FAILURES.inc()
            self._log("spawn_fail",
                      f"{domain.name}: {type(e).__name__}: {e}")
            return None

    def min_for(self, role):
        """Shrink floor for one role pool."""
        return self.min_replicas_by_role.get(role, self.min_replicas)

    def _autoscale(self, now):
        """Per-role autoscaling (ISSUE 16): each pool's pressure drives
        its own grow/shrink with its own hold/cooldown state, so a
        saturated prefill pool grows even while the decode pool idles —
        and a decode lull cannot mask a prefill grow hint (or vice
        versa). A rollup without a roles block (homogeneous fleet, stub
        signals) degrades to the single blended loop this method always
        was."""
        sig = self.frontend.fleet_signal()
        roles = sig.get("roles") or None
        if not roles:
            roles = {"blended": {"scale_hint": sig.get("scale_hint")}}
        for role in sorted(roles):
            self._autoscale_role(now, role, roles[role].get("scale_hint"))

    def _autoscale_role(self, now, role, hint):
        for h in ("grow", "shrink"):
            key = (role, h)
            if hint != h:
                self._hint_since[key] = None
            elif self._hint_since.get(key) is None:
                self._hint_since[key] = now
        live_all = [r for r in self.frontend.replicas if r.state == LIVE]
        live = [r for r in live_all if r.role == role]
        if hint == "grow" and len(live_all) < self.max_replicas:
            since = self._hint_since[(role, "grow")]
            if now - since < self.grow_hold_s:
                return  # hysteresis: pressure must SUSTAIN, not spike
            with self._lock:
                self._scale_seq += 1
                seq = self._scale_seq
            # role-tagged scale domain: a crash-looping prefill spawn
            # exhausts ITS domain's restart budget, never decode's
            domain = self._domain(f"scale-{role}{seq}")
            self._bump_generation(domain)
            new = self._spawn(domain, role=role)
            if new is not None:
                _M_SCALE_UPS.inc()
                self._log("scale_up", f"{new.name} ({role})")
            self._hint_since[(role, "grow")] = None  # re-arm either way
        elif hint == "shrink" and len(live) > self.min_for(role):
            since = self._hint_since[(role, "shrink")]
            if now - since < self.shrink_cooldown_s:
                return  # cooldown: a lull is not a trend
            victim = min(live, key=lambda r: r.load())
            if self._shrink(victim):
                self._hint_since[(role, "shrink")] = None

    def _shrink(self, rep):
        """Retire one replica, always via drain() — the no-lost-requests
        contract. A drain that cannot finish in time aborts the shrink
        (the replica revives; the cooldown re-arms)."""
        rep.retired = True
        if not self.frontend.drain(rep, timeout=self.drain_timeout_s):
            rep.retired = False
            self.frontend.revive(rep)
            self._log("shrink_aborted", f"{rep.name}: drain timed out")
            return False
        # fence the retired incarnation BEFORE removal: its dispatcher is
        # still alive in the wake-wait and must not keep publishing
        if rep.fence is not None:
            rep.fence.revoke()
        self.frontend.remove_replica(rep)
        _M_SCALE_DOWNS.inc()
        self._log("scale_down", rep.name)
        return True

    # ---- report ------------------------------------------------------------
    def report(self):
        """The ``serving_report()["supervisor"]`` / statusz block."""
        now = self._clock()
        with self._lock:
            domains = {
                d.name: {
                    "generation": d.generation,
                    "attempts": d.attempts,
                    "exhausted": d.exhausted,
                    "backoff_remaining_s": round(
                        max(0.0, d.next_attempt_t - now), 3),
                }
                for d in self._domains.values()
            }
        return {
            "running": self._thread is not None,
            "superseded": self.superseded,
            "min_replicas": self.min_replicas,
            "min_replicas_by_role": dict(self.min_replicas_by_role),
            "max_replicas": self.max_replicas,
            "restart_budget": self.restart_budget,
            "budget_window_s": self.budget_window_s,
            "interval_s": self.interval_s,
            "grow_hold_s": self.grow_hold_s,
            "shrink_cooldown_s": self.shrink_cooldown_s,
            "domains": domains,
            "events": list(self._events)[-16:],
        }
