"""Online serving control plane (ISSUE 4 tentpole): request frontend,
SLO-aware scheduler, and multi-replica prefix-aware router over N
``ContinuousBatchingEngine`` replicas.

    from paddle_tpu import serving

    frontend = serving.ServingFrontend([engine_a, engine_b])
    h = frontend.submit(prompt, max_new_tokens=64,
                        slo_class="interactive", deadline_s=2.0)
    for tok in h.stream():
        ...
    print(frontend.serving_report())

Layering (each file is one concern, unit-testable alone):

- ``frontend.py``  — request lifecycle: submit/RequestHandle (result /
  stream / cancel / status), per-replica dispatcher threads driving the
  engines' non-blocking hooks, replica-death rerouting, drain, telemetry.
- ``scheduler.py`` — policy: SLO classes (interactive/batch), EDF over
  virtual deadlines (starvation-free), bounded-queue admission with
  ``Overloaded`` load shedding, deadline expiry.
- ``router.py``    — placement: prefix-cache affinity + session hints
  blended with load; LIVE/PROBATION/DRAINING/DEAD replica health off
  flap-damped heartbeats.
- ``brownout.py``  — overload brownout ladder (ISSUE 12): declared
  degradation steps with hysteresis, machine-readable ``Overloaded``
  rejections, and the per-class anti-retry-storm retry budget.
- ``breaker.py``   — per-replica circuit breaking (ISSUE 12): windowed
  error/latency scoring trips a sick replica into PROBATION (half-open
  probes only) before it fails hard.
- ``supervisor.py``— the self-healing actor (ISSUE 12 tentpole):
  replaces dead replicas (per-domain restart budget + backoff +
  generation fencing) and autoscales the fleet from the PR-11
  pressure/scale_hint rollup — per disaggregation role (ISSUE 16) —
  always via drain(). Default-off (``PADDLE_SUPERVISOR``): zero threads
  unless armed.
- ``handoff.py``   — disaggregated prefill/decode KV-page handoff
  (ISSUE 16): atomic validated bundles, generation fencing, bounded
  publish retry, and the blended degradation contract (a handoff failure
  costs latency, never a wrong token and never availability).
- ``transport.py`` — the wire transport (ISSUE 18): the same validated
  bundle frames over a TCPStore-style socket channel
  (``PADDLE_KV_TRANSPORT=wire``; ``spool`` keeps the PR-16 directory
  path byte-identical), plus the fabric's peer blob fetches — typed
  KVFetchTimeout/KVPartitionError failures, bounded-backoff retries.
- ``wireformat.py`` — the NON-EXECUTABLE encoding every wire-crossing
  payload uses (JSON spec + dtype-allowlisted raw array heap): the
  unauthenticated channel cannot be leveraged into code execution —
  hostile bytes are a typed refusal, never an interpreter.
- ``kvfabric.py``  — cluster tiered KV-prefix cache (ISSUE 18): device
  pool → host spill ring → peer fetch → recompute, with residency
  advertisements the router and fleet rollup score placement against;
  every failure a typed ``kv.fallthrough{reason=}`` into recompute.
- ``tenancy.py``   — multi-tenant plane (ISSUE 19): the bounded tenant
  registry with token-bucket quota admission (typed
  ``Overloaded(step="tenant_quota", tenant=, retry_after_s=<refill
  deficit>)``), per-tenant inflight caps, and per-tenant isolation
  (private brownout ladder + retry budget + SLO burn-rate monitor) —
  layered ABOVE the EDF scheduler in ``submit(tenant=...)``.
- ``adapters.py``  — per-request LoRA hot-swap (ISSUE 19): the
  ref-counted LRU-bounded digest-keyed host cache of low-rank A/B
  pairs; the engine batches mixed adapters per decode step with zero
  recompiles across warmed signatures (``warmup(lora_ranks=...)``).

Chaos sites ``serving.route`` / ``serving.replica_kill`` /
``serving.replica_slow`` / ``serving.spawn_fail`` / ``supervisor.decision``
/ ``serving.handoff.send`` / ``serving.handoff.adopt`` /
``serving.handoff.corrupt`` / ``serving.decode_pool_empty`` /
``serving.kv.fetch`` / ``serving.kv.timeout`` / ``serving.kv.partition``
/ ``serving.kv.corrupt``
make the failure paths deterministically testable (tests/
test_serving_frontend.py, tests/test_supervisor.py, tests/test_disagg.py,
tests/test_kvfabric.py).
docs/SERVING.md is the operator guide; every later serving PR
(multi-model) builds on this subsystem.
"""
from ..inference.continuous import EngineRequest, canonical_sampling  # noqa: F401
from .adapters import AdapterRegistry, LoRAAdapter  # noqa: F401
from .breaker import BreakerPolicy, CircuitBreaker  # noqa: F401
from .brownout import (  # noqa: F401
    BrownoutLadder,
    BrownoutStep,
    RetryBudget,
)
from .frontend import (  # noqa: F401
    CANCELLED,
    DONE,
    FAILED,
    QUEUED,
    RUNNING,
    RequestCancelled,
    RequestFailed,
    RequestHandle,
    ResultTimeout,
    ServingFrontend,
)
from .handoff import (  # noqa: F401
    HandoffBundle,
    HandoffCorruptError,
    HandoffError,
    HandoffManager,
    StaleHandoffError,
)
from .kvfabric import HostSpillRing, KVFabric  # noqa: F401
from .router import (  # noqa: F401
    DEAD,
    DRAINING,
    LIVE,
    PROBATION,
    NoLiveReplicas,
    ReplicaHandle,
    Router,
)
from .scheduler import (  # noqa: F401
    BATCH,
    INTERACTIVE,
    DeadlineExceeded,
    Overloaded,
    SLOClass,
    SLOScheduler,
)
from .supervisor import ReplicaFence, ReplicaSupervisor  # noqa: F401
from .tenancy import DEFAULT_TENANT, Tenant, TenantRegistry  # noqa: F401
from .transport import (  # noqa: F401
    KVFetchTimeout,
    KVPageServer,
    KVPartitionError,
    KVTransportError,
    WireTransport,
    make_transport,
)

__all__ = [
    "ServingFrontend", "RequestHandle", "RequestFailed", "RequestCancelled",
    "ResultTimeout",
    "QUEUED", "RUNNING", "DONE", "FAILED", "CANCELLED",
    "Router", "ReplicaHandle", "NoLiveReplicas",
    "LIVE", "PROBATION", "DRAINING", "DEAD",
    "SLOScheduler", "SLOClass", "Overloaded", "DeadlineExceeded",
    "INTERACTIVE", "BATCH", "EngineRequest", "canonical_sampling",
    "BrownoutLadder", "BrownoutStep", "RetryBudget",
    "CircuitBreaker", "BreakerPolicy",
    "ReplicaSupervisor", "ReplicaFence",
    "HandoffManager", "HandoffBundle", "HandoffError",
    "HandoffCorruptError", "StaleHandoffError",
    "KVFabric", "HostSpillRing",
    "WireTransport", "KVPageServer", "make_transport",
    "KVTransportError", "KVFetchTimeout", "KVPartitionError",
    "Tenant", "TenantRegistry", "DEFAULT_TENANT",
    "LoRAAdapter", "AdapterRegistry",
]
