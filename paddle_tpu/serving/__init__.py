"""Online serving control plane (ISSUE 4 tentpole): request frontend,
SLO-aware scheduler, and multi-replica prefix-aware router over N
``ContinuousBatchingEngine`` replicas.

    from paddle_tpu import serving

    frontend = serving.ServingFrontend([engine_a, engine_b])
    h = frontend.submit(prompt, max_new_tokens=64,
                        slo_class="interactive", deadline_s=2.0)
    for tok in h.stream():
        ...
    print(frontend.serving_report())

Layering (each file is one concern, unit-testable alone):

- ``frontend.py``  — request lifecycle: submit/RequestHandle (result /
  stream / cancel / status), per-replica dispatcher threads driving the
  engines' non-blocking hooks, replica-death rerouting, drain, telemetry.
- ``scheduler.py`` — policy: SLO classes (interactive/batch), EDF over
  virtual deadlines (starvation-free), bounded-queue admission with
  ``Overloaded`` load shedding, deadline expiry.
- ``router.py``    — placement: prefix-cache affinity + session hints
  blended with load; LIVE/DRAINING/DEAD replica health off heartbeats.

Chaos sites ``serving.route`` / ``serving.replica_kill`` make the failure
paths deterministically testable (tests/test_serving_frontend.py kills a
replica under concurrent mixed-SLO load). docs/SERVING.md is the operator
guide; every later serving PR (autoscaling, multi-model, disaggregated
prefill) builds on this subsystem.
"""
from ..inference.continuous import EngineRequest, canonical_sampling  # noqa: F401
from .frontend import (  # noqa: F401
    CANCELLED,
    DONE,
    FAILED,
    QUEUED,
    RUNNING,
    RequestCancelled,
    RequestFailed,
    RequestHandle,
    ServingFrontend,
)
from .router import (  # noqa: F401
    DEAD,
    DRAINING,
    LIVE,
    NoLiveReplicas,
    ReplicaHandle,
    Router,
)
from .scheduler import (  # noqa: F401
    BATCH,
    INTERACTIVE,
    DeadlineExceeded,
    Overloaded,
    SLOClass,
    SLOScheduler,
)

__all__ = [
    "ServingFrontend", "RequestHandle", "RequestFailed", "RequestCancelled",
    "QUEUED", "RUNNING", "DONE", "FAILED", "CANCELLED",
    "Router", "ReplicaHandle", "NoLiveReplicas", "LIVE", "DRAINING", "DEAD",
    "SLOScheduler", "SLOClass", "Overloaded", "DeadlineExceeded",
    "INTERACTIVE", "BATCH", "EngineRequest", "canonical_sampling",
]
