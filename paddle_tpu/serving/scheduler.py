"""SLO-aware request scheduling for the online serving control plane.

Policy, not mechanism: this module owns WHICH queued request runs next and
WHETHER a new request is admitted at all. It holds no queues, starts no
threads, and never touches an engine — the frontend feeds it pending lists
and applies its verdicts, which keeps every decision unit-testable without
a model.

Three decisions:

- **Admission** (:meth:`SLOScheduler.check_admission`): bounded queue depth
  with load shedding. A full queue rejects the submit with
  :class:`Overloaded` *immediately* — the client gets a fast, explicit
  signal it can retry against another cell, instead of a request that sits
  in a hopeless queue until it times out silently. Interactive traffic may
  additionally reserve headroom (``interactive_reserve``) that batch
  submissions cannot consume, so a batch flood can't shed interactive
  requests.

- **Ordering** (:meth:`SLOScheduler.pick`): earliest-*virtual*-deadline
  first. Every request gets ``virtual_deadline = enqueue_time +
  min(user deadline, slo.target_wait_s)``. Interactive targets are small
  (they sort first under mixed load); batch targets are large but FINITE —
  once a batch request has waited past its target it has the earliest
  deadline in the queue and nothing submitted later can overtake it. EDF
  over finite virtual deadlines is starvation-free by construction, and the
  property is asserted under an interactive storm in
  tests/test_serving_frontend.py.

- **Expiry** (:meth:`SLOScheduler.expired`): a request whose *user-supplied*
  deadline passed while it queued is failed with :class:`DeadlineExceeded`
  at pick time — running it would waste decode slots producing tokens the
  caller has already abandoned.
"""
import time

__all__ = ["Overloaded", "DeadlineExceeded", "SLOClass", "SLOScheduler",
           "INTERACTIVE", "BATCH"]


class Overloaded(RuntimeError):
    """Raised by submit(): the control plane is shedding load. Retry against
    another cell / later — the request was never queued.

    Machine-readable (ISSUE 12): clients back off from the structured
    fields instead of parsing the message — ``retry_after_s`` is the
    server's backoff demand (honoring it is what keeps a retry storm from
    re-saturating a recovering fleet; retries that ignore it burn the
    per-class retry budget and get rejected harder), ``level``/``step``
    identify the brownout rung that shed the request (``None``/"queue"
    for a plain queue-bound shed), ``slo_class`` echoes the class, and
    ``tenant`` names the tenant whose quota/inflight bound (or private
    brownout ladder) shed it — ``step`` is ``"tenant_quota"`` /
    ``"tenant_inflight"`` for those sheds (ISSUE 19), with
    ``retry_after_s`` derived from the token bucket's refill deficit."""

    def __init__(self, msg, retry_after_s=None, level=None, step=None,
                 slo_class=None, tenant=None):
        super().__init__(msg)
        self.retry_after_s = retry_after_s
        self.level = level
        self.step = step
        self.slo_class = slo_class
        self.tenant = tenant


class DeadlineExceeded(RuntimeError):
    """The request's deadline passed before it reached a decode slot."""


class SLOClass:
    """One service class: a name and the queue-wait target that positions it
    in the EDF order. ``target_wait_s`` is the promise — interactive
    requests should start within ~this; batch requests tolerate this much
    delay but are guaranteed to start once it elapses (their virtual
    deadline becomes the earliest in the queue).

    The optional SLO-objective fields (ISSUE 7) declare what the class
    PROMISES externally — "``slo_objective`` of requests see TTFT within
    ``ttft_slo_s`` / per-token latency within ``tpot_slo_s``" — and seed
    the frontend's burn-rate monitor (observability/slo.py). None disables
    that objective for the class; the deadline-miss objective always
    exists."""

    __slots__ = ("name", "target_wait_s", "ttft_slo_s", "tpot_slo_s",
                 "slo_objective")

    def __init__(self, name, target_wait_s, ttft_slo_s=None, tpot_slo_s=None,
                 slo_objective=0.99):
        self.name = str(name)
        self.target_wait_s = float(target_wait_s)
        self.ttft_slo_s = float(ttft_slo_s) if ttft_slo_s else None
        self.tpot_slo_s = float(tpot_slo_s) if tpot_slo_s else None
        self.slo_objective = float(slo_objective)

    def __repr__(self):
        return f"SLOClass({self.name!r}, target_wait_s={self.target_wait_s})"


INTERACTIVE = SLOClass("interactive", target_wait_s=0.05,
                       ttft_slo_s=1.0, tpot_slo_s=0.25)
BATCH = SLOClass("batch", target_wait_s=2.0,
                 ttft_slo_s=30.0, tpot_slo_s=1.0, slo_objective=0.95)


class SLOScheduler:
    def __init__(self, max_queue_depth=256, interactive_reserve=0.1,
                 classes=(INTERACTIVE, BATCH)):
        if max_queue_depth < 1:
            raise ValueError("max_queue_depth must be >= 1")
        self.max_queue_depth = int(max_queue_depth)
        #: fraction of the queue only sub-target classes (interactive) may
        #: use; batch submissions shed once depth reaches (1-reserve)*max
        self.interactive_reserve = float(interactive_reserve)
        self.classes = {c.name: c for c in classes}
        # the lowest-target class is the one the reserve protects
        self._reserve_class = min(self.classes.values(),
                                  key=lambda c: c.target_wait_s).name

    @property
    def reserve_class(self):
        """Name of the class the admission reserve (and the brownout
        ladder's shed_batch rung) protects — the lowest-target class."""
        return self._reserve_class

    def resolve(self, slo_class):
        """Name or SLOClass -> SLOClass (unknown names raise)."""
        if isinstance(slo_class, SLOClass):
            return slo_class
        try:
            return self.classes[slo_class]
        except KeyError:
            raise ValueError(
                f"unknown slo_class {slo_class!r}; have "
                f"{sorted(self.classes)}") from None

    # ---- admission ---------------------------------------------------------
    def check_admission(self, queued_count, slo):
        """Raise Overloaded instead of queueing past the bound. The caller
        holds its queue lock around check+enqueue so the depth can't race."""
        limit = self.max_queue_depth
        if slo.name != self._reserve_class:
            limit = int(limit * (1.0 - self.interactive_reserve))
        if queued_count >= limit:
            raise Overloaded(
                f"queue depth {queued_count} >= {limit} for SLO class "
                f"{slo.name!r} (max_queue_depth={self.max_queue_depth})",
                step="queue", slo_class=slo.name)

    # ---- ordering ----------------------------------------------------------
    @staticmethod
    def virtual_deadline(t_enqueue, slo, deadline_s=None):
        """Absolute EDF key: enqueue + the tighter of the class target and
        the caller's deadline."""
        vd = t_enqueue + slo.target_wait_s
        if deadline_s is not None:
            vd = min(vd, t_enqueue + float(deadline_s))
        return vd

    @staticmethod
    def expired(entry, now=None):
        """True when the USER deadline (not the class target) has passed
        before the request started running."""
        if entry.deadline_t is None:
            return False
        return (now if now is not None else time.monotonic()) > entry.deadline_t

    @staticmethod
    def pick(pending, now=None):
        """Index of the next entry to admit from ``pending`` (any indexable
        of objects with ``.virtual_deadline``), or None when empty. O(n)
        scan — pending lists are bounded by max_queue_depth, and an O(n)
        min beats a heap's churn under the re-queue/reroute paths."""
        if not pending:
            return None
        best, best_vd = None, None
        for i, e in enumerate(pending):
            vd = e.virtual_deadline
            if best_vd is None or vd < best_vd:
                best, best_vd = i, vd
        return best
