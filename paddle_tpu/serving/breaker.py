"""Per-replica circuit breaking (ISSUE 12 tentpole, part 3).

The health ladder before this PR was binary above DRAINING: a replica was
LIVE (full traffic) or DEAD (everything reroutes). A replica that is
*sick* — spewing request errors from a corrupted KV pool, or running 5x
slower than its peers (the straggler verdict shape from the PR-11 fleet
detector, applied per-replica) — kept receiving its full placement share
until it either died or burned the SLO budget. The breaker adds the
intermediate verdict:

    LIVE --(error rate / slow strikes over a sliding window)--> PROBATION
    PROBATION: only rate-limited *probe* requests are routed there
               (half-open); its pending queue is re-routed to healthy
               replicas the moment it trips
    PROBATION --(probe_successes consecutive probe OKs)--> LIVE  (close)
    PROBATION --(probation_failures probe errors)--> DEAD  (fail hard —
               the normal replica-death relocation machinery takes over)

Probes are real requests (that is what half-open means), but they are not
sacrificed: a probe that fails on a PROBATION replica is transparently
re-routed by the frontend (stream-unconsumed requests re-run bit-identically
elsewhere), so the probing traffic observes the failure without the caller
eating it.

Scoring feeds (all event-driven, no threads here):

- ``record(name, ok)`` — per-request outcomes from the frontend's finish
  path (the same per-request ``req.error`` plumbing ``request_errors``
  rides).
- ``note_slow(name)`` / ``note_on_pace(name)`` — the frontend monitor's
  per-tick latency verdict: a replica whose dispatch EWMA exceeds
  ``slow_ratio`` x the cross-replica median for ``slow_strikes``
  consecutive ticks trips exactly like an error storm (the PR-11
  compute-straggler classification, applied to serving dispatch).

The breaker only renders verdicts ("trip" / "close" / "fail_hard" /
None); the frontend owns the actual state transitions so every replica
state write stays under the one frontend lock.
"""
import threading
import time
from collections import deque

from ..observability.metrics import registry as _registry

__all__ = ["BreakerPolicy", "CircuitBreaker"]

_M_TRIPS = _registry.counter(
    "breaker.trips", help="LIVE -> PROBATION circuit-breaker trips")
_M_PROBES = _registry.counter(
    "breaker.probes", help="probe requests routed to PROBATION replicas")
_M_RECOVERIES = _registry.counter(
    "breaker.recoveries", help="PROBATION -> LIVE half-open closes")
_M_FAILED_HARD = _registry.counter(
    "breaker.failed_hard",
    help="PROBATION -> DEAD transitions after failed probes")

#: breaker.state gauge values per replica
_ST_CLOSED, _ST_PROBATION, _ST_OPEN = 0, 1, 2


class BreakerPolicy:
    """Trip/recovery thresholds (all overridable; clock injectable so the
    probe rate limit is unit-testable without sleeping)."""

    __slots__ = ("window", "error_threshold", "min_samples", "slow_ratio",
                 "slow_strikes", "probe_interval_s", "probe_successes",
                 "probation_failures")

    def __init__(self, window=20, error_threshold=0.5, min_samples=4,
                 slow_ratio=4.0, slow_strikes=3, probe_interval_s=0.25,
                 probe_successes=3, probation_failures=3):
        self.window = int(window)
        self.error_threshold = float(error_threshold)
        self.min_samples = int(min_samples)
        self.slow_ratio = float(slow_ratio)
        self.slow_strikes = int(slow_strikes)
        self.probe_interval_s = float(probe_interval_s)
        self.probe_successes = int(probe_successes)
        self.probation_failures = int(probation_failures)


class _ReplicaScore:
    __slots__ = ("outcomes", "slow_strikes", "probing", "last_probe_t",
                 "probe_ok", "probe_bad", "tripped_reason")

    def __init__(self, window):
        self.outcomes = deque(maxlen=window)  # True = error
        self.slow_strikes = 0
        self.probing = False
        self.last_probe_t = None
        self.probe_ok = 0
        self.probe_bad = 0
        self.tripped_reason = None


class CircuitBreaker:
    """Sliding-window scorer + half-open probe budget per replica name.
    Thread-safe; every method is a few dict/deque ops under one lock."""

    def __init__(self, policy=None, clock=time.monotonic):
        self.policy = policy or BreakerPolicy()
        self._clock = clock
        self._lock = threading.Lock()
        self._scores = {}

    def _score(self, name):
        s = self._scores.get(name)
        if s is None:
            s = self._scores[name] = _ReplicaScore(self.policy.window)
        return s

    def _gauge(self, name):
        return _registry.gauge(
            "breaker.state", labels={"replica": str(name)},
            help="circuit state per replica: 0 closed (LIVE), "
                 "1 probation (half-open), 2 open (failed hard)")

    # ---- scoring feeds ----------------------------------------------------
    def record(self, name, ok):
        """One request outcome on a LIVE replica. Returns "trip" when the
        windowed error rate crosses the threshold, else None."""
        p = self.policy
        with self._lock:
            s = self._score(name)
            if s.probing:
                return None  # probation outcomes go through probe_result
            s.outcomes.append(not ok)
            n = len(s.outcomes)
            if n < p.min_samples:
                return None
            if sum(s.outcomes) / n >= p.error_threshold:
                return self._trip_locked(
                    name, s,
                    f"error rate {sum(s.outcomes)}/{n} over the last "
                    f"{n} requests")
        return None

    def note_slow(self, name):
        """One monitor-tick slow verdict (dispatch EWMA vs the fleet
        median). Trips after ``slow_strikes`` consecutive verdicts."""
        p = self.policy
        with self._lock:
            s = self._score(name)
            if s.probing:
                return None
            s.slow_strikes += 1
            if s.slow_strikes >= p.slow_strikes:
                return self._trip_locked(
                    name, s,
                    f"dispatch latency > {p.slow_ratio}x the replica "
                    f"median for {s.slow_strikes} consecutive checks")
        return None

    def note_on_pace(self, name):
        with self._lock:
            s = self._scores.get(name)
            if s is not None and not s.probing:
                s.slow_strikes = 0

    def _trip_locked(self, name, s, reason):
        s.probing = True
        s.tripped_reason = reason
        s.last_probe_t = None
        s.probe_ok = s.probe_bad = 0
        s.outcomes.clear()
        s.slow_strikes = 0
        _M_TRIPS.inc()
        self._gauge(name).set(_ST_PROBATION)
        return "trip"

    # ---- half-open probes --------------------------------------------------
    def allow_probe(self, name):
        """Rate-limited probe admission for a PROBATION replica: at most
        one probe per ``probe_interval_s``."""
        now = self._clock()
        with self._lock:
            s = self._scores.get(name)
            if s is None or not s.probing:
                return False
            if s.last_probe_t is not None \
                    and now - s.last_probe_t < self.policy.probe_interval_s:
                return False
            s.last_probe_t = now
        _M_PROBES.inc()
        return True

    def probe_result(self, name, ok):
        """One probe outcome: "close" after ``probe_successes``
        consecutive OKs, "fail_hard" after ``probation_failures`` errors,
        else None (keep probing)."""
        p = self.policy
        with self._lock:
            s = self._scores.get(name)
            if s is None or not s.probing:
                return None
            if ok:
                s.probe_ok += 1
                s.probe_bad = 0
                if s.probe_ok >= p.probe_successes:
                    s.probing = False
                    s.tripped_reason = None
                    s.outcomes.clear()
                    _M_RECOVERIES.inc()
                    self._gauge(name).set(_ST_CLOSED)
                    return "close"
                return None
            s.probe_ok = 0
            s.probe_bad += 1
            if s.probe_bad >= p.probation_failures:
                s.probing = False
                _M_FAILED_HARD.inc()
                self._gauge(name).set(_ST_OPEN)
                return "fail_hard"
        return None

    # ---- lifecycle ---------------------------------------------------------
    def forget(self, name):
        """Replica left the pool (death/retirement): drop its score and
        retire its state gauge so removed names stop exporting."""
        with self._lock:
            self._scores.pop(name, None)
        _registry.remove("breaker.state", labels={"replica": str(name)})

    def tripped_reason(self, name):
        with self._lock:
            s = self._scores.get(name)
            return s.tripped_reason if s is not None else None

    def report(self):
        with self._lock:
            return {
                name: {
                    "probing": s.probing,
                    "reason": s.tripped_reason,
                    "window_errors": sum(s.outcomes),
                    "window_n": len(s.outcomes),
                    "slow_strikes": s.slow_strikes,
                    "probe_ok": s.probe_ok,
                    "probe_bad": s.probe_bad,
                }
                for name, s in sorted(self._scores.items())
            }
