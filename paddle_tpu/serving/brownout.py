"""Overload brownout ladder + retry budget (ISSUE 12 tentpole, part 2).

Queue-bound shedding (scheduler.py) is a cliff: below the bound every
request gets full service, at the bound requests are rejected outright.
A fleet under a load swing (or recovering from a replica loss) needs the
slope between those extremes — *declared* degradation steps that buy
capacity back gradually, cheapest-first, and release in reverse order as
pressure drains:

    level 0  normal             full service
    level 1  shed_prefill_depth concurrent chunked prefills capped
                                (``prefill_depth_cap()``) — new prompt
                                work queues a little so in-flight decode
                                keeps its TPOT; nothing is rejected
    level 2  shed_peer_fetch    cluster KV-fabric peer fetches off
                                (``peer_fetch_enabled()``) — a fetch
                                spends wire bandwidth and adopt work to
                                SAVE compute, which is the wrong trade
                                once the fleet is pressed; local
                                recompute is bit-identical anyway
    level 3  clamp_tokens       batch-class max_new_tokens clamped
                                (bounded decode work per batch request)
    level 4  shed_extras        optional work off: hedged/speculative
                                extras are declared disabled
                                (``extras_enabled()``), the router skips
                                the O(prompt-bytes) prefix-affinity probe
                                and places by load alone, and no
                                per-request traces are minted
    level 5  shed_batch         batch-class submits rejected with a
                                machine-readable
                                ``Overloaded(retry_after_s=)``;
                                interactive still served
    level 6  reject             everything rejected with ``Overloaded``

Engagement is pressure-driven with hysteresis: a step engages the moment
pressure crosses its ``engage_at`` (climbing one rung per observation so
the engagement sequence is the declared order), and releases one rung at
a time only after pressure has stayed at/below the rung's ``release_at``
for ``dwell_s`` — a ladder without dwell oscillates at the threshold,
which is its own outage.

The **retry budget** (:class:`RetryBudget`) is the anti-retry-storm
valve: every *accepted* request deposits ``ratio`` tokens into its
class's bucket; a submit marked ``is_retry=True`` must withdraw a whole
token or it is rejected immediately (``brownout.retry_denied``) with a
``retry_after_s`` that grows with the brownout level. While the fleet is
healthy, accepted traffic keeps the bucket full and retries are free;
while it is browning out, acceptances dwindle, the bucket drains, and a
client herd re-submitting its rejections cannot re-saturate admission —
the budget caps retry traffic at ``ratio`` of the goodput the fleet is
actually sustaining (the Finagle/gRPC retry-budget construction).

Policy only — no threads, no engine access, injectable clock; the
frontend feeds ``observe()`` from its monitor tick and consults the
query methods at submit time (docs/SERVING.md has the operator view).
"""
import threading
import time

from ..observability.metrics import registry as _registry
from .scheduler import Overloaded

__all__ = ["BrownoutStep", "BrownoutLadder", "RetryBudget",
           "DEFAULT_STEPS", "SHED_PREFILL_DEPTH", "SHED_PEER_FETCH",
           "CLAMP_TOKENS", "SHED_EXTRAS", "SHED_BATCH", "REJECT"]

SHED_PREFILL_DEPTH = "shed_prefill_depth"
SHED_PEER_FETCH = "shed_peer_fetch"
CLAMP_TOKENS = "clamp_tokens"
SHED_EXTRAS = "shed_extras"
SHED_BATCH = "shed_batch"
REJECT = "reject"

_M_LEVEL = _registry.gauge(
    "brownout.level", help="current brownout ladder level (0 = normal)")


class BrownoutStep:
    """One declared degradation rung: a name the metrics/docs refer to,
    the pressure that engages it, and the (lower) pressure that releases
    it — ``release_at < engage_at`` is the hysteresis band."""

    __slots__ = ("name", "engage_at", "release_at")

    def __init__(self, name, engage_at, release_at):
        if not 0.0 < release_at <= engage_at:
            raise ValueError(
                f"step {name!r}: need 0 < release_at <= engage_at, got "
                f"release_at={release_at} engage_at={engage_at}")
        self.name = str(name)
        self.engage_at = float(engage_at)
        self.release_at = float(release_at)

    def __repr__(self):
        return (f"BrownoutStep({self.name!r}, engage_at={self.engage_at}, "
                f"release_at={self.release_at})")


DEFAULT_STEPS = (
    # cheapest rung first (ISSUE 16): capping concurrent chunked prefills
    # costs only prompt-admission latency — decode TPOT and every already-
    # admitted request are untouched — so it engages well before anything
    # that clamps or rejects
    BrownoutStep(SHED_PREFILL_DEPTH, engage_at=0.72, release_at=0.55),
    # peer KV fetches next (ISSUE 18): a fetch trades wire + adopt work
    # for saved prefill compute — a good trade only while there is slack.
    # Shedding it costs nothing but the cache win; recompute is
    # bit-identical, so this rung is invisible to correctness.
    BrownoutStep(SHED_PEER_FETCH, engage_at=0.76, release_at=0.58),
    BrownoutStep(CLAMP_TOKENS, engage_at=0.80, release_at=0.60),
    BrownoutStep(SHED_EXTRAS, engage_at=0.88, release_at=0.70),
    BrownoutStep(SHED_BATCH, engage_at=0.94, release_at=0.78),
    BrownoutStep(REJECT, engage_at=0.99, release_at=0.86),
)


class RetryBudget:
    """Per-SLO-class token bucket refilled by accepted requests. Starts
    full (``burst`` tokens) so a healthy fleet never penalizes the first
    retries; sustained rejection drains it faster than ``ratio`` of the
    surviving goodput refills it."""

    def __init__(self, ratio=0.1, burst=10.0):
        self.ratio = float(ratio)
        self.burst = float(burst)
        self._tokens = {}
        self._lock = threading.Lock()

    def on_accepted(self, slo_class):
        with self._lock:
            cur = self._tokens.get(slo_class, self.burst)
            self._tokens[slo_class] = min(self.burst, cur + self.ratio)

    def try_consume(self, slo_class):
        """Withdraw one token for a retry; False = over budget."""
        with self._lock:
            cur = self._tokens.get(slo_class, self.burst)
            if cur < 1.0:
                return False
            self._tokens[slo_class] = cur - 1.0
            return True

    def tokens(self, slo_class):
        with self._lock:
            return self._tokens.get(slo_class, self.burst)


class BrownoutLadder:
    """The ladder state machine + the submit-time policy queries.

    ``observe(pressure)`` advances at most one rung per call (up
    immediately, down after ``dwell_s`` at/below the release threshold);
    everything else is a read. All transitions land on the metrics
    registry (``brownout.level`` gauge, ``brownout.engaged`` /
    ``brownout.released`` counters labeled ``{step=}``) and in a bounded
    ``history`` the supervisor/statusz report.

    Per-tenant ladders (ISSUE 19): ``labels`` (e.g. the tenant label a
    :class:`~paddle_tpu.serving.tenancy.Tenant` builds from its
    registry-declared name) keeps a private ladder's gauge/counter series
    distinct from the fleet ladder's, and ``tenant`` stamps the tenant
    name into every ``Overloaded`` this ladder raises."""

    def __init__(self, steps=DEFAULT_STEPS, batch_token_cap=64,
                 dwell_s=2.0, retry_after_base_s=0.5,
                 retry_budget=None, clock=time.monotonic,
                 labels=None, tenant=None):
        self.steps = list(steps)
        if not self.steps:
            raise ValueError("need at least one brownout step")
        names = [s.name for s in self.steps]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate step names in {names}")
        eng = [s.engage_at for s in self.steps]
        if eng != sorted(eng):
            raise ValueError("steps must be declared in engage_at order")
        self.batch_token_cap = int(batch_token_cap)
        self.dwell_s = float(dwell_s)
        self.retry_after_base_s = float(retry_after_base_s)
        self.retry_budget = retry_budget or RetryBudget()
        self.labels = dict(labels) if labels else {}
        self.tenant = tenant
        # a labeled (per-tenant) ladder gets its own gauge series; the
        # unlabeled fleet ladder keeps the module-level one so existing
        # dashboards read byte-identically
        self._g_level = (_M_LEVEL if not self.labels else _registry.gauge(
            "brownout.level", labels=self.labels,
            help="current brownout ladder level (0 = normal)"))
        self._clock = clock
        self._lock = threading.Lock()
        self._level = 0            # 0 = normal, i = steps[i-1] engaged
        self._below_since = None   # pressure <= release_at continuously
        self.history = []          # bounded [(t, "engage"/"release", step)]
        self.pressure = 0.0        # last observed (report convenience)

    # ---- state machine ----------------------------------------------------
    def observe(self, pressure, now=None):
        """One control-cadence sample of fleet pressure (0..1). Returns
        the (possibly changed) level. At most one rung of movement per
        call, so engagement events always fire in the declared order."""
        now = self._clock() if now is None else now
        with self._lock:
            self.pressure = float(pressure)
            lvl = self._level
            if lvl < len(self.steps) \
                    and pressure >= self.steps[lvl].engage_at:
                self._level = lvl + 1
                self._below_since = None
                step = self.steps[lvl]
                self.history.append((now, "engage", step.name))
                del self.history[:-64]
                _registry.counter(
                    "brownout.engaged",
                    labels={"step": step.name, **self.labels},
                    help="brownout rung engagements per declared step").inc()
                self._g_level.set(self._level)
                return self._level
            if lvl > 0 and pressure <= self.steps[lvl - 1].release_at:
                if self._below_since is None:
                    self._below_since = now
                elif now - self._below_since >= self.dwell_s:
                    step = self.steps[lvl - 1]
                    self._level = lvl - 1
                    self._below_since = now  # dwell again per rung
                    self.history.append((now, "release", step.name))
                    del self.history[:-64]
                    _registry.counter(
                        "brownout.released",
                        labels={"step": step.name, **self.labels},
                        help="brownout rung releases per declared step").inc()
                    self._g_level.set(self._level)
            else:
                self._below_since = None
        return self._level

    @property
    def level(self):
        return self._level

    def step_name(self, level=None):
        lvl = self._level if level is None else level
        return self.steps[lvl - 1].name if lvl else None

    def _engaged_at_least(self, step_name):
        for i, s in enumerate(self.steps):
            if s.name == step_name:
                return self._level >= i + 1
        return False

    # ---- submit-time policy queries ---------------------------------------
    def retry_after_s(self):
        """The backoff the server demands right now — grows with the
        ladder level so deeper brownout pushes clients further away."""
        return self.retry_after_base_s * (1 + self._level)

    def token_cap(self, slo, reserve_class):
        """max_new_tokens cap for this class (None = unclamped): batch
        classes are clamped from ``clamp_tokens`` up, the reserve
        (interactive) class never is."""
        if slo.name == reserve_class:
            return None
        if self._engaged_at_least(CLAMP_TOKENS):
            return self.batch_token_cap
        return None

    def extras_enabled(self):
        """False from ``shed_extras`` up: hedged/speculative extras,
        affinity probing, and per-request trace minting are off."""
        return not self._engaged_at_least(SHED_EXTRAS)

    def peer_fetch_enabled(self):
        """False from ``shed_peer_fetch`` up: the KV fabric skips the
        peer-fetch tier and falls straight through to local recompute
        (counted ``kv.fallthrough{reason=peer_fetch_shed}`` when a
        candidate actually existed)."""
        return not self._engaged_at_least(SHED_PEER_FETCH)

    def prefill_depth_cap(self):
        """Max concurrent chunked prefills per replica (None = uncapped):
        from ``shed_prefill_depth`` up, a replica already advancing
        ``prefill_depth_cap`` prompts defers admitting new prefill work,
        so queued prompts trade a little admission latency for the
        in-flight requests' decode cadence. The cap halves at each deeper
        rung (floor 1) — deeper brownout serializes prefills entirely."""
        if not self._engaged_at_least(SHED_PREFILL_DEPTH):
            return None
        for i, s in enumerate(self.steps):
            if s.name == SHED_PREFILL_DEPTH:
                return max(1, 2 >> (self._level - i - 1))
        return None

    def check_admission(self, slo, reserve_class):
        """Raise the machine-readable Overloaded for classes the current
        rung sheds (called by submit BEFORE the queue-bound check)."""
        if self._engaged_at_least(REJECT):
            shed_step = REJECT
        elif self._engaged_at_least(SHED_BATCH) \
                and slo.name != reserve_class:
            shed_step = SHED_BATCH
        else:
            return
        raise Overloaded(
            f"brownout level {self._level} ({self.step_name()}): shedding "
            f"{slo.name!r} traffic; retry after "
            f"{self.retry_after_s():.2f}s",
            retry_after_s=self.retry_after_s(), level=self._level,
            step=shed_step, slo_class=slo.name, tenant=self.tenant)

    def check_retry(self, slo):
        """A retry must withdraw a whole token from its class budget or
        be rejected on the spot — the valve that keeps a client herd's
        re-submissions from re-saturating a recovering fleet."""
        if self.retry_budget.try_consume(slo.name):
            return
        _registry.counter(
            "brownout.retry_denied",
            labels={"slo_class": slo.name, **self.labels},
            help="retries rejected because the class retry budget was "
                 "exhausted").inc()
        raise Overloaded(
            f"retry budget exhausted for class {slo.name!r}; retry after "
            f"{self.retry_after_s():.2f}s",
            retry_after_s=self.retry_after_s(), level=self._level,
            step="retry_budget", slo_class=slo.name, tenant=self.tenant)

    def on_accepted(self, slo):
        self.retry_budget.on_accepted(slo.name)

    # ---- report ------------------------------------------------------------
    def report(self):
        with self._lock:
            return {
                "level": self._level,
                "tenant": self.tenant,
                "step": self.step_name(),
                "pressure": round(self.pressure, 4),
                "steps": [{"name": s.name, "engage_at": s.engage_at,
                           "release_at": s.release_at}
                          for s in self.steps],
                "retry_after_s": round(self.retry_after_s(), 4),
                "history": [(round(t, 3), kind, name)
                            for t, kind, name in self.history[-16:]],
            }
