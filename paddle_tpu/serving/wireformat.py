"""Non-executable wire encoding for handoff bundles and fabric entries.

Everything that crosses the KV wire — handoff bundles, fabric spill
entries — used to be a pickle, which made ``loads`` itself the attack
surface: the transport has no peer authentication, so ANY reachable
endpoint (or a MITM on the segment) could return a crafted pickle and
execute code in the frontend before a single digest check ran. This
module closes that hole structurally instead of cryptographically: the
encoding simply cannot express code.

Format::

    >Q spec-length | UTF-8 JSON spec | raw array heap

The JSON spec is the value tree; binary leaves are markers referencing
the heap. Exactly these Python types are expressible, nothing else:

    None / bool / int / float / str      plain JSON
    bytes                                {"b": "<hex>"}
    tuple                                {"t": [...]}
    list                                 {"l": [...]}
    dict (str keys)                      {"d": {...}}
    numpy.ndarray                        {"a": [dtype, shape, off, nbytes]}

Decoding is ``json.loads`` plus ``np.frombuffer`` against a dtype
allowlist with offset/length bounds checks — no object construction, no
imports, no callables. A malformed spec raises :class:`WireFormatError`
(a ``ValueError``), which the callers' digest gates convert to their
typed corrupt errors.

Trust model (documented here because this IS the trust boundary): the
blob/bundle frame digest is unkeyed and guards against torn frames and
bit rot, not against an adversary — an attacker who owns the wire can
forge a self-consistent frame. What they get for it is a *refused*
entry, never code execution: the decoder is non-executable, and adoption
is still gated behind the independent keyed page-digest-chain
recomputation against the digests the REQUESTER derived locally
(:meth:`KVFabric._validate`, :meth:`HandoffBundle.verify_prompt_digests`).
A hostile wire can cost latency, never a wrong token and never control
of the process.
"""
import json
import math
import struct

import numpy as np

__all__ = ["WireFormatError", "encode", "decode"]

_JLEN = struct.Struct(">Q")

#: the ONLY dtypes the decoder will materialize — numeric data, no
#: object/void/structured dtypes (those are pickle's attack surface
#: wearing a numpy hat)
_DTYPES = {name: np.dtype(name) for name in (
    "bool", "int8", "int16", "int32", "int64",
    "uint8", "uint16", "uint32", "uint64",
    "float16", "float32", "float64")}
try:                                    # KV pools may be bfloat16 on TPU
    import ml_dtypes

    _DTYPES["bfloat16"] = np.dtype(ml_dtypes.bfloat16)
except ImportError:                     # pragma: no cover - baked into image
    pass


class WireFormatError(ValueError):
    """The bytes do not decode under this format (or the tree holds a
    type the format refuses to express). Callers at the digest gate
    surface this as their typed corrupt error."""


def _enc(node, heap):
    if node is None or isinstance(node, (bool, str)):
        return node
    if isinstance(node, (int, float)):
        return node
    if isinstance(node, np.generic):        # numpy scalar -> python scalar
        return _enc(node.item(), heap)
    if isinstance(node, (bytes, bytearray, memoryview)):
        return {"b": bytes(node).hex()}
    if isinstance(node, tuple):
        return {"t": [_enc(v, heap) for v in node]}
    if isinstance(node, list):
        return {"l": [_enc(v, heap) for v in node]}
    if isinstance(node, dict):
        out = {}
        for k, v in node.items():
            if not isinstance(k, str):
                raise WireFormatError(
                    f"dict key {k!r} is not a str: not wire-encodable")
            out[k] = _enc(v, heap)
        return {"d": out}
    if isinstance(node, np.ndarray):
        a = np.ascontiguousarray(node)
        name = a.dtype.name
        if name not in _DTYPES:
            raise WireFormatError(f"dtype {name!r} is not wire-encodable")
        off = len(heap)
        heap.extend(a.tobytes())
        return {"a": [name, list(a.shape), off, a.nbytes]}
    raise WireFormatError(
        f"type {type(node).__name__} is not wire-encodable")


def encode(tree):
    """Serialize ``tree`` (the closed type set above) to bytes."""
    heap = bytearray()
    spec = json.dumps(_enc(tree, heap), separators=(",", ":")).encode("utf-8")
    return _JLEN.pack(len(spec)) + spec + bytes(heap)


def _dec(node, heap):
    if node is None or isinstance(node, (bool, int, float, str)):
        return node
    if isinstance(node, dict) and len(node) == 1:
        (tag, val), = node.items()
        if tag == "b" and isinstance(val, str):
            try:
                return bytes.fromhex(val)
            except ValueError:
                raise WireFormatError("malformed hex bytes leaf")
        if tag == "t" and isinstance(val, list):
            return tuple(_dec(v, heap) for v in val)
        if tag == "l" and isinstance(val, list):
            return [_dec(v, heap) for v in val]
        if tag == "d" and isinstance(val, dict):
            return {k: _dec(v, heap) for k, v in val.items()}
        if tag == "a" and isinstance(val, list) and len(val) == 4:
            name, shape, off, nbytes = val
            dt = _DTYPES.get(name)
            if (dt is None or not isinstance(shape, list)
                    or not all(isinstance(d, int) and d >= 0 for d in shape)
                    or not isinstance(off, int) or not isinstance(nbytes, int)
                    or off < 0 or nbytes < 0 or off + nbytes > len(heap)
                    or math.prod(shape) * dt.itemsize != nbytes):
                raise WireFormatError("malformed array leaf")
            return np.frombuffer(
                heap[off:off + nbytes], dt).reshape(shape)
    raise WireFormatError(f"unknown spec node {node!r:.80}")


def decode(data):
    """Inverse of :func:`encode`. Raises :class:`WireFormatError` on any
    structural defect; never constructs anything beyond the closed type
    set (decoded arrays are read-only views into ``data``)."""
    if len(data) < _JLEN.size:
        raise WireFormatError("wire payload shorter than its header")
    (jlen,) = _JLEN.unpack_from(data)
    if len(data) < _JLEN.size + jlen:
        raise WireFormatError("wire spec truncated")
    try:
        spec = json.loads(bytes(data[_JLEN.size:_JLEN.size + jlen])
                          .decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise WireFormatError(f"wire spec unparsable: {e}")
    return _dec(spec, memoryview(data)[_JLEN.size + jlen:])
