"""Multi-tenant serving plane (ISSUE 19 tentpole, part 1): the tenant
registry with token-bucket quota admission and per-tenant isolation
state.

One noisy customer must not degrade every other customer's TTFT. The
construction layers ABOVE the EDF scheduler in
``ServingFrontend.submit(tenant=...)``:

- **quota admission** — each :class:`Tenant` owns a token bucket
  (``quota_rps`` refill, ``burst`` capacity) plus an in-flight request
  cap. An over-quota submit is shed with a typed
  ``Overloaded(step="tenant_quota", tenant=..., retry_after_s=...)``
  where ``retry_after_s`` is computed from the bucket's refill deficit
  (how long until one whole token exists), not a constant — the client's
  backoff demand is exactly the server's arithmetic.
- **per-tenant isolation** — every tenant carries its OWN brownout
  ladder (labeled metric series, tenant-stamped rejections) and, via the
  frontend, its own SLO burn-rate monitor and retry budget: a storming
  tenant walks the rung ladder and burns its budget alone while the
  fleet — and every other tenant — stays green.
- **bounded identity** — tenants are DECLARED (registered) up front;
  :meth:`TenantRegistry.resolve` raises on unknown names instead of
  minting state per request-supplied string. That bound is what makes
  the ``tenant=`` metric label safe (no unbounded label cardinality —
  the ``tenant-label-bounded`` analysis rule pins the code shape) and
  the registry itself O(declared tenants) forever. Untenanted traffic
  maps to the ``"default"`` tenant, unlimited unless
  ``PADDLE_TENANCY_DEFAULT_QUOTA_RPS`` says otherwise — the pre-tenancy
  API is byte-compatible.

Policy only — no threads, no engine access, injectable clock; the
frontend consults ``admit``/``acquire_slot`` at submit time and feeds
each tenant's ladder from its monitor tick (docs/SERVING.md).
"""
import re
import threading
import time

from ..observability.metrics import registry as _registry
from ..utils.envs import env_float, env_int
from .brownout import BrownoutLadder
from .scheduler import Overloaded

__all__ = ["Tenant", "TenantRegistry", "DEFAULT_TENANT"]

#: the tenant untenanted traffic maps to (byte-compat with the pre-ISSUE-19
#: submit path: unlimited quota unless the env says otherwise)
DEFAULT_TENANT = "default"

#: declared-name shape: metric-label-safe, path-safe, bounded length
_NAME_RE = re.compile(r"^[A-Za-z0-9][A-Za-z0-9_.\-]{0,63}$")


class Tenant:
    """One declared tenant: identity, quota, and isolation state.

    ``quota_rps <= 0`` means unlimited (no bucket accounting at all);
    ``burst`` defaults to ``max(1, quota_rps)`` — a tenant may always
    spend its steady-state second in one gulp. ``max_inflight`` bounds
    concurrently-running requests independently of arrival rate (a
    tenant of slow, long requests can saturate a fleet at 1 rps).
    ``adapters`` is an optional allowlist of LoRA adapter names/digests
    this tenant may request (empty = any registered adapter).
    """

    def __init__(self, name, slo_class=None, quota_rps=0.0, burst=None,
                 max_inflight=None, adapters=(), brownout=None,
                 clock=time.monotonic):
        if not _NAME_RE.match(str(name)):
            raise ValueError(
                f"tenant name {name!r} must match {_NAME_RE.pattern} "
                f"(it becomes a metric label and a report key)")
        self.name = str(name)
        self.slo_class = slo_class
        self.quota_rps = float(quota_rps)
        self.burst = (float(burst) if burst is not None
                      else max(1.0, self.quota_rps))
        if self.burst < 1.0:
            raise ValueError(f"tenant {name!r}: burst must be >= 1")
        self.max_inflight = (int(max_inflight)
                             if max_inflight is not None else None)
        self.adapters = tuple(adapters or ())
        self._clock = clock
        self._lock = threading.Lock()
        self._tokens = self.burst
        self._refill_t = self._clock()
        self._inflight = 0
        # private isolation plane: this tenant's brownout ladder (labeled
        # series + tenant-stamped Overloaded) and, via the ladder, its own
        # retry budget — a storming tenant browns out ALONE
        self.brownout = brownout or BrownoutLadder(
            labels={"tenant": self.name}, tenant=self.name, clock=clock)
        self._m_admitted = _registry.counter(
            "tenant.admitted", labels={"tenant": self.name},
            help="requests admitted past the tenant quota layer")
        self._m_shed = _registry.counter(
            "tenant.shed", labels={"tenant": self.name},
            help="submits shed by the tenant layer (quota, inflight cap, "
                 "or the tenant's private brownout ladder)")
        self._g_inflight = _registry.gauge(
            "tenant.inflight", labels={"tenant": self.name},
            help="this tenant's requests currently queued or running")

    # ---- token bucket ------------------------------------------------------
    def _refill_locked(self, now):
        if self.quota_rps <= 0:
            return
        dt = max(0.0, now - self._refill_t)
        self._refill_t = now
        self._tokens = min(self.burst, self._tokens + dt * self.quota_rps)

    def admit(self, now=None):
        """Withdraw one token or shed. The typed rejection's
        ``retry_after_s`` is the refill deficit — the exact seconds until
        one whole token exists at ``quota_rps`` — so an honoring client
        retries the moment it can succeed and not before."""
        if self.quota_rps <= 0:
            return
        now = self._clock() if now is None else now
        with self._lock:
            self._refill_locked(now)
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                return
            deficit = (1.0 - self._tokens) / self.quota_rps
        self._m_shed.inc()
        raise Overloaded(
            f"tenant {self.name!r} over quota ({self.quota_rps} rps, "
            f"burst {self.burst}); retry after {deficit:.3f}s",
            retry_after_s=deficit, step="tenant_quota", tenant=self.name)

    def tokens(self, now=None):
        """Current bucket level (refilled to now) — report/test surface."""
        if self.quota_rps <= 0:
            return self.burst
        now = self._clock() if now is None else now
        with self._lock:
            self._refill_locked(now)
            return self._tokens

    # ---- inflight cap ------------------------------------------------------
    def acquire_slot(self):
        """Count one queued/running request against ``max_inflight``; the
        frontend releases at the handle's terminal transition. The shed's
        ``retry_after_s`` is one steady-state inter-arrival gap (there is
        no refill clock to derive a deficit from — a slot frees when some
        request finishes, which the quota rate approximates)."""
        with self._lock:
            if (self.max_inflight is not None
                    and self._inflight >= self.max_inflight):
                retry = (max(1.0 / self.quota_rps, 0.05)
                         if self.quota_rps > 0 else 0.5)
                inflight = self._inflight
            else:
                self._inflight += 1
                self._g_inflight.set(self._inflight)
                return
        self._m_shed.inc()
        raise Overloaded(
            f"tenant {self.name!r} at max_inflight={self.max_inflight} "
            f"({inflight} in flight); retry after {retry:.3f}s",
            retry_after_s=retry, step="tenant_inflight", tenant=self.name)

    def release_slot(self):
        with self._lock:
            self._inflight = max(0, self._inflight - 1)
            self._g_inflight.set(self._inflight)

    @property
    def inflight(self):
        return self._inflight

    def count_shed(self):
        """One shed attributed to this tenant by a layer outside this
        class (the tenant's private brownout ladder / retry budget —
        the frontend's catch site calls this)."""
        self._m_shed.inc()

    def count_admitted(self):
        self._m_admitted.inc()

    # ---- isolation plane ---------------------------------------------------
    def pressure(self):
        """This tenant's OWN pressure (0..1) for its private ladder: how
        close it runs to its declared bounds — bucket drained and/or
        inflight cap reached — not how pressed the fleet is."""
        p = 0.0
        if self.quota_rps > 0:
            p = max(p, 1.0 - self.tokens() / self.burst)
        if self.max_inflight:
            p = max(p, min(1.0, self._inflight / self.max_inflight))
        return p

    def allows_adapter(self, adapter):
        """True when ``adapter`` (a LoRAAdapter, or a name/digest) is in
        this tenant's allowlist (empty allowlist = any adapter)."""
        if not self.adapters:
            return True
        refs = {adapter} if isinstance(adapter, str) else {
            getattr(adapter, "name", None), getattr(adapter, "digest", None)}
        return bool(refs & set(self.adapters))

    def report(self):
        return {
            "slo_class": self.slo_class,
            "quota_rps": self.quota_rps,
            "burst": self.burst,
            "tokens": round(self.tokens(), 3),
            "max_inflight": self.max_inflight,
            "inflight": self._inflight,
            "adapters": list(self.adapters),
            "pressure": round(self.pressure(), 4),
            "shed": self._m_shed.value,
            "admitted": self._m_admitted.value,
            "brownout": self.brownout.report(),
        }

    def __repr__(self):
        return (f"Tenant({self.name!r}, quota_rps={self.quota_rps}, "
                f"burst={self.burst}, max_inflight={self.max_inflight})")


class TenantRegistry:
    """The bounded set of declared tenants.

    ``resolve(None)`` maps untenanted traffic to the auto-created
    ``"default"`` tenant (unlimited unless
    ``PADDLE_TENANCY_DEFAULT_QUOTA_RPS`` > 0 — byte-compatible with the
    pre-tenancy submit path); ``resolve(<unknown name>)`` raises
    ``ValueError`` — tenants are declared, never minted per request,
    which is the whole label-cardinality/bounded-state contract."""

    def __init__(self, tenants=(), default=None, max_tenants=None):
        self.max_tenants = (env_int("PADDLE_TENANCY_MAX_TENANTS", 64)
                            if max_tenants is None else int(max_tenants))
        self._lock = threading.Lock()
        self._tenants = {}
        self.default = default or Tenant(
            DEFAULT_TENANT,
            quota_rps=env_float("PADDLE_TENANCY_DEFAULT_QUOTA_RPS", 0.0))
        self.register(self.default)
        for t in tenants:
            self.register(t)

    def register(self, tenant):
        """Declare a tenant (bounded; duplicate names refused)."""
        if not isinstance(tenant, Tenant):
            raise TypeError(f"register() takes a Tenant, got {tenant!r}")
        with self._lock:
            if tenant.name in self._tenants:
                raise ValueError(f"tenant {tenant.name!r} already declared")
            if len(self._tenants) >= self.max_tenants:
                raise ValueError(
                    f"tenant registry full ({self.max_tenants}; "
                    f"PADDLE_TENANCY_MAX_TENANTS)")
            self._tenants[tenant.name] = tenant
        return tenant

    def resolve(self, tenant):
        """None | name | Tenant -> the declared Tenant (unknown raises)."""
        if tenant is None:
            return self.default
        if isinstance(tenant, Tenant):
            tenant = tenant.name
        with self._lock:
            try:
                return self._tenants[tenant]
            except KeyError:
                raise ValueError(
                    f"unknown tenant {tenant!r}; declared: "
                    f"{sorted(self._tenants)}") from None

    def names(self):
        with self._lock:
            return sorted(self._tenants)

    def tenants(self):
        with self._lock:
            return list(self._tenants.values())

    def __len__(self):
        with self._lock:
            return len(self._tenants)

    def __contains__(self, name):
        with self._lock:
            return name in self._tenants

    def report(self):
        with self._lock:
            items = sorted(self._tenants.items())
        return {name: t.report() for name, t in items}
