"""Cluster KV-page fabric: a tiered prefix cache with typed degradation.

The engine's per-replica prefix index (PR 6) reuses KV pages only when
the SAME replica saw the prefix. This module widens that to the cluster
via a tier ladder — each tier strictly cheaper than the next, each
failure a typed fallthrough to the one below, recompute the
unconditional floor:

    device pool   — the engine's own prefix index (free; not this module,
                    but advertised into the residency map so peers know)
    host spill    — :class:`HostSpillRing`, a bounded LRU of framed
                    entries evicted/spilled from the device pool
    peer fetch    — :meth:`WireTransport.fetch_blob` from a replica that
                    advertised the prefix, digest-validated on arrival
    recompute     — prefill from scratch; always correct, always there

The robustness contract is the headline: **a failed fetch is strictly
cheaper than a wrong one.** Every failure mode — torn frame, digest
mismatch, fetch timeout, peer death mid-stream, partition, brownout
shed — ends in a typed ``kv.fallthrough{reason=}`` plus transparent
recompute, bit-identical to the no-fabric path (the sampled key stream
depends only on (seed, rid, index), never on where the KV pages came
from). A pure miss is not a fallthrough and is not counted.

Residency: replicas advertise which prefixes they hold
(:meth:`advertise_prompt` / :meth:`spill`); the map feeds the router's
transfer-discounted peer-affinity term (:meth:`resident_owners`), the
fleet rollup (``fleet.serving.kv_resident``), and ``/kvz``. The
supervisor evicts a dead replica's advertisements
(:meth:`evict_replica`) — a corpse must not attract placements.

Keying: an entry for the first ``n`` pages of a prompt is keyed
``digests[n-1].hex() + ":" + n`` using the chained keyed blake2b page
digests (:func:`.handoff.page_digests`). Chained digests of shared
prefixes are equal, so an n-page entry hits ANY longer prompt at n —
partial-prefix reuse with no payload slicing and no prompt-token keys
on the wire.

Chaos seam: ``serving.kv.fetch`` fires per peer-fetch attempt; the
transport adds ``serving.kv.{timeout,partition,corrupt}``. Together the
four make every fallthrough row a deterministic drill (docs/CHAOS.md).
"""
import threading
import time
from collections import OrderedDict

import numpy as np

from ..observability.metrics import registry as _registry
from ..testing import chaos
from . import wireformat
from .handoff import HandoffCorruptError, HandoffError, page_digests
from .transport import frame_blob, unframe_blob
from ..utils.envs import env_bool, env_float, env_int

__all__ = ["KVFabric", "HostSpillRing"]

_M_FALLTHROUGHS = _registry.counter(
    "kv.fallthroughs", help="total typed tier-ladder fallthroughs")
_M_FETCH_S = _registry.histogram(
    "kv.fetch_s", help="peer KV-prefix fetch latency (success only)")
_G_SPILL = _registry.gauge(
    "kv.spill_bytes", help="bytes resident in the host spill ring")
_G_RESIDENCY = _registry.gauge(
    "kv.residency", help="advertised prefix entries across the cluster")


def _hit(tier):
    _registry.counter("kv.hits", labels={"tier": tier},
                      help="prefix-cache hits by tier").inc()


class HostSpillRing:
    """Bounded LRU of framed spill entries — the host-RAM tier.

    Both bounds are hard: inserting past ``max_bytes`` or ``max_entries``
    evicts from the LRU end until the new entry fits. ``put`` returns
    the evicted keys so the fabric can retract their residency
    advertisements (a retracted lie is a miss; an unretracted one is a
    partition drill on every placement). An entry larger than the byte
    bound is refused outright — one monster prefix must not flush the
    whole ring.
    """

    def __init__(self, max_bytes=None, max_entries=None):
        self.max_bytes = (env_int("PADDLE_KV_SPILL_MB", 64) * (1 << 20)
                          if max_bytes is None else int(max_bytes))
        self.max_entries = (env_int("PADDLE_KV_SPILL_ENTRIES", 256)
                            if max_entries is None else int(max_entries))
        self._lock = threading.Lock()
        self._ring = OrderedDict()          # key -> framed bytes
        self._nbytes = 0

    def __len__(self):
        with self._lock:
            return len(self._ring)

    @property
    def nbytes(self):
        return self._nbytes

    def put(self, key, framed):
        """Insert (or refresh) an entry; returns the list of keys
        evicted to make room (empty when none, ``[key]`` itself when the
        entry is larger than the ring)."""
        size = len(framed)
        evicted = []
        with self._lock:
            old = self._ring.pop(key, None)
            if old is not None:
                self._nbytes -= len(old)
            if size > self.max_bytes:
                self._set_gauge()
                return [key]
            self._ring[key] = framed
            self._nbytes += size
            while (self._nbytes > self.max_bytes
                   or len(self._ring) > self.max_entries):
                k, v = self._ring.popitem(last=False)
                self._nbytes -= len(v)
                evicted.append(k)
            self._set_gauge()
        return evicted

    def get(self, key):
        with self._lock:
            framed = self._ring.get(key)
            if framed is not None:
                self._ring.move_to_end(key)
            return framed

    def discard(self, key):
        with self._lock:
            framed = self._ring.pop(key, None)
            if framed is not None:
                self._nbytes -= len(framed)
                self._set_gauge()

    def _set_gauge(self):
        _G_SPILL.set(self._nbytes)


def prefix_key(digests, n_pages):
    """Registry key for the first ``n_pages`` pages: the chain tail
    identifies the whole prefix (each link is keyed by the previous)."""
    return digests[n_pages - 1].hex() + ":" + str(n_pages)


class KVFabric:
    """Per-frontend view of the cluster KV-page fabric.

    ``transport`` is a :class:`.transport.WireTransport` (or None for a
    spill-ring-only fabric — still useful single-host). Peers register
    via :meth:`register_peer` with either a wire ``"host:port"``
    endpoint string or a callable ``fetcher(key) -> framed bytes|None``
    (tests inject failure shapes without a socket).

    Locking: ``_lock`` guards the residency maps and peer table only.
    Digest-chain computation, ring access, and — critically — peer
    fetches all run OUTSIDE it; candidates are snapshotted under the
    lock, then dialed after release (the blocking-under-lock contract:
    a slow peer must never stall advertise/evict).
    """

    def __init__(self, name="frontend", transport=None, spill=None,
                 clock=time.monotonic):
        self.name = name
        self.enabled = env_bool("PADDLE_KV_FABRIC", True)
        self.transport = transport
        # `is None`, not `or`: a freshly constructed ring is empty and
        # therefore falsy (__len__ == 0) — `or` would silently drop it
        self.spill = spill if spill is not None else HostSpillRing()
        self.clock = clock
        self._lock = threading.Lock()
        self._residency = {}                # key -> set of owner names
        self._by_owner = {}                 # owner -> set of keys
        self._peers = {}                    # owner -> endpoint str | callable
        # capacity-aware peer selection (ISSUE 19 satellite): advisory
        # 0..1 load per peer, stamped by the frontend monitor every tick.
        # Candidates rank least-loaded-first and peers at/above the
        # saturation threshold are skipped outright — fetching from a
        # saturated peer steals exactly the capacity it is short of.
        self._peer_load = {}                # owner -> advertised load
        self.peer_saturation = env_float("PADDLE_KV_PEER_SATURATION", 0.95)

    # ---- residency --------------------------------------------------------
    def _advertise(self, key, owner):
        with self._lock:
            self._residency.setdefault(key, set()).add(owner)
            self._by_owner.setdefault(owner, set()).add(key)
            _G_RESIDENCY.set(len(self._residency))

    def _retract(self, key, owner):
        with self._lock:
            owners = self._residency.get(key)
            if owners is not None:
                owners.discard(owner)
                if not owners:
                    self._residency.pop(key, None)
            keys = self._by_owner.get(owner)
            if keys is not None:
                keys.discard(key)
            _G_RESIDENCY.set(len(self._residency))

    def advertise_prompt(self, prompt, page_size, owner):
        """Advertise every full-page prefix of ``prompt`` as resident on
        ``owner`` (the device tier: the owner's engine indexed these
        pages — peers may fetch or route toward them)."""
        if not self.enabled:
            return
        p = np.asarray(prompt, np.int32).reshape(-1)
        n = len(p) // int(page_size)
        if n <= 0:
            return
        digs = page_digests(p, int(page_size), n)
        for j in range(1, n + 1):
            self._advertise(prefix_key(digs, j), owner)

    def evict_replica(self, owner):
        """Drop every advertisement and the peer registration for a dead
        replica — the supervisor's hook. A corpse must neither attract
        router placements nor be dialed for fetches."""
        with self._lock:
            keys = self._by_owner.pop(owner, set())
            for key in keys:
                owners = self._residency.get(key)
                if owners is not None:
                    owners.discard(owner)
                    if not owners:
                        self._residency.pop(key, None)
            self._peers.pop(owner, None)
            self._peer_load.pop(owner, None)
            _G_RESIDENCY.set(len(self._residency))
        return len(keys)

    def residency_count(self, owner):
        with self._lock:
            return len(self._by_owner.get(owner, ()))

    def resident_owners(self, prompt, page_size):
        """{owner: resident_fraction} over the cluster for ``prompt`` —
        ONE digest pass, called once per router placement, OUTSIDE the
        router lock. Fraction = longest advertised prefix / total full
        pages, so the router's peer-affinity term is comparable to the
        engine's own ``prefix_match_pages`` score."""
        if not self.enabled:
            return {}
        p = np.asarray(prompt, np.int32).reshape(-1)
        n = len(p) // int(page_size)
        if n <= 0:
            return {}
        digs = page_digests(p, int(page_size), n)
        best = {}
        with self._lock:
            for j in range(n, 0, -1):
                for owner in self._residency.get(prefix_key(digs, j), ()):
                    if owner not in best:
                        best[owner] = j / n
        return best

    def register_peer(self, owner, fetcher):
        """``fetcher``: a wire endpoint string (dialed via the
        transport) or a callable ``key -> framed bytes|None``."""
        with self._lock:
            self._peers[owner] = fetcher

    # ---- spill ------------------------------------------------------------
    def spill_prefix(self, prompt, page_size, payload, owner=None):
        """Spill ``payload`` (the engine's opaque page export for every
        full page of ``prompt``) into the host ring, publish it to the
        wire store when a transport is attached, and advertise it.
        Returns the entry key."""
        if not self.enabled:
            return None
        owner = owner or self.name
        p = np.asarray(prompt, np.int32).reshape(-1)
        n = len(p) // int(page_size)
        if n <= 0:
            return None
        digs = page_digests(p, int(page_size), n)
        key = prefix_key(digs, n)
        entry = {"n_pages": n, "page_size": int(page_size),
                 "prompt": p[:n * int(page_size)], "payload": payload}
        framed = frame_blob(wireformat.encode(entry))
        evicted = self.spill.put(key, framed)
        for k in evicted:
            if k != key:
                self._retract(k, owner)
        if key in evicted:              # larger than the whole ring
            return None
        if self.transport is not None:
            try:
                self.transport.put_blob(key, framed)
            except HandoffError:
                pass        # ring copy still serves; wire copy is best-effort
        self._advertise(key, owner)
        return key

    # ---- the tier ladder --------------------------------------------------
    def acquire(self, prompt, page_size, allow_peer=True):
        """Walk the ladder for the longest reusable prefix of ``prompt``.

        Returns ``(entry, tier)`` — ``entry`` the dict stored by
        :meth:`spill_prefix`, ``tier`` in {"host", "peer"} — or None,
        meaning: recompute (the caller's unconditional floor). The
        device tier is not visible here; the engine consults its own
        prefix index before the frontend ever calls this.

        Failure taxonomy: every PEER failure is a counted typed
        fallthrough (timeout / partition / corrupt / fetch_failed /
        peer_fetch_shed); a corrupt RING entry is discarded, counted,
        and the walk continues; a pure miss returns None uncounted.
        """
        if not self.enabled:
            return None
        p = np.asarray(prompt, np.int32).reshape(-1)
        n = len(p) // int(page_size)
        if n <= 0:
            return None
        digs = page_digests(p, int(page_size), n)

        # host tier: longest spilled prefix wins
        for j in range(n, 0, -1):
            key = prefix_key(digs, j)
            framed = self.spill.get(key)
            if framed is None:
                continue
            try:
                entry = self._validate(framed, digs, j, int(page_size))
            except HandoffCorruptError:
                self.spill.discard(key)
                self._retract(key, self.name)
                self.count_fallthrough("corrupt")
                continue
            _hit("host")
            return entry, "host"

        if not allow_peer:
            # counted only when shedding actually cost us candidates —
            # a shed miss is still just a miss
            if self._peer_candidates(digs, n):
                self.count_fallthrough("peer_fetch_shed")
            return None

        # peer tier: snapshot candidates under the lock, dial outside it
        for key, j, owner, fetcher in self._peer_candidates(
                digs, n, count_saturated=True):
            t0 = self.clock()
            try:
                chaos.site("serving.kv.fetch")
                if callable(fetcher):
                    framed = fetcher(key)
                else:
                    framed = self.transport.fetch_blob(fetcher, key)
                if framed is None:
                    raise HandoffError(f"peer {owner} no longer holds {key}")
                entry = self._validate(framed, digs, j, int(page_size))
            except Exception as e:
                self.count_fallthrough(getattr(e, "reason", None) or (
                    "corrupt" if isinstance(e, HandoffCorruptError)
                    else "fetch_failed"))
                continue
            _M_FETCH_S.observe(max(0.0, self.clock() - t0))
            # cache for the next request — mirroring spill_prefix: retract
            # whatever the insert evicted, and advertise only if the entry
            # actually stayed resident (an oversize fetch is consumed here
            # and held nowhere — advertising it would be a residency lie
            # every peer dials into a guaranteed miss)
            evicted = self.spill.put(key, framed)
            for k in evicted:
                if k != key:
                    self._retract(k, self.name)
            if key not in evicted:
                self._advertise(key, self.name)
            _hit("peer")
            return entry, "peer"
        return None

    def _peer_candidates(self, digs, n, count_saturated=False):
        """[(key, n_pages, owner, fetcher)] longest-prefix-first, peers
        with a registered fetcher only, self excluded — gathered under
        the lock so the dial loop runs lock-free.

        Capacity-aware ordering (ISSUE 19 satellite): within one prefix
        length, peers rank by advertised load ascending (name tiebreak —
        deterministic under equal load), and a peer at/above
        ``peer_saturation`` (PADDLE_KV_PEER_SATURATION) is skipped
        entirely; ``count_saturated=True`` (the real fetch walk, not the
        advisory probe) counts one ``peer_saturated`` fallthrough when
        saturation removed at least one candidate."""
        out = []
        skipped = 0
        with self._lock:
            for j in range(n, 0, -1):
                key = prefix_key(digs, j)
                ranked = []
                for owner in self._residency.get(key, ()):
                    if owner == self.name:
                        continue
                    fetcher = self._peers.get(owner)
                    if fetcher is None:
                        continue
                    load = self._peer_load.get(owner, 0.0)
                    if load >= self.peer_saturation:
                        skipped += 1
                        continue
                    ranked.append((load, owner, fetcher))
                for load, owner, fetcher in sorted(
                        ranked, key=lambda c: (c[0], c[1])):
                    out.append((key, j, owner, fetcher))
        if skipped and count_saturated:
            self.count_fallthrough("peer_saturated")
        return out

    def set_peer_load(self, owner, load):
        """Advisory 0..1 load signal for ``owner`` (the frontend monitor
        stamps every replica's blended load each tick; a cluster deploy
        would gossip it). Unknown peers read as load 0 — fetchable."""
        with self._lock:
            self._peer_load[owner] = float(load)

    def peer_load(self, owner):
        with self._lock:
            return self._peer_load.get(owner, 0.0)

    @staticmethod
    def _validate(framed, digs, n_pages, page_size):
        """The trust boundary for ring and wire entries alike: frame
        digest, a NON-EXECUTABLE decode (:mod:`.wireformat` — the wire
        has no peer auth, so the decoder must not be an interpreter),
        then an independent recomputation of the page-digest chain from
        the entry's own prompt bytes against the REQUESTED key's chain.
        Any disagreement is :class:`HandoffCorruptError` — adopting
        would risk a wrong token."""
        payload = unframe_blob(framed)
        try:
            entry = wireformat.decode(payload)
            n = int(entry["n_pages"])
            prompt = np.asarray(entry["prompt"], np.int32).reshape(-1)
        except HandoffError:
            raise
        except Exception as e:
            raise HandoffCorruptError(f"spill entry unreadable: {e}")
        if n != n_pages or int(entry.get("page_size", page_size)) != page_size:
            raise HandoffCorruptError(
                f"spill entry shape mismatch: {n} pages != {n_pages}")
        chain = page_digests(prompt, page_size, n)
        if not chain or chain[-1] != digs[n_pages - 1]:
            raise HandoffCorruptError(
                "spill entry prompt/digest chain mismatch")
        return entry

    # ---- accounting / introspection ---------------------------------------
    def count_fallthrough(self, reason):
        _M_FALLTHROUGHS.inc()
        _registry.counter("kv.fallthrough", labels={"reason": str(reason)},
                          help="tier-ladder fallthroughs by typed reason").inc()

    def report(self):
        """The ``/kvz`` payload — everything an operator needs to judge
        fabric health at a glance."""
        with self._lock:
            by_owner = {o: len(k) for o, k in self._by_owner.items() if k}
            entries = len(self._residency)
            peers = sorted(self._peers)
            peer_load = {o: round(v, 4)
                         for o, v in sorted(self._peer_load.items())}
        counters = {}
        for name in _registry.names(prefix="kv."):
            m = _registry.get(name)
            if m is not None and hasattr(m, "value"):
                counters[name] = m.value
        return {
            "enabled": self.enabled,
            "name": self.name,
            "transport": type(self.transport).__name__
            if self.transport is not None else None,
            "spill": {"entries": len(self.spill),
                      "bytes": self.spill.nbytes,
                      "max_bytes": self.spill.max_bytes,
                      "max_entries": self.spill.max_entries},
            "residency": {"entries": entries, "by_owner": by_owner},
            "peers": peers,
            "peer_load": peer_load,
            "peer_saturation": self.peer_saturation,
            "metrics": counters,
        }
