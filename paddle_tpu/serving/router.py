"""Multi-replica placement: prefix-cache affinity blended with load, plus
replica health.

The TPU serving comparison (PAPERS.md) and Ragged Paged Attention both make
the same point: above a fast attention kernel, serving throughput is won in
the layer that decides WHERE a request runs. Two requests sharing a system
prompt served by the same replica cost one prefill and a page-table
pointer; scattered across replicas they cost two full prefills and double
the pool pressure. The router therefore scores every LIVE replica as

    score = affinity_weight * prefix_fraction        # indexed pages NOW
          + hint_weight     * session_hint           # where this prefix went
          - load_weight     * replica.load()         # slots + pages + queue

and places on the argmax. ``prefix_fraction`` probes the engine's
content-addressed prefix index (read-only dict lookups — safe against the
dispatcher thread). The *session hint* covers the race the index can't: the
second request of a new prefix usually arrives before the first finishes
prefilling, so the index is still empty — the hint table remembers which
replica the prefix was last routed to and keeps the session sticky.

Health is a four-state ladder per replica — ``LIVE`` (routable),
``PROBATION`` (circuit-broken: only rate-limited probe traffic routes
there — see serving/breaker.py), ``DRAINING`` (finishes in-flight work,
admits nothing, receives no new placements), ``DEAD`` (gone; its queue is
rerouted) — driven by the PR-2 watchdog heartbeat mechanism: every
dispatcher loop stamps :meth:`ReplicaHandle.beat` (and, when
``PADDLE_TELEMETRY_DIR`` is set, launcher-format
``serving/heartbeat.<idx>.json`` files — namespaced so replica indexes
never clobber training ranks' files), and the frontend's monitor declares
a replica DEAD when its beat stays stale for ``heartbeat_misses``
consecutive monitor checks (flap damping, ISSUE 12: ONE slow scrape used
to trigger a full reroute storm — now it is a counted flap,
``serving.replica_flaps``, not a death).

When a :class:`ReplicaSupervisor` (serving/supervisor.py) manages the
fleet, each handle carries a generation ``fence`` (the PR-9 elastic
fencing contract): a superseded replica — one the supervisor already
replaced — has its late heartbeat-file and fleet-snapshot writes
rejected (``supervisor.fenced_writes``), so a zombie dispatcher can't
masquerade as its own replacement in the telemetry dir.

Chaos site ``serving.route`` fires on every placement decision so tests can
inject routing outages; ``serving.replica_kill`` (in the frontend's
dispatcher loop) kills a replica mid-flight; ``serving.replica_slow`` (in
the dispatcher's step path) stalls a busy replica's dispatch.
"""
import os
import threading
import time

from ..observability.metrics import registry as _registry
from ..testing import chaos
from ..utils.envs import env_str

__all__ = ["LIVE", "PROBATION", "DRAINING", "DEAD", "NoLiveReplicas",
           "ReplicaHandle", "Router"]

LIVE = "LIVE"
PROBATION = "PROBATION"
DRAINING = "DRAINING"
DEAD = "DEAD"

#: states a dispatcher admits work from its pending list in (PROBATION
#: admits only what the breaker's probe budget routed there)
ADMITTING = (LIVE, PROBATION)

_M_ROUTED = _registry.counter("serving.routed")
_M_AFFINITY_PLACED = _registry.counter("serving.routed_by_affinity")
_M_FENCED = _registry.counter(
    "supervisor.fenced_writes",
    help="late heartbeat/snapshot writes rejected from superseded replicas")


class NoLiveReplicas(RuntimeError):
    """Every replica is DRAINING or DEAD — nothing can take the request."""


class ReplicaHandle:
    """One engine replica as the control plane sees it: the engine, its
    pending (routed-but-not-admitted) queue, health state, and liveness
    beats. All mutable fields are guarded by the frontend's lock except
    ``last_beat`` (a monotonic float stamped only by the dispatcher and read
    by the monitor — a benign single-writer race)."""

    def __init__(self, name, engine, index=0, role="blended"):
        self.name = str(name)
        self.engine = engine
        self.index = int(index)
        # disaggregated serving role (ISSUE 16): "prefill" replicas admit
        # new prompts and hand finished prefills off, "decode" replicas
        # adopt handed-off pages and stream tokens, "blended" replicas do
        # both (the pre-disaggregation behavior, and the degradation
        # target when a pool is sick)
        self.role = str(role)
        self.state = LIVE
        self.pending = []          # routed Entry objects, scheduler-ordered
        self.inflight = {}         # rid -> Entry, admitted into the engine
        self.last_beat = time.monotonic()
        self.thread_ident = None   # stamped by the dispatcher thread itself
        self.death_reason = None
        # flap damping (ISSUE 12): consecutive monitor checks that found
        # the beat stale; written only by the monitor thread
        self.missed_beats = 0
        # supervisor bookkeeping: failure domain (restart budgets/backoff
        # are per-domain) and the generation fence a supervisor installs —
        # a superseded incarnation's late telemetry writes are rejected
        self.domain = None
        self.fence = None
        self.retired = False       # removed by scale-down, not a failure
        # dispatch-latency EWMA (seconds per step() call, stamped by the
        # dispatcher; read by the monitor's slow-replica classification)
        self.step_ewma = 0.0
        self.step_samples = 0
        # cluster KV fabric (ISSUE 18): advertised prefix entries owned by
        # this replica — stamped by the monitor tick from the fabric's
        # residency map, rolled up fleet-wide as fleet.serving.kv_resident
        self.kv_resident = 0
        # PR-2 integration: when the launcher exports PADDLE_TELEMETRY_DIR,
        # serving replicas publish launcher-format heartbeat files — in
        # their OWN serving/ subdirectory, NOT the telemetry root: replica
        # indexes overlap training rank numbers, and a replica beating
        # heartbeat.<rank>.json would mask a genuinely hung trainer of the
        # same rank from the pod HangWatchdog (and vice versa)
        self._wd_heartbeat = None
        self._wd_last_write = 0.0
        # fleet snapshot publication (ISSUE 11): dispatchers publish
        # fleetsnap files in the same serving/ namespace as their
        # heartbeats, carrying the replica's control-plane state so the
        # cluster aggregator can roll up serving cells it never imported
        self._fleet_pub = None
        d = env_str("PADDLE_TELEMETRY_DIR")
        if d:
            try:
                from ..observability.watchdog import Heartbeat

                self._wd_heartbeat = Heartbeat(os.path.join(d, "serving"),
                                               rank=self.index,
                                               install_faulthandler=False)
            except OSError:
                self._wd_heartbeat = None
            try:
                from ..observability.fleet import (
                    SnapshotPublisher,
                    process_instance,
                )

                # instance=host+pid: replica INDEXES repeat across
                # frontend processes (and pids repeat across hosts)
                # sharing one telemetry dir — the instance keeps their
                # snapshot files (and tmp paths) from colliding. Only
                # replica 0 carries the (process-shared) registry export;
                # the others publish identity + control-plane state, so N
                # dispatchers don't each serialize the full registry per
                # cadence just for the aggregator to collapse N-1 of them
                self._fleet_pub = SnapshotPublisher(
                    os.path.join(d, "serving"), rank=self.index,
                    role="replica", instance=process_instance(),
                    include_metrics=(self.index == 0),
                    extra_provider=lambda: {"replica": self.snapshot()})
            except OSError:
                self._fleet_pub = None
        # labeled series of one family each (ISSUE 7 satellite: a real
        # scraper aggregates over {replica=...}, which per-replica metric
        # NAMES made impossible)
        self._occ_gauge = _registry.gauge(
            "serving.replica.occupancy", labels={"replica": self.name},
            help="per-replica active slots / max_seqs")
        self._queue_gauge = _registry.gauge(
            "serving.replica.queue_depth", labels={"replica": self.name},
            help="per-replica routed-but-not-admitted requests")
        self._pages_gauge = _registry.gauge(
            "serving.replica.pages_in_use", labels={"replica": self.name},
            help="per-replica KV pool pages referenced")

    def beat(self, step=None):
        now = time.monotonic()
        self.last_beat = now
        # the in-memory stamp is per-loop; the FILE write (json + rename) is
        # rate-limited — an idle dispatcher loops ~200x/s and the pod
        # watchdog samples at whole-second granularity anyway
        if self._wd_heartbeat is not None and now - self._wd_last_write >= 1.0:
            self._wd_last_write = now
            if not self.fence_writable():
                return  # superseded incarnation: no telemetry writes
            try:
                self._wd_heartbeat.beat(step=step, role="serving")
            except OSError:
                pass  # full disk must not take the dispatcher down
            if self._fleet_pub is not None:
                self._fleet_pub.maybe_publish(step=step)

    def fence_writable(self):
        """PR-9 fencing contract applied to serving telemetry: a replica
        the supervisor already superseded must not publish heartbeat files
        or fleet snapshots its replacement's aggregator would trust. The
        in-memory ``last_beat`` stamp stays unfenced — liveness of the
        thread is a fact either way."""
        if self.fence is None:
            return True
        from ..distributed.fleet.elastic.fencing import StaleGenerationError

        try:
            self.fence.check(f"serving.heartbeat[{self.name}]")
        except StaleGenerationError:
            _M_FENCED.inc()
            return False
        except Exception:
            return True  # fencing fails open, exactly like PR 9
        return True

    def note_step(self, wall_s):
        """Dispatcher-side dispatch-latency sample (single writer: only
        this replica's dispatcher calls it; the monitor only reads, and a
        torn read costs one pace verdict, not correctness)."""
        self.step_samples += 1  # lint: shared-mutation-without-lock-ok (single dispatcher writer; monitor reads are advisory)
        if self.step_samples == 1:
            self.step_ewma = wall_s  # lint: shared-mutation-without-lock-ok (same single-writer contract)
        else:
            self.step_ewma += 0.2 * (wall_s - self.step_ewma)  # lint: shared-mutation-without-lock-ok (same single-writer contract)

    def publish_gauges(self):
        eng = self.engine
        self._occ_gauge.set(eng.active_count() / eng.max_seqs)
        self._queue_gauge.set(len(self.pending))
        self._pages_gauge.set(eng.pages_in_use())

    def retire_gauges(self):
        """Remove this replica's labeled per-replica series (replacement /
        scale-down): a removed name must stop exporting — a frozen stale
        gauge reads as a live zero to a scraper."""
        for fam in ("serving.replica.occupancy",
                    "serving.replica.queue_depth",
                    "serving.replica.pages_in_use"):
            _registry.remove(fam, labels={"replica": self.name})

    def load(self):
        """0..~1 pressure blend: decode slots, pool pages, queue depth. Each
        term saturates at 1 so one exhausted resource reads as heavy load
        even when the others are idle."""
        eng = self.engine
        slots = eng.active_count() / eng.max_seqs
        pages = eng.pages_in_use() / max(1, eng.num_pages - 1)
        queue = min(1.0, len(self.pending) / max(1, eng.max_seqs * 2))
        return (slots + pages + queue) / 3.0

    def pool_headroom(self):
        """Fraction of the KV pool still free (0..1) — the decode-pool
        placement signal: an adopted request arrives with its full page
        reservation already sized, so what matters is whether the pages
        fit, not whether this replica has seen the prefix before."""
        eng = self.engine
        return 1.0 - eng.pages_in_use() / max(1, eng.num_pages - 1)

    def prefix_fraction(self, prompt):
        """Fraction of this prompt's full pages already indexed here.
        O(prompt bytes): the engine's prefix index is keyed by chained
        per-page digests (ISSUE 6 satellite), not full-prefix re-hashes."""
        total = max(1, (len(prompt) - 1) // self.engine.page_size)
        return self.engine.prefix_match_pages(prompt) / total

    def snapshot(self):
        return {
            "name": self.name,
            "role": self.role,
            "state": self.state,
            "active": self.engine.active_count(),
            "max_seqs": self.engine.max_seqs,
            "pending": len(self.pending),
            "pages_in_use": self.engine.pages_in_use(),
            "load": round(self.load(), 4),
            "death_reason": self.death_reason,
            "missed_beats": self.missed_beats,
            "domain": self.domain,
            "step_ewma_s": round(self.step_ewma, 6),
            "kv_resident": self.kv_resident,
        }

    def __repr__(self):
        return f"ReplicaHandle({self.name!r}, {self.state})"


class Router:
    """Placement policy over a replica set. ``policy='prefix'`` (default)
    scores affinity+load as in the module docstring; ``policy='round_robin'``
    is the baseline the E2E test compares hit rates against; ``policy='load'``
    is pure least-loaded (affinity weights zeroed)."""

    #: tokens hashed for the session-hint key — one engine page is the
    #: natural sharing granularity, and 16 matches the default page_size
    HINT_TOKENS = 16

    def __init__(self, policy="prefix", affinity_weight=1.0, hint_weight=0.5,
                 load_weight=1.0, headroom_weight=1.0, max_hints=4096,
                 peer_affinity_discount=0.5, adapter_affinity_weight=0.5):
        if policy not in ("prefix", "round_robin", "load"):
            raise ValueError(f"unknown router policy {policy!r}")
        self.policy = policy
        self.affinity_weight = float(affinity_weight)
        self.hint_weight = float(hint_weight)
        self.load_weight = float(load_weight)
        # LoRA adapter affinity (ISSUE 19): a replica whose engine already
        # holds the request's adapter on device (its per-digest cache)
        # skips the host->device upload on admission — worth a bounded
        # nudge, weaker than prefix affinity (pages dwarf adapter weights)
        self.adapter_affinity_weight = float(adapter_affinity_weight)
        # cluster KV fabric (ISSUE 18): a prefix resident on a PEER is
        # worth something — the target can fetch instead of recompute —
        # but strictly less than local residency, because the fetch costs
        # a wire transfer and can fail. The discount scales the fabric's
        # resident-fraction before it competes with the local index term.
        self.peer_affinity_discount = float(peer_affinity_discount)
        # installed by the frontend when the fabric is enabled; consulted
        # read-only (one resident_owners() pass per placement, OUTSIDE
        # self._lock — the digest chain walk must not serialize submits)
        self.fabric = None
        # decode-pool placement weight (ISSUE 16): free-page fraction of
        # the candidate replica's KV pool — see place()'s role branch
        self.headroom_weight = float(headroom_weight)
        self.max_hints = int(max_hints)
        self._hints = {}   # prefix-head bytes -> replica name (insertion LRU)
        self._rr = 0
        # circuit breaker (ISSUE 12): installed by the frontend; when set,
        # PROBATION replicas receive rate-limited probe placements
        self.breaker = None
        # place() is called from the submit path (under the frontend lock)
        # AND from reroute/monitor paths (not under it) — the hint table and
        # rr cursor need their own lock or a concurrent LRU-evict can pop
        # the same head key twice (KeyError)
        self._lock = threading.Lock()

    def _hint_key(self, prompt):
        return prompt[:self.HINT_TOKENS].tobytes()

    def place(self, entry, replicas, exclude=(), cheap=False):
        """Pick a LIVE replica for ``entry`` (an object with ``.req``).
        ``exclude`` names replicas the request must avoid (the one that just
        died under it). Raises NoLiveReplicas when nothing can take it.
        ``cheap=True`` (brownout ``shed_extras``) skips the per-replica
        affinity probe and session hints — pure least-loaded placement.

        Pure decision — no hint writes, no counters. The frontend calls
        :meth:`committed` once the entry actually lands in a pending list,
        so a submission that is subsequently SHED (or loses the append
        race) cannot re-home a live session's hint to a replica it never
        reached, and the routing counters count real placements only."""
        chaos.site("serving.route")
        entry.probe = False
        # role targeting (ISSUE 16): a disaggregated entry names the pool
        # it needs ("prefill" before handoff, "decode" after); blended
        # replicas serve either. The filter is a PREFERENCE, not a fence —
        # when the targeted pool has no live replica the entry falls back
        # to the whole live set (the frontend's degradation ladder already
        # decided blended completion is acceptable before routing here).
        role = getattr(entry, "target_role", None)

        def _role_ok(r):
            return role is None or r.role in (role, "blended")

        if self.breaker is not None:
            # half-open probes win over normal scoring: a PROBATION
            # replica only ever sees traffic through this rate-limited
            # path, and without it there is no recovery signal at all
            for r in replicas:
                if r.state == PROBATION and r.name not in exclude \
                        and _role_ok(r) and self.breaker.allow_probe(r.name):
                    entry.probe = True
                    entry.route_affinity = False
                    entry.route_score = 0.0
                    return r
        live = [r for r in replicas
                if r.state == LIVE and r.name not in exclude]
        if role is not None:
            in_role = [r for r in live if _role_ok(r)]
            if in_role:
                live = in_role
        if not live:
            raise NoLiveReplicas(
                f"no LIVE replica for request {entry.req.rid} "
                f"(states: {[(r.name, r.state) for r in replicas]})")
        # no len(live)==1 shortcut for the scoring policies: the prefix
        # policy must still score (and later record the session hint)
        # while one replica has the pool to itself (a drain window), or
        # every session re-homes blind when the drained replica returns
        with self._lock:  # _hints read + rr cursor only — the affinity
            # probe below must not serialize concurrent submits
            # or make a replica-death relocation queue behind them
            if self.policy == "round_robin":
                pick = live[self._rr % len(live)]
                self._rr += 1
                entry.route_affinity = False
                entry.route_score = 0.0
                return pick
            prompt = entry.req.prompt
            hinted = (None if cheap
                      else self._hints.get(self._hint_key(prompt)))
        # cluster-wide prefix residency (ISSUE 18): one digest pass per
        # placement, outside self._lock. cheap=True (shed_extras) skips it
        # with the other affinity probes.
        peer_res = {}
        if (self.fabric is not None and not cheap
                and self.policy == "prefix" and role != "decode"):
            try:
                peer_res = self.fabric.resident_owners(
                    prompt, getattr(live[0].engine, "page_size", 16))
            except Exception:
                peer_res = {}
        # adapter-affinity probe (ISSUE 19): which replicas already hold
        # this request's LoRA adapter on device. Advisory (the engine's
        # digest-keyed device cache, read without its lock — a stale read
        # costs one re-upload, never correctness); skipped under cheap
        # like every other affinity probe.
        req_ad = getattr(entry.req, "adapter", None)
        if cheap or req_ad is None:
            req_ad = None
        best, best_score, best_aff = None, None, 0.0
        best_via_peer = False
        for r in live:
            if role == "decode":
                # decode placement scores pool HEADROOM, not prefix
                # affinity: the handed-off request brings its own KV —
                # what matters is whether its page reservation fits
                aff = hint = 0.0
                via_peer = False
                score = (self.headroom_weight * r.pool_headroom()
                         - self.load_weight * r.load())
            else:
                if self.policy == "load" or cheap:
                    aff = hint = 0.0
                    via_peer = False
                else:
                    local = r.prefix_fraction(prompt)
                    # peer-resident prefixes count as weaker, transfer-
                    # discounted affinity: the replica can FETCH the
                    # prefix over the fabric instead of recomputing it
                    peer = (self.peer_affinity_discount
                            * peer_res.get(r.name, 0.0))
                    aff = max(local, peer)
                    via_peer = peer > local
                    hint = 1.0 if r.name == hinted else 0.0
                lora = 0.0
                if req_ad is not None:
                    devs = getattr(r.engine, "_lora_device", None)
                    if devs is not None and req_ad.digest in devs:
                        lora = 1.0
                score = (self.affinity_weight * aff
                         + self.hint_weight * hint
                         + self.adapter_affinity_weight * lora
                         - self.load_weight * r.load())
            if best_score is None or score > best_score:
                best, best_score, best_aff = r, score, aff
                best_via_peer = via_peer
        entry.route_affinity = best_aff > 0.0 or hinted == best.name
        # a peer-residency placement is speculative until the fetch
        # actually lands: committed() defers the session-hint write and
        # adoption_landed() records it — a failed fetch (recompute
        # fallthrough) must not re-home session stickiness
        entry.kv_hint_deferred = best_via_peer
        # trace attribution (ISSUE 7): the request's trace records WHY it
        # landed where it did — the winning blended score and whether
        # affinity (index hit or session hint) carried the decision
        entry.route_score = best_score
        return best

    def committed(self, entry, rep):
        """The placement landed: record it. Counters here (not in place())
        so shed/raced submissions don't count, and the session hint only
        re-homes for requests that will actually warm ``rep``'s cache."""
        _M_ROUTED.inc()
        if entry.route_affinity:
            _M_AFFINITY_PLACED.inc()
        if getattr(entry, "probe", False):
            # a half-open probe is diagnostic traffic: it must not re-home
            # a live session's hint to a replica still under suspicion
            return
        if getattr(entry, "target_role", None) == "decode":
            # a decode-pool adoption placement carries its KV with it — it
            # must not re-home the prefix session hint away from the
            # prefill replica whose cache actually holds the prefix
            return
        if self.policy != "prefix":
            return
        if getattr(entry, "kv_hint_deferred", False):
            # peer-residency placement: the prefix is not on rep yet, only
            # fetchable. The hint write waits for adoption_landed() — a
            # fetch that falls through to recompute still lands (and then
            # records), but a shed/failed placement never re-homes the
            # session to a replica whose cache stayed cold
            return
        self._record_hint(self._hint_key(entry.req.prompt), rep.name)

    def adoption_landed(self, entry, rep):
        """The deferred cluster-hint write: the peer-routed entry's pages
        are actually resident on ``rep`` now (fetched and adopted, or
        recomputed locally — either way the cache is warm THERE)."""
        if not getattr(entry, "kv_hint_deferred", False):
            return
        entry.kv_hint_deferred = False
        if self.policy == "prefix":
            self._record_hint(self._hint_key(entry.req.prompt), rep.name)

    def _record_hint(self, key, name):
        # remember the session: the NEXT request with this prefix head
        # goes to the same replica even before the index has its pages
        with self._lock:
            self._hints.pop(key, None)
            self._hints[key] = name
            while len(self._hints) > self.max_hints:
                self._hints.pop(next(iter(self._hints)))

    def forget_replica(self, name):
        """Drop a dead replica's session hints so new traffic re-homes."""
        with self._lock:
            for k in [k for k, v in self._hints.items() if v == name]:
                del self._hints[k]
