"""Online request frontend: submit/stream/cancel over N engine replicas.

This is the entry point the ROADMAP's "heavy traffic" north star needs and
``ContinuousBatchingEngine.serve(prompts, ...)`` is not: requests arrive
one at a time from many client threads, get an SLO class and an optional
deadline, and are placed onto one of N engine replicas by the router —
then a **per-replica dispatcher thread** drives that engine continuously
through the non-blocking hooks (``try_admit_one`` / ``step``), so slots
refill the moment they free instead of waiting for a batch boundary.

Lifecycle of one request::

    handle = frontend.submit(prompt, max_new_tokens=64,
                             slo_class="interactive", deadline_s=2.0)
    for tok in handle.stream():   # or: handle.result(timeout=...)
        ...
    handle.cancel()               # any time; frees the slot at the next
                                  # block boundary

    submit -> SLOScheduler.check_admission   (Overloaded = shed, fast)
           -> Router.place                   (prefix affinity + load)
           -> replica.pending                (EDF order, aging built in)
    dispatcher: pick -> engine.try_admit_one -> engine.step loop
           -> handle tokens stream out as each decode block lands

Failure semantics (no hangs, no lost handles — the E2E chaos test's
contract): a replica that dies mid-flight (chaos ``serving.replica_kill``,
a wedged dispatcher caught by stale heartbeats, or an engine-fatal error)
has its queued requests transparently re-routed to surviving replicas; its
in-flight requests are re-routed too when their stream has not been
consumed yet (identical output — the sampled key stream depends only on
(seed, rid, index)), and cleanly failed with the replica's death reason
when tokens were already observed (a spliced stream would be a silent
correctness bug). Every handle always reaches a terminal state.

Concurrency rules: ONE frontend lock guards routing state (pending lists,
inflight maps, replica states); each engine is touched only by its own
dispatcher thread; RequestHandle has its own condition + token queue so
result()/stream() never contend with routing. The only dispatcher sleep is
the wake-event wait when a replica is fully idle.
"""
import itertools
import queue as _queue
import threading
import time

from ..inference.continuous import (
    _COMPILE_LOCK,
    EngineRequest,
    canonical_sampling,
)
from ..observability import compilemem as _compilemem
from ..observability import devprof as _devprof
from ..observability import fleet as _fleet
from ..observability import goodput as _goodput
from ..observability import request_trace as _rtrace
from ..observability import tracing as _tracing
from ..observability.metrics import registry as _registry
from ..observability.slo import SLOMonitor
from ..testing import chaos
from ..utils.envs import env_bool
from .adapters import AdapterRegistry
from .breaker import CircuitBreaker
from .brownout import BrownoutLadder
from .tenancy import DEFAULT_TENANT, TenantRegistry
from .handoff import (
    HandoffBundle,
    HandoffError,
    StaleHandoffError,
    page_digests,
)
from .kvfabric import KVFabric
from .transport import make_transport
from .router import (
    ADMITTING,
    DEAD,
    DRAINING,
    LIVE,
    PROBATION,
    NoLiveReplicas,
    ReplicaHandle,
    Router,
)
from .scheduler import DeadlineExceeded, Overloaded, SLOScheduler

__all__ = ["QUEUED", "RUNNING", "DONE", "FAILED", "CANCELLED",
           "RequestFailed", "RequestCancelled", "ResultTimeout",
           "RequestHandle", "ServingFrontend"]

QUEUED = "QUEUED"
RUNNING = "RUNNING"
DONE = "DONE"
FAILED = "FAILED"
CANCELLED = "CANCELLED"
_TERMINAL = (DONE, FAILED, CANCELLED)

_M_SUBMITTED = _registry.counter("serving.submitted")
_M_COMPLETED = _registry.counter("serving.completed")
_M_FAILED = _registry.counter("serving.failed")
_M_SHED = _registry.counter("serving.shed")
_M_EXPIRED = _registry.counter("serving.deadline_expired")
_M_CANCELLED = _registry.counter("serving.cancelled")
_M_REROUTED = _registry.counter("serving.rerouted")
_M_DRAIN_REQUEUED = _registry.counter("serving.drain_requeued")
_M_REPLICA_DEAD = _registry.counter("serving.replica_dead")
_M_QUEUE = _registry.gauge("serving.queue_depth")
_M_FLAPS = _registry.counter(
    "serving.replica_flaps",
    help="stale-heartbeat observations that recovered before the miss "
         "budget ran out (damped — no reroute storm)")
_M_CLAMPED = _registry.counter(
    "brownout.tokens_clamped",
    help="batch-class submits whose max_new_tokens the brownout ladder "
         "clamped")
_M_HANDOFF_INITIATED = _registry.counter(
    "serving.handoff.initiated",
    help="prefill->decode KV-page handoffs initiated (bundle published and "
         "the request detached from its prefill replica)")


def _count_handoff_fallback(reason):
    """One rung of the degradation ladder fired: the request completes in
    blended mode instead of disaggregating (availability over perf)."""
    _registry.counter(
        "serving.handoff.fallback", labels={"reason": reason},
        help="requests that fell back to blended completion instead of a "
             "prefill->decode handoff, by reason").inc()


def _hist_summary(h):
    """Compact histogram rollup for serving_report()/tenant_report()."""
    return {"count": h.count, "mean": round(h.mean, 6),
            "p50": h.quantile(0.5), "p99": h.quantile(0.99)}


class RequestFailed(RuntimeError):
    """result()/stream(): the request reached FAILED; the message carries
    the per-request failure reason (satellite: rid -> exception string)."""


class RequestCancelled(RuntimeError):
    """result(): the request was cancelled before completing."""


class ResultTimeout(TimeoutError):
    """result(timeout=)/stream(timeout=): the caller's wait bound expired
    (ISSUE 12 satellite). The REQUEST is untouched — it keeps running and
    a later result()/stream() can still observe it; only the caller's
    blocking wait is bounded, so a wedged fleet can't hold every client
    thread hostage. Subclasses TimeoutError for drop-in compatibility."""


class _Entry:
    """Routing-layer wrapper: one EngineRequest + its handle + SLO facts."""

    __slots__ = ("req", "handle", "slo", "deadline_t", "virtual_deadline",
                 "observed", "route_affinity", "route_score", "probe",
                 "trace", "attempt_span", "queue_span", "attempt_n",
                 "target_role", "needs_handoff", "handoff_gen",
                 "bundle_path", "bundle", "kv_hint_deferred", "tenant")

    def __init__(self, req, handle, slo, deadline_t, virtual_deadline,
                 tenant=None):
        self.req = req
        self.handle = handle
        self.slo = slo
        self.deadline_t = deadline_t
        self.virtual_deadline = virtual_deadline
        # multi-tenant plane (ISSUE 19): the resolved Tenant this request
        # was admitted under — per-tenant observation/report attribution
        self.tenant = tenant
        self.observed = False   # queue_wait/ttft recorded (once per request)
        self.route_affinity = False  # last place(): won by affinity/hint?
        self.route_score = 0.0       # last place(): winning blended score
        self.probe = False           # last place(): half-open breaker probe?
        # request-scoped tracing (ISSUE 7): the trace context plus the open
        # per-attempt spans — an attempt is one placement; a reroute closes
        # it and opens the next, so the trace tree shows the failover
        self.trace = None
        self.attempt_span = None
        self.queue_span = None
        self.attempt_n = 0
        # disaggregated prefill/decode handoff state (ISSUE 16): the role the
        # router should prefer, whether the prefill side still owes a KV-page
        # handoff, the generation fence that drops superseded bundles, and
        # the published bundle awaiting adoption (path on disk / loaded copy)
        self.target_role = None
        self.needs_handoff = False
        self.handoff_gen = 0
        self.bundle_path = None
        self.bundle = None
        # cluster KV fabric (ISSUE 18): a peer-residency placement defers
        # the router's session-hint write until the adoption lands
        self.kv_hint_deferred = False


class RequestHandle:
    """The caller's view of one in-flight request. Thread-safe; every
    accessor works from any thread. Exactly one terminal transition ever
    happens (DONE / FAILED / CANCELLED) — late token pushes from a replica
    that was declared dead mid-step are discarded by the generation stamp."""

    def __init__(self, frontend, req, slo):
        self._frontend = frontend
        self._req = req
        self.slo_class = slo.name
        self.replica = None          # name of the replica serving it
        self.timed_out = False
        self._trace = None           # TraceContext (None = telemetry off)
        self._cond = threading.Condition()
        self._status = QUEUED
        self._result = None
        self._error = None           # rendered failure reason (string)
        self._tokens = []            # generated tokens observed so far
        self._stream_q = _queue.Queue()
        self._stream_consumed = False
        self._gen = 0                # bumped on reroute; stale pushes drop
        # set by cancel() BEFORE the frontend scans its queues, so a request
        # in the admission transit window (in neither pending nor inflight)
        # still sees the cancel when the dispatcher re-examines it
        self._cancel_requested = False
        # multi-tenant plane (ISSUE 19): fired exactly once at the terminal
        # transition (whichever path wins) — releases the tenant's inflight
        # slot and the request's LoRA adapter pin
        self._on_terminal = None

    # ---- caller surface ---------------------------------------------------
    @property
    def rid(self):
        return self._req.rid

    @property
    def status(self):
        with self._cond:
            return self._status

    @property
    def error(self):
        """Failure reason string (None unless FAILED)."""
        with self._cond:
            return self._error

    def tokens_so_far(self):
        with self._cond:
            return list(self._tokens)

    def done(self):
        return self.status in _TERMINAL

    def result(self, timeout=None):
        """Block for the full token array (prompt + generated). Raises
        RequestFailed (with the failure reason) / RequestCancelled /
        ResultTimeout. The timeout bounds only THIS caller's wait — the
        request itself keeps running (call cancel() to abandon it), so a
        wedged fleet can't hold the caller hostage forever. (A request the
        ENGINE timed out per its own ``timeout_s`` still returns its
        partial result with ``handle.timed_out`` set.)"""
        with self._cond:
            if not self._cond.wait_for(
                    lambda: self._status in _TERMINAL, timeout):
                raise ResultTimeout(
                    f"request {self.rid} not finished within {timeout}s "
                    f"(the request is still running — not cancelled)")
            if self._status == DONE:
                return self._result
            if self._status == CANCELLED:
                raise RequestCancelled(f"request {self.rid} was cancelled")
            raise RequestFailed(
                f"request {self.rid} failed: {self._error}")

    def stream(self, timeout=None):
        """Iterator over generated token ids, yielding each one as soon as
        its decode block lands. Ends at completion/cancellation; raises
        RequestFailed on failure; ``timeout`` bounds the wait for EACH next
        token (ResultTimeout — the request is NOT cancelled; the iterator
        can be resumed by calling stream() again). Consuming the stream
        pins the request to its replica — a consumed stream cannot be
        transparently re-routed, only failed."""
        with self._cond:
            # under the lock so the flag and _reset_for_reroute's check are
            # ordered: either the reroute sees it consumed and fails the
            # handle, or this iterator only ever observes the replay
            self._stream_consumed = True
        while True:
            try:
                kind, val = self._stream_q.get(timeout=timeout)
            except _queue.Empty:
                raise ResultTimeout(
                    f"request {self.rid}: no token within {timeout}s "
                    f"(the request is still running — not cancelled)") \
                    from None
            if kind == "tok":
                yield val
            elif kind == "end":
                return
            else:  # "err"
                raise RequestFailed(f"request {self.rid} failed: {val}")

    def cancel(self):
        """Best-effort cancel: a queued request never runs; a running one
        retires at the next block boundary. Idempotent; no-op once
        terminal."""
        self._frontend._cancel(self)

    # ---- dispatcher surface (frontend internals only) ---------------------
    def _push_token(self, tok, gen):
        with self._cond:
            if gen != self._gen or self._status in _TERMINAL:
                return  # stale replica still stepping after reroute/failure
            self._tokens.append(tok)
            # the queue put stays INSIDE the lock: _reset_for_reroute drains
            # the queue under the same lock, so a push that passed the gen
            # check can't slip a stale token in after the drain
            self._stream_q.put(("tok", tok))

    def _mark_running(self, replica_name):
        with self._cond:
            if self._status == QUEUED:
                self._status = RUNNING
                self.replica = replica_name

    def _mark_queued(self):
        with self._cond:
            if self._status == RUNNING:
                self._status = QUEUED
                self.replica = None

    def _reset_for_reroute(self):
        """Forget everything the dead replica produced; returns the new
        generation stamp for the replacement on_token closure, or None when
        the stream has been consumed (checked under the same lock stream()
        sets the flag under — a replay after the consumer dequeued a token
        would duplicate output)."""
        with self._cond:
            if self._stream_consumed:
                return None
            self._gen += 1
            self._tokens = []
            while True:
                try:
                    self._stream_q.get_nowait()
                except _queue.Empty:
                    break
            self._status = QUEUED
            self.replica = None
            return self._gen

    def _complete(self, req):
        with self._cond:
            if self._status in _TERMINAL:
                return
            self._result = req.result
            self.timed_out = req.timed_out
            self._status = DONE
            self._cond.notify_all()
        self._stream_q.put(("end", None))
        self._fire_terminal()
        self._trace_finish("ok", n_generated=req.n_generated,
                           timed_out=req.timed_out)

    def _fail(self, reason):
        with self._cond:
            if self._status in _TERMINAL:
                return
            self._error = str(reason)
            self._status = FAILED
            self._cond.notify_all()
        self._stream_q.put(("err", str(reason)))
        self._fire_terminal()
        self._trace_finish("error", error=str(reason))

    def _cancelled_now(self):
        with self._cond:
            if self._status in _TERMINAL:
                return
            self._status = CANCELLED
            self._cond.notify_all()
        self._stream_q.put(("end", None))
        self._fire_terminal()
        self._trace_finish("cancelled")

    def _fire_terminal(self):
        """Run the once-only terminal hook (tenant slot / adapter pin
        release). Only the transition that WON calls this — the early
        returns above never reach it — and the swap-to-None makes even a
        double call release exactly once."""
        cb, self._on_terminal = self._on_terminal, None
        if cb is not None:
            cb()

    def _trace_finish(self, status, **attrs):
        """Terminal trace transition, tied to the handle's own once-only
        terminal transition (whichever failure/completion path won): the
        trace finishes exactly once, and finish() sweeps any spans a dead
        replica's paths left open — structurally no orphan spans."""
        tr, self._trace = self._trace, None
        if tr is not None:
            tr.finish(status, **attrs)


class ServingFrontend:
    """The online serving control plane over N ContinuousBatchingEngine
    replicas. See the module docstring for the architecture; see
    docs/SERVING.md for the operator view (SLO classes, routing policy,
    drain semantics, env vars, metrics)."""

    def __init__(self, engines, scheduler=None, router=None,
                 poll_wait_s=0.005, heartbeat_deadline_s=30.0,
                 monitor_interval_s=None, heartbeat_misses=3,
                 brownout=None, breaker=None, engine_factory=None,
                 start=True, warmup=None,
                 slo_monitor=None, statusz_port=None,
                 roles=None, handoff=None, kvfabric=None,
                 tenants=None, adapters=None):
        # heartbeat_deadline_s must outlast the longest single engine call —
        # a first-compile prefill through a remote-compile tunnel can take
        # tens of seconds (PROFILE.md), and a false DEAD verdict reroutes a
        # healthy replica's work. warmup() the engines, then tighten it.
        if not engines:
            raise ValueError("need at least one engine replica")
        self.scheduler = scheduler or SLOScheduler()
        self.router = router or Router()
        self.poll_wait_s = float(poll_wait_s)
        self.heartbeat_deadline_s = float(heartbeat_deadline_s)
        # flap damping (ISSUE 12 satellite): LIVE->DEAD needs this many
        # CONSECUTIVE stale-beat monitor checks — one slow heartbeat scrape
        # is a counted flap (serving.replica_flaps), not a reroute storm
        self.heartbeat_misses = max(1, int(heartbeat_misses))
        self.monitor_interval_s = (float(monitor_interval_s)
                                   if monitor_interval_s is not None
                                   else max(0.05, self.heartbeat_deadline_s / 4))
        # a FULLY idle replica (engine empty, nothing routed) waits longer
        # than poll_wait_s — every transition that creates work sets the
        # wake event, so the only reason to wake at all is the heartbeat;
        # capped well under the deadline so idleness never reads as death
        self.idle_wait_s = min(1.0, self.heartbeat_deadline_s / 4)
        # disaggregated prefill/decode (ISSUE 16): ``roles`` assigns each
        # engine a pool ("prefill"/"decode"/"blended", default blended);
        # PADDLE_SERVING_DISAGG=0 force-disables the handoff path so a
        # roled fleet serves every request blended (byte-for-byte the
        # pre-disaggregation behavior — the keystone degradation switch)
        if roles is not None and len(roles) != len(engines):
            raise ValueError(
                f"roles has {len(roles)} entries for {len(engines)} engines")
        self.replicas = [
            ReplicaHandle(f"replica{i}", eng, index=i,
                          role=(roles[i] if roles else "blended"))
            for i, eng in enumerate(engines)]
        self._disagg_enabled = env_bool("PADDLE_SERVING_DISAGG", True)
        # KV-page handoff transport (ISSUE 18): PADDLE_KV_TRANSPORT picks
        # spool (the PR 16 directory path, default, byte-identical) or
        # wire (transport.WireTransport); injectable for tests
        self.handoff = handoff or make_transport()
        # cluster KV fabric (ISSUE 18): tiered prefix cache + residency
        # map. Constructed even when PADDLE_KV_FABRIC=0 (it no-ops
        # internally) so /kvz and serving_report stay shaped; the wire
        # transport is shared with handoff when one is configured
        self.kvfabric = kvfabric or KVFabric(
            name="frontend",
            transport=self.handoff if hasattr(self.handoff, "fetch_blob")
            else None)
        self._by_name = {r.name: r for r in self.replicas}
        self._lock = threading.Lock()
        self._rid_counter = itertools.count()
        self._wakes = {r.name: threading.Event() for r in self.replicas}
        self._drained = {r.name: threading.Event() for r in self.replicas}
        self._stop = threading.Event()
        self._threads = []
        self._started = False
        self._class_hists = {}
        # AOT precompile vocabulary: kwargs forwarded to each engine's
        # warmup() by ITS dispatcher thread before it serves (replicas
        # warm in parallel, serialized only on the shared compile lock),
        # so first requests don't eat the compile spikes. e.g.
        # warmup=dict(buckets=[64, 256, 1024], sampling=[(False,1,0,1)])
        self._warmup_kw = dict(warmup) if warmup else None
        # SLO burn-rate accounting (ISSUE 7): objectives default from the
        # scheduler's class declarations (ttft_slo_s/tpot_slo_s per class +
        # a deadline-miss objective); fed by the same observation points
        # as the per-class histograms, read via serving_report()//statusz
        self.slo = slo_monitor or SLOMonitor(
            classes=self.scheduler.classes.values())
        # overload brownout ladder (ISSUE 12): declared degradation steps
        # driven by the monitor's fleet-pressure observations; level 0
        # (no pressure ever observed) is a no-op on every submit path
        self.brownout = brownout or BrownoutLadder()
        # multi-tenant plane (ISSUE 19): the bounded tenant registry (a
        # TenantRegistry, or an iterable of Tenant declarations) and the
        # ref-counted LoRA adapter host cache. Untenanted submits resolve
        # to the registry's default tenant — byte-compatible with the
        # pre-tenancy API; per-tenant SLO burn-rate monitors are minted
        # lazily on a tenant's first observation (never for "default",
        # whose traffic stays on the fleet monitor alone)
        self.tenants = (tenants if isinstance(tenants, TenantRegistry)
                        else TenantRegistry(tenants or ()))
        self.adapters = (adapters if isinstance(adapters, AdapterRegistry)
                         else AdapterRegistry())
        self._tenant_slo = {}   # tenant name -> SLOMonitor (under _lock)
        # circuit breaker (ISSUE 12): per-replica error/latency scoring;
        # verdicts become PROBATION/LIVE/DEAD transitions under self._lock.
        # The router consults it for half-open probe placements.
        self.breaker = breaker or CircuitBreaker()
        self.router.breaker = self.breaker
        # the router scores placement against the CLUSTER-wide prefix
        # index: peer-resident prefixes become transfer-discounted
        # affinity (router.place reads fabric.resident_owners)
        self.router.fabric = self.kvfabric
        # replica index allocator for add_replica (heartbeat-file rank
        # namespace must never reuse a live index)
        self._next_index = len(self.replicas)
        # live introspection (ISSUE 7): statusz_port=0 picks a free port
        self.statusz = None
        if statusz_port is not None:
            self.statusz = self.serve_statusz(statusz_port)
        # replica lifecycle supervisor (ISSUE 12): attached by
        # ReplicaSupervisor itself; None = nobody owns spawn/scale.
        # ``engine_factory`` + PADDLE_SUPERVISOR=1 is the blessed opt-in —
        # the env default-off keeps this constructor at zero extra threads
        self.supervisor = None
        if start:
            self.start()
        if engine_factory is not None:
            from .supervisor import ReplicaSupervisor

            ReplicaSupervisor.from_env(self, engine_factory)

    # ---- lifecycle --------------------------------------------------------
    def start(self):
        if self._started:
            return self
        self._started = True
        # scope the (process-global) serving goodput split to this
        # frontend's lifetime: without the reset, an hour of training
        # before serving dilutes every serving fraction toward zero
        _goodput.serving.reset()
        for rep in self.replicas:
            t = threading.Thread(target=self._run_replica, args=(rep,),
                                 daemon=True,
                                 name=f"paddle-serving-{rep.name}")
            self._threads.append(t)
            t.start()
        m = threading.Thread(target=self._run_monitor, daemon=True,
                             name="paddle-serving-monitor")
        self._threads.append(m)
        m.start()
        return self

    def serve_statusz(self, port=0, host="127.0.0.1"):
        """Start (and return) a /statusz introspection server bound to this
        frontend — /statusz, /varz, /tracez, /healthz (observability/
        statusz.py). Stopped by shutdown()."""
        from ..observability.statusz import StatusServer

        return StatusServer(port=port, host=host, frontend=self).start()

    def shutdown(self, timeout=5.0):
        """Stop dispatchers and the monitor. In-flight work stops at the
        next block boundary; unfinished handles are failed (never lost)."""
        if self.supervisor is not None:
            self.supervisor.stop()
            self.supervisor = None
        if self.statusz is not None:
            self.statusz.stop()
            self.statusz = None
        self._stop.set()
        for ev in self._wakes.values():
            ev.set()
        for t in self._threads:
            t.join(timeout=timeout)
        with self._lock:
            orphans = []
            for rep in self.replicas:
                orphans.extend(rep.pending)
                orphans.extend(rep.inflight.values())
                rep.pending = []
                rep.inflight = {}
        for e in orphans:
            if e.bundle_path is not None:
                self.handoff.discard(e.bundle_path)
                e.bundle_path = None
            e.handle._fail("frontend shut down")
        close = getattr(self.handoff, "close", None)
        if close is not None:
            close()

    def __enter__(self):
        return self

    def __exit__(self, *exc_info):
        self.shutdown()
        return False

    # ---- submission -------------------------------------------------------
    def submit(self, prompt, max_new_tokens, slo_class=None,
               deadline_s=None, eos_token_id=None, do_sample=False,
               temperature=1.0, top_k=0, top_p=1.0, seed=0,
               timeout_s=None, is_retry=False, tenant=None, adapter=None):
        """Enqueue one request; returns its RequestHandle immediately.

        Raises Overloaded (load shed — the request was never queued) when
        the scheduler's queue bound is hit or the brownout ladder sheds
        this class (machine-readable ``retry_after_s``/``level``/``step``
        fields), or NoLiveReplicas when every replica is draining/dead.
        ``deadline_s`` is relative to now: it tightens the EDF priority
        and, if it expires before the request starts, the request fails
        fast with DeadlineExceeded instead of wasting decode slots.
        ``is_retry=True`` declares a client re-submission of a rejected/
        failed request: it must withdraw from the per-class retry budget
        or is rejected immediately — the valve that keeps a retry storm
        from re-saturating a recovering fleet (docs/SERVING.md).

        ``tenant`` (ISSUE 19) names a DECLARED tenant (or passes the
        Tenant itself); None maps to the registry's default tenant —
        byte-compatible with the pre-tenancy path. The tenant layer runs
        ABOVE the fleet ladder and the EDF queue bound: the tenant's
        private brownout ladder and retry budget, its token bucket
        (``Overloaded(step="tenant_quota", tenant=..., retry_after_s=
        <refill deficit>)``), and its inflight cap. ``slo_class=None``
        defaults to the tenant's declared class (else "interactive").

        ``adapter`` names a LoRA adapter registered in
        ``frontend.adapters`` (name, digest, or the LoRAAdapter). It is
        resolved + ref-pinned here and released at the handle's terminal
        transition; the tenant's allowlist is enforced. Adapter requests
        serve blended (never disaggregated) and co-batch with base
        traffic inside the engine."""
        t = self.tenants.resolve(tenant)   # unknown tenant -> ValueError
        slo = self.scheduler.resolve(
            slo_class or t.slo_class or "interactive")
        reserve = self.scheduler.reserve_class
        # tenant isolation layer (ISSUE 19), ABOVE every fleet-wide check:
        # the storming tenant must shed against ITS OWN ladder/bucket/cap —
        # tenant-stamped, with retry_after_s from its bucket's refill
        # deficit — before it can so much as read fleet state. The default
        # tenant's private ladder is a pass-through: untenanted traffic is
        # governed by the fleet ladder alone (running both would charge a
        # retry against two budgets — not byte-compatible with pre-tenancy)
        if t is not self.tenants.default:
            try:
                t.brownout.check_admission(slo, reserve)
                if is_retry:
                    t.brownout.check_retry(slo)
            except Overloaded:
                t.count_shed()
                _M_SHED.inc()
                raise
        try:
            t.admit()          # token bucket (counts its own shed)
            t.acquire_slot()   # inflight cap (likewise)
        except Overloaded:
            _M_SHED.inc()
            raise
        ad = None
        handle = None
        try:
            if adapter is not None:
                if not t.allows_adapter(adapter):
                    raise ValueError(
                        f"tenant {t.name!r} is not allowed adapter "
                        f"{getattr(adapter, 'name', adapter)!r}")
                ad = self.adapters.acquire(adapter)
            handle = self._submit_admitted(
                t, ad, slo, reserve, prompt, max_new_tokens, deadline_s,
                eos_token_id, do_sample, temperature, top_k, top_p, seed,
                timeout_s, is_retry)
            return handle
        except BaseException:
            # the slot/pin must not leak on ANY pre-queue failure; once a
            # handle exists its once-only terminal hook owns the release
            # (covers the window where the entry already became
            # dispatcher-visible before the raise)
            if handle is not None:
                handle._fire_terminal()
            else:
                t.release_slot()
                if ad is not None:
                    self.adapters.release(ad)
            raise

    def _submit_admitted(self, t, ad, slo, reserve, prompt, max_new_tokens,
                         deadline_s, eos_token_id, do_sample, temperature,
                         top_k, top_p, seed, timeout_s, is_retry):
        """submit() past the tenant layer: fleet brownout, queue bound,
        placement. The caller owns tenant-slot/adapter release on raise."""
        # brownout ladder (ISSUE 12): the declared degradation steps run
        # BEFORE the queue-bound check — they are cheaper (two int reads)
        # and shedding at the rung is the point of having rungs at all
        try:
            self.brownout.check_admission(slo, reserve)
            if is_retry:
                self.brownout.check_retry(slo)
        except Overloaded:
            _M_SHED.inc()
            raise
        cap = self.brownout.token_cap(slo, reserve)
        if cap is not None and max_new_tokens > cap:
            max_new_tokens = cap  # clamp_tokens rung: bounded decode work
            _M_CLAMPED.inc()
        # shed_extras rung: optional work off — no per-request trace
        # minting, no O(prompt-bytes) affinity probing in the router
        extras = self.brownout.extras_enabled()
        sampling = canonical_sampling(do_sample, temperature, top_k, top_p)
        rid = next(self._rid_counter)  # atomic under the GIL
        req = EngineRequest(rid, prompt, max_new_tokens,
                            eos_token_id=eos_token_id, sampling=sampling,
                            seed=seed, timeout_s=timeout_s, adapter=ad)
        handle = RequestHandle(self, req, slo)

        def _release_tenant():
            t.release_slot()
            if ad is not None:
                self.adapters.release(ad)

        # fired exactly once at whichever terminal transition wins (or by
        # submit()'s failure path): the tenant slot and adapter pin follow
        # the handle's lifetime, never a particular dispatcher's
        handle._on_terminal = _release_tenant
        req.on_token = self._make_on_token(handle, gen=0)
        deadline_t = (req.t_enqueue + float(deadline_s)
                      if deadline_s is not None else None)
        entry = _Entry(req, handle, slo, deadline_t,
                       self.scheduler.virtual_deadline(
                           req.t_enqueue, slo, deadline_s),
                       tenant=t)
        # disaggregated placement (ISSUE 16): with a roled fleet and a live
        # decode pool, the request targets the prefill pool and owes a
        # KV-page handoff after its first token. Token delivery is
        # suppressed until the decode side replays the bundle — satellite
        # fix: TTFT must span prefill queue wait + handoff transfer, so the
        # first client-visible token is stamped at decode-side delivery.
        # An empty/all-PROBATION decode pool degrades to blended here and
        # at every later checkpoint (availability over disaggregation).
        if self._disagg_active():
            if ad is not None:
                # LoRA requests complete blended (ISSUE 19): the adapter
                # delta lives in the decode program's operands, not the KV
                # bundle — a handoff would replay the prefix base-only
                _count_handoff_fallback("lora_adapter")
            elif self._decode_pool_live():
                entry.target_role = "prefill"
                entry.needs_handoff = True
                req.on_token = None
            else:
                _count_handoff_fallback("decode_pool_empty")
        # advisory fast-path shed (unlocked reads): overload traffic must
        # not pay the placement probe per rejected submit. The
        # authoritative check re-runs under the append lock below.
        try:
            self.scheduler.check_admission(
                sum(len(r.pending) for r in self.replicas), slo)
        except Overloaded:
            _M_SHED.inc()
            raise
        # request-scoped trace (ISSUE 7): minted AFTER the advisory shed —
        # a shed storm must not mint contexts — and finished by the
        # handle's terminal transition, whichever path that is. None when
        # telemetry is off (the zero-overhead contract) or the brownout
        # ladder shed extras.
        handle._trace = entry.trace = _rtrace.start(
            rid, slo=slo.name, prompt_len=len(req.prompt),
            max_new_tokens=req.max_new_tokens,
            deadline_s=float(deadline_s) if deadline_s is not None
            else None) if extras else None
        exclude = set()
        try:
            while True:
                # placement runs OUTSIDE the frontend lock: the
                # prefix-affinity probe hashes O(prompt bytes) per replica
                # (the engine's chained-digest index), and doing even that
                # under the one lock every dispatcher's admission pick needs
                # would stall all replicas behind each long-prompt submit.
                # Everything place() reads is advisory; the append below
                # re-checks the decisions that matter under the lock.
                rep = self.router.place(entry, self.replicas,
                                        exclude=exclude, cheap=not extras)
                # spans open BEFORE the entry becomes dispatcher-visible: a
                # dispatcher that pops it the instant the append lands must
                # find the queue span already open
                self._trace_commit(entry, rep)
                with self._lock:
                    # checked under the SAME lock shutdown's orphan sweep
                    # holds: an unlocked check could pass, the sweep run, and
                    # the append below then queue an entry no dispatcher will
                    # ever see — a handle that never reaches a terminal state
                    if self._stop.is_set():
                        raise RuntimeError("frontend is shut down")
                    queued = sum(len(r.pending) for r in self.replicas)
                    try:
                        # under the append lock so depth can't race past the
                        # bound (the scheduler's check+enqueue contract)
                        self.scheduler.check_admission(queued, slo)
                    except Overloaded:
                        _M_SHED.inc()
                        raise
                    # state can change between place() and here; a probe
                    # placement lands on its PROBATION target (that IS the
                    # half-open recovery signal)
                    if rep.state == LIVE or (entry.probe
                                             and rep.state == PROBATION):
                        rep.pending.append(entry)
                        _M_SUBMITTED.inc()
                        _M_QUEUE.set(queued + 1)
                        break
                self._trace_attempt_end(entry, "rerouted",
                                        reason=f"{rep.name} not LIVE")
                exclude.add(rep.name)
        except BaseException as e:
            if entry.trace is not None:
                handle._trace = None
                entry.trace.finish(
                    "shed" if isinstance(e, Overloaded) else "error",
                    error=f"{type(e).__name__}: {e}")
            raise
        self.router.committed(entry, rep)
        # accepted: deposit into the class retry budget — accepted goodput
        # is what funds future retries (the anti-retry-storm construction)
        self.brownout.on_accepted(slo)
        t.brownout.on_accepted(slo)
        t.count_admitted()
        self._wake(rep.name)
        return handle

    def _make_on_token(self, handle, gen):
        def on_token(rid, tok):
            handle._push_token(tok, gen)
        return on_token

    # ---- disaggregated prefill/decode (ISSUE 16) --------------------------
    def _disagg_active(self):
        """Handoffs happen only when the operator both enabled them
        (PADDLE_SERVING_DISAGG, default on) and gave the fleet a prefill
        pool. With neither, every path below is dead code and blended
        serving is byte-for-byte the pre-disaggregation behavior."""
        return self._disagg_enabled and any(
            r.role == "prefill" and r.state in ADMITTING
            for r in self.replicas)

    def _decode_pool_live(self):
        """True when at least one decode-role replica is LIVE. The
        ``serving.decode_pool_empty`` chaos seam sits on the check itself:
        an injected fault here declares the pool empty, which is exactly
        the degradation drill (blended completion, nothing lost)."""
        try:
            chaos.site("serving.decode_pool_empty")
        except Exception:
            return False
        return any(r.role == "decode" and r.state == LIVE
                   for r in self.replicas)

    def _handoff_fallback(self, entry, reason):
        """Blended completion for a request that was slated for handoff:
        deliver the suppressed tokens to the handle (the client's first
        token is NOW — TTFT is delivery-time, satellite 2) and stream
        normally from here. The request just keeps decoding wherever it
        already is; nothing was detached, so nothing can be lost."""
        _count_handoff_fallback(reason)
        req = entry.req
        entry.needs_handoff = False
        entry.target_role = None
        req.on_token = self._make_on_token(entry.handle, entry.handle._gen)
        if req.t_first_token is not None:
            req.t_first_token = time.monotonic()
        for tok in req.tokens[len(req.prompt):]:
            req.on_token(req.rid, tok)
        self._observe_admission(entry)

    def _initiate_handoffs(self, rep):
        """Prefill-side dispatcher hook: every in-flight request that has
        its first token and still owes a handoff gets one initiated."""
        with self._lock:
            candidates = [e for e in rep.inflight.values()
                          if e.needs_handoff
                          and e.req.t_first_token is not None
                          and not e.req.finished and not e.req.cancelled]
        moved = False
        for entry in candidates:
            moved |= self._initiate_handoff(rep, entry)
        return moved

    def _initiate_handoff(self, rep, entry):
        """Export the request's KV pages, publish the bundle, detach the
        request from the prefill engine, and requeue it toward the decode
        pool. Every failure BEFORE the detach degrades to blended (the
        request keeps decoding right here — handoff is a perf win, never
        an availability loss); after the detach the bundle on disk is the
        request, and the adopt path owns every failure from there."""
        eng, req = rep.engine, entry.req
        if not self._decode_pool_live():
            self._handoff_fallback(entry, "decode_pool_empty")
            return False
        span = None
        if entry.attempt_span is not None:
            span = entry.attempt_span.child("handoff", rid=req.rid,
                                            generation=entry.handoff_gen)
        try:
            payloads = eng.export_pages(req.slot)
        except Exception as e:
            if span is not None:
                span.end("error", error=f"{type(e).__name__}: {e}")
            self._handoff_fallback(entry, "export_failed")
            return False
        if payloads is None:
            # finished (or was retired) while settling the in-flight block:
            # nothing to hand off — _finish delivers the suppressed tokens
            if span is not None:
                span.end("skipped", reason="request already finished")
            return False
        n_pages = payloads["n_pages"]
        bundle = HandoffBundle(
            rid=req.rid, seed=req.seed, sampling=req.sampling,
            prompt=req.prompt, tokens=list(req.tokens[len(req.prompt):]),
            n_generated=req.n_generated, n_dispatched=req.n_dispatched,
            max_new_tokens=req.max_new_tokens,
            eos_token_id=req.eos_token_id, timeout_s=req.timeout_s,
            payloads=payloads,
            digests=page_digests(req.prompt, eng.page_size,
                                 min(n_pages, len(req.prompt)
                                     // eng.page_size)),
            page_size=eng.page_size, generation=entry.handoff_gen)
        try:
            path = self.handoff.publish(bundle)
        except Exception as e:
            # deadline/retries exhausted: nothing was detached, so the
            # request simply keeps decoding here in blended mode
            if span is not None:
                span.end("error", error=f"{type(e).__name__}: {e}")
            self._handoff_fallback(entry, "publish_failed")
            return False
        eng.detach_request(req.slot)
        with self._lock:
            rep.inflight.pop(req.rid, None)
        entry.needs_handoff = False
        entry.bundle_path = path
        entry.target_role = "decode"
        _M_HANDOFF_INITIATED.inc()
        if span is not None:
            span.end("ok", n_pages=n_pages,
                     n_tokens=len(bundle.tokens))
        # close the prefill attempt as handed off so _requeue's reroute
        # edge (the satellite's "attempt edge") is the only event stamped
        self._trace_attempt_end(entry, "handed_off",
                                reason="kv pages published to decode pool")
        self._requeue(entry, exclude=set(),
                      fail_reason="handoff to decode pool",
                      rerouted=False)
        return True

    def _adopt_one(self, rep, entry):
        """Decode-side admission for a bundle-carrying entry. Returns the
        try_admit_one status vocabulary ("admitted"/"deferred"/"failed")
        plus "requeued" when a corrupt/stale bundle sent the request back
        for a re-prefill. The spool file is consumed on first load; a
        deferred adopt keeps the validated bundle in memory and retries
        without re-reading."""
        eng, req = rep.engine, entry.req
        bundle = entry.bundle
        if bundle is None:
            try:
                bundle = self.handoff.load(
                    entry.bundle_path,
                    expected_generation=entry.handoff_gen)
            except StaleHandoffError as e:
                # a superseded prefill's late bundle: drop it, re-prefill
                entry.bundle_path = None
                self.kvfabric.count_fallthrough(
                    getattr(e, "reason", None) or "stale")
                self._reprefill(entry, f"stale handoff bundle: {e}")
                return "requeued"
            except HandoffError as e:
                # torn/corrupt (or unreadable) bundle: the typed-error
                # contract — never adopt, never a wrong token; re-prefill.
                # The wire transport's typed errors carry .reason
                # (timeout/partition/transport); spool corruption is
                # "corrupt" — either way the fallthrough is counted typed
                entry.bundle_path = None
                self.kvfabric.count_fallthrough(
                    getattr(e, "reason", None) or "corrupt")
                self._reprefill(entry, f"handoff bundle rejected: {e}")
                return "requeued"
            entry.bundle = bundle
            entry.bundle_path = None
            # restore the continuation state from the VALIDATED bundle (not
            # from whatever the prefill side last mutated in memory): the
            # decode replica replays exactly what was committed to disk
            req.tokens = list(req.prompt) + list(bundle.tokens)
            req.n_generated = bundle.n_generated
            req.n_dispatched = bundle.n_dispatched
            if bundle.tokens:
                req.last_token = bundle.tokens[-1]
        status = eng.adopt_request(req, bundle.payloads)
        if status == "admitted":
            entry.bundle = None
            # deliver the prefill-side tokens NOW: the client's first token
            # lands here, so serving.ttft_s spans prefill queue wait +
            # transfer + adopt (the satellite-2 histogram contract), and
            # the stream continues seamlessly from the engine's next block
            gen = entry.handle._gen
            req.on_token = self._make_on_token(entry.handle, gen)
            req.t_first_token = time.monotonic()
            for tok in bundle.tokens:
                req.on_token(req.rid, tok)
        elif status == "failed":
            entry.bundle = None
        return status

    def _kv_acquire(self, rep, entry):
        """Walk the fabric's tier ladder for a reusable prefix before the
        engine prefills from scratch. Pages land via the engine's OPTIONAL
        ``adopt_prefix(prompt, payload)`` seam (duck-typed — the stock
        engine's own prefix index already covers the device tier, so only
        engines that opt in adopt fabric entries). Every failure here is
        either counted inside acquire() or swallowed into recompute — this
        call can never fail an admission."""
        fab = self.kvfabric
        eng = rep.engine
        adopt = getattr(eng, "adopt_prefix", None)
        if fab is None or not fab.enabled or adopt is None:
            return
        try:
            got = fab.acquire(entry.req.prompt, eng.page_size,
                              allow_peer=self.brownout.peer_fetch_enabled())
            if got is None:
                return
            kv_entry, _tier = got
            adopt(kv_entry["prompt"], kv_entry["payload"])
        except Exception:
            # adoption is strictly best-effort; the prefill below is the
            # unconditional, bit-identical floor
            fab.count_fallthrough("adopt_failed")

    def _kv_note_admitted(self, rep, entry):
        """The entry's pages are resident on ``rep`` now: release the
        router's deferred cluster hint (a peer-routed placement only
        re-homes session stickiness once something actually landed) and
        advertise the prompt's prefix residency into the fabric. The
        engine may also export the prefix into the host spill ring via
        the optional ``export_prefix(prompt)`` seam."""
        fab = self.kvfabric
        try:
            self.router.adoption_landed(entry, rep)
        except Exception:
            pass
        if fab is None or not fab.enabled:
            return
        eng = rep.engine
        try:
            fab.advertise_prompt(entry.req.prompt, eng.page_size, rep.name)
            export = getattr(eng, "export_prefix", None)
            if export is not None:
                payload = export(entry.req.prompt)
                if payload is not None:
                    fab.spill_prefix(entry.req.prompt, eng.page_size,
                                     payload, owner=rep.name)
        except Exception:
            pass        # residency is advisory; admission already happened

    def _reprefill(self, entry, reason):
        """A handoff failed en route to (or at) the decode pool: clone the
        request and run the prefill again — bit-identical output, because
        the sampled key stream depends only on (seed, rid, index). The
        generation fence bumps so any late bundle from the superseded
        attempt is stale on arrival. After repeated handoff failures the
        request stops disaggregating and completes blended."""
        handle = entry.handle
        if entry.req.cancelled or handle._cancel_requested:
            _M_CANCELLED.inc()
            handle._cancelled_now()
            return
        gen = handle._reset_for_reroute()
        if gen is None:
            # stream already consumed — a replayed stream would splice
            _M_FAILED.inc()
            handle._fail(reason)
            return
        entry.observed = False
        entry.req = entry.req.clone_for_retry()
        entry.handoff_gen += 1
        entry.bundle = None
        entry.bundle_path = None
        if self._disagg_active() and entry.handoff_gen < 3 \
                and self._decode_pool_live():
            entry.needs_handoff = True
            entry.target_role = "prefill"
            entry.req.on_token = None
        else:
            _count_handoff_fallback("reprefill_blended")
            entry.needs_handoff = False
            entry.target_role = None
            entry.req.on_token = self._make_on_token(handle, gen)
        self._requeue(entry, exclude=set(), fail_reason=reason,
                      rerouted=True)

    def _wake(self, name):
        # .get, not []: a remove_replica can race a late wake from a
        # request that finished on the removed replica
        ev = self._wakes.get(name)
        if ev is not None:
            ev.set()

    def _cancel(self, handle):
        # flag first: if the scan below misses the request because its
        # dispatcher holds it in transit (popped from pending, not yet in
        # inflight), the dispatcher honors the flag when it re-surfaces
        handle._cancel_requested = True
        with self._lock:
            for rep in self.replicas:
                for i, e in enumerate(rep.pending):
                    if e.handle is handle:
                        rep.pending.pop(i)
                        if e.bundle_path is not None:
                            self.handoff.discard(e.bundle_path)
                            e.bundle_path = None
                        _M_CANCELLED.inc()
                        handle._cancelled_now()
                        return
                e = rep.inflight.get(handle.rid)
                if e is not None and e.handle is handle:
                    e.req.cancelled = True  # engine retires it next block
                    self._wake(rep.name)
                    return
        # already terminal or unknown: cancel() is idempotent

    # ---- dispatcher -------------------------------------------------------
    def _run_replica(self, rep):
        eng = rep.engine
        wake = self._wakes[rep.name]
        rep.thread_ident = threading.get_ident()  # for the lock-probe
        if self._warmup_kw is not None and hasattr(eng, "warmup"):
            # replica-start AOT precompilation. The compile-lock probe
            # spares this thread only WHILE it holds/awaits a lock; warmup
            # has unlocked windows (readbacks, host work between jitted
            # sections), so a sidecar beat keeps the heartbeat fresh for
            # the whole bounded warmup — otherwise a warmup longer than
            # heartbeat_deadline_s gets a healthy replica killed at start.
            warm_done = threading.Event()

            def _beat_through_warmup():
                # beats are PROGRESS-gated: each newly-warm program key
                # resets the clock, so a legitimately long multi-program
                # warmup stays covered, but a warmup wedged in one hung
                # device call stops being covered after heartbeat_deadline_s
                # and falls back to the normal watchdog + lock-probe verdict
                # (a sidecar that beat unconditionally would silence the
                # watchdog for an unbounded window)
                last_n, last_t = -1, time.monotonic()
                while not warm_done.is_set():
                    n = len(getattr(eng, "_warm", ()))
                    now = time.monotonic()
                    if n != last_n:
                        last_n, last_t = n, now
                    if now - last_t > self.heartbeat_deadline_s:
                        return  # no compile progress: let the monitor judge
                    rep.beat()
                    warm_done.wait(1.0)

            beater = threading.Thread(target=_beat_through_warmup,
                                      daemon=True,
                                      name=f"paddle-warmup-beat-{rep.name}")
            beater.start()
            try:
                eng.warmup(**self._warmup_kw)
            except BaseException as e:
                self._replica_died(rep, e)
                return
            finally:
                warm_done.set()
                beater.join(timeout=5.0)
        while not self._stop.is_set():
            rep.beat()
            rep.publish_gauges()
            try:
                # the chaos kill switch for E2E tests: an injected fault
                # here is a replica crash (dispatcher dies mid-flight)
                chaos.site("serving.replica_kill")
            except BaseException as e:
                self._replica_died(rep, e)
                return
            if rep.state == DEAD:
                return
            progressed = False
            try:
                if rep.state in ADMITTING:
                    progressed |= self._admit_pending(rep)
                if not eng.idle():
                    # chaos stall for a BUSY replica's dispatch: a delay
                    # rule here inflates step_ewma until the breaker's
                    # slow verdict trips — the deterministic "replica is
                    # 5x slower than its peers" drill
                    chaos.site("serving.replica_slow")
                    t_step = time.monotonic()
                    for r in eng.step():
                        self._finish(rep, r)
                    rep.note_step(time.monotonic() - t_step)
                    if getattr(eng, "prefill_chunk", 0):
                        # chunk-prefilling admissions observe TTFT lazily
                        # — their first token lands in a later step() than
                        # their admission did. Gated on the engine actually
                        # chunking: non-chunked engines observe at
                        # admission, and this scan would only add frontend-
                        # lock traffic per step for nothing.
                        with self._lock:
                            pend = [e for e in rep.inflight.values()
                                    if not e.observed]
                        for e in pend:
                            self._observe_admission(e)
                    if rep.role == "prefill" and rep.inflight:
                        # disaggregation (ISSUE 16): requests with a first
                        # token owe their KV pages to the decode pool
                        progressed |= self._initiate_handoffs(rep)
                    progressed = True
                elif rep.state == DRAINING and not rep.inflight:
                    drained = self._drained.get(rep.name)
                    if drained is not None:  # vs a racing remove_replica
                        drained.set()
            except BaseException as e:
                # anything escaping the engine hooks is replica-fatal (the
                # hooks isolate request-level failures internally).
                # BaseException, not Exception: _admit_pending re-raises
                # BaseException after re-appending the in-transit entry, and
                # a SystemExit/KeyboardInterrupt on this thread must mark
                # the replica DEAD and relocate its work — a silently dead
                # dispatcher would leave the replica LIVE and its requests
                # hanging until the heartbeat deadline
                self._replica_died(rep, e)
                return
            if not progressed:
                # unlocked len() is a heuristic only: submit/_requeue append
                # BEFORE setting the wake event, so a stale empty read still
                # wakes immediately off the event
                idle = eng.idle() and not rep.pending
                if _tracing.enabled():
                    # serving goodput (ISSUE 7 satellite): dispatcher waits
                    # are the 'idle' slice of the serving wall-clock split
                    t_w = time.monotonic()
                    wake.wait(self.idle_wait_s if idle else self.poll_wait_s)
                    _goodput.serving_note("idle", time.monotonic() - t_w)
                else:
                    wake.wait(self.idle_wait_s if idle else self.poll_wait_s)
                wake.clear()

    def _admit_pending(self, rep):
        eng, moved = rep.engine, False
        while rep.state in ADMITTING and eng.has_free_slot():
            cap = self.brownout.prefill_depth_cap()
            if cap is not None:
                ap = getattr(eng, "active_prefills", None)
                if ap is not None and ap() >= cap:
                    # shed_prefill_depth rung (cheapest brownout step): a
                    # replica already advancing `cap` chunked prefills
                    # defers new admissions so in-flight decode keeps its
                    # cadence; nothing is rejected, prompts just queue
                    break
            with self._lock:
                i = self.scheduler.pick(rep.pending)
                if i is None:
                    break
                entry = rep.pending.pop(i)
                _M_QUEUE.set(sum(len(r.pending) for r in self.replicas))
            if entry.handle._cancel_requested:
                _M_CANCELLED.inc()
                entry.handle._cancelled_now()
                moved = True
                continue
            if self.scheduler.expired(entry):
                _M_EXPIRED.inc()
                _M_FAILED.inc()
                if entry.bundle_path is not None:
                    self.handoff.discard(entry.bundle_path)
                    entry.bundle_path = None
                self.slo.observe_event(entry.slo.name, "deadline_miss", True)
                mon = self._tenant_monitor(entry.tenant)
                if mon is not None:
                    mon.observe_event(entry.slo.name, "deadline_miss", True)
                entry.handle._fail(DeadlineExceeded(
                    f"request {entry.req.rid} ({entry.slo.name}) spent "
                    f"longer than its deadline queued"))
                moved = True
                continue
            # while the entry is in neither pending nor inflight, a death/
            # drain sweep cannot see it — every exit below must put it back
            # somewhere sweepable (or hand it to the relocation path) before
            # giving up the thread, or its handle would hang forever
            try:
                if entry.bundle_path is not None or entry.bundle is not None:
                    # a handed-off request: adopt its KV-page bundle into
                    # this replica's pool instead of prefilling from scratch
                    status = self._adopt_one(rep, entry)
                else:
                    # cluster KV fabric (ISSUE 18): before prefilling from
                    # scratch, try the tier ladder (host spill -> peer
                    # fetch) for a reusable prefix; any failure falls
                    # through to the recompute below, bit-identically
                    self._kv_acquire(rep, entry)
                    status = eng.try_admit_one(entry.req)
            except BaseException:
                # the raise is about to reach _run_replica, whose handler
                # calls _replica_died -> sweeps pending. That sweep is a
                # no-op if the monitor/kill() ALREADY declared the replica
                # DEAD while we were stuck in the engine call — an entry
                # re-appended then would never be swept again, so hand it
                # straight to the relocation path instead
                with self._lock:
                    already_dead = rep.state == DEAD
                    if not already_dead:
                        rep.pending.append(entry)  # swept by _replica_died
                if already_dead:
                    self._requeue(entry, exclude={rep.name},
                                  fail_reason=f"replica {rep.name} died "
                                              f"during admission: "
                                              f"{rep.death_reason}")
                raise
            if status == "requeued":
                # corrupt/stale bundle: _adopt_one already sent the entry
                # back through _requeue for a bit-identical re-prefill
                moved = True
                continue
            if status != "deferred" and entry.queue_span is not None:
                # queueing ends the moment the engine resolved the
                # admission (a deferred pick keeps waiting — span stays
                # open); the engine's own admit/prefill spans carry on
                entry.queue_span.end()
                entry.queue_span = None
            if status == "deferred":
                with self._lock:
                    stranded = rep.state not in ADMITTING
                    if not stranded:
                        rep.pending.append(entry)
                if stranded:  # the sweep ran while we held the entry
                    self._requeue(entry, exclude={rep.name},
                                  fail_reason=f"{rep.name} became "
                                              f"{rep.state} during admission")
                elif self._stop.is_set():
                    # shutdown's orphan sweep may have already swept this
                    # pending list while the entry was in transit; failing
                    # directly is idempotent with the sweep
                    entry.handle._fail("frontend shut down")
                break
            moved = True
            if status == "admitted":
                with self._lock:
                    dead = rep.state == DEAD
                    if not dead:
                        rep.inflight[entry.req.rid] = entry
                entry.handle._mark_running(rep.name)
                self._observe_admission(entry)
                self._kv_note_admitted(rep, entry)
                if entry.handle._cancel_requested:
                    entry.req.cancelled = True  # retires at next block
                if dead:  # death sweep missed the in-transit entry
                    self._relocate_inflight(entry, rep,
                                            f"replica {rep.name} died: "
                                            f"{rep.death_reason}")
                    break
                if self._stop.is_set():
                    # same transit race against shutdown's sweep
                    entry.handle._fail("frontend shut down")
                    break
            elif status == "done":
                entry.handle._mark_running(rep.name)
                self._observe_admission(entry)
                self._kv_note_admitted(rep, entry)
                self._finish(rep, entry.req, entry=entry)
            else:  # "failed"
                if entry.probe:
                    # half-open probes are diagnostic traffic (breaker.py
                    # contract): the breaker observed the failure; the
                    # caller must not eat it — an unconsumed request
                    # re-runs bit-identically on a healthy replica
                    self._breaker_outcome(rep, entry, ok=False)
                    self._relocate_inflight(
                        entry, rep, f"probe failed on {rep.name}: "
                                    f"{entry.req.error_message}")
                else:
                    _M_FAILED.inc()
                    entry.handle._fail(entry.req.error_message)
                    self._breaker_outcome(rep, entry, ok=False)
        return moved

    def _finish(self, rep, req, entry=None):
        if entry is None:
            with self._lock:
                entry = rep.inflight.pop(req.rid, None)
            if entry is None:
                return  # already resolved (reroute/cancel race)
        # a chunk-prefilling request that graduates AND retires in the same
        # engine step leaves inflight before the dispatcher's lazy TTFT
        # scan can see it — observe here (idempotent; skips entries that
        # never produced a first token)
        self._observe_admission(entry)
        if entry.needs_handoff:
            # finished before the handoff could initiate (short generation,
            # eos at the first block): blended completion — deliver the
            # suppressed tokens to the stream before the terminal transition
            if req.error is None and not req.cancelled:
                self._handoff_fallback(entry, "finished_on_prefill")
            else:
                entry.needs_handoff = False
        handle = entry.handle
        if req.error is not None:
            if entry.probe:
                # breaker.py contract: a failed probe is observed by the
                # breaker (below may even fail the replica hard) but the
                # CALLER does not eat it — unconsumed requests re-run
                # bit-identically elsewhere, consumed streams fail cleanly
                self._breaker_outcome(rep, entry, ok=False)
                self._relocate_inflight(
                    entry, rep,
                    f"probe failed on {rep.name}: {req.error_message}")
                return
            _M_FAILED.inc()
            handle._fail(req.error_message)
            self._breaker_outcome(rep, entry, ok=False)
        elif req.cancelled:
            _M_CANCELLED.inc()
            handle._cancelled_now()  # caller's choice: no breaker signal
        else:
            _M_COMPLETED.inc()
            self._observe_completion(entry)
            self.slo.observe_event(entry.slo.name, "deadline_miss", False)
            mon = self._tenant_monitor(entry.tenant)
            if mon is not None:
                mon.observe_event(entry.slo.name, "deadline_miss", False)
            handle._complete(req)
            self._breaker_outcome(rep, entry, ok=True)

    # ---- replica death / drain -------------------------------------------
    def kill(self, replica, reason="killed by operator"):
        """Declare a replica dead NOW (ops/test hook — the same path chaos
        and the heartbeat monitor take)."""
        self._replica_died(self._resolve_replica(replica),
                           RuntimeError(reason))

    def drain(self, replica, timeout=30.0):
        """Stop routing to ``replica``, finish its in-flight requests, and
        re-queue its pending (not-yet-admitted) requests onto the other
        replicas. Returns True once the replica is idle (False on timeout).
        The replica stays DRAINING — call revive() to return it to LIVE."""
        rep = self._resolve_replica(replica)
        with self._lock:
            if rep.state == DEAD:
                raise ValueError(f"{rep.name} is DEAD, nothing to drain")
            rep.state = DRAINING
            self._drained[rep.name].clear()
            pending, rep.pending = rep.pending, []
        for entry in pending:
            _M_DRAIN_REQUEUED.inc()
            self._requeue(entry, exclude={rep.name},
                          fail_reason=f"{rep.name} draining")
        self._wake(rep.name)
        # the DRAINED signal comes from the dispatcher thread only: it is
        # the one thread that can hold an entry in transit between pending
        # and inflight, so its own idle check can never fire early
        return self._drained[rep.name].wait(timeout)

    def revive(self, replica):
        """DRAINING/PROBATION -> LIVE (a drained or circuit-broken replica
        rejoining the pool by operator fiat)."""
        rep = self._resolve_replica(replica)
        with self._lock:
            if rep.state == DEAD:
                raise ValueError(f"{rep.name} is DEAD; spawn a replacement "
                                 f"(add_replica) instead of reviving")
            was_probation = rep.state == PROBATION
            rep.state = LIVE
        if was_probation:
            # fresh slate: leaving the probing state without the breaker's
            # own close verdict would otherwise leave its score stuck in
            # half-open — record()/note_slow() no-op while probing, so the
            # revived replica could never trip again
            self.breaker.forget(rep.name)
        self._wake(rep.name)

    def _resolve_replica(self, replica):
        if isinstance(replica, ReplicaHandle):
            return replica
        try:
            return self._by_name[replica]
        except KeyError:
            raise ValueError(f"unknown replica {replica!r}; have "
                             f"{sorted(self._by_name)}") from None

    def _replica_died(self, rep, exc):
        """Mark DEAD and relocate its work: queued + unconsumed in-flight
        requests re-route (identical outputs — key streams are replica-
        independent); consumed streams fail with the death reason."""
        with self._lock:
            if rep.state == DEAD:
                return
            rep.state = DEAD
            rep.death_reason = f"{type(exc).__name__}: {exc}"
            pending, rep.pending = rep.pending, []
            inflight, rep.inflight = list(rep.inflight.values()), {}
        _M_REPLICA_DEAD.inc()
        self.router.forget_replica(rep.name)
        self.breaker.forget(rep.name)
        # a corpse must neither attract fabric-aware placements nor be
        # dialed for peer fetches: drop its residency advertisements
        self.kvfabric.evict_replica(rep.name)
        reason = f"replica {rep.name} died: {rep.death_reason}"
        for entry in pending:
            self._requeue(entry, exclude={rep.name}, fail_reason=reason)
        for entry in inflight:
            self._relocate_inflight(entry, rep, reason)

    def _relocate_inflight(self, entry, rep, reason):
        """One in-flight entry whose replica just died: honor a racing
        cancel, fail a consumed stream (a restart would duplicate or reorder
        observed tokens), transparently re-route anything else (identical
        output — key streams are replica-independent)."""
        if entry.req.cancelled or entry.handle._cancel_requested:
            # the cancel raced the death: honor it now instead of rerouting
            # a request nobody wants (the clone would not carry the flag)
            _M_CANCELLED.inc()
            entry.handle._cancelled_now()
            return
        gen = entry.handle._reset_for_reroute()
        if gen is None:  # stream consumed — only a clean failure is safe
            _M_FAILED.inc()
            entry.handle._fail(reason)
            return
        # the clone keeps t_enqueue so the NEXT admission's queue_wait/ttft
        # samples span the whole journey including the dead replica's time
        # (clone_for_retry's contract) — re-arm the once-only observation
        entry.observed = False
        entry.req = entry.req.clone_for_retry()
        # disaggregation (ISSUE 16): a dead replica invalidates whatever
        # handoff state the entry carried — drop any unconsumed bundle and
        # bump the generation fence so a superseded prefill's late bundle
        # is stale on arrival, then re-arm the handoff if the fleet still
        # disaggregates (else complete blended, tokens streaming normally)
        if entry.bundle_path is not None:
            self.handoff.discard(entry.bundle_path)
        entry.bundle = None
        entry.bundle_path = None
        entry.handoff_gen += 1
        if self._disagg_active() and entry.handoff_gen < 3 \
                and self._decode_pool_live():
            entry.needs_handoff = True
            entry.target_role = "prefill"
            entry.req.on_token = None
        else:
            if entry.needs_handoff or entry.target_role is not None:
                _count_handoff_fallback("replica_died")
            entry.needs_handoff = False
            entry.target_role = None
            entry.req.on_token = self._make_on_token(entry.handle, gen)
        self._requeue(entry, exclude={rep.name}, fail_reason=reason,
                      rerouted=True)

    def _requeue(self, entry, exclude, fail_reason, rerouted=False):
        if entry.handle.done():
            return
        # status flips BEFORE the entry becomes visible in a pending list:
        # flipping after the append races the target dispatcher, whose
        # _mark_running could land first and be clobbered back to QUEUED
        # for the rest of the request's run
        entry.handle._mark_queued()
        exclude = set(exclude)
        # the trace's reroute edge: the attempt on the excluded replica is
        # over (death, drain, strand) — close it and stamp the edge before
        # the replacement attempt opens
        self._trace_reroute(entry, next(iter(exclude), None), fail_reason)
        while True:
            try:
                target = self.router.place(entry, self.replicas,
                                           exclude=exclude)
            except Exception as e:  # NoLiveReplicas, chaos faults, ...
                _M_FAILED.inc()
                entry.handle._fail(f"{fail_reason}; re-route failed: {e}")
                return
            self._trace_commit(entry, target)
            with self._lock:
                # re-check under the lock: the target can die or start
                # draining between place() and here, and an entry appended
                # to a swept pending list would never be seen again — same
                # for shutdown's orphan sweep (the monitor thread can still
                # be relocating a dead replica's work while it runs)
                if self._stop.is_set():
                    shut_down = True
                else:
                    shut_down = False
                    if target.state == LIVE or (entry.probe
                                                and target.state == PROBATION):
                        target.pending.append(entry)
                        break
            if shut_down:
                # idempotent with the sweep: _fail is once-only
                _M_FAILED.inc()
                entry.handle._fail("frontend shut down")
                return
            self._trace_attempt_end(entry, "rerouted",
                                    reason=f"{target.name} not LIVE")
            exclude.add(target.name)
        self.router.committed(entry, target)
        if rerouted:
            _M_REROUTED.inc()
        self._wake(target.name)

    def _run_monitor(self):
        """Heartbeat watchdog over the dispatcher threads: a replica whose
        dispatcher stops beating (wedged in a jitted call, killed by a
        chaos fault that swallowed the thread) is declared DEAD so its
        requests relocate instead of hanging their handles forever. Also
        the control cadence for the closed loops (ISSUE 12): per-replica
        dispatch-pace verdicts feed the circuit breaker, and the fleet
        pressure sample drives the brownout ladder."""
        while not self._stop.is_set():
            now = time.monotonic()
            for rep in self.replicas:
                self._check_replica_liveness(rep, now)
                # fabric residency rollup feed (ISSUE 18): stamped here so
                # the replica snapshot (and the fleet aggregator's
                # fleet.serving.kv_resident sum) tracks the fabric map
                # without a lock — single monitor writer, advisory reads
                rep.kv_resident = self.kvfabric.residency_count(rep.name)
                # capacity advertisement (ISSUE 19 satellite): the fabric
                # ranks peer fetches by this load signal and skips
                # saturated peers entirely
                try:
                    self.kvfabric.set_peer_load(rep.name, rep.load())
                except Exception:
                    pass  # a mid-death replica must not wedge the monitor
            self._check_replica_pace()
            self.brownout.observe(self._pressure())
            # per-tenant isolation (ISSUE 19): each tenant's private
            # ladder follows its OWN pressure (bucket drain, inflight
            # cap) — a storming tenant browns out alone while the fleet
            # ladder, fed above, stays wherever fleet pressure puts it
            for t in self.tenants.tenants():
                t.brownout.observe(t.pressure())
            self._stop.wait(self.monitor_interval_s)

    def _check_replica_liveness(self, rep, now):
        """One monitor verdict for one replica (factored out so tests can
        drive it with crafted lock/beat states). Flap damping (ISSUE 12
        satellite): the DEAD verdict needs ``heartbeat_misses`` CONSECUTIVE
        stale observations — a beat that recovers in between was a flap
        (one slow scrape, a GC pause), counted on ``serving.replica_flaps``
        instead of triggering a full reroute storm."""
        if rep.state == DEAD:
            return
        if now - rep.last_beat <= self.heartbeat_deadline_s:
            if rep.missed_beats:
                _M_FLAPS.inc()
                rep.missed_beats = 0
            return
        # Lock decomposition (ISSUE 6): jitted execution serializes on the
        # replica's OWN engine lock; only first-compiles take the shared
        # process-wide compile lock, where N serialized traces can silence
        # a dispatcher for the SUM of compile times. A replica whose
        # dispatcher participates in EITHER lock (holder or blocked
        # acquirer) under a hold younger than the deadline is compiling or
        # queued behind a compile, not dead — defer the (irreversible)
        # verdict. Both conditions matter: a dispatcher wedged OUTSIDE the
        # locks (post-readback host work, a blocking user callback) must
        # not ride out its verdict on other threads' healthy compiles, and
        # a hold OLDER than the deadline is itself a hung device call —
        # deferring then would hang every handle forever, so the verdict
        # proceeds and the work relocates (or, once every blocked replica
        # is declared, fails cleanly).
        locks = [_COMPILE_LOCK]
        own = getattr(rep.engine, "dispatch_lock", None)
        if own is not None:
            locks.append(own)
        for lock in locks:
            if rep.thread_ident in lock.participants():
                held = lock.held_since()
                if held is None or now - held <= self.heartbeat_deadline_s:
                    return  # compiling, or queued behind a fresh hold
        rep.missed_beats += 1
        if rep.missed_beats < self.heartbeat_misses:
            return  # damped: not dead until the miss budget runs out
        self._replica_died(rep, TimeoutError(
            f"dispatcher heartbeat stale {now - rep.last_beat:.1f}s "
            f"(> {self.heartbeat_deadline_s}s) for {rep.missed_beats} "
            f"consecutive monitor checks"))

    def _check_replica_pace(self):
        """Per-tick dispatch-latency verdicts for the circuit breaker: a
        LIVE replica whose step EWMA exceeds ``slow_ratio`` x the
        cross-replica median (the PR-11 compute-straggler classification
        applied to serving dispatch) collects a slow strike; enough
        consecutive strikes trip it into PROBATION."""
        reps = [r for r in self.replicas
                if r.state == LIVE and r.step_samples >= 3]
        if len(reps) < 2:
            return  # no peers to be slower than
        ewmas = sorted(r.step_ewma for r in reps)
        # LOWER median: with an even replica count the upper median IS the
        # slowest minority member (2 replicas: the straggler itself, which
        # can never exceed slow_ratio x its own pace) — the lower median
        # stays anchored on the healthy majority
        median = ewmas[(len(ewmas) - 1) // 2]
        if median <= 0.0:
            return
        ratio = self.breaker.policy.slow_ratio
        for r in reps:
            if r.step_ewma > ratio * median:
                if self.breaker.note_slow(r.name) == "trip":
                    self._trip_replica(r)
            else:
                self.breaker.note_on_pace(r.name)

    def _pressure(self):
        """The brownout ladder's input: the fleet rollup's pressure blend
        (mean LIVE occupancy vs queue/slots) without the report machinery
        — cheap enough for every monitor tick. Computed PER ROLE and the
        worst pool wins (ISSUE 16): a saturated prefill pool must engage
        the shed rungs even when an idle decode pool would dilute a
        fleet-wide mean to comfortable."""
        worst = 0.0
        for _, occs, slots, queued in self._pressure_by_role():
            queue_pressure = (min(1.0, queued / slots) if slots
                              else (1.0 if queued else 0.0))
            occupancy = sum(occs) / len(occs) if occs else 0.0
            worst = max(worst, occupancy, queue_pressure)
        return worst

    def _pressure_by_role(self):
        """[(role, live_occupancies, live_slots, queued)] per replica role
        — the shared accumulation under _pressure and the supervisor's
        per-role scale pressure."""
        by_role = {}
        for r in self.replicas:
            occs, slots, queued = by_role.get(r.role, ([], 0, 0))
            queued += len(r.pending)
            if r.state == LIVE:
                occs.append(r.engine.active_count() / r.engine.max_seqs)
                slots += r.engine.max_seqs
            by_role[r.role] = (occs, slots, queued)
        return [(role, occs, slots, queued)
                for role, (occs, slots, queued) in by_role.items()]

    # ---- circuit breaking (ISSUE 12) --------------------------------------
    def _breaker_outcome(self, rep, entry, ok):
        """One request outcome lands on the breaker; its verdicts become
        replica state transitions (every state write under self._lock).
        Probe outcomes drive the half-open ladder; normal outcomes feed
        the windowed error score."""
        if entry.probe:
            verdict = self.breaker.probe_result(rep.name, ok)
            if verdict == "close":
                with self._lock:
                    if rep.state == PROBATION:
                        rep.state = LIVE
                self._wake(rep.name)
            elif verdict == "fail_hard":
                self._replica_died(rep, RuntimeError(
                    f"circuit breaker: "
                    f"{self.breaker.policy.probation_failures} consecutive "
                    f"probe failures after trip"))
            return
        if self.breaker.record(rep.name, ok) == "trip":
            self._trip_replica(rep)

    def _trip_replica(self, rep):
        """LIVE -> PROBATION: normal routing stops (the router only sends
        rate-limited probes), the pending queue re-routes to healthy
        replicas NOW — in-flight work finishes where it is (retiring it
        would waste the decode slots it already paid for)."""
        with self._lock:
            if rep.state != LIVE:
                return
            rep.state = PROBATION
            pending, rep.pending = rep.pending, []
        reason = (self.breaker.tripped_reason(rep.name)
                  or "circuit breaker tripped")
        for entry in pending:
            self._requeue(entry, exclude={rep.name},
                          fail_reason=f"{rep.name} tripped: {reason}")

    # ---- fleet membership (ISSUE 12: the supervisor's spawn/retire) -------
    def add_replica(self, engine, name=None, domain=None, fence=None,
                    role="blended"):
        """Grow the pool by one replica (the supervisor's spawn path; also
        an ops hook). The dispatcher starts immediately when the frontend
        is running. ``domain`` groups replicas into failure domains for
        the supervisor's restart budgets; ``fence`` is the PR-9-contract
        generation fence rejecting a superseded incarnation's telemetry
        writes; ``role`` joins the replica to a disaggregation pool
        ("prefill"/"decode"/"blended", ISSUE 16)."""
        with self._lock:
            if self._stop.is_set():
                raise RuntimeError("frontend is shut down")
            idx = self._next_index
            self._next_index += 1
            rep = ReplicaHandle(name or f"replica{idx}", engine, index=idx,
                                role=role)
            if rep.name in self._by_name:
                raise ValueError(f"replica name {rep.name!r} already exists")
            rep.domain = domain or rep.name
            rep.fence = fence
            self._wakes[rep.name] = threading.Event()
            self._drained[rep.name] = threading.Event()
            # copy-on-write: unlocked readers iterate either the old or
            # the new list, never a half-mutated one
            self.replicas = self.replicas + [rep]
            self._by_name[rep.name] = rep
            started = self._started
        if started:
            # prune exited dispatchers (removed/replaced replicas) so a
            # long-running supervisor's churn can't grow this list —
            # shutdown() joins it in full
            self._threads = [t for t in self._threads if t.is_alive()]
            t = threading.Thread(target=self._run_replica, args=(rep,),
                                 daemon=True,
                                 name=f"paddle-serving-{rep.name}")
            self._threads.append(t)
            t.start()
        return rep

    def remove_replica(self, replica):
        """Drop a DEAD (or drained DRAINING) replica from the pool and
        retire its labeled gauges — the supervisor's cleanup after a
        replacement or scale-down. Refuses replicas still holding work:
        drain() first."""
        rep = self._resolve_replica(replica)
        with self._lock:
            if rep.state not in (DEAD, DRAINING):
                raise ValueError(f"{rep.name} is {rep.state}; drain() or "
                                 f"kill() it before removing")
            if rep.pending or rep.inflight:
                raise ValueError(
                    f"{rep.name} still holds work ({len(rep.pending)} "
                    f"pending, {len(rep.inflight)} in flight) — drain() it")
            rep.state = DEAD  # a DRAINING dispatcher exits on next wake
            self.replicas = [r for r in self.replicas if r is not rep]
            self._by_name.pop(rep.name, None)
        self._wake(rep.name)
        self._wakes.pop(rep.name, None)
        self._drained.pop(rep.name, None)
        self.router.forget_replica(rep.name)
        self.breaker.forget(rep.name)
        self.kvfabric.evict_replica(rep.name)
        rep.retire_gauges()

    def fleet_signal(self):
        """The autoscaler's read: just the ``serving_report()["fleet"]``
        rollup (pressure / scale_hint / worst burn) without the rest of
        the report machinery — what the supervisor polls per tick."""
        with self._lock:
            replicas = {r.name: r.snapshot() for r in self.replicas}
        return _fleet.serving_rollup(replicas, self.slo.report(),
                                     _goodput.serving.report())

    # ---- request-scoped tracing (ISSUE 7) ---------------------------------
    def _trace_commit(self, entry, rep):
        """One placement landed (or is about to): open the attempt subtree
        — attempt span, place event (replica/score/affinity), queue span —
        and hand the attempt span to the EngineRequest so the engine's
        admit/prefill/decode spans nest under it."""
        tr = entry.trace
        if tr is None:
            return
        n = entry.attempt_n
        entry.attempt_n = n + 1
        entry.attempt_span = tr.root.child("attempt", n=n, replica=rep.name)
        entry.attempt_span.event(
            "place", replica=rep.name, affinity=entry.route_affinity,
            score=round(entry.route_score, 4))
        entry.queue_span = entry.attempt_span.child(
            "queue",
            slo=entry.slo.name,
            virtual_deadline_in_s=round(
                entry.virtual_deadline - entry.req.t_enqueue, 4))
        entry.req.trace = entry.attempt_span

    def _trace_attempt_end(self, entry, status, reason=None):
        """Close the open attempt subtree (reroute, drain, lost placement
        race). Idempotent; the handle's terminal finish() sweeps anything
        this missed."""
        if entry.trace is None or entry.attempt_span is None:
            return
        if entry.queue_span is not None:
            entry.queue_span.end(status)
            entry.queue_span = None
        entry.attempt_span.end(
            status, **({"reason": str(reason)} if reason else {}))
        entry.attempt_span = None

    def _trace_reroute(self, entry, from_replica, reason):
        """The reroute edge: close the failed attempt, stamp the edge on
        the root — trace_view renders failed attempt -> reroute -> replay
        as one tree."""
        if entry.trace is None:
            return
        self._trace_attempt_end(entry, "failed", reason=reason)
        entry.trace.root.event("reroute", from_replica=from_replica,
                               reason=str(reason))

    # ---- telemetry --------------------------------------------------------
    def _class_hist(self, family, slo_name, tenant=None):
        # short kind key for serving_report's per-class section; the third
        # key element is the tenant name (None = the fleet-wide series —
        # byte-identical labels to the pre-tenancy plane)
        key = (family[len("serving."):], slo_name,
               tenant.name if tenant is not None else None)
        with self._lock:  # dispatchers insert, serving_report() iterates
            h = self._class_hists.get(key)
            if h is None:
                # labeled series (ISSUE 7 satellite): one family per kind,
                # {slo_class=...} per class — scrapers aggregate across
                # classes, which per-class metric NAMES made impossible.
                # The tenant label (ISSUE 19) is BOUNDED by construction:
                # only a declared Tenant's .name ever reaches a labels
                # dict (the tenant-label-bounded analysis rule pins this)
                if tenant is not None:
                    labels = {"slo_class": slo_name, "tenant": tenant.name}
                else:
                    labels = {"slo_class": slo_name}
                h = self._class_hists[key] = _registry.histogram(
                    family, labels=labels,
                    help="per-SLO-class control-plane latency")
            return h

    def _tenant_monitor(self, tenant):
        """The tenant's lazily-minted SLO burn-rate monitor; None for the
        default tenant (its traffic stays on the fleet monitor alone —
        the pre-tenancy gauge series must not change shape)."""
        if tenant is None or tenant.name == DEFAULT_TENANT:
            return None
        with self._lock:
            mon = self._tenant_slo.get(tenant.name)
            if mon is None:
                mon = self._tenant_slo[tenant.name] = SLOMonitor(
                    classes=self.scheduler.classes.values(),
                    gauge_labels={"tenant": tenant.name})
            return mon

    def _observe_admission(self, entry):
        if entry.observed:
            return  # once per admission (reroutes re-arm the flag so the
            # failover tail lands in the histograms)
        if entry.needs_handoff or entry.bundle_path is not None \
                or entry.bundle is not None:
            return  # mid-handoff (satellite 2): the client has seen no
            # token yet — TTFT is observed at decode-side delivery so the
            # prefill queue wait AND the transfer land in the histogram
        if entry.req.t_first_token is None:
            return  # chunked prefill still streaming: no first token yet —
            # the dispatcher re-checks after every step()
        entry.observed = True
        req, name = entry.req, entry.slo.name
        queue_wait = req.t_admit - req.t_enqueue
        ttft = req.t_first_token - req.t_enqueue
        self._class_hist("serving.queue_wait_s", name).observe(queue_wait)
        self._class_hist("serving.ttft_s", name).observe(ttft)
        self.slo.observe(name, "ttft", ttft)
        mon = self._tenant_monitor(entry.tenant)
        if mon is not None:
            # tenant-labeled twins of the fleet series (ISSUE 19): the
            # fleet histograms above keep EVERY request, so aggregation
            # never depends on summing tenant slices
            self._class_hist("serving.queue_wait_s", name,
                             tenant=entry.tenant).observe(queue_wait)
            self._class_hist("serving.ttft_s", name,
                             tenant=entry.tenant).observe(ttft)
            mon.observe(name, "ttft", ttft)

    def _observe_completion(self, entry):
        req = entry.req
        if req.n_generated > 1 and req.t_first_token is not None:
            tpot = (req.t_done - req.t_first_token) / (req.n_generated - 1)
            self._class_hist("serving.tpot_s", entry.slo.name).observe(tpot)
            self.slo.observe(entry.slo.name, "tpot", tpot)
            mon = self._tenant_monitor(entry.tenant)
            if mon is not None:
                self._class_hist("serving.tpot_s", entry.slo.name,
                                 tenant=entry.tenant).observe(tpot)
                mon.observe(entry.slo.name, "tpot", tpot)

    def serving_report(self):
        """One structured snapshot of the whole control plane: per-replica
        health/occupancy, per-SLO-class latency summaries, and every
        serving.* counter — the operator's `kubectl describe` for the
        serving cell."""
        with self._lock:
            hists = sorted(
                self._class_hists.items(),
                key=lambda kv: tuple(str(k) for k in kv[0]))
            replicas = {r.name: r.snapshot() for r in self.replicas}
        # fleet-wide series only (tenant key None) — the tenant-labeled
        # twins land in the "tenants" section below, so this block stays
        # byte-compatible with the pre-tenancy report
        classes = {}
        for (kind, name, tname), h in hists:
            if tname is None:
                classes.setdefault(name, {})[kind] = _hist_summary(h)
        counters = {n: _registry.get(n).value for n in _registry.names("serving.")
                    if hasattr(_registry.get(n), "value")
                    and not hasattr(_registry.get(n), "hwm")}
        slo_report = self.slo.report()
        goodput_report = _goodput.serving.report()
        out = {
            "replicas": replicas,
            "slo_classes": classes,
            "counters": {k: v for k, v in counters.items() if v},
            "queue_depth": sum(len(r.pending) for r in self.replicas),
            # SLO burn rates + multi-window alerts (ISSUE 7)
            "slo": slo_report,
            # serving goodput split (ISSUE 7 satellite): engine wall clock
            # classified {prefill, decode, host_emit, idle, compile};
            # populated when telemetry is enabled (the goodput gate)
            "goodput": goodput_report,
            # cluster serving rollup (ISSUE 11): live replicas, cluster
            # queue/occupancy, worst multi-window burn, and ONE blended
            # pressure/scale_hint signal — what an autoscaler reads
            "fleet": _fleet.serving_rollup(replicas, slo_report,
                                           goodput_report),
            # compile ledger + HBM budget (ISSUE 8): cold-program counts,
            # churn alerts, and KV-pool/params bytes vs device capacity
            "compile": _compilemem.ledger.report(recent=8),
            "memory": _compilemem.memory.report(),
            # closed-loop state (ISSUE 12): the brownout ladder's rung +
            # history and the circuit breaker's per-replica scores
            "brownout": self.brownout.report(),
            "breaker": self.breaker.report(),
            # device-time attribution (ISSUE 17): per-program
            # device-seconds / MFU / roofline verdicts and the decode
            # device-s-per-token budget ({"enabled": False} while the
            # devprof plane is disarmed)
            "devprof": _devprof.serving_block(),
            # cluster KV fabric (ISSUE 18): tier hit/fallthrough counters,
            # spill-ring occupancy, and the residency map (/kvz's payload)
            "kv": self.kvfabric.report(),
            # multi-tenant plane (ISSUE 19): per-tenant quota/bucket/
            # inflight state, private brownout rung, lazily-minted SLO
            # burn rates, and tenant-labeled latency summaries — also
            # served standalone at /tenantz
            "tenants": self.tenant_report(),
            # LoRA adapter host cache (ISSUE 19): residency, bytes, and
            # per-adapter inflight pins
            "adapters": self.adapters.report(),
        }
        if self.supervisor is not None:
            out["supervisor"] = self.supervisor.report()
        return out

    def tenant_report(self):
        """Per-tenant rollup — ``serving_report()["tenants"]`` and the
        ``/tenantz`` payload: each declared tenant's quota/bucket/inflight
        state and private brownout ladder (``Tenant.report()``), plus its
        SLO burn-rate monitor and tenant-labeled latency summaries when
        the tenant has produced observations."""
        with self._lock:
            hists = list(self._class_hists.items())
            mons = dict(self._tenant_slo)
        latency = {}
        for (kind, name, tname), h in hists:
            if tname is not None:
                latency.setdefault(tname, {}).setdefault(
                    name, {})[kind] = _hist_summary(h)
        out = {}
        for t in self.tenants.tenants():
            rep = t.report()
            mon = mons.get(t.name)
            if mon is not None:
                rep["slo"] = mon.report()
            lat = latency.get(t.name)
            if lat:
                rep["latency"] = lat
            out[t.name] = rep
        return out
