"""Per-request LoRA adapter registry (ISSUE 19 tentpole, part 3).

The fine-tune -> serve loop (PAPERS.md: Gemma on Cloud TPU) needs one
base model to serve MANY customers' low-rank deltas. This module owns
the host side of that plane: a ref-counted, LRU-bounded, digest-keyed
cache of rank-r A/B pairs. The device side lives in
``inference/continuous.py`` — the engine gathers stacked adapter
weights per batch row inside the decode program, so one batch serves
mixed adapters with zero recompiles across warmed signatures.

Adapter math (the engine's contract): the adapter is a low-rank update
to the LM-head projection —

    logits = base_head(h) + scale * (h @ A) @ B

with ``A [hidden, r]`` and ``B [r, vocab]`` float32. No-adapter rows
ride the zero slot of the stacked weights (a ``+ 0.0`` delta), and a
batch with NO adapters at all dispatches the untouched base programs —
byte-for-byte the pre-LoRA path.

Trust & size limits (the operator boundary, docs/SERVING.md): adapter
weights are tenant-supplied DATA, never code — plain float32 arrays,
validated by shape/dtype at registration; anything else is a typed
``ValueError``. ``PADDLE_LORA_MAX_MB`` bounds one adapter (a monster
upload must not flush every co-tenant's adapters) and
``PADDLE_LORA_CACHE_MB`` bounds the whole cache; eviction is LRU over
refcount-0 entries only, so an adapter pinned by in-flight requests can
never be evicted out from under them.

Identity is the content digest (keyed blake2b over A, B, scale):
re-registering identical weights under any name is idempotent, and the
digest is what the engine's device cache, the router's affinity score,
and the handoff/KV planes key on — names are a human alias.
"""
import hashlib
import threading
from collections import OrderedDict

import numpy as np

from ..observability.metrics import registry as _registry
from ..utils.envs import env_int

__all__ = ["LoRAAdapter", "AdapterRegistry"]

_G_CACHE_BYTES = _registry.gauge(
    "lora.cache_bytes", help="host bytes resident in the adapter cache")
_G_CACHE_ENTRIES = _registry.gauge(
    "lora.cache_entries", help="adapters resident in the host cache")
_M_REGISTERED = _registry.counter(
    "lora.registered", help="adapter registrations accepted (idempotent "
                            "re-registrations not counted)")
_M_EVICTED = _registry.counter(
    "lora.evicted", help="refcount-0 adapters LRU-evicted to make room")


class LoRAAdapter:
    """One immutable adapter: ``a [hidden, r]``, ``b [r, vocab]``
    float32, a scalar ``scale``, and the content digest that names it
    everywhere below the registry."""

    __slots__ = ("name", "a", "b", "scale", "rank", "digest", "nbytes")

    def __init__(self, name, a, b, scale=1.0):
        a = np.asarray(a)
        b = np.asarray(b)
        if a.ndim != 2 or b.ndim != 2 or a.shape[1] != b.shape[0]:
            raise ValueError(
                f"adapter {name!r}: need a [hidden, r] and b [r, vocab] "
                f"with matching r, got {a.shape} / {b.shape}")
        if a.dtype != np.float32 or b.dtype != np.float32:
            # the trust boundary: adapter weights are float32 DATA only —
            # object/structured dtypes (or anything needing conversion
            # tricks) are refused, not coerced
            raise ValueError(
                f"adapter {name!r}: weights must be float32, got "
                f"{a.dtype} / {b.dtype}")
        self.name = str(name)
        self.a = np.ascontiguousarray(a)
        self.b = np.ascontiguousarray(b)
        self.scale = float(scale)
        self.rank = int(a.shape[1])
        if self.rank < 1:
            raise ValueError(f"adapter {name!r}: rank must be >= 1")
        self.nbytes = self.a.nbytes + self.b.nbytes
        h = hashlib.blake2b(digest_size=16, key=b"paddle-lora-v1")
        h.update(self.a.tobytes())
        h.update(self.b.tobytes())
        h.update(np.float64(self.scale).tobytes())
        self.digest = h.hexdigest()

    def __repr__(self):
        return (f"LoRAAdapter({self.name!r}, rank={self.rank}, "
                f"scale={self.scale}, digest={self.digest[:8]}...)")


class AdapterRegistry:
    """Ref-counted LRU host cache of :class:`LoRAAdapter`.

    ``register`` validates and inserts; ``acquire``/``release`` bracket
    a request's use (the frontend acquires at submit, releases at the
    handle's terminal transition), and eviction only ever touches
    refcount-0 entries. Lookup is by name OR digest.
    """

    def __init__(self, max_bytes=None, max_adapter_bytes=None):
        self.max_bytes = (env_int("PADDLE_LORA_CACHE_MB", 256) * (1 << 20)
                          if max_bytes is None else int(max_bytes))
        self.max_adapter_bytes = (
            env_int("PADDLE_LORA_MAX_MB", 64) * (1 << 20)
            if max_adapter_bytes is None else int(max_adapter_bytes))
        self._lock = threading.Lock()
        self._by_name = OrderedDict()   # name -> LoRAAdapter (LRU order)
        self._by_digest = {}            # digest -> LoRAAdapter
        self._refs = {}                 # digest -> inflight refcount
        self._nbytes = 0

    def __len__(self):
        with self._lock:
            return len(self._by_name)

    @property
    def nbytes(self):
        return self._nbytes

    # ---- registration -----------------------------------------------------
    def register(self, name, a, b, scale=1.0):
        """Validate + insert; returns the LoRAAdapter. Idempotent for
        identical content under the same name; replacing a name's weights
        is allowed only while no request holds the old ones (an in-flight
        request's adapter must stay exactly what it resolved). Raises
        ``ValueError`` on malformed weights, an over-limit adapter, or a
        cache that cannot fit it even after evicting every idle entry."""
        adapter = LoRAAdapter(name, a, b, scale=scale)
        if adapter.nbytes > self.max_adapter_bytes:
            raise ValueError(
                f"adapter {name!r} is {adapter.nbytes} bytes > "
                f"max_adapter_bytes={self.max_adapter_bytes} "
                f"(PADDLE_LORA_MAX_MB)")
        with self._lock:
            old = self._by_name.get(adapter.name)
            if old is not None:
                if old.digest == adapter.digest:
                    self._by_name.move_to_end(adapter.name)
                    return old          # identical content: idempotent
                if self._refs.get(old.digest, 0) > 0:
                    raise ValueError(
                        f"adapter {name!r} is held by in-flight requests; "
                        f"register the new weights under a new name")
                self._drop_locked(old)
            self._evict_for_locked(adapter.nbytes)
            if self._nbytes + adapter.nbytes > self.max_bytes:
                raise ValueError(
                    f"adapter cache full: {self._nbytes} + {adapter.nbytes}"
                    f" bytes > max_bytes={self.max_bytes} and every "
                    f"resident adapter is held by in-flight requests")
            self._by_name[adapter.name] = adapter
            self._by_digest[adapter.digest] = adapter
            self._nbytes += adapter.nbytes
            _M_REGISTERED.inc()
            self._set_gauges_locked()
        return adapter

    def _drop_locked(self, adapter):
        self._by_name.pop(adapter.name, None)
        self._by_digest.pop(adapter.digest, None)
        self._refs.pop(adapter.digest, None)
        self._nbytes -= adapter.nbytes
        self._set_gauges_locked()

    def _evict_for_locked(self, need):
        # LRU over refcount-0 entries only: a pinned adapter is never
        # evicted out from under the requests decoding with it
        while self._nbytes + need > self.max_bytes:
            victim = None
            for ad in self._by_name.values():       # LRU order
                if self._refs.get(ad.digest, 0) == 0:
                    victim = ad
                    break
            if victim is None:
                return
            self._drop_locked(victim)
            _M_EVICTED.inc()

    def _set_gauges_locked(self):
        _G_CACHE_BYTES.set(self._nbytes)
        _G_CACHE_ENTRIES.set(len(self._by_name))

    # ---- lookup / refcounting ---------------------------------------------
    def _resolve_locked(self, ref):
        if isinstance(ref, LoRAAdapter):
            ref = ref.digest
        ad = self._by_digest.get(ref)
        if ad is None:
            ad = self._by_name.get(ref)
        return ad

    def get(self, ref):
        """Name | digest | LoRAAdapter -> LoRAAdapter | None (no ref)."""
        with self._lock:
            return self._resolve_locked(ref)

    def acquire(self, ref):
        """Resolve + pin for one in-flight request; raises ``ValueError``
        for an unknown ref (requests must name REGISTERED adapters — the
        bounded-vocabulary contract the metric labels also lean on)."""
        with self._lock:
            ad = self._resolve_locked(ref)
            if ad is None:
                raise ValueError(f"unknown LoRA adapter {ref!r}")
            self._refs[ad.digest] = self._refs.get(ad.digest, 0) + 1
            self._by_name.move_to_end(ad.name)
            return ad

    def release(self, ref):
        """Unpin (idempotent past zero — a double release never
        underflows into negative pins)."""
        with self._lock:
            ad = self._resolve_locked(ref)
            if ad is None:
                return
            n = self._refs.get(ad.digest, 0)
            if n <= 1:
                self._refs.pop(ad.digest, None)
            else:
                self._refs[ad.digest] = n - 1

    def refcount(self, ref):
        with self._lock:
            ad = self._resolve_locked(ref)
            return 0 if ad is None else self._refs.get(ad.digest, 0)

    # ---- introspection ----------------------------------------------------
    def report(self):
        with self._lock:
            return {
                "entries": len(self._by_name),
                "bytes": self._nbytes,
                "max_bytes": self.max_bytes,
                "max_adapter_bytes": self.max_adapter_bytes,
                "adapters": [
                    {"name": ad.name, "digest": ad.digest,
                     "rank": ad.rank, "scale": ad.scale,
                     "nbytes": ad.nbytes,
                     "inflight": self._refs.get(ad.digest, 0)}
                    for ad in self._by_name.values()],
            }
