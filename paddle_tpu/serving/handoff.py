"""KV-page handoff between disaggregated prefill and decode replicas.

Disaggregated serving (docs/SERVING.md "Disaggregated prefill/decode")
splits the fleet into a prefill pool and a decode pool so one long
prefill can never head-of-line-block interactive decode. The seam
between the pools is this module: after a prefill replica produces a
request's first token, it exports the request's KV pages and publishes
them as a **handoff bundle**; a decode replica adopts the pages into its
own pool and continues decoding bit-identically (the sampled key stream
depends only on (seed, rid, index), and the bundle carries the exact
sampling state, generated tokens, and dispatch count).

Robustness contract (the reason this file exists, ISSUE 16):

1. **Atomic.** Bundles are written with the checkpoint tree's
   temp+fsync+rename discipline (:func:`...checkpoint.atomic.atomic_write`
   — the ckpt-atomic-write lint covers this package too), so a writer
   killed at any instruction leaves either nothing or a fully committed
   file — never a torn bundle under the real name.
2. **Validated.** The frame carries a blake2b digest over the payload
   plus the prefill engine's chained per-page prompt digests (PR 6's
   prefix-index chain). A torn, truncated, or bit-flipped bundle raises
   a typed :class:`HandoffCorruptError` at adopt — the frontend answers
   with a clean re-prefill. The payload itself is the non-executable
   :mod:`.wireformat` encoding (bundles cross an unauthenticated wire
   under ``PADDLE_KV_TRANSPORT=wire``, so the decoder must not be able
   to express code — see wireformat's trust-model notes). A corrupt or
   hostile bundle can cost latency, never a wrong token.
3. **Fenced.** Every (re-)prefill of a request bumps its handoff
   generation; the bundle stamps the generation it was built under, and
   the adopter rejects mismatches with :class:`StaleHandoffError` — a
   superseded prefill replica's late bundle can never clobber the retry
   that replaced it.
4. **Bounded.** Publish retries under a deadline with exponential
   backoff; past the deadline the caller falls back to blended mode
   (the prefill replica finishes the request itself), so handoff is
   only ever a perf win, never an availability loss.

Chaos seams: ``serving.handoff.send`` (per publish attempt),
``serving.handoff.adopt`` (per adopt attempt), ``serving.handoff.corrupt``
(between fsync and rename — a ``truncate`` rule here commits a torn file
the digest gate must catch). See docs/CHAOS.md.
"""
import hashlib
import os
import struct
import tempfile
import time

from ..distributed.checkpoint.atomic import atomic_write
from ..observability.metrics import registry as _registry
from ..testing import chaos
from ..utils.envs import env_float, env_int, env_str
from . import wireformat

__all__ = ["HandoffError", "HandoffCorruptError", "StaleHandoffError",
           "HandoffBundle", "HandoffManager", "page_digests"]

#: frame magic ("paddle_tpu handoff v1") — a loader pointed at a foreign
#: file fails the cheap prefix check before touching the decoder
_MAGIC = b"PTHO1\n"
_LEN = struct.Struct(">Q")
_DIGEST_SIZE = 16

_M_PUBLISHED = _registry.counter("serving.handoff.published")
_M_ADOPTED = _registry.counter("serving.handoff.adopted")
_M_CORRUPT = _registry.counter("serving.handoff.corrupt")
_M_STALE = _registry.counter("serving.handoff.stale")
_M_SEND_RETRIES = _registry.counter("serving.handoff.send_retries")
_M_TRANSFER = _registry.histogram("serving.handoff.transfer_s")


class HandoffError(ConnectionError):
    """Base for handoff failures. Subclasses ConnectionError so transport
    retry filters (and chaos's FaultInjected) compose with the same except
    clauses; the frontend's answer to any of these is degradation, not a
    user-visible failure."""


class HandoffCorruptError(HandoffError):
    """Bundle failed validation (torn frame, digest mismatch, or prompt
    page-digest chain mismatch). The adopter must discard it and the
    request must re-prefill — adopting would risk a wrong token."""


class StaleHandoffError(HandoffError):
    """Bundle's generation does not match the request's current handoff
    generation: a superseded prefill attempt published late. Dropped on
    the floor; the live attempt's bundle (or blended completion) wins."""


def page_digests(prompt, page_size, n_pages):
    """Chained blake2b digests over the first ``n_pages`` full prompt
    pages — digest[j] = H(digest[j-1] || page j's token bytes), byte-for-
    byte the engine's prefix-index chain (continuous._page_digests), so
    the adopt-side recomputation is an independent check that the bundle's
    prompt and digest chain agree with what the prefill side indexed."""
    out, h = [], b""
    for j in range(n_pages):
        h = hashlib.blake2b(
            prompt[j * page_size:(j + 1) * page_size].tobytes(),
            key=h, digest_size=_DIGEST_SIZE).digest()
        out.append(h)
    return out


class HandoffBundle:
    """Everything a decode replica needs to continue a request exactly
    where prefill left off. ``payloads`` is the engine's page export
    (opaque to this module — per-layer host arrays); ``digests`` is the
    chained prompt page-digest chain; ``tokens`` already includes every
    generated token (tok0 at minimum) so the adopter can replay them to
    the client stream; ``n_dispatched`` restores the engine invariant
    ``lengths[slot] = len(prompt) + n_dispatched - 1``."""

    __slots__ = ("rid", "seed", "sampling", "prompt", "tokens",
                 "n_generated", "n_dispatched", "max_new_tokens",
                 "eos_token_id", "timeout_s", "payloads", "digests",
                 "page_size", "generation", "t_publish")

    def __init__(self, rid, seed, sampling, prompt, tokens, n_generated,
                 n_dispatched, max_new_tokens, eos_token_id, timeout_s,
                 payloads, digests, page_size, generation):
        self.rid = int(rid)
        self.seed = int(seed)
        self.sampling = tuple(sampling)
        self.prompt = prompt
        self.tokens = list(tokens)
        self.n_generated = int(n_generated)
        self.n_dispatched = int(n_dispatched)
        self.max_new_tokens = int(max_new_tokens)
        self.eos_token_id = eos_token_id
        self.timeout_s = timeout_s
        self.payloads = payloads
        self.digests = list(digests)
        self.page_size = int(page_size)
        self.generation = int(generation)
        self.t_publish = None     # stamped by publish(); transfer_s metric

    def to_bytes(self):
        payload = wireformat.encode(
            {s: getattr(self, s) for s in self.__slots__})
        digest = hashlib.blake2b(payload, digest_size=_DIGEST_SIZE).digest()
        return _MAGIC + _LEN.pack(len(payload)) + digest + payload

    @classmethod
    def from_bytes(cls, data):
        """Parse + validate a frame. Any structural defect — wrong magic,
        short read, length mismatch, digest mismatch, undecodable payload —
        raises :class:`HandoffCorruptError`; there is no partial success.
        The payload decoder is :mod:`.wireformat`: non-executable by
        construction, so a frame from a hostile wire is refused, never
        interpreted."""
        hdr = len(_MAGIC) + _LEN.size + _DIGEST_SIZE
        if len(data) < hdr or not data.startswith(_MAGIC):
            raise HandoffCorruptError("bundle frame torn or foreign")
        (n,) = _LEN.unpack(data[len(_MAGIC):len(_MAGIC) + _LEN.size])
        digest = data[len(_MAGIC) + _LEN.size:hdr]
        payload = data[hdr:]
        if len(payload) != n:
            raise HandoffCorruptError(
                f"bundle payload truncated: {len(payload)}/{n} bytes")
        if hashlib.blake2b(payload, digest_size=_DIGEST_SIZE).digest() != digest:
            raise HandoffCorruptError("bundle payload digest mismatch")
        try:
            fields = wireformat.decode(payload)
        except Exception as e:
            raise HandoffCorruptError(f"bundle payload unreadable: {e}")
        bundle = cls.__new__(cls)
        try:
            for s in cls.__slots__:
                setattr(bundle, s, fields[s])
        except (KeyError, TypeError) as e:
            raise HandoffCorruptError(f"bundle missing field {e}")
        return bundle

    def verify_prompt_digests(self):
        """Independent adopt-side check: recompute the chained page digests
        from the bundle's own prompt and compare against the chain the
        prefill engine computed. A mismatch means the prompt bytes and the
        digest chain disagree — some part of the bundle is lying — and the
        only safe answer is re-prefill."""
        import numpy as np

        p = np.asarray(self.prompt, np.int32).reshape(-1)
        n = len(self.digests)
        if n and page_digests(p, self.page_size, n) != self.digests:
            raise HandoffCorruptError(
                f"rid {self.rid}: prompt page-digest chain mismatch")


class HandoffManager:
    """Publish/adopt bundles through a spool directory with deadlines,
    bounded-backoff retry, and generation fencing. All knobs come from
    ``PADDLE_HANDOFF_*`` env vars unless passed explicitly; ``clock`` and
    ``sleep`` are injectable so tests step time instead of sleeping."""

    def __init__(self, spool_dir=None, deadline_s=None, retries=None,
                 backoff_s=None, clock=time.monotonic, sleep=time.sleep):
        self.spool_dir = (spool_dir or env_str("PADDLE_HANDOFF_DIR")
                          or os.path.join(tempfile.gettempdir(),
                                          "paddle_handoff"))
        self.deadline_s = (env_float("PADDLE_HANDOFF_DEADLINE_S", 5.0)
                           if deadline_s is None else float(deadline_s))
        self.retries = (env_int("PADDLE_HANDOFF_RETRIES", 2)
                        if retries is None else int(retries))
        self.backoff_s = (env_float("PADDLE_HANDOFF_BACKOFF_S", 0.05)
                          if backoff_s is None else float(backoff_s))
        self.clock = clock
        self.sleep = sleep
        os.makedirs(self.spool_dir, exist_ok=True)

    def _path(self, bundle):
        return os.path.join(self.spool_dir,
                            f"handoff-{bundle.rid}-g{bundle.generation}.bin")

    def publish(self, bundle):
        """Write ``bundle`` atomically into the spool; returns its path.
        Each attempt fires the ``serving.handoff.send`` chaos seam; a
        transient failure retries with exponential backoff as long as both
        the attempt budget and the deadline allow. Exhaustion raises
        :class:`HandoffError` — the caller's cue to complete the request
        in blended mode (nothing was detached yet, so nothing is lost)."""
        bundle.t_publish = time.time()
        data = bundle.to_bytes()
        path = self._path(bundle)
        t0 = self.clock()
        attempt = 0
        while True:
            try:
                chaos.site("serving.handoff.send")
                atomic_write(
                    path, lambda f: f.write(data),
                    # the torn-bundle seam: a chaos `truncate` here commits
                    # a short file that from_bytes' digest gate must catch
                    before_commit=lambda tmp: chaos.site(
                        "serving.handoff.corrupt", path=tmp))
                _M_PUBLISHED.inc()
                return path
            except HandoffError:
                raise
            except Exception as e:
                attempt += 1
                delay = self.backoff_s * (2 ** (attempt - 1))
                if (attempt > self.retries
                        or self.clock() - t0 + delay > self.deadline_s):
                    raise HandoffError(
                        f"rid {bundle.rid}: publish failed after "
                        f"{attempt} attempt(s): {e}")
                _M_SEND_RETRIES.inc()
                self.sleep(delay)

    def load(self, path, expected_generation=None):
        """Read, validate, and CONSUME the bundle at ``path``. Fires the
        ``serving.handoff.adopt`` chaos seam first (an injected fault here
        models a decode replica dying mid-adopt). Validation failures
        raise :class:`HandoffCorruptError`; a generation mismatch raises
        :class:`StaleHandoffError`. The spool file is removed in every
        outcome — corrupt and stale bundles are garbage, and a validated
        bundle's pages now live in the adopter's pool."""
        chaos.site("serving.handoff.adopt")
        try:
            try:
                with open(path, "rb") as f:
                    data = f.read()
            except OSError as e:
                raise HandoffCorruptError(f"bundle unreadable: {e}")
            bundle = HandoffBundle.from_bytes(data)
            bundle.verify_prompt_digests()
            if (expected_generation is not None
                    and bundle.generation != expected_generation):
                _M_STALE.inc()
                raise StaleHandoffError(
                    f"rid {bundle.rid}: bundle generation "
                    f"{bundle.generation} != expected {expected_generation}")
        except HandoffCorruptError:
            _M_CORRUPT.inc()
            self.discard(path)
            raise
        except StaleHandoffError:
            self.discard(path)
            raise
        self.discard(path)
        _M_ADOPTED.inc()
        if bundle.t_publish is not None:
            _M_TRANSFER.observe(max(0.0, time.time() - bundle.t_publish))
        return bundle

    @staticmethod
    def discard(path):
        try:
            os.remove(path)
        except OSError:
            pass
