"""Wire transport for KV-page handoff bundles and fabric blob fetches.

PR 16's :mod:`.handoff` moved bundles through a spool *directory* — fine
within one host, useless across hosts. This module carries the SAME
atomic handoff-bundle frames (chained keyed blake2b page digests,
generation fencing, consumed-in-every-outcome semantics) over a
TCPStore-style socket channel, so a prefill replica on one host can hand
pages to a decode replica — or the KV fabric can fetch a hot prefix —
on another.

Design rules, in priority order:

1. **The digest gate is the only trust boundary.** The wire adds zero
   validation of its own and removes none: every byte string that
   crosses it is re-validated by :meth:`HandoffBundle.from_bytes` +
   :meth:`verify_prompt_digests` (bundles) or the blob frame digest +
   :meth:`KVFabric._validate` (fabric entries) on the receiving side —
   and every wire payload is the NON-EXECUTABLE :mod:`.wireformat`
   encoding, so the channel (which has no peer authentication) cannot
   be leveraged into code execution; see wireformat's trust-model
   notes. A flaky or malicious wire can cost latency, never a wrong
   token.
2. **One dial per op.** Like the native TCPStore client, each RPC opens
   a fresh connection, sends one request, reads one response, closes.
   No connection pool to leak, no half-open stream to reason about
   after a peer death — a dead peer is just a refused/timed-out dial.
3. **Bounded everything.** Retries use the handoff manager's exact
   bounded-backoff-inside-a-deadline loop. A timeout while CONNECTING
   is a dial failure like a refusal — retried, exhausting into
   :class:`KVPartitionError` (a blackholed peer is a partition, not a
   slow one). A timeout AFTER the dial was accepted — bounded by the op
   deadline, not the connect timeout — is typed :class:`KVFetchTimeout`
   immediately and never retried (waiting longer on a stuck peer is
   worse than recomputing).
4. **Consumed in every outcome.** Bundle adoption uses the server's
   ``TAK`` op (get+delete in one critical section), so a bundle is
   gone from the wire store whether adoption succeeds, finds it
   corrupt, or finds it stale — exactly the spool unlink discipline.

Transport selection (:func:`make_transport`): ``PADDLE_KV_TRANSPORT=spool``
(default) returns a plain :class:`HandoffManager` — byte-for-byte the
PR 16 path; ``wire`` returns a :class:`WireTransport` speaking to a
:class:`KVPageServer` (a loopback one is owned and started lazily when
no endpoint is configured).

Chaos seams: ``serving.kv.partition`` (per RPC attempt, before the
dial), ``serving.kv.timeout`` (between send and receive — converted to
the same ``socket.timeout`` path a stuck peer takes), ``serving.kv.corrupt``
(after receive — truncates the received bytes so the digest gate must
refuse them). See docs/CHAOS.md.
"""
import hashlib
import socket
import struct
import threading
import time

from ..observability.metrics import registry as _registry
from ..testing import chaos
from .handoff import (HandoffBundle, HandoffCorruptError, HandoffError,
                      HandoffManager, StaleHandoffError)
from ..utils.envs import env_float, env_int, env_str

__all__ = ["KVTransportError", "KVFetchTimeout", "KVPartitionError",
           "KVPageServer", "WireTransport", "make_transport",
           "frame_blob", "unframe_blob"]

#: blob frame magic ("paddle_tpu KV v1") — fabric spill entries get the
#: same cheap torn/foreign prefix check handoff bundles have
_BLOB_MAGIC = b"PTKV1\n"
_LEN = struct.Struct(">Q")
_KLEN = struct.Struct(">I")
_DIGEST_SIZE = 16

_M_PUBLISHED = _registry.counter("serving.handoff.published")
_M_ADOPTED = _registry.counter("serving.handoff.adopted")
_M_CORRUPT = _registry.counter("serving.handoff.corrupt")
_M_STALE = _registry.counter("serving.handoff.stale")
_M_SEND_RETRIES = _registry.counter("serving.handoff.send_retries")
_M_TRANSFER = _registry.histogram("serving.handoff.transfer_s")


class KVTransportError(HandoffError):
    """Wire-level failure that is neither a timeout nor retry exhaustion
    (protocol violation, unexpected response). ``reason`` feeds the
    fabric's typed ``kv.fallthrough{reason=}`` accounting."""

    reason = "transport"


class KVFetchTimeout(KVTransportError):
    """The peer accepted the dial but the response never arrived inside
    the socket timeout. Not retried: a peer slow enough to time out is
    slower than local recompute, and retrying a stuck peer holds the
    request hostage."""

    reason = "timeout"


class KVPartitionError(KVTransportError):
    """Every dial attempt inside the retry/deadline budget failed —
    connection refused, reset, unreachable, or timed out CONNECTING (a
    blackholed peer). The peer (or the network between us) is gone; the
    caller falls down the tier ladder."""

    reason = "partition"


def frame_blob(payload):
    """MAGIC + length + blake2b-16 + payload — the same frame discipline
    as :meth:`HandoffBundle.to_bytes`, for opaque fabric entries."""
    digest = hashlib.blake2b(payload, digest_size=_DIGEST_SIZE).digest()
    return _BLOB_MAGIC + _LEN.pack(len(payload)) + digest + payload


def unframe_blob(data):
    """Validate + strip a :func:`frame_blob` frame. Any defect raises
    :class:`HandoffCorruptError` — there is no partial success."""
    hdr = len(_BLOB_MAGIC) + _LEN.size + _DIGEST_SIZE
    if len(data) < hdr or not data.startswith(_BLOB_MAGIC):
        raise HandoffCorruptError("blob frame torn or foreign")
    (n,) = _LEN.unpack(data[len(_BLOB_MAGIC):len(_BLOB_MAGIC) + _LEN.size])
    digest = data[len(_BLOB_MAGIC) + _LEN.size:hdr]
    payload = data[hdr:]
    if len(payload) != n:
        raise HandoffCorruptError(
            f"blob payload truncated: {len(payload)}/{n} bytes")
    if hashlib.blake2b(payload, digest_size=_DIGEST_SIZE).digest() != digest:
        raise HandoffCorruptError("blob payload digest mismatch")
    return payload


def _recv_exact(sock, n):
    """Read exactly ``n`` bytes or raise ConnectionError — a short read
    means the peer died mid-stream, and a torn message must become a
    typed failure, not a silent truncation."""
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(65536, n - len(buf)))
        if not chunk:
            raise ConnectionError(
                f"peer closed mid-message ({len(buf)}/{n} bytes)")
        buf.extend(chunk)
    return bytes(buf)


class KVPageServer:
    """Minimal keyed byte store behind a socket — the wire-side spool.

    Protocol (all big-endian): request = op(3) + keylen(>I) + key +
    datalen(>Q) + data; response = status(3: ``OK `` / ``MIS``) +
    len(>Q) + body. Ops: ``PUT`` store, ``GET`` fetch, ``TAK`` fetch and
    delete in one critical section (the consumed-in-every-outcome op
    bundle adoption uses), ``DEL`` delete.

    Threading mirrors the native TCPStore server: an accept loop with a
    short timeout (so :meth:`stop` is prompt) hands each connection to a
    daemon thread. A handler reads the complete request off the socket
    BEFORE touching the store lock — a slow or stalled client must never
    hold the store hostage (the blocking-under-lock rule's contract).
    """

    def __init__(self, host="127.0.0.1", port=0):
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(64)
        self._sock.settimeout(0.2)
        self.host, self.port = self._sock.getsockname()[:2]
        self._store = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._accept_loop, name="kv-page-server", daemon=True)
        self._thread.start()

    @property
    def endpoint(self):
        return f"{self.host}:{self.port}"

    def __len__(self):
        with self._lock:
            return len(self._store)

    def _accept_loop(self):
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            threading.Thread(target=self._handle, args=(conn,),
                             daemon=True).start()
        try:
            self._sock.close()
        except OSError:
            pass

    def _handle(self, conn):
        try:
            with conn:
                conn.settimeout(5.0)
                op = _recv_exact(conn, 3)
                (klen,) = _KLEN.unpack(_recv_exact(conn, _KLEN.size))
                key = _recv_exact(conn, klen).decode("utf-8")
                (dlen,) = _LEN.unpack(_recv_exact(conn, _LEN.size))
                data = _recv_exact(conn, dlen) if dlen else b""
                # full request is in hand — only now touch the store
                if op == b"PUT":
                    with self._lock:
                        self._store[key] = data
                    body, status = b"", b"OK "
                elif op == b"GET":
                    with self._lock:
                        body = self._store.get(key)
                    status = b"MIS" if body is None else b"OK "
                    body = body or b""
                elif op == b"TAK":
                    with self._lock:
                        body = self._store.pop(key, None)
                    status = b"MIS" if body is None else b"OK "
                    body = body or b""
                elif op == b"DEL":
                    with self._lock:
                        self._store.pop(key, None)
                    body, status = b"", b"OK "
                else:
                    body, status = b"", b"ERR"
                conn.sendall(status + _LEN.pack(len(body)) + body)
        except (OSError, ConnectionError, struct.error):
            pass        # client died mid-request; its RPC layer retries

    def stop(self):
        self._stop.set()
        self._thread.join(timeout=2.0)


class WireTransport:
    """Socket-channel drop-in for :class:`HandoffManager`.

    Same surface — ``publish(bundle) -> token``, ``load(token,
    expected_generation)``, ``discard(token)`` — so the frontend's
    handoff code paths (cancel, expiry, shutdown, re-prefill) work on
    either transport unchanged. Tokens are ``kv:handoff-<rid>-g<gen>``
    strings: opaque to callers, like spool paths. Adds
    :meth:`fetch_blob` / :meth:`put_blob` for the fabric's peer-fetch
    tier.

    Unless an ``endpoint`` (or ``PADDLE_KV_WIRE_ADDR``) is given, the
    transport owns a loopback :class:`KVPageServer`, started lazily —
    single-host setups get cross-process handoff for free, tests get a
    real socket path without ceremony.
    """

    def __init__(self, endpoint=None, deadline_s=None, retries=None,
                 backoff_s=None, connect_timeout_s=None,
                 clock=time.monotonic, sleep=time.sleep):
        self._endpoint = endpoint or env_str("PADDLE_KV_WIRE_ADDR")
        self.deadline_s = (env_float("PADDLE_KV_DEADLINE_S", 5.0)
                           if deadline_s is None else float(deadline_s))
        self.retries = (env_int("PADDLE_KV_RETRIES", 2)
                        if retries is None else int(retries))
        self.backoff_s = (env_float("PADDLE_KV_BACKOFF_S", 0.05)
                          if backoff_s is None else float(backoff_s))
        self.connect_timeout_s = (
            env_float("PADDLE_KV_CONNECT_TIMEOUT_S", 1.0)
            if connect_timeout_s is None else float(connect_timeout_s))
        self.clock = clock
        self.sleep = sleep
        self._owned_server = None
        self._server_lock = threading.Lock()

    # ---- endpoint / lifecycle ---------------------------------------------
    @property
    def endpoint(self):
        if self._endpoint:
            return self._endpoint
        with self._server_lock:
            if self._owned_server is None:
                self._owned_server = KVPageServer()
            return self._owned_server.endpoint

    def close(self):
        with self._server_lock:
            if self._owned_server is not None:
                self._owned_server.stop()
                self._owned_server = None

    # ---- raw RPC ----------------------------------------------------------
    def _rpc(self, endpoint, op, key, data=b""):
        """One dial, one request, one response. A timeout in the CONNECT
        phase is a dial failure — reraised as a plain ConnectionError so
        _call's retry loop treats it like a refusal (exhausting into
        :class:`KVPartitionError`); once the peer has accepted the dial,
        the socket timeout is re-armed from the op deadline (the connect
        timeout must not bound response reads) and a send/recv
        ``socket.timeout`` surfaces as :class:`KVFetchTimeout`. Any other
        raw OSError propagates for the retry loop to classify."""
        host, _, port = endpoint.rpartition(":")
        kb = key.encode("utf-8")
        try:
            sock = socket.create_connection(
                (host, int(port)), timeout=self.connect_timeout_s)
        except socket.timeout as e:
            raise ConnectionError(
                f"{op.decode().strip()} {key!r}: dial {endpoint} "
                f"timed out: {e}")
        try:
            with sock:
                sock.settimeout(max(self.deadline_s,
                                    self.connect_timeout_s))
                sock.sendall(op + _KLEN.pack(len(kb)) + kb
                             + _LEN.pack(len(data)) + data)
                try:
                    # drill seam: models the peer going silent after
                    # accepting the request — same path a stuck peer takes
                    chaos.site("serving.kv.timeout")
                except chaos.FaultInjected:
                    raise socket.timeout("injected: peer went silent")
                status = _recv_exact(sock, 3)
                (n,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
                body = _recv_exact(sock, n) if n else b""
        except socket.timeout as e:
            raise KVFetchTimeout(f"{op.decode().strip()} {key!r} via "
                                 f"{endpoint}: {e}")
        if status == b"MIS":
            return None
        if status != b"OK ":
            raise KVTransportError(
                f"{op.decode().strip()} {key!r} via {endpoint}: "
                f"unexpected status {status!r}")
        return self._maybe_corrupt(body)

    @staticmethod
    def _maybe_corrupt(body):
        """``serving.kv.corrupt`` drill: truncate the received bytes so
        the digest gate downstream must refuse them — the drill proves
        the refusal path, not the injection."""
        try:
            chaos.site("serving.kv.corrupt")
        except chaos.FaultInjected:
            return body[:max(0, len(body) - 7)]
        return body

    def _call(self, endpoint, op, key, data=b""):
        """Bounded-backoff retry inside a deadline — the handoff
        manager's exact loop. Typed errors pass straight through (a
        timeout or digest refusal must not be retried into); transient
        dial failures retry until the attempt budget or deadline runs
        out, then raise :class:`KVPartitionError`."""
        t0 = self.clock()
        attempt = 0
        while True:
            try:
                chaos.site("serving.kv.partition")
                return self._rpc(endpoint, op, key, data)
            except (KVFetchTimeout, KVTransportError,
                    HandoffCorruptError, StaleHandoffError):
                raise
            except Exception as e:
                attempt += 1
                delay = self.backoff_s * (2 ** (attempt - 1))
                if (attempt > self.retries
                        or self.clock() - t0 + delay > self.deadline_s):
                    raise KVPartitionError(
                        f"{op.decode().strip()} {key!r} via {endpoint} "
                        f"failed after {attempt} attempt(s): {e}")
                _M_SEND_RETRIES.inc()
                self.sleep(delay)

    # ---- HandoffManager-compatible surface --------------------------------
    @staticmethod
    def _token(bundle):
        return f"kv:handoff-{bundle.rid}-g{bundle.generation}"

    def publish(self, bundle):
        """Serialize + PUT the bundle; returns its wire token. Fires the
        ``serving.handoff.send`` seam per attempt (same drill plans cover
        both transports) on top of the wire seams."""
        bundle.t_publish = time.time()
        data = bundle.to_bytes()
        token = self._token(bundle)
        t0 = self.clock()
        attempt = 0
        while True:
            try:
                chaos.site("serving.handoff.send")
                self._call(self.endpoint, b"PUT", token, data)
                _M_PUBLISHED.inc()
                return token
            except (KVFetchTimeout, KVPartitionError):
                raise
            except HandoffError:
                raise
            except Exception as e:
                attempt += 1
                delay = self.backoff_s * (2 ** (attempt - 1))
                if (attempt > self.retries
                        or self.clock() - t0 + delay > self.deadline_s):
                    raise HandoffError(
                        f"rid {bundle.rid}: publish failed after "
                        f"{attempt} attempt(s): {e}")
                _M_SEND_RETRIES.inc()
                self.sleep(delay)

    def load(self, token, expected_generation=None):
        """TAK + validate + fence — the spool :meth:`HandoffManager.load`
        contract over the wire. The server-side pop makes the bundle
        consumed in EVERY outcome: success, corrupt, and stale all leave
        the wire store empty."""
        chaos.site("serving.handoff.adopt")
        try:
            data = self._call(self.endpoint, b"TAK", token)
            if data is None:
                raise HandoffCorruptError(f"bundle {token!r} not on wire")
            bundle = HandoffBundle.from_bytes(data)
            bundle.verify_prompt_digests()
            if (expected_generation is not None
                    and bundle.generation != expected_generation):
                _M_STALE.inc()
                raise StaleHandoffError(
                    f"rid {bundle.rid}: bundle generation "
                    f"{bundle.generation} != expected {expected_generation}")
        except HandoffCorruptError:
            _M_CORRUPT.inc()
            raise
        _M_ADOPTED.inc()
        if bundle.t_publish is not None:
            _M_TRANSFER.observe(max(0.0, time.time() - bundle.t_publish))
        return bundle

    def discard(self, token):
        try:
            self._call(self.endpoint, b"DEL", token)
        except HandoffError:
            pass        # best-effort, like the spool's silent unlink

    # ---- fabric blob surface ----------------------------------------------
    def put_blob(self, key, data, endpoint=None):
        self._call(endpoint or self.endpoint, b"PUT", key, data)

    def fetch_blob(self, endpoint, key):
        """GET one fabric entry from a peer's wire store; None on miss.
        Typed wire errors propagate for the fabric's fallthrough
        accounting; the returned bytes are still framed — the caller
        runs them through :func:`unframe_blob`'s digest gate."""
        return self._call(endpoint, b"GET", key)

    def delete_blob(self, key, endpoint=None):
        try:
            self._call(endpoint or self.endpoint, b"DEL", key)
        except HandoffError:
            pass


def make_transport(kind=None, **kw):
    """Transport-selection shim (the ONLY change the PR 16 path sees):
    ``spool`` (default) returns a plain :class:`HandoffManager` —
    byte-for-byte the PR 16 handoff; ``wire`` returns a
    :class:`WireTransport`."""
    kind = kind or env_str("PADDLE_KV_TRANSPORT", "spool")
    if kind == "spool":
        return HandoffManager(**kw)
    if kind == "wire":
        return WireTransport(**kw)
    raise ValueError(
        f"PADDLE_KV_TRANSPORT={kind!r}: expected 'spool' or 'wire'")
