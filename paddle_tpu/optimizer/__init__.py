from . import lr
from .optimizer import L1Decay, L2Decay, Optimizer
from .optimizers import (
    ASGD,
    SGD,
    Adadelta,
    Adagrad,
    Adam,
    Adamax,
    AdamW,
    Lamb,
    Momentum,
    NAdam,
    RAdam,
    RMSProp,
    Rprop,
)

from .lbfgs import LBFGS  # noqa: F401
