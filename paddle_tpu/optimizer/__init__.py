from . import lr
from .optimizer import L1Decay, L2Decay, Optimizer
from .optimizers import (
    SGD,
    Adadelta,
    Adagrad,
    Adam,
    Adamax,
    AdamW,
    Lamb,
    Momentum,
    RMSProp,
)

from .lbfgs import LBFGS  # noqa: F401
