"""LBFGS (reference: python/paddle/optimizer/lbfgs.py — closure-driven full
-batch quasi-Newton with strong-Wolfe line search).

Unlike the per-slot optimizers, LBFGS is host-driven (history of (s, y)
pairs, line-search loop) — matching the reference's Python implementation.
The inner products/direction math are jnp ops on-device.
"""
import jax.numpy as jnp
import numpy as np

from ..framework.core import Tensor
from .optimizer import Optimizer


class LBFGS(Optimizer):
    def __init__(self, learning_rate=1.0, max_iter=20, max_eval=None,
                 tolerance_grad=1e-7, tolerance_change=1e-9, history_size=100,
                 line_search_fn=None, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, False, name)
        self.max_iter = max_iter
        self.max_eval = max_eval if max_eval is not None else max_iter * 5 // 4
        self.tolerance_grad = tolerance_grad
        self.tolerance_change = tolerance_change
        self.history_size = history_size
        self.line_search_fn = line_search_fn
        self._s_hist = []
        self._y_hist = []
        self._prev_x = None
        self._prev_flat_grad = None

    # -- flat helpers ------------------------------------------------------
    def _params(self):
        return [p for p in self._parameter_list if not p.stop_gradient]

    def _flat(self, tensors):
        return jnp.concatenate([jnp.ravel(t) for t in tensors])

    def _gather_grads(self):
        return self._flat([
            (p.grad._data if p.grad is not None else jnp.zeros(p._data.shape, p._data.dtype))
            for p in self._params()
        ]).astype(jnp.float32)

    def _assign_flat(self, flat):
        i = 0
        for p in self._params():
            n = int(np.prod(p.shape)) if p.shape else 1
            p._data = flat[i : i + n].reshape(p._data.shape).astype(p._data.dtype)
            i += n

    def _gather_params(self):
        return self._flat([p._data for p in self._params()]).astype(jnp.float32)

    def _direction(self, grad):
        """Two-loop recursion over the (s, y) history."""
        q = grad
        alphas = []
        for s, y in reversed(list(zip(self._s_hist, self._y_hist))):
            rho = 1.0 / jnp.maximum(jnp.vdot(y, s), 1e-10)
            a = rho * jnp.vdot(s, q)
            alphas.append((a, rho, s, y))
            q = q - a * y
        if self._s_hist:
            s, y = self._s_hist[-1], self._y_hist[-1]
            gamma = jnp.vdot(s, y) / jnp.maximum(jnp.vdot(y, y), 1e-10)
            q = q * gamma
        for a, rho, s, y in reversed(alphas):
            b = rho * jnp.vdot(y, q)
            q = q + s * (a - b)
        return -q

    def step(self, closure=None):
        """closure() -> loss Tensor, recomputing forward+backward. Without a
        closure, performs a single gradient-descent-flavored LBFGS update
        using the grads already on the parameters."""
        if closure is None:
            grad = self._gather_grads()
            x0 = self._gather_params()
            # secant pairs span successive step() calls here: pair the
            # previous (x, g) with the freshly computed (x, g)
            if self._prev_flat_grad is not None:
                self._update_history(self._prev_x, self._prev_flat_grad, x0, grad)
            d = self._direction(grad)
            lr = float(self.get_lr())
            self._assign_flat(x0 + lr * d)
            self._prev_x, self._prev_flat_grad = x0, grad
            self._global_step += 1
            return None

        loss = closure()
        grad = self._gather_grads()
        evals = 1
        for _ in range(self.max_iter):
            if float(jnp.max(jnp.abs(grad))) <= self.tolerance_grad:
                break
            d = self._direction(grad)
            x0 = self._gather_params()
            lr = float(self.get_lr())
            if self.line_search_fn == "strong_wolfe":
                lr, loss, grad, evals_ls = self._strong_wolfe(closure, x0, d, lr, loss, grad)
                evals += evals_ls
            else:
                self._assign_flat(x0 + lr * d)
                for p in self._params():
                    p.clear_grad()
                loss = closure()
                grad_new = self._gather_grads()
                self._update_history(x0, grad, self._gather_params(), grad_new)
                grad = grad_new
                evals += 1
            if evals >= self.max_eval:
                break
            x_new = self._gather_params()
            if float(jnp.max(jnp.abs(x_new - x0))) < self.tolerance_change:
                break
        self._global_step += 1
        return loss

    def _update_history(self, x_old, g_old, x_new, g_new):
        # secant condition: pair s_k = x_{k+1} - x_k with y_k = g_{k+1} - g_k
        s = x_new - x_old
        y = g_new - g_old
        if float(jnp.vdot(s, y)) > 1e-10:  # curvature guard keeps H_k PD
            self._s_hist.append(s)
            self._y_hist.append(y)
            if len(self._s_hist) > self.history_size:
                self._s_hist.pop(0)
                self._y_hist.pop(0)

    def _strong_wolfe(self, closure, x0, d, lr, f0, g0, c1=1e-4, c2=0.9, max_ls=20):
        """Backtracking line search satisfying (approximate) strong Wolfe."""
        dg0 = float(jnp.vdot(g0, d))
        evals = 0
        t = lr
        f_prev = float(f0.numpy()) if isinstance(f0, Tensor) else float(f0)
        for _ in range(max_ls):
            self._assign_flat(x0 + t * d)
            for p in self._params():
                p.clear_grad()
            loss = closure()
            evals += 1
            f_t = float(loss.numpy())
            g_t = self._gather_grads()
            if f_t <= f_prev + c1 * t * dg0 and abs(float(jnp.vdot(g_t, d))) <= c2 * abs(dg0):
                self._update_history(x0, g0, x0 + t * d, g_t)
                return t, loss, g_t, evals
            t_eval = t  # params/loss/grad all correspond to this step size
            t *= 0.5
        # exhausted: report the LAST EVALUATED point (params are still there)
        # so the secant pair and returned step stay mutually consistent
        self._update_history(x0, g0, x0 + t_eval * d, g_t)
        return t_eval, loss, g_t, evals

    def _create_slots(self, p):  # pragma: no cover - unused, host-driven
        return {}

    def _rule(self, p, g, slots, lr, step):  # pragma: no cover
        raise NotImplementedError
