"""Optimizer zoo (reference: python/paddle/optimizer/{sgd,momentum,adam,adamw,
adagrad,rmsprop,adadelta,adamax,lamb}.py). Each `_rule` is pure jnp — fusable
into the compiled train step."""
import jax.numpy as jnp

from .optimizer import Optimizer


class SGD(Optimizer):
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None, grad_clip=None,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, multi_precision, name)

    def _rule(self, p, g, slots, lr, step):
        return p - lr * g, slots


class Momentum(Optimizer):
    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None, use_nesterov=False,
                 weight_decay=None, grad_clip=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, multi_precision, name)
        self._momentum = momentum
        self._use_nesterov = use_nesterov

    def _create_slots(self, p):
        slots = super()._create_slots(p)
        base = slots.get("master_weight", p._data)
        slots["velocity"] = jnp.zeros_like(base)
        return slots

    def _rule(self, p, g, slots, lr, step):
        v = slots["velocity"] * self._momentum + g
        if self._use_nesterov:
            new_p = p - lr * (g + self._momentum * v)
        else:
            new_p = p - lr * v
        return new_p, {**slots, "velocity": v}


class Adam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8, parameters=None,
                 weight_decay=None, grad_clip=None, lazy_mode=False, multi_precision=False,
                 use_multi_tensor=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, multi_precision, name)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _create_slots(self, p):
        slots = super()._create_slots(p)
        base = slots.get("master_weight", p._data)
        slots["moment1"] = jnp.zeros_like(base)
        slots["moment2"] = jnp.zeros_like(base)
        return slots

    def _rule(self, p, g, slots, lr, step):
        b1, b2 = self._beta1, self._beta2
        m = b1 * slots["moment1"] + (1 - b1) * g
        v = b2 * slots["moment2"] + (1 - b2) * jnp.square(g)
        step_f = jnp.asarray(step, jnp.float32)
        mhat = m / (1 - b1**step_f)
        vhat = v / (1 - b2**step_f)
        new_p = p - lr * mhat / (jnp.sqrt(vhat) + self._epsilon)
        return new_p, {**slots, "moment1": m, "moment2": v}


class AdamW(Adam):
    """Decoupled weight decay (reference: python/paddle/optimizer/adamw.py;
    fused kernel phi/kernels/gpu/adamw_kernel.cu)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8, parameters=None,
                 weight_decay=0.01, lr_ratio=None, apply_decay_param_fun=None, grad_clip=None,
                 lazy_mode=False, multi_precision=False, name=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters, None, grad_clip,
                         lazy_mode, multi_precision, name=name)
        if isinstance(weight_decay, (int, float)):
            self._coeff = float(weight_decay)
        else:
            self._coeff = float(getattr(weight_decay, "coeff", 0.01))
        self._apply_decay_param_fun = apply_decay_param_fun
        self._decay_skip = set()
        if apply_decay_param_fun is not None and self._parameter_list:
            for p in self._parameter_list:
                if not apply_decay_param_fun(p.name or ""):
                    self._decay_skip.add(id(p))
        self._current_decay_mask = None

    def _rule(self, p, g, slots, lr, step):
        decay = slots.get("_decay", 1.0)
        p = p * (1.0 - lr * self._coeff * decay)
        return super()._rule(p, g, slots, lr, step)

    def step(self):
        # stash per-param decay masks into slots before the generic loop
        if self._parameter_list:
            for p in self._parameter_list:
                if p.grad is not None:
                    slots = self._slots_for(p)
                    no_decay = id(p) in self._decay_skip or getattr(p, "no_weight_decay", False)
                    slots["_decay"] = 0.0 if no_decay else 1.0
        super().step()

    def init_state(self, named_params):
        # same decay-mask rule as eager step(): Paddle decays every param
        # unless apply_decay_param_fun or the param itself opts out
        state = super().init_state(named_params)
        for name, p in named_params.items():
            no_decay = (
                self._apply_decay_param_fun is not None and not self._apply_decay_param_fun(name)
            ) or getattr(p, "no_weight_decay", False)
            state["slots"][name]["_decay"] = 0.0 if no_decay else 1.0
        return state


class Adagrad(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, parameters=None, weight_decay=None,
                 grad_clip=None, multi_precision=False, initial_accumulator_value=0.0, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, multi_precision, name)
        self._epsilon = epsilon
        self._init_acc = initial_accumulator_value

    def _create_slots(self, p):
        slots = super()._create_slots(p)
        base = slots.get("master_weight", p._data)
        slots["moment"] = jnp.full_like(base, self._init_acc)
        return slots

    def _rule(self, p, g, slots, lr, step):
        acc = slots["moment"] + jnp.square(g)
        new_p = p - lr * g / (jnp.sqrt(acc) + self._epsilon)
        return new_p, {**slots, "moment": acc}


class RMSProp(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0, centered=False,
                 parameters=None, weight_decay=None, grad_clip=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, multi_precision, name)
        self._rho, self._epsilon, self._momentum, self._centered = rho, epsilon, momentum, centered

    def _create_slots(self, p):
        slots = super()._create_slots(p)
        base = slots.get("master_weight", p._data)
        slots["mean_square"] = jnp.zeros_like(base)
        slots["momentum_acc"] = jnp.zeros_like(base)
        if self._centered:
            slots["mean_grad"] = jnp.zeros_like(base)
        return slots

    def _rule(self, p, g, slots, lr, step):
        ms = self._rho * slots["mean_square"] + (1 - self._rho) * jnp.square(g)
        out = {**slots, "mean_square": ms}
        if self._centered:
            mg = self._rho * slots["mean_grad"] + (1 - self._rho) * g
            denom = jnp.sqrt(ms - jnp.square(mg) + self._epsilon)
            out["mean_grad"] = mg
        else:
            denom = jnp.sqrt(ms + self._epsilon)
        mom = self._momentum * slots["momentum_acc"] + lr * g / denom
        out["momentum_acc"] = mom
        return p - mom, out


class Adadelta(Optimizer):
    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95, parameters=None,
                 weight_decay=None, grad_clip=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, multi_precision, name)
        self._epsilon, self._rho = epsilon, rho

    def _create_slots(self, p):
        slots = super()._create_slots(p)
        base = slots.get("master_weight", p._data)
        slots["avg_squared_grad"] = jnp.zeros_like(base)
        slots["avg_squared_update"] = jnp.zeros_like(base)
        return slots

    def _rule(self, p, g, slots, lr, step):
        asg = self._rho * slots["avg_squared_grad"] + (1 - self._rho) * jnp.square(g)
        update = -jnp.sqrt(slots["avg_squared_update"] + self._epsilon) / jnp.sqrt(asg + self._epsilon) * g
        asu = self._rho * slots["avg_squared_update"] + (1 - self._rho) * jnp.square(update)
        return p + lr * update, {**slots, "avg_squared_grad": asg, "avg_squared_update": asu}


class Adamax(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8, parameters=None,
                 weight_decay=None, grad_clip=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, multi_precision, name)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _create_slots(self, p):
        slots = super()._create_slots(p)
        base = slots.get("master_weight", p._data)
        slots["moment"] = jnp.zeros_like(base)
        slots["inf_norm"] = jnp.zeros_like(base)
        return slots

    def _rule(self, p, g, slots, lr, step):
        m = self._beta1 * slots["moment"] + (1 - self._beta1) * g
        u = jnp.maximum(self._beta2 * slots["inf_norm"], jnp.abs(g))
        step_f = jnp.asarray(step, jnp.float32)
        new_p = p - lr / (1 - self._beta1**step_f) * m / (u + self._epsilon)
        return new_p, {**slots, "moment": m, "inf_norm": u}


class Lamb(Optimizer):
    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9, beta2=0.999,
                 epsilon=1e-6, parameters=None, grad_clip=None, exclude_from_weight_decay_fn=None,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip, multi_precision, name)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon
        self._lamb_wd = lamb_weight_decay
        self._exclude_fn = exclude_from_weight_decay_fn

    def _create_slots(self, p):
        slots = super()._create_slots(p)
        base = slots.get("master_weight", p._data)
        slots["moment1"] = jnp.zeros_like(base)
        slots["moment2"] = jnp.zeros_like(base)
        return slots

    def _rule(self, p, g, slots, lr, step):
        b1, b2 = self._beta1, self._beta2
        m = b1 * slots["moment1"] + (1 - b1) * g
        v = b2 * slots["moment2"] + (1 - b2) * jnp.square(g)
        step_f = jnp.asarray(step, jnp.float32)
        mhat = m / (1 - b1**step_f)
        vhat = v / (1 - b2**step_f)
        decay = slots.get("_decay", 1.0)
        r = mhat / (jnp.sqrt(vhat) + self._epsilon) + self._lamb_wd * decay * p
        w_norm = jnp.linalg.norm(p)
        r_norm = jnp.linalg.norm(r)
        trust = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
        return p - lr * trust * r, {**slots, "moment1": m, "moment2": v}

    def _no_decay(self, p, name=""):
        return (self._exclude_fn is not None and self._exclude_fn(p)) or getattr(
            p, "no_weight_decay", False
        )

    def step(self):
        if self._parameter_list:
            for p in self._parameter_list:
                if p.grad is not None:
                    self._slots_for(p)["_decay"] = 0.0 if self._no_decay(p) else 1.0
        super().step()

    def init_state(self, named_params):
        state = super().init_state(named_params)
        for name, p in named_params.items():
            state["slots"][name]["_decay"] = 0.0 if self._no_decay(p, name) else 1.0
        return state


class NAdam(Adam):
    """reference: optimizer/nadam.py — Adam with Nesterov momentum
    (torch/paddle NAdam: mu-product bias correction)."""

    def __init__(self, learning_rate=0.002, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 momentum_decay=0.004, parameters=None, weight_decay=None,
                 grad_clip=None, multi_precision=False, name=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         weight_decay, grad_clip, False, multi_precision, name=name)
        self._psi = momentum_decay

    def _create_slots(self, p):
        slots = super()._create_slots(p)
        slots["mu_product"] = jnp.ones((), jnp.float32)
        return slots

    def _rule(self, p, g, slots, lr, step):
        b1, b2, eps = self._beta1, self._beta2, self._epsilon
        t = jnp.asarray(step, jnp.float32)
        mu_t = b1 * (1.0 - 0.5 * 0.96 ** (t * self._psi))
        mu_t1 = b1 * (1.0 - 0.5 * 0.96 ** ((t + 1.0) * self._psi))
        mu_prod = slots["mu_product"] * mu_t
        m = b1 * slots["moment1"] + (1 - b1) * g
        v = b2 * slots["moment2"] + (1 - b2) * jnp.square(g)
        mhat = mu_t1 * m / (1 - mu_prod * mu_t1) + (1 - mu_t) * g / (1 - mu_prod)
        vhat = v / (1 - b2**t)
        new_p = p - lr * mhat / (jnp.sqrt(vhat) + eps)
        return new_p, {**slots, "moment1": m, "moment2": v, "mu_product": mu_prod}


class RAdam(Adam):
    """reference: optimizer/radam.py — rectified Adam: falls back to SGD-with-
    momentum while the variance estimate is untrustworthy (small t)."""

    def _rule(self, p, g, slots, lr, step):
        b1, b2, eps = self._beta1, self._beta2, self._epsilon
        t = jnp.asarray(step, jnp.float32)
        m = b1 * slots["moment1"] + (1 - b1) * g
        v = b2 * slots["moment2"] + (1 - b2) * jnp.square(g)
        mhat = m / (1 - b1**t)
        rho_inf = 2.0 / (1.0 - b2) - 1.0
        rho_t = rho_inf - 2.0 * t * b2**t / (1 - b2**t)
        r = jnp.sqrt(
            jnp.maximum((rho_t - 4) * (rho_t - 2) * rho_inf, 0.0)
            / jnp.maximum((rho_inf - 4) * (rho_inf - 2) * rho_t, 1e-30)
        )
        vhat = jnp.sqrt(v / (1 - b2**t)) + eps
        adam_step = lr * r * mhat / vhat
        sgd_step = lr * mhat
        new_p = p - jnp.where(rho_t > 5.0, adam_step, sgd_step)
        return new_p, {**slots, "moment1": m, "moment2": v}


class Rprop(Optimizer):
    """reference: optimizer/rprop.py — resilient backprop: per-element step
    sizes grow on consistent gradient sign, shrink on sign flips (batch
    training only)."""

    def __init__(self, learning_rate=0.01, learning_rate_range=(1e-5, 50.0),
                 parameters=None, etas=(0.5, 1.2), grad_clip=None,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip,
                         multi_precision, name)
        self._eta_minus, self._eta_plus = etas
        self._lr_min, self._lr_max = learning_rate_range
        self._init_lr = learning_rate

    def _create_slots(self, p):
        slots = super()._create_slots(p)
        base = slots.get("master_weight", p._data)
        slots["prev_grad"] = jnp.zeros_like(base)
        slots["step_size"] = jnp.full_like(base, self._init_lr)
        return slots

    def _rule(self, p, g, slots, lr, step):
        sign = jnp.sign(g * slots["prev_grad"])
        size = jnp.clip(
            jnp.where(sign > 0, slots["step_size"] * self._eta_plus,
                      jnp.where(sign < 0, slots["step_size"] * self._eta_minus,
                                slots["step_size"])),
            self._lr_min, self._lr_max,
        )
        # on a sign flip, skip the update and zero the remembered grad
        g_eff = jnp.where(sign < 0, 0.0, g)
        new_p = p - jnp.sign(g_eff) * size
        return new_p, {**slots, "prev_grad": g_eff, "step_size": size}


class ASGD(Optimizer):
    """reference: optimizer/asgd.py — averaged SGD (Polyak-Ruppert): plain
    SGD steps plus a running average of the iterates in a slot."""

    def __init__(self, learning_rate=0.001, batch_num=1, parameters=None,
                 weight_decay=None, grad_clip=None, multi_precision=False,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision, name)

    def _create_slots(self, p):
        slots = super()._create_slots(p)
        base = slots.get("master_weight", p._data)
        slots["averaged_param"] = base.astype(jnp.float32)
        return slots

    def _rule(self, p, g, slots, lr, step):
        new_p = p - lr * g
        t = jnp.asarray(step, jnp.float32)
        avg = slots["averaged_param"] + (new_p.astype(jnp.float32) - slots["averaged_param"]) / t
        return new_p, {**slots, "averaged_param": avg}
