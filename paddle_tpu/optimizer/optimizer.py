"""Optimizer base (reference: python/paddle/optimizer/optimizer.py).

Design split for TPU: the math lives in `_rule` — a PURE function
(param, grad, slots, lr, step) -> (new_param, new_slots) on raw arrays. The
eager `.step()` loops it over parameters; the compiled train step
(paddle_tpu.jit.TrainStep / hapi.Model) calls the same rule inside one jit
so the whole update fuses into the step program (reference analogue: fused
adamw multi-tensor kernel, phi/kernels/gpu/adamw_kernel.cu).

Multi-precision master weights (reference: multi_precision flag + master
weight slots) are kept as fp32 slots when the param is fp16/bf16.
"""
import jax.numpy as jnp
import numpy as np

from ..framework import dtype as dtypes
from ..framework.core import Parameter, Tensor
from .lr import LRScheduler


class L2Decay:
    def __init__(self, coeff=0.0):
        self.coeff = coeff


class L1Decay:
    def __init__(self, coeff=0.0):
        self.coeff = coeff


class Optimizer:
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None, grad_clip=None,
                 multi_precision=False, name=None):
        self._learning_rate = learning_rate
        self._parameter_list = list(parameters) if parameters is not None else None
        self._param_groups = None
        if self._parameter_list and isinstance(self._parameter_list[0], dict):
            self._param_groups = self._parameter_list
            flat = []
            for g in self._param_groups:
                flat.extend(g["params"])
            self._parameter_list = flat
        self._grad_clip = grad_clip
        self._multi_precision = multi_precision
        if isinstance(weight_decay, (float, int)):
            self.regularization = L2Decay(float(weight_decay))
        else:
            self.regularization = weight_decay
        self._accumulators = {}  # id(param) -> dict slot name -> jnp array
        self._global_step = 0
        self._param_ids = {}

    # -- lr ------------------------------------------------------------------
    def get_lr(self):
        if isinstance(self._learning_rate, LRScheduler):
            return float(self._learning_rate())
        return float(self._learning_rate)

    def set_lr(self, value):
        if isinstance(self._learning_rate, LRScheduler):
            raise RuntimeError("cannot set_lr when using an LRScheduler")
        self._learning_rate = value

    def set_lr_scheduler(self, scheduler):
        self._learning_rate = scheduler

    # -- state ---------------------------------------------------------------
    def _slots_for(self, p):
        key = id(p)
        if key not in self._accumulators:
            self._accumulators[key] = self._create_slots(p)
            self._param_ids[key] = p
        return self._accumulators[key]

    def _create_slots(self, p):
        slots = {}
        if self._use_master_weights(p):
            slots["master_weight"] = p._data.astype(jnp.float32)
        return slots

    def _use_master_weights(self, p):
        return self._multi_precision and np.dtype(p.dtype) in (np.dtype(np.float16), np.dtype(dtypes.bfloat16))

    # -- the pure update rule (override in subclasses) -----------------------
    def _rule(self, param, grad, slots, lr, step):
        raise NotImplementedError

    def _apply_regularization(self, p, g):
        if isinstance(self.regularization, L2Decay) and self.regularization.coeff:
            return g + self.regularization.coeff * p
        if isinstance(self.regularization, L1Decay) and self.regularization.coeff:
            return g + self.regularization.coeff * jnp.sign(p)
        return g

    # -- eager step ----------------------------------------------------------
    @property
    def _needs_param_grads(self):
        return [(p, p.grad) for p in self._parameter_list if p.grad is not None and not p.stop_gradient]

    def step(self):
        if self._parameter_list is None:
            raise RuntimeError("optimizer constructed without parameters; use functional API")
        params_grads = [(p, p.grad) for p in self._parameter_list if p.grad is not None]
        if self._grad_clip is not None:
            params_grads = self._grad_clip(params_grads)
        self._global_step += 1
        lr = self.get_lr()
        for p, g in params_grads:
            lr_p = lr * p.optimize_attr.get("learning_rate", 1.0) if isinstance(p, Parameter) else lr
            slots = self._slots_for(p)
            master = slots.get("master_weight")
            pd = master if master is not None else p._data
            gd = g._data.astype(pd.dtype)
            gd = self._apply_regularization(pd, gd) if self._wd_in_grad(p) else gd
            new_p, new_slots = self._rule(pd, gd, slots, lr_p, self._global_step)
            if master is not None:
                new_slots = dict(new_slots)
                new_slots["master_weight"] = new_p
            # same dtype contract as apply_gradients: never let update-math
            # promotion (e.g. Adam's f32 bias correction) upcast the param
            p._data = new_p.astype(p._data.dtype)
            self._accumulators[id(p)] = new_slots
        from ..framework.core import _bump_mutation_version

        # weight-derived caches (serving prefix KV) key on this counter;
        # a direct _data rebind must invalidate them like set_value does
        _bump_mutation_version()

    def _wd_in_grad(self, p):
        # L2Decay folds into the gradient (reference: regularizer append path);
        # decoupled decay handled inside _rule by AdamW/Lamb.
        return True

    @property
    def _learning_rate_scheduler(self):
        return self._learning_rate if isinstance(self._learning_rate, LRScheduler) else None

    def clear_grad(self, set_to_zero=True):
        if self._parameter_list:
            for p in self._parameter_list:
                p.clear_grad()

    clear_gradients = clear_grad

    def minimize(self, loss, startup_program=None, parameters=None, no_grad_set=None):
        loss.backward()
        self.step()
        self.clear_grad()
        return None, None

    # -- functional API for compiled paths ----------------------------------
    def init_state(self, named_params):
        """named name -> Parameter; returns pytree state dict."""
        state = {"step": jnp.zeros((), jnp.int32)}
        slots = {}
        for name, p in named_params.items():
            slots[name] = self._create_slots(p)
        state["slots"] = slots
        return state

    def apply_gradients(self, params_data, grads_data, state, lr=None, skip_update=None):
        """Pure: dicts of raw arrays -> (new params, new state).

        `skip_update` (bool scalar) supports AMP dynamic loss scaling: when
        True the update is a no-op (reference: update_loss_scaling kernel
        gating via found_inf).
        """
        step = state["step"] + 1
        lr = self.get_lr() if lr is None else lr
        new_params, new_slots = {}, {}
        for name, pd in params_data.items():
            g = grads_data.get(name)
            slots = state["slots"].get(name, {})
            if g is None:
                new_params[name], new_slots[name] = pd, slots
                continue
            master = slots.get("master_weight")
            base = master if master is not None else pd
            gd = g.astype(base.dtype)
            gd = self._apply_regularization(base, gd)
            np_, ns = self._rule(base, gd, slots, lr, step)
            if skip_update is not None:
                np_ = jnp.where(skip_update, base, np_)
                ns = {k: jnp.where(skip_update, slots[k], v) if k in slots else v for k, v in ns.items()}
            if master is not None:
                ns = dict(ns)
                ns["master_weight"] = np_
            # ALWAYS land on the param's dtype: update math may promote to
            # f32 (Adam's bias correction divides by f32 step powers) and a
            # silent f32 param would poison every later forward — bf16
            # models were measured training in f32 after step 1 (round-5
            # on-chip memory forensics) before this cast.
            new_params[name] = np_.astype(pd.dtype)
            new_slots[name] = ns
        new_state = {"step": step, "slots": new_slots}
        if skip_update is not None:
            new_state["step"] = jnp.where(skip_update, state["step"], step)
        return new_params, new_state

    def state_dict(self):
        sd = {"global_step": self._global_step}
        if self._parameter_list is not None:
            names = {id(p): f"param_{i}" for i, p in enumerate(self._parameter_list)}
            for pid, slots in self._accumulators.items():
                for k, v in slots.items():
                    sd[f"{names.get(pid, pid)}.{k}"] = Tensor(v)
        if isinstance(self._learning_rate, LRScheduler):
            sd["LR_Scheduler"] = self._learning_rate.state_dict()
        return sd

    def set_state_dict(self, state_dict):
        self._global_step = int(state_dict.get("global_step", 0))
        if isinstance(self._learning_rate, LRScheduler) and "LR_Scheduler" in state_dict:
            self._learning_rate.set_state_dict(state_dict["LR_Scheduler"])
        if self._parameter_list is not None:
            names = {f"param_{i}": p for i, p in enumerate(self._parameter_list)}
            for key, v in state_dict.items():
                if key in ("global_step", "LR_Scheduler"):
                    continue
                pname, _, slot = key.rpartition(".")
                p = names.get(pname)
                if p is not None:
                    self._slots_for(p)[slot] = v._data if isinstance(v, Tensor) else jnp.asarray(v)
