"""Deterministic fault injection for the stack's fragile seams.

Production fault tolerance that is asserted but never exercised is fiction:
the recovery paths in this repo (elastic restart, checkpoint resume, PS/RPC
retries, serving-slot isolation) only stay honest if a test can make the
underlying operation fail *on demand, deterministically, mid-flight*. This
module is that switch.

Design constraints, in priority order:

1. **Zero overhead when disabled.** Every instrumented seam calls
   ``chaos.site("name")``. When no plan is armed that is one module-attribute
   load and a ``None`` check — no dict lookup, no string formatting, no lock.
   The serve/train hot paths stay hot.
2. **Deterministic.** A ``FaultRule`` fires on exact hit counts (``after`` /
   ``times``), or — for probabilistic soak runs — from a seeded
   ``random.Random``. Same plan + same execution order = same faults.
3. **Cross-process.** Trainer subprocesses, dataloader worker forks, and PS
   server processes inherit the plan through the ``PADDLE_CHAOS`` env var
   (compact spec, parsed once at first site hit), so the launcher's watch
   loop and elastic restart can be tested against *real* child crashes.

Instrumented sites (grep for ``_chaos`` at each seam):

========================  ===================================================
site                      seam
========================  ===================================================
store.set/get/add/...     framework/native.py TCPStore client ops
ps.call                   distributed/ps/service.py PsClient._call
rpc.invoke                distributed/rpc/rpc.py _invoke
ckpt.write                distributed/checkpoint save (per-shard data write)
ckpt.manifest             distributed/checkpoint metadata commit
ckpt.snapshot             checkpoint/tiers.py Tier-0 ring snapshot
ckpt.gc                   checkpoint/tiers.py retention GC, per deletion
ckpt.emergency            checkpoint/tiers.py SIGTERM Tier-0→durable flush
ckpt.peer.publish         checkpoint/replica.py Tier-1 snapshot publication
ckpt.peer.fetch           checkpoint/replica.py Tier-1 peer snapshot fetch
save.write                serialization.save (single-process checkpoints)
launch.watch              distributed/launch/controller.py watch tick
elastic.host_loss         controller watch loop, probed once per crashed
                          container: firing declares that container's host
                          PERMANENTLY gone (restart budget exhausted
                          deterministically) — under --elastic_level >= 2
                          the job re-forms at the surviving world size
elastic.regrow            controller watch loop capacity-return probe:
                          firing simulates parked capacity coming back, so
                          the shrink→grow path is testable without real
                          hardware churn (production signal: touch the
                          PADDLE_ELASTIC_REGROW_PATH file)
dataloader.worker         io/dataloader.py forked worker, per batch
serve.prefill             inference/continuous.py per-request prefill
serve.decode              inference/continuous.py per decode dispatch
serving.handoff.send      serving/handoff.py per publish attempt — a fault
                          here exercises the bounded-backoff retry and the
                          deadline's blended fallback
serving.handoff.adopt     serving/handoff.py per adopt attempt (a decode
                          replica dying mid-adopt)
serving.handoff.corrupt   serving/handoff.py between fsync and rename of a
                          bundle — a ``truncate`` rule commits a torn file
                          the digest gate must reject (HandoffCorruptError)
serving.decode_pool_empty serving/frontend.py decode-pool liveness check:
                          firing declares the decode pool empty, forcing
                          the blended degradation path deterministically
serving.kv.fetch          serving/kvfabric.py per peer-fetch attempt — a
                          fault here drills the fetch_failed fallthrough
                          (the request recomputes, bit-identically)
serving.kv.timeout        serving/transport.py between RPC send and
                          receive — converted to the socket.timeout path a
                          stuck peer takes (typed KVFetchTimeout, never
                          retried)
serving.kv.partition      serving/transport.py per RPC attempt, before
                          the dial — exercises bounded-backoff retry and
                          the KVPartitionError exhaustion path
serving.kv.corrupt        serving/transport.py after RPC receive — the
                          received bytes are truncated so the blob/bundle
                          digest gate must refuse them
                          (HandoffCorruptError, recompute fallthrough)
obs.oom                   the XLA dispatch seams (jit_api train-step
                          dispatch, continuous._locked_dispatch): inject a
                          synthetic RESOURCE_EXHAUSTED so OOM forensics
                          (observability/compilemem.py oom_report.json) is
                          testable deterministically — compilemem.is_oom
                          recognizes a FaultInjected from this site
trainer.step              user training loops (opt-in; autoresume docs)
========================  ===================================================

Fault kinds: ``exc`` (raise; default :class:`FaultInjected`, a
``ConnectionError`` so transport retry filters catch it), ``exit``
(``os._exit(code)`` — a hard crash no ``finally`` can mask, the moral
equivalent of a preempted VM), ``truncate`` (chop bytes off the file path
the site reports — partial checkpoint shards), and ``sleep`` (latency).

Env spec (one rule per comma-separated field)::

    PADDLE_CHAOS="serve.decode:exc:after=1:times=2,trainer.step:exit=17:after=3"

i.e. ``site:kind[=arg][:after=N][:times=N][:p=F]``. ``PADDLE_CHAOS_SEED``
seeds the probabilistic rules.
"""
import os
import random
import threading
import time

__all__ = ["FaultInjected", "FaultRule", "FaultPlan", "site", "arm",
           "disarm", "active_plan", "env_spec"]


class FaultInjected(ConnectionError):
    """Raised by ``exc`` rules. Subclasses ConnectionError so the transport
    retry filters (store/PS/RPC) treat it exactly like a real network fault —
    the injection exercises the same except clauses production errors hit."""

    def __init__(self, site_name, hit):
        super().__init__(f"chaos: injected fault at {site_name!r} (hit {hit})")
        self.site = site_name
        self.hit = hit


class FaultRule:
    """One fault at one site (or a ``*`` suffix glob over sites).

    after:  skip the first `after` matching hits (0 = fire on the first).
    times:  fire at most `times` times (None = every matching hit).
    p:      instead of exact counting, fire with probability p per hit from
            the plan's seeded RNG (after/times still bound the window).
    kind:   "exc" | "exit" | "truncate" | "sleep".
    arg:    exc: exception instance/factory; exit: status code;
            truncate: bytes to keep (tail is dropped); sleep: seconds.
    """

    def __init__(self, site, kind="exc", arg=None, after=0, times=1, p=None):
        if kind not in ("exc", "exit", "truncate", "sleep"):
            raise ValueError(f"unknown fault kind {kind!r}")
        self.site = site
        self.kind = kind
        self.arg = arg
        self.after = int(after)
        self.times = None if times is None else int(times)
        self.p = None if p is None else float(p)
        self.hits = 0      # matching site hits seen
        self.fired = 0     # faults actually injected

    def matches(self, name):
        if self.site.endswith("*"):
            return name.startswith(self.site[:-1])
        return name == self.site

    def _should_fire(self, rng):
        self.hits += 1
        if self.hits <= self.after:
            return False
        if self.times is not None and self.fired >= self.times:
            return False
        if self.p is not None and rng.random() >= self.p:
            return False
        return True

    def spec(self):
        """Round-trippable env-spec fragment (see parse_env_spec). An exc
        rule's custom exception object cannot cross the env boundary — it
        serializes as the bare kind (the child raises FaultInjected)."""
        parts = [self.site]
        if self.kind == "exc":
            parts.append("exc")
        else:
            arg = "" if self.arg is None else f"={self.arg}"
            parts.append(f"{self.kind}{arg}")
        if self.after:
            parts.append(f"after={self.after}")
        if self.times != 1:
            parts.append(f"times={'inf' if self.times is None else self.times}")
        if self.p is not None:
            parts.append(f"p={self.p}")
        return ":".join(parts)


class FaultPlan:
    """A set of FaultRules + the seeded RNG; armed globally via `arm()` or
    as a context manager. Thread-safe: concurrent sites (PS worker pools,
    dataloader readers) count hits under one lock."""

    def __init__(self, seed=0):
        self.rules = []
        self.seed = int(seed)
        self._rng = random.Random(self.seed)
        self._lock = threading.Lock()

    # -- construction sugar -------------------------------------------------
    def fail(self, site, times=1, after=0, exc=None, p=None):
        self.rules.append(FaultRule(site, "exc", exc, after, times, p))
        return self

    def exit(self, site, code=1, after=0, times=1):
        self.rules.append(FaultRule(site, "exit", int(code), after, times))
        return self

    def truncate(self, site, keep_bytes=0, after=0, times=1):
        self.rules.append(FaultRule(site, "truncate", int(keep_bytes), after, times))
        return self

    def delay(self, site, seconds, after=0, times=1, p=None):
        self.rules.append(FaultRule(site, "sleep", float(seconds), after, times, p))
        return self

    # -- runtime ------------------------------------------------------------
    def on_site(self, name, path=None):
        for rule in self.rules:
            if not rule.matches(name):
                continue
            with self._lock:
                fire = rule._should_fire(self._rng)
                if fire:
                    rule.fired += 1
            if not fire:
                continue
            _count(f"fault.injected.{name}")
            if rule.kind == "sleep":
                time.sleep(rule.arg)
            elif rule.kind == "truncate":
                if path is not None and os.path.exists(path):
                    with open(path, "rb+") as f:
                        f.truncate(rule.arg)
            elif rule.kind == "exit":
                os._exit(rule.arg if rule.arg is not None else 1)
            else:
                exc = rule.arg
                if exc is None:
                    raise FaultInjected(name, rule.hits)
                raise exc() if callable(exc) else exc

    def env_spec(self):
        """Serialize for child processes: exc args beyond the default cannot
        cross the env boundary — rules carrying exception objects serialize
        as the default FaultInjected."""
        return ",".join(r.spec() for r in self.rules)

    def __enter__(self):
        arm(self)
        return self

    def __exit__(self, *exc_info):
        disarm()
        return False


# -- global switch ----------------------------------------------------------
# _PLAN is THE hot-path gate: `site()` bails on `_PLAN is None` before doing
# anything else. Arming parses PADDLE_CHAOS lazily exactly once per process.
_PLAN = None
_ENV_PARSED = False


def _count(name):
    try:
        from ..utils.metrics_bus import counters

        counters.bump(name)
    except Exception:
        pass


def parse_env_spec(spec, seed=0):
    """'site:kind[=arg][:after=N][:times=N|inf][:p=F],...' -> FaultPlan"""
    plan = FaultPlan(seed=seed)
    for field in spec.split(","):
        field = field.strip()
        if not field:
            continue
        parts = field.split(":")
        site_name, opts = parts[0], parts[1:]
        kind, arg, kw = "exc", None, {}
        for o in opts:
            k, _, v = o.partition("=")
            if k in ("exc", "exit", "truncate", "sleep"):
                kind = k
                if v:
                    arg = float(v) if k == "sleep" else int(v)
            elif k in ("after", "times"):
                kw[k] = None if v == "inf" else int(v)
            elif k == "p":
                kw["p"] = float(v)
            else:
                raise ValueError(f"bad chaos option {o!r} in {field!r}")
        plan.rules.append(FaultRule(site_name, kind, arg, **kw))
    return plan


def arm(plan):
    global _PLAN
    _PLAN = plan
    return plan


def disarm():
    global _PLAN, _ENV_PARSED
    _PLAN = None
    _ENV_PARSED = True  # an explicit disarm also suppresses the env plan


def active_plan():
    return _PLAN


def env_spec(plan):
    """Env dict to arm `plan` in a child process."""
    return {"PADDLE_CHAOS": plan.env_spec(),
            "PADDLE_CHAOS_SEED": str(plan.seed)}


def site(name, path=None):
    """The instrumentation hook. Disabled cost: one global load + is-None
    check + an env-var membership probe on the first call only."""
    global _ENV_PARSED
    if _PLAN is None:
        if _ENV_PARSED:
            return
        _ENV_PARSED = True
        from ..utils.envs import env_int, env_str

        spec = env_str("PADDLE_CHAOS")
        if not spec:
            return
        arm(parse_env_spec(spec, seed=env_int("PADDLE_CHAOS_SEED", 0)))
    _PLAN.on_site(name, path=path)
