"""Testing utilities: deterministic fault injection (chaos) for exercising
the stack's recovery paths. Import surface:

    from paddle_tpu.testing import chaos
    with chaos.FaultPlan().fail("store.get", times=2):
        ...
"""
from . import chaos  # noqa: F401
