"""Runtime lock-order sanitizer (ISSUE 10 tentpole part e).

The static ``lock-order`` rule sees ``with`` statements; it cannot see
acquisition orders assembled through indirection — locks passed as
arguments, factories, ExitStacks, callbacks. This module records the
orders that ACTUALLY happen while the test suite runs and reports
inversions: lock pairs observed nested in both directions, which is a
deadlock waiting for the two threads to interleave.

Opt-in and zero-cost when off: arm with ``PADDLE_LOCKORDER=1`` —
``tests/conftest.py`` boot-loads this module BEFORE anything imports
``paddle_tpu`` (module-level locks like the engine compile lock must be
created through the patched factories) and fails the session on
inversions. Only locks ALLOCATED from repo code (``paddle_tpu/`` or
``tests/`` frames) are tracked; stdlib/jax internals keep real primitives.

Lock identity is the allocation site (``file:line``), or an explicit
label: a lock wrapper can stamp ``_lo_name`` on a tracked inner lock
(see ``_StampedRLock(name=...)``) so the compile lock and the per-engine
dispatch locks — born on the same source line — stay distinct order
classes.

No dependencies; importable standalone by path (the conftest boot
requirement — importing the ``paddle_tpu`` package would create its
locks before the patch lands).
"""
import json
import os
import sys
import threading

__all__ = ["Graph", "install", "installed", "graph", "report",
           "report_path", "wrap_lock"]

_REPO_MARKERS = (os.sep + "paddle_tpu" + os.sep, os.sep + "tests" + os.sep)


def _alloc_site():
    """file:line of the nearest stack frame outside this module and
    threading.py — where the lock was born (or acquired)."""
    f = sys._getframe(2)
    while f is not None:
        fn = f.f_code.co_filename
        if not fn.endswith(("lockorder.py", "threading.py")):
            return f"{os.path.basename(fn)}:{f.f_lineno}", fn
        f = f.f_back
    return "<unknown>", ""


class Graph:
    """The observed acquisition-order graph. Thread-safe via one private
    REAL lock (allocated before install() patches the factories when used
    as the global graph; explicitly real otherwise)."""

    def __init__(self, lock_factory=threading.Lock):
        self._mu = lock_factory()
        self._tls = threading.local()
        #: (a, b) -> {"count": n, "where": "file:line of b's acquire"}
        self.edges = {}
        #: (node, id_lo, id_hi) -> set of "asc"/"desc" — same-order-class
        #: instance pairs (two engines' dispatch locks) nested both ways
        #: are the classic peer-instance deadlock
        self.instance_orders = {}

    def _held(self):
        h = getattr(self._tls, "held", None)
        if h is None:
            h = self._tls.held = []
        return h

    def note_acquired(self, node, inst):
        held = self._held()
        site, _ = _alloc_site()
        new_edges = []
        for (h_node, h_inst) in held:
            if h_node != node:
                new_edges.append((h_node, node))
            elif h_inst != inst:
                key = (node, min(h_inst, inst), max(h_inst, inst))
                orient = "asc" if h_inst < inst else "desc"
                with self._mu:
                    self.instance_orders.setdefault(key, set()).add(orient)
        if new_edges:
            with self._mu:
                for e in new_edges:
                    rec = self.edges.get(e)
                    if rec is None:
                        self.edges[e] = {"count": 1, "where": site}
                    else:
                        rec["count"] += 1
        held.append((node, inst))

    def note_released(self, node, inst):
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            if held[i] == (node, inst):
                del held[i]
                return

    # ---- reporting -------------------------------------------------------
    def inversions(self):
        """Lock-order violations observed so far: 2-cycles (and longer
        cycles) in the node graph, plus peer-instance both-ways nestings
        of one order class."""
        with self._mu:
            edges = {k: dict(v) for k, v in self.edges.items()}
            inst = {k: set(v) for k, v in self.instance_orders.items()}
        graph = {}
        for (a, b) in edges:
            graph.setdefault(a, set()).add(b)
        out, seen = [], set()
        # cycles via DFS (2-cycles dominate in practice; longer ones are
        # reported from whichever node the DFS enters them)
        def dfs(node, path, on_path):
            for nxt in sorted(graph.get(node, ())):
                if nxt in on_path:
                    cyc = path[path.index(nxt):] + [nxt]
                    key = frozenset(cyc)
                    if key not in seen:
                        seen.add(key)
                        out.append({
                            "kind": "cycle",
                            "nodes": cyc,
                            "sites": [edges[(x, y)]["where"]
                                      for x, y in zip(cyc, cyc[1:])],
                        })
                elif nxt not in visited:
                    on_path.add(nxt)
                    dfs(nxt, path + [nxt], on_path)
                    on_path.discard(nxt)
            visited.add(node)

        visited = set()
        for start in sorted(graph):
            if start not in visited:
                dfs(start, [start], {start})
        for (node, lo, hi), orients in sorted(inst.items()):
            if len(orients) > 1:
                out.append({"kind": "instance-order",
                            "nodes": [node, node],
                            "sites": [f"two instances of {node} nested "
                                      f"in both orders"]})
        return out

    def report(self):
        with self._mu:
            n_edges = len(self.edges)
        return {"edges": n_edges, "inversions": self.inversions()}


class _TrackedLock:
    """Order-tracking proxy over a real Lock/RLock. Forwards everything
    it doesn't instrument (``_is_owned`` etc. keep Condition working)."""

    def __init__(self, inner, graph, name):
        self._lo_inner = inner
        self._lo_graph = graph
        self._lo_name = name

    def acquire(self, blocking=True, timeout=-1):
        ok = self._lo_inner.acquire(blocking, timeout)
        if ok:
            self._lo_graph.note_acquired(self._lo_name, id(self))
        return ok

    def release(self):
        self._lo_inner.release()
        self._lo_graph.note_released(self._lo_name, id(self))

    def __enter__(self):
        return self.acquire()

    def __exit__(self, *exc_info):
        self.release()
        return False

    def locked(self):
        return self._lo_inner.locked()

    def __getattr__(self, attr):
        return getattr(self._lo_inner, attr)

    def __repr__(self):
        return f"<lockorder-tracked {self._lo_name} {self._lo_inner!r}>"


class _TrackedCondition(_TrackedLock):
    """Condition proxy: acquire/release tracked like a lock; wait/notify
    forwarded (wait's internal release/re-acquire of the underlying lock
    happens while this thread is blocked — it records nothing, so the
    held stack stays consistent)."""

    def wait(self, timeout=None):
        return self._lo_inner.wait(timeout)

    def wait_for(self, predicate, timeout=None):
        return self._lo_inner.wait_for(predicate, timeout)

    def notify(self, n=1):
        return self._lo_inner.notify(n)

    def notify_all(self):
        return self._lo_inner.notify_all()


_GLOBAL = None
_ORIG = {}


def installed():
    return _GLOBAL is not None


def graph():
    return _GLOBAL


def wrap_lock(inner, name, graph_=None):
    """Explicitly wrap ``inner`` as a tracked lock named ``name`` —
    the unit-test surface (works without install())."""
    return _TrackedLock(inner, graph_ or _GLOBAL or Graph(), name)


def install():
    """Patch the threading lock factories; idempotent. Everything
    allocated FROM REPO CODE after this call is tracked."""
    global _GLOBAL
    if _GLOBAL is not None:
        return _GLOBAL
    _GLOBAL = Graph(lock_factory=threading.Lock)  # real lock, pre-patch
    _ORIG["Lock"] = threading.Lock
    _ORIG["RLock"] = threading.RLock
    _ORIG["Condition"] = threading.Condition

    def _repo_alloc():
        _, fn = _alloc_site()
        return any(m in fn for m in _REPO_MARKERS)

    def make_lock():
        inner = _ORIG["Lock"]()
        if not _repo_alloc():
            return inner
        site, _ = _alloc_site()
        return _TrackedLock(inner, _GLOBAL, f"Lock@{site}")

    def make_rlock():
        inner = _ORIG["RLock"]()
        if not _repo_alloc():
            return inner
        site, _ = _alloc_site()
        return _TrackedLock(inner, _GLOBAL, f"RLock@{site}")

    def make_condition(lock=None):
        if isinstance(lock, _TrackedLock):
            # the passed lock is already tracked — every cond acquire
            # flows through its proxy; a second wrapper would double-count
            return _ORIG["Condition"](lock)
        if not _repo_alloc():
            return _ORIG["Condition"](lock)
        site, _ = _alloc_site()
        # build over a REAL inner lock: tracking belongs to the condition
        # node, not to a second shadow node for its internal lock
        inner = _ORIG["Condition"](lock if lock is not None
                                   else _ORIG["RLock"]())
        return _TrackedCondition(inner, _GLOBAL, f"Condition@{site}")

    threading.Lock = make_lock
    threading.RLock = make_rlock
    threading.Condition = make_condition
    return _GLOBAL


def uninstall():
    """Restore the real factories (test hook). Locks already created keep
    their proxies; the global graph is dropped."""
    global _GLOBAL
    if _GLOBAL is None:
        return
    threading.Lock = _ORIG.pop("Lock")
    threading.RLock = _ORIG.pop("RLock")
    threading.Condition = _ORIG.pop("Condition")
    _GLOBAL = None


def report_path():
    """Where the sanitizer's report belongs: inside ``PADDLE_TELEMETRY_DIR``
    when it is set (next to the other telemetry artifacts), else
    ``telemetry/`` under the CWD — so a tier-1 run with a configured
    telemetry dir never litters the repo root. Read via the blessed env
    helper when ``paddle_tpu`` is importable (report time — the package is
    long loaded); the boot-time standalone constraint only applies to
    module import, not to this call."""
    try:
        from paddle_tpu.utils.envs import env_str

        d = env_str("PADDLE_TELEMETRY_DIR")
    except Exception:
        d = None
    return os.path.join(d or "telemetry", "lockorder_report.json")


def report(path=None):
    """The global graph's report; optionally committed to ``path`` as
    JSON. ``{"edges": 0, "inversions": []}`` when never installed."""
    rep = _GLOBAL.report() if _GLOBAL is not None else \
        {"edges": 0, "inversions": []}
    if path:
        try:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            with open(path, "w", encoding="utf-8") as f:
                json.dump(rep, f, indent=1)
        except OSError:
            pass
    return rep
