"""paddle.text parity (reference: python/paddle/text/ — datasets Imdb/
Conll05st/Movielens/UCIHousing/WMT14/WMT16 + ViterbiDecoder in paddle.text.
viterbi_decode lives in python/paddle/text/viterbi_decode.py).

No-egress environment: datasets read local files when paths are given and
fall back to deterministic synthetic corpora (same pattern as vision
datasets)."""
from .datasets import Conll05st, Imdb, Movielens, UCIHousing, WMT14, WMT16
from .viterbi_decode import ViterbiDecoder, viterbi_decode

__all__ = [
    "Imdb", "Conll05st", "Movielens", "UCIHousing", "WMT14", "WMT16",
    "ViterbiDecoder", "viterbi_decode",
]
