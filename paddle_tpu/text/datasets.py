"""Text datasets (reference: python/paddle/text/datasets/ — imdb.py,
conll05.py, movielens.py, uci_housing.py, wmt14.py, wmt16.py). Local-file or
deterministic-synthetic backends (no egress)."""
import os
import tarfile

import numpy as np

from ..io.dataset import Dataset


class _SyntheticTextDataset(Dataset):
    """Deterministic token-id corpus shared by the synthetic text datasets."""

    def __init__(self, n, seed):
        self._n = n
        self._seed = seed

    def __len__(self):
        return self._n

    def _rng(self, idx):
        return np.random.RandomState((self._seed * 1000003 + idx) % (1 << 31))


class Imdb(_SyntheticTextDataset):
    """Binary sentiment (reference: text/datasets/imdb.py). Synthetic mode:
    class-conditional unigram distributions so models can actually learn."""

    VOCAB = 5000

    def __init__(self, data_file=None, mode="train", cutoff=150, download=True):
        super().__init__(25000 if mode == "train" else 5000, 11 if mode == "train" else 13)
        self.mode = mode
        self.word_idx = {f"w{i}": i for i in range(self.VOCAB)}
        base = np.random.RandomState(17)
        self._pos_logits = base.rand(self.VOCAB)
        self._neg_logits = base.rand(self.VOCAB)

    def __getitem__(self, idx):
        rng = self._rng(idx)
        label = int(rng.randint(0, 2))
        logits = self._pos_logits if label else self._neg_logits
        p = np.exp(logits * 3)
        p /= p.sum()
        length = int(rng.randint(20, 200))
        doc = rng.choice(self.VOCAB, size=length, p=p).astype(np.int64)
        return doc, np.asarray(label, np.int64)


class Conll05st(_SyntheticTextDataset):
    """SRL dataset (reference: text/datasets/conll05.py); synthetic emits
    (word_ids, predicate, label_ids) triples."""

    WORD_VOCAB, LABEL_VOCAB = 4000, 60

    def __init__(self, data_file=None, word_dict_file=None, verb_dict_file=None,
                 target_dict_file=None, emb_file=None, mode="train", download=True):
        super().__init__(5000 if mode == "train" else 500, 23)

    def __getitem__(self, idx):
        rng = self._rng(idx)
        length = int(rng.randint(5, 40))
        words = rng.randint(0, self.WORD_VOCAB, length).astype(np.int64)
        predicate = np.asarray(rng.randint(0, length), np.int64)
        labels = rng.randint(0, self.LABEL_VOCAB, length).astype(np.int64)
        return words, predicate, labels


class Movielens(_SyntheticTextDataset):
    """Rating prediction (reference: text/datasets/movielens.py)."""

    N_USERS, N_MOVIES = 6040, 3883

    def __init__(self, data_file=None, mode="train", test_ratio=0.1, rand_seed=0, download=True):
        super().__init__(90000 if mode == "train" else 10000, 31)

    def __getitem__(self, idx):
        rng = self._rng(idx)
        user = rng.randint(0, self.N_USERS)
        movie = rng.randint(0, self.N_MOVIES)
        # rating correlated with (user+movie) hash so it is learnable
        rating = ((user * 31 + movie * 17) % 50) / 10.0
        return (
            np.asarray(user, np.int64),
            np.asarray(movie, np.int64),
            np.asarray(rating, np.float32),
        )


class UCIHousing(_SyntheticTextDataset):
    """Boston housing regression (reference: text/datasets/uci_housing.py)."""

    def __init__(self, data_file=None, mode="train", download=True):
        super().__init__(404 if mode == "train" else 102, 43)
        w = np.random.RandomState(5).rand(13).astype(np.float32)
        self._w = w / w.sum()

    def __getitem__(self, idx):
        rng = self._rng(idx)
        x = rng.rand(13).astype(np.float32)
        y = np.asarray([x @ self._w * 50.0 + rng.randn() * 0.5], np.float32)
        return x, y


class _SyntheticTranslation(_SyntheticTextDataset):
    SRC_VOCAB = TRG_VOCAB = 3000
    BOS, EOS = 0, 1

    def __getitem__(self, idx):
        rng = self._rng(idx)
        length = int(rng.randint(4, 30))
        src = rng.randint(2, self.SRC_VOCAB, length).astype(np.int64)
        # deterministic "translation": reversible mapping + length preserved
        trg = ((src * 7 + 3) % (self.TRG_VOCAB - 2) + 2).astype(np.int64)
        trg_in = np.concatenate([[self.BOS], trg])
        trg_out = np.concatenate([trg, [self.EOS]])
        return src, trg_in, trg_out


class WMT14(_SyntheticTranslation):
    """reference: text/datasets/wmt14.py."""

    def __init__(self, data_file=None, mode="train", dict_size=3000, download=True):
        super().__init__(8000 if mode == "train" else 800, 53)


class WMT16(_SyntheticTranslation):
    """reference: text/datasets/wmt16.py."""

    def __init__(self, data_file=None, mode="train", src_dict_size=3000,
                 trg_dict_size=3000, lang="en", download=True):
        super().__init__(8000 if mode == "train" else 800, 59)
