"""Viterbi decoding (reference: python/paddle/text/viterbi_decode.py —
`ViterbiDecoder` layer + `viterbi_decode` functional; C++ kernel
phi/kernels/cpu/viterbi_decode_kernel.cc).

TPU-native: the DP recursion is a lax.scan over time steps — compiles to one
fused XLA loop, batch-parallel on the MXU-friendly [B, N, N] score tensor.
"""
import jax
import jax.numpy as jnp

from ..framework.core import Tensor
from ..nn.layer.layers import Layer


def viterbi_decode(potentials, transition_params, lengths, include_bos_eos_tag=True, name=None):
    """potentials: [B, T, N] emission scores; transition_params: [N, N];
    lengths: [B]. Returns (scores [B], paths [B, T])."""
    pot = potentials._data if isinstance(potentials, Tensor) else jnp.asarray(potentials)
    trans = (
        transition_params._data
        if isinstance(transition_params, Tensor)
        else jnp.asarray(transition_params)
    )
    lens = lengths._data if isinstance(lengths, Tensor) else jnp.asarray(lengths)
    B, T, N = pot.shape

    if include_bos_eos_tag:
        # reference convention: tag N-2 is BOS, N-1 is EOS
        bos, eos = N - 2, N - 1
        init = pot[:, 0, :] + trans[bos][None, :]
    else:
        init = pot[:, 0, :]

    def step(carry, t):
        alpha, _ = carry
        # alpha: [B, N]; score of best path ending in each tag
        scores = alpha[:, :, None] + trans[None, :, :] + pot[:, t, :][:, None, :]
        best_prev = jnp.argmax(scores, axis=1)  # [B, N]
        new_alpha = jnp.max(scores, axis=1)
        # mask out past-length steps: keep previous alpha, backpointer=identity
        active = (t < lens)[:, None]
        new_alpha = jnp.where(active, new_alpha, alpha)
        best_prev = jnp.where(active, best_prev, jnp.arange(N)[None, :])
        return (new_alpha, t), best_prev

    (alpha, _), backptrs = jax.lax.scan(step, (init, 0), jnp.arange(1, T))
    # backptrs: [T-1, B, N]
    if include_bos_eos_tag:
        alpha = alpha + trans[:, eos][None, :]

    last_tag = jnp.argmax(alpha, axis=-1)  # [B]
    scores = jnp.max(alpha, axis=-1)

    def backtrack(carry, bp_t):
        tag = carry
        prev = jnp.take_along_axis(bp_t, tag[:, None], axis=1)[:, 0]
        return prev, tag

    # reverse scan emits path[1..T-1] (stacked forward); final carry is path[0]
    first_tag, tags_rest = jax.lax.scan(backtrack, last_tag, backptrs, reverse=True)
    paths = jnp.concatenate([first_tag[:, None], tags_rest.T], axis=1)  # [B, T]
    # zero out positions beyond each sequence's length
    mask = jnp.arange(T)[None, :] < lens[:, None]
    paths = jnp.where(mask, paths, 0)
    return Tensor(scores), Tensor(paths.astype(jnp.int64))


class ViterbiDecoder(Layer):
    def __init__(self, transitions, include_bos_eos_tag=True, name=None):
        super().__init__()
        self.transitions = transitions
        self.include_bos_eos_tag = include_bos_eos_tag

    def forward(self, potentials, lengths):
        return viterbi_decode(potentials, self.transitions, lengths, self.include_bos_eos_tag)
