"""In-program training-dynamics telemetry (ISSUE 13 tentpole).

Every telemetry layer before this one watches the HOST side — spans,
compile events, HBM budgets, fleet skew. This module observes the model's
own numerics INSIDE the compiled step: a small fixed-shape stats carry
(donated, like the non-finite sentinel's counters) is updated by pure
jit-side math every step and spilled to the host on a cadence, so a
diverging run is visible — and attributable to a layer group — before the
loss chart goes bad and the evidence is gone.

What the carry holds, per step:

- **global + per-layer-group gradient norms** (squared, f32) — the first
  signal a desyncing rank or an exploding layer shows;
- **per-group parameter norms and update norms** — ‖Δw‖/‖w‖ update ratios,
  the classic "is the LR sane for THIS layer" diagnostic;
- **loss EWMA + variance EWMA + spike z-score** — computed in-program so a
  spike is stamped at the exact step it happened, not at the next log line;
- **non-finite provenance**: a per-group mask of which groups' gradients
  were NaN/Inf this step, and a LATCHED first-occurrence mask + step —
  upgrading the PR-9 count-only sentinel to "group `layers.7` went
  non-finite first, at update 412".

Layer groups: parameter names are bucketed by :func:`group_of` —
``model.layers.3.self_attn.q_proj.weight`` → ``layers.3``; non-stacked
params group by their first dotted component. The group count is bounded
(``PADDLE_DYNAMICS_MAX_GROUPS``; overflow collapses into ``other``), so
the carry is a handful of ``f32[G]`` vectors — signature-stable, and the
per-group sums are an O(params) fusion into the step program XLA
schedules alongside the optimizer update.

Cost contract (the PR-2 discipline, asserted in tests/test_dynamics.py):

- **disabled** (``PADDLE_DYNAMICS`` unset): ``DynamicsMonitor.from_env``
  returns None — the compiled program carries NOTHING and the host
  epilogue pays one attribute-is-None check;
- **enabled, between spills**: the host path is one counter increment;
- **spill** (every ``PADDLE_DYNAMICS_EVERY_STEPS`` dispatches): ONE
  device→host read of the small carry (the only added sync per window),
  accounted to the explicit ``telemetry`` goodput phase — never silently
  inflating ``step`` time.

Spills publish ``train.grad_norm`` / ``train.param_norm`` /
``train.update_ratio{group=}`` / ``train.loss_spike_z`` gauges, append to
a bounded window ring (the flight recorder's "what led up to it" payload),
and fire the ``loss_spike`` flight trigger past ``PADDLE_DYNAMICS_SPIKE_Z``.

jax is imported lazily inside the jit-side helpers — the observability
package stays stdlib-only at import time.
"""
import collections
import math
import re
import threading
import time
import weakref

from ..utils.envs import env_bool, env_float, env_int
from .metrics import registry as _registry

__all__ = ["DynamicsMonitor", "group_of", "monitors", "reports",
           "flight_block", "fleet_block", "ENABLE_ENV", "EVERY_ENV",
           "SPIKE_Z_ENV", "EWMA_ENV", "MAX_GROUPS_ENV", "WINDOW_ENV"]

#: master switch — unset/false = the whole layer is one None check
ENABLE_ENV = "PADDLE_DYNAMICS"
#: host spill cadence in dispatches: at most one device sync per window
EVERY_ENV = "PADDLE_DYNAMICS_EVERY_STEPS"
#: EWMA decay for the loss mean/variance trackers
EWMA_ENV = "PADDLE_DYNAMICS_EWMA"
#: |z| past this fires the loss_spike flight trigger (<=0 disables)
SPIKE_Z_ENV = "PADDLE_DYNAMICS_SPIKE_Z"
#: layer-group cap — overflow groups collapse into 'other'
MAX_GROUPS_ENV = "PADDLE_DYNAMICS_MAX_GROUPS"
#: host-side summary ring length (the flight-record dynamics window)
WINDOW_ENV = "PADDLE_DYNAMICS_WINDOW"

#: repeated-block param names: the numbered block IS the layer group
_LAYER_RE = re.compile(
    r"(?:^|\.)((?:layers|layer|blocks|h|stages|encoder_layers|"
    r"decoder_layers)\.\d+)(?=\.|$)")

#: live monitors, for /dynamicsz and the fleet snapshot block — weak so a
#: dropped TrainStep takes its monitor out of the listing
_monitors = weakref.WeakValueDictionary()
_monitors_lock = threading.Lock()
_monitor_seq = 0


def group_of(name):
    """Layer group for a parameter name: the numbered transformer block
    (``layers.3``) when one appears in the dotted path, else the first
    dotted component (``embed_tokens``, ``lm_head``), else ``root``."""
    m = _LAYER_RE.search(name)
    if m:
        return m.group(1)
    head = name.split(".", 1)[0]
    return head or "root"


def monitors():
    """Live monitors, oldest first (usually exactly one per process)."""
    with _monitors_lock:
        return [m for _, m in sorted(_monitors.items())]


def reports():
    """The /dynamicsz monitor payloads."""
    return [m.report() for m in monitors()]


def flight_block():
    """The flight-record payload: per-monitor group list, last summary and
    the recent spill window."""
    out = []
    for m in monitors():
        out.append({
            "groups": list(m.group_names),
            "every": m.every,
            "last": m.last,
            "window": m.window_list(),
        })
    return out


def fleet_block():
    """The per-rank fleet-snapshot block (bounded: the newest monitor's
    last spilled summary only) — what the aggregator reads to flag
    cross-rank grad-norm skew. None when nothing has spilled."""
    ms = monitors()
    for m in reversed(ms):
        if m.last is not None:
            return dict(m.last)
    return None


class DynamicsMonitor:
    """One TrainStep's dynamics instrumentation: the static group mapping,
    the jit-side carry update, and the cadence-gated host spill."""

    def __init__(self, named_params, every=None, ewma=None, spike_z=None,
                 max_groups=None, window=None):
        max_groups = (int(max_groups) if max_groups is not None
                      else env_int(MAX_GROUPS_ENV, 64))
        groups = {}
        for name in named_params:
            groups.setdefault(group_of(name), []).append(name)
        names = sorted(groups)
        if len(names) > max_groups:
            kept, spill = names[:max_groups - 1], names[max_groups - 1:]
            other = []
            for g in spill:
                other.extend(groups.pop(g))
            groups["other"] = other
            names = kept + ["other"]
        #: group names, index-aligned with every f32[G] carry vector
        self.group_names = tuple(names)
        self._group_members = tuple(tuple(groups[g]) for g in names)
        self.every = max(1, every if every is not None
                         else env_int(EVERY_ENV, 32))
        self.ewma = float(ewma if ewma is not None
                          else env_float(EWMA_ENV, 0.1))
        self.spike_z = float(spike_z if spike_z is not None
                             else env_float(SPIKE_Z_ENV, 6.0))
        window = (int(window) if window is not None
                  else env_int(WINDOW_ENV, 32))
        #: recent spill summaries — the flight recorder's dynamics window.
        #: Appended by the training thread, read by statusz/flightrec
        #: threads: all access goes through _win_lock (iterating a deque
        #: mid-append raises RuntimeError, and that error would replace
        #: the dynamics block of exactly the bundle that needed it).
        self.window = collections.deque(maxlen=max(1, window))
        self._win_lock = threading.Lock()
        #: the newest spilled summary (None until the first spill)
        self.last = None
        global _monitor_seq
        with _monitors_lock:
            _monitor_seq += 1
            _monitors[_monitor_seq] = self

    @classmethod
    def from_env(cls, named_params):
        """The TrainStep hook: a monitor when ``PADDLE_DYNAMICS`` is
        truthy, else None — and None means the step carries nothing."""
        if not env_bool(ENABLE_ENV):
            return None
        return cls(named_params)

    # ---- jit side ----------------------------------------------------------
    def init_state(self):
        """The donated stats carry: fixed-shape f32/i32 leaves only, so the
        compiled signature is stable for the life of the step program."""
        import jax.numpy as jnp

        g = len(self.group_names)
        # one DISTINCT array per leaf: the whole carry is donated, and
        # donating one aliased buffer under two leaves is an XLA error
        # ("attempt to donate the same buffer twice")
        return {
            "count": jnp.zeros((), jnp.int32),
            "loss_ewma": jnp.zeros((), jnp.float32),
            "loss_var": jnp.zeros((), jnp.float32),
            "loss_z": jnp.zeros((), jnp.float32),
            # max-z latch since the last spill window reset: a one-step
            # spike that decays before the cadence read must still be
            # caught (same latch idea as nf_first_mask)
            "z_max": jnp.full((), -jnp.inf, jnp.float32),
            "z_max_at": jnp.full((), -1, jnp.int32),
            "last_loss": jnp.zeros((), jnp.float32),
            "grad_sq": jnp.zeros((g,), jnp.float32),
            "param_sq": jnp.zeros((g,), jnp.float32),
            "upd_sq": jnp.zeros((g,), jnp.float32),
            "nf_mask": jnp.zeros((g,), jnp.int32),
            "nf_first_mask": jnp.zeros((g,), jnp.int32),
            "nf_first_step": jnp.full((), -1, jnp.int32),
            "nf_steps": jnp.zeros((), jnp.int32),
        }

    def update(self, st, loss, grads, params, new_params):
        """Pure carry update, traced INTO the step program. ``grads`` are
        the unscaled pre-clip gradients (what the model actually produced);
        ``params``/``new_params`` bracket the optimizer update so
        ‖Δw‖ reflects clipping, weight decay and any skip-gating."""
        import jax.numpy as jnp

        f32 = jnp.float32
        gsq, psq, usq, gfin = [], [], [], []
        for members in self._group_members:
            g2 = jnp.zeros((), f32)
            p2 = jnp.zeros((), f32)
            u2 = jnp.zeros((), f32)
            fin = jnp.asarray(True)
            for n in members:
                g = grads.get(n)
                if g is not None:
                    g32 = g.astype(f32)
                    g2 = g2 + jnp.sum(g32 * g32)
                    fin = fin & jnp.all(jnp.isfinite(g32))
                p32 = params[n].astype(f32)
                p2 = p2 + jnp.sum(p32 * p32)
                d = new_params[n].astype(f32) - p32
                u2 = u2 + jnp.sum(d * d)
            gsq.append(g2)
            psq.append(p2)
            usq.append(u2)
            gfin.append(fin)
        grad_sq = jnp.stack(gsq)
        param_sq = jnp.stack(psq)
        upd_sq = jnp.stack(usq)
        finite = jnp.stack(gfin)

        loss32 = jnp.asarray(loss).astype(f32)
        loss_ok = jnp.isfinite(loss32)
        nf_mask = (~finite).astype(jnp.int32)
        nf_any = (~loss_ok) | jnp.any(~finite)
        newly = (st["nf_first_step"] < 0) & nf_any

        count = st["count"]
        a = f32(self.ewma)
        prev_mean, prev_var = st["loss_ewma"], st["loss_var"]
        delta = loss32 - prev_mean
        # z of THIS step's loss against the pre-update trackers; 0 until
        # the variance tracker has something to divide by, and a
        # non-finite loss reports the sentinel value 0 (the nf fields
        # carry that story — a NaN z would poison the spike gauge)
        z = jnp.where((count > 0) & (prev_var > 0) & loss_ok,
                      delta / jnp.sqrt(prev_var + f32(1e-12)), f32(0))
        # non-finite losses never enter the trackers: one NaN would stick
        # the EWMA at NaN forever and blind every later spike
        new_mean = jnp.where(
            loss_ok, jnp.where(count == 0, loss32, prev_mean + a * delta),
            prev_mean)
        new_var = jnp.where(loss_ok & (count > 0),
                            (f32(1) - a) * (prev_var + a * delta * delta),
                            prev_var)
        z_hit = z > st["z_max"]
        return {
            "count": count + 1,
            "loss_ewma": new_mean,
            "loss_var": new_var,
            "loss_z": z,
            "z_max": jnp.maximum(z, st["z_max"]),
            "z_max_at": jnp.where(z_hit, count,
                                  st["z_max_at"]).astype(jnp.int32),
            "last_loss": loss32,
            "grad_sq": grad_sq,
            "param_sq": param_sq,
            "upd_sq": upd_sq,
            "nf_mask": nf_mask,
            "nf_first_mask": jnp.where(newly, nf_mask,
                                       st["nf_first_mask"]),
            "nf_first_step": jnp.where(newly, count,
                                       st["nf_first_step"]).astype(jnp.int32),
            "nf_steps": st["nf_steps"] + nf_any.astype(jnp.int32),
        }

    # ---- host side ---------------------------------------------------------
    @staticmethod
    def _get(state):
        import jax

        return jax.device_get(state)

    def summarize(self, state, step=None):
        """One host read of the carry (THE sync) distilled into a plain
        dict. Does not publish or trigger — :meth:`spill` does."""
        if state is None:
            return None
        st = self._get(state)
        grad_sq = [float(v) for v in st["grad_sq"]]
        param_sq = [float(v) for v in st["param_sq"]]
        upd_sq = [float(v) for v in st["upd_sq"]]
        eps = 1e-20
        groups = {}
        for i, name in enumerate(self.group_names):
            groups[name] = {
                "grad_norm": round(math.sqrt(max(grad_sq[i], 0.0)), 8),
                "param_norm": round(math.sqrt(max(param_sq[i], 0.0)), 8),
                "update_ratio": round(
                    math.sqrt(max(upd_sq[i], 0.0)
                              / max(param_sq[i], eps)), 10),
            }
        nf_first_step = int(st["nf_first_step"])
        z_max = float(st["z_max"])
        summary = {
            "step": int(step) if step is not None else int(st["count"]),
            "updates": int(st["count"]),
            "time": time.time(),
            "loss": float(st["last_loss"]),
            "loss_ewma": float(st["loss_ewma"]),
            "loss_z": float(st["loss_z"]),
            "loss_z_max": z_max if math.isfinite(z_max) else None,
            "loss_z_max_at": int(st["z_max_at"]),
            "grad_norm": round(math.sqrt(max(sum(grad_sq), 0.0)), 8),
            "groups": groups,
            "nonfinite_steps": int(st["nf_steps"]),
            "nonfinite_groups": [self.group_names[i]
                                 for i, v in enumerate(st["nf_mask"]) if v],
            "nonfinite_first": None if nf_first_step < 0 else {
                "update": nf_first_step,
                "groups": [self.group_names[i]
                           for i, v in enumerate(st["nf_first_mask"]) if v],
            },
        }
        return summary

    def provenance(self, state):
        """The latched first-non-finite record (None while everything has
        stayed finite): which layer group(s) went NaN/Inf FIRST, at which
        update, plus the current per-step mask — the payload
        NonFiniteLossError and the nonfinite flight trigger attach."""
        if state is None:
            return None
        st = self._get({k: state[k] for k in
                        ("nf_first_mask", "nf_first_step", "nf_mask",
                         "nf_steps")})
        if int(st["nf_first_step"]) < 0:
            return None
        return {
            "first_update": int(st["nf_first_step"]),
            "first_groups": [self.group_names[i]
                             for i, v in enumerate(st["nf_first_mask"])
                             if v],
            "current_groups": [self.group_names[i]
                               for i, v in enumerate(st["nf_mask"]) if v],
            "nonfinite_steps": int(st["nf_steps"]),
        }

    def spill(self, state, step=None):
        """The cadence hook: read the carry once, publish the gauges,
        append to the window ring, and fire the loss-spike flight trigger
        when |z| crosses the threshold. Returns the summary (None when the
        carry is None)."""
        t0 = time.perf_counter()
        summary = self.summarize(state, step=step)
        if summary is None:
            return None
        _registry.gauge(
            "train.grad_norm",
            help="global gradient norm at the last dynamics spill"
        ).set(summary["grad_norm"])
        _registry.gauge(
            "train.loss_spike_z",
            help="loss z-score vs the in-program EWMA trackers"
        ).set(round(summary["loss_z"], 6))
        for name, g in summary["groups"].items():
            labels = {"group": name}
            _registry.gauge(
                "train.grad_norm", labels=labels,
                help="per-layer-group gradient norm at the last "
                     "dynamics spill"
            ).set(g["grad_norm"])
            _registry.gauge(
                "train.param_norm", labels=labels,
                help="per-layer-group parameter norm"
            ).set(g["param_norm"])
            _registry.gauge(
                "train.update_ratio", labels=labels,
                help="per-layer-group ||delta_w|| / ||w|| at the last spill"
            ).set(g["update_ratio"])
        with self._win_lock:
            self.window.append(summary)
        self.last = summary
        # one-sided: a SPIKE is the loss jumping UP. A healthy fast
        # convergence drifts z persistently negative (the EWMA lags the
        # drop) and must not page. The trigger reads the WINDOW MAX
        # latch, not the spill-step z — a one-step spike that decayed
        # before the cadence read still pages (reset_window() re-arms
        # the latch after each spill).
        z_trip = summary["loss_z_max"]
        if (self.spike_z > 0 and z_trip is not None
                and z_trip >= self.spike_z):
            _registry.counter(
                "train.loss_spikes",
                help="dynamics spills whose loss z-score crossed the "
                     "spike threshold").inc()
            from . import flightrec

            flightrec.record(
                "loss_spike", step=summary["step"],
                payload={"loss": summary["loss"],
                         "loss_ewma": summary["loss_ewma"],
                         "loss_z": summary["loss_z"],
                         "loss_z_max": z_trip,
                         "loss_z_max_at": summary["loss_z_max_at"],
                         "threshold": self.spike_z})
        _registry.histogram(
            "dynamics.spill_s",
            help="wall cost of one dynamics host spill (device read + "
                 "gauge publish)").observe(time.perf_counter() - t0)
        return summary

    def reset_window(self, state):
        """Re-arm the per-window latches after a spill (host side): a
        fresh max-z latch so each cadence window reports ITS OWN worst
        spike instead of the lifetime max shadowing later smaller ones.
        Returns the carry with replaced latch leaves (distinct fresh
        arrays — the carry is donated)."""
        if state is None:
            return None
        import jax.numpy as jnp

        st = dict(state)
        st["z_max"] = jnp.full((), -jnp.inf, jnp.float32)
        st["z_max_at"] = jnp.full((), -1, jnp.int32)
        return st

    def window_list(self):
        """Snapshot of the spill window, safe from any thread."""
        with self._win_lock:
            return list(self.window)

    def report(self):
        """The /dynamicsz payload for this monitor."""
        return {
            "enabled": True,
            "every": self.every,
            "ewma": self.ewma,
            "spike_z": self.spike_z,
            "groups": list(self.group_names),
            "group_sizes": [len(m) for m in self._group_members],
            "last": self.last,
            "window": self.window_list(),
        }
