"""Span tracing: ``with span("name"):`` through the seams that matter.

A span is a host-side timed region with parent/child nesting (per-thread
stack). Completed spans fan out to:

- a process-wide ring buffer (``last_spans``) — what the hang watchdog dumps
  when a rank stalls;
- the profiler's chrome-trace host-event buffer, when a Profiler is
  recording — spans appear in the same timeline RecordEvent always fed;
- any registered JSONL sinks (one json object per line, crash-safe: each
  record is flushed as written);
- a per-span-name duration histogram in the metrics registry
  (``span.<name>_s``) — the per-phase step breakdown falls out of the same
  data.

Request-scoped trace records (observability/request_trace.py) ride the
same ring and sinks via :func:`emit_record`, so one ``spans.<rank>.jsonl``
file carries both streams and scripts/trace_view.py can join them.

Cost contract (asserted in tests/test_telemetry.py like chaos.site's):
**disabled, an attr-less span is one module-global load + a None/False
check** returning a shared no-op context manager — no allocation, no clock
read. Spans called with ``**attrs`` pay the kwargs-dict build before the
check runs (Python semantics), so per-step/per-dispatch hot paths use
attr-less spans. Enable via ``enable()`` or ``PADDLE_TELEMETRY=1``.

Caveat: a span opened inside a jax trace (jit compile) measures TRACE time
once, not per-execution device time; device-side phase attribution rides
``jax.named_scope`` into xprof instead (see jit_api's fwd_bwd/optimizer
scopes and docs/OBSERVABILITY.md).
"""
import atexit
import collections
import json
import os
import sys
import threading
import time

from ..utils.envs import env_bool, env_str

__all__ = ["span", "enable", "disable", "enabled", "last_spans",
           "add_jsonl_sink", "clear_sinks", "JsonlSpanSink", "emit_record"]

_ENABLED = None           # tri-state: None = resolve from env on first use
_RING_DEFAULT = 512
_ring = collections.deque(maxlen=_RING_DEFAULT)
_sinks = []
_local = threading.local()
_tids = {}
_tids_lock = threading.Lock()


def _small_tid():
    """Small, stable per-thread id (chrome-trace tid / span record tid).
    Unlike ``get_ident() % 100000``, cannot collide: ids are assigned
    sequentially per distinct live thread identity."""
    ident = threading.get_ident()
    tid = _tids.get(ident)
    if tid is None:
        with _tids_lock:
            tid = _tids.setdefault(ident, len(_tids) + 1)
    return tid


def _resolve_enabled():
    global _ENABLED
    _ENABLED = env_bool("PADDLE_TELEMETRY")
    if _ENABLED:
        _autoconfigure_sinks()
    return _ENABLED


def enabled():
    """True when span tracing is on (env PADDLE_TELEMETRY or enable())."""
    e = _ENABLED
    return e if e is not None else _resolve_enabled()


def enable(jsonl_path=None, ring=None):
    """Turn span tracing on programmatically; optionally attach a JSONL sink
    and resize the ring buffer. Env-configured sinks (PADDLE_TELEMETRY_DIR)
    attach here too — a launcher-spawned worker that calls obs.enable()
    itself still streams spans where the hang watchdog looks."""
    global _ENABLED, _ring
    _ENABLED = True
    if ring is not None and ring != _ring.maxlen:
        _ring = collections.deque(_ring, maxlen=int(ring))
    if jsonl_path is not None and not any(
            getattr(s, "path", None) == jsonl_path for s in _sinks):
        add_jsonl_sink(jsonl_path)  # idempotent: re-enable ≠ duplicate sink
    _autoconfigure_sinks()


def disable():
    """Turn tracing off. The ring buffer and sinks are kept (post-mortem
    inspection of what was captured while enabled)."""
    global _ENABLED
    _ENABLED = False


_autosink_path = None


def _autoconfigure_sinks():
    """Env-armed processes (launcher-spawned trainers) stream spans to
    <PADDLE_TELEMETRY_DIR>/spans.<rank>.jsonl — the file the hang watchdog
    tails for its per-rank last-N-spans report. Idempotent: repeated
    enable() calls attach the sink once."""
    global _autosink_path
    d = env_str("PADDLE_TELEMETRY_DIR")
    if not d:
        return
    rank = env_str("PADDLE_TRAINER_ID", os.environ.get("RANK", "0"))
    path = os.path.join(d, f"spans.{rank}.jsonl")
    if path == _autosink_path and any(
            getattr(s, "path", None) == path for s in _sinks):
        return
    try:
        add_jsonl_sink(path)
        _autosink_path = path
    except OSError:
        pass


class JsonlSpanSink:
    """Crash-safe JSONL span sink: every record is written + flushed
    immediately, the file handle closes idempotently at exit (atexit) or via
    the context-manager protocol — a crash loses at most the record being
    formatted, never the flushed tail."""

    def __init__(self, path):
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        self.path = path
        self._f = open(path, "a")
        atexit.register(self.close)

    def __call__(self, record):
        f = self._f
        if f is None:
            return
        try:
            f.write(json.dumps(record) + "\n")
            f.flush()
        except ValueError:  # closed underneath us at interpreter teardown
            self._f = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def close(self):
        f, self._f = self._f, None
        if f is not None:
            try:
                f.close()
            except ValueError:
                pass
        try:
            atexit.unregister(self.close)
        except Exception:
            pass


def add_jsonl_sink(path):
    sink = JsonlSpanSink(path)
    _sinks.append(sink)
    return sink


def clear_sinks():
    while _sinks:
        s = _sinks.pop()
        close = getattr(s, "close", None)
        if close is not None:
            close()


def last_spans(n=64):
    """Most recent completed span records (oldest first) — the watchdog's
    'what was this rank doing' payload."""
    buf = list(_ring)
    return buf[-n:]


def clear():
    """Test hook: drop captured spans (sinks untouched)."""
    _ring.clear()


class _NullSpan:
    """Shared no-op context manager — the entire disabled cost of span()."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL = _NullSpan()


class _Span:
    __slots__ = ("name", "attrs", "_t0", "_parent")

    def __init__(self, name, attrs):
        self.name = name
        self.attrs = attrs

    def __enter__(self):
        stack = getattr(_local, "stack", None)
        if stack is None:
            stack = _local.stack = []
        self._parent = stack[-1].name if stack else None
        stack.append(self)
        self._t0 = time.perf_counter_ns()
        return self

    def __exit__(self, *exc):
        t1 = time.perf_counter_ns()
        stack = _local.stack
        if stack and stack[-1] is self:
            stack.pop()
        dur_us = (t1 - self._t0) / 1000.0
        rec = {
            "name": self.name,
            "ts_us": self._t0 / 1000.0,   # perf_counter epoch (chrome-trace)
            "dur_us": dur_us,
            "time": time.time(),          # wall clock (cross-rank alignment)
            "pid": os.getpid(),
            "tid": _small_tid(),
            "parent": self._parent,
            "depth": len(stack),
        }
        if self.attrs:
            rec["attrs"] = self.attrs
        _emit(rec, dur_us)
        return False


def _emit(rec, dur_us):
    _ring.append(rec)
    # same timeline as RecordEvent: host spans land in the chrome trace when
    # a Profiler is recording. sys.modules probe: never trigger a jax import
    # from the telemetry layer.
    prof = sys.modules.get("paddle_tpu.profiler")
    if prof is not None:
        try:
            prof._record_host_event(rec["name"], rec["ts_us"], rec["dur_us"])
        except Exception:
            pass
    from .metrics import registry

    try:
        registry.histogram(f"span.{rec['name']}_s").observe(dur_us / 1e6)
    except ValueError:
        pass  # name collision with a non-histogram metric: skip, don't kill
    for sink in _sinks:
        try:
            sink(rec)
        except Exception:
            pass


def emit_record(rec, profiler_name=None, profiler_ts_us=None,
                profiler_dur_us=None):
    """Route an externally-built record through the same fan-out completed
    spans get — the watchdog's ring buffer, every JSONL sink, and (when the
    optional profiler args are given and a Profiler is recording) the
    chrome-trace host-event buffer. This is how request-scoped trace
    records (observability/request_trace.py) land in the SAME
    ``spans.<rank>.jsonl`` files as thread spans, so scripts/trace_view.py
    and the hang watchdog see one stream. The span-duration histograms are
    NOT fed — those are keyed by the thread-span taxonomy."""
    _ring.append(rec)
    if profiler_name is not None:
        prof = sys.modules.get("paddle_tpu.profiler")
        if prof is not None:
            try:
                prof._record_host_event(profiler_name, profiler_ts_us,
                                        profiler_dur_us)
            except Exception:
                pass
    for sink in _sinks:
        try:
            sink(rec)
        except Exception:
            pass


def span(name, **attrs):
    """``with span("train.step.dispatch", step=i):`` — free when disabled."""
    e = _ENABLED
    if not (e if e is not None else _resolve_enabled()):
        return _NULL
    return _Span(name, attrs)
