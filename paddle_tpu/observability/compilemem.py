"""Compile & HBM observability (ISSUE 8): the XLA compile ledger, the
recompile-churn detector, the HBM memory ledger, and OOM forensics.

Two failure classes cost real sessions (PROFILE.md): **compile churn**
(cold paged-serve programs were a 7.3x throughput cliff until warmup();
one big compile killed two rounds) and **HBM fit** (a silent bf16->f32
Adam upcast ate ~3 GB). This module measures both instead of
rediscovering them post-mortem:

- :func:`ledgered_jit` — the blessed ``jax.jit`` wrapper every compile
  site in ``paddle_tpu/`` goes through (lint-enforced by scripts/ci.sh,
  so the ledger is complete by construction, not best-effort). It detects
  (re)traces exactly — the traced Python body only runs on a jit cache
  miss — and records one :class:`CompileLedger` event per compile:
  program key, abstract input signature, wall time, and trigger
  (cold / warmup / recompile).
- :class:`CompileLedger` — the event log + the churn detector: a program
  KEY names the logical program the caller intends to be stable
  (``train.step``; serving keys embed their bucket/sampling, so bucketed
  variants are distinct programs, not churn). The same key recompiling
  under shape/dtype drift past ``churn_threshold`` distinct signatures
  raises ``compile.churn_alerts``. Program-cache sizes
  (``TrainStep._compiled_multi``, the engine's per-program dicts) are
  exported as ``compile.cache_size{cache=...}`` gauges with a warn bound.
- :class:`MemoryLedger` — harvests ``compiled.memory_analysis()``
  (arg/output/temp/code bytes) per program, **lazily**: the abstract
  signature captured at compile time lets :meth:`MemoryLedger.analyze`
  re-lower with ShapeDtypeStructs on demand (statusz /memz, OOM
  forensics, tests) instead of doubling every compile. It also keeps the
  HBM budget ledger: component byte providers (params, optimizer state,
  KV page pool) registered by the train step and the serving engine,
  summed against the device capacity into ``device.hbm_*`` gauges.
- OOM forensics — :func:`maybe_oom_report` intercepts XLA
  ``RESOURCE_EXHAUSTED`` (and the ``obs.oom`` chaos site's synthetic
  injection) at the dispatch seams and writes
  ``telemetry/oom_report.json`` — ledger snapshot, top-N programs by
  temp bytes, registered contexts (active serving slots/pages), last-N
  compile events — before the exception re-raises.

Like the rest of the package this module imports **no jax at module
scope** (the launcher and forked workers import observability); jax is
imported lazily inside the functions that need it. Compile accounting is
always-on (the metrics cost model: compiles are seconds, a ledger append
is microseconds); the per-dispatch overhead of a warm ledgered call is a
thread-local check + two clock reads, inside the PR-2 <1%-of-step bound.
"""
import functools
import itertools
import json
import os
import sys
import threading
import time
import warnings
import weakref
from collections import OrderedDict, deque
from contextlib import contextmanager, nullcontext

from ..utils.envs import env_bool, env_int, env_str
from .metrics import registry as _registry

__all__ = [
    "CompileLedger", "MemoryLedger", "ledger", "memory", "ledgered_jit",
    "record_compile", "analyze_function", "tree_nbytes", "is_oom",
    "maybe_oom_report", "write_oom_report", "register_oom_context",
    "oom_report_path", "OOM_REPORT_NAME",
]

OOM_REPORT_NAME = "oom_report.json"

# ---- compile.* metrics (always-on, the EventCounters cost model) ----------
_M_EVENTS = _registry.counter(
    "compile.events", help="XLA compiles recorded by the compile ledger")
_M_RECOMPILES = _registry.counter(
    "compile.recompiles",
    help="compiles of a program key that had already compiled before")
_M_CHURN = _registry.counter(
    "compile.churn_alerts",
    help="same logical program recompiled under shape/dtype drift past "
         "the churn threshold")
_M_WALL = _registry.histogram(
    "compile.wall_s",
    buckets=(0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
             30.0, 60.0, 120.0, 300.0),
    help="per-compile wall time (trace + XLA build + first execution)")
_M_ACTIVE = _registry.gauge(
    "compile.active", help="compiles currently in flight")
_M_CACHE_WARN = _registry.counter(
    "compile.cache_warnings",
    help="program-cache size warnings past the configured bound")
_M_OOM = _registry.counter(
    "device.oom_reports", help="OOM forensics reports written")


def _rank():
    return env_str("PADDLE_TRAINER_ID",
                   os.environ.get("RANK", "0")) or "0"


def compiling_path(directory, rank):
    """The watchdog-visible mid-compile breadcrumb for ``rank``."""
    return os.path.join(directory, f"compiling.{rank}.json")


class CompileLedger:
    """Process-wide compile event log + recompile-churn detector.

    ``begin(key)`` / ``end(token, ...)`` bracket one compile: begin fires
    at trace start (the traced shim runs only on a jit cache miss), end
    after the dispatch returns — the window covers the XLA build, so a
    rank wedged mid-compile is visible in ``active()`` and in the
    ``compiling.<rank>.json`` breadcrumb the hang watchdog reads. Nested
    begins on one thread (an inner jitted fn traced inside an outer
    trace) are suppressed: the inner body is part of the outer program.
    """

    def __init__(self, max_events=512, churn_threshold=None,
                 cache_warn_bound=None):
        self._lock = threading.Lock()
        self._events = deque(maxlen=int(max_events))
        self._by_key = {}
        self._caches = {}
        self._cache_warned = set()
        self._active = {}
        self._counter = itertools.count(1)
        self._local = threading.local()
        self.churn_threshold = (int(churn_threshold)
                                if churn_threshold is not None
                                else env_int("PADDLE_COMPILE_CHURN_THRESHOLD", 3))
        self.cache_warn_bound = (int(cache_warn_bound)
                                 if cache_warn_bound is not None
                                 else env_int("PADDLE_COMPILE_CACHE_WARN", 64))

    # ---- trigger / suppression scopes ------------------------------------
    @contextmanager
    def trigger(self, label):
        """Label every compile recorded inside the scope (``warmup``)."""
        prev = getattr(self._local, "trigger", None)
        self._local.trigger = label
        try:
            yield
        finally:
            self._local.trigger = prev

    @contextmanager
    def suppressed(self):
        """Don't record compiles inside the scope — the memory ledger's
        re-lowering for analysis must not show up as real recompiles."""
        prev = getattr(self._local, "suppress", False)
        self._local.suppress = True
        try:
            yield
        finally:
            self._local.suppress = prev

    # ---- the begin/end protocol ------------------------------------------
    def begin(self, key):
        """Mark a compile of ``key`` started. Returns a token for end(),
        or None when this trace is nested (or suppressed) — end(None) is
        a no-op, so callers never need to branch."""
        depth = getattr(self._local, "depth", 0)
        self._local.depth = depth + 1
        if depth or getattr(self._local, "suppress", False):
            return None
        tok = next(self._counter)
        with self._lock:
            self._active[tok] = {"key": str(key), "started_at": time.time(),
                                 "tid": threading.get_ident()}
            _M_ACTIVE.set(len(self._active))
        self._write_compiling()
        return tok

    def exit_trace(self):
        """Trace-shim epilogue: the Python trace ended (the XLA build may
        still be running — the active entry stays until end())."""
        self._local.depth = max(0, getattr(self._local, "depth", 1) - 1)

    def end(self, token, key, wall_s=0.0, signature=None, trigger=None,
            error=None):
        """Close the compile ``begin()`` opened; records one event. A
        ``None`` token (nested/suppressed begin) is a no-op."""
        if token is None:
            return None
        with self._lock:
            self._active.pop(token, None)
            _M_ACTIVE.set(len(self._active))
        self._write_compiling()
        return _ledger_record(self, key, wall_s, signature, trigger, error)

    # ---- cache-size accounting -------------------------------------------
    def note_cache_size(self, name, size):
        """Export a program cache's size (gauge ``compile.cache_size``,
        labeled per cache) and warn once past the configured bound — the
        ``TrainStep._compiled_multi`` unbounded-growth satellite."""
        size = int(size)
        with self._lock:
            self._caches[str(name)] = size
        _registry.gauge("compile.cache_size",
                        help="compiled-program cache sizes, per cache",
                        labels={"cache": str(name)}).set(size)
        if size > self.cache_warn_bound and name not in self._cache_warned:
            with self._lock:
                if name in self._cache_warned:
                    return
                self._cache_warned.add(name)
            _M_CACHE_WARN.inc()
            warnings.warn(
                f"program cache {name!r} holds {size} compiled programs "
                f"(bound {self.cache_warn_bound}; PADDLE_COMPILE_CACHE_WARN"
                f") — unbounded growth usually means an unstable program "
                f"key (shape/dtype drift)", RuntimeWarning, stacklevel=3)

    # ---- introspection ----------------------------------------------------
    def active(self):
        """[{key, started_at, elapsed_s, tid}] — compiles in flight."""
        now = time.time()
        with self._lock:
            return [dict(v, elapsed_s=round(now - v["started_at"], 3))
                    for v in self._active.values()]

    def counts(self):
        """Cheap scalar snapshot (bench deltas): events / wall / churn."""
        with self._lock:
            return {
                "events": sum(e["count"] for e in self._by_key.values()),
                "total_wall_s": round(sum(e["wall_s"]
                                          for e in self._by_key.values()), 4),
                "recompiles": int(_M_RECOMPILES.value),
                "churn_alerts": int(_M_CHURN.value),
            }

    def events(self, n=32):
        """The last ``n`` compile events, oldest first."""
        with self._lock:
            buf = list(self._events)
        return buf[-int(n):]

    def report(self, recent=32):
        """The /compilez payload: per-key rollup, churned keys, recent
        events, in-flight compiles, cache sizes."""
        with self._lock:
            by_key = {
                k: {"count": e["count"], "wall_s": round(e["wall_s"], 4),
                    "signatures": len(e["signatures"]),
                    "triggers": dict(e["triggers"]),
                    "churn_alerts": e["churn_alerts"],
                    "last_signature": e["last_signature"]}
                for k, e in sorted(self._by_key.items())
            }
            caches = dict(self._caches)
        churned = {k: v for k, v in by_key.items() if v["churn_alerts"]}
        counts = self.counts()
        return {
            "events": counts["events"],
            "total_wall_s": counts["total_wall_s"],
            "recompiles": counts["recompiles"],
            "churn_alerts": counts["churn_alerts"],
            "by_key": by_key,
            "churned": churned,
            "recent": self.events(recent),
            "active": self.active(),
            "caches": caches,
            "churn_threshold": self.churn_threshold,
        }

    def reset(self):
        """Test hook: forget events/keys/caches (metric objects keep their
        values — reset those via registry.reset("compile."))."""
        with self._lock:
            self._events.clear()
            self._by_key.clear()
            self._caches.clear()
            self._cache_warned.clear()
            self._active.clear()

    # ---- watchdog breadcrumb ---------------------------------------------
    def _write_compiling(self):
        """Atomic ``compiling.<rank>.json`` under PADDLE_TELEMETRY_DIR so
        the launcher-side hang watchdog can say 'rank 3 is 214 s into
        compiling train.step', cross-process. Removed when nothing is in
        flight. Never raises (a full disk must not kill a compile)."""
        d = env_str("PADDLE_TELEMETRY_DIR")
        if not d:
            return
        path = compiling_path(d, _rank())
        try:
            active = self.active()
            if not active:
                try:
                    os.unlink(path)
                except FileNotFoundError:
                    pass
                return
            os.makedirs(d, exist_ok=True)
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump({"rank": _rank(), "pid": os.getpid(),
                           "active": active}, f)
            os.replace(tmp, path)
        except OSError:
            pass


def _ledger_record(led, key, wall_s, signature, trigger, error):
    """The shared event-append + churn/trigger classification (module
    function so both ledgered_jit and record_compile use one copy)."""
    key = str(key)
    sig = "?" if signature is None else str(signature)
    err = None if error is None else f"{type(error).__name__}: {error}"
    with led._lock:
        entry = led._by_key.get(key)
        first = entry is None
        if first:
            entry = led._by_key[key] = {
                "count": 0, "wall_s": 0.0, "signatures": OrderedDict(),
                "triggers": {}, "churn_alerts": 0, "last_signature": None,
                "warned": False,
            }
        resolved = (getattr(led._local, "trigger", None)
                    or trigger
                    or ("cold" if first else "recompile"))
        entry["count"] += 1
        entry["wall_s"] += float(wall_s)
        entry["triggers"][resolved] = entry["triggers"].get(resolved, 0) + 1
        new_sig = sig not in entry["signatures"]
        entry["signatures"][sig] = entry["signatures"].get(sig, 0) + 1
        while len(entry["signatures"]) > 64:  # bound per-key memory
            entry["signatures"].popitem(last=False)
        entry["last_signature"] = sig
        churned = (new_sig and err is None
                   and len(entry["signatures"]) > led.churn_threshold)
        if churned:
            entry["churn_alerts"] += 1
        rec = {"key": key, "signature": sig, "wall_s": round(float(wall_s), 4),
               "trigger": resolved, "time": time.time()}
        if err:
            rec["error"] = err
        led._events.append(rec)
    _M_EVENTS.inc()
    _M_WALL.observe(wall_s)
    if not first and err is None:
        _M_RECOMPILES.inc()
    if churned:
        _M_CHURN.inc()
        if not entry["warned"]:
            entry["warned"] = True
            warnings.warn(
                f"compile churn: program {key!r} has compiled "
                f"{entry['count']} times under {len(entry['signatures'])} "
                f"distinct input signatures (threshold "
                f"{led.churn_threshold}) — shape/dtype drift is defeating "
                f"the jit cache; bucket the inputs or split the key",
                RuntimeWarning, stacklevel=4)
    return rec


#: the process-wide singleton every compile site records into
ledger = CompileLedger()


def _signature_of(args, kwargs):
    """Stable abstract-signature string for the churn detector: dtype[shape]
    per array leaf, a short repr for static leaves; hashed tail past 512
    chars so huge pytrees stay bounded. Computed only on a compile."""
    import jax

    leaves, _ = jax.tree_util.tree_flatten((args, kwargs))
    parts = []
    for l in leaves:
        shape = getattr(l, "shape", None)
        dtype = getattr(l, "dtype", None)
        if shape is not None and dtype is not None:
            parts.append(f"{dtype}[{','.join(str(s) for s in shape)}]")
        else:
            parts.append(repr(l)[:24])
    sig = ";".join(parts)
    if len(sig) > 512:
        import hashlib

        h = hashlib.blake2b(sig.encode(), digest_size=8).hexdigest()
        sig = f"{sig[:480]}...#{h}"
    return sig


def _abstractify(args, kwargs):
    """(args, kwargs) with every array leaf replaced by a ShapeDtypeStruct —
    the handle MemoryLedger.analyze re-lowers from without holding any
    real buffers alive."""
    import jax

    def to_sds(l):
        shape = getattr(l, "shape", None)
        dtype = getattr(l, "dtype", None)
        if shape is not None and dtype is not None:
            try:
                return jax.ShapeDtypeStruct(tuple(shape), dtype)
            except TypeError:
                return l
        return l

    return jax.tree_util.tree_map(to_sds, (args, kwargs))


def ledgered_jit(fn, key=None, static_argnums=None, track_memory=True,
                 **jit_kwargs):
    """``jax.jit`` with the compile ledger wired in — the blessed wrapper
    scripts/ci.sh lints every ``paddle_tpu/`` compile site onto.

    Trace detection is exact and free: the traced shim's body only runs
    on a jit cache miss, so a warm call costs one thread-local store and
    two clock reads on top of the jitted dispatch. On a compile the
    ledger records (key, abstract signature, wall, trigger) and — when
    ``track_memory=True`` — the MemoryLedger keeps the ShapeDtypeStruct
    signature so ``compiled.memory_analysis()`` can be harvested lazily.
    Exceptions out of the dispatch pass through :func:`maybe_oom_report`,
    which makes every ledgered call site an OOM-forensics seam.
    """
    import jax

    if key is None:
        key = getattr(fn, "__qualname__", None) or getattr(
            fn, "__name__", "anonymous")
    led = ledger
    local = threading.local()

    @functools.wraps(fn)
    def _traced(*args, **kwargs):
        local.token = led.begin(key)
        local.traced = True
        try:
            return fn(*args, **kwargs)
        finally:
            led.exit_trace()

    if static_argnums is not None:
        jit_kwargs["static_argnums"] = static_argnums
    jitted = jax.jit(_traced, **jit_kwargs)  # compile-ledger-ok (the wrapper)

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        local.traced = False
        t0 = time.perf_counter()
        try:
            out = jitted(*args, **kwargs)
        except BaseException as e:
            # BaseException, not Exception: a KeyboardInterrupt / chaos
            # SystemExit escaping mid-compile must still release the
            # active-compile token and the compiling.<rank>.json
            # breadcrumb, or every later hang report claims this rank is
            # forever 'wedged compiling <key>'
            if getattr(local, "traced", False):
                led.end(getattr(local, "token", None), key,
                        wall_s=time.perf_counter() - t0,
                        signature=_safe_signature(args, kwargs), error=e)
            if isinstance(e, Exception):
                maybe_oom_report(e, program=key)
            raise
        if getattr(local, "traced", False):
            sig = _safe_signature(args, kwargs)
            led.end(getattr(local, "token", None), key,
                    wall_s=time.perf_counter() - t0, signature=sig)
            if track_memory:
                memory.note_program(key, jitted, args, kwargs,
                                    signature=sig)
        return out

    def lower(*args, **kwargs):
        with led.suppressed():
            return jitted.lower(*args, **kwargs)

    wrapper._jitted = jitted
    wrapper._ledger_key = key
    wrapper.lower = lower
    return wrapper


def _safe_signature(args, kwargs):
    try:
        return _signature_of(args, kwargs)
    except Exception:
        return "?"


@contextmanager
def record_compile(key, trigger=None, signature=None):
    """Explicit compile bracket for AOT sites (``jax.export`` /
    ``.lower(...).compile()``) where :func:`ledgered_jit` can't wrap the
    callable. Times the body, records one ledger event, and routes
    exceptions through OOM forensics before re-raising."""
    tok = ledger.begin(key)
    t0 = time.perf_counter()
    try:
        yield
    except BaseException as e:  # incl. interrupts: never leak the token
        ledger.exit_trace()
        ledger.end(tok, key, wall_s=time.perf_counter() - t0,
                   signature=signature, trigger=trigger, error=e)
        if isinstance(e, Exception):
            maybe_oom_report(e, program=key)
        raise
    ledger.exit_trace()
    ledger.end(tok, key, wall_s=time.perf_counter() - t0,
               signature=signature, trigger=trigger)


# ---------------------------------------------------------------------------
# memory ledger
# ---------------------------------------------------------------------------
def tree_nbytes(tree):
    """Total bytes across a pytree's array leaves, from shape/dtype only —
    no host sync, no device touch."""
    import jax

    total = 0
    for l in jax.tree_util.tree_leaves(tree):
        shape = getattr(l, "shape", None)
        dtype = getattr(l, "dtype", None)
        if shape is None or dtype is None:
            continue
        n = 1
        for s in shape:
            n *= int(s)
        try:
            import numpy as np

            total += n * np.dtype(dtype).itemsize
        except TypeError:
            total += n * getattr(dtype, "itemsize", 4)
    return int(total)


def _compile_lock():
    """The serving engines' process-wide compile lock, when the module is
    loaded — re-lowering model programs walks the framework's
    thread-oblivious Tensor state, exactly what that lock exists for."""
    m = sys.modules.get("paddle_tpu.inference.continuous")
    return m._COMPILE_LOCK if m is not None else nullcontext()


def _analysis_dict(ma):
    out = {}
    for name, short in (("argument_size_in_bytes", "argument_bytes"),
                        ("output_size_in_bytes", "output_bytes"),
                        ("temp_size_in_bytes", "temp_bytes"),
                        ("generated_code_size_in_bytes", "code_bytes"),
                        ("alias_size_in_bytes", "alias_bytes")):
        v = getattr(ma, name, None)
        if v is not None:
            out[short] = int(v)
    out["peak_bytes"] = (out.get("argument_bytes", 0)
                         + out.get("output_bytes", 0)
                         + out.get("temp_bytes", 0)
                         - out.get("alias_bytes", 0))
    return out


def _cost_dict(compiled):
    """``compiled.cost_analysis()`` distilled to the devprof join keys
    ({flops, bytes, transcendentals}, floats). Defensive on purpose: the
    API has returned a dict, a list of dicts, and nothing at all across
    jax versions/backends (CPU often omits byte counts) — a missing cost
    row must degrade the roofline, never break the memory harvest."""
    try:
        ca = compiled.cost_analysis()
    except Exception:
        return None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if not isinstance(ca, dict):
        return None
    out = {}
    for src, short in (("flops", "flops"), ("bytes accessed", "bytes"),
                       ("transcendentals", "transcendentals")):
        try:
            v = float(ca.get(src, 0) or 0)
        except (TypeError, ValueError):
            continue
        if v > 0:
            out[short] = v
    return out or None


class MemoryLedger:
    """HBM budget ledger + lazy per-program ``memory_analysis()`` harvest.

    Components (params, optimizer state, KV page pool, ...) are
    registered as weakly-bound byte providers so N live engines sum and a
    dead one drops out. ``analyze()`` re-lowers captured programs from
    their ShapeDtypeStruct signatures — one extra (suppressed, off-device)
    compile per program, paid only when someone asks (statusz /memz with
    analyze, the OOM report, tests) rather than on every real compile.
    """

    def __init__(self, max_programs=160):
        self._lock = threading.Lock()
        self._programs = OrderedDict()
        self._max_programs = int(max_programs)
        self._providers = {}
        self._static = {}

    # ---- HBM budget components -------------------------------------------
    def set_component(self, name, nbytes):
        """A fixed component byte count (rare; prefer providers)."""
        with self._lock:
            self._static[str(name)] = int(nbytes)

    def register_component_provider(self, name, obj, method_name):
        """Register ``obj.method_name() -> bytes`` weakly under component
        ``name``; multiple live objects per name sum, dead ones vanish."""
        ref = weakref.ref(obj)
        with self._lock:
            self._providers.setdefault(str(name), []).append(
                (ref, str(method_name)))

    def components(self):
        """{component: bytes} — static entries + live provider sums."""
        with self._lock:
            static = dict(self._static)
            providers = {k: list(v) for k, v in self._providers.items()}
            # prune dead refs IN PLACE under the lock (a write-back of the
            # snapshot would clobber providers registered concurrently —
            # e.g. an engine constructed while a scrape thread reports)
            for refs in self._providers.values():
                refs[:] = [(r, m) for r, m in refs if r() is not None]
        out = dict(static)
        for name, refs in providers.items():
            total, live = 0, False
            for ref, meth in refs:
                obj = ref()
                if obj is None:
                    continue
                live = True
                try:
                    total += int(getattr(obj, meth)())
                except Exception:
                    continue
            if live or name not in out:
                out[name] = out.get(name, 0) + total
        return out

    def capacity_bytes(self):
        """Device memory capacity: ``PADDLE_HBM_CAPACITY_BYTES`` env
        override first (CPU hosts have no HBM), else the backend's
        ``memory_stats()['bytes_limit']`` when it exposes one."""
        env = env_str("PADDLE_HBM_CAPACITY_BYTES")
        if env:
            try:
                return int(float(env))
            except ValueError:
                pass
        try:
            import jax

            stats = jax.devices()[0].memory_stats()
            if stats:
                return int(stats.get("bytes_limit", 0)) or None
        except Exception:
            pass
        return None

    # ---- program capture + lazy analysis ---------------------------------
    def note_program(self, key, jitted, args, kwargs, signature=None):
        """Capture (jitted, abstract signature) at compile time so the
        analysis can run later without the real buffers. Bounded LRU."""
        try:
            abstract = _abstractify(args, kwargs)
        except Exception:
            return
        try:
            ref = weakref.ref(jitted)
        except TypeError:
            ref = lambda j=jitted: j  # noqa: E731 — unweakrefable: pin it
        with self._lock:
            self._programs[str(key)] = {
                "jitted": ref, "abstract": abstract, "signature": signature,
                "analysis": None, "cost": None, "error": None,
            }
            self._programs.move_to_end(str(key))
            while len(self._programs) > self._max_programs:
                self._programs.popitem(last=False)

    def analyze(self, keys=None, force=False):
        """Harvest ``memory_analysis()`` for captured programs (all, or
        the given keys). Each un-analyzed program pays one suppressed
        re-lower+compile under the serving compile lock; results are
        cached. Returns {key: analysis-or-error}."""
        with self._lock:
            todo = [(k, v) for k, v in self._programs.items()
                    if (keys is None or k in keys)
                    and (force or (v["analysis"] is None
                                   and v["error"] is None))]
        out = {}
        for k, v in todo:
            jitted = v["jitted"]()
            if jitted is None:
                err = "program garbage-collected"
                with self._lock:
                    v["error"] = err
                out[k] = {"error": err}
                continue
            a, kw = v["abstract"]
            try:
                with _compile_lock(), ledger.suppressed():
                    compiled = jitted.lower(*a, **kw).compile()  # compile-ledger-ok (the ledger's own suppressed analysis)
                    analysis = _analysis_dict(compiled.memory_analysis())
                    cost = _cost_dict(compiled)
                with self._lock:
                    v["analysis"] = analysis
                    v["cost"] = cost
                    v["error"] = None
                out[k] = analysis
            except Exception as e:
                err = f"{type(e).__name__}: {str(e)[:200]}"
                with self._lock:
                    v["error"] = err
                out[k] = {"error": err}
        self.refresh_gauges()
        return out

    def programs(self):
        """{key: {signature, analysis|None, cost|None, error|None}} — no
        analysis is forced; un-analyzed programs show ``analysis: None``."""
        with self._lock:
            return {k: {"signature": v["signature"],
                        "analysis": v["analysis"],
                        "cost": v.get("cost"), "error": v["error"]}
                    for k, v in self._programs.items()}

    def program_cost(self, key):
        """The devprof join hook: the cached cost_analysis row for one
        program (flops + bytes), with byte counts backfilled from the
        memory analysis when cost_analysis omitted them (CPU backends
        report flops but not traffic). None until analyzed."""
        with self._lock:
            v = self._programs.get(str(key))
            if v is None:
                return None
            cost = dict(v.get("cost") or {})
            analysis = v["analysis"]
        if "bytes" not in cost and analysis and "error" not in analysis:
            nbytes = (analysis.get("argument_bytes", 0)
                      + analysis.get("output_bytes", 0))
            if nbytes > 0:
                cost["bytes"] = float(nbytes)
        return cost or None

    def top_programs_by_temp(self, n=5):
        """The analyzed programs ranked by temp bytes — the OOM report's
        'who ate the HBM' list."""
        progs = self.programs()
        ranked = sorted(
            ((k, v["analysis"]) for k, v in progs.items() if v["analysis"]),
            key=lambda kv: kv[1].get("temp_bytes", 0), reverse=True)
        return [{"key": k, **a} for k, a in ranked[:int(n)]]

    def temp_peak_bytes(self):
        progs = self.programs()
        return max((v["analysis"].get("temp_bytes", 0)
                    for v in progs.values() if v["analysis"]), default=0)

    # ---- the budget report ------------------------------------------------
    def refresh_gauges(self):
        """Publish the ``device.hbm_*`` gauges from the current ledger."""
        comps = self.components()
        used = sum(comps.values())
        cap = self.capacity_bytes()
        temp = self.temp_peak_bytes()
        for name, v in comps.items():
            _registry.gauge("device.hbm_component_bytes",
                            help="HBM budget components (params, optimizer "
                                 "state, KV page pool, ...)",
                            labels={"component": name}).set(v)
        _registry.gauge("device.hbm_used_bytes",
                        help="sum of registered HBM components").set(used)
        _registry.gauge(
            "device.hbm_temp_peak_bytes",
            help="largest analyzed per-program temp footprint").set(temp)
        if cap:
            _registry.gauge("device.hbm_capacity_bytes",
                            help="device memory capacity").set(cap)
            _registry.gauge(
                "device.hbm_headroom_bytes",
                help="capacity - components - temp high-water").set(
                max(0, cap - used - temp))
        return {"components": comps, "used_bytes": used,
                "capacity_bytes": cap, "temp_peak_bytes": temp}

    def report(self, analyze=False):
        """The /memz payload. ``analyze=True`` forces the lazy harvest
        first (an extra off-device compile per un-analyzed program)."""
        if analyze:
            self.analyze()
        budget = self.refresh_gauges()
        cap = budget["capacity_bytes"]
        used = budget["used_bytes"] + budget["temp_peak_bytes"]
        return {
            **budget,
            "headroom_bytes": (max(0, cap - used) if cap else None),
            "budget_fraction": (round(used / cap, 6) if cap else None),
            "programs": self.programs(),
            "top_programs_by_temp": self.top_programs_by_temp(),
        }

    def reset(self):
        with self._lock:
            self._programs.clear()
            self._providers.clear()
            self._static.clear()


memory = MemoryLedger()


def analyze_function(fn, *args, static_argnums=None, key=None):
    """One-off memory probe (the test_compiled_memory API, folded into the
    ledger): lower+compile ``fn`` for ``args`` and return the
    memory-analysis byte dict. Recorded in the compile ledger under
    ``probe.<name>`` with trigger ``probe`` and captured in the memory
    ledger like any other program."""
    import jax

    key = key or f"probe.{getattr(fn, '__name__', 'fn')}"
    kw = {}
    if static_argnums is not None:
        kw["static_argnums"] = static_argnums
    jitted = jax.jit(fn, **kw)  # compile-ledger-ok (recorded right below)
    with record_compile(key, trigger="probe",
                        signature=_safe_signature(args, {})):
        compiled = jitted.lower(*args).compile()  # compile-ledger-ok
    analysis = _analysis_dict(compiled.memory_analysis())
    memory.note_program(key, jitted, args, {},
                        signature=_safe_signature(args, {}))
    with memory._lock:
        if key in memory._programs:
            memory._programs[key]["analysis"] = analysis
    return analysis


# ---------------------------------------------------------------------------
# OOM forensics
# ---------------------------------------------------------------------------
_OOM_MARKERS = ("RESOURCE_EXHAUSTED", "Out of memory", "out of memory",
                "Allocation failure", "OOM")
_oom_contexts = []
_oom_lock = threading.Lock()
# (id of last reported exc, report path, monotonic stamp): the double-seam
# dedup. The two seams (ledgered wrapper + engine/train-step handler) fire
# within ONE raise propagation, so the id match is time-bounded — a later
# distinct OOM whose exception object happens to reuse the freed address
# still gets its own report. (A weakref would be cleaner, but built-in
# exception types don't support weak references.)
_last_oom = [None, None, 0.0]
_OOM_DEDUP_WINDOW_S = 5.0


def is_oom(exc):
    """Is this exception an XLA device-memory exhaustion? Matches the
    RESOURCE_EXHAUSTED family by message/type name, plus the ``obs.oom``
    chaos site's synthetic injection (the deterministic test hook)."""
    if exc is None:
        return False
    try:
        from ..testing.chaos import FaultInjected

        if isinstance(exc, FaultInjected) and exc.site == "obs.oom":
            return True
    except Exception:
        pass
    text = f"{type(exc).__name__}: {exc}"
    return any(m in text for m in _OOM_MARKERS)


def register_oom_context(name, obj, method_name):
    """Register ``obj.method_name() -> dict`` (weakly bound) to be
    snapshotted into the OOM report — the serving engine registers its
    active slots / page-pool occupancy here."""
    with _oom_lock:
        _oom_contexts.append((str(name), weakref.ref(obj),
                              str(method_name)))


def _collect_oom_contexts():
    out = {}
    with _oom_lock:
        items = list(_oom_contexts)
    live = []
    for name, ref, meth in items:
        obj = ref()
        if obj is None:
            continue
        live.append((name, ref, meth))
        try:
            out.setdefault(name, []).append(getattr(obj, meth)())
        except Exception as e:
            out.setdefault(name, []).append(
                {"error": f"{type(e).__name__}: {e}"})
    with _oom_lock:
        _oom_contexts[:] = live
    return out


def oom_report_path():
    d = env_str("PADDLE_TELEMETRY_DIR") or "telemetry"
    return os.path.join(d, OOM_REPORT_NAME)


def write_oom_report(exc, program=None, path=None, analyze=None):
    """Commit ``telemetry/oom_report.json``: the error, the compile
    ledger snapshot (incl. the last-N compile events), the HBM budget
    ledger with top-N programs by temp bytes, and every registered
    context (active serving slots/pages). Atomic tmp+rename; never
    raises — forensics must not mask the original exception."""
    try:
        if analyze is None:
            analyze = env_bool("PADDLE_OOM_ANALYZE", True)
        if analyze:
            try:
                memory.analyze()
            except Exception:
                pass
        report = {
            "time": time.time(),
            "pid": os.getpid(),
            "rank": _rank(),
            "error": f"{type(exc).__name__}: {exc}",
            "program": program,
            "compile": ledger.report(recent=32),
            "memory": memory.report(),
            "top_programs_by_temp": memory.top_programs_by_temp(10),
            "contexts": _collect_oom_contexts(),
        }
        path = path or oom_report_path()
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(report, f, indent=1, default=str)
        os.replace(tmp, path)
        _M_OOM.inc()
        return path
    except Exception:
        return None


def maybe_oom_report(exc, program=None):
    """The dispatch-seam hook: no-op for non-OOM exceptions (one string
    scan, only on the error path); for RESOURCE_EXHAUSTED writes the
    forensics report once per exception object (the engine seam and the
    ledgered-jit seam both fire for one failure)."""
    if not is_oom(exc):
        return None
    if (_last_oom[0] == id(exc)
            and time.monotonic() - _last_oom[2] < _OOM_DEDUP_WINDOW_S):
        return _last_oom[1]
    path = write_oom_report(exc, program=program)
    _last_oom[0] = id(exc)
    _last_oom[1] = path
    _last_oom[2] = time.monotonic()
    return path


def _reset_for_tests():
    """Forget ledger/memory/OOM state (metrics reset separately)."""
    ledger.reset()
    memory.reset()
    with _oom_lock:
        _oom_contexts.clear()
    _last_oom[0] = _last_oom[1] = None
    _last_oom[2] = 0.0
