"""Request-scoped distributed tracing for the serving stack (ISSUE 7).

PR-2 spans answer "what is this THREAD doing"; they cannot answer "where
did request X spend its 900 ms" because one request's lifecycle crosses
threads (submit thread -> dispatcher -> another dispatcher after a
replica-kill reroute) and, under the multi-replica frontend, processes.
This module adds the missing join key: :func:`start` mints a **trace
context** (a process-unique ``trace_id`` plus a root span) at
``ServingFrontend.submit()``, and every layer the request flows through
(scheduler queueing, router placement, engine admit / prefill chunks /
decode blocks / emit, reroutes across replica deaths) attaches child spans
to it — explicitly, by handle, not via the thread-local stack — so the
whole lifecycle reconstructs as ONE rooted tree.

Records are JSON-per-line, emitted through the SAME sinks PR-2 spans use
(``tracing.add_jsonl_sink`` / the ``PADDLE_TELEMETRY_DIR`` auto-sink) and
the same in-memory ring the hang watchdog dumps, so
``scripts/trace_view.py`` merges per-rank/per-replica files into one
request timeline.  Each record carries::

    {"trace": "<trace_id>", "span": "<trace_id>/3", "parent": "<id>|null",
     "name": "prefill_chunk", "rid": 7, "t0": <wall start>, "dur_s": 0.012,
     "time": <wall end>, "pid": ..., "status": "ok", "attrs": {...}}

Wall-clock stamps (``time.time()``) are the cross-process alignment, same
as PR-2 span records. Host spans additionally feed the profiler's
chrome-trace buffer (``req.<name>``) and, in the engine, dispatches run
under ``jax.profiler.TraceAnnotation("rtrace:<id>")`` host annotations —
the timeline join between these host records and xprof device traces.

Cost contract (same shape as tracing.span's): **disabled —
``start()`` is one enabled-flag check returning None**, and every call
site guards on that None, so the PR-2 <1%-of-step bound holds with
tracing compiled in. Enabled, a span is a dict build + ring/sink fan-out;
per-trace records are bounded (``MAX_SPANS_PER_TRACE``) with overflow
counted in ``rtrace.dropped_spans`` instead of unbounded growth.
"""
import os
import threading
import time
from collections import deque

from . import tracing
from .metrics import registry as _registry

__all__ = ["TraceContext", "Span", "start", "recent", "slowest", "errored",
           "clear", "MAX_SPANS_PER_TRACE"]

#: per-trace record bound: a runaway request (huge max_new_tokens) must not
#: hold an unbounded record list; overflow increments rtrace.dropped_spans
MAX_SPANS_PER_TRACE = 512

#: completed traces kept for /tracez (slow + errored views)
RECENT_TRACES = 128

_M_TRACES = _registry.counter(
    "rtrace.traces", help="request traces started")
_M_DROPPED = _registry.counter(
    "rtrace.dropped_spans",
    help="request-trace spans dropped by the per-trace bound")
_M_OPEN = _registry.gauge(
    "rtrace.open", help="request traces currently open")

_recent = deque(maxlen=RECENT_TRACES)
_recent_lock = threading.Lock()


def _emit(rec):
    """Fan one completed record out exactly where PR-2 spans land: the
    watchdog's ring, the profiler chrome-trace buffer (``req.<name>``, ts
    in perf_counter-epoch microseconds like tracing's records), every
    JSONL sink."""
    tracing.emit_record(
        rec,
        profiler_name=f"req.{rec['name']}",
        profiler_ts_us=(time.perf_counter() - rec["dur_s"]) * 1e6,
        profiler_dur_us=rec["dur_s"] * 1e6)


class Span:
    """One open request-scoped span. Unlike ``tracing.span`` this is an
    explicit handle: it can be opened on one thread and closed on another
    (submit opens ``queue``, a dispatcher closes it), and children hang off
    it by id, not off a thread-local stack. ``end()`` is idempotent — the
    context's finish() sweep may race a late closer benignly."""

    __slots__ = ("ctx", "span_id", "parent_id", "name", "attrs",
                 "_t0_wall", "_t0_perf", "_closed")

    def __init__(self, ctx, span_id, parent_id, name, attrs,
                 t0_wall=None, dur_s=None):
        self.ctx = ctx
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.attrs = attrs
        self._t0_wall = time.time() if t0_wall is None else t0_wall
        self._t0_perf = time.perf_counter() if dur_s is None else None
        self._closed = False
        if dur_s is not None:  # pre-timed span (emitted at readback points)
            self._finish(dur_s, "ok")

    def child(self, name, **attrs):
        """Open a child span (cross-thread safe)."""
        return self.ctx.begin(name, parent=self, **attrs)

    def event(self, name, **attrs):
        """Zero-duration child record — placement decisions, reroute edges."""
        return self.ctx.begin(name, parent=self, _dur_s=0.0, **attrs)

    def span_at(self, name, started_before_s, dur_s, **attrs):
        """Child span with explicit timing — for work whose start/end were
        stamped elsewhere with monotonic deltas (a decode block's
        dispatch→readback window). ``started_before_s`` is how long before
        NOW the work began; the wall-clock conversion happens here so hot
        paths never touch time.time() themselves (the ci.sh lint)."""
        return self.ctx.begin(name, parent=self,
                              _t0_wall=time.time() - started_before_s,
                              _dur_s=dur_s, **attrs)

    def end(self, status="ok", **attrs):
        if self._closed:
            return self
        dur = (time.perf_counter() - self._t0_perf
               if self._t0_perf is not None else 0.0)
        if attrs:
            self.attrs = {**(self.attrs or {}), **attrs}
        self._finish(dur, status)
        return self

    def _finish(self, dur_s, status):
        self._closed = True
        rec = {
            "trace": self.ctx.trace_id,
            "span": self.span_id,
            "parent": self.parent_id,
            "name": self.name,
            "rid": self.ctx.rid,
            "t0": self._t0_wall,
            "dur_s": dur_s,
            "time": self._t0_wall + dur_s,
            "pid": os.getpid(),
            "status": status,
        }
        if self.attrs:
            rec["attrs"] = self.attrs
        self.ctx._record(self, rec)

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        self.end("error" if exc_type is not None else "ok",
                 **({"error": f"{exc_type.__name__}: {exc}"}
                    if exc_type is not None else {}))
        return False


class _SuppressedSpan:
    """Inert span handle returned once a trace hits its span bound: every
    operation is a no-op that returns self, so over-budget call sites keep
    working while only the NEW span is dropped. Suppression happens at
    CREATION, not at record time — spans opened under budget (the root,
    the current attempt) still emit their close records, so a truncated
    trace stays a well-formed tree instead of orphaning already-emitted
    children under never-written parents."""

    __slots__ = ("ctx",)

    span_id = None
    parent_id = None

    def __init__(self, ctx):
        self.ctx = ctx

    def child(self, name, **attrs):
        return self.ctx.begin(name, parent=self)

    def event(self, name, **attrs):
        return self.ctx.begin(name, parent=self)

    def span_at(self, name, started_before_s, dur_s, **attrs):
        return self.ctx.begin(name, parent=self)

    def end(self, status="ok", **attrs):
        return self

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


class TraceContext:
    """One request's trace: the id, the root span, the (bounded) record
    buffer, and the set of still-open spans. Thread-safe — spans open and
    close from the submit thread, N dispatcher threads, and the monitor."""

    __slots__ = ("trace_id", "rid", "root", "records", "dropped",
                 "_seq", "_lock", "_open", "_finished")

    def __init__(self, trace_id, rid, **attrs):
        self.trace_id = trace_id
        self.rid = rid
        self.records = []
        self.dropped = 0
        self._seq = 0
        self._lock = threading.Lock()
        self._open = {}
        self._finished = False
        self.root = self.begin("request", parent=None, **attrs)

    def begin(self, name, parent=None, _t0_wall=None, _dur_s=None, **attrs):
        with self._lock:
            # the bound applies at CREATION: every span created here WILL
            # emit its close record, so a truncated trace never orphans
            # (parents always outlive — hence out-record — their children)
            if self._seq >= MAX_SPANS_PER_TRACE \
                    or isinstance(parent, _SuppressedSpan):
                self.dropped += 1
                _M_DROPPED.inc()
                return _SuppressedSpan(self)
            self._seq += 1
            span_id = f"{self.trace_id}/{self._seq}"
        parent_id = (parent.span_id if isinstance(parent, Span)
                     else parent)
        sp = Span(self, span_id, parent_id, name, attrs or None,
                  t0_wall=_t0_wall, dur_s=_dur_s)
        if not sp._closed:
            with self._lock:
                self._open[span_id] = sp
        return sp

    def _record(self, span, rec):
        with self._lock:
            self._open.pop(span.span_id, None)
            self.records.append(rec)
        _emit(rec)

    def finish(self, status="ok", **attrs):
        """Close the trace: every still-open non-root span is swept closed
        with the terminal status (structurally, a finished trace can have
        no orphan open spans), then the root closes and the trace joins the
        recent ring for /tracez. Idempotent — exactly one terminal
        transition wins, however many failure paths race."""
        with self._lock:
            if self._finished:
                return
            self._finished = True
            stragglers = [s for s in self._open.values()
                          if s is not self.root]
        for s in stragglers:
            s.end(status)
        self.root.end(status, **attrs)
        _M_OPEN.dec()
        dur = self.records[-1]["dur_s"] if self.records else 0.0
        root_rec = next((r for r in self.records
                         if r["span"] == self.root.span_id), None)
        summary = {
            "trace": self.trace_id,
            "rid": self.rid,
            "status": status,
            "dur_s": root_rec["dur_s"] if root_rec else dur,
            "t0": root_rec["t0"] if root_rec else None,
            "n_spans": len(self.records),
            "dropped": self.dropped,
            "records": list(self.records),
        }
        with _recent_lock:
            _recent.append(summary)


def start(rid, **attrs):
    """Mint a trace for one request, or None when telemetry is disabled
    (the zero-overhead contract: one flag check, no allocation)."""
    if not tracing.enabled():
        return None
    trace_id = os.urandom(8).hex()
    _M_TRACES.inc()
    _M_OPEN.inc()
    return TraceContext(trace_id, rid, **attrs)


def recent(n=RECENT_TRACES):
    """Most recently finished traces (oldest first), with full records."""
    with _recent_lock:
        return list(_recent)[-n:]


def slowest(n=10):
    """The n slowest recent traces, slowest first — /tracez's main view."""
    return sorted(recent(), key=lambda t: -(t["dur_s"] or 0.0))[:n]


def errored(n=10):
    """Recent traces that finished non-ok, newest first."""
    out = [t for t in recent() if t["status"] != "ok"]
    return out[::-1][:n]


def clear():
    """Test hook: drop the recent-trace ring."""
    with _recent_lock:
        _recent.clear()
