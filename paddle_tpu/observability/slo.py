"""SLO burn-rate accounting for the serving stack (ISSUE 7).

Latency histograms say what the tail WAS; an operator paging decision needs
"is the error budget burning down NOW, and is it a blip or a trend". This
module implements the standard multi-window burn-rate method (Google
SRE-workbook alerting): each :class:`SLOObjective` declares a target —
"99% of interactive requests see TTFT <= 1s" — and every observation is
classified good/bad into time-bucketed windows. The **burn rate** over a
window is ``bad_fraction / error_budget`` (1.0 = burning exactly the
budget; 14.4 over 5 minutes = the monthly budget gone in two days). An
alert fires only when BOTH the fast and the slow window exceed the
threshold: the fast window makes the alert responsive, the slow window
keeps a 30-second blip from paging.

Objectives come in three kinds:

- ``ttft``          — seconds from submit to first token (threshold_s)
- ``tpot``          — steady-state seconds per output token (threshold_s)
- ``deadline_miss`` — boolean: the request's user deadline expired

The serving frontend feeds a :class:`SLOMonitor` from its existing
observation points (``_observe_admission``/``_observe_completion``/expiry)
and surfaces ``monitor.report()`` in ``serving_report()`` and
``/statusz``. Stdlib-only, always-on (the registry cost model: an observe
is a few dict lookups + adds under one lock); the clock is injectable so
burn-rate math is unit-testable without sleeping.
"""
import threading
import time
from collections import deque

from .metrics import registry as _registry

__all__ = ["SLOObjective", "SLOMonitor", "default_objectives"]


class SLOObjective:
    """One promise: ``objective`` fraction of ``slo_class`` requests keep
    ``metric`` within ``threshold_s`` (threshold ignored for the boolean
    ``deadline_miss`` kind). ``error_budget = 1 - objective``."""

    __slots__ = ("name", "slo_class", "metric", "threshold_s", "objective")

    KINDS = ("ttft", "tpot", "deadline_miss")

    def __init__(self, slo_class, metric, threshold_s=None, objective=0.99,
                 name=None):
        if metric not in self.KINDS:
            raise ValueError(f"unknown SLO metric {metric!r}; "
                             f"have {self.KINDS}")
        if metric != "deadline_miss" and threshold_s is None:
            raise ValueError(f"{metric} objective needs threshold_s")
        if not 0.0 < objective < 1.0:
            raise ValueError(f"objective {objective} outside (0, 1)")
        self.slo_class = str(slo_class)
        self.metric = metric
        self.threshold_s = (float(threshold_s)
                            if threshold_s is not None else None)
        self.objective = float(objective)
        self.name = name or (
            f"{self.slo_class}.{metric}" +
            (f"<{self.threshold_s}s" if self.threshold_s is not None else ""))

    @property
    def error_budget(self):
        return 1.0 - self.objective

    def is_bad(self, value=None, bad=None):
        if self.metric == "deadline_miss":
            return bool(bad)
        return float(value) > self.threshold_s

    def __repr__(self):
        return (f"SLOObjective({self.slo_class!r}, {self.metric!r}, "
                f"threshold_s={self.threshold_s}, "
                f"objective={self.objective})")


def default_objectives(classes):
    """Build the default objective set from SLO classes (scheduler.SLOClass
    objects carrying ``ttft_slo_s``/``tpot_slo_s``/``slo_objective``, or
    anything duck-typed the same): one ttft + one tpot objective per class
    that declares a threshold, plus a shared per-class deadline_miss
    objective — the three kinds the serving comparison papers report."""
    out = []
    for c in classes:
        objective = float(getattr(c, "slo_objective", 0.99) or 0.99)
        ttft = getattr(c, "ttft_slo_s", None)
        if ttft:
            out.append(SLOObjective(c.name, "ttft", threshold_s=ttft,
                                    objective=objective))
        tpot = getattr(c, "tpot_slo_s", None)
        if tpot:
            out.append(SLOObjective(c.name, "tpot", threshold_s=tpot,
                                    objective=objective))
        out.append(SLOObjective(c.name, "deadline_miss", objective=0.999))
    return out


class _Window:
    """Time-bucketed good/bad counts over a bounded horizon. Buckets are
    coarse (horizon/60 by default) — burn-rate alerting needs minutes-scale
    resolution, not per-event timestamps — so memory is O(60) per window
    regardless of traffic."""

    __slots__ = ("bucket_s", "horizon_s", "_buckets", "_lock")

    def __init__(self, horizon_s, bucket_s=None):
        self.horizon_s = float(horizon_s)
        self.bucket_s = float(bucket_s) if bucket_s else max(
            1.0, self.horizon_s / 60.0)
        self._buckets = deque()  # [bucket_start, good, bad]
        self._lock = threading.Lock()

    def add(self, now, good, bad):
        start = now - (now % self.bucket_s)
        with self._lock:
            if self._buckets and self._buckets[-1][0] == start:
                self._buckets[-1][1] += good
                self._buckets[-1][2] += bad
            else:
                self._buckets.append([start, good, bad])
            self._prune(now)

    def _prune(self, now):
        limit = now - self.horizon_s - self.bucket_s
        while self._buckets and self._buckets[0][0] < limit:
            self._buckets.popleft()

    def totals(self, now):
        with self._lock:
            self._prune(now)
            good = sum(b[1] for b in self._buckets)
            bad = sum(b[2] for b in self._buckets)
        return good, bad


class SLOMonitor:
    """Burn-rate accounting over a set of objectives, two windows each.

    ``alert_burn_rate`` is the page threshold applied to BOTH windows
    (default 14.4 — the SRE-workbook 5m/1h pairing: sustaining it exhausts
    a 30-day budget in ~2 days). ``observe``/``observe_event`` are the feed
    points; ``report()`` is the /statusz + serving_report() payload and
    refreshes the ``slo.burn_rate`` gauges.

    ``gauge_labels`` (ISSUE 19) namespaces this monitor's gauge series —
    the per-tenant monitors the frontend keeps would otherwise all write
    the same ``slo.burn_rate{objective=,window=}`` series and clobber the
    fleet monitor's."""

    def __init__(self, objectives=None, classes=None, fast_window_s=300.0,
                 slow_window_s=3600.0, alert_burn_rate=14.4,
                 clock=time.monotonic, gauge_labels=None):
        if objectives is None:
            objectives = default_objectives(classes or ())
        self.objectives = list(objectives)
        names = [o.name for o in self.objectives]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate objective names in {names}")
        self.fast_window_s = float(fast_window_s)
        self.slow_window_s = float(slow_window_s)
        self.alert_burn_rate = float(alert_burn_rate)
        self.gauge_labels = dict(gauge_labels) if gauge_labels else {}
        self._clock = clock
        self._windows = {
            o.name: (_Window(self.fast_window_s), _Window(self.slow_window_s))
            for o in self.objectives}
        self._by_key = {}
        for o in self.objectives:
            self._by_key.setdefault((o.slo_class, o.metric), []).append(o)
        self._alerts_fired = _registry.counter(
            "slo.alerts_fired",
            help="multi-window SLO burn-rate alert transitions (off->on)")
        self._alerting = set()

    # ---- feed -------------------------------------------------------------
    def observe(self, slo_class, metric, value):
        """One latency sample (seconds) for every matching objective."""
        self._add(slo_class, metric, value=value)

    def observe_event(self, slo_class, metric, bad):
        """One boolean sample (deadline_miss kind)."""
        self._add(slo_class, metric, bad=bad)

    def _add(self, slo_class, metric, value=None, bad=None):
        objs = self._by_key.get((slo_class, metric))
        if not objs:
            return
        now = self._clock()
        for o in objs:
            is_bad = o.is_bad(value=value, bad=bad)
            fast, slow = self._windows[o.name]
            fast.add(now, 0 if is_bad else 1, 1 if is_bad else 0)
            slow.add(now, 0 if is_bad else 1, 1 if is_bad else 0)

    # ---- read -------------------------------------------------------------
    def _burn(self, o, window, now):
        good, bad = window.totals(now)
        total = good + bad
        if not total:
            return 0.0, 0
        return (bad / total) / o.error_budget, total

    def burn_rates(self):
        """{objective name: {fast, slow, fast_n, slow_n, budget}}"""
        now = self._clock()
        out = {}
        for o in self.objectives:
            fast_w, slow_w = self._windows[o.name]
            fast, fast_n = self._burn(o, fast_w, now)
            slow, slow_n = self._burn(o, slow_w, now)
            out[o.name] = {"fast": fast, "slow": slow,
                           "fast_n": fast_n, "slow_n": slow_n,
                           "budget": o.error_budget}
        return out

    def alerts(self, rates=None):
        """Objectives burning past the threshold in BOTH windows right now
        (the multi-window AND is what separates a page from a blip).
        ``rates`` lets report() reuse one burn_rates() pass."""
        out = []
        all_rates = rates if rates is not None else self.burn_rates()
        for o in self.objectives:
            r = all_rates[o.name]
            if (r["fast_n"] and r["slow_n"]
                    and r["fast"] >= self.alert_burn_rate
                    and r["slow"] >= self.alert_burn_rate):
                out.append({
                    "objective": o.name,
                    "slo_class": o.slo_class,
                    "metric": o.metric,
                    "threshold_s": o.threshold_s,
                    "burn_fast": round(r["fast"], 3),
                    "burn_slow": round(r["slow"], 3),
                    "alert_burn_rate": self.alert_burn_rate,
                })
        # transition counting: a NEW alerting objective bumps the counter
        # and flight-records the page (ISSUE 13) — the bundle freezes the
        # span ring / goodput split at the moment the burn crossed, the
        # evidence a post-hoc SLO review needs
        names = {a["objective"] for a in out}
        newly = names - self._alerting
        for name in newly:
            self._alerts_fired.inc()
        if newly:
            from . import flightrec

            flightrec.record(
                "slo_page",
                payload={"alerting": sorted(names),
                         "new": sorted(newly),
                         "alerts": [a for a in out
                                    if a["objective"] in newly]})
        self._alerting = names
        return out

    def report(self):
        """Structured snapshot for serving_report()//statusz; refreshes the
        ``slo.burn_rate`` gauge family as a side effect (scrape-visible)."""
        rates = self.burn_rates()
        for name, r in rates.items():
            for win in ("fast", "slow"):
                _registry.gauge("slo.burn_rate",
                                labels={"objective": name, "window": win,
                                        **self.gauge_labels},
                                help="SLO error-budget burn rate per window"
                                ).set(r[win])
        alerts = self.alerts(rates=rates)
        alerting = {a["objective"] for a in alerts}
        return {
            "windows_s": {"fast": self.fast_window_s,
                          "slow": self.slow_window_s},
            "alert_burn_rate": self.alert_burn_rate,
            "objectives": {
                name: {**{k: (round(v, 4) if isinstance(v, float) else v)
                          for k, v in r.items()},
                       "alerting": name in alerting}
                for name, r in rates.items()},
            "alerts": alerts,
        }
