"""Goodput accounting: where did the wall clock actually go?

Attributes elapsed time to a small fixed taxonomy —

- ``init``       first-dispatch compile + state placement (pays once, or
                 again after every elastic restart: restart badput)
- ``step``       productive optimizer steps — THE goodput
- ``data_wait``  input pipeline starvation (host blocked on the loader)
- ``checkpoint`` save/serialize stalls on the training thread
- ``recovery``   resume loads, restart rendezvous, watchdog-diagnosed stalls

so the chaos layer's preemptions and the launcher's restarts show up as
measured badput fractions, not vibes. ``report()`` divides by true wall
clock since process start (or ``reset()``), so untracked time is visible
too instead of silently inflating goodput.

Gating: ``account(cat)`` is a no-op context manager unless span tracing is
enabled (same switch: PADDLE_TELEMETRY / tracing.enable()) — hot loops carry
it for free, and ALL categories share the gate so a report never shows
badput-only fractions from a telemetry-off run. ``always=True`` exists for
callers that need unconditional attribution.
"""
import threading
import time

from . import tracing

__all__ = ["GoodputAccountant", "accountant", "account", "note", "report",
           "reset", "CATEGORIES"]

CATEGORIES = ("init", "step", "data_wait", "checkpoint", "recovery")


class _Timer:
    __slots__ = ("_acct", "_cat", "_t0")

    def __init__(self, acct, cat):
        self._acct = acct
        self._cat = cat

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self._acct.note(self._cat, time.perf_counter() - self._t0)
        return False


class GoodputAccountant:
    def __init__(self):
        self._lock = threading.Lock()
        self._totals = {}
        self._t0 = time.perf_counter()

    def account(self, category, always=False):
        """Context manager attributing the enclosed wall time to
        ``category``. Free (shared no-op) when telemetry is disabled unless
        ``always=True``."""
        if not always and not tracing.enabled():
            return tracing._NULL
        return _Timer(self, category)

    def note(self, category, seconds):
        with self._lock:
            self._totals[category] = self._totals.get(category, 0.0) + seconds

    def totals(self):
        with self._lock:
            return dict(self._totals)

    def report(self):
        """{wall_s, tracked_s, untracked_s, categories, fractions,
        goodput_fraction, badput}: fractions are of WALL clock, so they sum
        (with untracked) to 1."""
        wall = time.perf_counter() - self._t0
        totals = self.totals()
        tracked = sum(totals.values())
        frac = {k: (v / wall if wall > 0 else 0.0) for k, v in totals.items()}
        return {
            "wall_s": wall,
            "tracked_s": tracked,
            "untracked_s": max(0.0, wall - tracked),
            "categories": totals,
            "fractions": frac,
            "goodput_fraction": frac.get("step", 0.0),
            "badput": {k: v for k, v in frac.items() if k != "step"},
        }

    def reset(self):
        with self._lock:
            self._totals = {}
            self._t0 = time.perf_counter()


#: process singleton + module-level conveniences
accountant = GoodputAccountant()
account = accountant.account
note = accountant.note
totals = accountant.totals
report = accountant.report
reset = accountant.reset
