"""Goodput accounting: where did the wall clock actually go?

Attributes elapsed time to a small fixed taxonomy —

- ``init``       first-dispatch compile + state placement (pays once, or
                 again after every elastic restart: restart badput)
- ``step``       productive optimizer steps — THE goodput
- ``data_wait``  input pipeline starvation (host blocked on the loader)
- ``checkpoint`` save/serialize stalls on the training thread
- ``recovery``   resume loads, restart rendezvous, watchdog-diagnosed stalls
- ``telemetry``  cadence-gated host reads of device-resident telemetry
                 (the non-finite sentinel counters, the dynamics carry
                 spill) — each read synchronizes on the step, and that
                 wall must be attributed, not silently folded into step
                 time (ISSUE 13 satellite)

so the chaos layer's preemptions and the launcher's restarts show up as
measured badput fractions, not vibes. ``report()`` divides by true wall
clock since process start (or ``reset()``), so untracked time is visible
too instead of silently inflating goodput.

Gating: ``account(cat)`` is a no-op context manager unless span tracing is
enabled (same switch: PADDLE_TELEMETRY / tracing.enable()) — hot loops carry
it for free, and ALL categories share the gate so a report never shows
badput-only fractions from a telemetry-off run. ``always=True`` exists for
callers that need unconditional attribution.
"""
import threading
import time

from . import tracing

__all__ = ["GoodputAccountant", "accountant", "account", "note", "report",
           "reset", "CATEGORIES", "SERVING_CATEGORIES", "serving",
           "serving_note", "serving_report"]

CATEGORIES = ("init", "step", "data_wait", "checkpoint", "recovery",
              "telemetry")

#: serving-path taxonomy (ISSUE 7 satellite): engine wall clock classified
#: into device-productive work (prefill, decode) vs host/emit, dispatcher
#: idle, and compile stalls — the serving analogue of the training split
SERVING_CATEGORIES = ("prefill", "decode", "host_emit", "idle", "compile")


class _Timer:
    __slots__ = ("_acct", "_cat", "_t0")

    def __init__(self, acct, cat):
        self._acct = acct
        self._cat = cat

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self._acct.note(self._cat, time.perf_counter() - self._t0)
        return False


class GoodputAccountant:
    def __init__(self, goodput_categories=("step",)):
        #: the categories that COUNT as goodput in report() — ("step",) for
        #: the training accountant, ("prefill", "decode") for serving
        self.goodput_categories = tuple(goodput_categories)
        self._lock = threading.Lock()
        self._totals = {}
        self._t0 = time.perf_counter()

    def account(self, category, always=False):
        """Context manager attributing the enclosed wall time to
        ``category``. Free (shared no-op) when telemetry is disabled unless
        ``always=True``."""
        if not always and not tracing.enabled():
            return tracing._NULL
        return _Timer(self, category)

    def note(self, category, seconds):
        with self._lock:
            self._totals[category] = self._totals.get(category, 0.0) + seconds

    def totals(self):
        with self._lock:
            return dict(self._totals)

    def report(self):
        """{wall_s, tracked_s, untracked_s, categories, fractions,
        goodput_fraction, badput}: fractions are of WALL clock, so they sum
        (with untracked) to 1."""
        wall = time.perf_counter() - self._t0
        totals = self.totals()
        tracked = sum(totals.values())
        frac = {k: (v / wall if wall > 0 else 0.0) for k, v in totals.items()}
        good = self.goodput_categories
        return {
            "wall_s": wall,
            "tracked_s": tracked,
            "untracked_s": max(0.0, wall - tracked),
            "categories": totals,
            "fractions": frac,
            "goodput_fraction": sum(frac.get(c, 0.0) for c in good),
            "badput": {k: v for k, v in frac.items() if k not in good},
        }

    def reset(self):
        with self._lock:
            self._totals = {}
            self._t0 = time.perf_counter()


#: process singleton + module-level conveniences
accountant = GoodputAccountant()
account = accountant.account
note = accountant.note
totals = accountant.totals
report = accountant.report
reset = accountant.reset

#: the serving-path accountant: device work (prefill + decode) is the
#: goodput; host_emit / idle / compile are the badput the data-plane
#: pipelining (ISSUE 6) exists to hide. Fed by the engine's dispatch
#: epilogues and the frontend's idle waits — gated on the same telemetry
#: switch as every other timer (call sites check tracing.enabled()).
#: Attribution caveat: N dispatcher threads each contribute their own
#: time against ONE wall clock (reset at frontend start), so with N
#: replicas an idle cell reports idle ≈ N×wall and fractions can exceed
#: 1 — read the split as "where thread-seconds went", not a partition.
serving = GoodputAccountant(goodput_categories=("prefill", "decode"))
serving_note = serving.note
serving_report = serving.report

