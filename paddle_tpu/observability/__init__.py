"""Unified telemetry (ISSUE 2 tentpole): metrics registry, span tracing,
goodput accounting, and the distributed hang watchdog.

One import, four capabilities:

    from paddle_tpu import observability as obs

    obs.enable()                              # or PADDLE_TELEMETRY=1
    with obs.span("train.step.dispatch"):     # nested host spans
        ...
    obs.registry.counter("serve.requests").inc()
    print(obs.registry.to_prometheus())       # scrape-ready snapshot
    print(obs.goodput.report())               # {goodput_fraction, badput...}

The package is stdlib-only (no jax import) so the launcher, forked
dataloader workers, and test harnesses can use it without touching device
runtimes. Metric publication (counters/gauges/histograms) is always on —
it is the EventCounters cost model. Span tracing and goodput timers are
**zero-overhead when disabled** (a shared no-op context manager); see
docs/OBSERVABILITY.md for the metric/span taxonomy and env vars.
"""
from . import compilemem  # noqa: F401
from . import devprof  # noqa: F401
from . import dynamics  # noqa: F401
from . import fleet  # noqa: F401
from . import flightrec  # noqa: F401
from . import goodput  # noqa: F401
from . import request_trace  # noqa: F401
from . import slo  # noqa: F401
from .compilemem import (  # noqa: F401
    CompileLedger,
    MemoryLedger,
    ledgered_jit,
    record_compile,
)
from .devprof import DevProfPlane  # noqa: F401
from .dynamics import DynamicsMonitor  # noqa: F401
from .fleet import FleetAggregator, SnapshotPublisher  # noqa: F401
from .flightrec import FlightRecorder  # noqa: F401
from .goodput import GoodputAccountant  # noqa: F401
from .metrics import (  # noqa: F401
    DEFAULT_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    registry,
)
from .slo import SLOMonitor, SLOObjective  # noqa: F401
from .statusz import StatusServer  # noqa: F401
from .tracing import (  # noqa: F401
    JsonlSpanSink,
    add_jsonl_sink,
    disable,
    enable,
    enabled,
    last_spans,
    span,
)
from .watchdog import HangWatchdog, Heartbeat, maybe_beat  # noqa: F401

__all__ = [
    "DEFAULT_BUCKETS", "Counter", "Gauge", "Histogram", "MetricsRegistry",
    "registry", "span", "enable", "disable", "enabled", "last_spans",
    "add_jsonl_sink", "JsonlSpanSink", "goodput", "GoodputAccountant",
    "HangWatchdog", "Heartbeat", "maybe_beat", "request_trace", "slo",
    "SLOMonitor", "SLOObjective", "StatusServer", "compilemem",
    "CompileLedger", "MemoryLedger", "ledgered_jit", "record_compile",
    "fleet", "FleetAggregator", "SnapshotPublisher",
    "dynamics", "DynamicsMonitor", "flightrec", "FlightRecorder",
    "devprof", "DevProfPlane",
]
