"""Per-program device-time profiling plane (ISSUE 17 tentpole).

Every telemetry layer before this one measures the HOST — spans, compile
events, HBM budgets, fleet skew, in-program dynamics. None of them can
say how many device-seconds each compiled program actually consumes, or
whether a given program is compute- or memory-bound. This module closes
that gap by joining two sources, both keyed by the PR-8 compile-ledger
program key (``train.step``, ``serve.decode_block[k8,s...]``, ...):

- **static cost**: at analysis time the memory ledger harvests
  ``compiled.cost_analysis()`` next to ``memory_analysis()`` — FLOPs and
  bytes accessed per program (:meth:`MemoryLedger.analyze`);
- **measured device time**: on a sampling cadence
  (``PADDLE_DEVPROF_SAMPLE_EVERY``), the dispatch sites stamp a
  pre-dispatch clock and call :meth:`DevProfPlane.tick` with the
  program's output arrays. On-cadence ticks ``block_until_ready`` HERE —
  the one place a timed-dispatch device sync is legal (the
  ``devprof-seam`` analysis rule pins every other ``block_until_ready``
  in the tree) — and record wall-from-dispatch as the program's device
  time. Off-cadence ticks are one counter increment; the serving decode
  path stays fully async between samples.

From the join the plane derives, per program: achieved FLOP/s, achieved
HBM bandwidth, arithmetic intensity, MFU, and a **roofline verdict** —
``compute-bound`` when the program's arithmetic intensity sits above the
hardware knee (peak FLOP/s ÷ peak bytes/s), ``memory-bound`` below it,
and ``host-bound`` when measured device time dwarfs what the roofline
says the program should cost (the dispatch path, not the chip, is the
bottleneck). Hardware knees come from the device kind with
``PADDLE_DEVPROF_PEAK_FLOPS`` / ``PADDLE_DEVPROF_PEAK_BW`` overrides
(CPU CI has no HBM — same pattern as ``PADDLE_HBM_CAPACITY_BYTES``).

Aggregations: a serving decode budget (device-seconds per emitted token,
per bucket/chunk program signature — the paged-vs-dense gap program by
program) and a training step split that reconciles measured step device
time against the PR-11 compute-vs-collective-wait attribution.

Cost contract (the PR-2 discipline, asserted in tests/test_devprof.py):
disabled (``PADDLE_DEVPROF`` unset) the hot paths pay one
module-attribute-is-None check; enabled, between samples, one dict
counter increment; the sync itself happens at most once per cadence
window per call-site context.

Surfaces: ``/perfz`` (statusz), ``serving_report()["devprof"]``,
``devprof.*`` metrics, the fleet snapshot block (the aggregator flags a
rank whose per-program device time diverges from the fleet median — a
sick chip, not a slow host), and per-program rows in both benches'
``BENCH_trajectory.jsonl`` records so the trajectory guard can name
WHICH program regressed.

jax is imported lazily inside the sampling seam — the observability
package stays stdlib-only at import time.
"""
import math
import threading
import time

from ..utils.envs import env_bool, env_float, env_int
from .metrics import registry as _registry

__all__ = ["DevProfPlane", "arm_from_env", "enable", "disable", "enabled",
           "plane", "report", "serving_block", "fleet_block", "ENABLE_ENV",
           "EVERY_ENV", "PEAK_FLOPS_ENV", "PEAK_BW_ENV"]

#: master switch — unset/false = every hot path is one None check
ENABLE_ENV = "PADDLE_DEVPROF"
#: sampling cadence in dispatches per call-site context: at most one
#: timed (blocking) dispatch per window, the rest stay async
EVERY_ENV = "PADDLE_DEVPROF_SAMPLE_EVERY"
#: hardware peak FLOP/s override for the roofline/MFU denominators
PEAK_FLOPS_ENV = "PADDLE_DEVPROF_PEAK_FLOPS"
#: hardware peak HBM bytes/s override for the roofline knee
PEAK_BW_ENV = "PADDLE_DEVPROF_PEAK_BW"

#: bf16 peak FLOP/s and HBM bytes/s per chip by device-kind substring,
#: first match wins (same table shape as bench.peak_flops_per_chip)
_PEAKS = (
    ("v5 lite", 197e12, 819e9),
    ("v5e", 197e12, 819e9),
    ("lite", 197e12, 819e9),
    ("v5p", 459e12, 2765e9),
    ("v5", 459e12, 2765e9),
    ("v4", 275e12, 1228e9),
    ("v3", 123e12, 900e9),
)
#: nominal knees for CPU smoke runs — the roofline still needs a finite
#: denominator so MFU/verdicts are well-defined (and obviously nominal)
_CPU_PEAKS = (1e12, 100e9)

#: measured device time past this multiple of the roofline-predicted
#: time means the chip is idle most of the window: host-bound
_HOST_BOUND_RATIO = 10.0

#: the live plane — None means disabled and every hot path is the single
#: ``_PLANE is not None`` check (the watchdog/dynamics one-check pattern)
_PLANE = None
_plane_lock = threading.Lock()


def _device_peaks(peak_flops=None, peak_bw=None):
    """(kind, peak FLOP/s, peak bytes/s): env overrides first, else the
    device-kind table, else CPU nominals. Never raises — a plane must
    arm even when jax/devices are unavailable."""
    kind = "unknown"
    try:
        import jax

        d = jax.devices()[0]
        kind = (getattr(d, "device_kind", "") or d.platform or "cpu").lower()
    except Exception:
        pass
    flops, bw = _CPU_PEAKS
    for sub, f, b in _PEAKS:
        if sub in kind:
            flops, bw = f, b
            break
    flops = float(peak_flops if peak_flops is not None
                  else env_float(PEAK_FLOPS_ENV, flops))
    bw = float(peak_bw if peak_bw is not None
               else env_float(PEAK_BW_ENV, bw))
    return kind, max(flops, 1.0), max(bw, 1.0)


class DevProfPlane:
    """The process-wide sampler: per-context cadence counters, the
    per-program sample table, and the cost join that turns samples into
    roofline rows."""

    def __init__(self, sample_every=None, peak_flops=None, peak_bw=None):
        self.sample_every = max(1, int(sample_every) if sample_every
                                is not None else env_int(EVERY_ENV, 16))
        self.device_kind, self.peak_flops, self.peak_bw = _device_peaks(
            peak_flops, peak_bw)
        self._lock = threading.Lock()
        #: dispatches since the last timed sample, per call-site context
        #: ("train", "serve.decode", ...) — cadence is per SITE so a busy
        #: decode loop cannot starve the train step of samples
        self._since = {}
        #: program key -> accumulated sample stats
        self._programs = {}
        self.started = time.time()

    # ---- the sampling seam -------------------------------------------------
    def tick(self, key, t0, arrays, tokens=0, context=None):
        """One dispatch of ``key`` whose outputs are ``arrays`` and whose
        pre-dispatch ``time.monotonic()`` stamp is ``t0``. Off cadence:
        one counter increment. On cadence: THE timed sync — wait for the
        program's outputs inside this module and bank wall-from-dispatch
        as device time. Returns True when this tick sampled."""
        ctx = context or key
        with self._lock:
            n = self._since.get(ctx, 0) + 1
            if n < self.sample_every:
                self._since[ctx] = n
                return False
            self._since[ctx] = 0
        import jax

        jax.block_until_ready(arrays)  # devprof-seam-ok (the one legal timed-dispatch sync; see module docstring)
        dev_s = time.monotonic() - t0
        if dev_s < 0:  # a bad caller clock must not poison the table
            return False
        self._record(key, dev_s, tokens)
        return True

    def _record(self, key, dev_s, tokens):
        key = str(key)
        with self._lock:
            rec = self._programs.get(key)
            if rec is None:
                rec = self._programs[key] = {
                    "samples": 0, "device_s": 0.0, "last_s": 0.0,
                    "min_s": math.inf, "max_s": 0.0, "tokens": 0}
            rec["samples"] += 1
            rec["device_s"] += dev_s
            rec["last_s"] = dev_s
            rec["min_s"] = min(rec["min_s"], dev_s)
            rec["max_s"] = max(rec["max_s"], dev_s)
            rec["tokens"] += int(tokens)
        _registry.counter(
            "devprof.samples",
            help="timed (blocking) devprof dispatch samples taken").inc()
        _registry.histogram(
            "devprof.sample_s",
            help="sampled dispatch-to-ready device wall per timed "
                 "dispatch").observe(dev_s)
        labels = {"program": key}
        _registry.gauge(
            "devprof.device_s", labels=labels,
            help="last sampled device-seconds per dispatch of this "
                 "program").set(round(dev_s, 9))
        if tokens:
            _registry.gauge(
                "devprof.device_s_per_token", labels=labels,
                help="last sampled device-seconds per emitted token for "
                     "this decode program").set(round(dev_s / tokens, 9))
        cost = self._cost(key)
        flops = (cost or {}).get("flops")
        if flops:
            _registry.gauge(
                "devprof.mfu", labels=labels,
                help="achieved FLOP/s over device peak at the last "
                     "sample of this program").set(
                round(flops / dev_s / self.peak_flops, 6))

    # ---- the cost join -----------------------------------------------------
    @staticmethod
    def _cost(key):
        """The ledgered cost_analysis row for ``key`` (None until the
        memory ledger has analyzed that program)."""
        try:
            from . import compilemem

            return compilemem.memory.program_cost(key)
        except Exception:
            return None

    def _row(self, key, rec):
        n = rec["samples"]
        mean_s = rec["device_s"] / n if n else 0.0
        row = {
            "samples": n,
            "device_s_total": round(rec["device_s"], 6),
            "device_s_mean": round(mean_s, 9),
            "device_s_last": round(rec["last_s"], 9),
            "device_s_min": round(rec["min_s"], 9),
            "device_s_max": round(rec["max_s"], 9),
        }
        if rec["tokens"]:
            row["tokens"] = rec["tokens"]
            row["device_s_per_token"] = round(
                rec["device_s"] / rec["tokens"], 9)
        cost = self._cost(key) or {}
        flops = cost.get("flops") or 0.0
        nbytes = cost.get("bytes") or 0.0
        if flops:
            row["flops"] = flops
        if nbytes:
            row["bytes"] = nbytes
        if mean_s <= 0:
            row["verdict"] = "unknown"
            return row
        if flops:
            row["achieved_flops_s"] = round(flops / mean_s, 3)
            row["mfu"] = round(flops / mean_s / self.peak_flops, 6)
        if nbytes:
            row["achieved_bw_bytes_s"] = round(nbytes / mean_s, 3)
            row["hbm_util"] = round(nbytes / mean_s / self.peak_bw, 6)
        if flops and nbytes:
            row["arith_intensity"] = round(flops / nbytes, 4)
        # roofline: what SHOULD this program cost on this chip?
        t_compute = flops / self.peak_flops
        t_mem = nbytes / self.peak_bw
        bound = max(t_compute, t_mem)
        if bound <= 0:
            row["verdict"] = "unknown"
        elif mean_s > _HOST_BOUND_RATIO * bound:
            row["verdict"] = "host-bound"
        elif t_compute >= t_mem:
            row["verdict"] = "compute-bound"
        else:
            row["verdict"] = "memory-bound"
        return row

    # ---- surfaces ----------------------------------------------------------
    def _table(self):
        with self._lock:
            return {k: dict(v) for k, v in self._programs.items()}

    def report(self, analyze=False, program=None):
        """The /perfz payload: per-program roofline rows plus the serving
        decode-token budget and the training step split. ``analyze=True``
        forces the (suppressed re-compile) cost harvest for programs the
        ledger has not analyzed yet; ``program`` filters rows by key
        prefix."""
        if analyze:
            try:
                from . import compilemem

                compilemem.memory.analyze()
            except Exception:
                pass
        rows = {}
        for key, rec in sorted(self._table().items()):
            if program and not key.startswith(program):
                continue
            rows[key] = self._row(key, rec)
        out = {
            "enabled": True,
            "sample_every": self.sample_every,
            "device": {
                "kind": self.device_kind,
                "peak_flops_s": self.peak_flops,
                "peak_bw_bytes_s": self.peak_bw,
                "roofline_knee": round(self.peak_flops / self.peak_bw, 3),
            },
            "programs": rows,
        }
        serving = self._serving_split(rows)
        if serving:
            out["serving"] = serving
        training = self._training_split(rows)
        if training:
            out["training"] = training
        return out

    @staticmethod
    def _serving_split(rows):
        """The decode device-time budget: device-seconds per emitted
        token, overall and per decode program signature — BENCH_r05's
        paged-vs-dense gap, attributed program by program."""
        decode = {k: r for k, r in rows.items()
                  if k.startswith("serve.decode") and r.get("tokens")}
        if not decode:
            return None
        dev_s = sum(r["device_s_total"] for r in decode.values())
        tokens = sum(r["tokens"] for r in decode.values())
        return {
            "decode_device_s": round(dev_s, 6),
            "decode_tokens": tokens,
            "device_s_per_token": round(dev_s / tokens, 9) if tokens else None,
            "per_program": {k: {
                "device_s_per_token": r.get("device_s_per_token"),
                "mfu": r.get("mfu"),
                "verdict": r.get("verdict"),
            } for k, r in decode.items()},
        }

    @staticmethod
    def _training_split(rows):
        """The step split: measured step device time next to the PR-11
        compute-vs-collective-wait attribution, so "the step got slower"
        reconciles into "the chip got slower" vs "the ring got slower"."""
        train = {k: r for k, r in rows.items() if k.startswith("train.")}
        if not train:
            return None
        out = {"per_program": {k: {
            "device_s_mean": r["device_s_mean"],
            "mfu": r.get("mfu"),
            "verdict": r.get("verdict"),
        } for k, r in train.items()}}
        step = train.get("train.step")
        if step:
            out["step_device_s_mean"] = step["device_s_mean"]
            h = _registry.get("collective.wait_s")
            wait = h.mean if h is not None and h.count else None
            if wait is not None and step["device_s_mean"] > 0:
                out["collective_wait_s_mean"] = round(wait, 9)
                out["compute_fraction"] = round(
                    max(0.0, 1.0 - wait / step["device_s_mean"]), 6)
        return out

    def fleet_block(self):
        """The bounded per-rank snapshot block the aggregator medians
        across ranks: mean device-seconds per dispatch for the costliest
        programs. None until something has been sampled."""
        table = self._table()
        if not table:
            return None
        ranked = sorted(table.items(), key=lambda kv: kv[1]["device_s"],
                        reverse=True)[:16]
        return {
            "sample_every": self.sample_every,
            "programs": {k: round(v["device_s"] / v["samples"], 9)
                         for k, v in ranked if v["samples"]},
        }


# ---- module-level switches (the watchdog arm/disarm idiom) -----------------
def arm_from_env():
    """Install the plane when ``PADDLE_DEVPROF`` is truthy (idempotent —
    every TrainStep / serving engine constructor calls this). Returns
    the live plane or None."""
    global _PLANE
    if _PLANE is None and env_bool(ENABLE_ENV):
        with _plane_lock:
            if _PLANE is None:
                _PLANE = DevProfPlane()
    return _PLANE


def enable(sample_every=None, peak_flops=None, peak_bw=None):
    """Install a plane unconditionally (benches arm profiling AFTER their
    timed comparison phases this way). Replaces any live plane."""
    global _PLANE
    with _plane_lock:
        _PLANE = DevProfPlane(sample_every=sample_every,
                              peak_flops=peak_flops, peak_bw=peak_bw)
    return _PLANE


def disable():
    """Back to the disabled one-check state; sampled data is dropped."""
    global _PLANE
    with _plane_lock:
        _PLANE = None


#: test hook — same contract as the other observability _reset()s
_reset = disable


def enabled():
    return _PLANE is not None


def plane():
    """The live plane or None."""
    return _PLANE


def report(analyze=False, program=None):
    """The /perfz payload ({"enabled": False} while disarmed)."""
    p = _PLANE
    if p is None:
        return {"enabled": False}
    return p.report(analyze=analyze, program=program)


def serving_block():
    """The serving_report()["devprof"] block: full report, no forced
    analysis (a report scrape must never trigger re-compiles)."""
    p = _PLANE
    if p is None:
        return {"enabled": False}
    return p.report(analyze=False)


def fleet_block():
    """The per-rank fleet-snapshot block (None while disarmed or before
    the first sample)."""
    p = _PLANE
    if p is None:
        return None
    return p.fleet_block()
