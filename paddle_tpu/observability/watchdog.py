"""Distributed hang watchdog: turn "job is stuck" into a diagnosis.

Two halves, meeting in a shared telemetry directory
(``PADDLE_TELEMETRY_DIR``, set per-worker by the launcher):

- **Heartbeat** (trainer side): ``beat(step)`` atomically rewrites
  ``heartbeat.<rank>.json`` (rank, pid, step, wall time) once per optimizer
  step — wired into jit_api.TrainStep via ``maybe_beat`` (cached no-op when
  the env var is unset). Construction also registers a SIGUSR1 faulthandler
  that dumps ALL thread stacks to ``stacks.<rank>.txt`` — faulthandler's
  C-level handler fires even when the Python main thread is wedged inside a
  blocking call, which is exactly the hang case.

- **HangWatchdog** (launcher side, a monitor thread in
  distributed/launch/controller.py): polls the heartbeat files; when any
  rank's beat is staler than ``deadline_s`` it (1) signals EVERY rank's pid
  with SIGUSR1 for a fresh stack dump, (2) collects each rank's stack file
  and the tail of its span JSONL (what the rank was doing), and (3) commits
  one ``hang_report.json`` — all-rank stacks + last-N spans + heartbeat
  ages — before the launcher acts. Diagnostic mode fires at most once; with
  ``signal_stalled`` set (launcher ``--hang_preempt``) it additionally
  SIGTERMs stalled ranks (emergency-save + preempted exit), SIGKILLs any
  still wedged after ``kill_grace_s``, and re-arms to catch the NEXT hang
  of the restarted job.
"""
import json
import os
import re
import signal
import threading
import time

from ..utils.envs import env_str

__all__ = ["Heartbeat", "HangWatchdog", "maybe_beat", "heartbeat_path",
           "stacks_path", "spans_path", "REPORT_NAME", "DIR_ENV",
           "DEADLINE_ENV"]

DIR_ENV = "PADDLE_TELEMETRY_DIR"
DEADLINE_ENV = "PADDLE_HANG_DEADLINE_S"
REPORT_NAME = "hang_report.json"

_HB_RE = re.compile(r"^heartbeat\.(\d+)\.json$")


def _pid_alive(pid):
    try:
        os.kill(int(pid), 0)
        return True
    except ProcessLookupError:
        return False
    except (PermissionError, OSError, ValueError, OverflowError):
        return True  # can't prove it's dead: keep treating it as live


def heartbeat_path(d, rank):
    return os.path.join(d, f"heartbeat.{rank}.json")


def stacks_path(d, rank):
    return os.path.join(d, f"stacks.{rank}.txt")


def spans_path(d, rank):
    return os.path.join(d, f"spans.{rank}.jsonl")


class Heartbeat:
    """Per-rank liveness file + SIGUSR1 stack-dump hook."""

    def __init__(self, directory, rank, install_faulthandler=True):
        os.makedirs(directory, exist_ok=True)
        self.dir = directory
        self.rank = int(rank)
        from ..utils.envs import env_int

        self.generation = env_int("PADDLE_ELASTIC_GENERATION", 0)
        self.path = heartbeat_path(directory, self.rank)
        self._stack_f = None
        if install_faulthandler and hasattr(signal, "SIGUSR1"):
            import faulthandler

            try:
                # keep the handle open for the process lifetime: faulthandler
                # writes to the raw fd from a signal context, repeated dumps
                # append — the watchdog reads the accumulated file
                self._stack_f = open(stacks_path(directory, self.rank), "w")
                faulthandler.register(signal.SIGUSR1, file=self._stack_f,
                                      all_threads=True)
            except (ValueError, OSError, RuntimeError):
                # non-main thread / exotic platform: liveness still works,
                # only the stack dump is lost
                if self._stack_f is not None:
                    self._stack_f.close()
                    self._stack_f = None
        self.beat(step=None, phase="init")

    def beat(self, step=None, **extra):
        """Atomic heartbeat write (tmp + rename): the watchdog never reads a
        torn json. Each beat is stamped with the elastic generation so a
        re-formed job's watchdog can fence out old-incarnation stragglers."""
        rec = {"rank": self.rank, "pid": os.getpid(), "step": step,
               "time": time.time(), "generation": self.generation}
        if extra:
            rec.update(extra)
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(rec, f)
        os.replace(tmp, self.path)

    def close(self):
        if self._stack_f is not None:
            import faulthandler

            try:
                faulthandler.unregister(signal.SIGUSR1)
            except Exception:
                pass
            try:
                self._stack_f.close()
            except Exception:
                pass
            self._stack_f = None


#: cached process heartbeat: False = env unset (permanent no-op),
#: None = not yet resolved, Heartbeat = beating
_process_hb = None
_last_beat_t = 0.0
#: liveness granularity: sub-second step loops don't need sub-second
#: heartbeat writes (deadlines are seconds); throttling caps the hot-loop
#: file I/O at ~2 writes/s no matter how fast steps are
BEAT_INTERVAL_S = 0.5


def _env_heartbeat():
    """Resolve (once) the process heartbeat from PADDLE_TELEMETRY_DIR."""
    global _process_hb
    hb = _process_hb
    if hb is not None:
        return hb
    d = env_str(DIR_ENV)
    if not d:
        _process_hb = False
        return False
    rank = env_str("PADDLE_TRAINER_ID",
                   os.environ.get("RANK", "0")) or "0"
    try:
        hb = _process_hb = Heartbeat(d, int(rank))
    except (OSError, ValueError):
        hb = _process_hb = False
    return hb


def arm_from_env():
    """Register this process with the watchdog BEFORE the first step: writes
    the phase='init' beat (step=None), which the watchdog holds to the
    longer startup deadline — so a rank that wedges in rendezvous, mesh
    setup, or its first compile/collective still gets diagnosed instead of
    never appearing in the heartbeat directory at all. Called from
    TrainStep construction; free when telemetry is not configured."""
    _env_heartbeat()


def note_phase(phase):
    """Stamp a step=None phase beat before known LONG blocking host work
    (synchronous checkpoint save, resume load): the watchdog holds step-less
    beats to the startup deadline, so a legitimate 90s save can't read as a
    hang and burn the fire-once report. The next maybe_beat restores normal
    step-deadline monitoring. Bypasses the beat throttle (rare calls)."""
    hb = _env_heartbeat()
    if hb is False:
        return
    try:
        hb.beat(step=None, phase=phase)
    except OSError:
        pass


def maybe_beat(step=None):
    """The train-loop hook: one cached env check when telemetry is not
    configured; at most ~2 small atomic file writes per second when it is.
    Fleet snapshot publication (ISSUE 11) piggybacks on the same cadence:
    inside the throttled block, so the disabled path stays one check."""
    global _last_beat_t
    hb = _env_heartbeat()
    if hb is False:
        return
    now = time.monotonic()
    if now - _last_beat_t < BEAT_INTERVAL_S:
        return
    _last_beat_t = now
    try:
        hb.beat(step=step)
    except OSError:
        pass  # a full disk must not kill the training step
    from . import fleet

    fleet.maybe_publish(step)


def _reset_process_heartbeat():
    """Test hook: forget the cached heartbeat so env changes take effect."""
    global _process_hb, _last_beat_t
    if isinstance(_process_hb, Heartbeat):
        _process_hb.close()
    _process_hb = None
    _last_beat_t = 0.0
    from . import fleet

    fleet._reset_process_publisher()


class HangWatchdog:
    """Monitor thread over a telemetry directory's heartbeat files."""

    def __init__(self, directory, deadline_s, interval_s=None, on_hang=None,
                 last_n_spans=32, signal_grace_s=0.75,
                 startup_deadline_s=None, signal_stalled=None,
                 kill_grace_s=30.0, generation=0):
        self.dir = directory
        self.deadline_s = float(deadline_s)
        # elastic generation fencing (ISSUE 9): the launcher bumps this on
        # every shrink/grow re-form; heartbeats stamped by an OLDER
        # generation are invisible — a straggler from a dead incarnation
        # must not read as a live (or hung) rank of the new world
        self.generation = int(generation)
        # ranks that have only init-beaten (step=None: still in rendezvous /
        # first compile) get a longer leash — first dispatches legitimately
        # take many times a steady-state step
        self.startup_deadline_s = (float(startup_deadline_s)
                                   if startup_deadline_s is not None
                                   else 10.0 * self.deadline_s)
        self.interval_s = interval_s if interval_s is not None else max(
            0.2, self.deadline_s / 4.0)
        self.on_hang = on_hang
        self.last_n_spans = int(last_n_spans)
        self.signal_grace_s = float(signal_grace_s)
        # optional escalation AFTER the diagnosis is safely committed: send
        # this signal (typically SIGTERM) to each STALLED rank, so its
        # GracefulPreemption handler runs the emergency-save hooks
        # (checkpoint/recovery.py — Tier-0 flush to durable under the grace
        # deadline) and exits PREEMPTED, letting the launcher restart it
        # into the recovery ladder. A rank wedged too hard to ever reach a
        # checkpoint boundary (stuck inside a native collective) consumes
        # neither the flag nor the flush — so after kill_grace_s a
        # still-alive stalled pid is SIGKILLed: the launcher then restarts
        # the crash and recovery resolves from a peer or durable tier.
        self.signal_stalled = signal_stalled
        self.kill_grace_s = float(kill_grace_s)
        self.report_path = os.path.join(directory, REPORT_NAME)
        self.fired = threading.Event()
        self._stop = threading.Event()
        self._thread = None
        # staleness is measured from max(last beat, OUR start): a heartbeat
        # left over from a previous incarnation of the job (reused log_dir)
        # must not fire the first scan — it only counts as stalled once a
        # full deadline has elapsed on THIS watchdog's watch without a fresh
        # beat. The launcher additionally deletes a rank's heartbeat file
        # when it restarts that rank (see controller.watch), so restart
        # recompile time cannot masquerade as a hang.
        self._start_time = time.time()

    # ---- lifecycle --------------------------------------------------------
    def start(self):
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="paddle-hang-watchdog")
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)

    def _run(self):
        while not self._stop.is_set():
            try:
                if self.scan_once():
                    if self.signal_stalled is None:
                        return  # diagnostic mode: fire once, the report IS
                        # the product
                    # escalation mode keeps watching: the preempted/killed
                    # ranks restart and may hang AGAIN — re-arm with a fresh
                    # leash (restarted ranks get the full startup deadline;
                    # the launcher deleted their heartbeats on restart). The
                    # leash starts AFTER the kill grace window, so a rank
                    # still dying under SIGTERM→SIGKILL is not re-diagnosed,
                    # re-signaled, and re-reaped every deadline tick.
                    self._start_time = time.time() + self.kill_grace_s
            except Exception:
                pass  # a watchdog crash must never take the launcher down
            self._stop.wait(self.interval_s)

    # ---- scanning ---------------------------------------------------------
    def _read_heartbeats(self):
        hbs = {}
        try:
            names = os.listdir(self.dir)
        except OSError:
            return hbs
        for name in names:
            m = _HB_RE.match(name)
            if not m:
                continue
            try:
                with open(os.path.join(self.dir, name)) as f:
                    hb = json.load(f)
            except (OSError, ValueError):
                continue  # racing a writer: next tick sees it
            if int(hb.get("generation", self.generation)) < self.generation:
                continue  # old-generation straggler: fenced out
            hbs[int(m.group(1))] = hb
        return hbs

    def scan_once(self):
        """One poll; returns the report path if a hang was diagnosed."""
        hbs = self._read_heartbeats()
        if not hbs:
            return None
        now = time.time()
        stalled = {}
        for r, hb in hbs.items():
            limit = (self.startup_deadline_s if hb.get("step") is None
                     else self.deadline_s)
            stale = now - max(hb.get("time", 0), self._start_time)
            if stale <= limit:
                continue
            # a silent heartbeat with a DEAD pid is an exited rank, not a
            # hang (clean early finishers, crashes the launcher already
            # handles) — firing on it would burn the one report
            pid = hb.get("pid")
            if pid and not _pid_alive(pid):
                continue
            stalled[r] = stale
        if not stalled:
            return None
        self._dump(hbs, stalled)
        return self.report_path

    def _dump(self, hbs, stalled):
        # fresh stacks from EVERY rank — the straggler's peers show what the
        # collective was waiting on
        for hb in hbs.values():
            pid = hb.get("pid")
            if pid and hasattr(signal, "SIGUSR1"):
                try:
                    os.kill(pid, signal.SIGUSR1)
                except (ProcessLookupError, PermissionError, OSError):
                    pass  # dead rank: its last heartbeat tells the story
        time.sleep(self.signal_grace_s)
        now = time.time()
        ranks = {}
        for r, hb in sorted(hbs.items()):
            ranks[str(r)] = {
                "heartbeat": hb,
                "stale_s": now - hb.get("time", 0),
                "stalled": r in stalled,
                "stacks": self._read_text(stacks_path(self.dir, r)),
                "last_spans": self._tail_spans(spans_path(self.dir, r)),
            }
            # mid-compile diagnosis (ISSUE 8): the compile ledger writes a
            # compiling.<rank>.json breadcrumb while a compile is in
            # flight — a rank wedged inside XLA shows its program key and
            # elapsed compile time instead of an opaque native stack
            comp = self._read_compiling(r, now)
            if comp is not None:
                ranks[str(r)]["compiling"] = comp
        report = {
            "detected_at": now,
            "deadline_s": self.deadline_s,
            "generation": self.generation,
            "stalled_ranks": sorted(stalled),
            "stalled_for_s": {str(r): s for r, s in stalled.items()},
            "ranks": ranks,
        }
        tmp = self.report_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(report, f, indent=2)
        os.replace(tmp, self.report_path)
        from .metrics import registry

        registry.counter("fault.watchdog.hang").inc()
        # flight-record the hang (ISSUE 13): the bundle carries what the
        # full report cannot — THIS process's dynamics window, span ring
        # and compile tail — committed into the watched telemetry dir
        from . import flightrec

        flightrec.record(
            "hang", payload={"stalled_ranks": sorted(stalled),
                             "stalled_for_s": {str(r): round(s, 3)
                                               for r, s in stalled.items()},
                             "report": self.report_path},
            directory=self.dir)
        if self.signal_stalled is not None:
            pids = []
            for r in stalled:
                pid = hbs.get(r, {}).get("pid")
                if not pid:
                    continue
                try:
                    os.kill(pid, self.signal_stalled)
                    registry.counter("fault.watchdog.preempt").inc()
                    pids.append(pid)
                except (ProcessLookupError, PermissionError, OSError):
                    pass
            if pids:
                # escalation backstop for ranks too wedged to honor the
                # preemption flag: still alive after the grace window →
                # SIGKILL, so the launcher's restart + recovery ladder take
                # over instead of the job staying hung forever
                def _reap(pids=pids):
                    time.sleep(self.kill_grace_s)
                    for pid in pids:
                        if _pid_alive(pid):
                            try:
                                os.kill(pid, signal.SIGKILL)
                                registry.counter(
                                    "fault.watchdog.killed").inc()
                            except (ProcessLookupError, PermissionError,
                                    OSError):
                                pass

                threading.Thread(target=_reap, daemon=True,
                                 name="paddle-hang-reaper").start()
        self.fired.set()
        if self.on_hang is not None:
            try:
                self.on_hang(self.report_path)
            except Exception:
                pass

    def _read_compiling(self, rank, now):
        """The rank's in-flight-compile breadcrumb, with elapsed times
        stamped by the reader; None when no compile is in flight."""
        from .compilemem import compiling_path

        try:
            with open(compiling_path(self.dir, rank)) as f:
                rec = json.load(f)
        except (OSError, ValueError):
            return None
        for a in rec.get("active", []):
            if "started_at" in a:
                a["elapsed_s"] = round(now - a["started_at"], 3)
        return rec

    @staticmethod
    def _read_text(path, limit=1 << 20):
        try:
            with open(path, errors="replace") as f:
                return f.read(limit) or None
        except OSError:
            return None

    def _tail_spans(self, path):
        try:
            with open(path, "rb") as f:
                f.seek(0, os.SEEK_END)
                size = f.tell()
                f.seek(max(0, size - (1 << 18)))
                lines = f.read().decode(errors="replace").splitlines()
        except OSError:
            return []
        out = []
        for line in lines[-self.last_n_spans:]:
            try:
                out.append(json.loads(line))
            except ValueError:
                continue
        return out
