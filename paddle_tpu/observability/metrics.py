"""Metrics registry: counters, gauges, fixed-bucket histograms.

Unifies what used to be scattered (utils/metrics_bus.EventCounters, the
serving engine's ad-hoc ``stats`` dict, per-script timing prints) behind one
process-wide registry that every layer publishes into and that dumps two
ways: JSONL (one record per metric, machine-diffable across runs) and a
Prometheus-style text snapshot (scrape-ready, the operator-facing format the
TPU-vs-GPU serving comparison in PAPERS.md reports against).

Cost model (the same contract as testing/chaos.py): publishing is hot-path
code. A counter ``inc`` is one lock + one float add; a histogram ``observe``
is a bisect over a small tuple + two adds. Nothing here allocates per call,
formats strings, or touches the filesystem — rendering happens only in the
explicitly-invoked dump paths.
"""
import bisect
import json
import os
import re
import threading

__all__ = ["Counter", "Gauge", "Histogram", "MetricsRegistry", "registry",
           "DEFAULT_BUCKETS", "metric_key"]

#: latency-oriented default bucket upper bounds, in seconds (an implicit
#: +inf bucket is always appended): 0.5ms .. 60s covers a dispatch through a
#: full checkpoint write.
DEFAULT_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                   0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0)


class Counter:
    """Monotonic named counter. ``inc`` only; ``reset`` exists for tests and
    for the EventCounters compat shim's prefix reset."""

    __slots__ = ("name", "help", "family", "labels", "_lock", "_value")

    def __init__(self, name, help="", family=None, labels=None):
        self.name = name
        self.help = help
        self.family = family or name
        self.labels = labels
        self._lock = threading.Lock()
        self._value = 0

    def inc(self, n=1):
        with self._lock:
            self._value += n

    @property
    def value(self):
        return self._value

    def reset(self):
        with self._lock:
            self._value = 0


class Gauge:
    """Last-value gauge. Also tracks the high-water mark (``hwm``) since the
    last reset — queue depth / slot occupancy are only interesting at their
    peaks, and a scrape-time gauge alone misses transients."""

    __slots__ = ("name", "help", "family", "labels", "_lock", "_value", "_hwm")

    def __init__(self, name, help="", family=None, labels=None):
        self.name = name
        self.help = help
        self.family = family or name
        self.labels = labels
        self._lock = threading.Lock()
        self._value = 0.0
        self._hwm = 0.0

    def set(self, v):
        v = float(v)
        with self._lock:
            self._value = v
            if v > self._hwm:
                self._hwm = v

    def inc(self, n=1):
        with self._lock:
            self._value += n
            if self._value > self._hwm:
                self._hwm = self._value

    def dec(self, n=1):
        self.inc(-n)

    @property
    def value(self):
        return self._value

    @property
    def hwm(self):
        return self._hwm

    def reset(self):
        with self._lock:
            self._value = 0.0
            self._hwm = 0.0


class Histogram:
    """Fixed-bucket histogram (Prometheus-style cumulative export).

    ``bounds`` are the finite bucket upper limits; an implicit +inf bucket
    catches the tail. Per-``observe`` cost is a bisect over the bounds tuple
    plus two adds under the lock — no per-call allocation.
    """

    __slots__ = ("name", "help", "family", "labels", "bounds", "_lock",
                 "_counts", "_sum", "_count")

    def __init__(self, name, buckets=DEFAULT_BUCKETS, help="", family=None,
                 labels=None):
        self.name = name
        self.help = help
        self.family = family or name
        self.labels = labels
        self.bounds = tuple(sorted(float(b) for b in buckets))
        if not self.bounds:
            raise ValueError("histogram needs at least one bucket bound")
        self._lock = threading.Lock()
        self._counts = [0] * (len(self.bounds) + 1)  # last = +inf
        self._sum = 0.0
        self._count = 0

    def observe(self, v):
        v = float(v)
        i = bisect.bisect_left(self.bounds, v)
        with self._lock:
            self._counts[i] += 1
            self._sum += v
            self._count += 1

    @property
    def count(self):
        return self._count

    @property
    def sum(self):
        return self._sum

    @property
    def mean(self):
        return self._sum / self._count if self._count else 0.0

    def bucket_counts(self):
        """Raw (non-cumulative) per-bucket counts, +inf bucket last."""
        with self._lock:
            return list(self._counts)

    def cumulative(self):
        """[(upper_bound, cumulative_count)], ending with (inf, count)."""
        out, cum = [], 0
        counts = self.bucket_counts()
        for b, c in zip(self.bounds, counts[:-1]):
            cum += c
            out.append((b, cum))
        out.append((float("inf"), cum + counts[-1]))
        return out

    def quantile(self, q):
        """Bucket-resolution quantile estimate: the smallest upper bound
        whose cumulative count reaches q*count (inf if it lands in the
        overflow bucket). Good enough for p50/p99 dashboards; exact values
        need a trace, not a histogram."""
        if not 0 <= q <= 1:
            raise ValueError(f"quantile {q} outside [0, 1]")
        if not self._count:
            return 0.0
        target = q * self._count
        for bound, cum in self.cumulative():
            if cum >= target:
                return bound
        return float("inf")

    def reset(self):
        with self._lock:
            self._counts = [0] * (len(self.bounds) + 1)
            self._sum = 0.0
            self._count = 0


_PROM_SANITIZE = re.compile(r"[^a-zA-Z0-9_:]")


def _prom_name(name):
    n = _PROM_SANITIZE.sub("_", name)
    return "_" + n if n[:1].isdigit() else n


def _escape_label(v):
    """Prometheus label-value escaping: backslash, double quote, newline."""
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _escape_help(s):
    return str(s).replace("\\", "\\\\").replace("\n", "\\n")


def _label_suffix(labels, extra=None):
    """``{k="v",...}`` rendered suffix (labels sorted, values escaped);
    empty string when there is nothing to render."""
    pairs = []
    if labels:
        pairs.extend(sorted(labels.items()))
    if extra:
        pairs.extend(extra)
    if not pairs:
        return ""
    inner = ",".join(f'{k}="{_escape_label(v)}"' for k, v in pairs)
    return "{" + inner + "}"


def metric_key(name, labels=None):
    """Registry key for a (family, labels) pair — the family name alone when
    unlabeled, ``family{k="v",...}`` otherwise (sorted, escaped — two label
    dicts that render the same ARE the same series)."""
    if not labels:
        return name
    return name + _label_suffix(labels)


class MetricsRegistry:
    """Process-wide name -> metric map. Metric creation is idempotent
    (``counter("x")`` twice returns the same object); re-registering a name
    as a different type is a bug and raises. ``labels={...}`` registers one
    series of a metric FAMILY (keyed ``name{k="v"}``): the Prometheus
    rendering groups series under one ``# TYPE``/``# HELP`` header, which is
    what real scrapers require (a per-label-value metric NAME breaks every
    aggregation over the family)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics = {}
        self._family_types = {}

    def _get_or_create(self, name, cls, labels=None, **kw):
        key = metric_key(name, labels)
        m = self._metrics.get(key)
        if m is not None:
            if not isinstance(m, cls):
                raise ValueError(
                    f"metric {key!r} already registered as "
                    f"{type(m).__name__}, not {cls.__name__}")
            return m
        with self._lock:
            m = self._metrics.get(key)
            if m is None:
                fcls = self._family_types.setdefault(name, cls)
                if fcls is not cls:
                    # a family mixing types renders an unparseable exposition
                    raise ValueError(
                        f"metric family {name!r} already registered as "
                        f"{fcls.__name__}, not {cls.__name__}")
                m = self._metrics[key] = cls(
                    key, family=name,
                    labels=dict(labels) if labels else None, **kw)
            elif not isinstance(m, cls):
                raise ValueError(
                    f"metric {key!r} already registered as "
                    f"{type(m).__name__}, not {cls.__name__}")
            return m

    def counter(self, name, help="", labels=None):
        return self._get_or_create(name, Counter, help=help, labels=labels)

    def gauge(self, name, help="", labels=None):
        return self._get_or_create(name, Gauge, help=help, labels=labels)

    def histogram(self, name, buckets=DEFAULT_BUCKETS, help="", labels=None):
        return self._get_or_create(name, Histogram, buckets=buckets,
                                   help=help, labels=labels)

    def get(self, name, labels=None):
        """Existing metric or None — never creates."""
        return self._metrics.get(metric_key(name, labels))

    def remove(self, name, labels=None):
        """Delete one series (the fleet aggregator retiring a departed
        rank's labeled gauge — a rank that left the world must vanish
        from the exposition, not report its last score forever). The
        family's type registration is kept. Returns True if removed."""
        with self._lock:
            return self._metrics.pop(metric_key(name, labels),
                                     None) is not None

    def names(self, prefix=""):
        with self._lock:
            return sorted(n for n in self._metrics if n.startswith(prefix))

    # ---- snapshots ---------------------------------------------------------
    def snapshot(self, prefix=""):
        """{name: plain-python value} — counters/gauges as numbers,
        histograms as {count, sum, mean, buckets}. Zero-valued counters are
        omitted (the EventCounters contract: 'faults' is only present when
        something actually fired)."""
        out = {}
        with self._lock:
            items = list(self._metrics.items())
        for name, m in items:
            if not name.startswith(prefix):
                continue
            if isinstance(m, Counter):
                if m.value:
                    out[name] = m.value
            elif isinstance(m, Gauge):
                out[name] = {"value": m.value, "hwm": m.hwm}
            else:
                out[name] = {
                    "count": m.count, "sum": m.sum, "mean": m.mean,
                    "buckets": [[b, c] for b, c in m.cumulative()],
                }
        return out

    def export(self, prefixes=None):
        """Merge-ready structured series dump (the fleet snapshot payload,
        ISSUE 11): unlike :meth:`snapshot`, every record carries the family,
        type, labels, and — for histograms — the bucket BOUNDS alongside the
        raw counts, so a cross-rank aggregator can rebuild exact mergeable
        metrics instead of lossy summaries. Zero-valued counters and empty
        histograms are omitted (the snapshot bound matters more than
        registering silence)."""
        with self._lock:
            items = sorted(self._metrics.items())
        out = []
        for name, m in items:
            if prefixes and not m.family.startswith(tuple(prefixes)):
                continue
            rec = {"name": name, "family": m.family, "labels": m.labels,
                   "help": m.help}
            if isinstance(m, Counter):
                if not m.value:
                    continue
                rec["type"] = "counter"
                rec["value"] = m.value
            elif isinstance(m, Gauge):
                rec["type"] = "gauge"
                rec["value"] = m.value
                rec["hwm"] = m.hwm
            else:
                if not m.count:
                    continue
                rec["type"] = "histogram"
                rec["bounds"] = list(m.bounds)
                rec["counts"] = m.bucket_counts()
                rec["sum"] = m.sum
                rec["count"] = m.count
            out.append(rec)
        return out

    def load_series(self, rec, extra_labels=None):
        """Recreate one :meth:`export` record in THIS registry, optionally
        widening its label set (the aggregator adds ``rank=``/``replica=``
        so merged families stay one ``# TYPE`` with per-source series).
        Returns the metric, or None when the record's family is already
        registered here as a different type (conflicting sources must not
        kill a merge)."""
        labels = dict(rec.get("labels") or {})
        if extra_labels:
            labels.update(extra_labels)
        kind = rec.get("type")
        help_ = rec.get("help") or ""
        family = rec["family"]
        try:
            if kind == "counter":
                m = self.counter(family, help=help_, labels=labels)
                m.inc(rec.get("value", 0))
            elif kind == "gauge":
                m = self.gauge(family, help=help_, labels=labels)
                # set() tracks the high-water mark: replay hwm first so the
                # merged gauge carries the source's peak, then the live value
                m.set(rec.get("hwm", rec.get("value", 0.0)))
                m.set(rec.get("value", 0.0))
            elif kind == "histogram":
                m = self.histogram(family, buckets=rec["bounds"],
                                   help=help_, labels=labels)
                counts = list(rec.get("counts") or ())
                with m._lock:
                    for i, c in enumerate(counts[:len(m._counts)]):
                        m._counts[i] += int(c)
                    m._sum += float(rec.get("sum", 0.0))
                    m._count += int(rec.get("count", 0))
            else:
                return None
        except ValueError:
            return None
        return m

    def dump_jsonl(self, path, extra=None):
        """Append one JSON record per metric (plus the optional ``extra``
        dict on each line — rank/step stamps). Atomic enough for a telemetry
        sidecar: one write + flush per call."""
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        base = dict(extra) if extra else {}
        lines = []
        for name, val in self.snapshot().items():
            rec = dict(base)
            rec["name"] = name
            rec["value"] = val
            lines.append(json.dumps(rec))
        with open(path, "a") as f:
            f.write("\n".join(lines) + ("\n" if lines else ""))
            f.flush()

    def to_prometheus(self):
        """Prometheus text exposition format (the text a real scraper must
        parse — asserted against a strict parser in tests): dots in metric
        names become underscores, every family gets ``# HELP``/``# TYPE``
        headers and contiguous samples, label values are escaped, and
        histograms render the standard cumulative ``_bucket{le=...}`` series
        (``+Inf`` included) plus ``_sum``/``_count``."""
        lines = []
        with self._lock:
            items = sorted(self._metrics.items())
        families = {}
        for _, m in items:
            families.setdefault(m.family, []).append(m)

        def _header(pname, ms, kind):
            help_text = next((m.help for m in ms if m.help), "")
            if help_text:
                lines.append(f"# HELP {pname} {_escape_help(help_text)}")
            lines.append(f"# TYPE {pname} {kind}")

        for family in sorted(families):
            ms = families[family]
            pname = _prom_name(family)
            if isinstance(ms[0], Counter):
                _header(pname, ms, "counter")
                for m in ms:
                    lines.append(f"{pname}{_label_suffix(m.labels)} {m.value}")
            elif isinstance(ms[0], Gauge):
                _header(pname, ms, "gauge")
                for m in ms:
                    lines.append(f"{pname}{_label_suffix(m.labels)} {m.value}")
                # the high-water mark is its own gauge family (a second
                # sample under the same name would be a duplicate series)
                lines.append(f"# TYPE {pname}_hwm gauge")
                for m in ms:
                    lines.append(
                        f"{pname}_hwm{_label_suffix(m.labels)} {m.hwm}")
            else:
                _header(pname, ms, "histogram")
                for m in ms:
                    for bound, cum in m.cumulative():
                        le = "+Inf" if bound == float("inf") else repr(bound)
                        suffix = _label_suffix(m.labels, extra=[("le", le)])
                        lines.append(f"{pname}_bucket{suffix} {cum}")
                    lines.append(
                        f"{pname}_sum{_label_suffix(m.labels)} {m.sum}")
                    lines.append(
                        f"{pname}_count{_label_suffix(m.labels)} {m.count}")
        return "\n".join(lines) + ("\n" if lines else "")

    def reset(self, prefix=""):
        """Zero every metric under ``prefix`` (objects and handles stay
        valid — only values reset)."""
        with self._lock:
            items = list(self._metrics.items())
        for name, m in items:
            if name.startswith(prefix):
                m.reset()


#: the process-wide singleton every layer publishes into
registry = MetricsRegistry()
