"""Anomaly flight recorder (ISSUE 13): commit the evidence WHILE it exists.

When a training anomaly fires — a loss-spike z past threshold, a
non-finite skip, a persistent-straggler alert, an SLO page, a hang
diagnosis — the forensics that explain it (the dynamics window, the recent
span ring, the compile-ledger tail) are all in volatile process state and
gone by the time a human looks. The flight recorder closes that loop: any
trigger calls :func:`record`, which commits one bounded, deduped,
rate-limited atomic bundle ``<telemetry>/flight/<trigger>_<step>.json``
(same tmp+rename contract as the OOM/hang reports — a reader never sees a
torn file, and committing never raises into the training loop).

Bundle contents: trigger identity + payload, the dynamics window
(:func:`dynamics.flight_block`), the last-N host spans, the compile-ledger
tail, the goodput split, and the ``train.*``/``fault.*`` metric snapshot.

Bounding (all env-tunable):

- **rate limit** — per-trigger: a second bundle of the same trigger within
  ``PADDLE_FLIGHTREC_MIN_INTERVAL_S`` is suppressed (counted, not
  written), so a non-finite storm produces ONE bundle per window, not one
  per step;
- **dedup** — an exact ``(trigger, step)`` repeat never writes twice;
- **cap** — at most ``PADDLE_FLIGHTREC_MAX`` bundles per recorder; past it
  everything is suppressed (the first evidence is the valuable evidence).

**xprof capture registry.** The recorder also owns the process's ONE
on-demand ``jax.profiler`` capture: :func:`arm_capture` schedules a trace
of the next K train steps (``/profilez?steps=K`` live, or automatically on
any flight trigger when ``PADDLE_FLIGHTREC_CAPTURE_STEPS`` > 0), the
train-step epilogue hook :func:`maybe_capture_step` starts/advances/stops
it, and every capture — including the legacy
``profiler.start_xprof_trace`` API, which now delegates here — is ledgered
in a bounded history. The ``profiler-capture`` analysis rule forbids raw
``jax.profiler.start_trace/stop_trace`` anywhere else in the package, so
no profile artifact can be taken outside this registry. jax is imported
lazily only when a capture actually starts — the observability package
stays stdlib-only.

Cost: with nothing armed, :func:`maybe_capture_step` is one module-global
None check; :func:`record` is only ever called from anomaly paths.
"""
import json
import os
import threading
import time

from ..utils.envs import env_float, env_int, env_str
from .metrics import registry as _registry

__all__ = ["FlightRecorder", "record", "recorder", "report",
           "arm_capture", "disarm_capture", "maybe_capture_step",
           "start_capture", "stop_capture", "capture_status",
           "FLIGHT_DIR", "MAX_ENV", "MIN_INTERVAL_ENV", "CAPTURE_STEPS_ENV"]

#: subdirectory of the telemetry dir holding the bundles
FLIGHT_DIR = "flight"
#: bundle cap per recorder — past it, suppressed (first evidence wins)
MAX_ENV = "PADDLE_FLIGHTREC_MAX"
#: per-trigger rate limit between committed bundles, seconds
MIN_INTERVAL_ENV = "PADDLE_FLIGHTREC_MIN_INTERVAL_S"
#: >0 arms a K-step xprof capture automatically on every committed bundle
CAPTURE_STEPS_ENV = "PADDLE_FLIGHTREC_CAPTURE_STEPS"

_SAFE = "abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789_-"


def _sanitize(s):
    return "".join(c if c in _SAFE else "-" for c in str(s)) or "trigger"


def _rank():
    return env_str("PADDLE_TRAINER_ID", os.environ.get("RANK", "0")) or "0"


class FlightRecorder:
    """One directory's bundle writer with its dedup/rate-limit/cap state."""

    def __init__(self, directory=None, max_bundles=None, min_interval_s=None,
                 capture_steps=None):
        # same fallback as the OOM report: a recorder is ALWAYS available
        self.dir = os.path.join(
            directory or env_str("PADDLE_TELEMETRY_DIR") or "telemetry",
            FLIGHT_DIR)
        self.max_bundles = (int(max_bundles) if max_bundles is not None
                            else env_int(MAX_ENV, 16))
        self.min_interval_s = (float(min_interval_s)
                               if min_interval_s is not None
                               else env_float(MIN_INTERVAL_ENV, 30.0))
        self.capture_steps = (int(capture_steps) if capture_steps is not None
                              else env_int(CAPTURE_STEPS_ENV, 0))
        self._lock = threading.Lock()
        self._last_t = {}      # trigger -> monotonic time of last commit
        self._committed = []   # [(trigger, step, path)]
        self._seen = set()     # {(trigger, step)} — step-keyed dedup only
        self._seq = 0          # per-recorder sequence for stepless names
        self.suppressed = 0

    # ---- bundle building ---------------------------------------------------
    def _build(self, trigger, step, payload):
        """The evidence bundle. Each block is best-effort: a dying
        subsystem must not cost the others their last words."""
        bundle = {
            "kind": "flight_record",
            "trigger": trigger,
            "step": step,
            "time": time.time(),
            "rank": _rank(),
            "pid": os.getpid(),
            "payload": payload or {},
        }
        try:
            from . import dynamics as _dynamics

            bundle["dynamics"] = _dynamics.flight_block()
        except Exception as e:
            bundle["dynamics"] = {"error": f"{type(e).__name__}: {e}"}
        try:
            from . import tracing as _tracing

            bundle["spans"] = _tracing.last_spans(64)
        except Exception as e:
            bundle["spans"] = {"error": f"{type(e).__name__}: {e}"}
        try:
            from . import compilemem as _compilemem

            bundle["compile"] = _compilemem.ledger.report(recent=16)
        except Exception as e:
            bundle["compile"] = {"error": f"{type(e).__name__}: {e}"}
        try:
            from . import goodput as _goodput

            bundle["goodput"] = _goodput.report()
        except Exception as e:
            bundle["goodput"] = {"error": f"{type(e).__name__}: {e}"}
        try:
            bundle["metrics"] = {
                **_registry.snapshot(prefix="train."),
                **_registry.snapshot(prefix="fault."),
            }
        except Exception as e:
            bundle["metrics"] = {"error": f"{type(e).__name__}: {e}"}
        try:
            bundle["capture"] = capture_status()
        except Exception as e:
            bundle["capture"] = {"error": f"{type(e).__name__}: {e}"}
        return bundle

    def record(self, trigger, step=None, payload=None, force=False):
        """Commit one bundle; returns its path, or None when suppressed
        (dedup / rate limit / cap) or the write failed. Never raises."""
        trigger = _sanitize(trigger)
        now = time.monotonic()
        with self._lock:
            if not force:
                # exact-repeat dedup is STEP-KEYED only: a stepless
                # trigger (hang, slo_page, straggler) must stay eligible
                # after the rate window — (trigger, None) in the seen set
                # would suppress every later occurrence forever
                if step is not None and (trigger, step) in self._seen:
                    self.suppressed += 1
                    self._count_suppressed()
                    return None
                last = self._last_t.get(trigger)
                if last is not None and now - last < self.min_interval_s:
                    self.suppressed += 1
                    self._count_suppressed()
                    return None
                if len(self._committed) >= self.max_bundles:
                    self.suppressed += 1
                    self._count_suppressed()
                    return None
            # reserve the slot under the lock; build/write outside it
            self._last_t[trigger] = now
            if step is not None:
                self._seen.add((trigger, step))
            self._seq += 1
            seq = self._seq
        # stepless bundles get a per-recorder sequence suffix: a second
        # hang an hour later must not overwrite the first one's evidence
        name = (f"{trigger}_{step}.json" if step is not None
                else f"{trigger}_n{seq}.json")
        path = os.path.join(self.dir, name)
        try:
            bundle = self._build(trigger, step, payload)
            os.makedirs(self.dir, exist_ok=True)
            tmp = path + f".tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(bundle, f, indent=1, default=str)
            os.replace(tmp, path)
        except Exception:
            # a full disk must not take the train loop down — and a
            # FAILED write must not consume the dedup/rate-limit slot:
            # no evidence landed, so the trigger stays eligible
            with self._lock:
                self._seen.discard((trigger, step))
                if self._last_t.get(trigger) == now:
                    del self._last_t[trigger]
            return None
        with self._lock:
            self._committed.append((trigger, step, path))
        _registry.counter(
            "flightrec.bundles",
            help="flight-record bundles committed by this process").inc()
        if self.capture_steps > 0:
            # evidence escalation: the NEXT K steps get an xprof capture
            arm_capture(self.capture_steps, trigger=trigger)
        return path

    def _count_suppressed(self):
        _registry.counter(
            "flightrec.suppressed",
            help="flight-record triggers suppressed by dedup, the "
                 "per-trigger rate limit, or the bundle cap").inc()

    def status(self):
        with self._lock:
            return {
                "dir": self.dir,
                "max_bundles": self.max_bundles,
                "min_interval_s": self.min_interval_s,
                "auto_capture_steps": self.capture_steps,
                "committed": [
                    {"trigger": t, "step": s, "path": p}
                    for t, s, p in self._committed],
                "suppressed": self.suppressed,
            }


#: recorder per directory (the watchdog records into ITS telemetry dir,
#: which may differ from this process's env) — dedup state is per dir
_recorders = {}
_recorders_lock = threading.Lock()


def recorder(directory=None):
    key = directory or env_str("PADDLE_TELEMETRY_DIR") or "telemetry"
    with _recorders_lock:
        rec = _recorders.get(key)
        if rec is None:
            rec = _recorders[key] = FlightRecorder(directory=key)
        return rec


def record(trigger, step=None, payload=None, directory=None, force=False):
    """Module-level convenience: commit a bundle via the (cached) recorder
    for ``directory`` (default: this process's telemetry dir). A process
    with NO telemetry dir configured records nothing — the trigger seams
    (nf sentinel, SLO monitor, fleet aggregator) fire unconditionally,
    and un-armed processes must not sprinkle ``telemetry/`` dirs over
    whatever their cwd happens to be."""
    d = directory or env_str("PADDLE_TELEMETRY_DIR")
    if not d:
        return None
    return recorder(d).record(trigger, step=step, payload=payload,
                              force=force)


def report():
    """The /dynamicsz ``flight`` block: every live recorder's status."""
    with _recorders_lock:
        recs = list(_recorders.values())
    return [r.status() for r in recs]


def _reset():
    """Test hook: drop recorder caches, any armed capture, and the
    completed-capture history."""
    global _capture
    with _recorders_lock:
        _recorders.clear()
    with _cap_lock:
        _capture = None
        del _cap_history[:]
    _registry.gauge("flightrec.capture_active",
                    help=_CAP_ACTIVE_HELP).set(0)


# ---------------------------------------------------------------------------
# the xprof capture registry
# ---------------------------------------------------------------------------
_cap_lock = threading.Lock()
_capture = None        # the one armed/active capture, or None
_cap_history = []      # bounded completed-capture ledger
_CAP_HISTORY_MAX = 16
_CAP_ACTIVE_HELP = ("an xprof capture is armed or in flight "
                    "(the flight recorder's capture registry)")


def _default_log_dir(trigger):
    base = env_str("PADDLE_TELEMETRY_DIR") or "telemetry"
    return os.path.join(base, "xprof",
                        f"{_sanitize(trigger)}_{int(time.time())}")


def _start_backend(log_dir):
    """THE raw capture site (see the module docstring: the
    ``profiler-capture`` analysis rule forbids this call anywhere else)."""
    import jax  # lazy: only a live capture pays the import

    os.makedirs(log_dir, exist_ok=True)
    jax.profiler.start_trace(log_dir)


def _stop_backend():
    import jax

    jax.profiler.stop_trace()


def arm_capture(steps, log_dir=None, trigger="manual"):
    """Schedule an xprof capture of the next ``steps`` train steps (the
    /profilez?steps=K handler, and the auto-escalation on flight
    triggers). One capture at a time — arming while one is armed/active
    returns its status instead of stacking."""
    global _capture
    try:
        steps = int(steps)
    except (TypeError, ValueError):
        return {"error": f"steps must be an int, got {steps!r}"}
    if steps <= 0:
        return {"error": f"steps must be > 0, got {steps}"}
    with _cap_lock:
        if _capture is not None:
            return {"error": "a capture is already armed or active",
                    "capture": _public(_capture)}
        _capture = {
            "trigger": _sanitize(trigger),
            "steps": steps,
            "steps_left": steps,
            "log_dir": log_dir or _default_log_dir(trigger),
            "manual": False,
            "started": False,
            "armed_at": time.time(),
        }
        _registry.gauge("flightrec.capture_active",
                        help=_CAP_ACTIVE_HELP).set(1)
        return {"armed": True, "capture": _public(_capture)}


def disarm_capture():
    """Cancel an armed-but-not-started capture; stop a started one (the
    backend stop runs OUTSIDE the lock — see :func:`_stop_and_ledger`)."""
    with _cap_lock:
        cap = _capture
        if cap is None:
            return {"disarmed": False}
        if not cap["started"]:
            _clear_locked()
            return {"disarmed": True}
    _stop_and_ledger(cap, aborted=True)
    return {"disarmed": True}


def maybe_capture_step(step=None, n=1):
    """The train-step epilogue hook: one module-global None check when
    nothing is armed. First armed call starts the trace; each later call
    burns ``n`` steps (run_steps dispatches cover n optimizer steps — the
    K-step contract counts TRAIN steps, not dispatches); the Kth stops
    and ledgers it."""
    if _capture is None:
        return
    _capture_tick(step, n)


def _capture_tick(step, n=1):
    # backend start/stop can flush a large trace to disk — NEVER under
    # _cap_lock, or every /profilez scrape and flight-bundle build (via
    # capture_status) blocks behind the profiler I/O
    to_start = to_stop = None
    with _cap_lock:
        cap = _capture
        if cap is None or cap["manual"]:
            return
        if not cap["started"]:
            cap["started"] = True
            cap["first_step"] = step
            cap["t0"] = time.time()
            to_start = cap
        else:
            cap["steps_left"] -= max(1, int(n))
            if cap["steps_left"] <= 0:
                cap["last_step"] = step
                to_stop = cap
    if to_start is not None:
        try:
            _start_backend(to_start["log_dir"])
        except Exception as e:  # a broken profiler must not kill steps
            with _cap_lock:
                if _capture is to_start:
                    _finish_locked(error=f"{type(e).__name__}: {e}")
        return
    if to_stop is not None:
        _stop_and_ledger(to_stop)


def _stop_and_ledger(cap, aborted=False):
    """Stop the backend (outside the lock — trace flushing can take
    seconds) and ledger ``cap`` if it is still the live capture. A lost
    race (someone else already finished it) stops at most twice; the
    second jax stop raises and is swallowed, and the ledger entry is
    written exactly once."""
    error = None
    try:
        _stop_backend()
    except Exception as e:
        error = f"{type(e).__name__}: {e}"
    with _cap_lock:
        if _capture is cap:
            _finish_locked(error=error, aborted=aborted)


def start_capture(log_dir, trigger="profiler_api"):
    """Manual open-ended capture — the ``profiler.start_xprof_trace``
    delegate. Ledgered like step captures, stopped by
    :func:`stop_capture`. Raises RuntimeError if one is already live
    (matching jax.profiler's own single-trace contract). The slot is
    reserved under the lock; the backend start runs outside it."""
    global _capture
    with _cap_lock:
        if _capture is not None:
            raise RuntimeError(
                "an xprof capture is already armed or active: "
                f"{_public(_capture)}")
        cap = _capture = {
            "trigger": _sanitize(trigger),
            "steps": None,
            "steps_left": None,
            "log_dir": log_dir,
            "manual": True,
            "started": True,
            "armed_at": time.time(),
            "t0": time.time(),
        }
        _registry.gauge("flightrec.capture_active",
                        help=_CAP_ACTIVE_HELP).set(1)
    try:
        _start_backend(log_dir)
    except BaseException:
        with _cap_lock:
            if _capture is cap:
                _clear_locked()
        raise


def stop_capture():
    """Stop the manual capture started by :func:`start_capture`."""
    with _cap_lock:
        cap = _capture
        if cap is None or not cap["manual"]:
            raise RuntimeError("no manual xprof capture is active")
    _stop_and_ledger(cap)


def _finish_locked(error, aborted=False):
    """Ledger the capture and clear the slot. Caller holds ``_cap_lock``
    and has already stopped the backend (outside the lock)."""
    global _capture
    cap = _capture
    if cap is None:
        return
    rec = _public(cap)
    rec["ended_at"] = time.time()
    if cap.get("t0"):
        rec["duration_s"] = round(rec["ended_at"] - cap["t0"], 3)
    if error:
        rec["error"] = error
    if aborted:
        rec["aborted"] = True
    _cap_history.append(rec)
    del _cap_history[:-_CAP_HISTORY_MAX]
    if cap["started"] and not error and not aborted:
        _registry.counter(
            "flightrec.captures",
            help="xprof captures completed through the capture "
                 "registry").inc()
    _clear_locked()


def _clear_locked():
    global _capture
    _capture = None
    _registry.gauge("flightrec.capture_active",
                    help=_CAP_ACTIVE_HELP).set(0)


def _public(cap):
    return {k: cap.get(k) for k in
            ("trigger", "steps", "steps_left", "log_dir", "manual",
             "started", "armed_at", "first_step")}


def capture_status():
    """The /profilez payload: the armed/active capture (if any) and the
    bounded completed-capture history."""
    with _cap_lock:
        return {
            "active": _public(_capture) if _capture is not None else None,
            "completed": list(_cap_history),
        }
