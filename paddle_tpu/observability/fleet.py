"""Fleet-wide observability plane (ISSUE 11): cross-rank aggregation,
straggler/skew attribution, and cluster serving rollups.

Every telemetry surface before this one is per-process: each rank or
replica publishes into its own registry, streams its own JSONL, serves its
own endpoint. This module composes them into CLUSTER views — the layer the
disaggregated serving fleet (autoscaling needs one burn-rate signal, not N)
and overlap-scheduled multichip training (an MFU regression needs "which
rank was late", not N dashboards) both stand on.

Three pieces, meeting in the shared telemetry directory
(``PADDLE_TELEMETRY_DIR``, the same dir heartbeats already use):

- :class:`SnapshotPublisher` — every rank atomically publishes a BOUNDED
  snapshot (structured metric series incl. histogram bounds, goodput
  split, compile-ledger counts, per-op collective wait/body accumulators)
  to ``fleetsnap.<rank>.json`` on the existing heartbeat cadence
  (``watchdog.maybe_beat`` piggyback; serving dispatchers publish under
  ``serving/`` exactly like their heartbeats). Snapshots are
  generation-stamped like heartbeats, so a re-formed world's aggregator
  fences out old-incarnation stragglers.

- :class:`FleetAggregator` — merges a snapshot set into one view: a
  merged metrics registry (every series gains a ``rank=``/``replica=``
  label; labeled families stay grouped under one ``# TYPE`` — asserted
  against the strict Prometheus parser), cross-rank quantiles and skew
  for ``span.*_s`` step phases, and a **straggler detector** that
  separates "this rank computed slowly" from "this rank waited on a
  collective" using the wait-vs-body split recorded at the
  ``collective.*`` span seams, scoring persistently-slowest ranks over a
  sliding window into ``fleet.straggler.*`` gauges/alerts. Hosted by the
  launcher's monitor thread; startable standalone over any telemetry dir
  (``scripts/fleet_view.py`` is the offline twin).

- :func:`serving_rollup` — the cluster serving view in
  ``serving_report()["fleet"]`` and ``/fleetz``: live replicas, total
  queue depth, occupancy, goodput split, the worst multi-window SLO burn
  rate, and one blended ``pressure`` signal with a ``scale_hint`` —
  the single number an autoscaler reads.

Cost contract: publication rides the heartbeat throttle (~1 snapshot per
``PADDLE_FLEET_SNAPSHOT_EVERY_S``); with no telemetry dir configured the
whole plane is one cached ``False`` check (the PR-2 <1%-of-step disabled
bound is asserted with fleet publication compiled in). Stdlib-only, like
the rest of the package.
"""
import collections
import json
import math
import os
import re
import statistics
import threading
import time

from ..utils.envs import env_float, env_int, env_str
from . import goodput as _goodput
from . import tracing as _tracing
from .metrics import MetricsRegistry
from .metrics import registry as _registry

__all__ = ["SnapshotPublisher", "FleetAggregator", "CollectiveStats",
           "collective_seam", "collectives", "maybe_publish",
           "serving_rollup", "snapshot_path", "load_snapshots",
           "SNAP_RE"]

#: snapshot schema version (bump on incompatible changes; the aggregator
#: skips versions it does not understand instead of mis-merging them)
SNAPSHOT_VERSION = 1

SNAP_RE = re.compile(r"^fleetsnap\.(\d+)(?:\.([A-Za-z0-9_-]+))?\.json$")

_SANITIZE_INSTANCE = re.compile(r"[^A-Za-z0-9_-]")


_PROC_INSTANCE = None


def process_instance():
    """A publisher-instance discriminator unique across the processes
    that can share one telemetry dir: short hostname + a hash of the
    FULL hostname + pid. A pid alone is NOT unique across hosts (two
    containers are both pid 1), and a truncated hostname alone is not
    unique across same-prefix pod names — the hash of the untruncated
    name keeps 'serving-frontend-…-abcde' and '…-fghij' distinct.
    Computed once per process (hostname and pid are stable)."""
    global _PROC_INSTANCE
    if _PROC_INSTANCE is None:
        import hashlib
        import socket

        raw = socket.gethostname()
        host = _SANITIZE_INSTANCE.sub("-", raw)[:12] or "host"
        tag = hashlib.blake2s(raw.encode(), digest_size=3).hexdigest()
        _PROC_INSTANCE = f"{host}-{tag}-{os.getpid()}"
    return _PROC_INSTANCE


_reg_token_lock = threading.Lock()
_reg_token_counter = 0


def _registry_token(registry):
    """A per-registry token stable for the REGISTRY OBJECT's lifetime —
    stamped on the object itself, so a freed registry's reused id()
    address can never alias two distinct registries (which would make
    the aggregator collapse two ranks into one metric source)."""
    tok = getattr(registry, "_fleet_token", None)
    if tok is None:
        global _reg_token_counter
        with _reg_token_lock:
            tok = getattr(registry, "_fleet_token", None)
            if tok is None:
                _reg_token_counter += 1
                tok = registry._fleet_token = _reg_token_counter
    return tok

#: metric-family priority for the bounded snapshot: when the series cap
#: bites, the cross-rank-interesting families survive first
_PRIORITY = ("span.", "collective.", "serving.", "serve.", "slo.",
             "train.", "data.", "fleet.", "elastic.", "goodput.",
             "compile.", "device.", "devprof.")

#: step-phase families the straggler detector reads, most specific first
_STEP_FAMILIES = ("span.train.step.dispatch_s", "span.train.step_s",
                  "span.train.run_steps.dispatch_s")


def snapshot_path(directory, rank, instance=None):
    """``fleetsnap.<rank>.json``, or ``fleetsnap.<rank>.<instance>.json``
    when an instance discriminator is given. Training ranks are globally
    unique by the launcher contract; serving replica INDEXES are only
    unique within one frontend process, so ReplicaHandle publishes with
    ``instance=process_instance()`` (host + pid) — two frontends sharing
    a telemetry dir, even across hosts, must not overwrite (or tear, via
    the shared tmp path) each other's files."""
    if instance is None:
        return os.path.join(directory, f"fleetsnap.{int(rank)}.json")
    inst = _SANITIZE_INSTANCE.sub("-", str(instance))
    return os.path.join(directory, f"fleetsnap.{int(rank)}.{inst}.json")


# ---------------------------------------------------------------------------
# collective wait vs body attribution (the collective.* span seams)
# ---------------------------------------------------------------------------
class CollectiveStats:
    """Per-op accumulators for the wait-vs-body split at the collective
    seams. ``wait_s`` is the time between entering the collective entry
    point and the collective body starting — with the optional barrier
    probe armed (``PADDLE_FLEET_COLLECTIVE_WAIT=1``, multi-process only)
    that is literally the time this rank spent waiting for its peers;
    ``body_s`` is the collective itself. The aggregator uses the split to
    separate compute-slow ranks (low wait, high compute) from ranks stuck
    waiting on a slow peer or a slow wire (high wait)."""

    def __init__(self, registry=None):
        self.registry = registry if registry is not None else _registry
        self._lock = threading.Lock()
        self._ops = {}

    def note(self, op, wait_s, body_s):
        self.registry.histogram(
            "collective.wait_s", labels={"op": op},
            help="pre-collective wait before the body dispatches, per op"
        ).observe(wait_s)
        now = time.time()
        with self._lock:
            rec = self._ops.get(op)
            if rec is None:
                rec = self._ops[op] = {"count": 0, "wait_s": 0.0,
                                       "body_s": 0.0, "last_arrive": 0.0}
            rec["count"] += 1
            rec["wait_s"] += wait_s
            rec["body_s"] += body_s
            rec["last_arrive"] = now

    def export(self):
        with self._lock:
            return {op: dict(rec) for op, rec in self._ops.items()}

    def reset(self):
        with self._lock:
            self._ops.clear()


#: the process-global accumulator the ops.py seams feed
collectives = CollectiveStats()


def _wait_probe():
    """The pre-collective wait body. Default: only the ``fleet.
    collective_wait`` chaos seam (deterministic wait injection in tests).
    With ``PADDLE_FLEET_COLLECTIVE_WAIT=1`` in a REAL multi-process world,
    a host barrier runs here so the measured wait is exactly the
    waiting-on-peers time — an attribution-debug mode, not a default (a
    barrier per collective is badput by construction)."""
    from ..testing import chaos

    chaos.site("fleet.collective_wait")
    from ..utils.envs import env_bool

    if not env_bool("PADDLE_FLEET_COLLECTIVE_WAIT"):
        return
    import sys

    jax = sys.modules.get("jax")
    if jax is None or jax.process_count() <= 1:
        return
    try:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices("paddle_tpu_fleet_wait")
    except Exception:
        pass


class _CollectiveSeam:
    """Times the pre-collective wait distinctly from the collective body;
    the body runs under the existing ``collective.<op>`` span so every
    downstream consumer (ring buffer, sinks, span histograms) is
    unchanged."""

    __slots__ = ("name", "op", "_span", "_t0", "_t1")

    def __init__(self, name):
        self.name = name
        self.op = name.partition(".")[2] or name

    def __enter__(self):
        self._t0 = time.perf_counter()
        _wait_probe()
        self._t1 = time.perf_counter()
        self._span = _tracing.span(self.name)
        self._span.__enter__()
        return self

    def __exit__(self, *exc):
        self._span.__exit__(*exc)
        collectives.note(self.op, self._t1 - self._t0,
                         time.perf_counter() - self._t1)
        return False


def collective_seam(name):
    """The collective entry-point wrapper (communication/ops.py). With
    telemetry disabled this is a flag check, the chaos seam probe (the
    chaos contract: every seam fires regardless of telemetry — an armed
    ``fleet.collective_wait`` plan must inject even in a telemetry-off
    run), and the shared no-op; nothing is timed or recorded."""
    if not _tracing.enabled():
        from ..testing import chaos

        chaos.site("fleet.collective_wait")
        return _tracing._NULL
    return _CollectiveSeam(name)


# ---------------------------------------------------------------------------
# per-rank snapshot publication
# ---------------------------------------------------------------------------
class SnapshotPublisher:
    """Atomically publishes this process's telemetry as one bounded JSON
    snapshot (tmp + fsync-free rename — same contract as heartbeats: a
    reader never sees a torn file). ``role`` is ``"rank"`` for training
    ranks, ``"replica"`` for serving dispatchers (published under the
    ``serving/`` subdir by ReplicaHandle, mirroring their heartbeats).
    ``registry``/``collectives_stats`` are injectable so multi-rank tests
    can publish isolated per-rank registries from one process."""

    def __init__(self, directory, rank, role="rank", registry=None,
                 collectives_stats=None, min_interval_s=None,
                 max_series=None, generation=None, world=None,
                 extra_provider=None, instance=None, include_metrics=True):
        os.makedirs(directory, exist_ok=True)
        self.dir = directory
        self.rank = int(rank)
        self.role = str(role)
        self.registry = registry if registry is not None else _registry
        self.collectives = (collectives_stats if collectives_stats is not None
                            else collectives)
        self.min_interval_s = (float(min_interval_s)
                               if min_interval_s is not None
                               else env_float("PADDLE_FLEET_SNAPSHOT_EVERY_S",
                                              2.0))
        self.max_series = (int(max_series) if max_series is not None
                           else env_int("PADDLE_FLEET_SNAPSHOT_MAX_SERIES",
                                        512))
        self.generation = (int(generation) if generation is not None
                           else env_int("PADDLE_ELASTIC_GENERATION", 0))
        self.world = (int(world) if world is not None
                      else env_int("PADDLE_TRAINERS_NUM", 0))
        #: optional callable returning a dict merged into each snapshot
        #: (the serving ReplicaHandle attaches its control-plane state)
        self.extra_provider = extra_provider
        #: False = identity/extra-only snapshots (no registry export):
        #: N same-registry publishers in one process need exactly ONE
        #: metrics carrier — the aggregator collapses the rest anyway,
        #: so the other N-1 skip the full export+serialize per cadence
        self.include_metrics = bool(include_metrics)
        self.instance = (None if instance is None
                         else _SANITIZE_INSTANCE.sub("-", str(instance)))
        self.path = snapshot_path(directory, self.rank, instance=instance)
        self._seq = 0
        self._last_t = 0.0
        # publish() is called from the owning loop AND (for replicas)
        # potentially from tests/monitors: serialize writers so two
        # publishes can never interleave on the shared tmp file
        self._pub_lock = threading.Lock()

    def _series(self):
        """The registry export, priority-ordered and capped: when the cap
        bites, span/collective/serving families survive first and the
        snapshot says how many series were dropped (no silent truncation)."""
        recs = self.registry.export()

        def key(rec):
            fam = rec["family"]
            for i, p in enumerate(_PRIORITY):
                if fam.startswith(p):
                    return (i, rec["name"])
            return (len(_PRIORITY), rec["name"])

        recs.sort(key=key)
        dropped = max(0, len(recs) - self.max_series)
        return recs[:self.max_series], dropped

    def build(self, step=None):
        from . import compilemem as _compilemem

        if self.include_metrics:
            series, dropped = self._series()
        else:
            series, dropped = [], 0
        snap = {
            "kind": "fleet_snapshot",
            "version": SNAPSHOT_VERSION,
            "role": self.role,
            "rank": self.rank,
            "pid": os.getpid(),
            # source identity: ALWAYS host+pid-qualified — training-rank
            # publishers keep their rank-only filename, but their metric
            # SOURCE identity must survive cross-host pid collisions too
            "instance": self.instance or process_instance(),
            # registry identity: publishers sharing ONE registry (N
            # in-process replicas) publish the same series — the
            # aggregator merges each distinct registry once, not once per
            # publisher. A token stamped on the object, NOT id(): a freed
            # registry's reused address must never alias two ranks.
            "registry_id": _registry_token(self.registry),
            "generation": self.generation,
            "world": self.world,
            "step": step,
            "seq": self._seq,
            "time": time.time(),
            "metrics": series,
            "dropped_series": dropped,
            "goodput": _goodput.report(),
            "serving_goodput": _goodput.serving.report(),
            "compile": _compilemem.ledger.counts(),
            "collectives": self.collectives.export(),
            "dynamics": _dynamics_snapshot_block(),
            "devprof": _devprof_snapshot_block(),
        }
        if self.extra_provider is not None:
            try:
                snap.update(self.extra_provider() or {})
            except Exception:
                pass  # a dying engine must not break publication
        return snap

    def publish(self, step=None):
        t0 = time.perf_counter()
        snap = self.build(step=step)
        with self._pub_lock:
            self._seq += 1
            tmp = self.path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(snap, f)
            os.replace(tmp, self.path)
        self.registry.counter(
            "fleet.snapshots.published",
            help="fleet snapshots committed by this process").inc()
        self.registry.histogram(
            "fleet.snapshot.publish_s",
            help="wall cost of building + committing one fleet snapshot"
        ).observe(time.perf_counter() - t0)
        return self.path

    def maybe_publish(self, step=None):
        """Throttled publish — the heartbeat-cadence hook. OSError is
        swallowed: a full disk must not take the training step down."""
        now = time.monotonic()
        if now - self._last_t < self.min_interval_s:
            return None
        self._last_t = now
        try:
            return self.publish(step=step)
        except OSError:
            return None


def _dynamics_snapshot_block():
    """This process's last spilled dynamics summary (ISSUE 13), bounded to
    the cross-rank-interesting scalars — the aggregator reads it to flag
    grad-norm skew (a desyncing rank) before loss diverges. None when
    dynamics is off or nothing has spilled yet."""
    try:
        from . import dynamics as _dyn

        last = _dyn.fleet_block()
    except Exception:
        return None
    if not last:
        return None
    return {k: last.get(k) for k in
            ("step", "updates", "loss", "loss_ewma", "loss_z", "grad_norm",
             "nonfinite_steps", "nonfinite_first")}


def _devprof_snapshot_block():
    """This process's per-program mean device-seconds (ISSUE 17), bounded
    to the costliest programs — the aggregator medians these across ranks
    to flag the rank whose CHIP is slow (the straggler detector can only
    say a rank's step is slow; this says the same program takes longer on
    this device). None when devprof is off or nothing has sampled."""
    try:
        from . import devprof as _dp

        return _dp.fleet_block()
    except Exception:
        return None


#: cached process publisher: False = no telemetry dir (permanent no-op),
#: None = unresolved, SnapshotPublisher = publishing (same tri-state
#: pattern as watchdog._process_hb)
_process_pub = None


def _env_publisher():
    global _process_pub
    p = _process_pub
    if p is not None:
        return p
    d = env_str("PADDLE_TELEMETRY_DIR")
    if not d:
        _process_pub = False
        return False
    rank = env_str("PADDLE_TRAINER_ID",
                   os.environ.get("RANK", "0")) or "0"
    try:
        p = _process_pub = SnapshotPublisher(d, int(rank))
    except (OSError, ValueError):
        p = _process_pub = False
    return p


def maybe_publish(step=None):
    """The heartbeat piggyback (called from watchdog.maybe_beat): one
    cached check when no telemetry dir is configured; a throttled atomic
    snapshot write when there is."""
    p = _env_publisher()
    if p is False:
        return
    p.maybe_publish(step)


def _reset_process_publisher():
    """Test hook: forget the cached publisher so env changes take effect."""
    global _process_pub
    _process_pub = None


# ---------------------------------------------------------------------------
# snapshot loading
# ---------------------------------------------------------------------------
def load_snapshots(paths):
    """(snapshots, errors) from files / telemetry dirs. Directories are
    scanned for ``fleetsnap.*.json`` at the top level AND under
    ``serving/`` (where dispatchers publish, mirroring their heartbeats)."""
    files = []
    for p in paths:
        if os.path.isdir(p):
            for d in (p, os.path.join(p, "serving")):
                try:
                    names = sorted(os.listdir(d))
                except OSError:
                    continue
                files.extend(os.path.join(d, n) for n in names
                             if SNAP_RE.match(n))
        else:
            files.append(p)
    snaps, errors = [], []
    for f in files:
        try:
            with open(f) as fh:
                snap = json.load(fh)
        except (OSError, ValueError) as e:
            errors.append(f"{f}: {type(e).__name__}: {e}")
            continue
        if not isinstance(snap, dict) \
                or snap.get("kind") != "fleet_snapshot":
            errors.append(f"{f}: not a fleet snapshot")
            continue
        if snap.get("version", 0) > SNAPSHOT_VERSION:
            errors.append(f"{f}: snapshot version {snap.get('version')} "
                          f"newer than reader ({SNAPSHOT_VERSION})")
            continue
        snap["_path"] = f
        snaps.append(snap)
    return snaps, errors


def _median(vals):
    return statistics.median(vals) if vals else 0.0


# ---------------------------------------------------------------------------
# the aggregator
# ---------------------------------------------------------------------------
class FleetAggregator:
    """Merges per-rank/per-replica snapshots into cluster views.

    Generation fencing: only the newest generation present survives the
    merge (or ``generation=`` pins it — the launcher passes its live
    incarnation), exactly like heartbeat fencing; fenced snapshots are
    counted, never mixed in.

    Straggler scoring: per merge round, each rank's step-phase mean is
    split into compute (step − collective wait) and collective wait using
    the seam accumulators; ratios against the cross-rank median classify
    outliers as ``compute`` (this rank IS slow) or ``collective_wait``
    (this rank is stuck waiting — look at its peers). A rank flagged
    ``compute`` in a majority of the sliding window is a PERSISTENT
    straggler: ``fleet.straggler.alerts`` counts the transition and
    :meth:`straggler_advisory` renders the line the elastic launcher logs
    alongside its restart-budget decisions (advisory input — the budget
    still decides)."""

    def __init__(self, telemetry_dir=None, window=None, threshold=None,
                 expected_world=None, generation=None, interval_s=None,
                 registry=None, stale_s=None):
        dirs = telemetry_dir
        if isinstance(dirs, str):
            dirs = [dirs]
        self.dirs = list(dirs or [])
        self.window = (int(window) if window is not None
                       else env_int("PADDLE_FLEET_STRAGGLER_WINDOW", 8))
        self.threshold = (float(threshold) if threshold is not None
                          else env_float("PADDLE_FLEET_STRAGGLER_RATIO",
                                         1.5))
        # staleness fence, RELATIVE to the newest snapshot present (not
        # wall clock, so post-mortem dirs still merge): a publisher that
        # stopped publishing — a dead frontend pid, a crashed rank —
        # drops out instead of inflating members/quorum/rollups forever.
        # <= 0 disables.
        self.stale_s = (float(stale_s) if stale_s is not None
                        else env_float("PADDLE_FLEET_SNAPSHOT_STALE_S",
                                       120.0))
        self.expected_world = expected_world
        self.generation = generation
        self.interval_s = (float(interval_s) if interval_s is not None
                           else max(1.0, env_float(
                               "PADDLE_FLEET_SNAPSHOT_EVERY_S", 2.0)))
        self.registry = registry if registry is not None else _registry
        self._lock = threading.Lock()
        self._history = {}          # rank -> deque of per-round verdicts
        self._prev_totals = {}      # rank -> last advancing-round totals
        self._persistent = set()
        self._gn_flagged = set()    # ranks currently grad-norm-skew-flagged
        self._dp_flagged = set()    # ranks currently device-time-flagged
        self._scored_ranks = set()  # ranks with a live score gauge
        self._skew_phases = set()   # phases with a live skew gauge
        self._rounds = 0
        self._last_view = None
        self._stop = threading.Event()
        self._thread = None

    # ---- lifecycle (the launcher's monitor hosts this) -------------------
    def start(self):
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="paddle-fleet-aggregator")
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def _run(self):
        while not self._stop.is_set():
            try:
                self.collect()
            except Exception:
                pass  # the aggregator must never take the launcher down
            self._stop.wait(self.interval_s)

    # ---- merging ----------------------------------------------------------
    def collect(self, advance=True):
        """One aggregation round over the configured dirs: refreshes the
        ``fleet.*`` gauges and (with ``advance=True`` — the monitor
        thread's cadence) advances the straggler sliding window."""
        snaps, errors = load_snapshots(self.dirs)
        return self.merge(snaps, errors=errors, advance=advance)

    def view(self, refresh=False):
        """The last merged view (collect()s lazily on first use or when
        ``refresh=True``) — the /fleetz payload. View refreshes NEVER
        advance the straggler window: persistence must track the monitor
        cadence, not the scrape rate (a 0.5 s scraper against an 8-round
        window would otherwise fabricate persistent stragglers from two
        real slow rounds)."""
        if refresh or self._last_view is None:
            return self.collect(advance=False)
        return self._last_view

    def _fence(self, snaps):
        gens = sorted({int(s.get("generation", 0)) for s in snaps})
        gen = (int(self.generation) if self.generation is not None
               else (gens[-1] if gens else 0))
        kept = [s for s in snaps if int(s.get("generation", 0)) == gen]
        return gen, gens, kept, len(snaps) - len(kept)

    @staticmethod
    def _source_id(s):
        """The publishing process's identity: the host+pid ``instance``
        discriminator when the publisher stamped one, else the pid — a
        bare pid is not unique across hosts sharing a telemetry dir."""
        return s.get("instance") or s.get("pid", 0)

    @classmethod
    def _dedupe(cls, kept):
        """One snapshot per member identity — newest publication wins.
        Training ranks are globally unique (launcher contract) so the
        rank IS the identity; serving replica indexes repeat across
        frontend processes (and hosts), so a replica's identity is
        ``rank@<instance-or-pid>``."""
        by_id = {}
        for s in kept:
            role = s.get("role", "rank")
            rank = int(s.get("rank", 0))
            ident = (rank if role == "rank"
                     else f"{rank}@{cls._source_id(s)}")
            key = (role, ident)
            cur = by_id.get(key)
            if cur is None or s.get("time", 0) > cur.get("time", 0):
                by_id[key] = s
        return by_id

    @classmethod
    def _metric_sources(cls, by_id):
        """Snapshots whose ``metrics`` block should be merged: one per
        (source process, registry) — N in-process publishers sharing one
        registry publish the same series, and merging that registry N
        times would N-fold every counter. Distinct registries in one
        process (per-rank test harnesses) each merge once."""
        newest = {}
        for s in by_id.values():
            key = (cls._source_id(s),
                   s.get("registry_id", s.get("rank", 0)))
            cur = newest.get(key)
            # prefer the snapshot that actually carries metrics (the
            # designated per-process carrier), newest among equals —
            # an identity-only twin must not shadow the metrics payload
            rank_s = (bool(s.get("metrics")), s.get("time", 0))
            rank_c = (bool(cur.get("metrics")), cur.get("time", 0)) \
                if cur is not None else (False, -1)
            if cur is None or rank_s > rank_c:
                newest[key] = s
        chosen = {id(s) for s in newest.values()}
        return [s for s in by_id.values() if id(s) in chosen]

    def _fence_stale(self, snaps):
        """Drop snapshots older than ``stale_s`` behind the NEWEST one —
        the publisher stopped publishing (dead pid, crashed rank) and
        must not count as a live member."""
        if self.stale_s <= 0 or not snaps:
            return snaps, 0
        newest = max(s.get("time", 0) for s in snaps)
        fresh = [s for s in snaps
                 if newest - s.get("time", 0) <= self.stale_s]
        return fresh, len(snaps) - len(fresh)

    def merge(self, snaps, errors=(), advance=True):
        snaps, stale = self._fence_stale(snaps)
        gen, gens, kept, fenced = self._fence(snaps)
        by_id = self._dedupe(kept)
        sources = self._metric_sources(by_id)
        rank_snaps = {r: s for (role, r), s in by_id.items()
                      if role == "rank"}
        replica_snaps = {r: s for (role, r), s in by_id.items()
                         if role == "replica"}
        phases = self._phase_stats(
            [s for s in sources if s.get("role", "rank") == "rank"])
        straggler = self._straggler(rank_snaps, advance=advance)
        dynamics = self._dynamics_agg(rank_snaps, advance=advance)
        devprof = self._devprof_agg(rank_snaps, advance=advance)
        now = time.time()
        members = {}
        for (role, r), s in sorted(by_id.items()):
            members[f"{role}:{r}"] = {
                "role": role, "rank": r, "pid": s.get("pid"),
                "step": s.get("step"), "generation": s.get("generation", 0),
                "age_s": round(now - s.get("time", now), 3),
                "world": s.get("world"),
            }
        expected = self.expected_world
        if expected is None:
            worlds = [int(s.get("world") or 0) for s in rank_snaps.values()]
            expected = max(worlds) if worlds else 0
        present = sorted(rank_snaps)
        missing = (sorted(set(range(expected)) - set(present))
                   if expected else [])
        view = {
            "time": now,
            "generation": gen,
            "generations_seen": gens,
            "fenced_out": fenced,
            "stale_out": stale,
            "members": members,
            "quorum": {"expected_world": expected, "present": present,
                       "missing": missing},
            "phases": phases,
            "straggler": straggler,
            "dynamics": dynamics,
            "devprof": devprof,
            "serving": self._serving_agg(replica_snaps),
            "errors": list(errors),
        }
        self.registry.gauge(
            "fleet.snapshots.merged",
            help="snapshots merged into the last fleet view").set(len(by_id))
        self.registry.gauge(
            "fleet.snapshots.fenced",
            help="old-generation snapshots fenced out of the last merge"
        ).set(fenced)
        self._last_view = view
        return view

    # ---- cross-rank phase stats -------------------------------------------
    @staticmethod
    def _rank_family_stats(snap, match):
        """{family: (sum, count, bounds, counts)} for one snapshot's
        histogram series whose family ``match()`` accepts, label-sets of a
        family merged together."""
        fams = {}
        for rec in snap.get("metrics", ()):
            if rec.get("type") != "histogram" or not match(rec["family"]):
                continue
            cur = fams.get(rec["family"])
            if cur is None:
                fams[rec["family"]] = [rec.get("sum", 0.0),
                                       rec.get("count", 0),
                                       list(rec.get("bounds") or ()),
                                       list(rec.get("counts") or ())]
            else:
                cur[0] += rec.get("sum", 0.0)
                cur[1] += rec.get("count", 0)
                if cur[2] == list(rec.get("bounds") or ()):
                    cur[3] = [a + b for a, b in
                              zip(cur[3], rec.get("counts") or ())]
        return fams

    def _phase_stats(self, rank_sources):
        """Cross-rank stats per span/collective-wait family: per-rank
        means, the skew ratio (max mean / median mean), and merged-bucket
        quantiles when every rank shares the bucket ladder."""
        from .metrics import Histogram

        per_rank = {}
        for s in rank_sources:
            r = int(s.get("rank", 0))
            per_rank[r] = self._rank_family_stats(
                s, lambda f: f.startswith("span.")
                or f == "collective.wait_s")
        families = sorted({f for fams in per_rank.values() for f in fams})
        out = {}
        for fam in families:
            means, merged_bounds, merged_counts = {}, None, None
            total_sum = total_count = 0
            mergeable = True
            for r, fams in per_rank.items():
                rec = fams.get(fam)
                if rec is None:
                    continue
                s, c, bounds, counts = rec
                if c:
                    means[r] = s / c
                total_sum += s
                total_count += c
                if merged_bounds is None:
                    merged_bounds, merged_counts = bounds, list(counts)
                elif bounds == merged_bounds:
                    merged_counts = [a + b for a, b in
                                     zip(merged_counts, counts)]
                else:
                    mergeable = False
            if not means:
                continue
            med = _median(list(means.values()))
            worst = max(means, key=means.get)
            lo = min(means.values())
            entry = {
                "ranks": {str(r): round(m, 6)
                          for r, m in sorted(means.items())},
                "mean": round(total_sum / total_count, 6)
                if total_count else 0.0,
                "median_rank_mean": round(med, 6),
                "max_rank": worst,
                # skew: how much slower the worst rank is than the
                # median; spread: the full max-min range over the median
                # (catches a LOW outlier too — e.g. the one rank that
                # never waits because everyone waits on IT)
                "skew": round(means[worst] / med, 4) if med > 0 else 1.0,
                "spread": round((means[worst] - lo) / med, 4)
                if med > 0 else 0.0,
            }
            if mergeable and merged_bounds:
                h = Histogram(fam, buckets=merged_bounds)
                with h._lock:
                    for i, c in enumerate(
                            merged_counts[:len(h._counts)]):
                        h._counts[i] = int(c)
                    h._count = sum(h._counts)
                    h._sum = total_sum
                entry["p50"] = h.quantile(0.5)
                entry["p99"] = h.quantile(0.99)
            out[fam] = entry
        for fam, e in out.items():
            self.registry.gauge(
                "fleet.phase_skew", labels={"phase": fam},
                help="max-rank mean / median-rank mean per step phase"
            ).set(e["skew"])
        # phases that stopped appearing (departed ranks took their spans
        # with them, or <2 peers remain) retire from the exposition
        with self._lock:
            for fam in self._skew_phases - set(out):
                self.registry.remove("fleet.phase_skew",
                                     labels={"phase": fam})
            self._skew_phases = set(out)
        return out

    # ---- cross-rank training dynamics (ISSUE 13) ---------------------------
    def _dynamics_agg(self, rank_snaps, advance=True):
        """Merge the per-rank dynamics blocks into the desync view: in
        data-parallel training every rank consumes a different shard of
        the SAME distribution, so a rank whose grad norm sits far off the
        cross-rank median is desyncing (corrupt shard, diverging local
        state) — visible here BEFORE the loss chart shows it. Ratios
        against the median reuse the straggler threshold; transitions
        count into ``fleet.dynamics.skew_alerts``."""
        per_rank = {}
        for r, s in rank_snaps.items():
            d = s.get("dynamics")
            if isinstance(d, dict) and d.get("grad_norm") is not None:
                per_rank[r] = d
        if not per_rank:
            # dynamics went away (disabled on restart, no spill yet):
            # retire the gauge and the flag state, like the straggler
            # detector's vanished-rank retirement — a stale skew must
            # not linger in the exposition, and a later re-flag must
            # still count as an off -> on transition. ADVANCING rounds
            # only: a /fleetz?refresh=1 scrape racing a re-forming world
            # must not perturb alert-transition state (the straggler
            # window keeps the same contract).
            if advance:
                with self._lock:
                    self._gn_flagged = set()
                self.registry.remove("fleet.grad_norm_skew")
            return None
        norms = {r: float(d["grad_norm"]) for r, d in per_rank.items()}
        med = _median(list(norms.values()))
        worst = max(norms, key=norms.get)
        lo = min(norms.values())
        skew = round(norms[worst] / med, 4) if med > 0 else 1.0
        self.registry.gauge(
            "fleet.grad_norm_skew",
            help="max-rank grad norm / median-rank grad norm at the last "
                 "merge (a desyncing rank shows here before loss "
                 "diverges)").set(skew)
        flagged = set()
        if len(norms) >= 2 and med > 0:
            # both tails: a rank desyncs by exploding (corrupt shard,
            # diverged local state) OR by collapsing toward zero (dead
            # shard, flat region) — the low outlier is the one a
            # high-only ratio never sees
            flagged = {r for r, v in norms.items()
                       if v >= med * self.threshold
                       or v <= med / self.threshold}
        out = {
            "ranks": {str(r): {
                "grad_norm": norms[r],
                "loss": d.get("loss"),
                "loss_z": d.get("loss_z"),
                "step": d.get("step"),
                "nonfinite_steps": d.get("nonfinite_steps"),
                "nonfinite_first": d.get("nonfinite_first"),
            } for r, d in sorted(per_rank.items())},
            "median_grad_norm": round(med, 8),
            "max_rank": worst,
            "skew": skew,
            # the full max-min range over the median: catches the LOW
            # outlier the max/median ratio cannot (same rationale as the
            # phase-stats spread)
            "spread": round((norms[worst] - lo) / med, 4) if med > 0
            else 0.0,
            "flagged": sorted(flagged),
            "nonfinite_ranks": sorted(
                r for r, d in per_rank.items()
                if (d.get("nonfinite_steps") or 0) > 0),
        }
        if advance:
            with self._lock:
                newly = flagged - self._gn_flagged
                if newly:
                    self.registry.counter(
                        "fleet.dynamics.skew_alerts",
                        help="grad-norm-skew flag transitions (off -> on) "
                             "per rank across merges").inc(len(newly))
                self._gn_flagged = flagged
        return out

    def _devprof_agg(self, rank_snaps, advance=True):
        """Merge the per-rank devprof blocks (ISSUE 17) into the sick-chip
        view: every data-parallel rank runs the SAME compiled programs, so
        per-program device time off the cross-rank median is a device
        problem (thermal throttle, degraded HBM, bad chip), not a slow
        host — the exact complement of the straggler detector's
        compute-vs-wait split. A rank's score is the median over shared
        programs of (rank device time / fleet-median device time); the
        threshold reuses the straggler ratio and transitions count into
        ``fleet.devprof.skew_alerts``."""
        per_rank = {}
        for r, s in rank_snaps.items():
            d = s.get("devprof")
            if isinstance(d, dict) and d.get("programs"):
                per_rank[r] = {str(k): float(v)
                               for k, v in d["programs"].items()
                               if isinstance(v, (int, float)) and v > 0}
        per_rank = {r: p for r, p in per_rank.items() if p}
        if not per_rank:
            # devprof went away (disabled on restart, nothing sampled):
            # retire the gauge + flag state on ADVANCING rounds only —
            # same contract as the dynamics retirement above
            if advance:
                with self._lock:
                    self._dp_flagged = set()
                self.registry.remove("fleet.devprof.skew")
            return None
        # fleet-median device time per program, over the ranks that ran it
        medians = {}
        for p in per_rank.values():
            for k in p:
                medians.setdefault(k, []).append(p[k])
        medians = {k: _median(v) for k, v in medians.items()}
        scores = {}
        for r, p in per_rank.items():
            ratios = sorted(p[k] / medians[k] for k in p if medians[k] > 0)
            if ratios:
                scores[r] = round(_median(ratios), 4)
        if not scores:
            return None
        worst = max(scores, key=scores.get)
        skew = scores[worst]
        self.registry.gauge(
            "fleet.devprof.skew",
            help="max-rank per-program device time / fleet median at the "
                 "last merge (a sick chip shows here; a slow host shows "
                 "in the straggler split)").set(skew)
        flagged = set()
        if len(scores) >= 2:
            # both tails: slow = the sick chip; implausibly FAST means
            # the rank is not running the same work (sharding/config
            # divergence) — the tail a slow-only ratio never sees
            flagged = {r for r, v in scores.items()
                       if v >= self.threshold or v <= 1.0 / self.threshold}
        out = {
            "ranks": {str(r): {
                "score": scores.get(r),
                "programs": {k: round(v, 9) for k, v in
                             sorted(p.items())},
            } for r, p in sorted(per_rank.items())},
            "program_median_s": {k: round(v, 9)
                                 for k, v in sorted(medians.items())},
            "max_rank": worst,
            "skew": skew,
            "flagged": sorted(flagged),
        }
        if advance:
            with self._lock:
                newly = flagged - self._dp_flagged
                if newly:
                    self.registry.counter(
                        "fleet.devprof.skew_alerts",
                        help="per-program device-time skew flag "
                             "transitions (off -> on) per rank across "
                             "merges").inc(len(newly))
                self._dp_flagged = flagged
        return out

    # ---- straggler detection ----------------------------------------------
    @staticmethod
    def _rank_step_totals(snap):
        """Lifetime (step_sum, step_count, wait_total) for one rank's
        snapshot — wait comes from the collective seam accumulators
        (falling back to the ``collective.wait_s`` series)."""
        fams = FleetAggregator._rank_family_stats(
            snap, lambda f: f in _STEP_FAMILIES or f == "collective.wait_s")
        step = next((fams[f] for f in _STEP_FAMILIES if f in fams), None)
        if step is None or not step[1]:
            return None
        wait_total = sum(rec.get("wait_s", 0.0)
                         for rec in (snap.get("collectives") or {}).values())
        if not wait_total and "collective.wait_s" in fams:
            wait_total = fams["collective.wait_s"][0]
        return step[0], step[1], wait_total

    def _rank_step_split(self, rank, snap, advance):
        """(step_mean, wait_per_step, compute_mean, steps) for the steps
        since the PREVIOUS advancing round — histograms accumulate over
        the process lifetime, and lifetime means would dilute a rank
        that degrades mid-run past ever tripping an 8-round window.
        Falls back to lifetime means on first sight of a rank (or after
        its counters reset, e.g. a restart with a fresh registry)."""
        totals = self._rank_step_totals(snap)
        if totals is None:
            return None
        step_sum, step_count, wait_total = totals
        with self._lock:
            prev = self._prev_totals.get(rank)
            if advance:
                self._prev_totals[rank] = totals
        if prev is not None and step_count > prev[1]:
            d_steps = step_count - prev[1]
            step_mean = (step_sum - prev[0]) / d_steps
            wait_per_step = max(wait_total - prev[2], 0.0) / d_steps
        else:
            # first sight, counters reset, or no new steps this round:
            # the lifetime means are the best available estimate
            step_mean = step_sum / step_count
            wait_per_step = wait_total / step_count
        compute_mean = max(step_mean - wait_per_step, 0.0)
        return step_mean, wait_per_step, compute_mean, step_count

    def _straggler(self, rank_snaps, advance=True):
        splits = {}
        for r, snap in rank_snaps.items():
            split = self._rank_step_split(r, snap, advance)
            if split is not None:
                splits[r] = split
        result = {"window": self.window, "threshold": self.threshold,
                  "rounds": self._rounds, "ranks": {},
                  "persistent": sorted(self._persistent)}
        # departed ranks (stale-fenced, shrunk world) leave the window
        # and the persistent set even when too few peers remain to score
        if advance:
            with self._lock:
                for r in list(self._history):
                    if r not in splits:
                        self._history.pop(r)
                        self._persistent.discard(r)
                for r in list(self._prev_totals):
                    if r not in rank_snaps:
                        self._prev_totals.pop(r)
                result["persistent"] = sorted(self._persistent)
        # ... and their score gauges leave the exposition (remove() —
        # a departed rank must vanish from /varz, not report its last
        # score forever). Before the <2-peers return: a shrink to one
        # survivor still retires everyone who left.
        with self._lock:
            for r in self._scored_ranks - set(splits):
                self.registry.remove("fleet.straggler.score",
                                     labels={"rank": str(r)})
            self._scored_ranks = set(splits)
        if len(splits) < 2:
            return result  # skew needs peers to be skewed against
        med_compute = _median([s[2] for s in splits.values()])
        med_wait = _median([s[1] for s in splits.values()])
        eps = 1e-9
        verdicts = {}
        for r, (step_mean, wait, compute, _n) in sorted(splits.items()):
            compute_ratio = compute / max(med_compute, eps)
            wait_ratio = wait / max(med_wait, eps) if med_wait > eps else (
                1.0 if wait <= eps else float("inf"))
            if compute_ratio >= self.threshold:
                verdict = "compute"
            elif wait >= med_wait * self.threshold \
                    and wait > 0.1 * max(step_mean, eps):
                verdict = "collective_wait"
            else:
                verdict = "ok"
            verdicts[r] = verdict
            result["ranks"][str(r)] = {
                "step_mean_s": round(step_mean, 6),
                "collective_wait_per_step_s": round(wait, 6),
                "compute_mean_s": round(compute, 6),
                "compute_ratio": round(compute_ratio, 4),
                "wait_ratio": (round(wait_ratio, 4)
                               if math.isfinite(wait_ratio) else "inf"),
                "verdict": verdict,
            }
            self.registry.gauge(
                "fleet.straggler.score", labels={"rank": str(r)},
                help="per-rank compute mean / cross-rank median (sliding "
                     "straggler score)").set(round(compute_ratio, 4))
        # sliding window: persistence separates a one-round blip from a
        # rank that is ALWAYS the slow one. Mutated only on ADVANCING
        # rounds (the monitor cadence) — a view refresh reports the
        # current window read-only, so the verdict tracks cluster
        # behavior, never the scrape rate.
        with self._lock:
            if advance:
                self._rounds += 1
                for r, verdict in verdicts.items():
                    hist = self._history.get(r)
                    if hist is None:
                        hist = self._history[r] = collections.deque(
                            maxlen=self.window)
                    hist.append(verdict)
            result["rounds"] = self._rounds
            # STRICT majority of the full window, and the window must
            # have accumulated at least that many rounds: a rank flagged
            # in the first 2 ticks after aggregator start (cold-compile
            # warm-up skew is normal) is a blip, not persistence
            need = self.window // 2 + 1
            newly_persistent = set()
            for r, hist in self._history.items():
                flagged = sum(1 for v in hist if v == "compute")
                if str(r) in result["ranks"]:
                    result["ranks"][str(r)]["flagged_rounds"] = flagged
                if len(hist) >= need and flagged >= need:
                    newly_persistent.add(r)
            new_alerts = set()
            if advance:
                new_alerts = newly_persistent - self._persistent
                for r in new_alerts:
                    self.registry.counter(
                        "fleet.straggler.alerts",
                        help="persistent-straggler transitions (off -> on) "
                             "over the sliding window").inc()
                self._persistent = newly_persistent
            result["persistent"] = sorted(self._persistent)
        if new_alerts:
            # flight-record the alert (ISSUE 13): freeze the window
            # verdicts + per-rank splits at the transition. Outside the
            # lock — committing a bundle is file I/O.
            from . import flightrec

            flightrec.record(
                "straggler",
                payload={"new_persistent": sorted(new_alerts),
                         "persistent": result["persistent"],
                         "window": self.window,
                         "ranks": dict(result["ranks"])})
        return result

    def straggler_advisory(self):
        """One log line for the launcher (None when nothing persists):
        advisory input recorded alongside restart-budget decisions."""
        view = self._last_view
        if not view:
            return None
        strag = view.get("straggler") or {}
        parts = []
        for r in strag.get("persistent", ()):
            info = strag.get("ranks", {}).get(str(r), {})
            parts.append(
                f"rank {r} computing {info.get('compute_ratio', '?')}x the "
                f"median (flagged {info.get('flagged_rounds', '?')}/"
                f"{strag.get('window')} rounds)")
        if not parts:
            return None
        return "fleet straggler advisory: " + "; ".join(parts)

    # ---- serving aggregation (cross-process replicas) ---------------------
    def _serving_agg(self, replica_snaps):
        if not replica_snaps:
            return None
        sources = self._metric_sources(
            {("replica", r): s for r, s in replica_snaps.items()})
        # occupancy averages LIVE replicas only, matching serving_rollup:
        # a dead replica's gauge lingers in its frontend's registry at
        # zero, and averaging it in dilutes the pressure signal exactly
        # when the survivors saturate. Known handle names with a non-LIVE
        # state are excluded; unknown label values (no matching replica
        # block) stay counted.
        dead_names = {rep.get("name")
                      for s in replica_snaps.values()
                      for rep in (s.get("replica") or {},)
                      if rep.get("name") and rep.get("state") != "LIVE"}
        queue = occ = pages = 0.0
        occ_n = 0
        counters = {}
        # _metric_sources already collapsed shared-registry twins to one
        # snapshot per (pid, registry); every remaining source is an
        # independent process, so identically-named series SUM — dropping
        # them would undercount every frontend after the first
        for s in sources:
            for rec in s.get("metrics", ()):
                fam = rec["family"]
                if not fam.startswith("serving."):
                    continue
                if rec.get("type") == "counter":
                    counters[fam] = counters.get(fam, 0) + rec.get("value", 0)
                elif rec.get("type") == "gauge":
                    v = rec.get("value", 0.0)
                    if fam == "serving.replica.queue_depth":
                        queue += v
                    elif fam == "serving.replica.occupancy":
                        if (rec.get("labels") or {}).get("replica") \
                                in dead_names:
                            continue
                        occ += v
                        occ_n += 1
                    elif fam == "serving.replica.pages_in_use":
                        pages += v
        replicas = {}
        for r, s in sorted(replica_snaps.items()):
            rep = s.get("replica") or {}
            replicas[str(r)] = {
                "state": rep.get("state"),
                "pending": rep.get("pending"),
                "active": rep.get("active"),
                "load": rep.get("load"),
                "age_s": round(time.time() - s.get("time", 0), 3),
            }
        return {
            "replicas": replicas,
            "queue_depth": queue,
            "occupancy_mean": round(occ / occ_n, 4) if occ_n else 0.0,
            "pages_in_use": pages,
            "counters": counters,
        }

    # ---- Prometheus merge --------------------------------------------------
    def merged_registry(self, snaps=None):
        """A fresh MetricsRegistry holding every source series widened
        with its origin label (``rank=`` / ``replica=``): labeled
        families stay grouped under one ``# HELP``/``# TYPE`` after the
        merge, which is what a real scraper of the aggregated /varz
        requires (asserted against the strict exposition parser)."""
        if snaps is None:
            snaps, _ = load_snapshots(self.dirs)
        # same fences as merge(): the exposition and the JSON view of one
        # directory must agree — a dead publisher's gauges must not
        # outlive it in /varz-style dashboards either
        snaps, _ = self._fence_stale(snaps)
        _, _, kept, _ = self._fence(snaps)
        by_id = self._dedupe(kept)
        sources = self._metric_sources(by_id)
        merged = MetricsRegistry()
        for s in sources:
            if s.get("role") == "replica":
                # replica indexes repeat across frontend processes: the
                # origin label carries the full identity
                label_key = "replica"
                label_val = f"{s.get('rank', 0)}@{self._source_id(s)}"
            else:
                label_key = "rank"
                label_val = str(s.get("rank", 0))
            extra = {label_key: label_val}
            for rec in s.get("metrics", ()):
                labels = dict(rec.get("labels") or {})
                if label_key in labels:
                    # the record already uses the origin key as a label
                    # (e.g. serving.replica.*{replica=...}): disambiguate
                    # under a secondary key instead of dropping — replica
                    # NAMES repeat across frontend processes, and
                    # first-wins would discard every process after the
                    # first (shared-registry twins were already collapsed
                    # by _metric_sources, so a key collision here is
                    # always a distinct source)
                    extra_for_rec = {"origin": label_val}
                else:
                    extra_for_rec = extra
                merged.load_series(rec, extra_labels=extra_for_rec)
        return merged

    def to_prometheus(self, snaps=None):
        """The merged fleet /varz payload."""
        return self.merged_registry(snaps).to_prometheus()


def bench_block():
    """The ``extra.fleet`` block for the bench contracts (ISSUE 11
    satellite): publish this process's snapshot (into the configured
    telemetry dir, or a scratch dir), aggregate, and distill — snapshot
    count, the worst cross-rank phase skew, straggler verdicts — so every
    bench run records cluster health next to its perf numbers."""
    import tempfile

    d = env_str("PADDLE_TELEMETRY_DIR")
    scratch = None
    if not d:
        scratch = tempfile.mkdtemp(prefix="paddle_fleet_bench_")
        d = scratch
    try:
        SnapshotPublisher(d, rank=env_int("PADDLE_TRAINER_ID", 0),
                          min_interval_s=0.0).publish()
        agg = FleetAggregator(d, registry=MetricsRegistry())
        view = agg.collect()
        phases = view.get("phases") or {}
        max_skew, skew_phase = 0.0, None
        for fam, e in phases.items():
            if e["skew"] > max_skew:
                max_skew, skew_phase = e["skew"], fam
        strag = view.get("straggler") or {}
        return {
            "snapshots": len(view.get("members") or {}),
            "generation": view.get("generation"),
            "fenced_out": view.get("fenced_out"),
            "max_skew": round(max_skew, 4),
            "skew_phase": skew_phase,
            "stragglers": {r: info["verdict"]
                           for r, info in (strag.get("ranks") or {}).items()
                           if info["verdict"] != "ok"},
        }
    except Exception as e:  # the bench line must land regardless
        return {"error": f"{type(e).__name__}: {str(e)[:200]}"}
    finally:
        if scratch is not None:
            import shutil

            shutil.rmtree(scratch, ignore_errors=True)


# ---------------------------------------------------------------------------
# cluster serving rollup (live, in-frontend)
# ---------------------------------------------------------------------------
def serving_rollup(replica_snapshots, slo_report, goodput_report):
    """The ``serving_report()["fleet"]`` block: one cluster-level view —
    per-replica burn inputs are already cluster-scoped (the SLO monitor
    spans every dispatcher), so this distills replicas + burn + goodput
    into the single ``pressure``/``scale_hint`` signal an autoscaler
    reads, and publishes the ``fleet.serving.*`` gauges a scraper joins
    with the training-side fleet view."""
    states = [s.get("state") for s in replica_snapshots.values()]
    live = sum(1 for st in states if st == "LIVE")
    queue_depth = sum(s.get("pending") or 0
                      for s in replica_snapshots.values())
    # occupancy over LIVE replicas only, matching the slots accounting:
    # averaging in DEAD replicas' zero occupancy dilutes the pressure
    # signal exactly when the survivors are saturated — the moment an
    # autoscaler most needs to hear "grow"
    occs, slots = [], 0
    for s in replica_snapshots.values():
        max_seqs = s.get("max_seqs") or 0
        if max_seqs and s.get("state") == "LIVE":
            occs.append((s.get("active") or 0) / max_seqs)
            slots += max_seqs
    occupancy_mean = round(sum(occs) / len(occs), 4) if occs else 0.0
    # cluster KV fabric (ISSUE 18): advertised prefix residency summed
    # across replicas — the router scores placement against this index,
    # so the rollup is how an operator sees the cluster cache's size
    kv_resident = sum(s.get("kv_resident") or 0
                      for s in replica_snapshots.values())
    # the multi-window AND: an objective pages only when BOTH windows
    # burn, so min(fast, slow) is the page-relevant burn per objective
    worst_burn, worst_objective = 0.0, None
    for name, r in (slo_report.get("objectives") or {}).items():
        burn = min(r.get("fast", 0.0), r.get("slow", 0.0))
        if burn > worst_burn:
            worst_burn, worst_objective = burn, name
    alerts = slo_report.get("alerts") or []
    queue_pressure = (min(1.0, queue_depth / slots) if slots
                      else (1.0 if queue_depth else 0.0))
    pressure = round(max(occupancy_mean, queue_pressure), 4)
    if alerts or (live == 0 and states):
        scale_hint = "grow"
    elif pressure > 0.85:
        scale_hint = "grow"
    elif pressure < 0.15 and live > 1 and worst_burn < 1.0:
        scale_hint = "shrink"
    else:
        scale_hint = "hold"
    _registry.gauge(
        "fleet.serving.live_replicas",
        help="replicas currently LIVE in this serving cell").set(live)
    _registry.gauge(
        "fleet.serving.queue_depth",
        help="cluster-wide routed-but-not-admitted requests").set(
        queue_depth)
    _registry.gauge(
        "fleet.serving.occupancy_mean",
        help="mean decode-slot occupancy across replicas").set(
        occupancy_mean)
    _registry.gauge(
        "fleet.serving.worst_burn",
        help="worst min(fast, slow) SLO burn rate across objectives"
    ).set(round(worst_burn, 4))
    _registry.gauge(
        "fleet.serving.pressure",
        help="blended autoscaling pressure signal (0..1)").set(pressure)
    _registry.gauge(
        "fleet.serving.kv_resident",
        help="cluster KV-fabric prefix entries advertised across "
             "replicas").set(kv_resident)
    # per-role sub-rollup (ISSUE 16): a disaggregated fleet's prefill and
    # decode pools saturate independently, so each role gets its own
    # pressure + scale_hint — the supervisor scales the pools off these,
    # and a blended mean can no longer hide one saturated pool behind the
    # other's idle slots. Homogeneous fleets roll up as one "blended" role.
    by_role = {}
    for s in replica_snapshots.values():
        role = s.get("role") or "blended"
        r = by_role.setdefault(role, {"replicas": 0, "live": 0,
                                      "queue_depth": 0, "occs": [],
                                      "slots": 0})
        r["replicas"] += 1
        r["queue_depth"] += s.get("pending") or 0
        max_seqs = s.get("max_seqs") or 0
        if s.get("state") == "LIVE":
            r["live"] += 1
            if max_seqs:
                r["occs"].append((s.get("active") or 0) / max_seqs)
                r["slots"] += max_seqs
    roles = {}
    for role, r in sorted(by_role.items()):
        occ = (round(sum(r["occs"]) / len(r["occs"]), 4)
               if r["occs"] else 0.0)
        qp = (min(1.0, r["queue_depth"] / r["slots"]) if r["slots"]
              else (1.0 if r["queue_depth"] else 0.0))
        p = round(max(occ, qp), 4)
        if alerts or (r["live"] == 0 and r["replicas"]):
            hint = "grow"
        elif p > 0.85:
            hint = "grow"
        elif p < 0.15 and r["live"] > 1 and worst_burn < 1.0:
            hint = "shrink"
        else:
            hint = "hold"
        _registry.gauge(
            "serving.role.pressure", labels={"role": role},
            help="per-role autoscaling pressure (0..1) — prefill/decode "
                 "pools saturate independently").set(p)
        _registry.gauge(
            "serving.role.live_replicas", labels={"role": role},
            help="LIVE replicas per disaggregation role").set(r["live"])
        roles[role] = {"replicas": r["replicas"], "live": r["live"],
                       "queue_depth": r["queue_depth"],
                       "occupancy_mean": occ, "pressure": p,
                       "scale_hint": hint}
    return {
        "replicas": len(replica_snapshots),
        "live_replicas": live,
        "queue_depth": queue_depth,
        "occupancy_mean": occupancy_mean,
        "goodput": {k: round(v, 4) for k, v in
                    (goodput_report.get("fractions") or {}).items()},
        "slo": {
            "worst_burn": round(worst_burn, 4),
            "worst_objective": worst_objective,
            "alerting": [a.get("objective") for a in alerts],
        },
        "pressure": pressure,
        "scale_hint": scale_hint,
        "roles": roles,
        "kv_resident": kv_resident,
    }
