"""Live introspection HTTP endpoint (ISSUE 7): /statusz, /varz, /tracez,
/healthz — zero dependencies (stdlib ``http.server``), served from the
serving frontend (``ServingFrontend(statusz_port=...)``) and the launcher
(``--statusz_port``), and startable standalone around any process that
publishes into the telemetry registry.

Routes (GET only):

- ``/statusz``  — JSON overview: process facts, uptime, telemetry state,
  training + serving goodput splits, the serving control-plane report
  (replica health, per-SLO-class latency, SLO burn rates) when a frontend
  is attached.
- ``/varz``     — the metrics registry in Prometheus text exposition format
  (``text/plain; version=0.0.4``) — point a real scraper at it.
- ``/tracez``   — recent request traces: the slowest N and the errored N
  (full span records — the live sibling of ``scripts/trace_view.py``).
- ``/compilez`` — the XLA compile ledger (ISSUE 8): per-program compile
  counts/wall, churned programs, in-flight compiles, cache sizes.
- ``/memz``     — the HBM budget ledger: components (params/optimizer/KV
  pool) vs device capacity, per-program ``memory_analysis()`` harvests
  (``?analyze=1`` forces the lazy harvest).
- ``/fleetz``   — the fleet view (ISSUE 11): merged per-rank/per-replica
  snapshots — members, quorum, phase skew, straggler verdicts, serving
  rollup (``?refresh=1`` forces a fresh merge).
- ``/dynamicsz`` — training dynamics (ISSUE 13): per-monitor layer groups,
  grad/param/update norms, loss spike z, non-finite provenance, the
  recent spill window, and the flight recorder's bundle ledger.
- ``/profilez`` — the on-demand xprof capture: ``?steps=K`` arms a
  capture of the next K train steps via the flight recorder's capture
  registry, ``?disarm=1`` cancels it; bare GET returns capture status +
  history.
- ``/perfz``    — device-time profiling (ISSUE 17): per-program
  device-seconds, achieved FLOP/s and bandwidth, MFU, roofline verdicts,
  the serving decode-token budget and the training step split
  (``?program=<key-prefix>`` filters, ``?analyze=1`` forces the cost
  harvest).
- ``/healthz``  — liveness: 200 with per-replica / per-rank heartbeat ages,
  503 when nothing can serve (no LIVE replica) or every heartbeat is stale.

Dispatch is table-driven (``self.routes``): the 404 body's route listing
derives from the same dict, so a new route can never be silently omitted.

The server binds 127.0.0.1 by default (introspection is an operator
surface, not a public one) and ``port=0`` picks a free port (tests). All
payload builders are plain methods, unit-testable without sockets.
"""
import json
import os
import threading
import time

from ..utils.envs import env_str
from . import compilemem, goodput, request_trace, tracing
from .metrics import registry as _registry

__all__ = ["StatusServer"]


class StatusServer:
    """One daemon HTTP server exposing the process's telemetry.

    ``frontend`` (optional) is a ServingFrontend — /statusz gains its
    ``serving_report()`` and /healthz its replica states. ``telemetry_dir``
    (optional, defaults to ``PADDLE_TELEMETRY_DIR``) lets /healthz reuse
    the PR-2 heartbeat files the watchdog reads."""

    def __init__(self, port=0, host="127.0.0.1", frontend=None,
                 telemetry_dir=None, heartbeat_stale_s=60.0,
                 tracez_n=10, elastic_info=None, fleet=None):
        self.host = host
        self.port = int(port)
        self.frontend = frontend
        # elastic membership provider (ISSUE 9): the launcher passes a
        # callable with its live view (generation/world/parked); worker
        # processes fall back to their env contract
        self.elastic_info = elastic_info
        # fleet aggregator (ISSUE 11): the launcher passes its live
        # FleetAggregator; standalone servers build one lazily over
        # telemetry_dir on the first /fleetz hit
        self.fleet = fleet
        self.telemetry_dir = (telemetry_dir
                              or env_str("PADDLE_TELEMETRY_DIR"))
        self.heartbeat_stale_s = float(heartbeat_stale_s)
        self.tracez_n = int(tracez_n)
        self._t0 = time.time()
        self._httpd = None
        self._thread = None
        # THE dispatch table: every route — handler, 404 listing, docs
        # test — derives from this one dict, so a new route cannot be
        # silently omitted from the listing (ISSUE 11 satellite). Each
        # handler takes the query string and returns (code, body, ctype).
        self.routes = {
            "/statusz": self._route_json(lambda q: (200, self.statusz())),
            "/varz": lambda q: (200, self.varz(),
                                "text/plain; version=0.0.4"),
            "/tracez": self._route_json(lambda q: (200, self.tracez())),
            "/compilez": self._route_json(
                lambda q: (200, self.compilez())),
            "/memz": self._route_json(
                lambda q: (200, self.memz(analyze="analyze=1" in q))),
            "/fleetz": self._route_json(
                lambda q: (200, self.fleetz(refresh="refresh=1" in q))),
            "/dynamicsz": self._route_json(
                lambda q: (200, self.dynamicsz())),
            "/profilez": self._route_json(
                lambda q: (200, self.profilez(q))),
            "/perfz": self._route_json(
                lambda q: (200, self.perfz(q))),
            "/kvz": self._route_json(lambda q: (200, self.kvz())),
            "/tenantz": self._route_json(lambda q: (200, self.tenantz())),
            "/healthz": self._route_json(lambda q: self.healthz()),
        }

    @staticmethod
    def _route_json(fn):
        def handler(query):
            code, payload = fn(query)
            return (code, json.dumps(payload, indent=1, default=str),
                    "application/json")
        return handler

    def route_names(self):
        """The live route listing (served in the 404 body) — derived from
        the dispatch table, never hand-maintained."""
        return sorted(self.routes)

    # ---- payload builders (plain methods: no sockets needed to test) ------
    def statusz(self):
        out = {
            "pid": os.getpid(),
            "time": time.time(),
            "uptime_s": round(time.time() - self._t0, 3),
            "telemetry_enabled": tracing.enabled(),
            "telemetry_dir": self.telemetry_dir,
            "goodput": goodput.report(),
            "serving_goodput": goodput.serving.report(),
            "traces": {
                "started": getattr(_registry.get("rtrace.traces"),
                                   "value", 0),
                "open": getattr(_registry.get("rtrace.open"), "value", 0),
                "dropped_spans": getattr(
                    _registry.get("rtrace.dropped_spans"), "value", 0),
                "recent": len(request_trace.recent()),
            },
            "metrics": len(_registry.names()),
            "elastic": self._elastic(),
        }
        fe = self.frontend
        if fe is not None:
            try:
                out["serving"] = fe.serving_report()
            except Exception as e:  # a shut-down frontend must not 500
                out["serving"] = {"error": f"{type(e).__name__}: {e}"}
        return out

    def kvz(self):
        """Cluster KV fabric view (ISSUE 18): tier hit/fallthrough
        counters, spill-ring occupancy, residency by owner — the
        frontend fabric's ``report()``, armored like every other route
        (a frontend-less or shut-down server answers shaped JSON)."""
        fe = self.frontend
        fab = getattr(fe, "kvfabric", None) if fe is not None else None
        if fab is None:
            return {"enabled": False,
                    "error": "no serving frontend (or no KV fabric) bound"}
        try:
            return fab.report()
        except Exception as e:
            return {"enabled": False, "error": f"{type(e).__name__}: {e}"}

    def tenantz(self):
        """Multi-tenant serving view (ISSUE 19): per-tenant quota/bucket/
        inflight state, private brownout rung, SLO burn rates, and
        tenant-labeled latency summaries, plus the LoRA adapter cache —
        the frontend's ``tenant_report()``, armored like /kvz (a
        frontend-less or shut-down server answers shaped JSON)."""
        fe = self.frontend
        if fe is None or not hasattr(fe, "tenant_report"):
            return {"error": "no serving frontend (or no tenant plane) "
                             "bound"}
        try:
            out = {"tenants": fe.tenant_report()}
            adapters = getattr(fe, "adapters", None)
            if adapters is not None:
                out["adapters"] = adapters.report()
            return out
        except Exception as e:
            return {"error": f"{type(e).__name__}: {e}"}

    def _elastic(self):
        """Elastic membership view: the configured provider (launcher), or
        this process's env contract (worker), or a fixed-width default."""
        if self.elastic_info is not None:
            try:
                return self.elastic_info()
            except Exception as e:
                return {"error": f"{type(e).__name__}: {e}"}
        # armored parses: /statusz must survive exactly the malformed env a
        # misconfigured worker is being debugged FOR
        from ..utils.envs import env_int

        out = {
            "generation": env_int("PADDLE_ELASTIC_GENERATION", 0),
            "world_size": env_int("PADDLE_TRAINERS_NUM", 0) or None,
            "live_ranks": None,
        }
        raw = env_str("PADDLE_ELASTIC_RANKS")
        if raw:
            try:
                out["live_ranks"] = [int(r) for r in raw.split(",")
                                     if r.strip()]
            except ValueError:
                pass
        return out

    def varz(self):
        return _registry.to_prometheus()

    def tracez(self):
        return {
            "recent": len(request_trace.recent()),
            "dropped_spans": getattr(
                _registry.get("rtrace.dropped_spans"), "value", 0),
            "slowest": request_trace.slowest(self.tracez_n),
            "errored": request_trace.errored(self.tracez_n),
        }

    def compilez(self):
        """The compile ledger (ISSUE 8): per-key compile rollup, churned
        programs, recent events, in-flight compiles, cache sizes."""
        return compilemem.ledger.report()

    def memz(self, analyze=False):
        """The HBM budget ledger (ISSUE 8): components vs capacity, the
        captured programs and their memory analyses. ``?analyze=1``
        forces the lazy ``memory_analysis()`` harvest (one extra
        off-device compile per un-analyzed program — operator opt-in)."""
        return compilemem.memory.report(analyze=analyze)

    def fleetz(self, refresh=False):
        """The fleet view (ISSUE 11): merged per-rank/per-replica
        snapshots — members, quorum, cross-rank phase skew, straggler
        verdicts, serving rollup. A launcher-hosted aggregator serves its
        monitor thread's last view (``?refresh=1`` forces a fresh merge);
        a standalone server lazily builds an aggregator over its
        telemetry dir."""
        agg = self.fleet
        if agg is None:
            if not self.telemetry_dir:
                return {"error": "no telemetry dir configured "
                                 "(PADDLE_TELEMETRY_DIR or telemetry_dir=)"}
            from .fleet import FleetAggregator
            from .metrics import MetricsRegistry

            # scratch registry: a scrape-driven merge must not inject
            # cluster-level fleet.* gauges into THIS process's live
            # registry (its own snapshot publisher would re-export them
            # as if they were local series)
            agg = self.fleet = FleetAggregator(
                self.telemetry_dir, registry=MetricsRegistry())
        if callable(agg) and not hasattr(agg, "view"):
            return agg()  # provider callable (tests / custom hosts)
        # a launcher-hosted aggregator refreshes on its own monitor
        # cadence — serve its last view; a lazily-built standalone one has
        # no thread, so every scrape must merge fresh or the view freezes
        # at the first-ever request
        if getattr(agg, "_thread", None) is None:
            refresh = True
        return agg.view(refresh=refresh)

    def dynamicsz(self):
        """The training-dynamics view (ISSUE 13): every live monitor's
        layer groups, last spilled summary and recent window, plus the
        flight recorder's committed-bundle ledger."""
        from . import dynamics, flightrec

        return {
            "monitors": dynamics.reports(),
            "flight": flightrec.report(),
            "capture": flightrec.capture_status(),
        }

    def profilez(self, query):
        """The on-demand xprof capture surface (ISSUE 13):
        ``/profilez?steps=K`` arms a capture of the next K train steps
        through the flight recorder's capture registry;
        ``?disarm=1`` cancels/stops the armed capture (the remediation
        for a capture armed on a process that never steps — without it
        the one-capture slot would wedge until restart); a bare GET
        returns the armed/active capture and the completed-capture
        history."""
        import re as _re

        from . import flightrec

        if _re.search(r"(?:^|&)disarm=1", query or ""):
            return flightrec.disarm_capture()
        m = _re.search(r"(?:^|&)steps=(\d+)", query or "")
        if m:
            return flightrec.arm_capture(int(m.group(1)), trigger="http")
        return flightrec.capture_status()

    def perfz(self, query):
        """The device-time profiling surface (ISSUE 17): per-program
        device-seconds, MFU, and roofline verdicts from the devprof
        plane. ``?program=<key-prefix>`` filters rows (URL-encoded —
        program keys contain brackets); ``?analyze=1`` forces the
        compile-ledger cost harvest for not-yet-analyzed programs."""
        import re as _re
        import urllib.parse as _up

        from . import devprof

        program = None
        m = _re.search(r"(?:^|&)program=([^&]*)", query or "")
        if m and m.group(1):
            program = _up.unquote(m.group(1))
        return devprof.report(analyze="analyze=1" in (query or ""),
                              program=program)

    def _heartbeats(self):
        """{rank: age_s} from the PR-2 heartbeat files, when a telemetry
        dir is configured."""
        d = self.telemetry_dir
        if not d:
            return {}
        from .watchdog import _HB_RE

        out = {}
        try:
            names = os.listdir(d)
        except OSError:
            return out
        now = time.time()
        for name in names:
            m = _HB_RE.match(name)
            if not m:
                continue
            try:
                with open(os.path.join(d, name)) as f:
                    hb = json.load(f)
                out[m.group(1)] = round(now - hb.get("time", 0), 3)
            except (OSError, ValueError):
                continue
        return out

    def healthz(self):
        """(http_status, payload). ``status`` is the worst verdict across
        both signals — replica states and heartbeat ages — and the HTTP
        code follows it (503 iff ``unhealthy``), so a probe keying on
        either agrees with one keying on the other."""
        payload = {"uptime_s": round(time.time() - self._t0, 3)}
        status = "ok"
        fe = self.frontend
        if fe is not None:
            states = {r.name: r.state for r in fe.replicas}
            payload["replicas"] = states
            if any(s == "DEAD" for s in states.values()):
                status = "degraded"
            if not any(s == "LIVE" for s in states.values()):
                status = "unhealthy"
        hbs = self._heartbeats()
        if hbs:
            payload["heartbeat_age_s"] = hbs
            stale = {r: a for r, a in hbs.items()
                     if a > self.heartbeat_stale_s}
            if stale:
                payload["stale_ranks"] = sorted(stale)
                if len(stale) == len(hbs):
                    status = "unhealthy"
                elif status == "ok":
                    status = "degraded"
        payload["status"] = status
        return (503 if status == "unhealthy" else 200), payload

    # ---- HTTP ------------------------------------------------------------
    def start(self):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        server = self

        class _Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # no stderr spam from scrapers
                pass

            def _send(self, code, body, ctype):
                data = body.encode()
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                raw_path, _, query = self.path.partition("?")
                path = raw_path.rstrip("/") or "/statusz"
                handler = server.routes.get(path)
                try:
                    if handler is None:
                        # the listing IS the dispatch table: a route added
                        # above appears here by construction
                        self._send(404, json.dumps(
                            {"error": "not found",
                             "routes": server.route_names()}),
                            "application/json")
                    else:
                        code, body, ctype = handler(query)
                        self._send(code, body, ctype)
                except Exception as e:  # introspection must never crash
                    self._send(500, json.dumps(
                        {"error": f"{type(e).__name__}: {e}"}),
                        "application/json")

        self._httpd = ThreadingHTTPServer((self.host, self.port), _Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True,
                                        name="paddle-statusz")
        self._thread.start()
        return self

    def stop(self):
        httpd, self._httpd = self._httpd, None
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
