"""MoE layer (reference: python/paddle/incubate/distributed/models/moe/
moe_layer.py MoELayer; token dispatch collective ops
paddle/fluid/operators/collective/global_scatter_op.* / global_gather_op.*).

TPU-native redesign: instead of the reference's explicit
global_scatter → per-rank expert forward → global_gather over an NCCL
expert group, the layer is three einsums over dense dispatch/combine
tensors:

    dispatched = einsum('tec,tm->ecm', dispatch, tokens)
    expert_out = experts(dispatched)          # [E, C, M]
    output     = einsum('tec,ecm->tm', combine, expert_out)

With the expert dim E sharded on a mesh axis (``expert_axis``, default
"dp"), GSPMD lowers the two routing einsums to exactly the all_to_all pair
the reference implements by hand — but scheduled/overlapped by XLA over ICI.
"""
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .....framework.core import Tensor, apply
from .....nn import initializer as I
from .....nn.layer.container import LayerList
from .....nn.layer.layers import Layer
from .....tensor.einsum import einsum
from .gate import BaseGate, GShardGate, NaiveGate, SwitchGate


class _StackedExperts(Layer):
    """Marker base for stacked-weight expert banks ([E, ...] arrays, one
    batched einsum over all experts) — the TPU fast path MoELayer detects."""


class ExpertStack(_StackedExperts):
    """Stacked-weight expert FFN bank — the TPU fast path. All E experts'
    weights live in single [E, ...] arrays sharded on the expert mesh axis,
    so the expert forward is one batched einsum on the MXU (no Python loop,
    no per-expert kernel launches)."""

    def __init__(self, num_expert, d_model, d_hidden, activation="gelu", expert_axis="dp"):
        super().__init__()
        self.num_expert, self.d_model, self.d_hidden = num_expert, d_model, d_hidden
        self.activation = activation
        self.w1 = self.create_parameter([num_expert, d_model, d_hidden],
                                        default_initializer=I.XavierUniform())
        self.b1 = self.create_parameter([num_expert, 1, d_hidden], is_bias=True)
        self.w2 = self.create_parameter([num_expert, d_hidden, d_model],
                                        default_initializer=I.XavierUniform())
        self.b2 = self.create_parameter([num_expert, 1, d_model], is_bias=True)
        if expert_axis:
            self.w1.partition_spec = P(expert_axis, None, "mp")
            self.b1.partition_spec = P(expert_axis, None, "mp")
            self.w2.partition_spec = P(expert_axis, "mp", None)
            self.b2.partition_spec = P(expert_axis, None, None)
            for p in (self.w1, self.b1, self.w2, self.b2):
                p.is_distributed = True

    def forward(self, dispatched):
        """dispatched: [E, C, M] → [E, C, M]."""
        import jax.nn as jnn

        act = {"gelu": jnn.gelu, "relu": jnn.relu, "silu": jnn.silu}[self.activation]

        def fn(x, w1, b1, w2, b2):
            h = jnp.einsum("ecm,emh->ech", x, w1) + b1
            return jnp.einsum("ech,ehm->ecm", act(h), w2) + b2

        return apply(fn, dispatched, self.w1, self.b1, self.w2, self.b2, name="expert_stack")


class SwiGLUExpertStack(_StackedExperts):
    """Gated (LLaMA-style) expert FFN bank: silu(x@wg) * (x@wu) @ wd, all E
    experts stacked in [E, ...] arrays sharded on the expert axis — same
    one-batched-einsum MXU shape as ExpertStack, SwiGLU math."""

    def __init__(self, num_expert, d_model, d_hidden, expert_axis="dp"):
        super().__init__()
        self.num_expert, self.d_model, self.d_hidden = num_expert, d_model, d_hidden
        self.w_gate = self.create_parameter([num_expert, d_model, d_hidden],
                                            default_initializer=I.XavierUniform())
        self.w_up = self.create_parameter([num_expert, d_model, d_hidden],
                                          default_initializer=I.XavierUniform())
        self.w_down = self.create_parameter([num_expert, d_hidden, d_model],
                                            default_initializer=I.XavierUniform())
        if expert_axis:
            self.w_gate.partition_spec = P(expert_axis, None, "mp")
            self.w_up.partition_spec = P(expert_axis, None, "mp")
            self.w_down.partition_spec = P(expert_axis, "mp", None)
            for p in (self.w_gate, self.w_up, self.w_down):
                p.is_distributed = True

    def forward(self, dispatched):
        """dispatched: [E, C, M] → [E, C, M]."""
        import jax.nn as jnn

        def fn(x, wg, wu, wd):
            h = jnn.silu(jnp.einsum("ecm,emh->ech", x, wg)) * jnp.einsum(
                "ecm,emh->ech", x, wu)
            return jnp.einsum("ech,ehm->ecm", h, wd)

        return apply(fn, dispatched, self.w_gate, self.w_up, self.w_down,
                     name="swiglu_expert_stack")


class MoELayer(Layer):
    """reference signature: MoELayer(d_model, experts, gate, moe_group,
    recompute_interval). `experts` is either an ExpertStack (fast path) or a
    list/LayerList of arbitrary per-expert Layers (generic path: traced
    Python loop over E — fine for modest E, still batched per expert)."""

    def __init__(self, d_model, experts=None, gate=None, moe_group=None, mp_group=None,
                 recompute_interval=0, random_routing=False, expert_axis="dp", **kwargs):
        super().__init__()
        self.d_model = d_model
        if isinstance(gate, dict):  # reference accepts a gate config dict
            gate_type = gate.get("type", "gshard")
            default_n = experts.num_expert if isinstance(experts, _StackedExperts) else (
                len(experts) if experts is not None else 1)
            num_expert = gate.get("num_expert", default_n)
            top_k = gate.get("top_k", 2)
            cls = {"naive": NaiveGate, "gshard": GShardGate, "switch": SwitchGate}[gate_type]
            if gate_type == "switch":
                gate = cls(d_model, num_expert)
            elif gate_type == "gshard":
                gate = cls(d_model, num_expert, top_k=top_k, random_routing=random_routing)
            else:
                gate = cls(d_model, num_expert, top_k=top_k)
        if gate is None:
            num_expert = len(experts) if not isinstance(experts, _StackedExperts) else experts.num_expert
            gate = GShardGate(d_model, num_expert)
        self.gate = gate
        if isinstance(experts, (list, tuple)):
            experts = LayerList(experts)
        self.experts = experts
        self.num_expert = gate.tot_expert
        self.recompute_interval = recompute_interval
        self.expert_axis = expert_axis
        self.l_aux = None

    def forward(self, x):
        orig_shape = x.shape
        M = orig_shape[-1]
        from .....tensor import manipulation

        tokens = manipulation.reshape(x, [-1, M])  # [T, M]
        combine, dispatch, aux = self.gate(tokens)
        self.l_aux = aux

        dispatched = einsum("tec,tm->ecm", dispatch, tokens)  # [E, C, M]

        remat = self.recompute_interval > 0
        if remat:
            from .....distributed.fleet.recompute import recompute
        if isinstance(self.experts, _StackedExperts):
            # pass the Layer itself so recompute lifts its parameters as
            # differentiable inputs of the checkpointed region
            expert_out = recompute(self.experts, dispatched) if remat else self.experts(dispatched)
        else:
            outs = []
            for e, expert in enumerate(self.experts):
                outs.append(recompute(expert, dispatched[e]) if remat else expert(dispatched[e]))
            expert_out = manipulation.stack(outs, axis=0)
        out = einsum("tec,ecm->tm", combine, expert_out)  # [T, M]
        return manipulation.reshape(out, list(orig_shape[:-1]) + [M])


class MoE(MoELayer):
    """Back-compat alias (reference exposes both MoELayer and incubate MoE)."""
