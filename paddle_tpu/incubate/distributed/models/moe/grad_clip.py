"""MoE-aware global-norm clip (reference:
python/paddle/incubate/distributed/models/moe/grad_clip.py
ClipGradForMOEByGlobalNorm).

The reference computes the global norm in two parts — ordinary params
(norm all-reduced over the full world) and expert params (norm summed only
within the expert group) — because each rank holds distinct experts. Under
the single-controller global-view model, every jax.Array is already global,
so the two-part sum reduces to one norm over all grads; the class is kept
for script parity and for the is_expert_param partition logic.
"""
from .....nn.clip import ClipGradByGlobalNorm


def is_expert_param(p):
    return getattr(p, "is_distributed", False) and getattr(p, "no_sync", False)


class ClipGradForMOEByGlobalNorm(ClipGradByGlobalNorm):
    def __init__(self, clip_norm, is_expert_param_func=None, moe_group=None, group_name="default_moe_group"):
        super().__init__(clip_norm, group_name=group_name)
        self.is_expert_param_func = is_expert_param_func or is_expert_param
        self.moe_group = moe_group
