"""MoE gates (reference: python/paddle/incubate/distributed/models/moe/
gate/{naive_gate,gshard_gate,switch_gate}.py).

TPU-native formulation: a gate maps tokens [T, M] to dense dispatch/combine
tensors with a STATIC expert capacity —

    combine_weights [T, E, C]  (float; routing probabilities)
    dispatch_mask   [T, E, C]  (0/1 float; combine > 0)

so the whole MoE layer is three einsums that XLA partitions over the expert
mesh axis (the all_to_all the reference issues by hand via global_scatter/
global_gather becomes an XLA collective inserted by GSPMD). Static capacity
is what keeps shapes XLA-compilable; overflow tokens are dropped exactly as
in GShard/Switch.
"""
import jax
import jax.numpy as jnp

from .....framework.core import Tensor, apply
from .....nn import initializer as I
from .....nn.layer.layers import Layer


def _capacity(num_tokens, num_experts, top_k, capacity_factor):
    cap = int(capacity_factor * top_k * num_tokens / num_experts)
    return max(cap, 4)


def top_k_dispatch(probs, top_k, capacity, normalize=True):
    """GShard-style top-k routing with positional capacity assignment.

    probs: [T, E] routing probabilities. Returns
    (combine [T,E,C], dispatch [T,E,C], aux_loss scalar).
    Pure jnp — called under `apply` so gradients flow to the gate weight.
    """
    T, E = probs.shape
    gate_vals, gate_idx = jax.lax.top_k(probs, top_k)  # [T, k]
    if normalize:
        gate_vals = gate_vals / (gate_vals.sum(-1, keepdims=True) + 1e-9)
    mask = jax.nn.one_hot(gate_idx, E, dtype=probs.dtype)  # [T, k, E]

    # position-in-expert: all 1st choices queue before any 2nd choice
    # (k-major cumsum), matching GShard's priority rule.
    mask_kmaj = jnp.transpose(mask, (1, 0, 2)).reshape(top_k * T, E)
    pos_kmaj = jnp.cumsum(mask_kmaj, axis=0) - mask_kmaj
    pos = jnp.transpose(pos_kmaj.reshape(top_k, T, E), (1, 0, 2))  # [T, k, E]

    keep = (pos < capacity).astype(probs.dtype) * mask  # [T, k, E]
    pos_in_e = (pos * mask).sum(-1).astype(jnp.int32)  # [T, k]
    onehot_c = jax.nn.one_hot(pos_in_e, capacity, dtype=probs.dtype)  # [T, k, C]
    combine = jnp.einsum("tke,tk,tkc->tec", keep, gate_vals, onehot_c)
    dispatch = (combine > 0).astype(probs.dtype)

    # load-balancing auxiliary loss (GShard eq. for top-1 fraction)
    me = probs.mean(axis=0)  # mean router prob per expert
    first_choice = mask[:, 0, :]
    ce = first_choice.mean(axis=0)  # fraction of tokens whose 1st choice is e
    aux = (me * ce).sum() * E
    return combine, dispatch, aux


class BaseGate(Layer):
    def __init__(self, d_model, num_expert, world_size=1, top_k=2, capacity_factor=1.25):
        super().__init__()
        self.d_model = d_model
        self.num_expert = num_expert
        self.world_size = world_size
        self.tot_expert = num_expert * world_size
        self.top_k = top_k
        self.capacity_factor = capacity_factor
        self.weight = self.create_parameter(
            [d_model, self.tot_expert], default_initializer=I.XavierUniform()
        )
        self._loss = None

    def get_loss(self, clear=True):
        loss = self._loss
        if clear:
            self._loss = None
        return loss

    def set_loss(self, loss):
        self._loss = loss

    def _route(self, x, noise="none", noise_eps=0.0):
        """x: [T, M] Tensor → (combine, dispatch, aux_loss) Tensors.

        noise: "none" | "mult_uniform" (Switch: logits × U[1-eps, 1+eps]) |
        "gumbel" (GShard random_routing: stochastic tie-breaking). Keys come
        from the framework RNG stream (framework/random.py next_key) so the
        draw differs per step / per traced call, eagerly and under jit.
        """
        from .....framework import random as prandom

        T = x.shape[0]
        cap = _capacity(T, self.tot_expert, self.top_k, self.capacity_factor)
        k = self.top_k
        key = prandom.next_key() if noise != "none" else None

        def fn(xx, w):
            logits = xx @ w
            if noise == "mult_uniform":
                u = jax.random.uniform(key, logits.shape, logits.dtype,
                                       1.0 - noise_eps, 1.0 + noise_eps)
                logits = logits * u
            elif noise == "gumbel":
                g = jax.random.gumbel(key, logits.shape, logits.dtype)
                logits = logits + noise_eps * g
            probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1).astype(xx.dtype)
            return top_k_dispatch(probs, k, cap)

        combine, dispatch, aux = apply(fn, x, self.weight, name="moe_gate")
        self.set_loss(aux)
        return combine, dispatch, aux

    def forward(self, x):
        return self._route(x)


class NaiveGate(BaseGate):
    """Plain learned top-k gate, no noise (reference: gate/naive_gate.py)."""


class GShardGate(BaseGate):
    """Top-2 gate with load-balance aux loss (reference: gate/gshard_gate.py).
    random_routing: stochastic second-choice routing during training,
    realized as gumbel perturbation of the logits (reference randomly accepts
    the 2nd expert proportional to its gate value — same exploration effect,
    expressed as a shape-static perturbation XLA can compile)."""

    def __init__(self, d_model, num_expert, world_size=1, top_k=2, capacity=(1.2, 2.4),
                 random_routing=True, group=None):
        cf = capacity[0] if isinstance(capacity, (tuple, list)) else capacity
        super().__init__(d_model, num_expert, world_size, top_k=top_k, capacity_factor=cf)
        self.random_routing = random_routing

    def forward(self, x):
        if self.random_routing and self.training:
            return self._route(x, noise="gumbel", noise_eps=0.01)
        return self._route(x)


class SwitchGate(BaseGate):
    """Top-1 switch-transformer gate (reference: gate/switch_gate.py)."""

    def __init__(self, d_model, num_expert, world_size=1, top_k=1, switch_eps=0.1,
                 capacity=(1.2, 2.4), group=None):
        cf = capacity[0] if isinstance(capacity, (tuple, list)) else capacity
        super().__init__(d_model, num_expert, world_size, top_k=1, capacity_factor=cf)
        self.switch_eps = switch_eps

    def forward(self, x):
        if self.training and self.switch_eps:
            return self._route(x, noise="mult_uniform", noise_eps=self.switch_eps)
        return self._route(x)
