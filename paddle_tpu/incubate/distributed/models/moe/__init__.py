from .gate import BaseGate, GShardGate, NaiveGate, SwitchGate, top_k_dispatch  # noqa: F401
from .grad_clip import ClipGradForMOEByGlobalNorm  # noqa: F401
from .moe_layer import MoE, ExpertStack, MoELayer, SwiGLUExpertStack  # noqa: F401
