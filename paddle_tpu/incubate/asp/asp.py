"""ASP workflow (reference: python/paddle/incubate/asp/asp.py — ASPHelper,
decorate → OptimizerWithSparsityGuarantee, prune_model)."""
import jax.numpy as jnp
import numpy as np

from ...framework.core import Tensor
from ...nn.layer.common import Linear
from ...nn.layer.conv import Conv2D
from .utils import CheckMethod, MaskAlgo, check_sparsity, create_mask

_SUPPORTED_TYPES = {Linear, Conv2D}
_EXCLUDED = set()


def set_excluded_layers(param_names, main_program=None):
    _EXCLUDED.update(param_names)


def reset_excluded_layers(main_program=None):
    _EXCLUDED.clear()


def add_supported_layer(layer_type):
    _SUPPORTED_TYPES.add(layer_type)


class ASPHelper:
    MASK_APPENDDED_NAME = "asp_mask"
    masks = {}  # param name -> np mask

    @classmethod
    def _is_supported_param(cls, model, name, param):
        if name in _EXCLUDED:
            return False
        if param.ndim < 2:
            return False
        # only params of supported layer types (weight, not bias)
        owner = name.rsplit(".", 1)[0] if "." in name else ""
        leaf = name.rsplit(".", 1)[-1]
        if leaf != "weight":
            return False
        sub = model
        try:
            for part in owner.split(".") if owner else []:
                sub = getattr(sub, part)
        except AttributeError:
            return False  # can't resolve owner layer → don't prune blindly
        # prune only FC/Conv weights (reference ASP supported-layer set);
        # embeddings/norms etc. must never be 2:4-pruned
        return any(isinstance(sub, t) for t in _SUPPORTED_TYPES)

    @classmethod
    def prune_model(cls, model, n=2, m=4, mask_algo=MaskAlgo.MASK_1D, with_mask=True):
        cls.masks.clear()
        for name, p in model.named_parameters():
            if not cls._is_supported_param(model, name, p):
                continue
            w = np.asarray(p.numpy())
            mask = create_mask(w, mask_algo, n, m)
            p._data = jnp.asarray(w * mask, p._data.dtype)
            if with_mask:
                cls.masks[name] = mask
        return cls.masks


def prune_model(model, n=2, m=4, mask_algo="mask_1d", with_mask=True):
    """Prune supported weights to n:m sparsity, record masks for training."""
    algo = MaskAlgo(mask_algo) if not isinstance(mask_algo, MaskAlgo) else mask_algo
    return ASPHelper.prune_model(model, n, m, algo, with_mask)


class OptimizerWithSparsityGuarantee:
    """Re-applies the pruning masks after every optimizer step so pruned
    weights stay exactly zero (reference: same-named class)."""

    def __init__(self, optimizer, model):
        self._inner = optimizer
        self._model = model

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def step(self):
        self._inner.step()
        params = dict(self._model.named_parameters())
        for name, mask in ASPHelper.masks.items():
            p = params.get(name)
            if p is not None:
                p._data = p._data * jnp.asarray(mask, p._data.dtype)

    def clear_grad(self, *a, **k):
        return self._inner.clear_grad(*a, **k)


def decorate(optimizer, model=None):
    """reference: asp.decorate(optimizer). The model binds at decorate time
    (our optimizers don't back-reference the Layer)."""
    if model is None:
        raise ValueError("paddle_tpu asp.decorate needs the model: decorate(opt, model)")
    return OptimizerWithSparsityGuarantee(optimizer, model)
