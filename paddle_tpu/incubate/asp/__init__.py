"""Automatic SParsity (reference: python/paddle/incubate/asp/ —
asp.py decorate/prune_model, utils.py 2:4 mask kernels
check_mask_1d/get_mask_1d/check_mask_2d/get_mask_2d_greedy/best).

n:m structured sparsity: every group of m consecutive weights keeps the n
largest by magnitude. Masks are applied on prune and re-applied by the
decorated optimizer after each step so pruned weights stay zero through
training (the reference's OptimizerWithSparsityGuarantee).
"""
from .asp import (
    ASPHelper,
    add_supported_layer,
    decorate,
    prune_model,
    reset_excluded_layers,
    set_excluded_layers,
)
from .utils import (
    CheckMethod,
    MaskAlgo,
    check_mask_1d,
    check_mask_2d,
    check_sparsity,
    create_mask,
    get_mask_1d,
    get_mask_2d_best,
    get_mask_2d_greedy,
)

__all__ = [
    "decorate", "prune_model", "set_excluded_layers", "reset_excluded_layers",
    "add_supported_layer", "ASPHelper",
    "create_mask", "check_sparsity", "get_mask_1d", "check_mask_1d",
    "get_mask_2d_greedy", "get_mask_2d_best", "check_mask_2d",
    "MaskAlgo", "CheckMethod",
]
