"""n:m sparsity mask algorithms (reference: python/paddle/incubate/asp/utils.py
— get_mask_1d, get_mask_2d_greedy, get_mask_2d_best, checkers)."""
import itertools
from enum import Enum

import numpy as np


class MaskAlgo(Enum):
    MASK_1D = "mask_1d"
    MASK_2D_GREEDY = "mask_2d_greedy"
    MASK_2D_BEST = "mask_2d_best"


class CheckMethod(Enum):
    CHECK_1D = "check_1d"
    CHECK_2D = "check_2d"

    @staticmethod
    def get_checking_method(mask_algo):
        return CheckMethod.CHECK_1D if mask_algo == MaskAlgo.MASK_1D else CheckMethod.CHECK_2D


def _reshape_1d(mat, m):
    """Pad the flattened last dim to a multiple of m and view as rows of m."""
    flat = mat.reshape(mat.shape[0], -1)
    pad = (-flat.shape[1]) % m
    if pad:
        flat = np.concatenate([flat, np.zeros((flat.shape[0], pad), flat.dtype)], 1)
    return flat, pad


def get_mask_1d(mat, n, m):
    """Keep the n largest |w| in every m consecutive weights along rows."""
    mat = np.asarray(mat)
    flat, pad = _reshape_1d(mat, m)
    groups = flat.reshape(-1, m)
    order = np.argsort(-np.abs(groups), axis=1)[:, :n]
    mask = np.zeros_like(groups)
    np.put_along_axis(mask, order, 1.0, axis=1)
    mask = mask.reshape(flat.shape)
    if pad:
        mask = mask[:, :-pad]
    return mask.reshape(mat.shape)


def check_mask_1d(mat, n, m):
    mat = np.asarray(mat)
    flat, pad = _reshape_1d(mat, m)
    groups = flat.reshape(-1, m)
    return bool(np.all((groups != 0).sum(1) <= n))


def get_mask_2d_greedy(mat, n, m):
    """m×m block-wise greedy: pick entries largest-first while keeping each
    row and column of the block ≤ n nonzeros."""
    mat = np.asarray(mat)
    h, w = mat.shape
    ph, pw = (-h) % m, (-w) % m
    padded = np.pad(np.abs(mat), ((0, ph), (0, pw)))
    H, W = padded.shape
    mask = np.zeros_like(padded)
    for bi in range(0, H, m):
        for bj in range(0, W, m):
            block = padded[bi : bi + m, bj : bj + m]
            order = np.dstack(np.unravel_index(np.argsort(-block, axis=None), (m, m)))[0]
            rows = np.zeros(m, int)
            cols = np.zeros(m, int)
            for r, c in order:
                if rows[r] < n and cols[c] < n:
                    mask[bi + r, bj + c] = 1.0
                    rows[r] += 1
                    cols[c] += 1
    return mask[:h, :w]


def get_mask_2d_best(mat, n, m):
    """Exhaustive best m×m mask (small m only) — maximizes retained |w| sum
    over row-and-column n:m patterns; falls back to greedy for m > 4."""
    mat = np.asarray(mat)
    if m > 4:
        return get_mask_2d_greedy(mat, n, m)
    # all binary m×m masks with each row/col summing to n — precompute once
    patterns = _valid_patterns(n, m)
    h, w = mat.shape
    ph, pw = (-h) % m, (-w) % m
    padded = np.pad(np.abs(mat), ((0, ph), (0, pw)))
    H, W = padded.shape
    mask = np.zeros_like(padded)
    for bi in range(0, H, m):
        for bj in range(0, W, m):
            block = padded[bi : bi + m, bj : bj + m]
            scores = np.einsum("pij,ij->p", patterns, block)
            mask[bi : bi + m, bj : bj + m] = patterns[int(np.argmax(scores))]
    return mask[:h, :w]


_PATTERN_CACHE = {}


def _valid_patterns(n, m):
    key = (n, m)
    if key in _PATTERN_CACHE:
        return _PATTERN_CACHE[key]
    rows = [p for p in itertools.product((0.0, 1.0), repeat=m) if sum(p) == n]
    out = []
    for combo in itertools.product(rows, repeat=m):
        arr = np.asarray(combo)
        if np.all(arr.sum(0) == n):
            out.append(arr)
    pats = np.stack(out)
    _PATTERN_CACHE[key] = pats
    return pats


def check_mask_2d(mat, n, m):
    mat = np.asarray(mat)
    h, w = mat.shape
    for bi in range(0, h - m + 1, m):
        for bj in range(0, w - m + 1, m):
            block = mat[bi : bi + m, bj : bj + m] != 0
            if np.any(block.sum(0) > n) or np.any(block.sum(1) > n):
                return False
    return True


def create_mask(tensor, func_name=MaskAlgo.MASK_1D, n=2, m=4):
    t = np.asarray(tensor)
    shape = t.shape
    if t.ndim == 1:
        mat = t.reshape(1, -1)
    elif t.ndim == 2:
        mat = t
    elif t.ndim == 4:
        # conv weights [O,I,H,W] → [O, I*H*W] (reference layout handling)
        mat = t.reshape(shape[0], -1)
    else:
        mat = t.reshape(shape[0], -1)
    algo = MaskAlgo(func_name) if not isinstance(func_name, MaskAlgo) else func_name
    if algo == MaskAlgo.MASK_1D:
        mask = get_mask_1d(mat, n, m)
    elif algo == MaskAlgo.MASK_2D_GREEDY:
        mask = get_mask_2d_greedy(mat, n, m)
    else:
        mask = get_mask_2d_best(mat, n, m)
    return mask.reshape(shape).astype(t.dtype)


def check_sparsity(tensor, func_name=CheckMethod.CHECK_1D, n=2, m=4):
    t = np.asarray(tensor)
    mat = t.reshape(t.shape[0], -1) if t.ndim != 2 else t
    method = CheckMethod(func_name) if not isinstance(func_name, CheckMethod) else func_name
    if method == CheckMethod.CHECK_1D:
        return check_mask_1d(mat, n, m)
    return check_mask_2d(mat, n, m)
