"""incubate fused functionals (reference: python/paddle/incubate/nn/functional/
— fused_rotary_position_embedding, fused_rms_norm, fused_linear...).

On TPU these are jnp compositions XLA fuses into adjacent matmuls; rope gets
a Pallas kernel upgrade path in paddle_tpu/ops/.
"""
import jax
import jax.numpy as jnp

from ...framework.core import Tensor, apply, to_tensor


@jax.custom_vjp
def _barrier_diff(xs):
    return jax.lax.optimization_barrier(xs)


def _barrier_diff_fwd(xs):
    return jax.lax.optimization_barrier(xs), None


def _barrier_diff_bwd(_, cts):
    # upstream's rule exactly: the transpose is a barrier on the cotangents,
    # which is what sequences the unrolled backward chunks
    return (jax.lax.optimization_barrier(cts),)


_barrier_diff.defvjp(_barrier_diff_fwd, _barrier_diff_bwd)
_OPT_BARRIER = None  # resolved on first use


def _opt_barrier(xs):
    """lax.optimization_barrier with a differentiation fallback: releases
    before ~0.5 ship the primitive without a grad rule, so the unrolled
    fused-CE chain (differentiable chunk-loss token) would fail to
    transpose there. The custom_vjp twin is semantically identical."""
    global _OPT_BARRIER
    if _OPT_BARRIER is None:
        try:
            jax.grad(lambda x: jax.lax.optimization_barrier((x,))[0].sum())(
                jnp.ones((1,), jnp.float32))
            _OPT_BARRIER = jax.lax.optimization_barrier
        except NotImplementedError:
            _OPT_BARRIER = _barrier_diff
    return _OPT_BARRIER(xs)


def _t(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


def fused_linear(x, weight, bias=None, transpose_weight=False, name=None):
    from ...nn import functional as F
    from ...tensor import linalg

    if transpose_weight:
        weight = linalg.t(weight)
    return F.linear(x, weight, bias)


def fused_rms_norm(x, norm_weight, norm_bias=None, epsilon=1e-6, begin_norm_axis=-1, **kw):
    from ...nn.functional.norm import rms_norm

    out = rms_norm(x, norm_weight, epsilon)
    if norm_bias is not None:
        out = out + _t(norm_bias)
    return out


def fused_layer_norm(x, norm_weight, norm_bias, epsilon=1e-5, **kw):
    from ...nn.functional.norm import layer_norm

    return layer_norm(x, [_t(x).shape[-1]], norm_weight, norm_bias, epsilon)


def fused_bias_dropout_residual_layer_norm(x, residual, bias=None, ln_scale=None, ln_bias=None,
                                           dropout_rate=0.0, ln_epsilon=1e-5, training=True):
    from ...nn import functional as F

    y = x if bias is None else x + _t(bias)
    y = F.dropout(y, dropout_rate, training=training)
    y = y + residual
    return F.layer_norm(y, [y.shape[-1]], ln_scale, ln_bias, ln_epsilon)


def rope_rotate(x, cos, sin):
    """Rotate-half rope application on [B, S, H, D]."""
    x1, x2 = jnp.split(x, 2, axis=-1)
    rotated = jnp.concatenate([-x2, x1], axis=-1)
    return x * cos + rotated * sin


def fused_rotary_position_embedding(q, k=None, v=None, sin=None, cos=None, position_ids=None,
                                    use_neox_rotary_style=True, rotary_emb_base=10000.0):
    """reference: incubate fused_rope (phi/kernels/fusion/gpu/fused_rope*). Computes
    sin/cos on the fly if not given. Layout [batch, seq, heads, head_dim]."""
    q = _t(q)
    B, S, H, D = q.shape
    if sin is None or cos is None:
        inv = 1.0 / (rotary_emb_base ** (jnp.arange(0, D, 2, dtype=jnp.float32) / D))
        pos = jnp.arange(S, dtype=jnp.float32)
        freqs = jnp.outer(pos, inv)  # S, D/2
        emb = jnp.concatenate([freqs, freqs], axis=-1)
        cos_a = jnp.cos(emb)[None, :, None, :]
        sin_a = jnp.sin(emb)[None, :, None, :]
    else:
        cos_a = _t(cos)._data
        sin_a = _t(sin)._data
        if cos_a.ndim == 2:
            cos_a = cos_a[None, :, None, :]
            sin_a = sin_a[None, :, None, :]
    if position_ids is not None:
        pid = _t(position_ids)._data  # B, S
        cos_a = jnp.take(cos_a[0, :, 0, :], pid, axis=0)[:, :, None, :]
        sin_a = jnp.take(sin_a[0, :, 0, :], pid, axis=0)[:, :, None, :]

    outs = []
    for t in (q, k, v):
        if t is None:
            outs.append(None)
            continue
        t = _t(t)
        outs.append(apply(lambda a: rope_rotate(a.astype(jnp.float32), cos_a, sin_a).astype(a.dtype), t, name="fused_rope"))
    return tuple(outs)


def fused_dropout_add(x, y, p=0.0, training=True, mode="upscale_in_train"):
    from ...nn import functional as F

    return F.dropout(x, p, training=training, mode=mode) + _t(y)


def swiglu(x, y=None, name=None):
    """LLaMA MLP gate: silu(x) * y (reference: phi swiglu fusion kernel)."""
    if y is None:
        a, b = jnp.split(_t(x)._data, 2, axis=-1)
        return apply(lambda v: jax.nn.silu(v[..., : v.shape[-1] // 2]) * v[..., v.shape[-1] // 2 :], _t(x), name="swiglu")
    return apply(lambda a, b: jax.nn.silu(a) * b, _t(x), _t(y), name="swiglu")


def fused_multi_head_attention(x, qkv_weight, linear_weight, pre_layer_norm=False,
                               pre_ln_scale=None, pre_ln_bias=None, ln_scale=None,
                               ln_bias=None, pre_ln_epsilon=1e-5, qkv_bias=None,
                               linear_bias=None, cache_kv=None, attn_mask=None,
                               dropout_rate=0.5, attn_dropout_rate=0.5,
                               ln_epsilon=1e-5, training=True,
                               mode="upscale_in_train", ring_id=-1,
                               add_residual=True, name=None):
    """reference: incubate.nn.functional.fused_multi_head_attention
    (fused_attention CUDA kernel): [pre-LN ->] qkv matmul -> MHA (+mask,
    attn dropout) -> out proj -> dropout -> [+residual] [-> post-LN], in
    the reference's weight layout qkv_weight [3, H, Dh, D], qkv_bias
    [3, H, Dh]. One traced expression here — XLA produces the fusion the
    reference hand-wrote.
    """
    from ...nn import functional as NF
    from ...tensor import linalg, manipulation

    if cache_kv is not None:
        raise NotImplementedError(
            "decode caches are served by GenerationMixin.generate (generation.py)"
        )
    three, H, Dh, D = qkv_weight.shape
    if three != 3 or D != x.shape[-1]:
        raise ValueError(f"qkv_weight must be [3, H, Dh, D={x.shape[-1]}], got {qkv_weight.shape}")
    B, S = x.shape[0], x.shape[1]
    residual = x
    h = x
    if pre_layer_norm:
        h = NF.layer_norm(h, [D], weight=pre_ln_scale, bias=pre_ln_bias,
                          epsilon=pre_ln_epsilon)
    w2d = manipulation.transpose(manipulation.reshape(qkv_weight, [3 * H * Dh, D]), [1, 0])
    qkv = linalg.matmul(h, w2d)  # [B, S, 3*H*Dh]
    if qkv_bias is not None:
        qkv = qkv + manipulation.reshape(qkv_bias, [3 * H * Dh])
    qkv = manipulation.reshape(qkv, [B, S, 3, H, Dh])
    q = qkv[:, :, 0]
    k = qkv[:, :, 1]
    v = qkv[:, :, 2]
    out = NF.scaled_dot_product_attention(
        q, k, v, attn_mask=attn_mask, dropout_p=attn_dropout_rate,
        is_causal=False, training=training,
    )
    out = manipulation.reshape(out, [B, S, H * Dh])
    out = linalg.matmul(out, linear_weight)
    if linear_bias is not None:
        out = out + linear_bias
    out = NF.dropout(out, p=dropout_rate, training=training, mode=mode)
    if add_residual:
        out = residual + out
    if not pre_layer_norm:
        out = NF.layer_norm(out, [D], weight=ln_scale, bias=ln_bias, epsilon=ln_epsilon)
    return out


def fused_linear_cross_entropy(hidden, weight, labels, ignore_index=-100,
                               chunk_size=None, reduction="mean",
                               checkpoint_chunks=True, name=None):
    """Cross-entropy straight from hidden states — the [N, vocab] logits
    tensor is never materialized (reference analogue: fused softmax-CE
    kernels in paddle/phi/kernels/fusion/ + PaddleNLP's parallel CE; here the
    memory win matters most: O(chunk·vocab) live instead of O(N·vocab)).

    hidden [..., H] (any leading dims), weight [H, V], labels [...] int.
    chunk_size (default 4096, or FLAGS_fused_ce_chunk_size) trades peak
    memory against loop count; a single-chunk call skips the loop entirely
    so XLA sees one fused matmul+softmax. checkpoint_chunks=False keeps
    chunk logits live for the backward (faster when memory allows); True
    recomputes them, so peak is one chunk of logits fwd + one bwd.
    Chunked matmuls stay MXU-sized for chunk_size ≥ 512.

    When the static chunk count is ≤ FLAGS_fused_ce_unroll (default 0 =
    disabled) the chunk loop is unrolled into the trace instead of lowered
    to an XLA while-loop: the r5 xprof trace of the headline training shape
    billed 8.2% of device-busy time to while-loop control for a 3-iteration
    CE loop (xprof_traces/tpu/20260731T043440). Each unrolled chunk is
    chained through `lax.optimization_barrier` on the previous chunk's loss
    so both the forward and the transposed backward schedule sequentially,
    preserving the one-chunk live-logits bound. OPT-IN until measured on
    chip: XLA *CPU* strips opt-barrier during optimization (verified — the
    barriers are in the StableHLO but absent from the optimized module, and
    unconstrained unrolled chunks overlap to 2.5× the loop's temp at the
    8192×32000 probe shape), so the memory bound is only enforceable on
    TPU, where opt-barrier is honored. scripts/perf_exp.py variants 11/12
    measure it on the headline shape.
    """
    import os

    if chunk_size is None:
        chunk_size = int(os.environ.get("FLAGS_fused_ce_chunk_size", 4096))
    hidden = _t(hidden)
    weight = _t(weight)
    labels = _t(labels)

    def fn(h, w, lab):
        hs = h.reshape(-1, h.shape[-1])
        ls = lab.reshape(-1).astype(jnp.int32)
        n, hd = hs.shape
        c = min(chunk_size, n)

        def chunk_fn(args):
            hc, lc = args
            logits = jnp.matmul(hc, w, preferred_element_type=jnp.float32)
            lse = jax.scipy.special.logsumexp(logits, axis=-1)
            safe = jnp.clip(lc, 0, logits.shape[-1] - 1)
            ll = jnp.take_along_axis(logits, safe[:, None], axis=-1)[:, 0]
            valid = lc != ignore_index
            return jnp.where(valid, lse - ll, 0.0), valid

        if c >= n:
            body = jax.checkpoint(chunk_fn) if checkpoint_chunks else chunk_fn
            losses, valids = body((hs, ls))
        else:
            pad = (-n) % c
            if pad:
                hs = jnp.concatenate([hs, jnp.zeros((pad, hd), hs.dtype)], 0)
                ls = jnp.concatenate([ls, jnp.full((pad,), ignore_index, ls.dtype)], 0)
            hs = hs.reshape(-1, c, hd)
            ls = ls.reshape(-1, c)
            body = jax.checkpoint(chunk_fn) if checkpoint_chunks else chunk_fn
            unroll_limit = int(os.environ.get("FLAGS_fused_ce_unroll", 0))
            if hs.shape[0] <= unroll_limit:
                # Unrolled chunks alone let XLA overlap them, holding several
                # chunk-logits buffers live at once (measured 2.5x the loop's
                # temp at the 8192x32000 probe shape — worse than the full
                # logits fused-CE exists to avoid). Chaining each chunk's
                # input through an optimization_barrier with the previous
                # chunk's output forces sequential scheduling: while-loop
                # gone, same one-chunk live-memory bound.
                # The chain token must be DIFFERENTIABLE (the chunk loss):
                # the barrier's transpose then also sequences the backward —
                # chunk i's cotangent chain completes only after chunk i+1's
                # remat+grad, which is where the peak actually lives.
                outs = []
                token = jnp.zeros((1,), jnp.float32)
                for i in range(hs.shape[0]):
                    hc, _ = _opt_barrier((hs[i], token))
                    li, vi = body((hc, ls[i]))
                    token = li[:1]
                    outs.append((li, vi))
                losses = jnp.stack([o[0] for o in outs])
                valids = jnp.stack([o[1] for o in outs])
            else:
                losses, valids = jax.lax.map(body, (hs, ls))
        total = jnp.sum(losses)
        count = jnp.sum(valids)
        if reduction == "mean":
            return total / jnp.maximum(count, 1)
        if reduction == "sum":
            return total
        return losses.reshape(-1)[: lab.size].reshape(lab.shape)

    return apply(fn, hidden, weight, labels, name="fused_linear_cross_entropy")


def segment_sum(data, segment_ids, name=None):
    """reference: incubate.segment_sum — jax.ops.segment_sum, the TPU-native
    lowering of the phi segment kernels."""
    import jax

    d, s = _t(data), _t(segment_ids)
    n = int(jnp.max(s._data)) + 1 if s._data.size else 0
    return apply(lambda a, i: jax.ops.segment_sum(a, i, num_segments=n), d, s,
                 name="segment_sum")


def _segment_reduce(reducer):
    import jax

    def op(data, segment_ids, name=None):
        d, s = _t(data), _t(segment_ids)
        n = int(jnp.max(s._data)) + 1 if s._data.size else 0

        def fn(a, i):
            out = reducer(a, i, n)
            # empty segments → 0 (paddle semantics), detected by COUNT so
            # integer sentinels and legitimate ±inf values both survive
            cnt = jax.ops.segment_sum(jnp.ones(i.shape, jnp.int32), i, num_segments=n)
            cnt = cnt.reshape(cnt.shape + (1,) * (out.ndim - 1))
            return jnp.where(cnt > 0, out, jnp.zeros((), out.dtype))

        return apply(fn, d, s, name="segment_reduce")

    return op


def _seg_mean(a, i, n):
    import jax

    tot = jax.ops.segment_sum(a, i, num_segments=n)
    cnt = jax.ops.segment_sum(jnp.ones(a.shape[:1], a.dtype), i, num_segments=n)
    cnt = cnt.reshape(cnt.shape + (1,) * (a.ndim - 1))
    return tot / jnp.maximum(cnt, 1)


def _seg_max(a, i, n):
    import jax

    return jax.ops.segment_max(a, i, num_segments=n)


def _seg_min(a, i, n):
    import jax

    return jax.ops.segment_min(a, i, num_segments=n)


segment_mean = _segment_reduce(_seg_mean)
segment_max = _segment_reduce(_seg_max)
segment_min = _segment_reduce(_seg_min)


def softmax_mask_fuse(x, mask, name=None):
    """reference: incubate.softmax_mask_fuse — additive mask + softmax in
    one fused expression (XLA fuses into adjacent matmuls)."""
    return apply(
        lambda a, m: jax.nn.softmax(a.astype(jnp.float32) + m.astype(jnp.float32), axis=-1).astype(a.dtype),
        _t(x), _t(mask), name="softmax_mask_fuse",
    )


def softmax_mask_fuse_upper_triangle(x, name=None):
    def fn(a):
        s = a.shape[-1]
        mask = jnp.tril(jnp.ones((a.shape[-2], s), bool), k=s - a.shape[-2])
        logits = jnp.where(mask, a.astype(jnp.float32), jnp.finfo(jnp.float32).min)
        return jax.nn.softmax(logits, axis=-1).astype(a.dtype)

    return apply(fn, _t(x), name="softmax_mask_fuse_upper_triangle")


def graph_send_recv(x, src_index, dst_index, reduce_op="sum", out_size=None, name=None):
    """reference: incubate.graph_send_recv — gather messages at src, reduce
    at dst (segment reduction over edges)."""
    import jax

    if reduce_op not in ("sum", "max", "min", "mean"):
        raise ValueError(f"graph_send_recv: unsupported reduce_op {reduce_op!r}")
    xd, si, di = _t(x), _t(src_index), _t(dst_index)
    n = out_size or int(xd.shape[0])
    red = {"sum": jax.ops.segment_sum, "max": jax.ops.segment_max,
           "min": jax.ops.segment_min}.get(reduce_op)

    def fn(a, s, d):
        msgs = a[s]
        cnt = jax.ops.segment_sum(jnp.ones(d.shape, jnp.int32), d, num_segments=n)
        cshape = cnt.reshape(cnt.shape + (1,) * (a.ndim - 1))
        if red is not None:
            out = red(msgs, d, num_segments=n)
            if reduce_op in ("max", "min"):
                out = jnp.where(cshape > 0, out, jnp.zeros((), out.dtype))
            return out
        tot = jax.ops.segment_sum(msgs, d, num_segments=n)
        return tot / jnp.maximum(cshape, 1).astype(tot.dtype)

    return apply(fn, xd, si, di, name="graph_send_recv")
