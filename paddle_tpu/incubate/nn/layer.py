"""incubate fused layers (reference: python/paddle/incubate/nn/layer/...)."""
from ...nn import functional as F
from ...nn import initializer as I
from ...nn.layer.layers import Layer


class FusedLinear(Layer):
    def __init__(self, in_features, out_features, weight_attr=None, bias_attr=None, transpose_weight=False, name=None):
        super().__init__()
        if transpose_weight:
            shape = [out_features, in_features]
        else:
            shape = [in_features, out_features]
        self.weight = self.create_parameter(shape, attr=weight_attr, default_initializer=I.XavierNormal())
        self.bias = None if bias_attr is False else self.create_parameter([out_features], attr=bias_attr, is_bias=True)
        self.transpose_weight = transpose_weight

    def forward(self, x):
        from . import functional as FF

        return FF.fused_linear(x, self.weight, self.bias, self.transpose_weight)


class FusedMultiHeadAttention(Layer):
    def __init__(self, embed_dim, num_heads, dropout_rate=0.0, attn_dropout_rate=0.0, **kw):
        super().__init__()
        from ...nn.layer.transformer import MultiHeadAttention

        self.inner = MultiHeadAttention(embed_dim, num_heads, attn_dropout_rate)

    def forward(self, query, key=None, value=None, attn_mask=None):
        return self.inner(query, key, value, attn_mask)


class FusedFeedForward(Layer):
    def __init__(self, d_model, dim_feedforward, dropout_rate=0.1, activation="relu", **kw):
        super().__init__()
        from ...nn.layer.common import Dropout, Linear

        self.linear1 = Linear(d_model, dim_feedforward)
        self.linear2 = Linear(dim_feedforward, d_model)
        self.dropout = Dropout(dropout_rate)
        self.activation = getattr(F, activation)

    def forward(self, x):
        return self.linear2(self.dropout(self.activation(self.linear1(x))))


class FusedMultiTransformer(Layer):
    """reference: incubate.nn.FusedMultiTransformer (fused_multi_transformer
    kernel — the inference-fused N-layer transformer the reference builds
    from hand-written fused CUDA ops).

    TPU-native redesign: all per-layer weights live STACKED with a leading
    [num_layers] dim and the forward is one `lax.scan` over layers — XLA
    traces a single block and fuses LN + qkv matmul + attention + FFN per
    iteration, which is the whole point of the reference's fused kernel.
    Weight layout (own, MXU-friendly — not the reference's [3, H, Dh, D]):
    qkv_weight [L, D, 3D], linear_weight [L, D, D], ffn1 [L, D, F],
    ffn2 [L, F, D]; LN params [L, D].

    Inference-path layer: dropout_rate must be 0 (the reference's is also
    serving-oriented); training uses nn.TransformerEncoder. KV-cache decode
    lives in generation.py (fixed-shape cache + jitted loop), not here.

    attn_mask: None (full), "causal", or an additive float mask
    broadcastable to [B, H, S, S].
    """

    def __init__(self, embed_dim, num_heads, dim_feedforward, dropout_rate=0.0,
                 activation="gelu", normalize_before=True, ln_scale_attrs=None,
                 ln_bias_attrs=None, epsilon=1e-5, num_layers=-1, nranks=1,
                 trans_qkvw=True, ring_id=-1, name=None):
        super().__init__()
        if dropout_rate:
            raise ValueError(
                "FusedMultiTransformer is the inference-fused path: "
                "dropout_rate must be 0 (train with nn.TransformerEncoder)"
            )
        if embed_dim % num_heads:
            raise ValueError(f"embed_dim {embed_dim} not divisible by heads {num_heads}")
        if num_layers < 1:
            raise ValueError("num_layers must be >= 1 (pass it explicitly)")
        self.embed_dim, self.num_heads = embed_dim, num_heads
        self.head_dim = embed_dim // num_heads
        self.normalize_before = normalize_before
        self.epsilon = epsilon
        self.num_layers = num_layers
        self._act = activation
        L, D, FF = num_layers, embed_dim, dim_feedforward
        mk = self.create_parameter
        ones = I.Constant(1.0)
        zeros = I.Constant(0.0)
        xav = I.XavierNormal()
        self.ln_scale = mk([L, D], default_initializer=ones)
        self.ln_bias = mk([L, D], default_initializer=zeros, is_bias=True)
        self.qkv_weight = mk([L, D, 3 * D], default_initializer=xav)
        self.qkv_bias = mk([L, 3 * D], default_initializer=zeros, is_bias=True)
        self.linear_weight = mk([L, D, D], default_initializer=xav)
        self.linear_bias = mk([L, D], default_initializer=zeros, is_bias=True)
        self.ffn_ln_scale = mk([L, D], default_initializer=ones)
        self.ffn_ln_bias = mk([L, D], default_initializer=zeros, is_bias=True)
        self.ffn1_weight = mk([L, D, FF], default_initializer=xav)
        self.ffn1_bias = mk([L, FF], default_initializer=zeros, is_bias=True)
        self.ffn2_weight = mk([L, FF, D], default_initializer=xav)
        self.ffn2_bias = mk([L, D], default_initializer=zeros, is_bias=True)

    def forward(self, src, attn_mask=None, caches=None, pre_caches=None,
                rotary_embs=None, rotary_emb_dims=0, time_step=None):
        import jax
        import jax.numpy as jnp

        from ...framework.core import apply

        if caches is not None or pre_caches is not None:
            raise NotImplementedError(
                "KV-cache decode is served by GenerationMixin.generate "
                "(fixed-shape cache, generation.py)"
            )
        H, Dh, eps = self.num_heads, self.head_dim, self.epsilon
        pre_ln = self.normalize_before
        act = {"gelu": jax.nn.gelu, "relu": jax.nn.relu}[self._act]
        causal = isinstance(attn_mask, str) and attn_mask == "causal"
        add_mask = None if (attn_mask is None or causal) else attn_mask

        def ln(x, s, b):
            mu = jnp.mean(x, -1, keepdims=True)
            var = jnp.var(x, -1, keepdims=True)
            return (x - mu) * jax.lax.rsqrt(var + eps) * s + b

        def run(x, *ws, mask=None):
            def block(h, w):
                (ln_s, ln_b, qkv_w, qkv_b, out_w, out_b,
                 f_ln_s, f_ln_b, f1_w, f1_b, f2_w, f2_b) = w
                B, S, D = h.shape
                a_in = ln(h, ln_s, ln_b) if pre_ln else h
                qkv = (a_in @ qkv_w + qkv_b).reshape(B, S, 3, H, Dh)
                q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
                logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) / jnp.sqrt(
                    jnp.asarray(Dh, h.dtype)
                )
                if causal:
                    cm = jnp.tril(jnp.ones((S, S), bool))
                    logits = jnp.where(cm, logits, jnp.finfo(logits.dtype).min)
                if mask is not None:
                    logits = logits + mask
                probs = jax.nn.softmax(logits.astype(jnp.float32), -1).astype(h.dtype)
                attn = jnp.einsum("bhqk,bkhd->bqhd", probs, v).reshape(B, S, D)
                attn = attn @ out_w + out_b
                h = h + attn if pre_ln else ln(h + attn, ln_s, ln_b)
                f_in = ln(h, f_ln_s, f_ln_b) if pre_ln else h
                f = act(f_in @ f1_w + f1_b) @ f2_w + f2_b
                h = h + f if pre_ln else ln(h + f, f_ln_s, f_ln_b)
                return h, None

            out, _ = jax.lax.scan(block, x, ws)
            return out

        ws = (self.ln_scale, self.ln_bias, self.qkv_weight, self.qkv_bias,
              self.linear_weight, self.linear_bias, self.ffn_ln_scale,
              self.ffn_ln_bias, self.ffn1_weight, self.ffn1_bias,
              self.ffn2_weight, self.ffn2_bias)
        if add_mask is not None:
            return apply(lambda x, m, *w: run(x, *w, mask=m), src, add_mask, *ws,
                         name="fused_multi_transformer")
        return apply(run, src, *ws, name="fused_multi_transformer")
