"""incubate fused layers (reference: python/paddle/incubate/nn/layer/...)."""
from ...nn import functional as F
from ...nn import initializer as I
from ...nn.layer.layers import Layer


class FusedLinear(Layer):
    def __init__(self, in_features, out_features, weight_attr=None, bias_attr=None, transpose_weight=False, name=None):
        super().__init__()
        if transpose_weight:
            shape = [out_features, in_features]
        else:
            shape = [in_features, out_features]
        self.weight = self.create_parameter(shape, attr=weight_attr, default_initializer=I.XavierNormal())
        self.bias = None if bias_attr is False else self.create_parameter([out_features], attr=bias_attr, is_bias=True)
        self.transpose_weight = transpose_weight

    def forward(self, x):
        from . import functional as FF

        return FF.fused_linear(x, self.weight, self.bias, self.transpose_weight)


class FusedMultiHeadAttention(Layer):
    def __init__(self, embed_dim, num_heads, dropout_rate=0.0, attn_dropout_rate=0.0, **kw):
        super().__init__()
        from ...nn.layer.transformer import MultiHeadAttention

        self.inner = MultiHeadAttention(embed_dim, num_heads, attn_dropout_rate)

    def forward(self, query, key=None, value=None, attn_mask=None):
        return self.inner(query, key, value, attn_mask)


class FusedFeedForward(Layer):
    def __init__(self, d_model, dim_feedforward, dropout_rate=0.1, activation="relu", **kw):
        super().__init__()
        from ...nn.layer.common import Dropout, Linear

        self.linear1 = Linear(d_model, dim_feedforward)
        self.linear2 = Linear(dim_feedforward, d_model)
        self.dropout = Dropout(dropout_rate)
        self.activation = getattr(F, activation)

    def forward(self, x):
        return self.linear2(self.dropout(self.activation(self.linear1(x))))
