from . import functional
from .layer import FusedLinear, FusedMultiHeadAttention, FusedFeedForward
