from . import functional
from .layer import FusedFeedForward, FusedLinear, FusedMultiHeadAttention, FusedMultiTransformer  # noqa: F401
