"""paddle.incubate parity (reference: python/paddle/incubate/) — fused nn
ops and distributed extras. On TPU, "fused" means XLA/Pallas fusion."""
from . import distributed, nn
from .nn import functional

from . import asp
from .optimizer import DistributedFusedLamb  # noqa: F401
from .nn.functional import (  # noqa: F401
    graph_send_recv,
    segment_max,
    segment_mean,
    segment_min,
    segment_sum,
    softmax_mask_fuse,
    softmax_mask_fuse_upper_triangle,
)
