"""paddle.incubate parity (reference: python/paddle/incubate/) — fused nn
ops and distributed extras. On TPU, "fused" means XLA/Pallas fusion."""
from . import distributed, nn
from .nn import functional

from . import asp
