"""incubate distributed optimizers (reference:
python/paddle/incubate/optimizer/distributed_fused_lamb.py)."""
from ..optimizer.optimizers import Lamb


class DistributedFusedLamb(Lamb):
    """reference: incubate.DistributedFusedLamb — LAMB with flattened/fused
    parameter storage, gradient allreduce, and optimizer states sharded
    across the data-parallel group.

    TPU-native mapping: every "distributed fused" mechanism the reference
    hand-builds is the compiled step's job here —

    - fused flat storage & fused kernel: the whole update is ONE XLA program
      (TrainStep jits every per-param `_rule` together; XLA fuses);
    - grad allreduce + `is_grad_scaled_by_nranks`: DistributedTrainStep's
      mean-psum over the batch axes;
    - sharded optimizer states: `sharding_stage>=1` shards the moment/master
      slots over the `sharding` mesh axis (XLA weight-update sharding);
    - `clip_after_allreduce`: global-norm clip always sees post-reduction
      grads inside the compiled step, so True is the only semantics.

    The class therefore carries the reference's constructor surface, applies
    the LAMB math (decoupled decay mask per `exclude_from_weight_decay_fn`),
    and validates the knobs that would silently change numerics.
    """

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9,
                 beta2=0.999, epsilon=1e-6, parameters=None, grad_clip=None,
                 exclude_from_weight_decay_fn=None, clip_after_allreduce=True,
                 is_grad_scaled_by_nranks=True, alignment=128,
                 use_master_param_norm=True, gradient_accumulation_steps=1,
                 use_master_acc_grad=True, nproc_per_node=-1, name=None):
        if not clip_after_allreduce:
            raise ValueError(
                "clip_after_allreduce=False is unrepresentable here: the "
                "compiled step clips the already-reduced gradient"
            )
        super().__init__(
            learning_rate=learning_rate, lamb_weight_decay=lamb_weight_decay,
            beta1=beta1, beta2=beta2, epsilon=epsilon, parameters=parameters,
            grad_clip=grad_clip,
            exclude_from_weight_decay_fn=exclude_from_weight_decay_fn,
            multi_precision=use_master_param_norm, name=name,
        )
        # accumulation is a TrainStep(accumulate_steps=...) concern; stored so
        # hapi/Engine can read it off the optimizer like the reference does
        self.gradient_accumulation_steps = int(gradient_accumulation_steps)
        self.is_grad_scaled_by_nranks = bool(is_grad_scaled_by_nranks)
