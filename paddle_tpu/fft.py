"""paddle.fft parity over jnp.fft (reference: python/paddle/fft.py)."""
import jax.numpy as jnp

from .framework.core import Tensor, apply, to_tensor


def _t(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


def _mk(fn):
    def op(x, n=None, axis=-1, norm="backward", name=None):
        return apply(lambda a: fn(a, n=n, axis=axis, norm=norm), _t(x))

    return op


def _mk_nd(fn):
    def op(x, s=None, axes=None, norm="backward", name=None):
        return apply(lambda a: fn(a, s=s, axes=axes, norm=norm), _t(x))

    return op


fft = _mk(jnp.fft.fft)
ifft = _mk(jnp.fft.ifft)
rfft = _mk(jnp.fft.rfft)
irfft = _mk(jnp.fft.irfft)
hfft = _mk(jnp.fft.hfft)
ihfft = _mk(jnp.fft.ihfft)
fft2 = _mk_nd(jnp.fft.fft2)
ifft2 = _mk_nd(jnp.fft.ifft2)
rfft2 = _mk_nd(jnp.fft.rfft2)
irfft2 = _mk_nd(jnp.fft.irfft2)
fftn = _mk_nd(jnp.fft.fftn)
ifftn = _mk_nd(jnp.fft.ifftn)
rfftn = _mk_nd(jnp.fft.rfftn)
irfftn = _mk_nd(jnp.fft.irfftn)


def fftfreq(n, d=1.0, dtype=None, name=None):
    return Tensor(jnp.fft.fftfreq(n, d))


def rfftfreq(n, d=1.0, dtype=None, name=None):
    return Tensor(jnp.fft.rfftfreq(n, d))


def fftshift(x, axes=None, name=None):
    return apply(lambda a: jnp.fft.fftshift(a, axes=axes), _t(x))


def ifftshift(x, axes=None, name=None):
    return apply(lambda a: jnp.fft.ifftshift(a, axes=axes), _t(x))
