"""Gumbel distribution (reference: python/paddle/distribution/gumbel.py)."""
import jax
import jax.numpy as jnp

from .distribution import Distribution, _data

_EULER = 0.57721566490153286


class Gumbel(Distribution):
    def __init__(self, loc, scale, name=None):
        (self.loc, self.scale), shape = self._validate_args(
            self._to_float(loc), self._to_float(scale)
        )
        super().__init__(batch_shape=shape)
        self._track(loc=loc, scale=scale)

    @property
    def mean(self):
        from ..framework.core import Tensor

        return Tensor(self.loc + self.scale * _EULER)

    @property
    def variance(self):
        from ..framework.core import Tensor

        return Tensor((jnp.pi**2 / 6) * self.scale**2)

    @property
    def stddev(self):
        from ..framework.core import Tensor

        return Tensor(jnp.sqrt((jnp.pi**2 / 6)) * self.scale)

    def _sample(self, key, shape):
        full = tuple(shape) + self._batch_shape
        g = jax.random.gumbel(key, full, self.loc.dtype)
        return self.loc + self.scale * g

    def log_prob(self, value):
        from ..framework.core import Tensor

        z = (_data(value) - self.loc) / self.scale
        return Tensor(-(z + jnp.exp(-z)) - jnp.log(self.scale))

    def entropy(self):
        from ..framework.core import Tensor

        return Tensor(jnp.log(self.scale) + 1 + _EULER)

    def cdf(self, value):
        from ..framework.core import Tensor

        z = (_data(value) - self.loc) / self.scale
        return Tensor(jnp.exp(-jnp.exp(-z)))
