"""Beta distribution (reference: python/paddle/distribution/beta.py)."""
import jax
import jax.numpy as jnp

from .distribution import Distribution, _data


class Beta(Distribution):
    def __init__(self, alpha, beta, name=None):
        (self.alpha, self.beta), shape = self._validate_args(
            self._to_float(alpha), self._to_float(beta)
        )
        super().__init__(batch_shape=shape)
        self._track(alpha=alpha, beta=beta)

    @property
    def mean(self):
        from ..framework.core import Tensor

        return Tensor(self.alpha / (self.alpha + self.beta))

    @property
    def variance(self):
        from ..framework.core import Tensor

        s = self.alpha + self.beta
        return Tensor(self.alpha * self.beta / (s**2 * (s + 1)))

    def _sample(self, key, shape):
        full = tuple(shape) + self._batch_shape
        return jax.random.beta(key, self.alpha, self.beta, full)

    def log_prob(self, value):
        from ..framework.core import Tensor

        v = _data(value)
        lbeta = (
            jax.scipy.special.gammaln(self.alpha)
            + jax.scipy.special.gammaln(self.beta)
            - jax.scipy.special.gammaln(self.alpha + self.beta)
        )
        return Tensor((self.alpha - 1) * jnp.log(v) + (self.beta - 1) * jnp.log1p(-v) - lbeta)

    def entropy(self):
        from ..framework.core import Tensor

        a, b = self.alpha, self.beta
        lbeta = (
            jax.scipy.special.gammaln(a) + jax.scipy.special.gammaln(b)
            - jax.scipy.special.gammaln(a + b)
        )
        dg = jax.scipy.special.digamma
        return Tensor(lbeta - (a - 1) * dg(a) - (b - 1) * dg(b) + (a + b - 2) * dg(a + b))
