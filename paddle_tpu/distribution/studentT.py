"""Student's t distribution (reference: python/paddle/distribution/studentT.py)."""
import jax
import jax.numpy as jnp

from .distribution import Distribution, _data


class StudentT(Distribution):
    def __init__(self, df, loc, scale, name=None):
        (self.df, self.loc, self.scale), shape = self._validate_args(
            self._to_float(df), self._to_float(loc), self._to_float(scale)
        )
        super().__init__(batch_shape=shape)
        self._track(df=df, loc=loc, scale=scale)

    @property
    def mean(self):
        from ..framework.core import Tensor

        return Tensor(jnp.where(self.df > 1, self.loc, jnp.nan))

    @property
    def variance(self):
        from ..framework.core import Tensor

        v = jnp.where(
            self.df > 2,
            self.scale**2 * self.df / (self.df - 2),
            jnp.where(self.df > 1, jnp.inf, jnp.nan),
        )
        return Tensor(v)

    def _sample(self, key, shape):
        full = tuple(shape) + self._batch_shape
        t = jax.random.t(key, self.df, full, self.loc.dtype)
        return self.loc + self.scale * t

    def log_prob(self, value):
        from ..framework.core import Tensor

        z = (_data(value) - self.loc) / self.scale
        df = self.df
        gl = jax.scipy.special.gammaln
        return Tensor(
            gl((df + 1) / 2) - gl(df / 2)
            - 0.5 * jnp.log(df * jnp.pi) - jnp.log(self.scale)
            - (df + 1) / 2 * jnp.log1p(z**2 / df)
        )

    def entropy(self):
        from ..framework.core import Tensor

        df = self.df
        dg = jax.scipy.special.digamma
        gl = jax.scipy.special.gammaln
        return Tensor(
            (df + 1) / 2 * (dg((df + 1) / 2) - dg(df / 2))
            + 0.5 * jnp.log(df) + gl(df / 2) - gl((df + 1) / 2)
            + 0.5 * jnp.log(jnp.pi) + jnp.log(self.scale)
        )
