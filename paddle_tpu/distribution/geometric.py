"""Geometric distribution (reference: python/paddle/distribution/geometric.py
— counts failures before first success, support {0, 1, 2, ...})."""
import jax
import jax.numpy as jnp

from .distribution import Distribution, _data


class Geometric(Distribution):
    def __init__(self, probs, name=None):
        self.probs = self._to_float(probs)
        super().__init__(batch_shape=jnp.shape(self.probs))
        self._track(probs=probs)

    @property
    def mean(self):
        from ..framework.core import Tensor

        return Tensor((1 - self.probs) / self.probs)

    @property
    def variance(self):
        from ..framework.core import Tensor

        return Tensor((1 - self.probs) / self.probs**2)

    @property
    def stddev(self):
        from ..framework.core import Tensor

        return Tensor(jnp.sqrt((1 - self.probs) / self.probs**2))

    def _sample(self, key, shape):
        full = tuple(shape) + self._batch_shape
        u = jax.random.uniform(key, full, self.probs.dtype, 1e-7, 1.0)
        return jnp.floor(jnp.log(u) / jnp.log1p(-self.probs))

    def log_prob(self, value):
        from ..framework.core import Tensor

        k = _data(value)
        return Tensor(k * jnp.log1p(-self.probs) + jnp.log(self.probs))

    def pmf(self, value):
        from ..framework.core import Tensor

        return Tensor(jnp.exp(self.log_prob(value)._data))

    def entropy(self):
        from ..framework.core import Tensor

        p = self.probs
        return Tensor(-((1 - p) * jnp.log1p(-p) + p * jnp.log(p)) / p)

    def cdf(self, value):
        from ..framework.core import Tensor

        k = _data(value)
        return Tensor(1 - jnp.power(1 - self.probs, k + 1))

    def kl_divergence(self, other):
        from ..framework.core import Tensor

        if isinstance(other, Geometric):
            p, q = self.probs, other.probs
            return Tensor(jnp.log(p / q) + ((1.0 - p) / p) * jnp.log((1.0 - p) / (1.0 - q)))
        return super().kl_divergence(other)
