"""Chi2 / ExponentialFamily / MultivariateNormal / ContinuousBernoulli
(reference: python/paddle/distribution/{chi2,exponential_family,
multivariate_normal,continuous_bernoulli}.py)."""
import jax
import jax.numpy as jnp

from .distribution import Distribution, _data
from .gamma import Gamma


class ExponentialFamily(Distribution):
    """reference: distribution/exponential_family.py — base class carrying
    the Bregman-divergence entropy identity. Subclasses define natural
    parameters and log_normalizer; entropy falls out via autodiff."""

    @property
    def _natural_parameters(self):
        raise NotImplementedError

    def _log_normalizer(self, *natural_params):
        raise NotImplementedError

    @property
    def _mean_carrier_measure(self):
        return 0.0

    def entropy(self):
        """-H = E[log p] via the exponential-family identity:
        entropy = logZ - sum(eta_i * dlogZ/deta_i) - E[carrier]."""
        from ..framework.core import Tensor

        nat = tuple(jnp.asarray(p) for p in self._natural_parameters)
        logz, grads = jax.value_and_grad(
            lambda etas: jnp.sum(self._log_normalizer(*etas)), argnums=0
        )(nat)
        ent = logz - sum(jnp.sum(e * g) for e, g in zip(nat, grads))
        return Tensor(ent - self._mean_carrier_measure)


class Chi2(Gamma):
    """reference: distribution/chi2.py — Gamma(df/2, rate=1/2)."""

    def __init__(self, df, name=None):
        df = self._to_float(df)
        super().__init__(concentration=df / 2.0, rate=jnp.full_like(jnp.asarray(df), 0.5))
        self.df = df

    def __repr__(self):
        return f"Chi2(df={self.df})"


class MultivariateNormal(Distribution):
    """reference: distribution/multivariate_normal.py — parameterized by
    loc + covariance_matrix (or precision_matrix / scale_tril)."""

    def __init__(self, loc, covariance_matrix=None, precision_matrix=None,
                 scale_tril=None, name=None):
        self.loc = jnp.asarray(_data(loc), jnp.float32)
        given = [a is not None for a in (covariance_matrix, precision_matrix, scale_tril)]
        if sum(given) != 1:
            raise ValueError(
                "exactly ONE of covariance_matrix / precision_matrix / "
                "scale_tril must be given"
            )
        if scale_tril is not None:
            self._scale_tril = jnp.asarray(_data(scale_tril), jnp.float32)
        elif covariance_matrix is not None:
            self._scale_tril = jnp.linalg.cholesky(
                jnp.asarray(_data(covariance_matrix), jnp.float32)
            )
        else:
            prec = jnp.asarray(_data(precision_matrix), jnp.float32)
            self._scale_tril = jnp.linalg.cholesky(jnp.linalg.inv(prec))
        super().__init__(batch_shape=self.loc.shape[:-1],
                         event_shape=self.loc.shape[-1:])

    @property
    def covariance_matrix(self):
        from ..framework.core import Tensor

        return Tensor(self._scale_tril @ jnp.swapaxes(self._scale_tril, -1, -2))

    @property
    def mean(self):
        from ..framework.core import Tensor

        return Tensor(self.loc)

    @property
    def variance(self):
        from ..framework.core import Tensor

        return Tensor(jnp.sum(jnp.square(self._scale_tril), axis=-1))

    def _sample(self, key, shape):
        full = tuple(shape) + self._batch_shape + self._event_shape
        eps = jax.random.normal(key, full)
        return self.loc + jnp.einsum("...ij,...j->...i", self._scale_tril, eps)

    def log_prob(self, value):
        from ..framework.core import Tensor

        v = jnp.asarray(_data(value), jnp.float32)
        d = v.shape[-1]
        diff = v - self.loc
        Lb = jnp.broadcast_to(
            self._scale_tril, diff.shape[:-1] + self._scale_tril.shape[-2:]
        )
        sol = jax.scipy.linalg.solve_triangular(Lb, diff[..., None], lower=True)[..., 0]
        maha = jnp.sum(jnp.square(sol), axis=-1)
        logdet = 2.0 * jnp.sum(
            jnp.log(jnp.diagonal(self._scale_tril, axis1=-2, axis2=-1)), axis=-1
        )
        return Tensor(-0.5 * (d * jnp.log(2.0 * jnp.pi) + logdet + maha))

    def entropy(self):
        from ..framework.core import Tensor

        d = self._event_shape[0]
        logdet = 2.0 * jnp.sum(
            jnp.log(jnp.diagonal(self._scale_tril, axis1=-2, axis2=-1)), axis=-1
        )
        return Tensor(0.5 * (d * (1.0 + jnp.log(2.0 * jnp.pi)) + logdet))


class ContinuousBernoulli(Distribution):
    """reference: distribution/continuous_bernoulli.py — the [0, 1]-supported
    exponential-family relaxation of Bernoulli (Loaiza-Ganem & Cunningham
    2019): p(x) = C(lam) lam^x (1-lam)^(1-x)."""

    def __init__(self, probs, lims=(0.499, 0.501), name=None):
        self.probs = jnp.asarray(_data(self._to_float(probs)), jnp.float32)
        self._lims = lims
        super().__init__(batch_shape=self.probs.shape)

    def _outside_lims(self):
        return (self.probs < self._lims[0]) | (self.probs > self._lims[1])

    def _log_norm_const(self):
        # C(lam) = 2 atanh(1-2lam) / (1-2lam) for lam != 0.5, else 2
        lam = jnp.where(self._outside_lims(), self.probs, self._lims[0])
        x = 1.0 - 2.0 * lam
        log_c = jnp.log(2.0 * jnp.arctanh(x) / x)
        # Taylor around lam=0.5: log(2 + x^2 * 2/3 ...) ~ log 2 + x^2/3
        taylor = jnp.log(2.0) + jnp.square(1.0 - 2.0 * self.probs) / 3.0
        return jnp.where(self._outside_lims(), log_c, taylor)

    @property
    def mean(self):
        from ..framework.core import Tensor

        lam = jnp.where(self._outside_lims(), self.probs, self._lims[0])
        m = lam / (2.0 * lam - 1.0) + 1.0 / (2.0 * jnp.arctanh(1.0 - 2.0 * lam))
        return Tensor(jnp.where(self._outside_lims(), m, 0.5))

    def log_prob(self, value):
        from ..framework.core import Tensor

        v = jnp.asarray(_data(value), jnp.float32)
        return Tensor(
            self._log_norm_const()
            + v * jnp.log(jnp.maximum(self.probs, 1e-12))
            + (1.0 - v) * jnp.log(jnp.maximum(1.0 - self.probs, 1e-12))
        )

    def _sample(self, key, shape):
        # inverse-CDF sampling
        full = tuple(shape) + self._batch_shape
        u = jax.random.uniform(key, full, minval=1e-6, maxval=1.0 - 1e-6)
        lam = jnp.where(self._outside_lims(), self.probs, self._lims[0])
        icdf = (
            jnp.log1p(u * (2.0 * lam - 1.0) / (1.0 - lam))
            / (jnp.log(lam) - jnp.log1p(-lam))
        )
        return jnp.where(self._outside_lims(), icdf, u)
