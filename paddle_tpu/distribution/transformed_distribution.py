"""TransformedDistribution (reference:
python/paddle/distribution/transformed_distribution.py)."""
import jax.numpy as jnp

from .distribution import Distribution, _data
from .transform import ChainTransform


class TransformedDistribution(Distribution):
    def __init__(self, base, transforms):
        self.base = base
        self.transforms = list(transforms)
        self._chain = ChainTransform(self.transforms)
        shape = tuple(base.batch_shape) + tuple(base.event_shape)
        out_shape = self._chain.forward_shape(shape)
        nb = len(base.batch_shape)
        super().__init__(batch_shape=out_shape[:nb], event_shape=out_shape[nb:])

    def _sample(self, key, shape):
        x = self.base._sample(key, shape)
        return self._chain._forward(x)

    def sample(self, shape=()):
        from ..framework.core import Tensor
        from ..framework import random as prandom

        return Tensor(self._sample(prandom.next_key(), tuple(shape)))

    def log_prob(self, value):
        from ..framework.core import Tensor

        y = _data(value)
        x = self._chain._inverse(y)
        base_lp = _data(self.base.log_prob(x))
        ld = self._chain._forward_log_det_jacobian(x)
        return Tensor(base_lp - ld)
