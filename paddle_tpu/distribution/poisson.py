"""Poisson distribution (reference: python/paddle/distribution/poisson.py)."""
import jax
import jax.numpy as jnp

from .distribution import Distribution, _data


class Poisson(Distribution):
    def __init__(self, rate, name=None):
        self.rate = self._to_float(rate)
        super().__init__(batch_shape=jnp.shape(self.rate))
        self._track(rate=rate)

    @property
    def mean(self):
        from ..framework.core import Tensor

        return Tensor(self.rate)

    @property
    def variance(self):
        from ..framework.core import Tensor

        return Tensor(self.rate)

    def _sample(self, key, shape):
        full = tuple(shape) + self._batch_shape
        return jax.random.poisson(key, self.rate, full).astype(self.rate.dtype)

    def log_prob(self, value):
        from ..framework.core import Tensor

        k = _data(value).astype(self.rate.dtype)
        return Tensor(k * jnp.log(self.rate) - self.rate - jax.scipy.special.gammaln(k + 1))

    def entropy(self):
        """Exact truncated-support sum when the rate is concrete; asymptotic
        expansion H ≈ ½log(2πeλ) − 1/(12λ) − 1/(24λ²) under tracing."""
        from ..framework.core import Tensor

        r = self.rate
        try:
            rmax = float(jnp.max(r))
        except (jax.errors.TracerArrayConversionError, jax.errors.ConcretizationTypeError):
            rmax = None
        if rmax is not None and rmax <= 256.0:
            kmax = int(rmax + 10.0 * rmax**0.5 + 24.0)
            ks = jnp.arange(kmax, dtype=r.dtype).reshape((kmax,) + (1,) * r.ndim)
            lp = ks * jnp.log(r) - r - jax.scipy.special.gammaln(ks + 1)
            return Tensor(-jnp.sum(jnp.exp(lp) * lp, axis=0))
        return Tensor(
            0.5 * jnp.log(2 * jnp.pi * jnp.e * r) - 1 / (12 * r) - 1 / (24 * r**2)
        )

    def kl_divergence(self, other):
        from ..framework.core import Tensor

        if isinstance(other, Poisson):
            r1, r2 = self.rate, other.rate
            return Tensor(r1 * jnp.log(r1 / r2) - r1 + r2)
        return super().kl_divergence(other)
