"""Cauchy distribution (reference: python/paddle/distribution/cauchy.py)."""
import jax
import jax.numpy as jnp

from .distribution import Distribution, _data


class Cauchy(Distribution):
    def __init__(self, loc, scale, name=None):
        (self.loc, self.scale), shape = self._validate_args(
            self._to_float(loc), self._to_float(scale)
        )
        super().__init__(batch_shape=shape)
        self._track(loc=loc, scale=scale)

    @property
    def mean(self):
        raise ValueError("Cauchy distribution has no mean.")

    @property
    def variance(self):
        raise ValueError("Cauchy distribution has no variance.")

    @property
    def stddev(self):
        raise ValueError("Cauchy distribution has no stddev.")

    def _sample(self, key, shape):
        full = tuple(shape) + self._batch_shape
        return self.loc + self.scale * jax.random.cauchy(key, full, self.loc.dtype)

    def log_prob(self, value):
        from ..framework.core import Tensor

        z = (_data(value) - self.loc) / self.scale
        return Tensor(-jnp.log(jnp.pi * self.scale * (1 + z**2)))

    def entropy(self):
        from ..framework.core import Tensor

        return Tensor(jnp.log(4 * jnp.pi * self.scale))

    def cdf(self, value):
        from ..framework.core import Tensor

        z = (_data(value) - self.loc) / self.scale
        return Tensor(jnp.arctan(z) / jnp.pi + 0.5)

    def kl_divergence(self, other):
        from ..framework.core import Tensor

        if isinstance(other, Cauchy):
            # closed form (Chyzak & Nielsen 2019)
            num = (self.scale + other.scale) ** 2 + (self.loc - other.loc) ** 2
            den = 4 * self.scale * other.scale
            return Tensor(jnp.log(num / den))
        return super().kl_divergence(other)
