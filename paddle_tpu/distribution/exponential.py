"""Exponential distribution (reference: python/paddle/distribution/exponential.py)."""
import jax
import jax.numpy as jnp

from .distribution import Distribution, _data


class Exponential(Distribution):
    def __init__(self, rate, name=None):
        self.rate = self._to_float(rate)
        super().__init__(batch_shape=jnp.shape(self.rate))
        self._track(rate=rate)

    @property
    def mean(self):
        from ..framework.core import Tensor

        return Tensor(1.0 / self.rate)

    @property
    def variance(self):
        from ..framework.core import Tensor

        return Tensor(1.0 / self.rate**2)

    def _sample(self, key, shape):
        full = tuple(shape) + self._batch_shape
        return jax.random.exponential(key, full, self.rate.dtype) / self.rate

    def log_prob(self, value):
        from ..framework.core import Tensor

        v = _data(value)
        return Tensor(jnp.log(self.rate) - self.rate * v)

    def entropy(self):
        from ..framework.core import Tensor

        return Tensor(1.0 - jnp.log(self.rate))

    def cdf(self, value):
        from ..framework.core import Tensor

        return Tensor(-jnp.expm1(-self.rate * _data(value)))

    def kl_divergence(self, other):
        from ..framework.core import Tensor

        if isinstance(other, Exponential):
            r = self.rate / other.rate
            return Tensor(jnp.log(r) + 1.0 / r - 1.0)
        return super().kl_divergence(other)
