"""Multinomial distribution (reference: python/paddle/distribution/multinomial.py)."""
import jax
import jax.numpy as jnp

from .distribution import Distribution, _data


class Multinomial(Distribution):
    def __init__(self, total_count, probs, name=None):
        self.total_count = int(total_count)
        self.probs = self._to_float(probs)
        self._retrace()
        super().__init__(
            batch_shape=self.probs.shape[:-1], event_shape=self.probs.shape[-1:]
        )
        self._track(probs=probs)

    def _retrace(self):
        self.probs = self.probs / jnp.sum(self.probs, -1, keepdims=True)

    @property
    def mean(self):
        from ..framework.core import Tensor

        return Tensor(self.total_count * self.probs)

    @property
    def variance(self):
        from ..framework.core import Tensor

        return Tensor(self.total_count * self.probs * (1 - self.probs))

    def _sample(self, key, shape):
        full = tuple(shape) + self._batch_shape
        logits = jnp.log(self.probs)
        draws = jax.random.categorical(
            key, logits, axis=-1, shape=(self.total_count,) + full
        )
        k = self.probs.shape[-1]
        counts = jax.nn.one_hot(draws, k, dtype=self.probs.dtype).sum(0)
        return counts

    def log_prob(self, value):
        from ..framework.core import Tensor

        v = _data(value).astype(self.probs.dtype)
        gl = jax.scipy.special.gammaln
        logfact = gl(jnp.asarray(self.total_count + 1.0)) - jnp.sum(gl(v + 1.0), -1)
        return Tensor(logfact + jnp.sum(v * jnp.log(self.probs), -1))

    def entropy(self):
        # no closed form; Monte-Carlo-free bound used by paddle: compute via
        # sum over categories of binomial entropies is an approximation —
        # return the exact series truncated at total_count like torch does is
        # heavy; use the normal approximation paddle documents.
        from ..framework.core import Tensor

        n, p = self.total_count, self.probs
        return Tensor(
            0.5 * jnp.sum(jnp.log(2 * jnp.pi * jnp.e * n * p * (1 - p) + 1e-8), -1)
        )
