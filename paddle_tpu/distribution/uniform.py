"""Uniform distribution (reference: python/paddle/distribution/uniform.py)."""
import jax
import jax.numpy as jnp

from .distribution import Distribution, _data


class Uniform(Distribution):
    def __init__(self, low, high, name=None):
        (self.low, self.high), shape = self._validate_args(
            self._to_float(low), self._to_float(high)
        )
        super().__init__(batch_shape=shape)
        self._track(low=low, high=high)

    @property
    def mean(self):
        from ..framework.core import Tensor

        return Tensor((self.low + self.high) / 2)

    @property
    def variance(self):
        from ..framework.core import Tensor

        return Tensor((self.high - self.low) ** 2 / 12)

    def _sample(self, key, shape):
        full = tuple(shape) + self._batch_shape
        u = jax.random.uniform(key, full, self.low.dtype)
        return self.low + u * (self.high - self.low)

    def log_prob(self, value):
        from ..framework.core import Tensor

        v = _data(value)
        inside = (v >= self.low) & (v < self.high)
        lp = -jnp.log(self.high - self.low)
        return Tensor(jnp.where(inside, lp, -jnp.inf))

    def entropy(self):
        from ..framework.core import Tensor

        return Tensor(jnp.log(self.high - self.low))

    def cdf(self, value):
        from ..framework.core import Tensor

        v = _data(value)
        return Tensor(jnp.clip((v - self.low) / (self.high - self.low), 0.0, 1.0))
