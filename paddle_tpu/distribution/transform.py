"""Bijective transforms (reference: python/paddle/distribution/transform.py —
``Transform`` zoo with forward/inverse/log_det_jacobian used by
TransformedDistribution)."""
import functools

import jax
import jax.numpy as jnp

from .distribution import _data


def _box(x):
    from ..framework.core import Tensor

    return Tensor(x) if not isinstance(x, Tensor) else x


class Type:
    BIJECTION = "bijection"
    INJECTION = "injection"
    SURJECTION = "surjection"
    OTHER = "other"


class Transform:
    _type = Type.OTHER

    def forward(self, x):
        return _box(self._forward(_data(x)))

    def inverse(self, y):
        return _box(self._inverse(_data(y)))

    def forward_log_det_jacobian(self, x):
        return _box(self._forward_log_det_jacobian(_data(x)))

    def inverse_log_det_jacobian(self, y):
        y = _data(y)
        return _box(-self._forward_log_det_jacobian(self._inverse(y)))

    def forward_shape(self, shape):
        return tuple(shape)

    def inverse_shape(self, shape):
        return tuple(shape)

    # subclass hooks on raw jnp arrays
    def _forward(self, x):
        raise NotImplementedError

    def _inverse(self, y):
        raise NotImplementedError

    def _forward_log_det_jacobian(self, x):
        # generic: d forward / dx via jax for elementwise transforms
        g = jax.vmap(jax.grad(lambda t: self._forward(t).sum()))(x.reshape(-1))
        return jnp.log(jnp.abs(g)).reshape(x.shape)


class AbsTransform(Transform):
    _type = Type.SURJECTION

    def _forward(self, x):
        return jnp.abs(x)

    def _inverse(self, y):
        return y  # principal branch


class AffineTransform(Transform):
    _type = Type.BIJECTION

    def __init__(self, loc, scale):
        self.loc = _data(loc)
        self.scale = _data(scale)

    def _forward(self, x):
        return self.loc + self.scale * x

    def _inverse(self, y):
        return (y - self.loc) / self.scale

    def _forward_log_det_jacobian(self, x):
        return jnp.broadcast_to(jnp.log(jnp.abs(self.scale)), x.shape)


class ExpTransform(Transform):
    _type = Type.BIJECTION

    def _forward(self, x):
        return jnp.exp(x)

    def _inverse(self, y):
        return jnp.log(y)

    def _forward_log_det_jacobian(self, x):
        return x


class PowerTransform(Transform):
    _type = Type.BIJECTION

    def __init__(self, power):
        self.power = _data(power)

    def _forward(self, x):
        return jnp.power(x, self.power)

    def _inverse(self, y):
        return jnp.power(y, 1.0 / self.power)

    def _forward_log_det_jacobian(self, x):
        return jnp.log(jnp.abs(self.power * jnp.power(x, self.power - 1)))


class SigmoidTransform(Transform):
    _type = Type.BIJECTION

    def _forward(self, x):
        return jax.nn.sigmoid(x)

    def _inverse(self, y):
        return jnp.log(y) - jnp.log1p(-y)

    def _forward_log_det_jacobian(self, x):
        return -jax.nn.softplus(-x) - jax.nn.softplus(x)


class TanhTransform(Transform):
    _type = Type.BIJECTION

    def _forward(self, x):
        return jnp.tanh(x)

    def _inverse(self, y):
        return jnp.arctanh(y)

    def _forward_log_det_jacobian(self, x):
        return 2.0 * (jnp.log(2.0) - x - jax.nn.softplus(-2.0 * x))


class SoftmaxTransform(Transform):
    _type = Type.OTHER

    def _forward(self, x):
        return jax.nn.softmax(x, axis=-1)

    def _inverse(self, y):
        return jnp.log(y)


class StickBreakingTransform(Transform):
    _type = Type.BIJECTION

    def _forward(self, x):
        offset = x.shape[-1] + 1 - jnp.arange(1, x.shape[-1] + 1)
        z = jax.nn.sigmoid(x - jnp.log(offset.astype(x.dtype)))
        zc = jnp.cumprod(1 - z, axis=-1)
        pad = jnp.ones(x.shape[:-1] + (1,), x.dtype)
        return jnp.concatenate([z, pad], -1) * jnp.concatenate([pad, zc], -1)

    def _inverse(self, y):
        # logit of the per-step fraction: x_k = log(y_k / (1-Σ_{j≤k} y_j)) + log(offset)
        y_crop = y[..., :-1]
        offset = y_crop.shape[-1] + 1 - jnp.arange(1, y_crop.shape[-1] + 1)
        sf_after = 1 - jnp.cumsum(y_crop, axis=-1)
        x = jnp.log(y_crop / sf_after)
        return x + jnp.log(offset.astype(y.dtype))

    def _forward_log_det_jacobian(self, x):
        offset = x.shape[-1] + 1 - jnp.arange(1, x.shape[-1] + 1)
        z = jax.nn.sigmoid(x - jnp.log(offset.astype(x.dtype)))
        # dy_k/dx_k = z_k(1-z_k)·prod_{j<k}(1-z_j); Jacobian lower-triangular
        detail = jnp.log(z) + jnp.log1p(-z)
        sf = jnp.cumsum(jnp.log1p(-z), axis=-1)
        sf = jnp.concatenate([jnp.zeros(x.shape[:-1] + (1,), x.dtype), sf[..., :-1]], -1)
        return jnp.sum(detail + sf, -1)

    def forward_shape(self, shape):
        return tuple(shape[:-1]) + (shape[-1] + 1,)

    def inverse_shape(self, shape):
        return tuple(shape[:-1]) + (shape[-1] - 1,)


class ReshapeTransform(Transform):
    _type = Type.BIJECTION

    def __init__(self, in_event_shape, out_event_shape):
        self.in_event_shape = tuple(in_event_shape)
        self.out_event_shape = tuple(out_event_shape)

    def _forward(self, x):
        batch = x.shape[: x.ndim - len(self.in_event_shape)]
        return x.reshape(batch + self.out_event_shape)

    def _inverse(self, y):
        batch = y.shape[: y.ndim - len(self.out_event_shape)]
        return y.reshape(batch + self.in_event_shape)

    def _forward_log_det_jacobian(self, x):
        batch = x.shape[: x.ndim - len(self.in_event_shape)]
        return jnp.zeros(batch, x.dtype)

    def forward_shape(self, shape):
        n = len(self.in_event_shape)
        return tuple(shape[:-n] if n else shape) + self.out_event_shape

    def inverse_shape(self, shape):
        n = len(self.out_event_shape)
        return tuple(shape[:-n] if n else shape) + self.in_event_shape


class ChainTransform(Transform):
    def __init__(self, transforms):
        self.transforms = list(transforms)

    def _forward(self, x):
        for t in self.transforms:
            x = t._forward(x)
        return x

    def _inverse(self, y):
        for t in reversed(self.transforms):
            y = t._inverse(y)
        return y

    def _forward_log_det_jacobian(self, x):
        total = 0.0
        for t in self.transforms:
            total = total + t._forward_log_det_jacobian(x)
            x = t._forward(x)
        return total

    def forward_shape(self, shape):
        for t in self.transforms:
            shape = t.forward_shape(shape)
        return shape

    def inverse_shape(self, shape):
        for t in reversed(self.transforms):
            shape = t.inverse_shape(shape)
        return shape


class IndependentTransform(Transform):
    def __init__(self, base, reinterpreted_batch_rank):
        self.base = base
        self.rank = int(reinterpreted_batch_rank)

    def _forward(self, x):
        return self.base._forward(x)

    def _inverse(self, y):
        return self.base._inverse(y)

    def _forward_log_det_jacobian(self, x):
        ld = self.base._forward_log_det_jacobian(x)
        return jnp.sum(ld, axis=tuple(range(-self.rank, 0)))


class StackTransform(Transform):
    def __init__(self, transforms, axis=0):
        self.transforms = list(transforms)
        self.axis = axis

    def _split(self, x):
        return [jnp.squeeze(s, self.axis) for s in jnp.split(x, len(self.transforms), self.axis)]

    def _forward(self, x):
        return jnp.stack(
            [t._forward(s) for t, s in zip(self.transforms, self._split(x))], self.axis
        )

    def _inverse(self, y):
        return jnp.stack(
            [t._inverse(s) for t, s in zip(self.transforms, self._split(y))], self.axis
        )

    def _forward_log_det_jacobian(self, x):
        return jnp.stack(
            [t._forward_log_det_jacobian(s) for t, s in zip(self.transforms, self._split(x))],
            self.axis,
        )
