"""Laplace distribution (reference: python/paddle/distribution/laplace.py)."""
import jax
import jax.numpy as jnp

from .distribution import Distribution, _data


class Laplace(Distribution):
    def __init__(self, loc, scale, name=None):
        (self.loc, self.scale), shape = self._validate_args(
            self._to_float(loc), self._to_float(scale)
        )
        super().__init__(batch_shape=shape)
        self._track(loc=loc, scale=scale)

    @property
    def mean(self):
        from ..framework.core import Tensor

        return Tensor(self.loc)

    @property
    def variance(self):
        from ..framework.core import Tensor

        return Tensor(2 * self.scale**2)

    @property
    def stddev(self):
        from ..framework.core import Tensor

        return Tensor(jnp.sqrt(2.0) * self.scale)

    def _sample(self, key, shape):
        full = tuple(shape) + self._batch_shape
        return jax.random.laplace(key, full, self.loc.dtype) * self.scale + self.loc

    def log_prob(self, value):
        from ..framework.core import Tensor

        v = _data(value)
        return Tensor(-jnp.abs(v - self.loc) / self.scale - jnp.log(2 * self.scale))

    def entropy(self):
        from ..framework.core import Tensor

        return Tensor(1 + jnp.log(2 * self.scale))

    def cdf(self, value):
        from ..framework.core import Tensor

        z = (_data(value) - self.loc) / self.scale
        return Tensor(0.5 - 0.5 * jnp.sign(z) * jnp.expm1(-jnp.abs(z)))

    def icdf(self, value):
        from ..framework.core import Tensor

        p = _data(value)
        return Tensor(self.loc - self.scale * jnp.sign(p - 0.5) * jnp.log1p(-2 * jnp.abs(p - 0.5)))

    def kl_divergence(self, other):
        from ..framework.core import Tensor

        if isinstance(other, Laplace):
            d = jnp.abs(self.loc - other.loc)
            return Tensor(
                jnp.log(other.scale / self.scale)
                + (self.scale * jnp.exp(-d / self.scale) + d) / other.scale
                - 1.0
            )
        return super().kl_divergence(other)
