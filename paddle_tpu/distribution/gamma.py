"""Gamma distribution (reference: python/paddle/distribution/gamma.py)."""
import jax
import jax.numpy as jnp

from .distribution import Distribution, _data


class Gamma(Distribution):
    def __init__(self, concentration, rate, name=None):
        (self.concentration, self.rate), shape = self._validate_args(
            self._to_float(concentration), self._to_float(rate)
        )
        super().__init__(batch_shape=shape)
        self._track(concentration=concentration, rate=rate)

    @property
    def mean(self):
        from ..framework.core import Tensor

        return Tensor(self.concentration / self.rate)

    @property
    def variance(self):
        from ..framework.core import Tensor

        return Tensor(self.concentration / self.rate**2)

    def _sample(self, key, shape):
        full = tuple(shape) + self._batch_shape
        return jax.random.gamma(key, self.concentration, full) / self.rate

    def log_prob(self, value):
        from ..framework.core import Tensor

        v = _data(value)
        a, r = self.concentration, self.rate
        return Tensor(a * jnp.log(r) + (a - 1) * jnp.log(v) - r * v - jax.scipy.special.gammaln(a))

    def entropy(self):
        from ..framework.core import Tensor

        a = self.concentration
        dg = jax.scipy.special.digamma
        return Tensor(a - jnp.log(self.rate) + jax.scipy.special.gammaln(a) + (1 - a) * dg(a))

    def kl_divergence(self, other):
        from ..framework.core import Tensor

        if isinstance(other, Gamma):
            a1, r1, a2, r2 = self.concentration, self.rate, other.concentration, other.rate
            dg = jax.scipy.special.digamma
            gl = jax.scipy.special.gammaln
            return Tensor(
                (a1 - a2) * dg(a1) - gl(a1) + gl(a2)
                + a2 * (jnp.log(r1) - jnp.log(r2)) + a1 * (r2 / r1 - 1.0)
            )
        return super().kl_divergence(other)
