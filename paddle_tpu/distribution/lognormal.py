"""LogNormal distribution (reference: python/paddle/distribution/lognormal.py)."""
import jax
import jax.numpy as jnp

from .distribution import Distribution, _data
from .normal import Normal


class LogNormal(Distribution):
    def __init__(self, loc, scale, name=None):
        self._base = Normal(loc, scale)
        self.loc, self.scale = self._base.loc, self._base.scale
        super().__init__(batch_shape=self._base._batch_shape)
        self._track(loc=loc, scale=scale)

    def _retrace(self):
        self._base = Normal(self.loc, self.scale)

    @property
    def mean(self):
        from ..framework.core import Tensor

        return Tensor(jnp.exp(self.loc + self.scale**2 / 2))

    @property
    def variance(self):
        from ..framework.core import Tensor

        return Tensor(jnp.expm1(self.scale**2) * jnp.exp(2 * self.loc + self.scale**2))

    def _sample(self, key, shape):
        return jnp.exp(self._base._sample(key, shape))

    def log_prob(self, value):
        from ..framework.core import Tensor

        v = _data(value)
        return Tensor(self._base.log_prob(jnp.log(v))._data - jnp.log(v))

    def entropy(self):
        from ..framework.core import Tensor

        return Tensor(self._base.entropy()._data + self.loc)

    def kl_divergence(self, other):
        if isinstance(other, LogNormal):
            return self._base.kl_divergence(other._base)
        return super().kl_divergence(other)
