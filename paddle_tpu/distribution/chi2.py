"""reference: python/paddle/distribution/chi2.py — Gamma(df/2, rate=1/2)."""
import jax.numpy as jnp

from .distribution import _data
from .gamma import Gamma


class Chi2(Gamma):
    def __init__(self, df, name=None):
        df_raw = self._to_float(df)
        super().__init__(concentration=df_raw / 2.0,
                         rate=jnp.full_like(jnp.asarray(df_raw), 0.5))
        self.df = df_raw
        # differentiability: track the ORIGINAL df tensor; _retrace rebuilds
        # the Gamma parameters from the traced df inside taped methods
        self._track(df=df)

    def _retrace(self):
        self.concentration = jnp.asarray(self.df) / 2.0
        self.rate = jnp.full_like(jnp.asarray(self.df), 0.5)

    def __repr__(self):
        return f"Chi2(df={self.df})"
