"""paddle.distribution parity (reference: python/paddle/distribution/ —
Distribution base in distribution.py, kl registry in kl.py).

TPU-native design: every density/sampling routine is pure jnp + jax.random,
so distributions compose with jit/vmap/grad; sampling draws keys from the
framework's threaded PRNG (framework/random.py) exactly like creation ops do.
"""
from .distribution import Distribution
from .normal import Normal
from .uniform import Uniform
from .categorical import Categorical
from .bernoulli import Bernoulli
from .beta import Beta
from .dirichlet import Dirichlet
from .exponential import Exponential
from .chi2 import Chi2  # noqa: F401
from .continuous_bernoulli import ContinuousBernoulli  # noqa: F401
from .exponential_family import ExponentialFamily  # noqa: F401
from .multivariate_normal import MultivariateNormal  # noqa: F401
from .gamma import Gamma
from .geometric import Geometric
from .gumbel import Gumbel
from .laplace import Laplace
from .lognormal import LogNormal
from .multinomial import Multinomial
from .poisson import Poisson
from .cauchy import Cauchy
from .binomial import Binomial
from .studentT import StudentT
from .independent import Independent
from .transformed_distribution import TransformedDistribution
from .transform import (
    AbsTransform,
    AffineTransform,
    ChainTransform,
    ExpTransform,
    IndependentTransform,
    PowerTransform,
    ReshapeTransform,
    SigmoidTransform,
    SoftmaxTransform,
    StackTransform,
    StickBreakingTransform,
    TanhTransform,
    Transform,
)
from .kl import kl_divergence, register_kl

__all__ = [
    "Distribution", "Normal", "Uniform", "Categorical", "Bernoulli", "Beta",
    "Dirichlet", "Exponential", "Gamma", "Geometric", "Gumbel", "Laplace",
    "LogNormal", "Multinomial", "Poisson", "Cauchy", "Binomial", "StudentT",
    "Independent", "TransformedDistribution", "kl_divergence", "register_kl",
    "Transform", "AbsTransform", "AffineTransform", "ChainTransform",
    "ExpTransform", "IndependentTransform", "PowerTransform",
    "ReshapeTransform", "SigmoidTransform", "SoftmaxTransform",
    "StackTransform", "StickBreakingTransform", "TanhTransform",
]
