"""Normal distribution (reference: python/paddle/distribution/normal.py
``class Normal(Distribution)``)."""
import math

import jax
import jax.numpy as jnp

from .distribution import Distribution, _data

# plain math, not jnp: module import must not initialize the jax backend
_HALF_LOG_2PI = 0.5 * math.log(2.0 * math.pi)


class Normal(Distribution):
    def __init__(self, loc, scale, name=None):
        (self.loc, self.scale), shape = self._validate_args(
            self._to_float(loc), self._to_float(scale)
        )
        super().__init__(batch_shape=shape)
        self._track(loc=loc, scale=scale)

    @property
    def mean(self):
        from ..framework.core import Tensor

        return Tensor(self.loc)

    @property
    def variance(self):
        from ..framework.core import Tensor

        return Tensor(self.scale**2)

    @property
    def stddev(self):
        from ..framework.core import Tensor

        return Tensor(self.scale)

    def _sample(self, key, shape):
        full = tuple(shape) + self._batch_shape
        eps = jax.random.normal(key, full, self.loc.dtype)
        return self.loc + eps * self.scale

    def log_prob(self, value):
        from ..framework.core import Tensor

        v = _data(value)
        var = self.scale**2
        return Tensor(-((v - self.loc) ** 2) / (2 * var) - jnp.log(self.scale) - _HALF_LOG_2PI)

    def entropy(self):
        from ..framework.core import Tensor

        return Tensor(0.5 + _HALF_LOG_2PI + jnp.log(self.scale) * jnp.ones_like(self.loc))

    def cdf(self, value):
        from ..framework.core import Tensor

        v = _data(value)
        return Tensor(0.5 * (1 + jax.scipy.special.erf((v - self.loc) / (self.scale * jnp.sqrt(2.0)))))

    def icdf(self, value):
        from ..framework.core import Tensor

        v = _data(value)
        return Tensor(self.loc + self.scale * jnp.sqrt(2.0) * jax.scipy.special.erfinv(2 * v - 1))

    def kl_divergence(self, other):
        from ..framework.core import Tensor

        if isinstance(other, Normal):
            var_ratio = (self.scale / other.scale) ** 2
            t1 = ((self.loc - other.loc) / other.scale) ** 2
            return Tensor(0.5 * (var_ratio + t1 - 1 - jnp.log(var_ratio)))
        return super().kl_divergence(other)
