"""Binomial distribution (reference: python/paddle/distribution/binomial.py)."""
import jax
import jax.numpy as jnp

from .distribution import Distribution, _data


class Binomial(Distribution):
    def __init__(self, total_count, probs, name=None):
        self.total_count = int(total_count) if jnp.ndim(total_count) == 0 else total_count
        self.probs = self._to_float(probs)
        super().__init__(batch_shape=jnp.shape(self.probs))
        self._track(probs=probs)

    @property
    def mean(self):
        from ..framework.core import Tensor

        return Tensor(self.total_count * self.probs)

    @property
    def variance(self):
        from ..framework.core import Tensor

        return Tensor(self.total_count * self.probs * (1 - self.probs))

    def _sample(self, key, shape):
        full = tuple(shape) + self._batch_shape
        n = jnp.asarray(self.total_count, self.probs.dtype)
        return jax.random.binomial(key, n, self.probs, full).astype(self.probs.dtype)

    def log_prob(self, value):
        from ..framework.core import Tensor

        k = _data(value).astype(self.probs.dtype)
        n = jnp.asarray(self.total_count, self.probs.dtype)
        gl = jax.scipy.special.gammaln
        eps = 1e-8
        p = jnp.clip(self.probs, eps, 1 - eps)
        return Tensor(
            gl(n + 1) - gl(k + 1) - gl(n - k + 1) + k * jnp.log(p) + (n - k) * jnp.log1p(-p)
        )

    def entropy(self):
        """Exact support sum for concrete scalar n ≤ 1024; Gaussian
        approximation ½log(2πe·np(1−p)) otherwise."""
        from ..framework.core import Tensor

        n = jnp.asarray(self.total_count, self.probs.dtype)
        p = self.probs
        if jnp.ndim(self.total_count) == 0 and isinstance(self.total_count, int) \
                and self.total_count <= 1024:
            k = jnp.arange(self.total_count + 1, dtype=p.dtype)
            k = k.reshape((self.total_count + 1,) + (1,) * p.ndim)
            lp = self.log_prob(k)._data
            return Tensor(-jnp.sum(jnp.exp(lp) * lp, axis=0))
        return Tensor(0.5 * jnp.log(2 * jnp.pi * jnp.e * n * p * (1 - p) + 1e-8))

    def kl_divergence(self, other):
        from ..framework.core import Tensor

        if isinstance(other, Binomial):
            n = jnp.asarray(self.total_count, self.probs.dtype)
            eps = 1e-8
            p = jnp.clip(self.probs, eps, 1 - eps)
            q = jnp.clip(other.probs, eps, 1 - eps)
            return Tensor(n * (p * jnp.log(p / q) + (1 - p) * jnp.log((1 - p) / (1 - q))))
        return super().kl_divergence(other)
