"""KL divergence registry (reference: python/paddle/distribution/kl.py —
``register_kl`` decorator + ``kl_divergence`` double dispatch)."""
import jax.numpy as jnp

_REGISTRY = {}


def register_kl(p_cls, q_cls):
    def deco(fn):
        from .distribution import _tape_wrap

        # registered closed forms run through the tape like method KLs do
        _REGISTRY[(p_cls, q_cls)] = _tape_wrap(fn)
        return fn

    return deco


def _lookup(p_cls, q_cls):
    # exact, then MRO-walk (most-derived match wins)
    if (p_cls, q_cls) in _REGISTRY:
        return _REGISTRY[(p_cls, q_cls)]
    matches = [
        (pc, qc)
        for (pc, qc) in _REGISTRY
        if issubclass(p_cls, pc) and issubclass(q_cls, qc)
    ]
    if not matches:
        return None
    matches.sort(key=lambda pq: (p_cls.__mro__.index(pq[0]), q_cls.__mro__.index(pq[1])))
    return _REGISTRY[matches[0]]


def kl_divergence(p, q):
    fn = _lookup(type(p), type(q))
    if fn is not None:
        return fn(p, q)
    # same-family closed forms implemented on the distributions themselves
    if type(p) is type(q):
        own = type(p).kl_divergence
        from .distribution import Distribution

        if own is not Distribution.kl_divergence:
            return own(p, q)
    # Monte-Carlo fallback
    from ..framework.core import Tensor
    from .distribution import _data

    x = p.sample((256,))
    lp = _data(p.log_prob(x))
    lq = _data(q.log_prob(x))
    return Tensor(jnp.mean(lp - lq, axis=0))


# -- closed forms across families ----------------------------------------
def _register_defaults():
    from .beta import Beta
    from .dirichlet import Dirichlet
    import jax

    @register_kl(Beta, Beta)
    def _kl_beta_beta(p, q):
        from ..framework.core import Tensor

        gl, dg = jax.scipy.special.gammaln, jax.scipy.special.digamma
        a1, b1, a2, b2 = p.alpha, p.beta, q.alpha, q.beta
        s1, s2 = a1 + b1, a2 + b2
        return Tensor(
            gl(s1) - gl(a1) - gl(b1) - (gl(s2) - gl(a2) - gl(b2))
            + (a1 - a2) * dg(a1) + (b1 - b2) * dg(b1) - (s1 - s2) * dg(s1)
        )

    @register_kl(Dirichlet, Dirichlet)
    def _kl_dir_dir(p, q):
        from ..framework.core import Tensor

        gl, dg = jax.scipy.special.gammaln, jax.scipy.special.digamma
        a, b = p.concentration, q.concentration
        a0 = jnp.sum(a, -1)
        return Tensor(
            gl(a0) - jnp.sum(gl(a), -1) - gl(jnp.sum(b, -1)) + jnp.sum(gl(b), -1)
            + jnp.sum((a - b) * (dg(a) - dg(a0)[..., None]), -1)
        )


_register_defaults()
