"""reference: python/paddle/distribution/continuous_bernoulli.py — the
[0, 1]-supported exponential-family relaxation of Bernoulli (Loaiza-Ganem
& Cunningham 2019): p(x) = C(lam) lam^x (1-lam)^(1-x)."""
import jax
import jax.numpy as jnp

from .distribution import Distribution, _data


class ContinuousBernoulli(Distribution):
    def __init__(self, probs, lims=(0.499, 0.501), name=None):
        self.probs = jnp.asarray(_data(self._to_float(probs)), jnp.float32)
        self._lims = lims
        super().__init__(batch_shape=self.probs.shape)
        self._track(probs=probs)

    def _outside_lims(self):
        return (self.probs < self._lims[0]) | (self.probs > self._lims[1])

    def _log_norm_const(self):
        # C(lam) = 2 atanh(1-2lam) / (1-2lam) for lam != 0.5, else 2
        lam = jnp.where(self._outside_lims(), self.probs, self._lims[0])
        x = 1.0 - 2.0 * lam
        log_c = jnp.log(2.0 * jnp.arctanh(x) / x)
        # Taylor around lam=0.5: log C ~ log 2 + x^2/3
        taylor = jnp.log(2.0) + jnp.square(1.0 - 2.0 * self.probs) / 3.0
        return jnp.where(self._outside_lims(), log_c, taylor)

    @property
    def mean(self):
        from ..framework.core import Tensor

        lam = jnp.where(self._outside_lims(), self.probs, self._lims[0])
        m = lam / (2.0 * lam - 1.0) + 1.0 / (2.0 * jnp.arctanh(1.0 - 2.0 * lam))
        # Taylor around lam=0.5: mean ~ 0.5 + (lam - 0.5)/3 — keeps the value
        # continuous AND d(mean)/d(probs) ~ 1/3 inside the clamp region
        taylor = 0.5 + (self.probs - 0.5) / 3.0
        return Tensor(jnp.where(self._outside_lims(), m, taylor))

    def log_prob(self, value):
        from ..framework.core import Tensor

        v = jnp.asarray(_data(value), jnp.float32)
        return Tensor(
            self._log_norm_const()
            + v * jnp.log(jnp.maximum(self.probs, 1e-12))
            + (1.0 - v) * jnp.log(jnp.maximum(1.0 - self.probs, 1e-12))
        )

    def _sample(self, key, shape):
        # inverse-CDF sampling
        full = tuple(shape) + self._batch_shape
        u = jax.random.uniform(key, full, minval=1e-6, maxval=1.0 - 1e-6)
        lam = jnp.where(self._outside_lims(), self.probs, self._lims[0])
        icdf = (
            jnp.log1p(u * (2.0 * lam - 1.0) / (1.0 - lam))
            / (jnp.log(lam) - jnp.log1p(-lam))
        )
        return jnp.where(self._outside_lims(), icdf, u)
