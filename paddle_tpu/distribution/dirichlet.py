"""Dirichlet distribution (reference: python/paddle/distribution/dirichlet.py)."""
import jax
import jax.numpy as jnp

from .distribution import Distribution, _data


class Dirichlet(Distribution):
    def __init__(self, concentration, name=None):
        self.concentration = self._to_float(concentration)
        super().__init__(
            batch_shape=self.concentration.shape[:-1],
            event_shape=self.concentration.shape[-1:],
        )
        self._track(concentration=concentration)

    @property
    def mean(self):
        from ..framework.core import Tensor

        return Tensor(self.concentration / jnp.sum(self.concentration, -1, keepdims=True))

    @property
    def variance(self):
        from ..framework.core import Tensor

        a0 = jnp.sum(self.concentration, -1, keepdims=True)
        m = self.concentration / a0
        return Tensor(m * (1 - m) / (a0 + 1))

    def _sample(self, key, shape):
        full = tuple(shape) + self._batch_shape
        return jax.random.dirichlet(key, self.concentration, full)

    def log_prob(self, value):
        from ..framework.core import Tensor

        v = _data(value)
        a = self.concentration
        norm = jnp.sum(jax.scipy.special.gammaln(a), -1) - jax.scipy.special.gammaln(
            jnp.sum(a, -1)
        )
        return Tensor(jnp.sum((a - 1) * jnp.log(v), -1) - norm)

    def entropy(self):
        from ..framework.core import Tensor

        a = self.concentration
        k = a.shape[-1]
        a0 = jnp.sum(a, -1)
        dg = jax.scipy.special.digamma
        lnB = jnp.sum(jax.scipy.special.gammaln(a), -1) - jax.scipy.special.gammaln(a0)
        return Tensor(lnB + (a0 - k) * dg(a0) - jnp.sum((a - 1) * dg(a), -1))
