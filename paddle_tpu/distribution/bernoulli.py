"""Bernoulli distribution (reference: python/paddle/distribution/bernoulli.py)."""
import jax
import jax.numpy as jnp

from .distribution import Distribution, _data


class Bernoulli(Distribution):
    def __init__(self, probs, name=None):
        self.probs = self._to_float(probs)
        self._retrace()
        super().__init__(batch_shape=jnp.shape(self.probs))
        self._track(probs=probs)

    def _retrace(self):
        self.logits = jnp.log(self.probs) - jnp.log1p(-self.probs)

    @property
    def mean(self):
        from ..framework.core import Tensor

        return Tensor(self.probs)

    @property
    def variance(self):
        from ..framework.core import Tensor

        return Tensor(self.probs * (1 - self.probs))

    def _sample(self, key, shape):
        full = tuple(shape) + self._batch_shape
        return jax.random.bernoulli(key, self.probs, full).astype(self.probs.dtype)

    def rsample(self, shape=(), temperature=1.0):
        """Gumbel-softmax relaxation (paddle's rsample contract)."""
        from ..framework.core import Tensor
        from ..framework import random as prandom

        full = tuple(shape) + self._batch_shape
        u = jax.random.uniform(prandom.next_key(), full, self.probs.dtype, 1e-6, 1 - 1e-6)
        logistic = jnp.log(u) - jnp.log1p(-u)
        return Tensor(jax.nn.sigmoid((self.logits + logistic) / temperature))

    def log_prob(self, value):
        from ..framework.core import Tensor

        v = _data(value).astype(self.probs.dtype)
        eps = 1e-8
        p = jnp.clip(self.probs, eps, 1 - eps)
        return Tensor(v * jnp.log(p) + (1 - v) * jnp.log1p(-p))

    def entropy(self):
        from ..framework.core import Tensor

        eps = 1e-8
        p = jnp.clip(self.probs, eps, 1 - eps)
        return Tensor(-(p * jnp.log(p) + (1 - p) * jnp.log1p(-p)))

    def cdf(self, value):
        from ..framework.core import Tensor

        v = _data(value)
        return Tensor(jnp.where(v < 0, 0.0, jnp.where(v < 1, 1 - self.probs, 1.0)))

    def kl_divergence(self, other):
        from ..framework.core import Tensor

        if isinstance(other, Bernoulli):
            eps = 1e-8
            p = jnp.clip(self.probs, eps, 1 - eps)
            q = jnp.clip(other.probs, eps, 1 - eps)
            return Tensor(p * (jnp.log(p) - jnp.log(q)) + (1 - p) * (jnp.log1p(-p) - jnp.log1p(-q)))
        return super().kl_divergence(other)
