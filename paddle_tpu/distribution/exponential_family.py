"""reference: python/paddle/distribution/exponential_family.py."""
import jax
import jax.numpy as jnp

from .distribution import Distribution


class ExponentialFamily(Distribution):
    """Base class carrying the Bregman-divergence entropy identity.
    Subclasses define natural parameters and log_normalizer; entropy falls
    out via autodiff, per batch element."""

    @property
    def _natural_parameters(self):
        raise NotImplementedError

    def _log_normalizer(self, *natural_params):
        raise NotImplementedError

    @property
    def _mean_carrier_measure(self):
        return 0.0

    def entropy(self):
        """entropy = logZ - sum_i eta_i * dlogZ/deta_i - E[carrier], kept
        per batch element (logZ is elementwise over the batch, so the grad
        of its SUM is exactly the per-element derivative)."""
        from ..framework.core import Tensor

        nat = tuple(jnp.asarray(p) for p in self._natural_parameters)
        logz = self._log_normalizer(*nat)
        grads = jax.grad(lambda etas: jnp.sum(self._log_normalizer(*etas)))(nat)
        ent = logz - sum(e * g for e, g in zip(nat, grads)) - self._mean_carrier_measure
        return Tensor(ent)
