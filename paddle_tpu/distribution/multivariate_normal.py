"""reference: python/paddle/distribution/multivariate_normal.py."""
import jax
import jax.numpy as jnp

from .distribution import Distribution, _data


class MultivariateNormal(Distribution):
    """Parameterized by loc + exactly one of covariance_matrix /
    precision_matrix / scale_tril. Batch dims of loc and the matrix
    broadcast (reference semantics)."""

    def __init__(self, loc, covariance_matrix=None, precision_matrix=None,
                 scale_tril=None, name=None):
        self.loc = jnp.asarray(_data(loc), jnp.float32)
        given = [a is not None for a in (covariance_matrix, precision_matrix, scale_tril)]
        if sum(given) != 1:
            raise ValueError(
                "exactly ONE of covariance_matrix / precision_matrix / "
                "scale_tril must be given"
            )
        if scale_tril is not None:
            self._param_kind = "tril"
            orig = scale_tril
        elif covariance_matrix is not None:
            self._param_kind = "cov"
            orig = covariance_matrix
        else:
            self._param_kind = "prec"
            orig = precision_matrix
        self._param = jnp.asarray(_data(orig), jnp.float32)
        self._retrace()
        batch = jnp.broadcast_shapes(self.loc.shape[:-1], self._scale_tril.shape[:-2])
        self.loc = jnp.broadcast_to(self.loc, batch + self.loc.shape[-1:])
        self._scale_tril = jnp.broadcast_to(
            self._scale_tril, batch + self._scale_tril.shape[-2:]
        )
        super().__init__(batch_shape=batch, event_shape=self.loc.shape[-1:])
        # differentiability: taped methods rebuild _scale_tril from the
        # traced parameter via _retrace
        self._track(loc=loc, _param=orig)

    def _retrace(self):
        p = jnp.asarray(self._param)
        if self._param_kind == "tril":
            self._scale_tril = p
        elif self._param_kind == "cov":
            self._scale_tril = jnp.linalg.cholesky(p)
        else:
            self._scale_tril = jnp.linalg.cholesky(jnp.linalg.inv(p))

    @property
    def covariance_matrix(self):
        from ..framework.core import Tensor

        return Tensor(self._scale_tril @ jnp.swapaxes(self._scale_tril, -1, -2))

    @property
    def mean(self):
        from ..framework.core import Tensor

        return Tensor(self.loc)

    @property
    def variance(self):
        from ..framework.core import Tensor

        return Tensor(jnp.sum(jnp.square(self._scale_tril), axis=-1))

    def _sample(self, key, shape):
        full = tuple(shape) + self._batch_shape + self._event_shape
        eps = jax.random.normal(key, full)
        return self.loc + jnp.einsum("...ij,...j->...i", self._scale_tril, eps)

    def log_prob(self, value):
        from ..framework.core import Tensor

        v = jnp.asarray(_data(value), jnp.float32)
        d = v.shape[-1]
        diff = v - self.loc
        Lb = jnp.broadcast_to(
            self._scale_tril, diff.shape[:-1] + self._scale_tril.shape[-2:]
        )
        sol = jax.scipy.linalg.solve_triangular(Lb, diff[..., None], lower=True)[..., 0]
        maha = jnp.sum(jnp.square(sol), axis=-1)
        logdet = 2.0 * jnp.sum(
            jnp.log(jnp.diagonal(self._scale_tril, axis1=-2, axis2=-1)), axis=-1
        )
        return Tensor(-0.5 * (d * jnp.log(2.0 * jnp.pi) + logdet + maha))

    def entropy(self):
        from ..framework.core import Tensor

        d = self._event_shape[0]
        logdet = 2.0 * jnp.sum(
            jnp.log(jnp.diagonal(self._scale_tril, axis1=-2, axis2=-1)), axis=-1
        )
        return Tensor(0.5 * (d * (1.0 + jnp.log(2.0 * jnp.pi)) + logdet))
