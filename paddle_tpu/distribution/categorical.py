"""Categorical distribution (reference: python/paddle/distribution/categorical.py
— paddle parameterizes by unnormalized `logits` acting as relative weights)."""
import jax
import jax.numpy as jnp

from .distribution import Distribution, _data


class Categorical(Distribution):
    def __init__(self, logits, name=None):
        # paddle semantics: `logits` are non-negative relative weights (not
        # log-space); normalize to probabilities
        self.logits = self._to_float(logits)
        self._retrace()
        super().__init__(batch_shape=self.logits.shape[:-1])
        self._track(logits=logits)

    def _retrace(self):
        self._probs = self.logits / jnp.sum(self.logits, axis=-1, keepdims=True)

    @property
    def probs_array(self):
        return self._probs

    def _sample(self, key, shape):
        full = tuple(shape) + self._batch_shape
        return jax.random.categorical(key, jnp.log(self._probs), shape=full)

    def sample(self, shape=()):
        from ..framework.core import Tensor
        from ..framework import random as prandom

        return Tensor(self._sample(prandom.next_key(), shape))

    def probs(self, value):
        from ..framework.core import Tensor

        idx = _data(value).astype(jnp.int32)
        return Tensor(jnp.take_along_axis(self._probs, idx[..., None], axis=-1)[..., 0])

    def log_prob(self, value):
        from ..framework.core import Tensor

        return Tensor(jnp.log(self.probs(value)._data))

    def entropy(self):
        from ..framework.core import Tensor

        p = self._probs
        return Tensor(-jnp.sum(p * jnp.log(jnp.where(p > 0, p, 1.0)), axis=-1))

    def kl_divergence(self, other):
        from ..framework.core import Tensor

        if isinstance(other, Categorical):
            p, q = self._probs, other._probs
            return Tensor(jnp.sum(p * (jnp.log(p) - jnp.log(q)), axis=-1))
        return super().kl_divergence(other)
