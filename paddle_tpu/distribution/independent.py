"""Independent wrapper (reference: python/paddle/distribution/independent.py —
reinterprets batch dims as event dims)."""
import jax.numpy as jnp

from .distribution import Distribution, _data


class Independent(Distribution):
    def __init__(self, base, reinterpreted_batch_rank):
        self.base = base
        self.reinterpreted_batch_rank = int(reinterpreted_batch_rank)
        shape = tuple(base.batch_shape) + tuple(base.event_shape)
        split = len(base.batch_shape) - self.reinterpreted_batch_rank
        super().__init__(batch_shape=shape[:split], event_shape=shape[split:])

    @property
    def mean(self):
        return self.base.mean

    @property
    def variance(self):
        return self.base.variance

    def _sample(self, key, shape):
        return self.base._sample(key, shape)

    def sample(self, shape=()):
        return self.base.sample(shape)

    def log_prob(self, value):
        from ..framework.core import apply

        r = self.reinterpreted_batch_rank
        return apply(
            lambda a: jnp.sum(a, axis=tuple(range(-r, 0))), self.base.log_prob(value)
        )

    def entropy(self):
        from ..framework.core import apply

        r = self.reinterpreted_batch_rank
        return apply(lambda a: jnp.sum(a, axis=tuple(range(-r, 0))), self.base.entropy())
