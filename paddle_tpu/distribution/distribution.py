"""Distribution base class (reference: python/paddle/distribution/distribution.py
``class Distribution`` — batch_shape/event_shape, sample/log_prob/entropy).

Functional core: subclasses implement `_sample(key, shape)` and pure-jnp
`log_prob`; the base class handles Tensor boxing, key threading, and the
broadcasting rules paddle's API exposes.

Differentiability: subclass __init__ calls `self._track(attr=original, ...)`
with the user-passed parameters; every density method (log_prob/entropy/kl/…)
is auto-wrapped (``__init_subclass__``) to run through the dygraph tape
(core.apply) with those Tensors as differentiable inputs — so VAE/ELBO/policy
losses backprop into distribution parameters, matching the reference's
differentiable distributions.
"""
import copy
import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..framework import random as prandom
from ..framework.core import Tensor


def _data(x):
    if isinstance(x, Tensor):
        return x._data
    return jnp.asarray(x, jnp.float32) if not hasattr(x, "dtype") else jnp.asarray(x)


_TAPED_METHODS = ("log_prob", "pmf", "entropy", "cdf", "icdf", "kl_divergence", "rsample")
_TAPED_PROPS = ("mean", "variance", "stddev")


def _run_taped(fn, dists, args, kwargs=None):
    """Run fn(self, *args, **kwargs) recording ONE tape node over all tracked
    parameter Tensors of every Distribution involved (self, plus any
    Distribution args for KL) and any Tensor-valued args. kwargs are closed
    over as constants."""
    from ..framework.core import apply

    kwargs = kwargs or {}

    spec, tensors = [], []
    for di, d in enumerate(dists):
        for attr, t, shape in getattr(d, "_tracked", ()):
            spec.append((di, attr, shape))
            tensors.append(t)
    arg_idx = [i for i, a in enumerate(args) if isinstance(a, Tensor)]
    all_tensors = tensors + [args[i] for i in arg_idx]
    if not all_tensors:
        return fn(dists[0], *args, **kwargs)

    def raw(*arrays):
        ps, vs = arrays[: len(spec)], arrays[len(spec):]
        clones = [copy.copy(d) for d in dists]
        for c in clones:
            c._tracked = ()
        for (di, attr, shape), p in zip(spec, ps):
            cur = getattr(clones[di], attr)
            val = p.astype(cur.dtype)
            if shape is not None:
                val = jnp.broadcast_to(val, shape)
            setattr(clones[di], attr, val)
        for c in clones:
            retrace = getattr(c, "_retrace", None)
            if retrace is not None:
                retrace()
        new_args = list(args)
        rest = iter(clones[1:])
        for i, a in enumerate(new_args):
            if isinstance(a, Distribution):
                new_args[i] = next(rest)
        for i, v in zip(arg_idx, vs):
            new_args[i] = v
        out = fn(clones[0], *new_args, **kwargs)
        return out._data if isinstance(out, Tensor) else out

    return apply(raw, *all_tensors, name=getattr(fn, "__qualname__", "dist_op"))


def _tape_wrap(fn):
    @functools.wraps(fn)
    def wrapper(self, *args, **kwargs):
        dists = [self] + [a for a in args if isinstance(a, Distribution)]
        return _run_taped(fn, dists, args, kwargs)

    wrapper._taped = True
    return wrapper


class Distribution:
    def __init_subclass__(cls, **kw):
        super().__init_subclass__(**kw)
        for name in _TAPED_METHODS:
            impl = cls.__dict__.get(name)
            if impl is not None and callable(impl) and not getattr(impl, "_taped", False):
                setattr(cls, name, _tape_wrap(impl))
        for name in _TAPED_PROPS:
            impl = cls.__dict__.get(name)
            if isinstance(impl, property) and not getattr(impl.fget, "_taped", False):
                setattr(cls, name, property(_tape_wrap(impl.fget)))

    def __init__(self, batch_shape=(), event_shape=()):
        self._batch_shape = tuple(batch_shape)
        self._event_shape = tuple(event_shape)

    def _track(self, **orig):
        """Record original (possibly differentiable) parameter Tensors; the
        attr named must already hold the broadcast raw array."""
        tracked = []
        for attr, v in orig.items():
            if isinstance(v, Tensor):
                cur = getattr(self, attr, None)
                shape = tuple(cur.shape) if hasattr(cur, "shape") else None
                tracked.append((attr, v, shape))
        self._tracked = tuple(tracked)

    @property
    def batch_shape(self):
        return list(self._batch_shape)

    @property
    def event_shape(self):
        return list(self._event_shape)

    @property
    def mean(self):
        raise NotImplementedError

    @property
    def variance(self):
        raise NotImplementedError

    # -- sampling ---------------------------------------------------------
    def _sample(self, key, shape):
        raise NotImplementedError

    def sample(self, shape=()):
        shape = tuple(shape)
        return Tensor(self._sample(prandom.next_key(), shape))

    def rsample(self, shape=()):
        # reparameterized (pathwise) where the underlying sampler is; runs
        # through the tape so gradients reach tracked parameters
        key = prandom.next_key()
        shape = tuple(shape)
        return _run_taped(lambda d: Tensor(d._sample(key, shape)), [self], ())

    # -- densities --------------------------------------------------------
    def log_prob(self, value):
        raise NotImplementedError

    def prob(self, value):
        from ..framework.core import apply

        return apply(jnp.exp, self.log_prob(value))

    def entropy(self):
        raise NotImplementedError

    def kl_divergence(self, other):
        from .kl import kl_divergence

        return kl_divergence(self, other)

    # -- helpers ----------------------------------------------------------
    def _extend_shape(self, sample_shape):
        return tuple(sample_shape) + self._batch_shape + self._event_shape

    @staticmethod
    def _validate_args(*args):
        """Broadcast params to a common shape, returning jnp arrays."""
        arrs = [_data(a) for a in args]
        shape = jnp.broadcast_shapes(*[a.shape for a in arrs])
        return [jnp.broadcast_to(a, shape) for a in arrs], shape

    @staticmethod
    def _to_float(*args):
        out = []
        for a in args:
            d = _data(a)
            if not np.issubdtype(np.dtype(d.dtype), np.floating):
                d = d.astype(jnp.float32)
            out.append(d)
        return out[0] if len(out) == 1 else out
