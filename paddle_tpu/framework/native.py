"""ctypes bindings for the native C++ runtime (native/*.cc →
lib/libpaddle_tpu_native.so): TCPStore rendezvous (reference:
paddle/phi/core/distributed/store/tcp_store.cc) and the DataLoader blocking
queue (reference: paddle/fluid/operators/reader/blocking_queue.h).

If the shared lib is missing, it is built on demand with `make` (g++ is in
the image); if that fails, pure-Python fallbacks keep every API working —
the native path is a performance/GIL-contention win, not a correctness
dependency.
"""
import ctypes
import os
import queue as _pyqueue
import socket
import struct
import subprocess
import threading

from ..testing import chaos
from ..utils.retry import RetryPolicy

_LIB = None
_TRIED = False


def _lib_path():
    return os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                        "lib", "libpaddle_tpu_native.so")


def load_native():
    """Load (building if needed) the native lib; returns None on failure."""
    global _LIB, _TRIED
    if _LIB is not None or _TRIED:
        return _LIB
    _TRIED = True
    path = _lib_path()
    if not os.path.exists(path):
        native_dir = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(path))), "native")
        if os.path.isdir(native_dir):
            try:
                subprocess.run(["make"], cwd=native_dir, check=True,
                               capture_output=True, timeout=120)
            except Exception:
                return None
    if not os.path.exists(path):
        return None
    try:
        lib = ctypes.CDLL(path)
    except OSError:
        return None
    # signatures
    lib.tcpstore_server_start.restype = ctypes.c_void_p
    lib.tcpstore_server_start.argtypes = [ctypes.c_int]
    lib.tcpstore_server_port.restype = ctypes.c_int
    lib.tcpstore_server_port.argtypes = [ctypes.c_void_p]
    lib.tcpstore_server_stop.argtypes = [ctypes.c_void_p]
    lib.tcpstore_client_connect.restype = ctypes.c_void_p
    lib.tcpstore_client_connect.argtypes = [ctypes.c_char_p, ctypes.c_int, ctypes.c_int]
    lib.tcpstore_set.restype = ctypes.c_int
    lib.tcpstore_set.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int]
    lib.tcpstore_get.restype = ctypes.c_int
    lib.tcpstore_get.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.POINTER(ctypes.c_char_p)]
    lib.tcpstore_add.restype = ctypes.c_longlong
    lib.tcpstore_add.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_longlong]
    lib.tcpstore_check.restype = ctypes.c_int
    lib.tcpstore_check.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.tcpstore_delete.restype = ctypes.c_int
    lib.tcpstore_delete.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.tcpstore_client_close.argtypes = [ctypes.c_void_p]
    lib.tcpstore_free.argtypes = [ctypes.c_char_p]
    lib.bq_create.restype = ctypes.c_void_p
    lib.bq_create.argtypes = [ctypes.c_int]
    lib.bq_push.restype = ctypes.c_int
    lib.bq_push.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_longlong, ctypes.c_int]
    lib.bq_pop.restype = ctypes.c_longlong
    lib.bq_pop.argtypes = [ctypes.c_void_p, ctypes.POINTER(ctypes.c_char_p), ctypes.c_int]
    lib.bq_size.restype = ctypes.c_int
    lib.bq_size.argtypes = [ctypes.c_void_p]
    lib.bq_close.argtypes = [ctypes.c_void_p]
    lib.bq_destroy.argtypes = [ctypes.c_void_p]
    lib.bq_free.argtypes = [ctypes.c_char_p]
    _LIB = lib
    return _LIB


def native_available():
    return load_native() is not None


# --------------------------------------------------------------------------
# TCPStore
# --------------------------------------------------------------------------
class _PyStoreServer:
    """Pure-Python fallback server, protocol-compatible with tcp_store.cc."""

    def __init__(self, port):
        self._kv = {}
        self._cond = threading.Condition()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind(("0.0.0.0", port))
        self._sock.listen(128)
        self._stop = False
        self._thread = threading.Thread(target=self._accept_loop, daemon=True)
        self._thread.start()

    @property
    def port(self):
        return self._sock.getsockname()[1]

    def _accept_loop(self):
        while not self._stop:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,), daemon=True).start()

    def _recv(self, conn, n):
        data = b""
        while len(data) < n:
            chunk = conn.recv(n - len(data))
            if not chunk:
                raise ConnectionError
            data += chunk
        return data

    def _serve(self, conn):
        try:
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            while True:
                op = self._recv(conn, 1)
                (klen,) = struct.unpack("<I", self._recv(conn, 4))
                key = self._recv(conn, klen).decode()
                (vlen,) = struct.unpack("<I", self._recv(conn, 4))
                val = self._recv(conn, vlen) if vlen else b""
                if op == b"S":
                    with self._cond:
                        self._kv[key] = val
                        self._cond.notify_all()
                    conn.sendall(b"O" + struct.pack("<I", 0))
                elif op == b"G":
                    with self._cond:
                        ok = self._cond.wait_for(
                            lambda: self._stop or key in self._kv, timeout=600)
                        v = self._kv.get(key)
                    if ok and v is not None:
                        conn.sendall(b"O" + struct.pack("<I", len(v)) + v)
                    else:
                        conn.sendall(b"N" + struct.pack("<I", 0))
                elif op == b"A":
                    (delta,) = struct.unpack("<q", val)
                    with self._cond:
                        cur = struct.unpack("<q", self._kv.get(key, b"\0" * 8))[0]
                        res = cur + delta
                        self._kv[key] = struct.pack("<q", res)
                        self._cond.notify_all()
                    conn.sendall(b"O" + struct.pack("<I", 8) + struct.pack("<q", res))
                elif op == b"D":
                    with self._cond:
                        self._kv.pop(key, None)
                    conn.sendall(b"O" + struct.pack("<I", 0))
                elif op == b"C":
                    with self._cond:
                        has = key in self._kv
                    conn.sendall((b"O" if has else b"N") + struct.pack("<I", 0))
                elif op == b"L":
                    with self._cond:
                        n = len(self._kv)
                    conn.sendall(b"O" + struct.pack("<I", 8) + struct.pack("<q", n))
                else:
                    return
        except (ConnectionError, OSError):
            pass
        finally:
            conn.close()

    def stop(self):
        self._stop = True
        with self._cond:
            self._cond.notify_all()
        try:
            self._sock.close()
        except OSError:
            pass


class AmbiguousOpError(RuntimeError):
    """A non-idempotent store op (add) failed AFTER its request frame was
    fully sent: the server may or may not have applied it, so a transparent
    retry could double-apply (e.g. double-count rank assignment and hang the
    rendezvous with rank 0 unclaimed). Deliberately NOT a ConnectionError —
    the retry layer must not catch it; the caller's recovery tier owns the
    redo with knowledge of the op's semantics."""


class _PyStoreClient:
    def __init__(self, host, port, timeout_ms):
        import time

        deadline = time.monotonic() + timeout_ms / 1000.0
        while True:
            try:
                self._sock = socket.create_connection((host, port), timeout=5)
                self._sock.settimeout(None)
                self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                self._lock = threading.Lock()
                return
            except OSError:
                if time.monotonic() >= deadline:
                    raise TimeoutError(f"cannot connect to store at {host}:{port}")
                time.sleep(0.1)

    def _recv(self, n):
        data = b""
        while len(data) < n:
            chunk = self._sock.recv(n - len(data))
            if not chunk:
                raise ConnectionError
            data += chunk
        return data

    def _request(self, op, key, val=b"", non_idempotent=False):
        with self._lock:
            k = key.encode()
            # send/recv failures are distinguished on purpose: a sendall
            # failure means the length-prefixed frame never arrived whole —
            # the server cannot have applied it, so retry is always safe. A
            # recv failure after a complete send is AMBIGUOUS (applied, ack
            # lost?); for non-idempotent ops that must not be retried.
            self._sock.sendall(op + struct.pack("<I", len(k)) + k + struct.pack("<I", len(val)) + val)
            try:
                status = self._recv(1)
                (rlen,) = struct.unpack("<I", self._recv(4))
                out = self._recv(rlen) if rlen else b""
            except (ConnectionError, OSError) as e:
                if non_idempotent:
                    raise AmbiguousOpError(
                        f"store {op!r} on {key!r}: reply lost after a "
                        f"complete send — may or may not have applied") from e
                raise
        return status, out

    def set(self, key, val):
        st, _ = self._request(b"S", key, val)
        return st == b"O"

    def get(self, key):
        st, out = self._request(b"G", key)
        return out if st == b"O" else None

    def add(self, key, delta):
        st, out = self._request(b"A", key, struct.pack("<q", delta),
                                non_idempotent=True)
        return struct.unpack("<q", out)[0] if st == b"O" else -1

    def check(self, key):
        st, _ = self._request(b"C", key)
        return st == b"O"

    def delete(self, key):
        st, _ = self._request(b"D", key)
        return st == b"O"

    def close(self):
        self._sock.close()


class TCPStore:
    """reference: paddle.base.core.TCPStore(host, port, is_master, world_size,
    timeout). is_master starts the in-process server (rank 0)."""

    #: store ops ride one shared bounded-backoff policy (utils/retry.py):
    #: a transient RST/timeout redials and retries instead of failing the
    #: rendezvous; attempts are capped so a genuinely dead master still
    #: surfaces promptly. Chaos sites (testing/chaos.py "store.<op>") fire
    #: INSIDE the retried op, so injected outages exercise this exact path.
    retry_policy = RetryPolicy(attempts=4, base_delay=0.05)

    def __init__(self, host, port, is_master=False, world_size=1, timeout=900,
                 use_native=True):
        self._server = None
        self._native = use_native and native_available()
        self.host, self.port = host, port
        self._timeout_ms = int(timeout * 1000)
        if is_master:
            if self._native:
                lib = load_native()
                self._server = lib.tcpstore_server_start(port)
                if not self._server:
                    raise RuntimeError(f"TCPStore: cannot bind port {port}")
                self.port = lib.tcpstore_server_port(self._server)
            else:
                self._server = _PyStoreServer(port)
                self.port = self._server.port
            host = "127.0.0.1"
        self._connect_host = host
        if self._native:
            lib = load_native()
            self._client = lib.tcpstore_client_connect(host.encode(), self.port, self._timeout_ms)
            if not self._client:
                raise TimeoutError(f"cannot connect to store at {host}:{self.port}")
        else:
            self._client = _PyStoreClient(host, self.port, self._timeout_ms)

    def _reconnect(self, *_):
        """Retry hook: drop the (possibly poisoned) connection and redial."""
        if self._native:
            lib = load_native()
            if self._client:
                try:
                    lib.tcpstore_client_close(self._client)
                except Exception:
                    pass
            self._client = lib.tcpstore_client_connect(
                self._connect_host.encode(), self.port, 5000)
            if not self._client:
                raise ConnectionError(
                    f"cannot reconnect to store at {self._connect_host}:{self.port}")
        else:
            try:
                self._client.close()
            except OSError:
                pass
            self._client = _PyStoreClient(self._connect_host, self.port, 5000)

    def _retry(self, name, op):
        return self.retry_policy.run(op, name=name, on_retry=self._reconnect)

    def set(self, key, value):
        if isinstance(value, str):
            value = value.encode()

        def op():
            chaos.site("store.set")
            if self._native:
                lib = load_native()
                if lib.tcpstore_set(self._client, key.encode(), value, len(value)) != 0:
                    raise ConnectionError(f"TCPStore.set({key}) failed")
            elif not self._client.set(key, value):
                raise ConnectionError(f"TCPStore.set({key}) failed")

        self._retry("store.set", op)

    def get(self, key):
        """Blocking get (waits for the key)."""

        def op():
            chaos.site("store.get")
            if self._native:
                lib = load_native()
                out = ctypes.c_char_p()
                n = lib.tcpstore_get(self._client, key.encode(), ctypes.byref(out))
                if n < 0:
                    return None
                data = ctypes.string_at(out, n)
                lib.tcpstore_free(out)
                return data
            return self._client.get(key)

        return self._retry("store.get", op)

    def add(self, key, delta=1):
        # add is not idempotent, so only provably-unapplied failures retry:
        # chaos faults and send-phase errors (frame never arrived whole).
        # A reply lost AFTER a complete send raises AmbiguousOpError
        # (a RuntimeError the retry filter does not catch) — a double-
        # counted rank assignment would un-claim rank 0 and hang the whole
        # rendezvous, which is strictly worse than failing the join fast.
        def op():
            chaos.site("store.add")
            if self._native:
                lib = load_native()
                return int(lib.tcpstore_add(self._client, key.encode(), delta))
            return self._client.add(key, delta)

        return self._retry("store.add", op)

    def wait(self, keys, timeout=None):
        for k in keys if isinstance(keys, (list, tuple)) else [keys]:
            self.get(k)

    def check(self, key):
        def op():
            chaos.site("store.check")
            if self._native:
                lib = load_native()
                return lib.tcpstore_check(self._client, key.encode()) == 1
            return self._client.check(key)

        return self._retry("store.check", op)

    def delete_key(self, key):
        def op():
            chaos.site("store.delete")
            if self._native:
                lib = load_native()
                return lib.tcpstore_delete(self._client, key.encode()) == 0
            return self._client.delete(key)

        return self._retry("store.delete", op)

    def barrier(self, name, world_size, timeout=600):
        """All `world_size` participants block until everyone arrives."""
        import time

        n = self.add(f"__barrier/{name}", 1)
        if n >= world_size:
            self.set(f"__barrier/{name}/done", b"1")
            return
        deadline = time.monotonic() + timeout
        while not self.check(f"__barrier/{name}/done"):
            if time.monotonic() > deadline:
                raise TimeoutError(f"barrier {name}: {n}/{world_size} after {timeout}s")
            time.sleep(0.05)

    def stop_server(self):
        if self._server is None:
            return
        if self._native:
            load_native().tcpstore_server_stop(self._server)
        else:
            self._server.stop()
        self._server = None

    def __del__(self):
        try:
            if self._native and self._client:
                load_native().tcpstore_client_close(self._client)
                self._client = None
            elif not self._native and getattr(self, "_client", None):
                self._client.close()
        except Exception:
            pass


# --------------------------------------------------------------------------
# BlockingQueue
# --------------------------------------------------------------------------
class BlockingQueue:
    """Bounded byte-buffer queue; native (no-GIL handoff) when the lib is
    loadable, queue.Queue otherwise. Payloads are bytes (the DataLoader
    pickles numpy batches into it)."""

    def __init__(self, capacity=8, use_native=True):
        self._native = use_native and native_available()
        if self._native:
            self._h = load_native().bq_create(capacity)
        else:
            self._q = _pyqueue.Queue(maxsize=capacity)
            self._closed = False

    def push(self, data: bytes, timeout=None):
        if self._native:
            rc = load_native().bq_push(self._h, data, len(data),
                                       -1 if timeout is None else int(timeout * 1000))
            if rc == -1:
                raise RuntimeError("queue closed")
            if rc == -2:
                raise TimeoutError
            return
        if self._closed:
            raise RuntimeError("queue closed")
        try:
            self._q.put(data, timeout=timeout)
        except _pyqueue.Full:
            raise TimeoutError from None

    def pop(self, timeout=None):
        """Returns bytes, or None when closed and drained."""
        if self._native:
            lib = load_native()
            out = ctypes.c_char_p()
            n = lib.bq_pop(self._h, ctypes.byref(out),
                           -1 if timeout is None else int(timeout * 1000))
            if n == -1:
                return None
            if n == -2:
                raise TimeoutError
            data = ctypes.string_at(out, n)
            lib.bq_free(out)
            return data
        while True:
            try:
                return self._q.get(timeout=0.1 if self._closed else timeout)
            except _pyqueue.Empty:
                if self._closed and self._q.empty():
                    return None
                if timeout is not None:
                    raise TimeoutError from None

    def size(self):
        return load_native().bq_size(self._h) if self._native else self._q.qsize()

    def close(self):
        if self._native:
            load_native().bq_close(self._h)
        else:
            self._closed = True

    def __del__(self):
        try:
            if self._native and getattr(self, "_h", None):
                load_native().bq_destroy(self._h)
                self._h = None
        except Exception:
            pass
