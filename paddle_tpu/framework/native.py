"""ctypes bindings for the native C++ runtime (native/*.cc →
lib/libpaddle_tpu_native.so): TCPStore rendezvous (reference:
paddle/phi/core/distributed/store/tcp_store.cc) and the DataLoader blocking
queue (reference: paddle/fluid/operators/reader/blocking_queue.h).

If the shared lib is missing, it is built on demand with `make` (g++ is in
the image); if that fails, pure-Python fallbacks keep every API working —
the native path is a performance/GIL-contention win, not a correctness
dependency.
"""
import ctypes
import os
import queue as _pyqueue
import socket
import struct
import subprocess
import threading

_LIB = None
_TRIED = False


def _lib_path():
    return os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                        "lib", "libpaddle_tpu_native.so")


def load_native():
    """Load (building if needed) the native lib; returns None on failure."""
    global _LIB, _TRIED
    if _LIB is not None or _TRIED:
        return _LIB
    _TRIED = True
    path = _lib_path()
    if not os.path.exists(path):
        native_dir = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(path))), "native")
        if os.path.isdir(native_dir):
            try:
                subprocess.run(["make"], cwd=native_dir, check=True,
                               capture_output=True, timeout=120)
            except Exception:
                return None
    if not os.path.exists(path):
        return None
    try:
        lib = ctypes.CDLL(path)
    except OSError:
        return None
    # signatures
    lib.tcpstore_server_start.restype = ctypes.c_void_p
    lib.tcpstore_server_start.argtypes = [ctypes.c_int]
    lib.tcpstore_server_port.restype = ctypes.c_int
    lib.tcpstore_server_port.argtypes = [ctypes.c_void_p]
    lib.tcpstore_server_stop.argtypes = [ctypes.c_void_p]
    lib.tcpstore_client_connect.restype = ctypes.c_void_p
    lib.tcpstore_client_connect.argtypes = [ctypes.c_char_p, ctypes.c_int, ctypes.c_int]
    lib.tcpstore_set.restype = ctypes.c_int
    lib.tcpstore_set.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int]
    lib.tcpstore_get.restype = ctypes.c_int
    lib.tcpstore_get.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.POINTER(ctypes.c_char_p)]
    lib.tcpstore_add.restype = ctypes.c_longlong
    lib.tcpstore_add.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_longlong]
    lib.tcpstore_check.restype = ctypes.c_int
    lib.tcpstore_check.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.tcpstore_delete.restype = ctypes.c_int
    lib.tcpstore_delete.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.tcpstore_client_close.argtypes = [ctypes.c_void_p]
    lib.tcpstore_free.argtypes = [ctypes.c_char_p]
    lib.bq_create.restype = ctypes.c_void_p
    lib.bq_create.argtypes = [ctypes.c_int]
    lib.bq_push.restype = ctypes.c_int
    lib.bq_push.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_longlong, ctypes.c_int]
    lib.bq_pop.restype = ctypes.c_longlong
    lib.bq_pop.argtypes = [ctypes.c_void_p, ctypes.POINTER(ctypes.c_char_p), ctypes.c_int]
    lib.bq_size.restype = ctypes.c_int
    lib.bq_size.argtypes = [ctypes.c_void_p]
    lib.bq_close.argtypes = [ctypes.c_void_p]
    lib.bq_destroy.argtypes = [ctypes.c_void_p]
    lib.bq_free.argtypes = [ctypes.c_char_p]
    _LIB = lib
    return _LIB


def native_available():
    return load_native() is not None


# --------------------------------------------------------------------------
# TCPStore
# --------------------------------------------------------------------------
class _PyStoreServer:
    """Pure-Python fallback server, protocol-compatible with tcp_store.cc."""

    def __init__(self, port):
        self._kv = {}
        self._cond = threading.Condition()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind(("0.0.0.0", port))
        self._sock.listen(128)
        self._stop = False
        self._thread = threading.Thread(target=self._accept_loop, daemon=True)
        self._thread.start()

    @property
    def port(self):
        return self._sock.getsockname()[1]

    def _accept_loop(self):
        while not self._stop:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,), daemon=True).start()

    def _recv(self, conn, n):
        data = b""
        while len(data) < n:
            chunk = conn.recv(n - len(data))
            if not chunk:
                raise ConnectionError
            data += chunk
        return data

    def _serve(self, conn):
        try:
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            while True:
                op = self._recv(conn, 1)
                (klen,) = struct.unpack("<I", self._recv(conn, 4))
                key = self._recv(conn, klen).decode()
                (vlen,) = struct.unpack("<I", self._recv(conn, 4))
                val = self._recv(conn, vlen) if vlen else b""
                if op == b"S":
                    with self._cond:
                        self._kv[key] = val
                        self._cond.notify_all()
                    conn.sendall(b"O" + struct.pack("<I", 0))
                elif op == b"G":
                    with self._cond:
                        ok = self._cond.wait_for(
                            lambda: self._stop or key in self._kv, timeout=600)
                        v = self._kv.get(key)
                    if ok and v is not None:
                        conn.sendall(b"O" + struct.pack("<I", len(v)) + v)
                    else:
                        conn.sendall(b"N" + struct.pack("<I", 0))
                elif op == b"A":
                    (delta,) = struct.unpack("<q", val)
                    with self._cond:
                        cur = struct.unpack("<q", self._kv.get(key, b"\0" * 8))[0]
                        res = cur + delta
                        self._kv[key] = struct.pack("<q", res)
                        self._cond.notify_all()
                    conn.sendall(b"O" + struct.pack("<I", 8) + struct.pack("<q", res))
                elif op == b"D":
                    with self._cond:
                        self._kv.pop(key, None)
                    conn.sendall(b"O" + struct.pack("<I", 0))
                elif op == b"C":
                    with self._cond:
                        has = key in self._kv
                    conn.sendall((b"O" if has else b"N") + struct.pack("<I", 0))
                elif op == b"L":
                    with self._cond:
                        n = len(self._kv)
                    conn.sendall(b"O" + struct.pack("<I", 8) + struct.pack("<q", n))
                else:
                    return
        except (ConnectionError, OSError):
            pass
        finally:
            conn.close()

    def stop(self):
        self._stop = True
        with self._cond:
            self._cond.notify_all()
        try:
            self._sock.close()
        except OSError:
            pass


class _PyStoreClient:
    def __init__(self, host, port, timeout_ms):
        import time

        deadline = time.monotonic() + timeout_ms / 1000.0
        while True:
            try:
                self._sock = socket.create_connection((host, port), timeout=5)
                self._sock.settimeout(None)
                self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                self._lock = threading.Lock()
                return
            except OSError:
                if time.monotonic() >= deadline:
                    raise TimeoutError(f"cannot connect to store at {host}:{port}")
                time.sleep(0.1)

    def _recv(self, n):
        data = b""
        while len(data) < n:
            chunk = self._sock.recv(n - len(data))
            if not chunk:
                raise ConnectionError
            data += chunk
        return data

    def _request(self, op, key, val=b""):
        with self._lock:
            k = key.encode()
            self._sock.sendall(op + struct.pack("<I", len(k)) + k + struct.pack("<I", len(val)) + val)
            status = self._recv(1)
            (rlen,) = struct.unpack("<I", self._recv(4))
            out = self._recv(rlen) if rlen else b""
        return status, out

    def set(self, key, val):
        st, _ = self._request(b"S", key, val)
        return st == b"O"

    def get(self, key):
        st, out = self._request(b"G", key)
        return out if st == b"O" else None

    def add(self, key, delta):
        st, out = self._request(b"A", key, struct.pack("<q", delta))
        return struct.unpack("<q", out)[0] if st == b"O" else -1

    def check(self, key):
        st, _ = self._request(b"C", key)
        return st == b"O"

    def delete(self, key):
        st, _ = self._request(b"D", key)
        return st == b"O"

    def close(self):
        self._sock.close()


class TCPStore:
    """reference: paddle.base.core.TCPStore(host, port, is_master, world_size,
    timeout). is_master starts the in-process server (rank 0)."""

    def __init__(self, host, port, is_master=False, world_size=1, timeout=900,
                 use_native=True):
        self._server = None
        self._native = use_native and native_available()
        self.host, self.port = host, port
        timeout_ms = int(timeout * 1000)
        if is_master:
            if self._native:
                lib = load_native()
                self._server = lib.tcpstore_server_start(port)
                if not self._server:
                    raise RuntimeError(f"TCPStore: cannot bind port {port}")
                self.port = lib.tcpstore_server_port(self._server)
            else:
                self._server = _PyStoreServer(port)
                self.port = self._server.port
            host = "127.0.0.1"
        if self._native:
            lib = load_native()
            self._client = lib.tcpstore_client_connect(host.encode(), self.port, timeout_ms)
            if not self._client:
                raise TimeoutError(f"cannot connect to store at {host}:{self.port}")
        else:
            self._client = _PyStoreClient(host, self.port, timeout_ms)

    def set(self, key, value):
        if isinstance(value, str):
            value = value.encode()
        if self._native:
            lib = load_native()
            if lib.tcpstore_set(self._client, key.encode(), value, len(value)) != 0:
                raise RuntimeError(f"TCPStore.set({key}) failed")
        else:
            self._client.set(key, value)

    def get(self, key):
        """Blocking get (waits for the key)."""
        if self._native:
            lib = load_native()
            out = ctypes.c_char_p()
            n = lib.tcpstore_get(self._client, key.encode(), ctypes.byref(out))
            if n < 0:
                return None
            data = ctypes.string_at(out, n)
            lib.tcpstore_free(out)
            return data
        return self._client.get(key)

    def add(self, key, delta=1):
        if self._native:
            lib = load_native()
            return int(lib.tcpstore_add(self._client, key.encode(), delta))
        return self._client.add(key, delta)

    def wait(self, keys, timeout=None):
        for k in keys if isinstance(keys, (list, tuple)) else [keys]:
            self.get(k)

    def check(self, key):
        if self._native:
            lib = load_native()
            return lib.tcpstore_check(self._client, key.encode()) == 1
        return self._client.check(key)

    def delete_key(self, key):
        if self._native:
            lib = load_native()
            return lib.tcpstore_delete(self._client, key.encode()) == 0
        return self._client.delete(key)

    def barrier(self, name, world_size, timeout=600):
        """All `world_size` participants block until everyone arrives."""
        import time

        n = self.add(f"__barrier/{name}", 1)
        if n >= world_size:
            self.set(f"__barrier/{name}/done", b"1")
            return
        deadline = time.monotonic() + timeout
        while not self.check(f"__barrier/{name}/done"):
            if time.monotonic() > deadline:
                raise TimeoutError(f"barrier {name}: {n}/{world_size} after {timeout}s")
            time.sleep(0.05)

    def stop_server(self):
        if self._server is None:
            return
        if self._native:
            load_native().tcpstore_server_stop(self._server)
        else:
            self._server.stop()
        self._server = None

    def __del__(self):
        try:
            if self._native and self._client:
                load_native().tcpstore_client_close(self._client)
                self._client = None
            elif not self._native and getattr(self, "_client", None):
                self._client.close()
        except Exception:
            pass


# --------------------------------------------------------------------------
# BlockingQueue
# --------------------------------------------------------------------------
class BlockingQueue:
    """Bounded byte-buffer queue; native (no-GIL handoff) when the lib is
    loadable, queue.Queue otherwise. Payloads are bytes (the DataLoader
    pickles numpy batches into it)."""

    def __init__(self, capacity=8, use_native=True):
        self._native = use_native and native_available()
        if self._native:
            self._h = load_native().bq_create(capacity)
        else:
            self._q = _pyqueue.Queue(maxsize=capacity)
            self._closed = False

    def push(self, data: bytes, timeout=None):
        if self._native:
            rc = load_native().bq_push(self._h, data, len(data),
                                       -1 if timeout is None else int(timeout * 1000))
            if rc == -1:
                raise RuntimeError("queue closed")
            if rc == -2:
                raise TimeoutError
            return
        if self._closed:
            raise RuntimeError("queue closed")
        try:
            self._q.put(data, timeout=timeout)
        except _pyqueue.Full:
            raise TimeoutError from None

    def pop(self, timeout=None):
        """Returns bytes, or None when closed and drained."""
        if self._native:
            lib = load_native()
            out = ctypes.c_char_p()
            n = lib.bq_pop(self._h, ctypes.byref(out),
                           -1 if timeout is None else int(timeout * 1000))
            if n == -1:
                return None
            if n == -2:
                raise TimeoutError
            data = ctypes.string_at(out, n)
            lib.bq_free(out)
            return data
        while True:
            try:
                return self._q.get(timeout=0.1 if self._closed else timeout)
            except _pyqueue.Empty:
                if self._closed and self._q.empty():
                    return None
                if timeout is not None:
                    raise TimeoutError from None

    def size(self):
        return load_native().bq_size(self._h) if self._native else self._q.qsize()

    def close(self):
        if self._native:
            load_native().bq_close(self._h)
        else:
            self._closed = True

    def __del__(self):
        try:
            if self._native and getattr(self, "_h", None):
                load_native().bq_destroy(self._h)
                self._h = None
        except Exception:
            pass
