"""Version-compat shims over jax API drift.

The repo targets the current jax API; containers pinning an older jax must
still import and run (robustness tier: the framework cannot be taken down
by a substrate minor-version skew). Each shim resolves ONCE at call time to
the native API when present and only translates when it must.

shard_map: top-level `jax.shard_map(..., check_vma=, axis_names=)` landed
after 0.4.37; older releases spell it `jax.experimental.shard_map.shard_map`
with `check_rep=` and an inverted `auto=` (axes NOT manual) instead of
`axis_names=` (axes manual).
"""
import jax


def shard_map(f, mesh, in_specs, out_specs, check_vma=None, axis_names=None):
    if hasattr(jax, "shard_map"):
        kw = {}
        if check_vma is not None:
            kw["check_vma"] = check_vma
        if axis_names is not None:
            kw["axis_names"] = axis_names
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, **kw)
    from jax.experimental.shard_map import shard_map as _sm

    # check_rep (the old replication checker) lacks rules for ops the new
    # vma checker handles (sharding_constraint, psum-of-masked) — it is a
    # lint, not a semantics switch, so default it OFF when translating.
    #
    # axis_names (partial-auto: named axes manual, the rest GSPMD-auto) is
    # deliberately NOT translated to the old `auto=` parameter: 0.4.x
    # partial-auto cannot lower axis_index/psum in manual-vs-auto mixes
    # ("PartitionId is not supported for SPMD partitioning"). Full-manual is
    # the sound fallback — axes unmentioned by in_specs are replicated into
    # the body, which preserves numerics exactly and only forgoes GSPMD
    # sharding over the auto axes inside the region (memory/perf, not
    # semantics; real-accelerator builds run the native path anyway).
    kw = {"check_rep": bool(check_vma) if check_vma is not None else False}
    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw)
