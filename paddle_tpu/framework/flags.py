"""Runtime flag registry (reference: paddle/utils/flags.h
PD_DEFINE_EXPORTED_* + flags.cc registry; Python surface paddle.set_flags /
paddle.get_flags; env override contract FLAGS_<name>=value).

The reference exports ~200 C++ flags; here the registry carries the ones
with TPU-meaningful behavior plus accepts unknown names (stored, inert) so
scripts that set CUDA-era flags keep running.
"""
import os
import threading

_lock = threading.Lock()


def _env_default(name, default, typ):
    raw = os.environ.get(f"FLAGS_{name}")
    if raw is None:
        return default
    if typ is bool:
        return raw.lower() in ("1", "true", "yes", "on")
    try:
        return typ(raw)
    except ValueError:
        return default


# name -> (default, type, help)
_DEFS = {
    # debugging (reference: nan_inf_utils_detail, enforce)
    "check_nan_inf": (False, bool, "scan every eager op output for NaN/Inf"),
    "check_nan_inf_level": (0, int, "0 raise, 1 warn"),
    "call_stack_level": (2, int, "error message verbosity"),
    # determinism (reference: cudnn_deterministic)
    "cudnn_deterministic": (False, bool, "accepted for script compat; XLA on TPU is deterministic per compile"),
    "embedding_deterministic": (0, int, "script compat"),
    # allocator stats (reference: FLAGS_fraction_of_gpu_memory_to_use etc.)
    "fraction_of_gpu_memory_to_use": (0.92, float, "script compat; XLA preallocation analogue"),
    "allocator_strategy": ("auto_growth", str, "script compat"),
    "gpu_memory_limit_mb": (0, int, "script compat"),
    # profiler / logging
    "enable_profiler": (False, bool, "v1 profiler toggle"),
    "v": (0, int, "glog-style verbosity (GLOG_v)"),
    # distributed
    "distributed_timeout_s": (900, int, "rendezvous / collective timeout"),
    "stop_check_timeout": (300, int, "launcher watchdog timeout"),
    # numerics
    "use_tf32": (True, bool, "script compat; TPU matmuls are bf16/fp32 per dtype"),
    "matmul_use_bf16": (True, bool, "prefer bf16 matmul accumulation inputs"),
}

_values = {}
_types = {}
for _n, (_d, _t, _h) in _DEFS.items():
    _values[_n] = _env_default(_n, _d, _t)
    _types[_n] = _t


def set_flags(flags):
    """paddle.set_flags parity. Accepts {'FLAGS_name': value} or {'name': value}."""
    with _lock:
        for k, v in flags.items():
            name = k[6:] if k.startswith("FLAGS_") else k
            t = _types.get(name)
            if t is bool and isinstance(v, str):
                v = v.lower() in ("1", "true", "yes", "on")
            elif t is not None and not isinstance(v, t):
                v = t(v)
            _values[name] = v


def get_flags(flags):
    """paddle.get_flags parity: name or list of names → {FLAGS_name: value}."""
    names = [flags] if isinstance(flags, str) else list(flags)
    out = {}
    with _lock:
        for k in names:
            name = k[6:] if k.startswith("FLAGS_") else k
            if name not in _values:
                raise ValueError(f"unknown flag {k}")
            out[f"FLAGS_{name}"] = _values[name]
    return out


def flag(name, default=None):
    """Internal fast read."""
    return _values.get(name, default)
