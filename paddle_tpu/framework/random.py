"""RNG discipline.

Paddle seeds a global generator (`paddle.seed`) and layers draw from it
imperatively. On TPU, randomness must be functional: a PRNG key threaded
through the program. We bridge the two:

- Eager mode: a global key split on every draw (imperative ergonomics).
- Traced mode (inside `paddle_tpu.jit` / compiled train steps): the trainer
  installs a traced base key via `rng_guard`; draws fold in a per-call
  counter so the trace stays pure and reproducible.
- TP-parallel dropout (reference: fleet meta_parallel/parallel_layers/
  random.py RNGStatesTracker): `RNGStatesTracker` keeps named states whose
  keys fold in mesh coordinates, so "local" dropout differs across model-
  parallel ranks while "global" seeds agree.
"""
import contextlib
import threading

import jax
import numpy as np

_state = threading.local()


def _tls():
    if not hasattr(_state, "key"):
        _state.key = jax.random.PRNGKey(np.random.randint(0, 2**31 - 1))
        _state.traced_key = None
        _state.counter = 0
    return _state


def seed(s: int):
    """paddle.seed parity: reseed the global generator."""
    t = _tls()
    t.key = jax.random.PRNGKey(int(s))
    t.counter = 0
    get_rng_state_tracker().reset(int(s))
    return t.key


def next_key():
    """Draw a fresh PRNG key. Pure under trace (fold_in counter), split eagerly."""
    t = _tls()
    if t.traced_key is not None:
        t.counter += 1
        return jax.random.fold_in(t.traced_key, t.counter)
    t.key, sub = jax.random.split(t.key)
    return sub


@contextlib.contextmanager
def rng_guard(key):
    """Install a (possibly traced) base key; draws become fold_in(key, n)."""
    t = _tls()
    prev, prev_c = t.traced_key, t.counter
    t.traced_key, t.counter = key, 0
    try:
        yield
    finally:
        t.traced_key, t.counter = prev, prev_c


def get_rng_state():
    return _tls().key


def set_rng_state(key):
    _tls().key = key


class RNGStatesTracker:
    """Named RNG states for tensor-parallel dropout (reference:
    fleet/meta_parallel/parallel_layers/random.py, get_rng_state_tracker)."""

    def __init__(self):
        self._seeds = {}

    def reset(self, base_seed=0):
        self._seeds = {}
        self._base = base_seed

    def add(self, name, seed_):
        if name in self._seeds and self._seeds[name][0] != seed_:
            raise ValueError(f"rng state {name} already exists")
        self._seeds[name] = (seed_, jax.random.PRNGKey(seed_))

    @contextlib.contextmanager
    def rng_state(self, name="model_parallel_rng"):
        if name not in self._seeds:
            self.add(name, np.random.randint(0, 2**31 - 1))
        s, key = self._seeds[name]
        t = _tls()
        prev_key, prev_traced, prev_c = t.key, t.traced_key, t.counter
        t.key, t.traced_key, t.counter = key, key, 0
        try:
            yield
        finally:
            self._seeds[name] = (s, jax.random.fold_in(key, 1))
            t.key, t.traced_key, t.counter = prev_key, prev_traced, prev_c


_tracker = RNGStatesTracker()


def get_rng_state_tracker():
    return _tracker
