"""Dtype registry — Paddle-style dtype names over jnp dtypes.

Reference parity: paddle/phi/common/data_type.h (DataType enum) and
python/paddle/framework/dtype.py. Here dtypes are plain numpy/jnp dtypes —
XLA is the single source of truth for device layouts.
"""
import jax.numpy as jnp
import numpy as np

bool = jnp.bool_
uint8 = jnp.uint8
int8 = jnp.int8
int16 = jnp.int16
int32 = jnp.int32
int64 = jnp.int64
float16 = jnp.float16
bfloat16 = jnp.bfloat16
float32 = jnp.float32
float64 = jnp.float64
complex64 = jnp.complex64
complex128 = jnp.complex128

_NAME2DTYPE = {
    "bool": jnp.bool_,
    "uint8": jnp.uint8,
    "int8": jnp.int8,
    "int16": jnp.int16,
    "int32": jnp.int32,
    "int64": jnp.int64,
    "float16": jnp.float16,
    "bfloat16": jnp.bfloat16,
    "float32": jnp.float32,
    "float64": jnp.float64,
    "complex64": jnp.complex64,
    "complex128": jnp.complex128,
}

_default_dtype = jnp.float32


def convert_dtype(dtype):
    """Normalize a dtype spec (str | np.dtype | jnp dtype) to a numpy dtype."""
    if dtype is None:
        return None
    if isinstance(dtype, str):
        if dtype not in _NAME2DTYPE:
            raise ValueError(f"unknown dtype {dtype!r}")
        return np.dtype(_NAME2DTYPE[dtype])
    return np.dtype(dtype)


def dtype_name(dtype):
    return np.dtype(dtype).name


def set_default_dtype(d):
    global _default_dtype
    _default_dtype = convert_dtype(d)


def get_default_dtype():
    return _default_dtype


def is_floating_point_dtype(dtype):
    return np.issubdtype(np.dtype(dtype), np.floating) or np.dtype(dtype) == jnp.bfloat16
