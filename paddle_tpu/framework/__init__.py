from . import dtype, random
from .core import (
    GradNode,
    Parameter,
    Tensor,
    apply,
    enable_grad,
    is_grad_enabled,
    no_grad,
    no_grad_guard,
    to_tensor,
)
from .dtype import convert_dtype, get_default_dtype, set_default_dtype
from .random import get_rng_state, seed, set_rng_state
