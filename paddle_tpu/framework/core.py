"""Dygraph core: Tensor + tape autograd over jax.vjp.

Reference parity (architecture, not a port):
- paddle/fluid/eager/ (GradNodeBase, RunBackward in eager/backward.cc): the
  reference records a GradNode per op and runs a reverse topological queue
  with pending-count scheduling. We do the same, but each node's backward is
  the vjp closure jax.vjp returned at forward time.
- The decisive TPU divergence (SURVEY.md §3.1): this entire tape is built
  from traceable jax operations, so a whole train step — forward, backward,
  optimizer — wrapped in `paddle_tpu.jit` becomes ONE XLA program. Eager
  Python dispatch cost exists only in uncompiled (debug) mode.
"""
from __future__ import annotations

import contextlib
import threading

import jax
import jax.numpy as jnp
import numpy as np

from . import dtype as dtypes

_tls = threading.local()


def _grad_enabled():
    return getattr(_tls, "grad_enabled", True)


@contextlib.contextmanager
def no_grad_guard():
    prev = _grad_enabled()
    _tls.grad_enabled = False
    try:
        yield
    finally:
        _tls.grad_enabled = prev


class no_grad:
    """paddle.no_grad parity: usable as context manager or decorator."""

    def __enter__(self):
        self._cm = no_grad_guard()
        return self._cm.__enter__()

    def __exit__(self, *exc):
        return self._cm.__exit__(*exc)

    def __call__(self, fn):
        def wrapper(*a, **k):
            with no_grad_guard():
                return fn(*a, **k)

        return wrapper


def enable_grad():
    _tls.grad_enabled = True


def is_grad_enabled():
    return _grad_enabled()


class set_grad_enabled:
    """paddle.set_grad_enabled parity: immediate toggle that also works as
    a context manager (restores the previous mode on exit)."""

    def __init__(self, mode):
        self._prev = _grad_enabled()
        _tls.grad_enabled = bool(mode)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        _tls.grad_enabled = self._prev
        return False


def init_tensor_slots(t, name=None):
    """Bootstrap Tensor's bookkeeping slots for subclasses that do NOT call
    Tensor.__init__ (symbolic/sparse tensors with a lazy or absent _data).
    Single source of truth next to __slots__ — keep in lock-step."""
    t.stop_gradient = True
    t.grad = None
    t._node = None
    t._out_idx = 0
    t._hooks = []
    t.name = name
    t._dist_attr = None


class GradNode:
    """One recorded op on the tape (reference: eager/grad_node_info.h
    GradNodeBase). Holds the vjp closure and edges to input tensors."""

    __slots__ = ("vjp_fn", "inputs", "out_avals", "n_outputs", "name", "cotangents", "pending")

    def __init__(self, vjp_fn, inputs, out_avals, name=""):
        self.vjp_fn = vjp_fn
        self.inputs = inputs  # list of (Tensor, is_diff)
        self.out_avals = out_avals  # [(shape, dtype)] per output
        self.n_outputs = len(out_avals)
        self.name = name
        self.cotangents = None
        self.pending = 0


def _is_inexact(d):
    return np.issubdtype(np.dtype(d), np.inexact) or np.dtype(d) == jnp.bfloat16


# Monotonic count of in-place tensor mutations (set_value/copy_/fill_/zero_
# and every load path, which funnels through set_value). Consumers that
# cache anything derived from weights — the serving engine's prefix KV
# cache — snapshot this and invalidate on change. Unlike identity (id())
# tuples, a counter can never false-match when CPython recycles a freed
# array's address (ADVICE r5 medium). Over-invalidation (a mutation of an
# UNRELATED tensor also bumps it) is deliberate: a spurious cache clear
# costs a recompute; a stale prefix KV silently serves wrong tokens.
_MUTATION_VERSION = 0


def _bump_mutation_version():
    global _MUTATION_VERSION
    _MUTATION_VERSION += 1  # GIL-atomic enough: races only over-invalidate


def tensor_mutation_version():
    return _MUTATION_VERSION


class Tensor:
    """Imperative tensor over a jax.Array (reference: phi::DenseTensor +
    the eager Tensor in paddle/fluid/pybind/eager.cc).

    Registered as a jax pytree, so Tensors flow through jax.jit / pjit /
    shard_map unchanged.
    """

    __slots__ = ("_data", "stop_gradient", "grad", "_node", "_out_idx", "_hooks", "name",
                 "_dist_attr", "__weakref__")
    __array_priority__ = 100  # win over numpy operator dispatch

    def __init__(self, data, stop_gradient=True, name=None):
        if isinstance(data, Tensor):
            data = data._data
        if not isinstance(data, (jax.Array, jax.core.Tracer)):
            data = jnp.asarray(data)
        self._data = data
        self.stop_gradient = stop_gradient
        self.grad = None
        self._node = None
        self._out_idx = 0
        self._hooks = []
        self.name = name
        self._dist_attr = None

    # -- basic properties ---------------------------------------------------
    @property
    def shape(self):
        return list(self._data.shape)

    @property
    def ndim(self):
        return self._data.ndim

    dim = ndim

    @property
    def dtype(self):
        return self._data.dtype

    @property
    def size(self):
        return int(np.prod(self._data.shape)) if self._data.shape else 1

    @property
    def T(self):
        from ..tensor import manipulation

        return manipulation.transpose(self, list(range(self.ndim))[::-1])

    @property
    def data(self):
        return self

    @data.setter
    def data(self, value):
        self._data = value._data if isinstance(value, Tensor) else jnp.asarray(value)

    @property
    def place(self):
        try:
            return str(next(iter(self._data.devices())))
        except Exception:
            return "traced"

    @property
    def is_leaf(self):
        return self._node is None

    def numel(self):
        return self.size

    def numpy(self):
        return np.asarray(self._data)

    def item(self, *args):
        return self.numpy().item(*args)

    def tolist(self):
        return self.numpy().tolist()

    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of a 0-d tensor")
        return self._data.shape[0]

    def __repr__(self):
        sg = self.stop_gradient
        try:
            body = repr(self._data)
        except Exception:
            body = f"<traced {self._data.aval}>"
        return f"Tensor(shape={self.shape}, dtype={dtypes.dtype_name(self.dtype)}, stop_gradient={sg},\n       {body})"

    def __bool__(self):
        return bool(self._data)

    def __int__(self):
        return int(self._data)

    def __float__(self):
        return float(self._data)

    def __format__(self, spec):
        if self.ndim == 0:
            return format(self.item(), spec)
        return format(repr(self), spec)

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    __hash__ = object.__hash__

    # -- autograd -----------------------------------------------------------
    def backward(self, grad_tensor=None, retain_graph=False):
        """Reverse-mode pass (reference: egr::Backward in eager/backward.cc):
        pending-count scheduled reverse topological walk over GradNodes."""
        if self.stop_gradient:
            raise RuntimeError("backward() on a tensor with stop_gradient=True")
        if grad_tensor is None:
            if not _is_inexact(self.dtype):
                raise RuntimeError("backward() requires a floating tensor")
            seed = jnp.ones_like(self._data)
        else:
            seed = grad_tensor._data if isinstance(grad_tensor, Tensor) else jnp.asarray(grad_tensor)

        if self._node is None:
            self._accum_grad(seed)
            return

        # Pass 1: discover reachable nodes and per-node consumer counts.
        nodes = []
        seen = set()
        stack = [self._node]
        while stack:
            n = stack.pop()
            if id(n) in seen:
                continue
            seen.add(id(n))
            nodes.append(n)
            n.cotangents = [None] * n.n_outputs
            n.pending = 0
            # Only traverse differentiable edges: a node reachable solely via
            # non-diff edges never receives cotangents and must not inflate
            # pending counts (it would deadlock diff-reachable ancestors).
            for t, is_diff in n.inputs:
                if t._node is not None and is_diff:
                    stack.append(t._node)
        for n in nodes:
            for t, is_diff in n.inputs:
                if t._node is not None and is_diff:
                    t._node.pending += 1

        # Seed the root.
        root = self._node
        root.cotangents[self._out_idx] = seed

        ready = [n for n in nodes if n.pending == 0]
        # Root must be processed first; pending counts guarantee ancestors of
        # any ready node already ran, and the root has no consumers here.
        while ready:
            n = ready.pop()
            cts = tuple(
                c if c is not None else jnp.zeros(shape, dtype)
                for c, (shape, dtype) in zip(n.cotangents, n.out_avals)
            )
            if n.vjp_fn is None:
                raise RuntimeError(
                    "the backward graph has been freed; call backward(retain_graph=True) "
                    "to backprop through the same graph twice"
                )
            in_cts = n.vjp_fn(cts if n.n_outputs > 1 else cts[0])
            if not retain_graph:
                n.vjp_fn = None
            ct_iter = iter(in_cts)
            for t, is_diff in n.inputs:
                if not is_diff:
                    continue
                ct = next(ct_iter)
                if t._node is not None:
                    m = t._node
                    prev = m.cotangents[t._out_idx]
                    m.cotangents[t._out_idx] = ct if prev is None else prev + ct
                    m.pending -= 1
                    if m.pending == 0:
                        ready.append(m)
                elif not t.stop_gradient:
                    t._accum_grad(ct)
            n.cotangents = None

    def _accum_grad(self, ct):
        for h in self._hooks:
            out = h(Tensor(ct, stop_gradient=True))
            if out is not None:
                ct = out._data if isinstance(out, Tensor) else out
        if self.grad is None:
            self.grad = Tensor(ct, stop_gradient=True)
        else:
            self.grad = Tensor(self.grad._data + ct, stop_gradient=True)

    def register_hook(self, hook):
        self._hooks.append(hook)

        class _Handle:
            def remove(handle_self):
                if hook in self._hooks:
                    self._hooks.remove(hook)

        return _Handle()

    def clear_grad(self):
        self.grad = None

    clear_gradient = clear_grad

    def detach(self):
        t = Tensor(self._data, stop_gradient=True, name=self.name)
        return t

    def detach_(self):
        self._node = None
        self.stop_gradient = True
        return self

    def clone(self):
        return apply(lambda x: x + 0, self, name="clone")

    # -- conversion ---------------------------------------------------------
    def astype(self, dt):
        dt = dtypes.convert_dtype(dt)
        return apply(lambda x: x.astype(dt), self, name="cast")

    cast = astype

    def to(self, *args, **kwargs):
        for a in list(args) + list(kwargs.values()):
            if isinstance(a, str) and a in dtypes._NAME2DTYPE:
                return self.astype(a)
            if a in (np.float32, np.float16, jnp.bfloat16, np.float64):
                return self.astype(a)
        return self

    def cpu(self):
        return self

    def pin_memory(self):
        return self

    def cuda(self, *_):
        return self

    # -- mutation -----------------------------------------------------------
    def set_value(self, value):
        v = value._data if isinstance(value, Tensor) else jnp.asarray(value)
        self._data = v.astype(self.dtype) if v.dtype != self.dtype else v
        _bump_mutation_version()
        return self

    def copy_(self, other, *_):
        return self.set_value(other)

    def fill_(self, v):
        self._data = jnp.full_like(self._data, v)
        _bump_mutation_version()
        return self

    def zero_(self):
        self._data = jnp.zeros_like(self._data)
        _bump_mutation_version()
        return self

    def scale_(self, s):
        self._data = self._data * s
        return self

    def _inplace_from(self, out):
        self._data = out._data if isinstance(out, Tensor) else jnp.asarray(out)
        return self

    def add_(self, y):
        return self._inplace_from(self + y)

    def subtract_(self, y):
        return self._inplace_from(self - y)

    def multiply_(self, y):
        return self._inplace_from(self * y)

    def clip_(self, min=None, max=None):
        from ..tensor.math import clip

        return self._inplace_from(clip(self, min, max))

    def scatter_(self, index, updates, overwrite=True):
        from ..tensor.manipulation import scatter

        return self._inplace_from(scatter(self, index, updates, overwrite))

    def masked_fill_(self, mask, value):
        from ..tensor.manipulation import masked_fill

        return self._inplace_from(masked_fill(self, mask, value))

    def fill_diagonal_(self, value, offset=0, wrap=False):
        rows, cols = self._data.shape[-2], self._data.shape[-1]
        if wrap and self._data.ndim == 2 and rows > cols:
            # torch-style wrap: the diagonal restarts every cols+1 rows;
            # same (i, i+offset) convention as the non-wrap branch
            r = jnp.arange(rows)
            c = (r + offset) % (cols + 1)
            on = c < cols
            self._data = self._data.at[r[on], c[on]].set(value)
            return self
        # offset >= 0: (i, i+offset); offset < 0: (i-offset, i)
        n = min(rows, cols - offset) if offset >= 0 else min(rows + offset, cols)
        if n <= 0:
            return self
        idx = jnp.arange(n)
        ri, ci = (idx, idx + offset) if offset >= 0 else (idx - offset, idx)
        self._data = self._data.at[..., ri, ci].set(value)
        return self

    def normal_(self, mean=0.0, std=1.0):
        from . import random as prandom

        self._data = (
            mean + std * jax.random.normal(prandom.next_key(), self._data.shape)
        ).astype(self.dtype)
        return self

    def uniform_(self, min=-1.0, max=1.0):
        from . import random as prandom

        self._data = jax.random.uniform(
            prandom.next_key(), self._data.shape, minval=min, maxval=max
        ).astype(self.dtype)
        return self

    def exponential_(self, lam=1.0):
        from . import random as prandom

        self._data = (
            jax.random.exponential(prandom.next_key(), self._data.shape) / lam
        ).astype(self.dtype)
        return self

    # -- torch-flavored trivia the reference also carries -------------------
    @property
    def mT(self):
        from ..tensor.manipulation import transpose

        perm = list(range(self.ndim))
        perm[-2], perm[-1] = perm[-1], perm[-2]
        return transpose(self, perm)

    def contiguous(self):
        return self  # XLA arrays have no strided views

    def is_contiguous(self):
        return True

    def element_size(self):
        return int(jnp.dtype(self.dtype).itemsize)

    def ndimension(self):
        return self.ndim

    def retain_grads(self):
        return None  # non-leaf grads are already materialized by the tape

    def __setitem__(self, idx, value):
        idx = _index_data(idx)
        v = value._data if isinstance(value, Tensor) else value
        self._data = self._data.at[idx].set(v)

    def __getitem__(self, idx):
        idx = _index_data(idx)
        return apply(lambda x: x[idx], self, name="getitem")


def _index_data(idx):
    if isinstance(idx, Tensor):
        return idx._data
    if isinstance(idx, tuple):
        return tuple(i._data if isinstance(i, Tensor) else i for i in idx)
    return idx


class Parameter(Tensor):
    """Trainable tensor (reference: paddle.base.framework.Parameter —
    stop_gradient defaults False, carries an optional trainable flag and a
    distributed PartitionSpec hint used by the pjit paths)."""

    __slots__ = ("trainable", "optimize_attr", "is_distributed", "partition_spec", "no_sync",
                 "sequence_parallel", "__dict__")

    def __init__(self, data, trainable=True, name=None):
        super().__init__(data, stop_gradient=not trainable, name=name)
        self.trainable = trainable
        self.optimize_attr = {"learning_rate": 1.0}
        self.is_distributed = False
        self.partition_spec = None
        self.no_sync = False
        self.sequence_parallel = False


# -- pytree registration ----------------------------------------------------
def _tensor_flatten(t):
    return (t._data,), (type(t), t.stop_gradient, t.name)


def _tensor_unflatten(aux, children):
    cls, sg, name = aux
    t = cls.__new__(cls)
    data = children[0]
    if isinstance(data, (jax.Array, jax.core.Tracer, np.ndarray)):
        Tensor.__init__(t, data, stop_gradient=sg, name=name)
    else:
        # pytree contract: unflatten must accept ARBITRARY leaf objects —
        # jax internally rebuilds trees with placeholder leaves (make_jaxpr
        # ArgInfo, tree_map sentinels). Coercing those through jnp.asarray
        # (Tensor.__init__) raises; store them untouched instead.
        t._data = data
        t.stop_gradient = sg
        t.grad = None
        t._node = None
        t._out_idx = 0
        t._hooks = []
        t.name = name
        t._dist_attr = None
    if cls is Parameter:
        t.trainable = not sg
        t.optimize_attr = {"learning_rate": 1.0}
        t.is_distributed = False
        t.partition_spec = None
        t.no_sync = False
    return t


jax.tree_util.register_pytree_node(Tensor, _tensor_flatten, _tensor_unflatten)
jax.tree_util.register_pytree_node(Parameter, _tensor_flatten, _tensor_unflatten)


# -- the op recorder --------------------------------------------------------
def _nan_check(name, outs):
    """FLAGS_check_nan_inf: per-op output scan in eager mode (reference:
    CheckVarHasNanOrInf, framework/details/nan_inf_utils_detail.cc). Only
    concrete arrays are checked — inside a jit trace this is a no-op, matching
    the reference's debug workflow of rerunning eagerly with the flag set."""
    from . import flags as F

    if not F.flag("check_nan_inf"):
        return
    for i, o in enumerate(outs):
        if isinstance(o, jax.core.Tracer) or not _is_inexact(getattr(o, "dtype", np.int32)):
            continue
        bad = int(jnp.sum(~jnp.isfinite(o.astype(jnp.float32))))
        if bad:
            msg = f"Operator {name or 'unknown'} output {i} contains {bad} NaN/Inf values"
            if F.flag("check_nan_inf_level", 0) >= 1:
                import warnings

                warnings.warn(msg, stacklevel=3)
            else:
                raise FloatingPointError(msg)


def apply(fn, *tensors, name="", n_outputs=None, **kw):
    """Run `fn` on raw arrays; record a GradNode when grad is needed.

    `fn` may return a single array or a tuple. Non-floating inputs are closed
    over as constants (no float0 cotangent bookkeeping).
    """
    tensors = [t if isinstance(t, Tensor) else Tensor(t) for t in tensors]
    # A sparse tensor produced by a taped sparse op (conv/pool) carries its
    # grad node on `_taped_values`, not on the container — a dense op on the
    # container would otherwise treat it as a leaf and silently drop the
    # upstream weight grads. Substitute its taped dense view (same _data,
    # real grad node; scatter vjp routes dense cotangents back to values).
    tensors = [t.to_dense() if getattr(t, "_taped_values", None) is not None
               else t for t in tensors]
    if kw:
        base = fn
        fn = lambda *xs: base(*xs, **kw)
    if any(getattr(t, "_is_static_var", False) for t in tensors):
        # static-graph mode: record the op on the default Program instead of
        # executing (paddle.static — symbolic Variables have no data)
        from ..static import record_static_op

        return record_static_op(fn, tensors, name=name)
    datas = [t._data for t in tensors]

    diff_mask = [
        (not t.stop_gradient) and _is_inexact(t.dtype) and _grad_enabled() for t in tensors
    ]
    needs_grad = any(diff_mask)

    if not needs_grad:
        out = fn(*datas)
        if isinstance(out, (tuple, list)):
            _nan_check(name, out)
            return type(out)(Tensor(o, stop_gradient=True) for o in out)
        _nan_check(name, (out,))
        return Tensor(out, stop_gradient=True)

    diff_idx = [i for i, m in enumerate(diff_mask) if m]

    def diff_fn(*diff_args):
        full = list(datas)
        for i, a in zip(diff_idx, diff_args):
            full[i] = a
        return fn(*full)

    out, vjp_fn = jax.vjp(diff_fn, *[datas[i] for i in diff_idx])

    multi = isinstance(out, (tuple, list))
    outs = list(out) if multi else [out]
    _nan_check(name, outs)
    node = GradNode(
        vjp_fn,
        [(t, m) for t, m in zip(tensors, diff_mask)],
        [(o.shape, o.dtype) for o in outs],
        name=name,
    )
    wrapped = []
    for i, o in enumerate(outs):
        w = Tensor(o, stop_gradient=False)
        w._node = node
        w._out_idx = i
        wrapped.append(w)
    if multi:
        return type(out)(wrapped)
    return wrapped[0]


def to_tensor(data, dtype=None, place=None, stop_gradient=True):
    """paddle.to_tensor parity (python/paddle/tensor/creation.py)."""
    if isinstance(data, Tensor):
        d = data._data
    elif isinstance(data, (jax.Array, jax.core.Tracer, np.ndarray)):
        d = jnp.asarray(data)
    else:
        arr = np.asarray(data)
        if arr.dtype == np.float64 and dtype is None:
            arr = arr.astype(dtypes.get_default_dtype())
        d = jnp.asarray(arr)
    if dtype is not None:
        dt = dtypes.convert_dtype(dtype)
        if d.dtype != dt:
            d = d.astype(dt)
    return Tensor(d, stop_gradient=stop_gradient)


def _ensure_tensor(x):
    return x if isinstance(x, Tensor) else to_tensor(x)
