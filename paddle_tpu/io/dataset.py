"""Datasets (reference: python/paddle/io/dataloader/dataset.py)."""
import bisect

import numpy as np


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset does not support indexing")

    def __len__(self):
        raise RuntimeError("IterableDataset has no len()")


class TensorDataset(Dataset):
    def __init__(self, tensors):
        assert all(len(t) == len(tensors[0]) for t in tensors)
        self.tensors = tensors

    def __getitem__(self, index):
        return tuple(t[index] for t in self.tensors)

    def __len__(self):
        return len(self.tensors[0])


class ComposeDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)
        assert all(len(d) == len(self.datasets[0]) for d in self.datasets)

    def __len__(self):
        return len(self.datasets[0])

    def __getitem__(self, idx):
        sample = []
        for d in self.datasets:
            item = d[idx]
            sample.extend(item if isinstance(item, (list, tuple)) else [item])
        return tuple(sample)


class ChainDataset(IterableDataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __iter__(self):
        for d in self.datasets:
            yield from d


class ConcatDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)
        self.cumulative_sizes = np.cumsum([len(d) for d in self.datasets]).tolist()

    def __len__(self):
        return self.cumulative_sizes[-1]

    def __getitem__(self, idx):
        if idx < 0:
            idx += len(self)
        i = bisect.bisect_right(self.cumulative_sizes, idx)
        off = idx - (self.cumulative_sizes[i - 1] if i > 0 else 0)
        return self.datasets[i][off]


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = indices

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


class RandomSplitDataset(Subset):
    pass


def random_split(dataset, lengths, generator=None):
    if all(isinstance(l, float) for l in lengths):
        n = len(dataset)
        lengths = [int(l * n) for l in lengths]
        lengths[-1] = n - sum(lengths[:-1])
    total = sum(lengths)
    perm = np.random.permutation(total)
    out, offset = [], 0
    for l in lengths:
        out.append(Subset(dataset, perm[offset : offset + l].tolist()))
        offset += l
    return out
