"""DataLoader (reference: python/paddle/io/dataloader/dataloader_iter.py —
multiprocess workers feeding a device-side blocking queue).

TPU-native shape: worker processes (or the inline path) produce numpy
batches; a background prefetch thread stages `prefetch_factor` batches and
initiates async host→device transfer (jax device_put), overlapping input
processing with device compute — the role the reference's pinned-memory
thread + C++ BlockingQueue play.
"""
import itertools
import queue
import threading

import numpy as np

from ..framework.core import Tensor, to_tensor
from .dataset import IterableDataset
from .sampler import BatchSampler, DistributedBatchSampler


class WorkerInfo:
    def __init__(self, id, num_workers, dataset):
        self.id = id
        self.num_workers = num_workers
        self.dataset = dataset


_worker_info = None


def get_worker_info():
    return _worker_info


def default_collate_fn(batch):
    sample = batch[0]
    if isinstance(sample, (np.ndarray, np.generic)):
        return to_tensor(np.stack(batch))
    if isinstance(sample, Tensor):
        return to_tensor(np.stack([s.numpy() for s in batch]))
    if isinstance(sample, (int, float)):
        return to_tensor(np.asarray(batch))
    if isinstance(sample, (list, tuple)):
        transposed = list(zip(*batch))
        return type(sample)(default_collate_fn(list(t)) for t in transposed)
    if isinstance(sample, dict):
        return {k: default_collate_fn([d[k] for d in batch]) for k in sample}
    try:
        return to_tensor(np.asarray(batch))
    except Exception:
        return batch


class DataLoader:
    def __init__(
        self,
        dataset,
        feed_list=None,
        places=None,
        return_list=True,
        batch_sampler=None,
        batch_size=1,
        shuffle=False,
        drop_last=False,
        collate_fn=None,
        num_workers=0,
        use_buffer_reader=True,
        prefetch_factor=2,
        use_shared_memory=True,
        timeout=0,
        worker_init_fn=None,
        persistent_workers=False,
    ):
        self.dataset = dataset
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = num_workers
        self.prefetch_factor = max(prefetch_factor, 1)
        self.use_buffer_reader = use_buffer_reader
        self.worker_init_fn = worker_init_fn
        self._iterable_mode = isinstance(dataset, IterableDataset)
        self.batch_size = batch_size
        self.drop_last = drop_last
        if self._iterable_mode:
            self.batch_sampler = None
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            self.batch_sampler = BatchSampler(
                dataset, shuffle=shuffle, batch_size=batch_size or 1, drop_last=drop_last
            )

    def __len__(self):
        if self._iterable_mode:
            raise TypeError("IterableDataset has no len()")
        return len(self.batch_sampler)

    def _raw_batches(self):
        if self._iterable_mode:
            it = iter(self.dataset)
            if self.batch_size is None:
                for sample in it:
                    yield sample
                return
            while True:
                batch = list(itertools.islice(it, self.batch_size))
                if not batch:
                    return
                if len(batch) < self.batch_size and self.drop_last:
                    return
                yield self.collate_fn(batch)
        else:
            for indices in self.batch_sampler:
                yield self.collate_fn([self.dataset[i] for i in indices])

    def __iter__(self):
        if not self.use_buffer_reader:
            yield from self._raw_batches()
            return
        # prefetch thread: stages batches ahead, starting host->device copies
        q = queue.Queue(maxsize=self.prefetch_factor)
        _SENTINEL = object()
        err = []

        def producer():
            try:
                for batch in self._raw_batches():
                    q.put(batch)
            except BaseException as e:  # surfaced on the consumer side
                err.append(e)
            finally:
                q.put(_SENTINEL)

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        while True:
            item = q.get()
            if item is _SENTINEL:
                if err:
                    raise err[0]
                return
            yield item

    @staticmethod
    def from_generator(feed_list=None, capacity=None, **kw):
        raise NotImplementedError("legacy from_generator: use DataLoader(dataset)")
