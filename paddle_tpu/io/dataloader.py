"""DataLoader (reference: python/paddle/io/dataloader/dataloader_iter.py —
_DataLoaderIterMultiProcess: worker processes feeding a C++ blocking queue).

TPU-native shape: with num_workers>0, forked worker processes produce numpy
batches, pickle them into per-worker pipes; parent reader threads stage the
raw bytes into the NATIVE BlockingQueue (native/blocking_queue.cc — the
GIL-free handoff), and the consumer unpickles + converts to Tensors,
overlapping input processing with device compute — the role the reference's
pinned-memory thread + C++ BlockingQueue play. num_workers=0 keeps the
inline thread-prefetch path.
"""
import itertools
import os
import pickle
import queue
import struct
import threading
import time

import numpy as np

from ..framework.core import Tensor, to_tensor
from ..framework.native import BlockingQueue
from ..observability.metrics import registry as _registry
from ..testing import chaos
from .dataset import IterableDataset
from .sampler import BatchSampler, DistributedBatchSampler

# consumer-side wait for the next batch: when this histogram's tail grows,
# the step loop is data-starved (goodput category "data_wait") — per-batch
# observe cost is a bisect + two adds, negligible against a batch
_wait_hist = _registry.histogram("data.wait_s")
_batches = _registry.counter("data.batches")


class WorkerInfo:
    def __init__(self, id, num_workers, dataset):
        self.id = id
        self.num_workers = num_workers
        self.dataset = dataset


_worker_info = None


def get_worker_info():
    return _worker_info


def default_collate_fn(batch):
    sample = batch[0]
    if isinstance(sample, (np.ndarray, np.generic)):
        return to_tensor(np.stack(batch))
    if isinstance(sample, Tensor):
        return to_tensor(np.stack([s.numpy() for s in batch]))
    if isinstance(sample, (int, float)):
        return to_tensor(np.asarray(batch))
    if isinstance(sample, (list, tuple)):
        transposed = list(zip(*batch))
        return type(sample)(default_collate_fn(list(t)) for t in transposed)
    if isinstance(sample, dict):
        return {k: default_collate_fn([d[k] for d in batch]) for k in sample}
    try:
        return to_tensor(np.asarray(batch))
    except Exception:
        return batch


def _tensors_to_numpy(obj):
    """Make a batch picklable across the worker pipe (Tensors → numpy)."""
    if isinstance(obj, Tensor):
        return np.asarray(obj._data)
    if isinstance(obj, (list, tuple)):
        return type(obj)(_tensors_to_numpy(o) for o in obj)
    if isinstance(obj, dict):
        return {k: _tensors_to_numpy(v) for k, v in obj.items()}
    return obj


class DataLoader:
    def __init__(
        self,
        dataset,
        feed_list=None,
        places=None,
        return_list=True,
        batch_sampler=None,
        batch_size=1,
        shuffle=False,
        drop_last=False,
        collate_fn=None,
        num_workers=0,
        use_buffer_reader=True,
        prefetch_factor=2,
        use_shared_memory=True,
        timeout=0,
        worker_init_fn=None,
        persistent_workers=False,
    ):
        self.dataset = dataset
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = num_workers
        self.prefetch_factor = max(prefetch_factor, 1)
        self.use_buffer_reader = use_buffer_reader
        self.worker_init_fn = worker_init_fn
        self._iterable_mode = isinstance(dataset, IterableDataset)
        self.batch_size = batch_size
        self.drop_last = drop_last
        if self._iterable_mode:
            self.batch_sampler = None
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            self.batch_sampler = BatchSampler(
                dataset, shuffle=shuffle, batch_size=batch_size or 1, drop_last=drop_last
            )

    def __len__(self):
        if self._iterable_mode:
            raise TypeError("IterableDataset has no len()")
        return len(self.batch_sampler)

    def _raw_batches(self):
        if self._iterable_mode:
            it = iter(self.dataset)
            if self.batch_size is None:
                for sample in it:
                    yield sample
                return
            while True:
                batch = list(itertools.islice(it, self.batch_size))
                if not batch:
                    return
                if len(batch) < self.batch_size and self.drop_last:
                    return
                yield self.collate_fn(batch)
        else:
            for indices in self.batch_sampler:
                yield self.collate_fn([self.dataset[i] for i in indices])

    def _np_collate(self, batch):
        """Numpy-only collate for forked workers (no jax in children)."""
        sample = batch[0]
        if isinstance(sample, (np.ndarray, np.generic)):
            return np.stack(batch)
        if isinstance(sample, (int, float)):
            return np.asarray(batch)
        if isinstance(sample, (list, tuple)):
            return type(sample)(self._np_collate(list(t)) for t in zip(*batch))
        if isinstance(sample, dict):
            return {k: self._np_collate([d[k] for d in batch]) for k in sample}
        return np.asarray(batch)

    def _to_tensors(self, obj):
        if isinstance(obj, np.ndarray):
            return to_tensor(obj)
        if isinstance(obj, (list, tuple)):
            return type(obj)(self._to_tensors(o) for o in obj)
        if isinstance(obj, dict):
            return {k: self._to_tensors(v) for k, v in obj.items()}
        return obj

    #: a worker that dies mid-epoch (OOM-killed, injected crash) is re-forked
    #: at the batch it owed, at most this many times per epoch — bounded so a
    #: deterministically-crashing __getitem__ still fails the epoch instead
    #: of fork-looping forever.
    max_worker_respawns = 2

    def _spawn_worker(self, w, start_bi, all_indices, custom_collate):
        """Fork worker `w` producing batches start_bi, start_bi+W, ... into a
        fresh pipe; returns (pid, BlockingQueue fed by a reader thread)."""
        global _worker_info
        W = self.num_workers
        r, wr = os.pipe()
        pid = os.fork()
        if pid == 0:  # child
            try:
                os.close(r)
                _worker_info = WorkerInfo(w, W, self.dataset)
                if self.worker_init_fn is not None:
                    self.worker_init_fn(w)
                for bi in range(start_bi, len(all_indices), W):
                    chaos.site("dataloader.worker")
                    samples = [self.dataset[i] for i in all_indices[bi]]
                    batch = self.collate_fn(samples) if custom_collate else self._np_collate(samples)
                    blob = pickle.dumps(_tensors_to_numpy(batch), protocol=4)
                    os.write(wr, struct.pack("<q", len(blob)))
                    left = blob
                    while left:
                        n = os.write(wr, left)
                        left = left[n:]
                os.write(wr, struct.pack("<q", 0))
                os.close(wr)
            finally:
                os._exit(0)
        os.close(wr)
        q = BlockingQueue(capacity=self.prefetch_factor)

        def reader(fd=r, bq=q):
            try:
                while True:
                    hdr = b""
                    while len(hdr) < 8:
                        chunk = os.read(fd, 8 - len(hdr))
                        if not chunk:
                            return
                        hdr += chunk
                    (n,) = struct.unpack("<q", hdr)
                    if n == 0:
                        return
                    buf = bytearray()
                    while len(buf) < n:
                        chunk = os.read(fd, min(1 << 20, n - len(buf)))
                        if not chunk:
                            return
                        buf.extend(chunk)
                    bq.push(bytes(buf))
            finally:
                bq.close()
                os.close(fd)

        threading.Thread(target=reader, daemon=True).start()
        return pid, q

    def _mp_iter(self):
        """Forked-worker path. Batch i is produced by worker i % W; the
        consumer round-robins pops so sampler order is preserved (same
        ordering contract as the reference's _DataLoaderIterMultiProcess).
        A worker whose pipe closes before its batches are delivered is
        respawned at the owed batch (bounded; see max_worker_respawns)."""
        W = self.num_workers
        all_indices = list(self.batch_sampler)
        custom_collate = self.collate_fn is not default_collate_fn
        pids, queues = [], []
        respawns = [0] * W
        for w in range(W):
            pid, q = self._spawn_worker(w, w, all_indices, custom_collate)
            pids.append(pid)
            queues.append(q)
        try:
            for bi in range(len(all_indices)):
                w = bi % W
                t0 = time.perf_counter()
                blob = queues[w].pop()
                while blob is None:
                    if respawns[w] >= self.max_worker_respawns:
                        raise RuntimeError(
                            f"DataLoader worker {w} exited early at batch {bi} "
                            f"({respawns[w]} respawns exhausted)")
                    respawns[w] += 1
                    from ..utils.metrics_bus import counters

                    counters.bump("fault.dataloader_respawn")
                    try:  # reap the dead fork before replacing it
                        os.waitpid(pids[w], 0)
                    except ChildProcessError:
                        pass
                    pids[w], queues[w] = self._spawn_worker(
                        w, bi, all_indices, custom_collate)
                    blob = queues[w].pop()
                _wait_hist.observe(time.perf_counter() - t0)
                _batches.inc()
                yield self._to_tensors(pickle.loads(blob))
        finally:
            for pid in pids:
                try:
                    os.waitpid(pid, os.WNOHANG)
                except ChildProcessError:
                    pass

    def __iter__(self):
        if self.num_workers > 0 and not self._iterable_mode:
            yield from self._mp_iter()
            return
        if not self.use_buffer_reader:
            yield from self._raw_batches()
            return
        # prefetch thread: stages batches ahead, starting host->device copies
        q = queue.Queue(maxsize=self.prefetch_factor)
        _SENTINEL = object()
        err = []

        def producer():
            try:
                for batch in self._raw_batches():
                    q.put(batch)
            except BaseException as e:  # surfaced on the consumer side
                err.append(e)
            finally:
                q.put(_SENTINEL)

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        while True:
            t0 = time.perf_counter()
            item = q.get()
            if item is _SENTINEL:
                if err:
                    raise err[0]
                return
            _wait_hist.observe(time.perf_counter() - t0)
            _batches.inc()
            yield item

    @staticmethod
    def from_generator(feed_list=None, capacity=None, **kw):
        raise NotImplementedError("legacy from_generator: use DataLoader(dataset)")
