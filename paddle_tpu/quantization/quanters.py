"""Fake quanters (reference: python/paddle/quantization/quanters/abs_max.py —
FakeQuanterWithAbsMaxObserver; C++ kernels fake_quantize_abs_max etc. in
paddle/fluid/operators/fake_quantize_op.*).

The straight-through estimator is the whole trick: forward quantizes, backward
is identity — `x + stop_gradient(quant(x) - x)` gives exactly that under
jax.vjp, no custom gradient registration needed.
"""
import jax
import jax.numpy as jnp

from ..framework.core import Tensor, apply
from ..nn.layer.layers import Layer


def _quant_dequant(x, scale, bit_length):
    qmax = float(2 ** (bit_length - 1) - 1)
    s = jnp.maximum(scale, 1e-9) / qmax
    q = jnp.clip(jnp.round(x / s), -qmax, qmax) * s
    return x + jax.lax.stop_gradient(q - x)


def fake_quant(x, scale, bit_length=8):
    """Quantize-dequantize with straight-through gradient. `x` Tensor,
    `scale` Tensor or float (per-tensor) / vector (per-channel, last axis)."""
    scale_t = scale if isinstance(scale, Tensor) else Tensor(jnp.asarray(scale, jnp.float32))
    return apply(
        lambda xd, sd: _quant_dequant(xd, sd, bit_length), x, scale_t, name="fake_quant"
    )


class FakeQuanterWithAbsMaxObserver(Layer):
    """Per-tensor fake quant with moving-average abs-max scale (reference:
    FakeQuanterWithAbsMaxObserver + moving_average_abs_max kernel)."""

    def __init__(self, moving_rate=0.9, bit_length=8, dtype="float32", name=None):
        super().__init__()
        self._moving_rate = moving_rate
        self._bit_length = bit_length
        self.register_buffer("scale", Tensor(jnp.ones((), jnp.float32)))
        self.register_buffer("accum", Tensor(jnp.ones((), jnp.float32)))
        self.register_buffer("state", Tensor(jnp.ones((), jnp.float32)))

    def forward(self, x):
        if self.training:
            absmax = jnp.max(jnp.abs(jax.lax.stop_gradient(x._data))).astype(jnp.float32)
            r = self._moving_rate
            state = self.state._data * r + 1.0
            accum = self.accum._data * r + absmax
            self.state._data = state
            self.accum._data = accum
            self.scale._data = accum / state
        return fake_quant(x, Tensor(self.scale._data), self._bit_length)

    def quant_axis(self):
        return None

    def scales(self):
        return self.scale


class FakeQuanterChannelWiseAbsMaxObserver(Layer):
    """Per-channel abs-max fake quant (reference:
    quanters/channel_wise_abs_max.py; quant_axis=output-channel)."""

    def __init__(self, quant_axis=-1, bit_length=8, dtype="float32", name=None):
        super().__init__()
        self._quant_axis = quant_axis
        self._bit_length = bit_length
        self.scale = None  # lazily sized on first call

    def forward(self, x):
        axis = self._quant_axis if self._quant_axis >= 0 else x.ndim + self._quant_axis
        reduce_axes = tuple(i for i in range(x.ndim) if i != axis)
        absmax = jnp.max(
            jnp.abs(jax.lax.stop_gradient(x._data)), axis=reduce_axes, keepdims=True
        ).astype(jnp.float32)
        if self.scale is None:
            self.register_buffer("scale", Tensor(absmax))
        else:
            self.scale._data = absmax
        return fake_quant(x, Tensor(absmax), self._bit_length)

    def quant_axis(self):
        return self._quant_axis

    def scales(self):
        return self.scale
