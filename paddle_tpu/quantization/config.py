"""QuantConfig (reference: python/paddle/quantization/config.py — maps layers
to (activation, weight) quanter/observer factories by type, name, or
prefix)."""
import copy

from ..nn.layer.layers import Layer


class SingleLayerConfig:
    def __init__(self, activation, weight):
        self.activation = activation
        self.weight = weight


class QuantConfig:
    def __init__(self, activation=None, weight=None):
        self._global = SingleLayerConfig(activation, weight) if (activation or weight) else None
        self._type_configs = {}
        self._name_configs = {}
        self._prefix_configs = {}
        self._customized_leaves = []

    # -- registration ------------------------------------------------------
    def add_layer_config(self, layer, activation=None, weight=None):
        layers = layer if isinstance(layer, (list, tuple)) else [layer]
        for l in layers:
            # match specific instances by identity (reference uses full_name)
            self._name_configs[id(l)] = SingleLayerConfig(activation, weight)

    def add_name_config(self, layer_name, activation=None, weight=None):
        names = layer_name if isinstance(layer_name, (list, tuple)) else [layer_name]
        for n in names:
            self._prefix_configs[n] = SingleLayerConfig(activation, weight)

    def add_type_config(self, layer_type, activation=None, weight=None):
        types = layer_type if isinstance(layer_type, (list, tuple)) else [layer_type]
        for t in types:
            self._type_configs[t] = SingleLayerConfig(activation, weight)

    def add_qat_layer_mapping(self, source, target):
        from .quantize import QAT_LAYER_MAP

        QAT_LAYER_MAP[source] = target

    def add_customized_leaves(self, layer_type):
        self._customized_leaves.append(layer_type)

    # -- lookup ------------------------------------------------------------
    def _get_config_for_layer(self, layer, name=""):
        if id(layer) in self._name_configs:
            return self._name_configs[id(layer)]
        for prefix, cfg in self._prefix_configs.items():
            if name.startswith(prefix):
                return cfg
        if type(layer) in self._type_configs:
            return self._type_configs[type(layer)]
        return self._global

    def copy(self):
        return copy.deepcopy(self)
